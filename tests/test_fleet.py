"""Fleet serving (lightgbm_tpu/serving/fleet.py + the multi-replica /
device-TreeSHAP extensions to forest/dispatch/registry).

Contracts under test:

- **stacked scoring**: models paged into one family stack score
  identically to their own boosters, and paging a different model into
  a slot never recompiles (the slot index is traced, the stack shapes
  are the executable's identity);
- **LRU paging**: a fleet larger than its residency capacity keeps
  ``resident <= capacity``, evicts least-recently-used, and re-paged
  models still score exactly;
- **hot-swap atomicity**: readers hammering a model THROUGH a v1->v2
  swap (while a cold model pages in beside them) each get a result
  bit-equal to v1 or v2 — never a torn table, a dropped future, or
  another model's scores;
- **device TreeSHAP**: ``pred_contrib`` computed on-device over the
  packed tables matches the host ``shap.py`` oracle on every model
  family, and rows sum to the raw score (non-linear trees);
- **replicas**: N predictor replicas behind one registry answer
  bit-identically to a single replica, direct and via the
  continuous-batching front.
"""

import json
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import shap as host_shap
from lightgbm_tpu.serving import (
    MicroBatcher,
    ModelFleet,
    ModelRegistry,
    TensorForest,
)


def _train(params, X, y, rounds=8, **ds_kw):
    ds = lgb.Dataset(X, label=y, free_raw_data=False, **ds_kw)
    p = dict(verbosity=-1, min_data_in_leaf=5, deterministic=True)
    p.update(params)
    return lgb.train(p, ds, num_boost_round=rounds)


def _reg_booster(rng, seed=0, leaves=15, rounds=8, feats=6, depth=None):
    r = np.random.RandomState(seed)
    X = r.randn(500, feats)
    y = X[:, 0] * (seed % 5 + 1) + X[:, 1] + 0.1 * r.randn(500)
    p = {"objective": "regression", "num_leaves": leaves}
    if depth is not None:
        p["max_depth"] = depth
    return _train(p, X, y, rounds=rounds)


def _contrib_families(rng):
    """(name, booster, query matrix) across the model families the
    device TreeSHAP must explain (docs/SERVING.md)."""
    out = []
    X = rng.randn(900, 8)
    yreg = X @ rng.randn(8) + 0.1 * rng.randn(900)
    out.append(("regression",
                _train({"objective": "regression", "num_leaves": 15},
                       X, yreg, rounds=10),
                rng.randn(80, 8)))

    Xc = rng.randn(900, 8)
    Xc[:, 3] = rng.randint(0, 8, 900)
    Xc[rng.rand(900) < 0.08, 1] = np.nan
    yb = (np.nan_to_num(Xc[:, 0]) + (Xc[:, 3] % 3 == 0) > 0.3).astype(float)
    Xq = rng.randn(80, 8)
    Xq[:, 3] = rng.randint(0, 8, 80)
    Xq[rng.rand(80) < 0.08, 1] = np.nan
    out.append(("binary+cat+nan",
                _train({"objective": "binary", "num_leaves": 15}, Xc, yb,
                       rounds=10, categorical_feature=[3]),
                Xq))

    ym = rng.randint(0, 3, 900)
    out.append(("multiclass",
                _train({"objective": "multiclass", "num_class": 3,
                        "num_leaves": 15}, X, ym, rounds=6),
                rng.randn(60, 8)))

    Xl = rng.randn(800, 5)
    yl = Xl[:, 0] * 2 + Xl[:, 1] + 0.1 * rng.randn(800)
    dsl = lgb.Dataset(Xl, label=yl, free_raw_data=False,
                      params={"linear_tree": True})
    bl = lgb.train({"objective": "regression", "num_leaves": 15,
                    "linear_tree": True, "verbosity": -1,
                    "min_data_in_leaf": 5}, dsl, num_boost_round=6)
    out.append(("linear_tree", bl, rng.randn(60, 5)))
    return out


def _host_contrib(bst, Xq):
    g = bst._gbdt
    return host_shap.predict_contrib(
        list(g.models), np.asarray(Xq, np.float64), Xq.shape[1],
        g.num_class, 0, -1, bool(getattr(g, "average_output", False)),
    )


# ---------------------------------------------------------- TreeSHAP
def test_device_contrib_parity_all_families(rng):
    """On-device pred_contrib vs the host shap.py oracle: Booster
    layout (N, K*(F+1)), every family; rows sum to the raw score for
    constant-leaf trees (linear leaves attribute via leaf constants —
    the oracle's semantics — so their row-sum check is skipped)."""
    for name, bst, Xq in _contrib_families(rng):
        host = _host_contrib(bst, Xq)
        forest = TensorForest.from_booster(bst)
        dev = forest.predict_contrib(np.asarray(Xq, np.float32))
        assert dev.shape == host.shape, name
        scale = max(1.0, np.max(np.abs(host)))
        assert np.max(np.abs(dev - host)) / scale < 5e-4, name
        if name == "linear_tree":
            continue
        raw = bst.predict(Xq, raw_score=True)
        raw = raw if raw.ndim == 2 else raw[:, None]
        K = max(bst._gbdt.num_class, 1)
        sums = dev.reshape(len(Xq), K, Xq.shape[1] + 1).sum(axis=2)
        np.testing.assert_allclose(sums, raw, rtol=1e-4, atol=2e-3,
                                   err_msg=name)


def test_device_contrib_truncation_and_registry_endpoint(rng):
    """Iteration truncation matches the truncated host oracle, and the
    registry/fleet pred_contrib endpoints return the device values."""
    bst = _reg_booster(rng, seed=3, rounds=10)
    Xq = np.random.RandomState(1).randn(40, 6).astype(np.float32)
    forest = TensorForest.from_booster(bst)
    g = bst._gbdt
    for start, num in ((0, 4), (2, 5)):
        host = host_shap.predict_contrib(
            list(g.models)[start:start + num], np.asarray(Xq, np.float64),
            6, g.num_class, 0, -1, False,
        )
        dev = forest.predict_contrib(Xq, start, num)
        assert np.max(np.abs(dev - host)) < 5e-4, (start, num)

    reg = ModelRegistry()
    reg.load("m", bst)
    via_reg = reg.predict("m", Xq, pred_contrib=True)
    assert np.max(np.abs(via_reg - _host_contrib(bst, Xq))) < 5e-4


# ---------------------------------------------------- stacked scoring
def test_fleet_stacked_parity_and_no_repage_recompile(retrace_guard, rng):
    """Different-shaped models of one family share one stack
    executable: paging model after model into the stack never
    recompiles (the slot is traced data, not a trace constant)."""
    from lightgbm_tpu.serving.forest import _stacked_apply_jit

    fleet = ModelFleet(buckets=(16, 64), capacity=2, slots_per_family=2)
    # max_depth pinned: the family key pads trees/nodes/leaves to pow2
    # but keys on the depth bound, so equal depth = one family
    boosters = {
        f"m{i}": _reg_booster(rng, seed=i, leaves=6 + (i % 3),
                              rounds=5 + i, depth=3)
        for i in range(4)
    }
    names = list(boosters)
    for name in names:
        fleet.load(name, boosters[name])
    Xq = np.random.RandomState(7).randn(30, 6)
    try:
        for name in names:  # pages everything once: compiles happen here
            fleet.predict(name, Xq)
        # the point of the pow2-padded family key: these four different
        # models (different leaf/tree counts) share ONE stack family
        assert len(fleet._stacks) == 1, list(fleet._stacks)
        with retrace_guard(
            entry_points=[_stacked_apply_jit()], max_retraces=0,
            what="fleet paging across 4 models (2 resident slots)",
        ):
            for _ in range(2):
                for name in names:
                    got = fleet.predict(name, Xq)
                    ref = boosters[name].predict(Xq)
                    np.testing.assert_allclose(got, ref, rtol=1e-6,
                                               atol=1e-6, err_msg=name)
    finally:
        fleet.close()


def test_fleet_lru_paging_and_metrics(rng):
    """resident <= capacity always; LRU eviction under a sweep larger
    than capacity; evicted models re-page and still score exactly; the
    pager's traffic lands in the per-model metrics registry."""
    from lightgbm_tpu.obs.metrics import default_registry

    fleet = ModelFleet(buckets=(16, 64), capacity=3, slots_per_family=2)
    boosters = {f"m{i}": _reg_booster(rng, seed=10 + i) for i in range(6)}
    for name, b in boosters.items():
        fleet.load(name, b)
    Xq = np.random.RandomState(3).randn(20, 6)
    try:
        for sweep in range(2):
            for name, b in boosters.items():
                got = fleet.predict(name, Xq)
                np.testing.assert_allclose(got, b.predict(Xq), rtol=1e-6,
                                           atol=1e-6, err_msg=name)
                assert fleet.fleet_stats()["resident"] <= 3
        fs = fleet.fleet_stats()
        assert fs["capacity"] == 3
        assert fs["evictions"] > 0, "LRU never exercised"
        assert fs["pages_in"] > len(boosters), "no re-paging happened"
        snap = default_registry().snapshot()
        pages = snap.get("lgbmtpu_fleet_page_events_total", {})
        assert any('model="m0"' in k and 'event="page_in"' in k
                   for k in pages), pages.keys()
        reqs = snap.get("lgbmtpu_serve_requests_total", {})
        assert any('model="m0"' in k for k in reqs), reqs.keys()
        assert "lgbmtpu_fleet_resident_models" in snap
    finally:
        fleet.close()


# ------------------------------------------------- swap under load
def test_fleet_swap_rollback_atomic_under_concurrent_load(rng):
    """The satellite contract: reader threads hammer model A straight
    through a v1->v2 hot swap while a cold model B pages in beside
    them. Every single result must be bit-equal to either v1's or v2's
    full prediction — no torn model, no dropped request, no
    other-model scores — and after rollback the fleet answers v1
    again."""
    fleet = ModelFleet(buckets=(16,), capacity=2, slots_per_family=2)
    b1 = _reg_booster(rng, seed=21, leaves=12, rounds=6)
    b2 = _reg_booster(rng, seed=22, leaves=12, rounds=6)
    bcold = _reg_booster(rng, seed=23, leaves=12, rounds=6)
    Xq = np.random.RandomState(5).randn(16, 6)
    refc = bcold.predict(Xq)

    # bit-level references must come off the SAME stacked executable
    # the fleet runs (float32 device math, not the float64 host walk):
    # a scratch fleet of the same family produces bit-identical output
    scratch = ModelFleet(buckets=(16,), capacity=2, slots_per_family=2)
    scratch.load("r1", b1)
    scratch.load("r2", b2)
    ref1 = np.asarray(scratch.predict("r1", Xq))
    ref2 = np.asarray(scratch.predict("r2", Xq))
    scratch.close()
    assert np.max(np.abs(ref1 - ref2)) > 1e-3  # distinguishable models

    fleet.load("A", b1)
    np.testing.assert_array_equal(fleet.predict("A", Xq), ref1)
    errors: list = []
    torn: list = []
    stop = threading.Event()

    def hammer(seed: int) -> None:
        try:
            while not stop.is_set():
                got = fleet.predict("A", Xq)
                if not (np.array_equal(got, ref1)
                        or np.array_equal(got, ref2)):
                    torn.append(got)
                    return
        except Exception as e:  # noqa: BLE001 — collected and re-raised below
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
               for i in range(6)]
    for t in threads:
        t.start()
    try:
        v2 = fleet.load("A", b2, activate=False)
        fleet.load("B", bcold)          # cold page-in during the storm
        np.testing.assert_allclose(fleet.predict("B", Xq), refc,
                                   rtol=1e-6, atol=1e-6)
        fleet.swap("A", v2)
        # give readers time to cross the swap boundary
        for _ in range(20):
            fleet.predict("A", Xq)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert not torn, "torn/foreign prediction observed during swap"
    np.testing.assert_array_equal(fleet.predict("A", Xq), ref2)
    assert fleet.rollback("A") == 1
    np.testing.assert_array_equal(fleet.predict("A", Xq), ref1)
    fleet.close()


def test_fleet_qos_and_residency_rejection(rng):
    """Per-tenant QoS rides load(): a model with a tiny queue_cap
    rejects a backlog with QueueOverflow (HTTP 503), and a fleet whose
    residency is exhausted by pinned models rejects rather than
    deadlocks."""
    from lightgbm_tpu.resilience.errors import QueueOverflow

    fleet = ModelFleet(buckets=(16,), capacity=1, slots_per_family=1,
                       page_timeout_s=0.2)
    fleet.load("a", _reg_booster(rng, seed=31))
    fleet.load("b", _reg_booster(rng, seed=32))
    Xq = np.random.RandomState(9).randn(8, 6)
    fleet.predict("a", Xq)

    # pin "a" by holding its residency from inside a predict: simulate
    # by paging "b" while "a" is the sole resident — with capacity 1
    # and no pins this must evict and succeed, proving the timeout
    # path only fires for genuinely pinned stacks
    fleet.predict("b", Xq)
    assert fleet.fleet_stats()["resident"] == 1

    # per-tenant QoS rides load(): the tenant's continuous-batching
    # front is built with ITS deadline/queue bound, not the fleet's
    v = fleet.load("q", _reg_booster(rng, seed=33), queue_cap=3,
                   deadline_ms=2500)
    assert v == 1
    fleet.predict("q", Xq, via_queue=True)  # builds the tenant batcher
    entry = fleet._names["q"]["versions"][0]
    assert entry.batcher.queue_cap == 3
    assert entry.batcher.deadline_s == pytest.approx(2.5)
    # admission control enforces that bound: with a backlog present, a
    # request overflowing 3 rows is rejected (maps to HTTP 503)
    with pytest.raises(QueueOverflow):
        entry.batcher._pending.append(
            (np.zeros((1, 6), np.float32), object(), None))
        entry.batcher._pending_rows += 1
        try:
            fleet.predict("q", Xq, via_queue=True)
        finally:
            entry.batcher._pending.pop()
            entry.batcher._pending_rows -= 1
    fleet.close()


# ------------------------------------------------------- replicas
def test_registry_replicas_bit_identical_and_coalesced(rng):
    """N replicas behind one registry: concurrent direct and queued
    traffic answers bit-identically to a single-replica registry, and
    the continuous-batching front drains through every replica."""
    bst = _reg_booster(rng, seed=41, rounds=10)
    Xq = np.random.RandomState(11).randn(24, 6).astype(np.float32)
    single = ModelRegistry()
    single.load("m", bst)
    ref = np.asarray(single.predict("m", Xq))

    reg = ModelRegistry(replicas=3)
    reg.load("m", bst)
    mv = reg._entry("m")
    assert len(mv.replicas) == 3
    results: list = []
    errors: list = []
    lock = threading.Lock()

    def worker(i: int) -> None:
        try:
            mine = []
            for j in range(8):
                got = reg.predict("m", Xq, via_queue=(j % 2 == 0))
                mine.append(np.asarray(got))
            with lock:
                results.extend(mine)
        except Exception as e:  # noqa: BLE001 — collected and re-raised below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert len(results) == 64
    for got in results:
        np.testing.assert_array_equal(got, ref)
    # the shared batcher fronts all three replicas
    assert len(reg.batcher("m").dispatchers) == 3
    reg.unload("m")  # closes the batcher workers


def test_registry_batcher_accessor_and_multi_dispatcher_close(rng):
    """registry.batcher() hands out the SAME continuous-batching front
    predict(via_queue=True) uses; submit() resolves to raw margins;
    a multi-dispatcher MicroBatcher joins every worker on close."""
    bst = _reg_booster(rng, seed=51)
    Xq = np.random.RandomState(13).randn(10, 6).astype(np.float32)
    reg = ModelRegistry(replicas=2)
    reg.load("m", bst)
    b = reg.batcher("m")
    assert b is reg.batcher("m")
    raw = np.asarray(b.submit(Xq).result())
    ref = np.asarray(reg.predict("m", Xq, raw_score=True))
    np.testing.assert_array_equal(raw.reshape(-1), ref.reshape(-1))

    disp = [r for r in reg._entry("m").replicas]
    mb = MicroBatcher(disp)
    assert len(mb._workers) == 2
    futs = [mb.submit(Xq) for _ in range(6)]
    for f in futs:
        np.testing.assert_array_equal(
            np.asarray(f.result()).reshape(-1), ref.reshape(-1))
    mb.close()
    for w in mb._workers:
        assert not w.is_alive()


# ----------------------------------------------------------- HTTP
def test_fleet_over_http(rng):
    """The fleet behind the HTTP front end: QoS-tagged load, score,
    the contrib op, /v1/fleet residency stats, and per-model series on
    /metrics."""
    import urllib.request

    from lightgbm_tpu.serving import serve_http

    bst = _reg_booster(rng, seed=61)
    fleet = ModelFleet(buckets=(16,), capacity=4)
    httpd = serve_http(fleet, port=0, block=False)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    Xq = np.random.RandomState(17).randn(6, 6)
    try:
        def post(path, body):
            req = urllib.request.Request(
                base + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        out = post("/v1/load", {"model": "h", "model_str":
                                bst.model_to_string(),
                                "deadline_ms": 2000, "queue_cap": 4096})
        assert out["version"] == 1
        out = post("/v1/score", {"model": "h", "rows": Xq.tolist()})
        np.testing.assert_allclose(out["pred"], bst.predict(Xq),
                                   rtol=1e-5, atol=1e-6)
        out = post("/v1/contrib", {"model": "h", "rows": Xq.tolist()})
        host = _host_contrib(bst, Xq)
        assert np.max(np.abs(np.asarray(out["pred"]) - host)) < 5e-4
        with urllib.request.urlopen(base + "/v1/fleet", timeout=30) as r:
            fl = json.loads(r.read())["fleet"]
        assert fl["resident"] >= 1 and fl["capacity"] == 4
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            text = r.read().decode()
        assert 'model="h"' in text
        assert "lgbmtpu_fleet_resident_models" in text
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)
        fleet.close()
