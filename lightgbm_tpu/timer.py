"""Named per-phase accumulating timers (the reference's USE_TIMETAG
subsystem: Timer/FunctionTimer, utils/common.h:979-1043, global_timer
printed at exit, per-phase instrumentation across the tree learner and
network layers — SURVEY §5).

TPU adaptation: phases are HOST-side regions (dispatch, collect,
binning, eval). Device work inside jit is asynchronous, so a scope that
must include device completion passes `block=True` to synchronize
before stopping the clock (used by bench/profilers, off in production
paths). Scopes also enter `jax.profiler.TraceAnnotation`-compatible
`jax.named_scope` so traces collected with jax.profiler line up with
the same names.

Enable summary-at-exit with env LIGHTGBM_TPU_TIMETAG=1 (the analog of
the reference's compile-time USE_TIMETAG), with the `timetag` config /
CLI param, or at runtime via `global_timer.enable()` — unlike the
reference's compile-time flag, timing can be turned on and off without
restarting the process.

While an obs.tracing recorder is active, every scope additionally
records a Chrome trace-event span (the recorder installs itself here
through `set_trace_sink`), so the phase table, the trace timeline, and
jax.profiler annotations all carry the same names.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, List, Optional

# active span sinks: obs.tracing installs `(name, start_s, dur_s) ->
# None` here while recording, and obs.recorder adds its per-round
# phase accumulator alongside (module attributes, not Timer fields, so
# the subscribers observe every Timer instance). `set_trace_sink`
# keeps its original single-slot semantics for obs.tracing; extra
# subscribers ride `add_trace_sink`/`remove_trace_sink`.
_trace_sinks: tuple = ()
_primary_sink: Optional[Callable[[str, float, float], None]] = None


def set_trace_sink(
    sink: Optional[Callable[[str, float, float], None]]
) -> None:
    """Install (or clear, with None) the span recorder scopes report
    to. Owned by obs.tracing; exposed here so timer stays a leaf
    module with no obs import. Replaces only the slot it owns — sinks
    added through add_trace_sink are unaffected."""
    global _trace_sinks, _primary_sink
    sinks = [s for s in _trace_sinks if s is not _primary_sink]
    _primary_sink = sink
    if sink is not None:
        sinks.append(sink)
    _trace_sinks = tuple(sinks)


def add_trace_sink(sink: Callable[[str, float, float], None]) -> None:
    """Subscribe an additional span sink (obs.recorder's per-round
    phase accumulator); idempotent."""
    global _trace_sinks
    if sink not in _trace_sinks:
        _trace_sinks = _trace_sinks + (sink,)


def remove_trace_sink(sink: Callable[[str, float, float], None]) -> None:
    # equality, not identity: a bound method is a fresh object on each
    # attribute access, so `is` would never match the stored sink
    global _trace_sinks
    _trace_sinks = tuple(s for s in _trace_sinks if s != sink)


def _sync_devices() -> None:
    """Barrier: wait for completion of all work dispatched so far on
    EVERY local device (the old hack synced one op on the default
    device only — a sharded computation's other shards kept running).
    Each device executes its stream in order, so blocking on a tiny
    computation enqueued per device flushes everything before it."""
    import jax

    try:
        jax.effects_barrier()
    except Exception:  # noqa: BLE001 — older jax without effects_barrier
        pass
    for d in jax.local_devices():
        try:
            (jax.device_put(0, d) + 0).block_until_ready()
        except Exception:  # noqa: BLE001 — never break the timed path
            continue


class Timer:
    """Accumulating named stopwatches (reference utils/common.h:979)."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._cnt: Dict[str, int] = {}
        self.enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")
        self._summary_at_exit = self.enabled

    def enable(self, summary_at_exit: bool = True) -> None:
        """Turn timing on at runtime (config/CLI `timetag` hook); the
        at-exit summary registers once."""
        self.enabled = True
        if summary_at_exit and not self._summary_at_exit:
            self._summary_at_exit = True
            atexit.register(self.print_summary)

    def disable(self) -> None:
        self.enabled = False

    @contextmanager
    def scope(self, name: str, block: bool = False) -> Iterator[None]:
        """Time a region; with block=True waits for completion of all
        dispatched device work (every local device) before stopping
        the clock, so the region includes its dispatched work."""
        sinks = _trace_sinks
        if not self.enabled and not sinks:
            yield
            return
        import jax

        t0 = time.perf_counter()
        with jax.named_scope(name.replace(" ", "_")):
            yield
        if block:
            _sync_devices()
        dt = time.perf_counter() - t0
        if self.enabled:
            self._acc[name] = self._acc.get(name, 0.0) + dt
            self._cnt[name] = self._cnt.get(name, 0) + 1
        for sink in sinks:
            sink(name, t0, dt)

    def add(self, name: str, seconds: float,
            start: Optional[float] = None) -> None:
        """Record an externally-timed region: accumulates like scope()
        and reports to the active trace sink (`start` is the region's
        time.perf_counter() start, for span placement)."""
        if self.enabled:
            self._acc[name] = self._acc.get(name, 0.0) + seconds
            self._cnt[name] = self._cnt.get(name, 0) + 1
        sinks = _trace_sinks
        if sinks:
            if start is None:
                start = time.perf_counter() - seconds
            for sink in sinks:
                sink(name, start, seconds)

    def summary(self) -> Dict[str, tuple]:
        return {
            k: (self._acc[k], self._cnt[k])
            for k in sorted(self._acc, key=lambda k: -self._acc[k])
        }

    def print_summary(self) -> None:
        """common.h:1012 — per-phase totals at exit."""
        from . import log

        if not self._acc:
            return
        log.info("LightGBM-TPU phase timings:")
        for name, (acc, cnt) in self.summary().items():
            log.info(f"  {name}: {acc:.3f}s ({cnt} calls)")

    def reset(self) -> None:
        self._acc.clear()
        self._cnt.clear()


global_timer = Timer()

if global_timer.enabled:
    atexit.register(global_timer.print_summary)


def enable_timetag() -> None:
    """Config/CLI hook (`timetag=true`): turn on the global phase timer
    mid-process (engine.train and cli.main both route here)."""
    global_timer.enable()


class LatencyStats:
    """Latency/throughput counters for serving paths.

    Unlike Timer scopes (accumulating host-region stopwatches for
    training phases), serving needs DISTRIBUTION statistics — a p99
    regression hides completely in an accumulated total. Keeps a ring
    of the most recent `window` request latencies plus lifetime count /
    row totals; `snapshot()` derives mean/p50/p95/p99 over the ring and
    rows/sec over the lifetime. Thread-safe: the serving server and the
    microbatch worker observe from different threads.
    """

    def __init__(self, window: int = 2048) -> None:
        self._window = int(window)
        self._ring: List[float] = []
        self._pos = 0
        self._count = 0
        self._rows = 0
        self._total_s = 0.0
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    def observe(self, seconds: float, rows: int = 1) -> None:
        with self._lock:
            if len(self._ring) < self._window:
                self._ring.append(float(seconds))
            else:
                self._ring[self._pos] = float(seconds)
                self._pos = (self._pos + 1) % self._window
            self._count += 1
            self._rows += int(rows)
            self._total_s += float(seconds)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            ring = sorted(self._ring)
            count, rows, total = self._count, self._rows, self._total_s
            uptime = time.perf_counter() - self._t0

        def pct(p: float) -> float:
            if not ring:
                return 0.0
            return ring[min(len(ring) - 1, int(p * (len(ring) - 1) + 0.5))]

        # mean over the same ring the percentiles cover — a lifetime
        # mean would stay inflated by cold-start outliers forever and
        # read as mean >> p99 on a warmed-up server
        mean = sum(ring) / len(ring) if ring else 0.0
        return {
            "count": count,
            "rows": rows,
            "mean_ms": round(1e3 * mean, 4),
            "p50_ms": round(1e3 * pct(0.50), 4),
            "p95_ms": round(1e3 * pct(0.95), 4),
            "p99_ms": round(1e3 * pct(0.99), 4),
            "rows_per_sec": round(rows / uptime, 2) if uptime > 0 else 0.0,
            "busy_frac": round(total / uptime, 4) if uptime > 0 else 0.0,
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pos = 0
            self._count = 0
            self._rows = 0
            self._total_s = 0.0
            self._t0 = time.perf_counter()


_latency: Dict[str, LatencyStats] = {}
_latency_lock = threading.Lock()


def latency_stats(name: str, model: Optional[str] = None) -> LatencyStats:
    """Named process-global LatencyStats (one per serving entry point,
    mirroring global_timer's named-scope registry). Each named ring
    registers itself on the obs metrics registry at creation, so
    `/metrics` scrapes and `ModelRegistry.stats()` read the SAME
    object — one source of truth for serving latency. ``model`` tags
    the exported series with a ``{model=...}`` label (fleet tenants;
    docs/OBSERVABILITY.md)."""
    with _latency_lock:
        created = name not in _latency
        if created:
            _latency[name] = LatencyStats()
        stats = _latency[name]
    if created:
        from .obs.metrics import register_latency_collector

        register_latency_collector(name, stats, model=model)
    return stats


def latency_summary() -> Dict[str, Dict[str, float]]:
    with _latency_lock:
        return {k: v.snapshot() for k, v in sorted(_latency.items())}
