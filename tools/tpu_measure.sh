#!/bin/bash
# Runs the kernel microbenchmarks + the end-to-end bench on a live TPU;
# appends everything to /tmp/tpu_measure.log (the builder folds results
# into BENCH_NOTES.md).
cd /root/repo
echo "==== tpu_measure $(date -u) ===="
timeout 1800 python tools/tpu_microbench.py 2>&1 | grep -v WARNING
echo "==== bench.py auto (rounds) ===="
timeout 1800 env BENCH_TREES=60 BENCH_WARMUP=2 python bench.py 2>bench_stderr.log
tail -5 bench_stderr.log
echo "==== bench.py quantized ===="
timeout 1200 env BENCH_TREES=60 BENCH_WARMUP=2 BENCH_QUANT=1 python - << 'PYEOF' 2>&1 | tail -3
import os, subprocess, sys
os.environ.setdefault("BENCH_GROWTH_MODE", "auto")
env = dict(os.environ)
# quantized variant rides the same bench with use_quantized_grad
src = open("bench.py").read().replace(
    '"tpu_growth_mode": growth_mode,',
    '"tpu_growth_mode": growth_mode, "use_quantized_grad": True,')
open("/tmp/bench_quant.py", "w").write(src)
r = subprocess.run([sys.executable, "/tmp/bench_quant.py"], capture_output=True, text=True, cwd="/root/repo")
print(r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "no output")
sys.stderr.write(r.stderr[-500:])
PYEOF
echo "==== done $(date -u) ===="
