"""Trace-safety static analysis suite (analysis/): rule fixtures with
known violations, red-to-green jaxpr contracts, the retrace guard, and
the strict clean run over the real package — the tier-1 hook that makes
new lint violations and jaxpr-contract breaks fail the suite."""

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lightgbm_tpu.analysis.lint import (
    Finding,
    RULES,
    format_findings,
    lint_package,
    lint_source,
)

REPO = Path(__file__).resolve().parents[1]


# ----------------------------------------------------------------- lint
_VIOLATIONS = '''
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

@jax.jit
def tracer_hazards(x, y):
    if x > 0:                       # tracer-branch
        z = float(x)                # tracer-cast
    q = x > 1 and y > 2             # tracer-branch (short-circuit)
    w = np.asarray(y)               # np-on-tracer
    v = x.item()                    # host-sync
    return x + y

@partial(jax.jit, static_argnames=("n",))
def static_ok(x, n):
    if n > 2:                       # static arg: clean
        x = x + 1
    G, N = x.shape
    if N > 4:                       # shape: clean
        x = x * 2
    if x is None:                   # identity: clean
        return x
    return jnp.sum(x)

def helper(a, flag=False):
    if flag:                        # literal-default param: clean
        a = a * 2
    return bool(a > 0)              # tracer-cast through the call graph

@jax.jit
def root(x):
    return helper(x)

def not_traced(q):
    if q:                           # host code: clean
        return float(q)
    return 0.0

def make_baked(base):
    arr = jnp.asarray(base)
    def inner(z):
        return z + arr
    return jax.jit(inner)           # device-closure

def mut(a, b=[]):                   # mutable-default
    return a
'''


def _rules_at(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


def test_each_rule_fires_on_fixture():
    fs = lint_source(_VIOLATIONS)
    assert len(_rules_at(fs, "tracer-branch")) == 2
    assert len(_rules_at(fs, "tracer-cast")) == 2  # float() + helper bool()
    assert len(_rules_at(fs, "np-on-tracer")) == 1
    assert len(_rules_at(fs, "host-sync")) == 1
    assert len(_rules_at(fs, "device-closure")) == 1
    assert len(_rules_at(fs, "mutable-default")) == 1
    # every registered rule is exercised by this fixture
    assert {f.rule for f in fs} == set(RULES)


def test_static_constructs_stay_clean():
    fs = lint_source(_VIOLATIONS)
    lines = {f.line for f in fs}
    src_lines = _VIOLATIONS.splitlines()
    for i, txt in enumerate(src_lines, start=1):
        if "clean" in txt:
            assert i not in lines, f"false positive on line {i}: {txt}"


def test_suppression_comment_and_file_allow():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)  # lint: allow[tracer-cast]\n"
    )
    fs = lint_source(src)
    assert len(fs) == 1 and fs[0].suppressed
    src2 = (
        "# lint: allow-file[tracer-cast]\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n"
    )
    fs2 = lint_source(src2)
    assert len(fs2) == 1 and fs2[0].suppressed
    # an unrelated rule id does NOT suppress
    src3 = src.replace("tracer-cast", "host-sync")
    fs3 = lint_source(src3)
    assert len(fs3) == 1 and not fs3[0].suppressed


def test_real_package_is_lint_clean():
    """The acceptance bar: zero unsuppressed violations over the real
    package source (intentional sites are annotated, not silenced)."""
    fs = lint_package(str(REPO / "lightgbm_tpu"))
    bad = [f for f in fs if not f.suppressed]
    assert not bad, "\n" + format_findings(bad)


def test_format_findings_counts():
    fs = lint_source(_VIOLATIONS)
    out = format_findings(fs, show_suppressed=True)
    assert "violation(s)" in out and "tracer-cast" in out


# ----------------------------------------------------- jaxpr contracts
def _wire_fixture_jaxpr(widen: bool):
    """An 8-shard psum_scatter wire, int32 or deliberately f32-widened
    (shared with tests/test_cost_audit.py's wire-bytes tests). Uses
    the audit suite's own `_mesh()` (the one XLA_FLAGS bootstrap
    owner) rather than a private mesh builder."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.analysis.jaxpr_audit import _mesh
    from lightgbm_tpu.parallel.data_parallel import shard_map_compat

    mesh = _mesh()

    def f(h):
        wire = h.astype(jnp.float32) if widen else h.astype(jnp.int32)
        return lax.psum_scatter(
            wire, "data", scatter_dimension=0, tiled=True
        )

    sm = shard_map_compat(f, mesh=mesh, in_specs=(P(None, "data"),),
                          out_specs=P("data"), check_vma=False)
    return jax.make_jaxpr(sm)(
        jax.ShapeDtypeStruct((16, 8), jnp.int32)
    )


def test_wire_dtype_red_to_green():
    """The dtype contract, parameterized (satellite of the int16 wire
    plan): a deliberately f32-widened reduce-scatter wire FAILS
    wire_dtype("int32"); the int32 wire passes — and the same int32
    wire FAILS wire_dtype("int16"), which is what pins the ROADMAP 3a
    flip once QUANT_WIRE_DTYPE changes."""
    from lightgbm_tpu.analysis.jaxpr_audit import audit_jaxpr, wire_dtype

    red = audit_jaxpr(_wire_fixture_jaxpr(widen=True),
                      [wire_dtype("int32")], "widened")
    assert not red.ok, red.format()
    green = audit_jaxpr(_wire_fixture_jaxpr(widen=False),
                        [wire_dtype("int32")], "int32")
    assert green.ok, green.format()
    # after the int16 flip, today's int32 wire must read as a regression
    not_halved = audit_jaxpr(_wire_fixture_jaxpr(widen=False),
                             [wire_dtype("int16")], "int32-vs-int16")
    assert not not_halved.ok, not_halved.format()


def test_entry_table_records_quant_wire_dtype():
    """The quant data-parallel entry declares its wire dtype in the
    entry table (the cost auditor and the jaxpr contract both read
    it), and it matches the module-level QUANT_WIRE_DTYPE flip point."""
    from lightgbm_tpu.analysis.jaxpr_audit import ENTRIES, QUANT_WIRE_DTYPE

    assert ENTRIES["rounds_quant_rs"].wire_dtype == QUANT_WIRE_DTYPE
    # ROADMAP 3a flipped in round 12 (rs_wire_dtype narrowest-exact
    # policy); the int32 step-down regime keeps its own pinned entry
    assert QUANT_WIRE_DTYPE == "int16"
    assert ENTRIES["rounds_quant_rs_int32"].wire_dtype == "int32"


def test_host_callback_contract_red_to_green():
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.jaxpr_audit import (
        audit_jaxpr,
        no_host_callbacks,
    )

    def dirty(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct((4,), jnp.float32), x,
        )

    red = audit_jaxpr(
        jax.make_jaxpr(dirty)(jax.ShapeDtypeStruct((4,), jnp.float32)),
        [no_host_callbacks()], "callback",
    )
    assert not red.ok
    green = audit_jaxpr(
        jax.make_jaxpr(lambda x: x * 2)(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        ),
        [no_host_callbacks()], "clean",
    )
    assert green.ok


def test_eqn_budget_contract_red_to_green():
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.jaxpr_audit import audit_jaxpr, within_budget

    closed = jax.make_jaxpr(lambda x: jnp.sin(x) + jnp.cos(x) * 2)(
        jax.ShapeDtypeStruct((4,), jnp.float32)
    )
    assert not audit_jaxpr(closed, [within_budget(1)], "tiny").ok
    assert audit_jaxpr(closed, [within_budget(100)], "roomy").ok
    # a missing checked-in budget is a FAILURE, not a skip
    assert not audit_jaxpr(closed, [within_budget(None)], "nobudget").ok


def test_rs_exact_ok_bounds():
    """The overflow/exactness gate (ADVICE r5 medium) as pure policy:
    global rows * levels < 2^31 AND local rows * levels < 2^24."""
    from lightgbm_tpu.learner.histogram import rs_exact_ok

    assert rs_exact_ok(2048, 8, 16)
    # local bound: rows * levels hits exactly 2^24 -> inexact f32 cast
    assert rs_exact_ok(2 ** 16 - 1, 8, 256)  # 16776960 < 2^24: ok
    assert not rs_exact_ok(2 ** 16, 8, 256)  # 2^24 exactly: gate off
    # global int32 wrap ISOLATED from the local bound: per-shard sum
    # 16776960 < 2^24 is fine, but 256 ranks push the global cell sum
    # to ~4.29e9 > 2^31 — only the global clause can catch this
    assert not rs_exact_ok(2 ** 16 - 1, 256, 256)
    # unquantized callers pass levels=0 -> treated as exact counts
    assert rs_exact_ok(2 ** 20, 8, 0)


def test_grower_wire_contracts_end_to_end():
    """The real entries: inside the bounds the int32 reduce-scatter
    wire is present end to end; past the per-shard bound the overflow
    gate removes it and the f32 psum fallback appears. (Red-to-green
    for the gate: before rounds.py grew rs_exact_ok, the overflow
    entry traced a reduce_scatter and this test fails.)"""
    from lightgbm_tpu.analysis.jaxpr_audit import run_audits

    results = {
        r.name: r
        for r in run_audits(
            names=["rounds_quant_rs", "rounds_quant_rs_overflow"]
        )
    }
    ok_entry = results["rounds_quant_rs"]
    assert ok_entry.ok, ok_entry.format()
    over = results["rounds_quant_rs_overflow"]
    assert over.ok, over.format()


def test_fold_attr_static_audit_green():
    from lightgbm_tpu.analysis.jaxpr_audit import audit_fold_attrs

    r = audit_fold_attrs()
    assert r.ok, r.format()


def test_fold_attr_runtime_audit_red_to_green():
    """A fold-varying device array outside _OBJ_FOLD_ATTRS must fail
    loudly at fused build time (ADVICE r5 item 3)."""
    import jax.numpy as jnp

    from lightgbm_tpu.boosting import _audit_fold_attrs
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.log import LightGBMError
    from lightgbm_tpu.objectives import create_objective

    obj = create_objective(Config({"objective": "regression"}))
    obj.label = jnp.zeros(8, jnp.float32)
    _audit_fold_attrs(obj)  # green: listed attrs only
    obj._evil_fold_state = jnp.ones(8, jnp.float32)
    with pytest.raises(LightGBMError, match="_evil_fold_state"):
        _audit_fold_attrs(obj)


# ------------------------------------------------------- retrace guard
def test_retrace_guard_red_to_green(retrace_guard):
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.retrace import RetraceError

    @jax.jit
    def f(x):
        return x * 2 + 1

    f(jnp.ones(3))  # warmup
    with retrace_guard(entry_points=[f], what="stable shapes") as rep:
        f(jnp.ones(3))
        f(jnp.zeros(3))
    assert rep.per_entry["f"] == 0

    # deliberately retracing function: every call sees a fresh shape
    with pytest.raises(RetraceError, match="f: 2 new trace-cache"):
        with retrace_guard(entry_points=[f], what="drifting shapes"):
            f(jnp.ones(4))
            f(jnp.ones(5))


def test_retrace_guard_leak_detection(retrace_guard):
    import jax
    import jax.numpy as jnp

    leaked = []

    with pytest.raises(Exception, match="[Ll]eak"):
        with retrace_guard(check_leaks=True):

            @jax.jit
            def g(x):
                leaked.append(x)  # tracer escapes the trace
                return x

            g(jnp.ones(2))


def test_grower_trains_without_retrace(retrace_guard):
    """The training entry point itself: a second identically-shaped
    tree growth must reuse the first trace (the regression class the
    guard exists for)."""
    import jax.numpy as jnp

    import lightgbm_tpu as lgb
    from lightgbm_tpu.learner.grower import grow_tree

    rs = np.random.RandomState(0)
    X = rs.randn(400, 5)
    y = (X @ rs.randn(5) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "tpu_growth_mode": "exact"}
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    lgb.train(params, ds, num_boost_round=2)  # warmup traces everything
    with retrace_guard(entry_points=[grow_tree], max_retraces=0,
                       what="repeated identical training"):
        ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
        lgb.train(params, ds2, num_boost_round=2)


# ----------------------------------------------------- strict CLI hook
@pytest.mark.slow
def test_cli_strict_exits_zero():
    """`python -m lightgbm_tpu.analysis --strict` is the CI hook: a new
    unsuppressed lint violation or broken jaxpr contract fails it."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.analysis", "--strict"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "analysis: clean" in proc.stdout


def test_strict_equivalent_in_process():
    """The same strict gate, in-process (runs in tier-1 even when the
    subprocess variant is skipped as slow): zero unsuppressed findings
    from BOTH AST linters AND every jaxpr/fold-attr audit green. (The
    cost/memory compiles are covered by their own tests in
    test_cost_audit.py plus the slow CLI test above — recompiling all
    five entries here would double tier-1's audit wall time.)"""
    from lightgbm_tpu.analysis.concurrency_lint import (
        concurrency_lint_package,
    )
    from lightgbm_tpu.analysis.jaxpr_audit import run_audits

    fs = lint_package(str(REPO / "lightgbm_tpu"))
    assert not [f for f in fs if not f.suppressed], format_findings(fs)
    cfs = concurrency_lint_package(str(REPO / "lightgbm_tpu"))
    assert not [f for f in cfs if not f.suppressed], \
        format_findings(cfs, label="concurrency")
    results = run_audits()
    bad = [r.format() for r in results if not r.ok]
    assert not bad, "\n".join(bad)
    # the bench-trajectory gate (Pass 6) runs in tier-1 too: cheap
    # JSON parsing, and a regressed checked-in BENCH point must fail
    # the suite just like a lint violation would
    from lightgbm_tpu.analysis.bench_gate import run_gate

    gate = run_gate()
    assert gate.ok, gate.format()
    # Pass 7 (scaling contracts) tier-1 hook: the tiny D in {1, 2}
    # ladder on the three law archetypes (1/D, elected + its baseline,
    # bounded) — budget pins still checked EXACT at those rungs. The
    # int32/overflow entries and the 4/8 rungs ride --strict /
    # tools/analysis.sh; re-tracing all five entries at every rung
    # here would blow the tier-1 time budget.
    from lightgbm_tpu.analysis.scale_audit import (
        TIER1_LADDER,
        run_scale_audits,
    )

    sresults = run_scale_audits(
        names=["rounds_quant_rs", "rounds_voting", "feature_parallel"],
        ladder=TIER1_LADDER,
    )
    sbad = [r.format() for r in sresults if not r.ok]
    assert not sbad, "\n".join(sbad)
