"""Pairwise composability grid (VERDICT r3 #4): every
(tree_learner x feature-flag) pair must either train cleanly or fail
with a documented LightGBMError — never crash mid-iteration or train
silently-wrong trees. The reference composes these freely
(tree_learner.cpp:17-59); where this build degrades (warn + fallback)
the degraded path must still produce a working model."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

LEARNERS = ["serial", "data", "voting", "feature"]

FLAGS = {
    "plain": {},
    "efb_sparse": {},  # sparse data triggers bundling (marker handled below)
    "extra_trees": {"extra_trees": True},
    "bynode": {"feature_fraction_bynode": 0.5},
    "cegb": {"cegb_tradeoff": 0.5, "cegb_penalty_split": 1e-5},
    "interaction": {"interaction_constraints": [[0, 1, 2], [3, 4, 5]]},
    "quantized": {"use_quantized_grad": True},
    "rounds": {"tpu_growth_mode": "rounds"},
    "monotone": {"monotone_constraints": [1, -1, 0, 0, 0, 0]},
    "linear": {"linear_tree": True},
}


def _data(sparse: bool, seed=0):
    rs = np.random.RandomState(seed)
    n, f = 2048, 6
    if sparse:
        X = np.zeros((n, f))
        for j in range(f):
            m = rs.rand(n) < 0.15
            X[m, j] = rs.randn(int(m.sum()))
    else:
        X = rs.randn(n, f)
    y = (X[:, 0] + X[:, 1] - X[:, 2] + 0.3 * rs.randn(n) > 0).astype(float)
    return X, y


@pytest.mark.parametrize("learner", LEARNERS)
@pytest.mark.parametrize("flag", sorted(FLAGS))
def test_pairwise_compose(learner, flag, tmp_path):
    sparse = flag == "efb_sparse"
    X, y = _data(sparse)
    params = dict(
        objective="binary",
        num_leaves=8,
        min_data_in_leaf=5,
        verbosity=-1,
        tree_learner=learner,
        **FLAGS[flag],
    )
    if flag == "linear" and learner in ("data", "feature", "voting"):
        pytest.skip("linear_tree is host-side (sync loop), mesh-agnostic")
    ds = lgb.Dataset(X, label=y, free_raw_data=False,
                     params={"linear_tree": True} if flag == "linear" else None)
    try:
        bst = lgb.train(params, ds, num_boost_round=3)
    except lgb.basic.LightGBMError as e:  # documented hard failure is OK
        pytest.skip(f"documented fatal: {e}")
    assert bst.num_trees() == 3
    pred = bst.predict(X[:64])
    assert np.isfinite(pred).all()
    assert pred.min() >= 0.0 and pred.max() <= 1.0


def test_voting_with_forced_falls_back(tmp_path):
    """voting + forcedsplits: the election is disabled (stale non-elected
    histogram columns would corrupt forced splits) but training runs."""
    import json

    X, y = _data(False, seed=2)
    p = tmp_path / "forced.json"
    p.write_text(json.dumps({"feature": 0, "threshold": 0.0}))
    params = dict(objective="binary", num_leaves=8, verbosity=-1,
                  tree_learner="voting", forcedsplits_filename=str(p))
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(params, ds, num_boost_round=2)
    assert bst.num_trees() == 2
    for t in bst._gbdt.models:
        assert int(t.split_feature[0]) == 0


def test_voting_composes_with_efb():
    """voting + EFB: bundle-column election (no enable_bundle=false
    requirement); the elected-column model must still learn."""
    from sklearn.metrics import roc_auc_score

    X, y = _data(True, seed=3)
    params = dict(objective="binary", num_leaves=8, min_data_in_leaf=5,
                  verbosity=-1, tree_learner="voting", top_k=3)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(params, ds, num_boost_round=10)
    assert bst.num_trees() == 10
    assert roc_auc_score(y, bst.predict(X)) > 0.75
