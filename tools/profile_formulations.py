"""Compare grower formulations on the real device: permuted vs flat.

Times ONE grow_tree call (after warmup) for each formulation at
1M x 28 / 255 leaves — isolates the grower from objective/metric/eval.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params

ROWS = int(os.environ.get("P_ROWS", 1_000_000))
FEATS = int(os.environ.get("P_FEATS", 28))
LEAVES = int(os.environ.get("P_LEAVES", 255))
REPS = int(os.environ.get("P_REPS", 3))

rs = np.random.RandomState(7)
X = rs.randn(ROWS, FEATS).astype(np.float32)
y = (X[:, 0] + rs.randn(ROWS) > 0).astype(np.float32)

cfg = Config({"objective": "binary", "num_leaves": LEAVES, "max_bin": 255})
ds = BinnedDataset.from_numpy(X, cfg, label=y)
d = ds.device_arrays()
N = ds.num_rows_padded()
F = ds.num_used_features

grad = jnp.asarray(rs.randn(N).astype(np.float32)) * d["valid"]
hess = jnp.ones(N, jnp.float32) * d["valid"]
mask = d["valid"]
feat_mask = jnp.ones(F, bool)
params = make_split_params(cfg)

print(f"platform={jax.devices()[0].platform} N={N} F={F} B={ds.max_num_bin}")

variants = [
    ("permuted", dict(partition="permuted")),
    ("flat_gather", dict(partition="flat", gather_hist=True)),
    ("flat_masked", dict(partition="flat", gather_hist=False)),
]
sel = os.environ.get("P_VARIANTS")
if sel:
    variants = [v for v in variants if v[0] in sel.split(",")]

for name, kw in variants:
    spec = GrowerSpec(
        num_leaves=LEAVES, num_bins=ds.max_num_bin, max_depth=-1, **kw
    )
    t0 = time.time()
    tree, row_leaf = grow_tree(
        d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
        grad, hess, mask, feat_mask, params, spec, valid=d["valid"],
    )
    jax.block_until_ready(row_leaf)
    compile_s = time.time() - t0
    times = []
    for r in range(REPS):
        t0 = time.time()
        tree, row_leaf = grow_tree(
            d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
            grad, hess, mask, feat_mask, params, spec, valid=d["valid"],
        )
        jax.block_until_ready(row_leaf)
        times.append(time.time() - t0)
    nn = int(tree.num_nodes)
    print(
        f"{name:12s} compile+1st={compile_s:7.2f}s "
        f"steady={min(times):7.3f}s/tree nodes={nn}",
        flush=True,
    )
