"""Native (C++) runtime components, loaded through ctypes.

The reference's data loader is C++ (src/io/parser.cpp, text_reader.h);
this package holds the TPU build's native equivalents. Libraries are
compiled ON DEMAND with the system toolchain (g++ -O3 -shared) and
cached next to the source; everything degrades gracefully to the pure
NumPy fallbacks when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fastparse.cpp")
_LIB = os.path.join(_DIR, "_fastparse.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """Compile fastparse to a tmp file and atomically rename into
    place. The rename makes concurrent builders safe WITHOUT a lock:
    each builder — thread or process — writes its own tmp .so (pid +
    thread id in the name) and os.replace is atomic, so a reader only
    ever sees a complete library — get_lib deliberately does not hold
    the module lock across this (the concurrency linter's
    blocking-under-lock rule: a 180 s g++ run under `_lock` would
    stall every thread touching the parser)."""
    import time

    from ..obs.metrics import record_native_build

    tmp = f"{_LIB}.build.{os.getpid()}.{threading.get_ident()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", tmp,
    ]
    t0 = time.perf_counter()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=180)
        if r.returncode != 0:
            from .. import log

            record_native_build(time.perf_counter() - t0, ok=False)
            log.warning(
                f"native fastparse build failed (falling back to numpy "
                f"parsers): {r.stderr.strip()[-300:]}"
            )
            return False
        os.replace(tmp, _LIB)
        record_native_build(time.perf_counter() - t0, ok=True)
        return True
    except (OSError, subprocess.TimeoutExpired):
        record_native_build(time.perf_counter() - t0, ok=False)
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _load_or_build() -> Optional[ctypes.CDLL]:
    """Build-if-stale + dlopen + bind, called OUTSIDE the module lock
    (only the _lib/_tried state below is lock-guarded)."""
    fresh = (
        os.path.exists(_LIB)
        and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
    )
    if not fresh and not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        return None
    try:
        _bind(lib)
    except AttributeError:
        # stale cached .so (newer mtime than the source but built
        # from an older version, e.g. rsync -t / restored backup):
        # rebuild once, then give up gracefully
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
            _bind(lib)
        except (OSError, AttributeError):
            return None
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The fastparse library, building it on first use; None if
    unavailable (no g++ / build failure). Concurrent first callers may
    each run a build (atomic-rename safe); the winner's handle is the
    one cached."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
    lib = _load_or_build()
    with _lock:
        # prefer a non-None result: a transiently-failing concurrent
        # loader must not cache None over another thread's good handle
        if not _tried or (_lib is None and lib is not None):
            _tried = True
            _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.fp_parse_delim.restype = ctypes.c_int
    lib.fp_parse_delim.argtypes = [
        ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.fp_parse_libsvm.restype = ctypes.c_int
    lib.fp_parse_libsvm.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.fp_free.restype = None
    lib.fp_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
    lib.fp_greedy_find_bin.restype = ctypes.c_int64
    lib.fp_greedy_find_bin.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.fp_values_to_bins.restype = None
    lib.fp_values_to_bins.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
    ]
    P = ctypes.POINTER
    lib.fp_predict.restype = ctypes.c_int64
    lib.fp_predict.argtypes = [
        P(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
        P(ctypes.c_int32), ctypes.c_int64,
        P(ctypes.c_int64), P(ctypes.c_int32), P(ctypes.c_double),
        P(ctypes.c_int32), P(ctypes.c_int32), P(ctypes.c_int32),
        P(ctypes.c_int64), P(ctypes.c_double),
        P(ctypes.c_uint32), P(ctypes.c_int64), P(ctypes.c_int64),
        P(ctypes.c_double),
    ]


def _take(lib, ptr, shape) -> np.ndarray:
    arr = np.ctypeslib.as_array(ptr, shape=shape).copy()
    lib.fp_free(ptr)
    return arr


def parse_delim(path: str, delim: str, skip_rows: int) -> Optional[np.ndarray]:
    """(rows, cols) float64 matrix, or None when native is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_double)()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.fp_parse_delim(
        path.encode(), delim.encode(), skip_rows,
        ctypes.byref(out), ctypes.byref(rows), ctypes.byref(cols),
    )
    if rc != 0:
        return None
    return _take(lib, out, (rows.value, cols.value))


def greedy_find_bin(distinct: np.ndarray, counts: np.ndarray, max_bin: int,
                    total_cnt: int, min_data_in_bin: int
                    ) -> Optional[np.ndarray]:
    """Native GreedyFindBin (bit-exact C++ mirror of binning.py:46 /
    reference bin.cpp:80); None when the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    distinct = np.ascontiguousarray(distinct, dtype=np.float64)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(max(int(max_bin), 1) + 2, dtype=np.float64)
    n = lib.fp_greedy_find_bin(
        distinct.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(distinct), int(max_bin), int(total_cnt), int(min_data_in_bin),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out[:n]


def values_to_bins(values: np.ndarray, bounds: np.ndarray, nan_target: int
                   ) -> Optional[np.ndarray]:
    """Native multithreaded numerical ValueToBin; None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.float64)
    bounds = np.ascontiguousarray(bounds, dtype=np.float64)
    out = np.empty(len(values), dtype=np.int32)
    lib.fp_values_to_bins(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(values),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(bounds), int(nan_target),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out


class PackedModel:
    """Flat tree arrays for fp_predict, built once per Booster model
    state (reference SingleRowPredictor caching, c_api.cpp:66).

    This offset-flat layout (per-tree node/leaf offsets into shared 1-D
    arrays) is the host/C++ walker's shape; the TPU serving predictor
    packs the same per-tree fields into DENSE (T, max_nodes) tables
    instead (serving/forest.py pack_forest_tables), because lockstep
    device traversal wants every lane indexing one rectangular table.
    Decision semantics must stay identical across all three predictors
    (tree.py go_left is the single source of truth; the serving parity
    tests assert it)."""

    def __init__(self, trees) -> None:
        n_nodes = [max(t.num_leaves - 1, 0) for t in trees]
        off = np.zeros(len(trees) + 1, np.int64)
        np.cumsum(n_nodes, out=off[1:])
        loff = np.zeros(len(trees) + 1, np.int64)
        np.cumsum([max(t.num_leaves, 1) for t in trees], out=loff[1:])
        tot = int(off[-1])
        self.node_off = off
        self.leaf_off = loff
        self.feature = np.zeros(tot, np.int32)
        self.threshold = np.zeros(tot, np.float64)
        self.dtype = np.zeros(tot, np.int32)
        self.left = np.zeros(tot, np.int32)
        self.right = np.zeros(tot, np.int32)
        self.leaf_value = np.zeros(int(loff[-1]), np.float64)
        catw_parts = []
        self.cat_lo = np.zeros(tot, np.int64)
        self.cat_hi = np.zeros(tot, np.int64)
        wbase = 0
        for ti, t in enumerate(trees):
            a, b = int(off[ti]), int(off[ti + 1])
            if b > a:
                self.feature[a:b] = t.split_feature[: b - a]
                self.threshold[a:b] = t.threshold[: b - a]
                self.dtype[a:b] = np.asarray(
                    t.decision_type[: b - a], np.int32
                )
                self.left[a:b] = t.left_child[: b - a]
                self.right[a:b] = t.right_child[: b - a]
                cb = np.asarray(t.cat_boundaries, np.int64)
                words = np.asarray(t.cat_threshold, np.uint32)
                if len(words):
                    catw_parts.append(words)
                cat_k = a + np.flatnonzero(self.dtype[a:b] & 1)
                if len(cat_k):
                    ci = self.threshold[cat_k].astype(np.int64)
                    self.cat_lo[cat_k] = wbase + cb[ci]
                    self.cat_hi[cat_k] = wbase + cb[ci + 1]
                wbase += len(words)
            la = int(loff[ti])
            lv = np.asarray(t.leaf_value, np.float64)
            self.leaf_value[la : la + len(lv)] = lv
        self.catw = (
            np.concatenate(catw_parts).astype(np.uint32)
            if catw_parts else np.zeros(1, np.uint32)
        )
        # widest feature referenced: callers must verify X has more
        # columns (the numpy walk raises IndexError; the C side would
        # read out of bounds)
        self.max_feature = int(self.feature.max()) if tot else -1


def predict_packed(pm: "PackedModel", X: np.ndarray,
                   tree_idx: np.ndarray) -> Optional[np.ndarray]:
    """Sum of leaf outputs of `tree_idx` trees per row; None when the
    native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    if X.shape[1] <= pm.max_feature:
        return None  # host walk raises the proper IndexError
    X = np.ascontiguousarray(X, dtype=np.float64)
    tree_idx = np.ascontiguousarray(tree_idx, dtype=np.int32)
    out = np.empty(X.shape[0], np.float64)

    def p(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    lib.fp_predict(
        p(X, ctypes.c_double), X.shape[0], X.shape[1],
        p(tree_idx, ctypes.c_int32), len(tree_idx),
        p(pm.node_off, ctypes.c_int64), p(pm.feature, ctypes.c_int32),
        p(pm.threshold, ctypes.c_double), p(pm.dtype, ctypes.c_int32),
        p(pm.left, ctypes.c_int32), p(pm.right, ctypes.c_int32),
        p(pm.leaf_off, ctypes.c_int64), p(pm.leaf_value, ctypes.c_double),
        p(pm.catw, ctypes.c_uint32), p(pm.cat_lo, ctypes.c_int64),
        p(pm.cat_hi, ctypes.c_int64), p(out, ctypes.c_double),
    )
    return out


def parse_libsvm(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(labels (N,), dense features (N, F)) or None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_double)()
    lab = ctypes.POINTER(ctypes.c_double)()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.fp_parse_libsvm(
        path.encode(), ctypes.byref(out), ctypes.byref(lab),
        ctypes.byref(rows), ctypes.byref(cols),
    )
    if rc != 0:
        return None
    feats = _take(lib, out, (rows.value, cols.value))
    labels = _take(lib, lab, (rows.value,))
    return labels, feats
