"""Multi-host training support (the reference's distributed runtime).

The reference runs one CLI process per machine connected by a
hand-rolled socket/MPI collective layer (src/network/linkers_socket.cpp
full-mesh TCP, network.cpp ring/halving collectives). The TPU-native
equivalent is JAX's multi-controller runtime: one process per host,
`jax.distributed.initialize` forms the cluster, and every collective in
the growers (psum / all_gather / psum_scatter) rides ICI within a slice
and DCN across hosts through the SAME code path as single-host — no
separate network layer.

This module maps the reference's network configuration
(`machines` / `machine_list_filename` / `num_machines` /
`local_listen_port`, config.h network params; python
`lgb.set_network`) onto `jax.distributed.initialize`, and provides the
pre-partitioned data assembly (`pre_partition=true` semantics,
dataset_loader.cpp:210: each rank holds its own row shard):

- `init_distributed(...)`: join/form the cluster.
- `allgather_binning_sample(sample)`: the reference's distributed
  binning (dataset_loader.cpp:1174: per-rank FindBin samples are
  allgathered so every rank builds IDENTICAL bin mappers).
- `global_rows(host_array, mesh, row_axis)`: assemble a process-local
  row shard into one global device array over the mesh
  (jax.make_array_from_process_local_data).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def resolve_rank(machines: Sequence[str], local_listen_port: int) -> int:
    """Best-effort self-rank discovery by local address match (the
    reference matches local IPs against the machine list,
    linkers_socket.cpp:38-49); falls back to the JAX_PROCESS_ID env."""
    import os
    import socket

    env = os.environ.get("JAX_PROCESS_ID")
    if env is not None:
        return int(env)
    local_names = {socket.gethostname(), "localhost", "127.0.0.1"}
    try:
        local_names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    for i, m in enumerate(machines):
        host, _, port = m.partition(":")
        if host in local_names and (not port or int(port) == local_listen_port):
            return i
    raise RuntimeError(
        "cannot determine this process's rank: no machine entry matches a "
        "local address; set JAX_PROCESS_ID or pass machine_rank"
    )


def init_distributed(
    machines: Optional[str] = None,
    machine_list_file: Optional[str] = None,
    num_machines: Optional[int] = None,
    local_listen_port: int = 12400,
    machine_rank: Optional[int] = None,
) -> int:
    """Join the multi-host cluster from reference-style network params.

    The first machine in the list is the coordinator (the reference has
    no coordinator — its socket mesh is symmetric — but rank 0 is the
    canonical choice). Returns this process's rank. No-op when the
    cluster is already initialized.
    """
    import jax

    # NOTE: no jax.process_count()/devices() probe here — touching the
    # backend before jax.distributed.initialize() poisons it
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "client", None) is not None:
        return jax.process_index()
    mlist = []
    if machine_list_file:
        with open(machine_list_file) as f:
            mlist = [ln.strip() for ln in f if ln.strip()]
    elif machines:
        mlist = [m.strip() for m in machines.split(",") if m.strip()]
    if not mlist:
        raise ValueError("init_distributed needs machines or machine_list_file")
    n = num_machines or len(mlist)
    rank = machine_rank if machine_rank is not None else resolve_rank(
        mlist, local_listen_port
    )
    coord = mlist[0]
    if ":" not in coord:
        coord = f"{coord}:{local_listen_port}"
    from ..resilience.backoff import retry_call

    # cluster join races the coordinator's startup: workers that boot
    # first see connection errors. Bounded retry-with-backoff instead
    # of failing the whole fleet on a few seconds' skew
    # (docs/RESILIENCE.md "Distributed recovery").
    retry_call(
        lambda: jax.distributed.initialize(
            coordinator_address=coord, num_processes=n, process_id=rank
        ),
        retries=3,
        base_s=1.0,
        retry_on=(OSError, RuntimeError),
        describe=f"jax.distributed.initialize({coord}, rank {rank})",
    )
    return rank


def gather_host_rows(arr: np.ndarray) -> np.ndarray:
    """Allgather a per-process host array (1-D or row-major N-D) with
    UNEVEN leading lengths into the process-order concatenation (every
    rank returns the same array): rows are padded to the cluster max and
    trimmed back after the gather. Used for global init-score statistics
    (gbdt.cpp BoostFromAverage must produce ONE value per cluster) and
    the distributed binning sample."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    arr = np.asarray(arr)
    n = arr.shape[0]
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray(n, np.int64))
    ).reshape(-1)
    mx = int(counts.max())
    pad = np.zeros((mx,) + arr.shape[1:], arr.dtype)
    pad[:n] = arr
    g = np.asarray(multihost_utils.process_allgather(pad))  # (P, mx, ...)
    return np.concatenate([g[i, : counts[i]] for i in range(len(counts))])


def allgather_binning_sample(sample: np.ndarray) -> np.ndarray:
    """Concatenate every process's binning sample (rows) so all ranks
    derive identical BinMappers (dataset_loader.cpp:1174-1250)."""
    return gather_host_rows(sample)


def host_global_array(a) -> np.ndarray:
    """Full host copy of a (possibly globally-sharded) device array on
    EVERY process — np.asarray raises on arrays spanning other
    processes' devices; those take a tiled process_allgather."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(a)
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(a, tiled=True))
    return np.asarray(a)


def global_rows(arr: np.ndarray, mesh, axis: int = 0):
    """Assemble per-process row shards into one global array sharded
    over the mesh's 'data' axis (pre_partition semantics: this
    process's rows are its shard; shards concatenate in process order).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * arr.ndim
    spec[axis] = "data"
    sharding = NamedSharding(mesh, P(*spec))
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


def write_metrics_snapshot(out_dir: str) -> str:
    """Dump THIS process's metrics registry as a snapshot file in a
    shared directory (obs/aggregate.py schema). Pure host-side I/O —
    deliberately not a jax collective, so fleet observability works on
    backends without cross-process collectives (the xfail'd CPU
    multihost configuration, docs/DESIGN_DECISIONS.md) and keeps
    working when the training fabric itself is what broke."""
    import os

    import jax

    from ..obs import aggregate

    os.makedirs(out_dir, exist_ok=True)
    rank = jax.process_index()
    path = os.path.join(out_dir, f"metrics_rank{rank:05d}.json")
    aggregate.write_snapshot(path, process=rank)
    return path


def merged_fleet_snapshot(out_dir: str):
    """Merge every worker's snapshot file from `out_dir` into one
    fleet view (counters sum across processes; gauges sum with min/max
    spread — see obs/aggregate.py). Any process can call this; it
    reads only files."""
    import glob
    import os

    from ..obs import aggregate

    paths = glob.glob(os.path.join(out_dir, "metrics_rank*.json"))
    if not paths:
        raise FileNotFoundError(
            f"no metrics_rank*.json snapshots under {out_dir}; call "
            "write_metrics_snapshot on each worker first"
        )
    return aggregate.merge_files(paths)


def run_distributed(
    params: dict,
    X: np.ndarray,
    y: np.ndarray,
    *,
    machines: Optional[str] = None,
    machine_list_file: Optional[str] = None,
    machine_rank: Optional[int] = None,
    num_machines: Optional[int] = None,
    local_listen_port: int = 12400,
    num_boost_round: int = 100,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    valid: Optional[tuple] = None,  # (Xv, yv) — rank-local validation shard
    callbacks: Optional[list] = None,
    obs_snapshot_dir: Optional[str] = None,  # shared dir for fleet metrics
):
    """One-call multi-host training — the python-package analog of
    dask.py:415 `_train`: joins the cluster from reference-style network
    params, builds IDENTICAL bin mappers on every rank from the
    allgathered binning sample (dataset_loader.cpp:1174 distributed
    binning), equalizes per-rank row padding for the global mesh, and
    runs `lgb.train(tree_learner=data)` over all processes' devices.

    `X`/`y` are THIS RANK's row shard (`pre_partition=true` semantics,
    config.h). Returns the Booster — identical on every rank (lockstep
    guarantee); save from rank 0.
    """
    import jax

    from .. import engine
    from ..basic import Dataset

    rank = init_distributed(
        machines=machines,
        machine_list_file=machine_list_file,
        num_machines=num_machines,
        local_listen_port=local_listen_port,
        machine_rank=machine_rank,
    )

    params = dict(params)
    params.setdefault("tree_learner", "data")
    params["num_machines"] = jax.process_count()

    # ---- identical mappers everywhere: bin on the global sample
    sample_cnt = int(params.get("bin_construct_sample_cnt", 200000))
    per_rank = max(1, sample_cnt // max(jax.process_count(), 1))
    if len(X) > per_rank:
        rs = np.random.RandomState(int(params.get("data_random_seed", 1)))
        idx = np.sort(rs.choice(len(X), per_rank, replace=False))
        local_sample = np.ascontiguousarray(X[idx], dtype=np.float64)
    else:
        local_sample = np.ascontiguousarray(X, dtype=np.float64)
    global_sample = allgather_binning_sample(local_sample)
    bin_ref = Dataset(
        global_sample,
        label=np.zeros(len(global_sample)),
        params={k: v for k, v in params.items()
                if k not in ("tree_learner", "num_machines")},
        free_raw_data=True,
    )
    bin_ref.construct()

    ds = Dataset(
        X, label=y, weight=weight, group=group,
        reference=bin_ref, free_raw_data=False,
    )
    ds.construct()
    # per-rank row-padding equalization happens inside GBDT setup
    # (boosting.py data-parallel init) AFTER the final row_block is
    # known — doing it here would be undone by ensure_row_block

    valid_sets = None
    valid_names = None
    if valid is not None:
        # every rank evaluates the FULL validation set (rank-local valid
        # shards are allgathered) so metrics — and therefore early
        # stopping — are identical across the cluster; the reference
        # reaches the same property through its metric allreduce
        Xv = allgather_binning_sample(
            np.ascontiguousarray(valid[0], dtype=np.float64)
        )
        yv = gather_host_rows(np.asarray(valid[1], dtype=np.float64))
        vs = Dataset(Xv, label=yv, reference=bin_ref, free_raw_data=False)
        valid_sets = [vs]
        valid_names = ["valid"]

    heartbeat = None
    if obs_snapshot_dir:
        # per-worker liveness files next to the metrics snapshots: a
        # rank that dies mid-train stops beating, and rank 0's health
        # report (below) names it — without any collective, so death
        # detection works precisely when the training fabric is what
        # broke (docs/RESILIENCE.md "Distributed recovery")
        from ..resilience.heartbeat import HeartbeatWriter

        heartbeat = HeartbeatWriter(obs_snapshot_dir, rank)
        heartbeat.start()
    try:
        bst = engine.train(
            params, ds, num_boost_round=num_boost_round,
            valid_sets=valid_sets, valid_names=valid_names,
            callbacks=callbacks,
        )
    finally:
        if heartbeat is not None:
            # clean exits write a final beat; a crash here leaves the
            # file stale, which is exactly what flags the death
            heartbeat.stop()
    bst._distributed_rank = rank
    if obs_snapshot_dir:
        # fleet observability: every rank dumps its registry; rank 0
        # merges the files into one view (host-side only — works even
        # where jax cross-process collectives don't). Deliberately no
        # barrier: ranks that haven't flushed yet are just absent, so
        # the merge reports HOW MANY snapshots it saw and warns when
        # partial — re-merge offline via merged_fleet_snapshot once
        # every worker has written.
        write_metrics_snapshot(obs_snapshot_dir)
        if rank == 0:
            merged = merged_fleet_snapshot(obs_snapshot_dir)
            bst._fleet_metrics = merged
            from .. import log
            from ..resilience.heartbeat import health_report

            health = health_report(
                obs_snapshot_dir, expected=jax.process_count()
            )
            bst._fleet_health = health
            if not health["healthy"]:
                log.warning(
                    f"fleet health: stale rank(s) {health['stale']}, "
                    f"missing rank(s) {health['missing']} — a worker "
                    "likely died mid-train; restart the fleet with "
                    "resume=auto to continue from the last checkpoint"
                )

            n = merged.get("processes", 0)
            total = jax.process_count()
            if n < total:
                log.warning(
                    f"fleet metrics merged from only {n}/{total} worker "
                    f"snapshot(s) under {obs_snapshot_dir} — stragglers "
                    "missing; re-merge offline with "
                    "merged_fleet_snapshot for the complete view"
                )
            else:
                log.info(
                    f"fleet metrics merged from {n} worker snapshot(s) "
                    f"under {obs_snapshot_dir}"
                )
    return bst
