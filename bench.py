"""Benchmark: Higgs-1M-like GBDT training throughput on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's published Higgs result — 500 iterations of
255-leaf trees over 10.5M x 28 in 130.094 s on 2xE5-2690v4
(reference docs/Experiments.rst:104-121, see BASELINE.md). Scaled
linearly to this bench's row count (histogram GBDT cost is ~linear in
rows), i.e. baseline trees/sec at R rows = (500 / 130.094) * (10.5e6 / R).

Env overrides: BENCH_ROWS, BENCH_FEATURES, BENCH_LEAVES, BENCH_TREES,
BENCH_WARMUP, BENCH_MAX_BIN.
"""

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    leaves = int(os.environ.get("BENCH_LEAVES", 255))
    trees = int(os.environ.get("BENCH_TREES", 10))
    warmup = int(os.environ.get("BENCH_WARMUP", 2))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 255))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import lightgbm_tpu as lgb

    rs = np.random.RandomState(17)
    X = rs.randn(rows, feats).astype(np.float32)
    w = rs.randn(feats)
    logits = X[:, : feats // 2] @ w[: feats // 2] + np.sin(X[:, feats // 2]) * 2.0
    y = (logits + rs.randn(rows) > 0).astype(np.float32)

    params = {
        "objective": "binary",
        "num_leaves": leaves,
        "max_bin": max_bin,
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "metric": "auc",
        "verbosity": -1,
    }
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    ds.construct()

    # warmup: compile + first trees
    bst = lgb.train(dict(params), ds, num_boost_round=warmup)
    t0 = time.time()
    bst2 = lgb.train(dict(params), ds, num_boost_round=trees)
    dt = time.time() - t0

    trees_per_sec = trees / dt
    baseline_tps = (500.0 / 130.094) * (10.5e6 / rows)
    auc = None
    try:
        from sklearn.metrics import roc_auc_score

        auc = float(roc_auc_score(y[:100000], bst2.predict(X[:100000])))
    except Exception:
        pass

    out = {
        "metric": f"higgs_synth_{rows // 1000}k_{leaves}leaves_trees_per_sec",
        "value": round(trees_per_sec, 4),
        "unit": "trees/sec",
        "vs_baseline": round(trees_per_sec / baseline_tps, 4),
    }
    if auc is not None:
        out["auc_100k"] = round(auc, 5)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
