"""Quantized-gradient training (use_quantized_grad,
gradient_discretizer.cpp:22 semantics through the dequantized-value
formulation in learner/quantize.py)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.learner.quantize import discretize_gradients


def test_discretize_levels_and_scales():
    rs = np.random.RandomState(0)
    g = jnp.asarray(rs.randn(5000).astype(np.float32))
    h = jnp.asarray((0.1 + rs.rand(5000)).astype(np.float32))
    nb = 4
    gq, hq = discretize_gradients(g, h, jax.random.key(0), nb, True)
    g_scale = float(jnp.max(jnp.abs(g))) / (nb // 2)
    h_scale = float(jnp.max(jnp.abs(h))) / nb
    # dequantized values sit exactly on the level grid
    lev_g = np.asarray(gq) / g_scale
    lev_h = np.asarray(hq) / h_scale
    np.testing.assert_allclose(lev_g, np.round(lev_g), atol=1e-4)
    np.testing.assert_allclose(lev_h, np.round(lev_h), atol=1e-4)
    assert np.abs(lev_g).max() <= nb // 2 + 1e-6
    assert lev_h.min() >= 0 and lev_h.max() <= nb + 1e-6
    # stochastic rounding is unbiased: mean error ~ 0
    assert abs(float(jnp.mean(gq - g))) < 3 * g_scale / np.sqrt(len(lev_g))


def test_deterministic_rounding():
    g = jnp.asarray(np.linspace(-1, 1, 101, dtype=np.float32))
    h = jnp.ones(101, jnp.float32)
    gq, _ = discretize_gradients(g, h, jax.random.key(0), 4, False)
    # plain rounding: nearest level (truncate after +0.5 toward zero)
    g_scale = 1.0 / 2
    np.testing.assert_allclose(
        np.asarray(gq) / g_scale,
        np.trunc(np.asarray(g) / g_scale + np.sign(np.asarray(g)) * 0.5),
        atol=1e-6,
    )


def _problem(n=4000, seed=1):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 8)
    w = rs.randn(8)
    y = ((X @ w + 0.5 * rs.randn(n)) > 0).astype(float)
    return X, y


@pytest.mark.parametrize("renew", [False, True])
def test_quantized_training_quality(renew):
    """AUC with 4-bin quantized gradients stays within tolerance of full
    precision (the reference's quantized-training guarantee)."""
    from sklearn.metrics import roc_auc_score

    X, y = _problem()
    params = {
        "objective": "binary",
        "num_leaves": 31,
        "learning_rate": 0.1,
        "verbosity": -1,
    }
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    full = lgb.train(dict(params), ds, num_boost_round=30)
    auc_full = roc_auc_score(y, full.predict(X))

    ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
    quant = lgb.train(
        {**params, "use_quantized_grad": True,
         "quant_train_renew_leaf": renew},
        ds2, num_boost_round=30,
    )
    auc_q = roc_auc_score(y, quant.predict(X))
    assert auc_q > auc_full - 0.01, (auc_q, auc_full)
    # quantization must actually change the model
    assert not np.allclose(quant.predict(X[:100]), full.predict(X[:100]))


def test_quantized_rides_fused_loop():
    X, y = _problem(seed=3)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "use_quantized_grad": True, "metric": "auc"},
        ds, num_boost_round=10, valid_sets=[ds], valid_names=["t"],
    )
    assert bst._gbdt.fused_eligible()
    assert bst.num_trees() == 10


def test_quantized_regression_l2():
    X, _ = _problem(seed=5)
    rs = np.random.RandomState(6)
    y = X @ rs.randn(8) + 0.2 * rs.randn(len(X))
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    q = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "use_quantized_grad": True},
        ds, num_boost_round=30,
    )
    mse = float(np.mean((q.predict(X) - y) ** 2))
    assert mse < 0.3 * float(np.var(y)), mse


def test_quantized_rounds_matches_dequantized_semantics():
    """The rounds grower's exact-int histogram path (spec.quant) must
    produce the same trees as feeding the DEQUANTIZED values through the
    standard channels: int sums x scale == sums of (level x scale)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params
    from lightgbm_tpu.learner.quantize import discretize_gradients_int

    rs = np.random.RandomState(3)
    X = rs.randn(4096, 6).astype(np.float32)
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5})
    ds = BinnedDataset.from_numpy(X, cfg)
    d = ds.device_arrays()
    N = ds.num_rows_padded()
    F = ds.num_used_features
    g = jnp.asarray(rs.randn(N).astype(np.float32)) * d["valid"]
    h = (jnp.ones(N, jnp.float32) * 0.25) * d["valid"]
    gq, hq, scale = discretize_gradients_int(g, h, jax.random.key(1), 4, False)
    params = make_split_params(Config({"num_leaves": 31, "max_bin": 63,
                                       "min_data_in_leaf": 5}))
    base = dict(num_leaves=31, num_bins=ds.max_num_bin, max_depth=-1)
    spec_q = GrowerSpec(**base, rounds_slots=25, quant=True)
    spec_f = GrowerSpec(**base, rounds_slots=25)
    tq, rlq = grow_tree(
        d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
        gq, hq, d["valid"], jnp.ones(F, bool), params, spec_q,
        valid=d["valid"], gh_scale=scale,
    )
    tf, rlf = grow_tree(
        d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
        gq * scale[0], hq * scale[1], d["valid"], jnp.ones(F, bool), params,
        spec_f, valid=d["valid"],
    )
    assert int(tq.num_nodes) == int(tf.num_nodes)
    np.testing.assert_array_equal(np.asarray(rlq), np.asarray(rlf))
    np.testing.assert_allclose(np.asarray(tq.leaf_value),
                               np.asarray(tf.leaf_value), atol=1e-5)


def test_quantized_multiclass_parity():
    """use_quantized_grad on multiclass (K gradient channels per
    iteration): accuracy and logloss stay within tolerance of the
    unquantized path (VERDICT r5 weak #4)."""
    rs = np.random.RandomState(11)
    n = 3000
    X = rs.randn(n, 8)
    centers = rs.randn(3, 8)
    y = np.argmax(X @ centers.T + 0.5 * rs.randn(n, 3), axis=1)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
              "verbosity": -1, "min_data_in_leaf": 5}
    full = lgb.train(dict(params),
                     lgb.Dataset(X, label=y, free_raw_data=False),
                     num_boost_round=20)
    quant = lgb.train({**params, "use_quantized_grad": True},
                      lgb.Dataset(X, label=y, free_raw_data=False),
                      num_boost_round=20)
    pf, pq = full.predict(X), quant.predict(X)
    acc_f = float(np.mean(np.argmax(pf, axis=1) == y))
    acc_q = float(np.mean(np.argmax(pq, axis=1) == y))
    eps = 1e-15
    ll_f = -float(np.mean(np.log(np.clip(pf[np.arange(n), y], eps, 1))))
    ll_q = -float(np.mean(np.log(np.clip(pq[np.arange(n), y], eps, 1))))
    assert acc_q > acc_f - 0.02, (acc_q, acc_f)
    assert ll_q < ll_f + 0.05, (ll_q, ll_f)
    # quantization must actually change the model
    assert not np.allclose(pf[:100], pq[:100])


def test_quantized_lambdarank_parity():
    """use_quantized_grad on LambdaRank: NDCG@5 parity with the
    unquantized path (VERDICT r5 weak #4)."""
    from sklearn.metrics import ndcg_score

    rs = np.random.RandomState(12)
    n_q, per_q = 40, 50
    n = n_q * per_q
    X = rs.randn(n, 8)
    rel = np.clip((X[:, 0] + X[:, 1] + 0.4 * rs.randn(n)) + 2, 0, 4)
    y = rel.astype(int)
    group = np.full(n_q, per_q)
    params = {"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 2}

    def ndcg5(bst):
        s = bst.predict(X)
        return float(np.mean([
            ndcg_score(y[q * per_q:(q + 1) * per_q][None, :],
                       s[q * per_q:(q + 1) * per_q][None, :], k=5)
            for q in range(n_q)
        ]))

    full = lgb.train(dict(params),
                     lgb.Dataset(X, label=y, group=group,
                                 free_raw_data=False),
                     num_boost_round=20)
    quant = lgb.train({**params, "use_quantized_grad": True},
                      lgb.Dataset(X, label=y, group=group,
                                  free_raw_data=False),
                      num_boost_round=20)
    nf, nq = ndcg5(full), ndcg5(quant)
    assert nq > nf - 0.02, (nq, nf)
    assert not np.allclose(full.predict(X[:100]), quant.predict(X[:100]))


def test_quantized_rounds_via_train_api():
    rs = np.random.RandomState(6)
    X = rs.randn(3000, 6)
    y = (X[:, 0] + X[:, 1] ** 2 + 0.3 * rs.randn(3000) > 1).astype(float)
    from sklearn.metrics import roc_auc_score

    params = dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
                  verbosity=-1, use_quantized_grad=True,
                  tpu_growth_mode="rounds")
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(params, ds, num_boost_round=8)
    assert roc_auc_score(y, bst.predict(X)) > 0.9
