#!/bin/bash
# Build the reference LightGBM CLI (/root/reference) for the parity
# harness. The image's cmake (3.25) is older than the reference's
# cmake_minimum_required (3.28), and the vendored submodules
# (fmt / fast_double_parser / eigen) are not checked out, so this
# compiles directly with g++ using the shim headers in this directory
# (strtod-backed fast_double_parser, snprintf-backed fmt::format_to_n,
# and an Eigen-free linear_tree stub that aborts if linear_tree=true).
#
# Usage: tools/refbuild/build.sh [REFERENCE_DIR] [OUT_DIR]
set -e
REF="${1:-/root/reference}"
OUT="${2:-$(dirname "$0")/../../.refbuild}"
SHIMS="$(cd "$(dirname "$0")" && pwd)"
mkdir -p "$OUT"
cd "$OUT"

if [ -x lightgbm ]; then
  echo "reference CLI already built: $OUT/lightgbm"
  exit 0
fi

ls "$REF"/src/*.cpp "$REF"/src/*/*.cpp 2>/dev/null \
  | grep -v cuda | grep -v c_api | grep -v linear_tree_learner > srcs.txt

g++ -O2 -std=c++17 -fopenmp \
  -DUSE_SOCKET -DMM_PREFETCH -DMM_MALLOC \
  -I"$SHIMS" -I"$REF/include" -I"$REF/src/treelearner" \
  $(cat srcs.txt) "$SHIMS/linear_tree_learner_stub.cpp" \
  -o lightgbm -lpthread

echo "built: $OUT/lightgbm"
