"""path_smooth and monotone-constraint interval propagation
(feature_histogram.hpp CalculateSplittedLeafOutput USE_SMOOTHING branch,
monotone_constraints.hpp:489 BasicLeafConstraints::Update)."""

from __future__ import annotations

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=3000, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 5)
    y = (
        2.0 * X[:, 0]
        + np.sin(3 * X[:, 1])
        + 0.5 * X[:, 2] * X[:, 3]
        + 0.3 * rs.randn(n)
    )
    return X, y


def test_path_smooth_changes_and_regularizes():
    X, y = _problem()
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "learning_rate": 0.2, "min_data_in_leaf": 5}

    def leaves(ps):
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train({**base, "path_smooth": ps}, ds, num_boost_round=5)
        d = bst.dump_model()
        vals = []

        def walk(node):
            if "leaf_value" in node:
                vals.append(node["leaf_value"])
            else:
                walk(node["left_child"])
                walk(node["right_child"])

        for t in d["tree_info"]:
            walk(t["tree_structure"])
        return np.asarray(vals), bst.predict(X)

    v0, p0 = leaves(0.0)
    v10, p10 = leaves(10.0)
    vbig, pbig = leaves(1e6)
    assert not np.allclose(p0, p10)
    # smoothing pulls leaf outputs toward their parents: the spread of
    # leaf values shrinks monotonically with the smoothing strength
    assert np.std(v10) < np.std(v0)
    assert np.std(vbig) < 0.1 * np.std(v0)


def test_path_smooth_quality_parity_with_reference():
    """Smoothed training still learns (sanity against over-shrinkage)."""
    X, y = _problem(seed=2)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "path_smooth": 1.0, "learning_rate": 0.1},
        ds, num_boost_round=40,
    )
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.5 * float(np.var(y)), mse


def _check_monotone(bst, X, feat, direction, n_checks=40, n_grid=25):
    rs = np.random.RandomState(1)
    rows = X[rs.choice(len(X), n_checks, replace=False)]
    grid = np.linspace(X[:, feat].min(), X[:, feat].max(), n_grid)
    for r in rows:
        tiled = np.tile(r, (n_grid, 1))
        tiled[:, feat] = grid
        pred = bst.predict(tiled)
        diffs = np.diff(pred) * direction
        assert (diffs >= -1e-9).all(), (
            f"monotone violation on feature {feat}: {diffs.min()}"
        )


@pytest.mark.parametrize("direction", [1, -1])
def test_monotone_constraints_hold_globally(direction):
    """Deep trees must respect the constraint through INHERITED intervals
    — candidate-level ordering alone (round-2 implementation) fails
    this for descendants of a constrained split."""
    rs = np.random.RandomState(3)
    n = 4000
    X = rs.randn(n, 4)
    # strong non-monotone dependence on x0 tempts violations
    y = direction * (1.5 * X[:, 0] + 0.8 * np.sin(4 * X[:, 0])) + X[:, 1] + 0.2 * rs.randn(n)
    mono = [direction, 0, 0, 0]
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 63, "verbosity": -1,
         "monotone_constraints": mono, "learning_rate": 0.2,
         "min_data_in_leaf": 3},
        ds, num_boost_round=15,
    )
    _check_monotone(bst, X, 0, direction)


def test_monotone_constraint_reference_cli_agrees(tmp_path):
    """Same constrained config through the reference CLI: both must hold
    the constraint; quality within tolerance."""
    import subprocess
    from pathlib import Path

    CLI = Path(__file__).resolve().parent.parent / ".refbuild" / "lightgbm"
    if not CLI.exists():
        pytest.skip("reference CLI not built")
    rs = np.random.RandomState(5)
    n = 3000
    X = rs.randn(n, 3)
    y = 1.2 * X[:, 0] + np.sin(3 * X[:, 0]) + X[:, 1] + 0.2 * rs.randn(n)
    np.savetxt(tmp_path / "tr.tsv", np.column_stack([y, X]),
               delimiter="\t", fmt="%.6f")
    r = subprocess.run(
        [str(CLI), "task=train", "objective=regression", "data=tr.tsv",
         "num_trees=10", "num_leaves=31", "monotone_constraints=1,0,0",
         "output_model=ref.txt", "verbosity=-1"],
        cwd=tmp_path, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    ref = lgb.Booster(model_file=tmp_path / "ref.txt")

    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    ours = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "monotone_constraints": [1, 0, 0]},
        ds, num_boost_round=10,
    )
    _check_monotone(ours, X, 0, 1, n_checks=20)
    mse_ref = float(np.mean((ref.predict(X) - y) ** 2))
    mse_ours = float(np.mean((ours.predict(X) - y) ** 2))
    assert mse_ours <= mse_ref * 1.2, (mse_ours, mse_ref)


def test_unimplemented_params_warn(capsys):
    """Honest params: anything accepted-but-inert must warn. The list
    has shrunk as features landed (linear_tree / extra_trees /
    interaction_constraints / cegb_* / position bias are implemented
    now); forced splits remain pending."""
    X, y = _problem(n=500, seed=7)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": 0,
         "forcedsplits_filename": "splits.json"},
        ds, num_boost_round=1,
    )
    text = capsys.readouterr().err
    assert "forcedsplits_filename" in text

    # implemented params must NOT warn
    ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
    lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": 0,
         "extra_trees": True, "interaction_constraints": "[0,1],[2,3]",
         "cegb_penalty_split": 0.1},
        ds2, num_boost_round=1,
    )
    text2 = capsys.readouterr().err
    assert "has no effect" not in text2


@pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
@pytest.mark.parametrize("direction", [1, -1])
def test_monotone_methods_violation_scan(method, direction):
    """Deep-tree violation scan for all three constraint methods
    (monotone_constraints.hpp basic:489, intermediate:516,
    advanced:858). On this default (exact-oracle) path advanced
    downgrades to the intermediate formulation with a warning — the
    true advanced refinement rides the rounds grower
    (test_monotone_rounds_mode_violation_scan)."""
    rs = np.random.RandomState(5)
    n = 4000
    X = rs.randn(n, 4)
    y = direction * (1.5 * X[:, 0] + 0.8 * np.sin(4 * X[:, 0])) \
        + X[:, 1] + 0.2 * rs.randn(n)
    mono = [direction, 0, 0, 0]
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 63, "verbosity": -1,
         "monotone_constraints": mono, "learning_rate": 0.2,
         "min_data_in_leaf": 3, "monotone_constraints_method": method},
        ds, num_boost_round=10,
    )
    _check_monotone(bst, X, 0, direction)


@pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
@pytest.mark.parametrize("direction", [1, -1])
def test_monotone_rounds_mode_violation_scan(method, direction):
    """Monotone constraints on the TPU fast path (VERDICT r4 item 3 +
    ISSUE 14): the round-batched grower enforces basic via inherited
    intervals, intermediate via the per-round ancestry-bounds recompute
    with the same-round opposite-subtree conflict guard, and advanced
    via the per-leaf bin-range overlap refinement of the
    opposite-subtree extrema (monotone_constraints.hpp:858) — deep
    trees grown in rounds mode must hold the constraint globally under
    all three."""
    rs = np.random.RandomState(5)
    n = 4000
    X = rs.randn(n, 4)
    y = direction * (1.5 * X[:, 0] + 0.8 * np.sin(4 * X[:, 0])) \
        + X[:, 1] + 0.2 * rs.randn(n)
    mono = [direction, 0, 0, 0]
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 63, "verbosity": -1,
         "monotone_constraints": mono, "learning_rate": 0.2,
         "min_data_in_leaf": 3, "monotone_constraints_method": method,
         "tpu_growth_mode": "rounds"},
        ds, num_boost_round=10,
    )
    _check_monotone(bst, X, 0, direction)


def test_monotone_advanced_mode_resolution():
    """method=advanced resolves to mono_mode=2 on the rounds path and
    downgrades to the intermediate formulation (mono_mode=1, with a
    warning) on the exact oracle, which only implements intermediate."""
    rs = np.random.RandomState(3)
    X = rs.randn(1500, 3)
    y = 1.1 * X[:, 0] + 0.5 * X[:, 1] + 0.2 * rs.randn(1500)
    modes = {}
    for mode in ("rounds", "exact"):
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(
            {"objective": "regression", "num_leaves": 15, "verbosity": -1,
             "monotone_constraints": [1, 0, 0],
             "monotone_constraints_method": "advanced",
             "tpu_growth_mode": mode},
            ds, num_boost_round=2,
        )
        modes[mode] = int(bst._gbdt.spec.mono_mode)
    assert modes == {"rounds": 2, "exact": 1}


def test_monotone_rounds_quality_close_to_exact():
    """Rounds-mode constrained training must stay within tolerance of
    the sequential exact grower's quality (same config, both methods)."""
    rs = np.random.RandomState(9)
    n = 4000
    X = rs.randn(n, 4)
    y = 1.2 * X[:, 0] + 0.6 * np.sin(3 * X[:, 0]) + 0.8 * X[:, 1] \
        + 0.2 * rs.randn(n)
    for method in ("basic", "intermediate"):
        mse = {}
        for mode in ("exact", "rounds"):
            ds = lgb.Dataset(X, label=y, free_raw_data=False)
            bst = lgb.train(
                {"objective": "regression", "num_leaves": 31,
                 "verbosity": -1, "monotone_constraints": [1, 0, 0, 0],
                 "learning_rate": 0.15, "min_data_in_leaf": 5,
                 "monotone_constraints_method": method,
                 "tpu_growth_mode": mode},
                ds, num_boost_round=15,
            )
            mse[mode] = float(np.mean((bst.predict(X) - y) ** 2))
        assert mse["rounds"] <= mse["exact"] * 1.15, (method, mse)


def test_monotone_intermediate_quality_at_least_basic():
    """The intermediate method bounds children by the opposite
    subtree's ACTUAL extrema instead of the frozen split midpoint —
    strictly weaker constraints, so training loss must not regress
    (reference docs: intermediate 'may slow the library very slightly'
    but 'should improve the results')."""
    rs = np.random.RandomState(8)
    n = 5000
    X = rs.randn(n, 4)
    y = 1.2 * X[:, 0] + 0.6 * np.sin(3 * X[:, 0]) + 0.8 * X[:, 1] \
        + 0.2 * rs.randn(n)
    mse = {}
    for method in ("basic", "intermediate"):
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(
            {"objective": "regression", "num_leaves": 31, "verbosity": -1,
             "monotone_constraints": [1, 0, 0, 0], "learning_rate": 0.15,
             "min_data_in_leaf": 5,
             "monotone_constraints_method": method},
            ds, num_boost_round=20,
        )
        mse[method] = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse["intermediate"] <= mse["basic"] * 1.02, mse
