"""Data-parallel tree growth: rows sharded, histograms psum'd.

Reference algorithm: src/treelearner/data_parallel_tree_learner.cpp —
  BeforeTrain: allreduce root (count, sum_grad, sum_hess)      (:169-221)
  FindBestSplits: local hists for all features -> ReduceScatter (:286)
  best split on aggregated hists -> allreduce-max split         (:443)
  Split: identical on all ranks using global counts             (:453)

Here the whole loop lives inside one `shard_map`-wrapped jit: `grow_tree`
takes `axis_name="data"` and issues `lax.psum` on root sums and on each
smaller-child histogram; everything downstream is computed redundantly
(and identically) on every shard, so trees stay in lockstep without any
split broadcast — the same invariant the reference relies on
(SURVEY §3.3). The psum payload per split is one (3, F, B) f32 histogram,
matching the reference's wire payload of histogram pairs. Under
tree_learner=voting the per-round election (rounds.py vote_reduce) cuts
that payload to the elected ~2k columns, in int16 when the quantized
sums provably fit.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..learner.grower import GrowerSpec, TreeArrays, grow_tree
from ..learner.split import SplitParams


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check_vma):
    """jax.shard_map across jax versions: new jax exposes it with a
    `check_vma` flag; 0.4.x ships jax.experimental.shard_map with the
    equivalent `check_rep` (and interim versions expose jax.shard_map
    still taking check_rep — probe the signature, not the version)."""
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    kw = "check_vma" if "check_vma" in params else "check_rep"
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{kw: check_vma})


def make_mesh(devices=None, axis_name: str = "data") -> Mesh:
    """1-D data mesh over all (or given) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


class DataParallelGrower:
    """Wraps grow_tree in shard_map over a 1-D data mesh.

    Rows (the leading `nblocks` axis of the blocked bin matrix and every
    per-row vector) are sharded over `axis_name`; per-feature vectors and
    split params are replicated; the returned TreeArrays are replicated
    (verified identical by construction) and row_leaf stays row-sharded.
    """

    def __init__(self, mesh: Mesh, spec: GrowerSpec, axis_name: str = "data"):
        self.mesh = mesh
        self.axis_name = axis_name
        n = int(mesh.devices.size)
        self.spec = spec._replace(axis_name=axis_name, axis_size=n)
        # (num_features -> payload bytes per grown tree) memo
        self._wire_est: dict = {}
        s = self.spec
        if (n > 1 and s.quant and not s.efb and not s.has_cat
                and not s.cat_subset and not s.mono_mode
                and not s.voting_k and not s.n_forced
                and not (s.extra_trees or s.ff_bynode or s.cegb
                         or s.n_groups)):
            from .. import log

            # ring collective wire per rank per round: allreduce moves
            # ~2(n-1)/n of the buffer, reduce-scatter (n-1)/n — and the
            # per-rank histogram pool shrinks to its owned feature block
            log.info(
                f"data-parallel histogram wire: int32 reduce-scatter "
                f"with per-rank feature ownership ({n} ranks) — ~2x "
                f"less wire per round and 1/{n} the histogram-pool "
                f"memory vs the f32 full-psum path (bin.h:63-81, "
                f"data_parallel_tree_learner.cpp:286); engaged only "
                f"while the worst-case integer sums stay exact "
                f"(histogram.rs_exact_ok: global < 2^31, per-shard "
                f"< 2^24), else the f32 psum path"
            )

        row = P(axis_name)  # shard the row axis of per-row vectors
        bins_spec = P(None, axis_name)  # bins are (F, N): rows on axis 1
        rep = P()

        def fn(bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
               feat_mask, params, valid, bundle, rng_key, group_mat, cegb,
               forced, gh_scale):
            tree, row_leaf = grow_tree(
                bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
                feat_mask, params, self.spec, valid=valid, bundle=bundle,
                rng_key=rng_key, group_mat=group_mat, cegb=cegb,
                forced=forced, gh_scale=gh_scale,
            )
            # tree state is identical on all shards (computed from psum'd
            # histograms); mark it replicated for the out_spec
            tree = jax.tree.map(lambda a: jax.lax.pmean(a, axis_name) if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)
            return tree, row_leaf

        in_specs = (bins_spec, rep, rep, rep, rep, row, row, row, rep, rep,
                    row, rep, rep, rep, rep, rep, rep)
        out_specs = (jax.tree.map(lambda _: rep, _tree_arrays_structure(spec)), row)
        self._fn = jax.jit(
            shard_map_compat(
                fn,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            )
        )

    def __call__(self, bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask,
                 feat_mask, params: SplitParams, valid, bundle=None,
                 rng_key=None, group_mat=None, cegb=None, forced=None,
                 gh_scale=None) -> Tuple[TreeArrays, jax.Array]:
        return self._fn(
            bins, nan_bin, num_bins, mono, is_cat, grad, hess, mask, feat_mask,
            params, valid, bundle, rng_key, group_mat, cegb, forced, gh_scale,
        )

    def wire_bytes_per_tree(self, num_features: int) -> int:
        """Host-side estimate of the collective payload per grown tree:
        one (channels, F, B) histogram reduce per split plus the root
        sums, 4-byte lanes (f32 psum or int32 reduce-scatter) — the
        RUNTIME twin of the static wire pins in
        analysis/cost_budget.json (obs/manifest.py puts the two side by
        side). Memoized per num_features. Boosting records this from
        its HOST loops, never from traced code, so the counter ticks
        per dispatched tree."""
        if self.spec.axis_size <= 1:
            return 0
        F = int(num_features)
        est = self._wire_est.get(F)
        if est is None:
            s = self.spec
            cols = F
            if s.voting_k:
                # voting-parallel: only the elected columns (2k, plus
                # any pinned forced-plan columns) cross the mesh per
                # round (rounds.py vote_reduce). 4-byte lanes is the
                # conservative bound — the quantized election wire may
                # ride int16 (histogram.rs_wire_dtype, decided from the
                # traced row count); the exact per-config payload is
                # pinned statically in analysis cost_budget.json.
                cols = min(2 * int(s.voting_k) + int(s.n_forced), F)
            per_split = 3 * cols * int(s.num_bins) * 4
            est = per_split * int(s.num_leaves)
            self._wire_est[F] = est
        return est

    def shard_inputs(self, dev: dict) -> dict:
        """device_put the dataset arrays with the right shardings.

        Multi-process clusters (jax.distributed): per-row arrays are
        PROCESS-LOCAL shards assembled into global arrays
        (pre_partition=true semantics, each rank contributed its rows);
        single-process meshes device_put directly."""
        from ..learner.histogram import HIST_BLK

        n_dev = self.mesh.devices.size
        n_rows = dev["bins"].shape[1]
        platform = jax.devices()[0].platform
        multiproc = jax.process_count() > 1
        local_dev = n_dev // jax.process_count() if multiproc else n_dev
        if platform == "tpu" and (n_rows // max(local_dev, 1)) % HIST_BLK != 0:
            from .. import log

            log.warning(
                f"per-shard rows ({n_rows}/{local_dev}) are not a multiple of "
                f"the pallas histogram block ({HIST_BLK}); histograms will use "
                f"the slow einsum fallback — pad rows to row_block*num_devices"
            )
        row = NamedSharding(self.mesh, P(self.axis_name))
        rep = NamedSharding(self.mesh, P())
        out = dict(dev)
        if multiproc:
            from .multihost import global_rows

            def put_rep(a):
                return jax.make_array_from_process_local_data(
                    rep, np.asarray(a)
                )

            out["bins"] = global_rows(np.asarray(dev["bins"]), self.mesh, axis=1)
            out["valid"] = global_rows(np.asarray(dev["valid"]), self.mesh, axis=0)
        else:

            def put_rep(a):
                return jax.device_put(a, rep)

            out["bins"] = jax.device_put(
                dev["bins"], NamedSharding(self.mesh, P(None, self.axis_name))
            )
            out["valid"] = jax.device_put(dev["valid"], row)
        for k in ("nan_bin", "num_bins", "mono", "is_cat"):
            out[k] = put_rep(dev[k])
        if dev.get("bundle") is not None:
            out["bundle"] = jax.tree.map(put_rep, dev["bundle"])
        return out


def _tree_arrays_structure(spec: GrowerSpec) -> TreeArrays:
    """A dummy TreeArrays with the right pytree structure for out_specs."""
    L = spec.num_leaves
    z = jnp.zeros
    return TreeArrays(
        num_nodes=z((), jnp.int32),
        node_feature=z(L - 1, jnp.int32), node_bin=z(L - 1, jnp.int32),
        node_gain=z(L - 1, jnp.float32), node_default_left=z(L - 1, bool),
        node_cat=z(L - 1, bool),
        node_cat_mask=z((L - 1, spec.num_bins), bool),
        node_left=z(L - 1, jnp.int32),
        node_right=z(L - 1, jnp.int32), node_value=z(L - 1, jnp.float32),
        node_weight=z(L - 1, jnp.float32), node_count=z(L - 1, jnp.float32),
        leaf_value=z(L, jnp.float32), leaf_weight=z(L, jnp.float32),
        leaf_count=z(L, jnp.float32), leaf_depth=z(L, jnp.int32),
    )
