"""Distributed training over a jax.sharding.Mesh.

TPU-native replacement for the reference's socket/MPI collective layer
(src/network/, include/LightGBM/network.h:89) and its parallel tree
learners (src/treelearner/*_parallel_tree_learner.cpp): rows are sharded
over a "data" mesh axis, per-shard histograms are globally reduced with
`lax.psum` riding ICI (the reference's ReduceScatter+Allgather,
network.cpp:93-95), and every shard then computes the identical best
split and partitions its local rows in lockstep — exactly the
data-parallel algorithm, with XLA collectives instead of TCP.
"""

from .data_parallel import DataParallelGrower, make_mesh

__all__ = ["DataParallelGrower", "make_mesh"]
