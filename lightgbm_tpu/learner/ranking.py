"""Device-resident learning-to-rank: LambdaRank gradients and NDCG.

Reference: src/objective/rank_objective.hpp:137-271 (LambdarankNDCG —
per-query score sort, pairwise delta-NDCG weighted sigmoid lambdas,
truncation level, optional norm), src/metric/rank_metric.hpp +
src/treelearner/../dcg_calculator.cpp (NDCG@k).

TPU formulation: queries are laid out as a PADDED (Q, M) index matrix
into the flat padded row axis (M = max docs per query, host-built once
per dataset). Each evaluation gathers scores/labels into (Q, M), sorts
along the doc axis, forms the (M, M) pairwise tensors for a CHUNK of
queries at a time under lax.map (memory stays bounded while total work
matches the reference's O(sum cnt^2) pair loop), and scatters gradients
back to flat rows. No host sync anywhere — lambdarank becomes
fused-loop eligible and ndcg a device metric.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple

import numpy as np


class QueryLayout(NamedTuple):
    """Static per-dataset query structure (host-built)."""

    qdoc: np.ndarray  # (Q, M) int32 flat row index; npad (out of range) = pad
    qvalid: np.ndarray  # (Q, M) bool
    num_queries: int
    max_docs: int
    npad: int  # flat padded row count


_layout_cache: dict = {}


def build_query_layout(group: np.ndarray, npad: int) -> QueryLayout:
    """Cached: the objective and every ndcg metric of a dataset share one
    layout (and thus one (Q, M) device constant after jit dedup)."""
    group = np.asarray(group, dtype=np.int64)
    key = (group.tobytes(), npad)
    hit = _layout_cache.get(key)
    if hit is not None:
        return hit
    qb = np.concatenate([[0], np.cumsum(group)]).astype(np.int64)
    Q = len(group)
    M = int(group.max()) if Q else 1
    qdoc = np.full((Q, M), npad, dtype=np.int32)
    qvalid = np.zeros((Q, M), dtype=bool)
    for q in range(Q):
        c = int(group[q])
        qdoc[q, :c] = np.arange(qb[q], qb[q + 1], dtype=np.int32)
        qvalid[q, :c] = True
    out = QueryLayout(qdoc, qvalid, Q, M, npad)
    if len(_layout_cache) > 64:
        _layout_cache.clear()
    _layout_cache[key] = out
    if M > 4096:
        from .. import log

        log.warning(
            f"a query with {M} documents makes the pairwise lambda tensor "
            f"{M}x{M}; expect high memory use — consider splitting giant "
            "queries (reference hits the same O(cnt^2) pair loop cost)"
        )
    return out


def default_label_gain(max_label: int) -> np.ndarray:
    """DCGCalculator::DefaultLabelGain: 2^i - 1."""
    return np.asarray([(1 << i) - 1 for i in range(max_label + 1)], np.float64)


def check_label_range(label: np.ndarray, num_gains: int) -> None:
    """DCGCalculator::CheckLabel: every label must index label_gain;
    host-validated once so the traced device fns can index freely."""
    mx = int(np.asarray(label).max()) if len(label) else 0
    if mx >= num_gains:
        from .. import log

        log.fatal(
            f"label {mx} exceeds label_gain size {num_gains}; set "
            "label_gain to cover all relevance levels"
        )


def inverse_max_dcg(
    label: np.ndarray, layout: QueryLayout, label_gain: np.ndarray, k: int
) -> np.ndarray:
    """1 / MaxDCG@k per query (0 when MaxDCG == 0); host, once per init."""
    out = np.zeros(layout.num_queries)
    lab = np.where(layout.qvalid, label[np.clip(layout.qdoc, 0, len(label) - 1)], -1)
    for q in range(layout.num_queries):
        lq = lab[q][layout.qvalid[q]].astype(int)
        srt = np.sort(lq)[::-1][:k]
        dcg = np.sum(label_gain[srt] / np.log2(np.arange(len(srt)) + 2.0))
        out[q] = 1.0 / dcg if dcg > 0 else 0.0
    return out


def _chunk(Q: int, M: int) -> int:
    """Queries per lax.map step: bound the (chunk, M, M) pair tensors to
    ~32 MB of f32."""
    per_query = 4 * M * M * 6  # ~6 live (M, M) f32 tensors
    return max(1, min(Q, (32 << 20) // max(per_query, 1)))


def lambdarank_gradients(
    layout: QueryLayout,
    score,  # (npad,) f32 device
    label,  # (npad,) f32 device
    label_gain,  # (G,) f32 device
    inv_max_dcg,  # (Q,) f32 device — at truncation level
    sigmoid: float,
    truncation_level: int,
    norm: bool,
):
    """(grad, hess) on the flat padded row axis; pure device fn.

    Matches GetGradientsForOneQuery (rank_objective.hpp:182-271)
    including the norm path's delta_ndcg /= (0.01 + |delta_score|)
    regularization and the log2(1+sum_lambdas)/sum_lambdas rescale.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    Q, M = layout.num_queries, layout.max_docs
    npad = layout.npad
    qdoc = jnp.asarray(layout.qdoc)
    qvalid = jnp.asarray(layout.qvalid)
    chunk = _chunk(Q, M)
    Qpad = ((Q + chunk - 1) // chunk) * chunk
    if Qpad != Q:
        qdoc = jnp.pad(qdoc, ((0, Qpad - Q), (0, 0)), constant_values=npad)
        qvalid = jnp.pad(qvalid, ((0, Qpad - Q), (0, 0)))
    imd = jnp.pad(jnp.asarray(inv_max_dcg, jnp.float32), (0, Qpad - Q))

    disc = 1.0 / jnp.log2(jnp.arange(M, dtype=jnp.float32) + 2.0)  # (M,)
    NEG = jnp.float32(-1e30)

    def one_chunk(args):
        qd, qv, im = args  # (C, M), (C, M), (C,)
        s = jnp.where(qv, score[jnp.clip(qd, 0, npad - 1)], NEG)
        lb = jnp.where(qv, label[jnp.clip(qd, 0, npad - 1)], 0.0)
        order = jnp.argsort(-s, axis=1, stable=True)  # (C, M)
        ss = jnp.take_along_axis(s, order, axis=1)
        sl = jnp.take_along_axis(lb, order, axis=1)
        sv = jnp.take_along_axis(qv, order, axis=1)
        gain = label_gain[jnp.clip(sl.astype(jnp.int32), 0, label_gain.shape[0] - 1)]

        # pairwise (C, M, M): i = first (higher) rank, j = second
        i_rank = jnp.arange(M)[None, :, None]
        j_rank = jnp.arange(M)[None, None, :]
        pair = (
            (i_rank < j_rank)
            & sv[:, :, None]
            & sv[:, None, :]
            & (i_rank < truncation_level)
            & (sl[:, :, None] != sl[:, None, :])
        )
        i_high = sl[:, :, None] > sl[:, None, :]
        ds = jnp.where(
            i_high, ss[:, :, None] - ss[:, None, :], ss[:, None, :] - ss[:, :, None]
        )
        dcg_gap = jnp.abs(gain[:, :, None] - gain[:, None, :])
        pdisc = jnp.abs(disc[None, :, None] - disc[None, None, :])
        dndcg = dcg_gap * pdisc * im[:, None, None]
        if norm:
            best = ss[:, 0]
            n_valid = jnp.sum(sv, axis=1)
            worst = jnp.take_along_axis(
                ss, jnp.maximum(n_valid - 1, 0)[:, None], axis=1
            )[:, 0]
            dndcg = jnp.where(
                (best != worst)[:, None, None],
                dndcg / (0.01 + jnp.abs(ds)),
                dndcg,
            )
        p = 1.0 / (1.0 + jnp.exp(sigmoid * ds))  # GetSigmoid(delta)
        lam = -sigmoid * dndcg * p  # p_lambda (negative)
        hess = sigmoid * sigmoid * dndcg * p * (1.0 - p)
        lam = jnp.where(pair, lam, 0.0)
        hess = jnp.where(pair, hess, 0.0)

        # contribution of pair (i, j): +lam to high, -lam to low;
        # +hess to both. P[i, j] signed for row i; column sum flips sign.
        sgn = jnp.where(i_high, 1.0, -1.0)
        P = sgn * lam
        gi = jnp.sum(P, axis=2) - jnp.sum(P, axis=1)
        hi = jnp.sum(hess, axis=2) + jnp.sum(hess, axis=1)

        if norm:
            sum_lambdas = -2.0 * jnp.sum(lam, axis=(1, 2))  # (C,)
            scale = jnp.where(
                sum_lambdas > 0,
                jnp.log2(1.0 + sum_lambdas) / jnp.where(sum_lambdas > 0, sum_lambdas, 1.0),
                1.0,
            )
            gi = gi * scale[:, None]
            hi = hi * scale[:, None]

        # unsort back to document order within the query
        inv = jnp.argsort(order, axis=1)
        gi = jnp.take_along_axis(gi, inv, axis=1)
        hi = jnp.take_along_axis(hi, inv, axis=1)
        return qd, gi, hi

    qd_c = qdoc.reshape(Qpad // chunk, chunk, M)
    qv_c = qvalid.reshape(Qpad // chunk, chunk, M)
    im_c = imd.reshape(Qpad // chunk, chunk)
    qd_all, gi_all, hi_all = lax.map(one_chunk, (qd_c, qv_c, im_c))

    g = jnp.zeros(npad, jnp.float32).at[qd_all.reshape(-1)].add(
        gi_all.reshape(-1), mode="drop"
    )
    h = jnp.zeros(npad, jnp.float32).at[qd_all.reshape(-1)].add(
        hi_all.reshape(-1), mode="drop"
    )
    return g, h


def ndcg_at(
    layout: QueryLayout,
    score,  # (npad,) device
    label,  # (npad,) device
    label_gain,  # (G,) device
    ks: List[int],
):
    """Device NDCG@k for each k; mean over queries, queries with zero
    ideal DCG count as 1.0 (host NDCGMetric semantics)."""
    import jax.numpy as jnp

    qdoc = jnp.asarray(layout.qdoc)
    qvalid = jnp.asarray(layout.qvalid)
    npad = layout.npad
    M = layout.max_docs
    NEG = jnp.float32(-1e30)

    s = jnp.where(qvalid, score[jnp.clip(qdoc, 0, npad - 1)], NEG)
    lb = jnp.where(qvalid, label[jnp.clip(qdoc, 0, npad - 1)], -1.0)
    order = jnp.argsort(-s, axis=1, stable=True)
    sl = jnp.take_along_axis(lb, order, axis=1)
    sv = jnp.take_along_axis(qvalid, order, axis=1)
    ideal = -jnp.sort(-lb, axis=1)  # labels descending
    gain = lambda x: label_gain[jnp.clip(x.astype(jnp.int32), 0, label_gain.shape[0] - 1)]
    disc = 1.0 / jnp.log2(jnp.arange(M, dtype=jnp.float32) + 2.0)

    out = []
    for k in ks:
        kmask = (jnp.arange(M) < k)[None, :]
        dcg = jnp.sum(jnp.where(kmask & sv, gain(sl) * disc[None, :], 0.0), axis=1)
        idcg = jnp.sum(
            jnp.where(kmask & (ideal >= 0), gain(ideal) * disc[None, :], 0.0), axis=1
        )
        nd = jnp.where(idcg > 0, dcg / jnp.where(idcg > 0, idcg, 1.0), 1.0)
        out.append(jnp.mean(nd))
    return jnp.stack(out)


def map_at(
    layout: QueryLayout,
    score,  # (npad,) device
    label,  # (npad,) device
    ks: List[int],
):
    """Device MAP@k for each k (src/metric/map_metric.hpp CalMapAtK):
    binary relevance label > 0.5; AP@k = sum over relevant positions
    j < k of hits(j)/(j+1), normalized by min(npos, k); queries with no
    positives count 1.0. Mean over queries."""
    import jax.numpy as jnp

    qdoc = jnp.asarray(layout.qdoc)
    qvalid = jnp.asarray(layout.qvalid)
    npad = layout.npad
    M = layout.max_docs
    NEG = jnp.float32(-1e30)

    s = jnp.where(qvalid, score[jnp.clip(qdoc, 0, npad - 1)], NEG)
    lb = jnp.where(qvalid, label[jnp.clip(qdoc, 0, npad - 1)], 0.0)
    order = jnp.argsort(-s, axis=1, stable=True)
    rel = jnp.take_along_axis(lb, order, axis=1) > 0.5
    sv = jnp.take_along_axis(qvalid, order, axis=1)
    rel = rel & sv
    hits = jnp.cumsum(rel.astype(jnp.float32), axis=1)
    pos_idx = jnp.arange(M, dtype=jnp.float32)[None, :]
    prec = jnp.where(rel, hits / (pos_idx + 1.0), 0.0)
    npos = jnp.sum(rel, axis=1).astype(jnp.float32)

    out = []
    for k in ks:
        kmask = (jnp.arange(M) < k)[None, :]
        sum_ap = jnp.sum(jnp.where(kmask, prec, 0.0), axis=1)
        denom = jnp.minimum(npos, float(k))
        ap = jnp.where(npos > 0, sum_ap / jnp.maximum(denom, 1.0), 1.0)
        out.append(jnp.mean(ap))
    return jnp.stack(out)
