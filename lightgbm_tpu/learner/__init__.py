"""Tree learner: TPU-native leaf-wise GBDT tree growth.

The package-level split mirrors the reference learner decomposition
(src/treelearner/cuda/): histogram construction
(cuda_histogram_constructor.cu -> histogram.py), best-split search
(cuda_best_split_finder.cu -> split.py), partition + growth loop
(cuda_data_partition.cu + cuda_single_gpu_tree_learner.cpp -> grower.py).
"""

from .grower import GrowerSpec, TreeArrays, grow_tree, make_split_params
from .histogram import HIST_BLK, build_gh8, histogram

__all__ = [
    "GrowerSpec",
    "TreeArrays",
    "grow_tree",
    "make_split_params",
    "histogram",
    "build_gh8",
    "HIST_BLK",
]
