"""Model registry: load / version / hot-swap Boosters behind one
scoring entry point.

The reference's serving story is one SingleRowPredictor per Booster
handle (c_api.cpp:66); a real scoring service juggles many models and
replaces them under traffic. The registry keeps, per model NAME, a
monotonically versioned list of (Booster, TensorForest,
BucketDispatcher) triples and an ACTIVE version pointer:

- ``load`` accepts a text model file, a ``.json`` dump file, a raw
  model string, a dump dict, or a live Booster (text / JSON via
  model_io.py) and builds the device tables + bucket dispatcher;
- ``swap`` / ``rollback`` move the active pointer atomically (a swap
  is a pointer write under the registry lock — in-flight requests on
  the old version finish on the old tables, which stay alive until
  ``unload``);
- ``predict`` scores on whatever version is active at call time.

Because TensorForest scores through one shared jitted entry, a
hot-swap to a model with the same (trees, nodes, leaves) table shapes
and power-of-two depth bucket reuses the compiled executable — no
recompile pause under traffic.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .. import log
from ..obs.metrics import record_registry_event
from .dispatch import DEFAULT_BUCKETS, BucketDispatcher
from .forest import TensorForest


@dataclass
class ModelVersion:
    version: int
    booster: Any
    forest: TensorForest
    dispatcher: BucketDispatcher
    source: str
    loaded_at: float = field(default_factory=time.time)
    batcher: Any = None  # lazy MicroBatcher (predict via_queue=True)
    # replica dispatchers (dispatcher is replicas[0]); direct predicts
    # round-robin over these, via_queue drains through all of them
    replicas: List[BucketDispatcher] = field(default_factory=list)


def _booster_from(source: Any):
    """Anything model-shaped -> Booster (text or JSON via model_io)."""
    from ..basic import Booster

    if isinstance(source, Booster):
        return source, "booster"
    if isinstance(source, dict):
        from ..model_io import load_model_dict

        cfg, gbdt = load_model_dict(source)
        b = Booster.__new__(Booster)
        b.params, b.best_iteration, b.best_score = {}, -1, {}
        b._train_data_name, b.pandas_categorical = "training", None
        b.config, b._gbdt = cfg, gbdt
        b.train_set, b._valid_sets, b._name_valid_sets = None, [], []
        return b, "json-dict"
    s = str(source)
    # a model STRING always spans many lines; a path never does (so a
    # file named tree_v2.txt is not misread as an inline model)
    if s.lstrip().startswith("tree") and "\n" in s:
        return Booster(model_str=s), "model-string"
    if s.endswith(".json"):
        import json
        from pathlib import Path

        return _booster_from(json.loads(Path(s).read_text()))[0], s
    return Booster(model_file=s), s


def _make_host_fallback(booster, forest):
    """Degradation closure for BucketDispatcher.host_fallback: rescore
    a faulted chunk with the HOST tree-walker (Booster.predict's
    default device=None path — no jax in the loop), returning the
    dispatcher's internal layout: summed raw margins (the dispatcher
    divides average_output models itself) and a full-width leaf matrix
    with the used tree range in place (docs/RESILIENCE.md)."""
    K = forest.num_class
    T = forest.num_trees

    def fallback(chunk, start, end):
        n = chunk.shape[0]
        ni = end - start if end > start else -1
        raw = booster.predict(
            chunk, start_iteration=start, num_iteration=ni,
            raw_score=True,
        )
        raw = np.asarray(raw, np.float64).reshape(n, K)
        if forest.average_output and end > start:
            # host predict averages; the dispatcher re-divides summed
            # chunk margins by (end - start) after concatenation
            raw = raw * (end - start)
        leaf = booster.predict(
            chunk, start_iteration=start, num_iteration=ni,
            pred_leaf=True,
        )
        leaf_full = np.zeros((n, T), np.int64)
        leaf_full[:, start * K: end * K] = (
            np.asarray(leaf, np.int64).reshape(n, -1)
        )
        return raw, leaf_full

    return fallback


class ModelRegistry:
    """Thread-safe named + versioned model store (docs/SERVING.md)."""

    # online-loop attachment points (duck-typed, like the fleet op's
    # ``fleet_stats`` probe): OnlineLoop.attach installs the ingest
    # spool and the /healthz liveness probe here; the transports reach
    # them via getattr so a plain serving registry needs neither
    ingest_sink = None
    health_probe = None

    def __init__(self, mesh=None, buckets=DEFAULT_BUCKETS,
                 warmup: bool = False, deadline_s: float = 0.0,
                 queue_cap: int = 0, host_fallback: bool = True,
                 replicas: int = 1):
        self.mesh = mesh
        self.buckets = tuple(int(b) for b in buckets)
        self.default_warmup = bool(warmup)
        # N predictor replicas per loaded version (round-robined over
        # the local devices); with a mesh the forest already spans the
        # devices, so replication applies to non-mesh registries only
        self.replicas = max(int(replicas), 1)
        if mesh is not None and self.replicas > 1:
            log.warning("registry replicas ignored under a mesh "
                        "(the mesh already spans the devices)")
            self.replicas = 1
        # resilience knobs (docs/RESILIENCE.md "Serving degradation"):
        # default queue deadline + admission cap for every lazily-built
        # MicroBatcher (serve_deadline_ms / serve_queue_cap params),
        # and whether device scoring faults degrade to the host
        # tree-walker instead of failing the request
        self.deadline_s = float(deadline_s)
        self.queue_cap = int(queue_cap)
        self.host_fallback = bool(host_fallback)
        self._lock = threading.RLock()
        self._models: Dict[str, List[ModelVersion]] = {}
        self._active: Dict[str, int] = {}
        self._rr = 0  # round-robin cursor for direct replica predicts

    # ------------------------------------------------------------------
    def load(self, name: str, source: Any, *, activate: bool = True,
             warmup: Optional[bool] = None,
             num_features: Optional[int] = None) -> int:
        """Build device tables for a model and register a new version.

        Packing + (optional) warm-up happen OUTSIDE the lock: a load
        must never stall scoring on already-active models."""
        booster, src = _booster_from(source)
        forest = TensorForest.from_booster(booster, mesh=self.mesh)
        forests = [forest]
        if self.replicas > 1:
            import jax

            from .forest import replicate_forest

            devs = jax.local_devices()
            forests += [
                replicate_forest(forest, devs[i % len(devs)])
                for i in range(1, self.replicas)
            ]
        dispatchers = [
            BucketDispatcher(
                f, self.buckets,
                name=f"serve:{name}" if i == 0 else f"serve:{name}:r{i}",
            )
            for i, f in enumerate(forests)
        ]
        dispatcher = dispatchers[0]
        if self.host_fallback:
            fb = _make_host_fallback(booster, forest)
            for d in dispatchers:
                d.host_fallback = fb
        do_warm = self.default_warmup if warmup is None else warmup
        if do_warm:
            if num_features is None:
                # warm at the model's DECLARED width (protocol rows carry
                # every column) — max_feature+1 would be too narrow, and
                # each bucket would recompile on the first real batch
                try:
                    num_features = booster.num_feature() or None
                except Exception:  # noqa: BLE001 — fall back to max_feature
                    num_features = None
            for d in dispatchers:  # each replica device compiles its own
                d.warmup(num_features)
        with self._lock:
            versions = self._models.setdefault(name, [])
            v = (versions[-1].version + 1) if versions else 1
            versions.append(ModelVersion(
                v, booster, forest, dispatcher, src,
                replicas=dispatchers,
            ))
            if activate or name not in self._active:
                self._active[name] = v
        record_registry_event("load", name)
        log.info(f"serving registry: loaded {name!r} v{v} from {src}")
        return v

    def _entry(self, name: str, version: Optional[int] = None) -> ModelVersion:
        with self._lock:
            if name not in self._models:
                raise KeyError(f"unknown model {name!r}")
            v = self._active[name] if version is None else int(version)
            for mv in self._models[name]:
                if mv.version == v:
                    return mv
            raise KeyError(f"model {name!r} has no version {v}")

    def swap(self, name: str, version: int) -> None:
        """Atomically point `name` at an already-loaded version."""
        with self._lock:
            mv = self._entry(name, version)
            self._active[name] = mv.version
        record_registry_event("swap", name)

    def rollback(self, name: str) -> int:
        """Activate the newest version BELOW the active one."""
        with self._lock:
            cur = self._active[name]
            older = [mv.version for mv in self._models[name]
                     if mv.version < cur]
            if not older:
                raise KeyError(f"model {name!r} has no version below {cur}")
            self._active[name] = max(older)
            active = self._active[name]
        record_registry_event("rollback", name)
        return active

    def unload(self, name: str, version: Optional[int] = None) -> None:
        """Drop one version (or the whole name); the active version of
        a name can only be dropped by dropping the name. Dropped
        versions' microbatch workers are closed, so unload really
        releases the forest tables (a parked worker thread would pin
        them)."""
        dropped: List[ModelVersion] = []
        with self._lock:
            if version is None:
                dropped = self._models.pop(name, [])
                self._active.pop(name, None)
            else:
                if self._active.get(name) == int(version):
                    raise ValueError(
                        f"version {version} of {name!r} is active; swap "
                        "first or unload the whole name"
                    )
                kept = []
                for mv in self._models.get(name, []):
                    (kept if mv.version != int(version)
                     else dropped).append(mv)
                self._models[name] = kept
        for mv in dropped:  # outside the lock: close() joins the worker
            if mv.batcher is not None:
                mv.batcher.close()
        if dropped:
            record_registry_event("unload", name)

    def models(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "active": self._active.get(name),
                    "versions": [
                        {"version": mv.version, "source": mv.source,
                         "num_trees": mv.forest.num_trees,
                         "num_class": mv.forest.num_class,
                         "loaded_at": mv.loaded_at}
                        for mv in versions
                    ],
                }
                for name, versions in self._models.items()
            }

    def stats(self) -> Dict[str, Any]:
        # one pass under the lock (like models()): resolving entries
        # after releasing it would let a concurrent unload turn the
        # whole stats request into a KeyError
        with self._lock:
            return {
                name: self._entry(name).dispatcher.stats()
                for name in self._models
            }

    def _batcher_for(self, name: str, mv) -> Optional[Any]:
        """The version's MicroBatcher, created lazily under the lock;
        None when mv was unloaded concurrently (a fresh worker thread
        nothing would ever close must not be resurrected)."""
        with self._lock:
            if not any(m is mv for m in self._models.get(name, [])):
                return None
            if mv.batcher is None:
                from .dispatch import MicroBatcher

                mv.batcher = MicroBatcher(
                    mv.replicas or mv.dispatcher,
                    deadline_s=self.deadline_s,
                    queue_cap=self.queue_cap,
                )
            return mv.batcher

    def batcher(self, name: str, version: Optional[int] = None):
        """The model's continuous-batching front (the same MicroBatcher
        ``predict(via_queue=True)`` coalesces through, shared across
        callers and drained by one worker per replica). Async clients
        ``submit(rows)`` and collect futures — each resolves to that
        request's (n, K) RAW margins — so a pipelined client keeps the
        queue fed without blocking per request (the pattern
        bench_serve.py's loaded phase drives)."""
        mv = self._entry(name, version)
        b = self._batcher_for(name, mv)
        if b is None:
            raise KeyError(f"model {name!r} was unloaded")
        return b

    # ------------------------------------------------------------------
    def predict(self, name: str, X, *, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False,
                via_queue: bool = False,
                version: Optional[int] = None,
                deadline_s: Optional[float] = None) -> np.ndarray:
        """One scoring entry point for every registered model; output
        layout matches Booster.predict ((N,) single-class, (N, K)
        multiclass, (N, T) for pred_leaf, (N, K*(F+1)) for
        pred_contrib — device TreeSHAP, host shap.py parity).

        via_queue=True routes default-parameter scoring through the
        version's MicroBatcher, so concurrent callers (the threaded
        HTTP server's request threads, protocol "queue": true) coalesce
        into shared padded device calls — with replicas, one queue
        worker per replica keeps admitting while other replicas'
        batches are in flight; truncated, pred_leaf, and pred_contrib
        requests always dispatch directly (a coalesced batch must share
        one parameter set)."""
        mv = self._entry(name, version)
        if pred_leaf:
            return mv.dispatcher.predict_leaf(
                X, start_iteration, num_iteration
            )
        if pred_contrib:
            # contrib is an explanation endpoint, not a margin — no
            # objective transform, no queue coalescing (its ladder cap
            # differs); always the primary replica's tables
            return mv.dispatcher.predict_contrib(
                X, start_iteration, num_iteration
            )
        batcher = None
        if via_queue and start_iteration == 0 and num_iteration == -1:
            batcher = self._batcher_for(name, mv)
        if batcher is not None:
            # per-request deadline overrides the registry default;
            # QueueOverflow / DeadlineExceeded propagate to the caller
            raw = batcher.submit(X, deadline_s=deadline_s).result().T
        else:
            d = mv.dispatcher
            if len(mv.replicas) > 1:
                with self._lock:
                    self._rr += 1
                    d = mv.replicas[self._rr % len(mv.replicas)]
            raw = d.score_raw(X, start_iteration, num_iteration)
        g = mv.booster._gbdt
        if not raw_score and g.objective is not None:
            raw = g.objective.convert_output(raw)
        return raw[0] if mv.forest.num_class == 1 else raw.T
