"""Test configuration: run on a virtual 8-device CPU mesh.

Must set the env vars BEFORE jax is imported anywhere (the platform and
device count are fixed at backend init).
"""

import os

# force cpu: the ambient environment presets JAX_PLATFORMS=axon (one real
# TPU behind a tunnel) — tests must run on the virtual 8-device CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax
import numpy as np
import pytest

# The ambient axon plugin (sitecustomize on PYTHONPATH) force-sets
# jax_platforms="axon,cpu" at interpreter start, overriding the env var;
# and initializing the axon backend contacts the (exclusive) TPU tunnel.
# Re-override at the config level so tests never touch the tunnel.
jax.config.update("jax_platforms", "cpu")

# persistent compile cache: the grower's while_loop compiles are 10-40s
# each on CPU; cache them across test runs
# cache dir fingerprinted by host CPU flags (cross-machine XLA:CPU AOT
# entries SIGILL — see lightgbm_tpu._cache.machine_tag)
from lightgbm_tpu._cache import machine_tag

jax.config.update(
    "jax_compilation_cache_dir",
    f"/root/.cache/jax_comp_cache_{machine_tag()}",
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# NOTE on the historical mid-suite segfaults (VERDICT r5 item 5, exit
# 139 under a fused_dispatch frame): root-caused to XLA:CPU buffer
# donation on the fused step — glibc malloc-internal crashes from a
# freed-buffer write, drifting between tests as allocation patterns
# changed. boosting._build_fused now disables donation on the cpu
# backend; the per-module cache-clearing workarounds are superseded.


@pytest.fixture
def rng():
    return np.random.RandomState(42)


# trace-safety fixtures (retrace_guard, jaxpr_audit) from the analysis
# suite's pytest plugin — imported rather than duplicated so the
# in-repo suite and external suites (opt-in via
# `pytest -p lightgbm_tpu.analysis.pytest_plugin`) share one definition
from lightgbm_tpu.analysis.pytest_plugin import (  # noqa: E402,F401
    concurrency_lint,
    cost_audit,
    jaxpr_audit,
    retrace_guard,
    scale_audit,
)


def make_synthetic_regression(n=1000, n_features=10, seed=42):
    """Small regression fixture (reference tests utils.py pattern)."""
    rs = np.random.RandomState(seed)
    X = rs.randn(n, n_features)
    w = rs.randn(n_features)
    y = X @ w + 0.1 * rs.randn(n)
    return X, y


def make_synthetic_binary(n=1000, n_features=10, seed=42):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, n_features)
    w = rs.randn(n_features)
    logits = X @ w
    y = (logits + 0.5 * rs.randn(n) > 0).astype(np.float64)
    return X, y
