import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp

xs = [jnp.zeros(16, jnp.float32) + i for i in range(100)]
jax.block_until_ready(xs)
big = jnp.zeros((100, 16), jnp.float32)
one = jnp.zeros(16, jnp.float32)
jax.block_until_ready([big, one])
for name, obj in [("1 tiny", one), ("list of 100 tiny", xs), ("1 packed (100,16)", big)]:
    t0 = time.time(); _ = jax.device_get(obj); t1 = time.time()
    t2 = time.time(); _ = jax.device_get(obj); t3 = time.time()
    print(f"device_get {name}: {min(t1-t0, t3-t2)*1000:.1f} ms")

# med array
m = jnp.zeros((255, 15), jnp.float32); jax.block_until_ready(m)
t0=time.time(); _ = jax.device_get(m); print(f"device_get (255,15): {(time.time()-t0)*1000:.1f} ms")

# host->device transfer latency
h = np.zeros(16, np.float32)
t0 = time.time()
for _ in range(10): d = jnp.asarray(h); jax.block_until_ready(d)
print(f"h2d tiny x10: {(time.time()-t0)*100:.1f} ms each")
