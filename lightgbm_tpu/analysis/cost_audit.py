"""XLA cost/memory auditor + collective wire-bytes accounting.

The jaxpr auditor (jaxpr_audit.py) proves STRUCTURAL contracts — which
primitives appear and with what dtypes. This pass goes one layer lower
and makes the *performance* contract machine-checkable: it
lowers-and-compiles the same hot entry points (jaxpr_audit.ENTRIES) on
the CPU backend and checks the compiled executable's
``cost_analysis()`` / ``memory_analysis()`` against checked-in budgets
(``cost_budget.json``):

- **flops** and **bytes accessed** — a fusion break or an
  accidentally-materialized intermediate shows up here long before a
  chip benchmark can (BENCH_r05 ran on CPU fallback; the auditor runs
  anywhere);
- **peak temp / output allocation** — the HBM-blowup guard: a new
  buffer the size of the bin matrix fails the budget instead of OOMing
  a chip three PRs later;
- **collective wire bytes** — for every ``psum`` / ``reduce_scatter``
  (``psum_scatter``) / ``all_gather`` / ... equation in an entry's
  jaxpr, payload bytes = prod(shape) x dtype.itemsize per operand,
  summed and asserted against a per-entry budget. Wire budgets are
  EXACT (no headroom): when ROADMAP 3a flips the quant histogram wire
  to int16 (the reference halves socket bytes the same way,
  include/LightGBM/bin.h:63-81), ``--refresh-budgets`` pins the halved
  number and any regression back to a wider payload fails the gate.

Budget refresh: ``python -m lightgbm_tpu.analysis --refresh-budgets``
rewrites cost_budget.json from current compiles (+25% headroom on the
cost metrics, exact wire bytes) and prints an old->new diff for
review. A missing budget is a FAILURE, not a skip — same posture as
jaxpr_audit.within_budget.

CPU-backend caveats: cost numbers are CPU-lowering numbers — useful as
a *regression ratchet*, not as TPU-cycle predictions. Entries that
contain pallas TPU kernels (``pallas_interpret=True`` in the entry
table) are traced under the pallas interpreter so XLA:CPU can compile
them; their budgets describe the interpreted lowering. Wire bytes are
backend-independent (read off the jaxpr, per-shard shapes).
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

from .jaxpr_audit import (
    AuditResult,
    Contract,
    ENTRIES,
    _core_modules,
    build_entry,
    iter_eqns,
)

_BUDGET_PATH = Path(__file__).with_name("cost_budget.json")
# compiled-cost metrics get this headroom on refresh (XLA lowering
# drifts a little across versions); wire bytes are pinned EXACT
_BUDGET_HEADROOM = 1.25
# budgeted keys read from cost_analysis()/memory_analysis()
_COST_KEYS = ("flops", "bytes_accessed", "temp_bytes", "output_bytes")

# cross-device collectives whose operand payload crosses ICI/DCN.
# lax.psum_scatter lowers to the `reduce_scatter` primitive.
_COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "reduce_scatter", "all_gather",
    "all_to_all", "ppermute", "pbroadcast",
}


class WireRecord(NamedTuple):
    prim: str
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int


class CostSummary(NamedTuple):
    flops: int
    bytes_accessed: int
    temp_bytes: int
    output_bytes: int
    argument_bytes: int
    wire: Tuple[WireRecord, ...]

    @property
    def wire_bytes(self) -> int:
        return sum(w.nbytes for w in self.wire)

    def metric(self, key: str) -> int:
        return self.wire_bytes if key == "wire_bytes" else getattr(self, key)


# ---------------------------------------------------------------- wire
def _aval_bytes(aval) -> Optional[int]:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    return int(math.prod(shape)) * int(dtype.itemsize)


def collect_wire(closed) -> Tuple[WireRecord, ...]:
    """Every collective equation in a ClosedJaxpr (via the shared
    jaxpr_audit.iter_eqns flattening, so sub-jaxpr discovery matches
    the structural audit exactly) with its payload bytes. Shapes inside
    shard_map bodies are PER-SHARD, so the account is per-device
    ICI/DCN bytes — the quantity the wire budget bounds."""
    out: List[WireRecord] = []
    for eqn in iter_eqns(closed):
        if eqn.primitive.name not in _COLLECTIVE_PRIMS:
            continue
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            nb = _aval_bytes(aval) if aval is not None else None
            if nb is not None:
                out.append(WireRecord(
                    eqn.primitive.name,
                    tuple(int(d) for d in aval.shape),
                    str(aval.dtype), nb,
                ))
    return tuple(out)


# ------------------------------------------------------------- compile
def _jaxpr_as_fun(closed):
    """jax.core.jaxpr_as_fun across jax versions (shared module probe
    with jaxpr_audit._jaxpr_types)."""
    for mod in _core_modules():
        fn = getattr(mod, "jaxpr_as_fun", None)
        if fn is not None:
            return fn(closed)
    raise RuntimeError("cannot locate jax jaxpr_as_fun")


def compile_entry(name: str) -> CostSummary:
    """Lower-and-compile one entry on the current (CPU) backend and
    read its compiled cost/memory analysis + jaxpr wire account. The
    trace comes from jaxpr_audit.build_entry's memo (pallas entries
    under the interpreter so XLA:CPU can compile them), so a strict
    run traces each entry once across both passes."""
    import jax

    closed = build_entry(name, ENTRIES[name].pallas_interpret)
    fn = jax.jit(_jaxpr_as_fun(closed))
    args = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in closed.in_avals]
    compiled = fn.lower(*args).compile()
    ca = compiled.cost_analysis()
    props = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    ma = compiled.memory_analysis()
    return CostSummary(
        flops=int(math.ceil(props.get("flops", 0.0))),
        bytes_accessed=int(math.ceil(props.get("bytes accessed", 0.0))),
        temp_bytes=int(getattr(ma, "temp_size_in_bytes", 0)),
        output_bytes=int(getattr(ma, "output_size_in_bytes", 0)),
        argument_bytes=int(getattr(ma, "argument_size_in_bytes", 0)),
        wire=collect_wire(closed),
    )


# ------------------------------------------------------------ contracts
def _fmt_bytes(n: int) -> str:
    return f"{n} B" if n < 4096 else f"{n} B ({n / 2**20:.2f} MiB)"


def audit_cost(summary: CostSummary, budget: Optional[Dict[str, Any]],
               name: str = "adhoc",
               wire_dtype: Optional[str] = None) -> AuditResult:
    """Check one entry's CostSummary against its checked-in budget
    (tests drive this directly with synthetic budgets, red-to-green)."""
    contracts: List[Contract] = []
    if budget is None:
        contracts.append(Contract(
            "cost_budget", False,
            "no checked-in cost budget — run "
            "`python -m lightgbm_tpu.analysis --refresh-budgets`",
        ))
    else:
        for key in _COST_KEYS:
            cap = budget.get(key)
            got = summary.metric(key)
            if cap is None:
                contracts.append(Contract(
                    key, False,
                    f"{got} but no {key!r} budget — run --refresh-budgets",
                ))
            else:
                contracts.append(Contract(
                    key, got <= int(cap),
                    f"{got} <= budget {cap}" if got <= int(cap)
                    else f"{got} EXCEEDS budget {cap} (fusion break / "
                    "materialized intermediate / allocation blowup?)",
                ))
        cap = budget.get("wire_bytes")
        got = summary.wire_bytes
        breakdown = ", ".join(
            f"{w.prim}[{w.dtype}{list(w.shape)}]={w.nbytes}B"
            for w in summary.wire
        ) or "no collectives"
        if cap is None:
            contracts.append(Contract(
                "wire_bytes", False,
                f"{got} wire bytes but no budget — run --refresh-budgets",
            ))
        else:
            contracts.append(Contract(
                "wire_bytes", got <= int(cap),
                (f"{_fmt_bytes(got)} <= budget {cap} ({breakdown})"
                 if got <= int(cap)
                 else f"{_fmt_bytes(got)} EXCEEDS wire budget {cap} — "
                 f"collective payload widened? ({breakdown})"),
            ))
    if wire_dtype is not None:
        # the dtype half of the wire contract rides here too so a
        # same-bytes dtype swap (int32 -> f32 at half the rows) cannot
        # sneak past the byte count
        bad = sorted({
            w.dtype for w in summary.wire
            if w.prim == "reduce_scatter" and w.dtype != wire_dtype
        })
        contracts.append(Contract(
            f"wire_{wire_dtype}", not bad,
            f"reduce_scatter payloads all {wire_dtype}" if not bad
            else f"reduce_scatter payload dtype(s) {bad} != {wire_dtype}",
        ))
    return AuditResult(
        name, all(c.ok for c in contracts), contracts, 0,
    )


# cross-entry DROP contracts (ISSUE 12 satellite): entry -> baseline
# whose MEASURED bytes_accessed it must strictly undercut. The
# headroomed per-entry budget only stops regressions; this pins the
# claimed improvement itself — the int-packed default path (3 integer
# channels) must access fewer bytes than the 5-channel bf16x2 path it
# replaces, or the perf story is fiction.
# The fused pair carries the structural proof: the interpreted kernel
# lowering accumulates nat_ch channel rows, so 3 vs 5 channels is a
# guaranteed gap. The serial pair is NOT pinned — the CPU einsum
# fallback collapses bf16x2 to 3 channels before contracting, leaving
# only a sliver of difference there (the rounds_serial_packed entry
# still budget-ratchets on its own).
_DROP_PAIRS: Dict[str, str] = {"hist_round_fused": "hist_round_fused_bf16"}

# same contract shape on the WIRE account (ISSUE 14 satellite): the
# voting-parallel entry's collective payload (votes + elected-columns
# psum) must land strictly below the all-feature reduce-scatter wire of
# the plain quantized data-parallel entry — the whole point of the
# election is moving less histogram across the mesh, and both sides are
# measured this run off the same jaxpr walker.
_WIRE_DROP_PAIRS: Dict[str, str] = {"rounds_voting": "rounds_quant_rs"}


def audit_bytes_drop(name: str, got: int, base: str, ref: int,
                     metric: str = "bytes") -> Contract:
    """`name` must show strictly fewer `metric` (compiled bytes
    accessed, or collective wire bytes) than `base` (both measured THIS
    run — no stale budget on either side)."""
    ok = got < ref
    return Contract(
        f"{metric}_drop_vs_{base}", ok,
        (f"{_fmt_bytes(got)} < {base}'s {_fmt_bytes(ref)} "
         f"({got / ref:.0%})" if ok else
         f"{_fmt_bytes(got)} does NOT undercut {base}'s "
         f"{_fmt_bytes(ref)} — the narrow path stopped being "
         "narrower"),
    )


# -------------------------------------------------------------- runner
def load_budgets() -> Dict[str, Dict[str, int]]:
    if _BUDGET_PATH.exists():
        return json.loads(_BUDGET_PATH.read_text())
    return {}


def _budget_from(summary: CostSummary) -> Dict[str, int]:
    out = {
        key: int(math.ceil(summary.metric(key) * _BUDGET_HEADROOM))
        for key in _COST_KEYS
    }
    out["wire_bytes"] = summary.wire_bytes  # exact: the halving proof
    return out


def run_cost_audits(names: Optional[Sequence[str]] = None
                    ) -> List[AuditResult]:
    if names is not None:
        unknown = set(names) - set(ENTRIES)
        if unknown:
            raise KeyError(
                f"unknown cost-audit entr"
                f"{'y' if len(unknown) == 1 else 'ies'} {sorted(unknown)}; "
                f"known: {sorted(ENTRIES)}"
            )
    budgets = load_budgets()
    out: List[AuditResult] = []
    summaries: Dict[str, CostSummary] = {}
    audited = [n for n in ENTRIES if names is None or n in names]
    for name in audited:
        summaries[name] = compile_entry(name)
    for name in audited:
        res = audit_cost(
            summaries[name], budgets.get(name), name,
            wire_dtype=ENTRIES[name].wire_dtype,
        )
        for pairs, metric in ((_DROP_PAIRS, "bytes"),
                              (_WIRE_DROP_PAIRS, "wire_bytes")):
            base = pairs.get(name)
            if base is None:
                continue
            # the baseline is measured this run even when the caller
            # filtered it out — a drop contract against a stale number
            # proves nothing
            if base not in summaries:
                summaries[base] = compile_entry(base)
            key = "bytes_accessed" if metric == "bytes" else "wire_bytes"
            c = audit_bytes_drop(
                name, summaries[name].metric(key),
                base, summaries[base].metric(key), metric=metric,
            )
            res = AuditResult(
                name, res.ok and c.ok, res.contracts + [c], 0,
            )
        out.append(res)
    return out


def refresh_budgets(names: Optional[Sequence[str]] = None
                    ) -> Tuple[Dict[str, Dict[str, int]],
                               Dict[str, Dict[str, int]]]:
    """Rewrite cost_budget.json from current compiles; returns
    (old, new) for diff display. Refreshing a subset keeps the other
    entries' budgets untouched."""
    old = load_budgets()
    new = {k: dict(v) for k, v in old.items()}
    for name in ENTRIES:
        if names is not None and name not in names:
            continue
        new[name] = _budget_from(compile_entry(name))
    # drop budgets for entries that no longer exist (orphan keys would
    # fail the budget/entry consistency meta-test)
    new = {k: v for k, v in new.items() if k in ENTRIES}
    _BUDGET_PATH.write_text(
        json.dumps(new, indent=2, sort_keys=True) + "\n"
    )
    return old, new


def format_budget_diff(old: Dict[str, Dict[str, int]],
                       new: Dict[str, Dict[str, int]]) -> str:
    """Old->new per-metric diff for --refresh-budgets review."""
    lines: List[str] = []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o == n:
            lines.append(f"  {name}: unchanged")
            continue
        if n is None:
            lines.append(f"- {name}: removed (entry no longer exists)")
            continue
        for key in list(_COST_KEYS) + ["wire_bytes"]:
            ov = (o or {}).get(key)
            nv = n.get(key)
            if ov == nv:
                continue
            delta = ""
            if isinstance(ov, int) and ov:
                delta = f" ({(nv - ov) / ov:+.1%})"
            lines.append(f"~ {name}.{key}: {ov} -> {nv}{delta}")
    return "\n".join(lines) if lines else "  (no budgets)"
