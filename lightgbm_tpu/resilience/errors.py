"""Typed failure vocabulary for the resilience layer (docs/RESILIENCE.md).

One exception family shared by training recovery and the serving
degradation paths, so callers can catch by CONTRACT instead of string-
matching messages:

- ``CheckpointError`` — a checkpoint file is unreadable/corrupt (never
  raised for a merely *absent* file under ``resume=auto``);
- ``DeadlineExceeded`` — a queued scoring request outlived its
  deadline before a worker picked it up (also a ``TimeoutError``, so
  generic timeout handling catches it);
- ``QueueOverflow`` — admission control: the microbatch queue is at
  its row cap and the request was fast-failed instead of queued;
  carries ``retry_after_s`` for the HTTP 503 ``Retry-After`` header;
- ``ShutdownError`` — the owning component is closing/closed; pending
  futures are failed with this instead of hanging forever;
- ``InjectedFault`` — raised only by resilience/faultinject.py; typed
  separately so chaos tests can assert the fault they planted (and so
  the HTTP transport can map it to a 500 distinct from bad requests).

Pure stdlib; importable from anywhere in the package without cycles.
"""

from __future__ import annotations


class ResilienceError(RuntimeError):
    """Base class for the resilience layer's typed failures."""


class CheckpointError(ResilienceError):
    """Checkpoint file exists but cannot be read back (torn/corrupt)."""


class DeadlineExceeded(ResilienceError, TimeoutError):
    """A queued request's deadline passed before it was scored."""


class QueueOverflow(ResilienceError):
    """Admission control fast-fail: the queue is at its row cap."""

    def __init__(self, msg: str, retry_after_s: int = 1):
        super().__init__(msg)
        self.retry_after_s = int(retry_after_s)


class ShutdownError(ResilienceError):
    """The component is shutting down; the request was not processed."""


class InjectedFault(ResilienceError):
    """Deterministic fault planted by resilience/faultinject.py."""
