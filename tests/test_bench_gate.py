"""Bench-trajectory regression gate (analysis/bench_gate.py, Pass 6):
trajectory parsing with stale/cpu-fallback filtering, red-to-green on
crafted regressed fixtures, the budget refresh flow, and registration
in the strict gate's pass registry."""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from lightgbm_tpu.analysis import bench_gate
from lightgbm_tpu.analysis.bench_gate import (
    load_trajectory,
    newest_values,
    run_gate,
)

REPO = Path(__file__).resolve().parents[1]


def _write(root: Path, name: str, payload):
    (root / name).write_text(json.dumps(payload) + "\n")


def _wrap(n, parsed):
    """Driver wrapper shape ({"n", "parsed"}) used by BENCH_r*.json."""
    return {"n": n, "cmd": "python bench.py", "rc": 0, "parsed": parsed}


# ----------------------------------------------------------- trajectory
def test_checked_in_trajectory_parses_and_gate_is_green():
    """ACCEPTANCE: the gate runs green on the repo's real trajectory
    (and the budget pins actually exist — a missing pin would be red)."""
    traj = load_trajectory()
    assert traj["train"], "checked-in BENCH files produced no points"
    newest = newest_values(traj)
    assert newest["train.trees_per_sec"]["value"] > 0
    result = run_gate()
    assert result.ok, result.format()
    names = {c.name for c in result.checks}
    assert {"train.trees_per_sec", "train.quantized_trees_per_sec",
            "serve.qps", "serve.p99_ms"} <= names


def test_stale_and_cpu_fallback_entries_are_ignored(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _wrap(1, {
        "value": 10.0, "platform": "tpu", "unit": "trees/sec",
    }))
    # r2 crashed: parsed is null
    _write(tmp_path, "BENCH_r02.json", _wrap(2, None))
    # r3 ran on cpu and its carried block is STALE -> contributes nothing
    _write(tmp_path, "BENCH_r03.json", _wrap(3, {
        "value": 0.05, "platform": "cpu",
        "last_tpu_verified": {"value": 99.0, "platform": "tpu",
                              "round": 3, "stale": True},
    }))
    traj = load_trajectory(tmp_path)
    assert [(p.round, p.values["trees_per_sec"]) for p in traj["train"]] \
        == [(1, 10.0)]
    # a NON-stale carried block does contribute, as a carried point
    _write(tmp_path, "BENCH_r04.json", _wrap(4, {
        "value": 0.05, "platform": "cpu",
        "last_tpu_verified": {"value": 12.0, "platform": "tpu",
                              "round": 4},
    }))
    traj = load_trajectory(tmp_path)
    newest = newest_values(traj)["train.trees_per_sec"]
    assert newest["value"] == 12.0 and newest["carried"]


def test_direct_measurement_beats_carried_for_same_round(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _wrap(1, {
        "value": 8.0, "platform": "tpu",
    }))
    # another artifact carrying round 1 at a slightly different value
    _write(tmp_path, "BENCH_r02.json", _wrap(2, {
        "platform": "cpu",
        "last_tpu_verified": {"value": 7.9, "platform": "tpu",
                              "round": 1},
    }))
    traj = load_trajectory(tmp_path)
    assert [(p.round, p.values["trees_per_sec"], p.carried)
            for p in traj["train"]] == [(1, 8.0, False)]


# ----------------------------------------------------------- gate logic
def _budget(**pins):
    return pins


def test_regressed_training_fixture_fails_loudly(tmp_path):
    """ACCEPTANCE: the gate is red on a regressed fixture — newest
    chip-verified trees/s below the pinned floor."""
    _write(tmp_path, "BENCH_r01.json", _wrap(1, {
        "value": 10.0, "quantized_trees_per_sec": 20.0,
        "platform": "tpu",
    }))
    _write(tmp_path, "BENCH_r02.json", _wrap(2, {
        "value": 3.0, "quantized_trees_per_sec": 20.0,
        "platform": "tpu",
    }))
    budget = _budget(**{
        "train.trees_per_sec": {"min": 8.0},
        "train.quantized_trees_per_sec": {"min": 16.0},
    })
    result = run_gate(tmp_path, budget)
    assert not result.ok
    bad = {c.name: c for c in result.checks if not c.ok}
    assert set(bad) == {"train.trees_per_sec"}
    assert "3.0" in bad["train.trees_per_sec"].detail
    # within headroom -> green
    ok = run_gate(tmp_path, _budget(**{
        "train.trees_per_sec": {"min": 2.5},
        "train.quantized_trees_per_sec": {"min": 16.0},
    }))
    assert ok.ok, ok.format()


def test_serving_qps_and_p99_gating(tmp_path):
    _write(tmp_path, "BENCH_SERVE_r01.json", {
        "schema": "lightgbm-tpu/bench-serve/v1",
        "qps": 1000.0, "p99_ms": 4.0, "platform": "tpu",
    })
    green = run_gate(tmp_path, _budget(**{
        "serve.qps": {"min": 800.0},
        "serve.p99_ms": {"max": 5.0},
    }))
    assert green.ok, green.format()
    red = run_gate(tmp_path, _budget(**{
        "serve.qps": {"min": 1200.0},
        "serve.p99_ms": {"max": 3.0},
    }))
    bad = {c.name for c in red.checks if not c.ok}
    assert bad == {"serve.qps", "serve.p99_ms"}


def test_points_without_pin_and_pin_without_points_both_fail(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _wrap(1, {
        "value": 10.0, "platform": "tpu",
    }))
    # eligible point, no pin -> "run --refresh-budgets"
    r = run_gate(tmp_path, {})
    bad = {c.name: c.detail for c in r.checks if not c.ok}
    assert "train.trees_per_sec" in bad
    assert "--refresh-budgets" in bad["train.trees_per_sec"]
    # pin for a series whose evidence vanished -> red too
    r2 = run_gate(tmp_path, _budget(**{
        "train.trees_per_sec": {"min": 8.0},
        "serve.qps": {"min": 100.0},
    }))
    bad2 = {c.name for c in r2.checks if not c.ok}
    assert "serve.qps" in bad2
    # neither points nor pin (serve.p99_ms here) -> reported, passes
    p99 = next(c for c in r2.checks if c.name == "serve.p99_ms")
    assert p99.ok and "unpinned" in p99.detail


# -------------------------------------------------------------- refresh
def test_refresh_budget_pins_with_headroom(tmp_path, monkeypatch):
    monkeypatch.setattr(bench_gate, "_BUDGET_PATH",
                        tmp_path / "bench_budget.json")
    _write(tmp_path, "BENCH_r01.json", _wrap(1, {
        "value": 10.0, "quantized_trees_per_sec": 20.0,
        "platform": "tpu",
    }))
    _write(tmp_path, "BENCH_SERVE_r01.json", {
        "qps": 1000.0, "p99_ms": 4.0, "platform": "tpu",
    })
    old, new = bench_gate.refresh_budget(tmp_path)
    assert old == {}
    written = json.loads((tmp_path / "bench_budget.json").read_text())
    assert written["train.trees_per_sec"]["min"] == pytest.approx(8.0)
    assert written["train.quantized_trees_per_sec"]["min"] == \
        pytest.approx(16.0)
    assert written["serve.qps"]["min"] == pytest.approx(800.0)
    assert written["serve.p99_ms"]["max"] == pytest.approx(4.8)
    assert written["train.trees_per_sec"]["pinned_from"]["value"] == 10.0
    diff = bench_gate.format_budget_diff(old, new)
    assert "train.trees_per_sec.min: None -> 8.0" in diff
    # the freshly-pinned gate is green against the same trajectory
    assert run_gate(tmp_path, new).ok
    # refresh keeps an existing pin when its series loses evidence
    (tmp_path / "BENCH_SERVE_r01.json").unlink()
    old2, new2 = bench_gate.refresh_budget(tmp_path)
    assert new2["serve.qps"] == new["serve.qps"]


def test_checked_in_budget_consistent_with_trajectory():
    """Meta: bench_budget.json's pins were produced by refresh_budget
    over the checked-in trajectory (pin = pinned_from * (1 -/+ 20%)),
    so the file cannot drift from the refresh flow."""
    budget = bench_gate.load_budget()
    assert budget, "bench_budget.json missing or empty"
    for spec in bench_gate.SERIES:
        name = f"{spec.group}.{spec.key}"
        pin = budget.get(name)
        if pin is None:
            continue
        v = pin["pinned_from"]["value"]
        if spec.higher_better:
            assert pin["min"] == pytest.approx(v * 0.8, rel=1e-3)
        else:
            assert pin["max"] == pytest.approx(v * 1.2, rel=1e-3)


# ---------------------------------------------------------- registration
def test_bench_gate_registered_in_strict_passes():
    """Satellite: the pass registry (and therefore --strict, the CLI,
    tools/analysis.sh, and the run-every-pass meta-test) includes the
    bench gate; it needs no jax backend."""
    from lightgbm_tpu.analysis.passes import PASSES, run_passes

    assert "bench_gate" in PASSES
    assert PASSES["bench_gate"].needs_jax is False
    results = run_passes(["bench_gate"])
    assert len(results) == 1 and results[0].name == "bench_gate"
    assert results[0].ok, results[0].report
    assert "train.trees_per_sec" in results[0].report
