"""Bucket-batched scoring dispatcher + thread-safe microbatch queue.

Serving traffic arrives in arbitrary batch sizes; a jit cache keyed on
raw shapes would compile once per distinct size (the classic shape-
churn retrace). The dispatcher pads every request up to a small fixed
ladder of row counts, so the number of XLA compiles is bounded by the
ladder length — a contract the retrace guard asserts in
tests/test_serving.py across a 100-request mixed-size sequence
(analysis/retrace.py). Oversized batches are chunked into max-bucket
pieces, so no request shape ever escapes the ladder.

``warmup()`` precompiles every bucket up front (scoring zeros), moving
all compile latency out of the serving path — the analog of the
reference's SingleRowPredictor being built once per model
(c_api.cpp:66), but per shape instead of per row.

``MicroBatcher`` is the queueing half: callers ``submit()`` rows from
any thread and get a Future; a single worker drains the queue,
coalesces pending requests into one padded device call, and fans the
rows of the result back out. Under concurrent small-batch load this
turns q tiny dispatches into one bucket-sized dispatch.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import log
from ..config import DEFAULT_SERVE_BUCKETS as DEFAULT_BUCKETS
from ..obs.metrics import (
    record_bucket_dispatch,
    record_coalesce,
    record_queue_depth,
)
from ..timer import latency_stats


class BucketDispatcher:
    """Pads requests to a fixed shape ladder and scores on device."""

    def __init__(self, forest, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 name: str = "serve"):
        if not buckets:
            raise ValueError("need at least one bucket size")
        n_dev = max(int(getattr(forest, "num_devices", 1)), 1)
        # every rung must shard evenly over the mesh row axis
        aligned = sorted({
            ((max(int(b), 1) + n_dev - 1) // n_dev) * n_dev for b in buckets
        })
        if list(aligned) != sorted(int(b) for b in buckets):
            log.warning(
                f"serving buckets {sorted(int(b) for b in buckets)} "
                f"realigned to {aligned} (mesh of {n_dev} devices needs "
                "row counts divisible by the device count)"
            )
        self.buckets: Tuple[int, ...] = tuple(aligned)
        self.forest = forest
        self.name = name
        self._stats = latency_stats(name)

    # ------------------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n, else the largest (caller chunks)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def warmup(self, num_features: Optional[int] = None) -> None:
        """Precompile every rung (zeros through the real entry point).

        num_features defaults to the forest's widest referenced feature
        + 1 — pass the true dataset width when it is larger, otherwise
        the serving path would compile again on the first real batch.
        """
        import jax.numpy as jnp

        F = max(self.forest.max_feature + 1, 1) \
            if num_features is None else int(num_features)
        tw = np.ones(self.forest.num_trees, np.float32)
        for b in self.buckets:
            score, _leaf = self.forest.apply(
                jnp.zeros((b, F), jnp.float32), tw
            )
            score.block_until_ready()

    # ------------------------------------------------------------------
    def _bucketed_chunks(self, X: np.ndarray, tw: np.ndarray):
        """Yield (score (n,K), leaf (n,T)) per max-bucket chunk, each
        scored at its padded ladder shape — EVERY device call in the
        dispatcher goes through here, so no request shape escapes the
        ladder (the bounded-compiles contract covers pred_leaf too)."""
        import jax.numpy as jnp

        N = X.shape[0]
        top = self.buckets[-1]
        pos = 0
        while pos < N:
            chunk = X[pos: pos + top]
            rows = chunk.shape[0]
            b = self.bucket_for(rows)
            record_bucket_dispatch(self.name, b, rows)
            if rows < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - rows, X.shape[1]), np.float32)]
                )
            score, leaf = self.forest.apply(jnp.asarray(chunk), tw)
            yield np.asarray(score)[:rows], np.asarray(leaf)[:rows]
            pos += top

    def _prep(self, X, start_iteration: int, num_iteration: int):
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        self.forest._check_width(X)
        tw, start, end = self.forest._tree_weights(
            start_iteration, num_iteration
        )
        return X, tw, start, end

    def score_raw(self, X: np.ndarray, start_iteration: int = 0,
                  num_iteration: int = -1) -> np.ndarray:
        """(K, N) raw margins via bucket-padded device calls."""
        X, tw, start, end = self._prep(X, start_iteration, num_iteration)
        if X.shape[0] == 0:  # filtered-empty request, not an error
            return np.zeros((self.forest.num_class, 0), np.float64)
        t0 = time.perf_counter()
        outs = [s for s, _ in self._bucketed_chunks(X, tw)]
        out = np.concatenate(outs).T.astype(np.float64)  # (K, N)
        if self.forest.average_output and end > start:
            out /= end - start
        self._stats.observe(time.perf_counter() - t0, X.shape[0])
        return out

    def predict_leaf(self, X: np.ndarray, start_iteration: int = 0,
                     num_iteration: int = -1) -> np.ndarray:
        """(N, used_trees) leaf indices through the same bucket ladder
        (a raw-shape forest.apply here would reintroduce the per-shape
        compile churn the ladder exists to bound)."""
        X, tw, start, end = self._prep(X, start_iteration, num_iteration)
        K = self.forest.num_class
        if X.shape[0] == 0:
            return np.zeros((0, (end - start) * K), np.int64)
        t0 = time.perf_counter()
        leaves = [lf for _, lf in self._bucketed_chunks(X, tw)]
        out = np.concatenate(leaves)[:, start * K: end * K]
        self._stats.observe(time.perf_counter() - t0, X.shape[0])
        return out.astype(np.int64)

    def stats(self) -> dict:
        return self._stats.snapshot()


class MicroBatcher:
    """Thread-safe request queue in front of a BucketDispatcher.

    submit(rows) -> Future resolving to that request's (n, K) scores.
    One worker thread drains the queue: everything pending (up to the
    largest bucket) coalesces into a single padded device call.
    """

    def __init__(self, dispatcher: BucketDispatcher,
                 max_delay_s: float = 0.002):
        self.dispatcher = dispatcher
        self.max_delay_s = float(max_delay_s)
        self._pending: List[Tuple[np.ndarray, Future]] = []
        self._cond = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="lgb-serve-microbatch", daemon=True
        )
        self._worker.start()

    def submit(self, X: np.ndarray) -> Future:
        """Queue rows for coalesced default-parameter scoring; resolves
        to that request's (n, K) RAW margins. Non-default scoring
        options (truncation, pred_leaf) go through the dispatcher
        directly — requests in one coalesced batch must share one
        parameter set."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        # validate in the submitter's thread: a malformed request must
        # fail ITS caller, never the innocent requests it would have
        # been coalesced with
        self.dispatcher.forest._check_width(X)
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._pending.append((X, fut))
            depth = len(self._pending)
            self._cond.notify()
        # gauge update outside the condition: the metrics registry has
        # its own lock and must not nest under the queue's
        record_queue_depth(self.dispatcher.name, depth)
        return fut

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=5)

    # ------------------------------------------------------------------
    def _run(self) -> None:
        top = self.dispatcher.buckets[-1]
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                # brief linger so near-simultaneous submitters coalesce
                if (len(self._pending) == 1
                        and self._pending[0][0].shape[0] < top
                        and not self._closed):
                    self._cond.wait(self.max_delay_s)
                batch: List[Tuple[np.ndarray, Future]] = []
                rows = 0
                # coalesce only same-width requests (widths >= the
                # model's widest feature are all valid, so a mixed
                # queue would break np.concatenate); stragglers stay
                # pending for the next drain
                width = self._pending[0][0].shape[1]
                while (self._pending and rows < top
                       and self._pending[0][0].shape[1] == width):
                    X, fut = self._pending.pop(0)
                    batch.append((X, fut))
                    rows += X.shape[0]
                depth = len(self._pending)
            record_queue_depth(self.dispatcher.name, depth)
            record_coalesce(self.dispatcher.name, len(batch), rows)
            try:
                Xall = np.concatenate([x for x, _ in batch]) \
                    if len(batch) > 1 else batch[0][0]
                out = self.dispatcher.score_raw(Xall)  # (K, N)
                pos = 0
                for X, fut in batch:
                    n = X.shape[0]
                    fut.set_result(out[:, pos: pos + n].T)  # (n, K)
                    pos += n
            except Exception as e:  # noqa: BLE001 — fan the error out
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
