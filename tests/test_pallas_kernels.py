"""Pallas TPU kernel coverage OFF hardware (VERDICT r3 weak #8).

`LGBM_TPU_PALLAS_INTERPRET=1` makes histogram.py dispatch to the real
pallas kernels under `pallas_call(interpret=True)` on CPU, so the MXU
one-hot formulation, the visit-plan slot kernel, and the slot-packed
natural-order kernel are all exercised by CI and compared against the
XLA einsum fallback — kernel drift fails the suite instead of waiting
for a live chip (the reference analog: running CUDA learner logic
through the CPU build's tests, test_consistency.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.learner.histogram import (
    HIST_BLK,
    build_gh8,
    _hist_fallback,
    _hist_nat_fallback,
)


@pytest.fixture
def interp(monkeypatch):
    """Force the interpreted-pallas dispatch AND clear jit caches at
    both ends: the growers' jit cache keys on (spec, shapes), not the
    env, so a cached fallback trace from a neighboring test would be
    silently reused under interp=1 (and vice versa), making the
    interpret-vs-fallback comparisons vacuous."""
    import jax

    jax.clear_caches()
    monkeypatch.setenv("LGBM_TPU_PALLAS_INTERPRET", "1")
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def data():
    rs = np.random.RandomState(3)
    N, F, B = 2 * HIST_BLK, 5, 64
    bins = jnp.asarray(rs.randint(0, B, (F, N)).astype(np.int32))
    gh8 = build_gh8(
        jnp.asarray(rs.randn(N).astype(np.float32)),
        jnp.asarray((rs.rand(N) + 0.5).astype(np.float32)),
        jnp.ones(N, jnp.float32),
    )
    return N, F, B, bins, gh8


def test_hist_tpu_interpret_matches_fallback(interp, data):
    N, F, B, bins, gh8 = data
    from lightgbm_tpu.learner.histogram import histogram

    out = histogram(bins, gh8, B)  # dispatches to interpreted hist_tpu
    ref = _hist_fallback(bins, gh8, B)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=1e-4)


def test_hist_slots_tpu_interpret_matches_fallback(interp, data):
    N, F, B, bins, gh8 = data
    from lightgbm_tpu.learner.histogram import hist_slots

    S = 4
    begins = jnp.asarray(np.int32([0, 700, HIST_BLK, 0]))
    counts = jnp.asarray(np.int32([700, 300, 1024, 0]))
    out = np.asarray(hist_slots(bins, gh8, begins, counts, B, S))
    for s in range(S):
        b, c = int(begins[s]), int(counts[s])
        if c == 0:
            np.testing.assert_allclose(out[s], 0.0)
            continue
        iota = np.arange(N)
        m = jnp.asarray(((iota >= b) & (iota < b + c)).astype(np.float32))
        ref = np.asarray(_hist_fallback(bins, gh8 * m[None, :], B))
        np.testing.assert_allclose(out[s], ref, atol=2e-3, rtol=1e-4)


def test_hist_nat_tpu_interpret_matches_fallback(interp, data):
    N, F, B, bins, gh8 = data
    from lightgbm_tpu.learner.histogram import hist_nat_slots

    rs = np.random.RandomState(4)
    S = 7
    slot = jnp.asarray(rs.randint(0, S + 1, N).astype(np.int32))
    out = hist_nat_slots(bins, gh8, slot, S, B)
    ref = _hist_nat_fallback(bins, gh8, slot, S, B)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=1e-4)


@pytest.mark.parametrize("oh_shift", [0, 4, 7])
def test_hist_nat_int8_interpret_exact(interp, data, oh_shift):
    """Quantized int8 mode: s8 x s8 -> s32 sums are EXACT integers and
    must equal the f32 fallback bit-for-bit (integer levels within
    +/-127 sum exactly in both paths at this size). Every SWAR one-hot
    scale (byte values 128/8/1, histogram.int8_oh_shift policy) must
    rescale back to identical sums."""
    N, F, B, bins, _ = data
    from lightgbm_tpu.learner.histogram import (
        build_gh8_quant,
        hist_nat_slots,
    )

    rs = np.random.RandomState(5)
    gq = jnp.asarray(rs.randint(-2, 3, N).astype(np.float32))
    hq = jnp.asarray(rs.randint(0, 5, N).astype(np.float32))
    gh8q = build_gh8_quant(gq, hq, jnp.ones(N, jnp.float32))
    S = 6
    slot = jnp.asarray(rs.randint(0, S + 1, N).astype(np.int32))
    out = hist_nat_slots(bins, gh8q, slot, S, B, quant=True, int8=True,
                         oh_shift=oh_shift)
    ref = _hist_nat_fallback(bins, gh8q, slot, S, B, quant=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_take_segsum_large_table_falls_back(interp, data):
    """ADVICE r4 medium: the take/seg_sum kernels materialize an
    (L, blk) one-hot in VMEM — at num_leaves-scale L (config allows up
    to 131072) that tile alone exceeds the scoped budget. Above
    _TAKE_L_CAP both must route to the XLA path and stay correct."""
    N, F, B, bins, _ = data
    from lightgbm_tpu.learner.histogram import (
        _TAKE_L_CAP,
        seg_sum,
        take_cols,
    )

    rs = np.random.RandomState(8)
    L = _TAKE_L_CAP + 100
    tab = jnp.asarray(rs.randn(2, L).astype(np.float32))
    idx = jnp.asarray(rs.randint(-1, L, N).astype(np.int32))
    out = np.asarray(take_cols(tab, idx))  # must not hit the kernel
    ii = np.asarray(idx)
    ref = np.where(ii[None, :] >= 0,
                   np.asarray(tab)[:, np.clip(ii, 0, L - 1)], 0.0)
    np.testing.assert_allclose(out, ref, rtol=1e-6)

    vals = jnp.asarray(rs.randn(2, N).astype(np.float32))
    s = np.asarray(seg_sum(vals, idx, L))
    assert s.shape == (2, L)
    nz = np.unique(ii[ii >= 0])[:20]
    for l in nz:
        np.testing.assert_allclose(
            s[:, l], np.asarray(vals)[:, ii == l].sum(axis=1),
            atol=1e-3, rtol=1e-5)


def test_int8_oh_shift_policy():
    from lightgbm_tpu.learner.histogram import int8_oh_shift

    assert int8_oh_shift(10 ** 6, 4) == 0  # bench shape: full speed
    assert int8_oh_shift(10 ** 6, 127) == 4  # 1M x 127 x 8 < 2^31
    assert int8_oh_shift(18 * 10 ** 6, 127) is None  # ADVICE r4 wrap
    assert int8_oh_shift(16 * 10 ** 6, 127) == 7


def _grow_case(spec_kw, quant=False):
    """Grow one tree on a synthetic set; returns (leaf_values, row_leaf,
    node_feature, node_bin)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params

    rs = np.random.RandomState(11)
    X = rs.randn(HIST_BLK, 6).astype(np.float32)
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5})
    ds = BinnedDataset.from_numpy(X, cfg)
    d = ds.device_arrays()
    N = ds.num_rows_padded()
    F = ds.num_used_features
    if quant:
        grad = jnp.asarray(
            rs.randint(-2, 3, N).astype(np.float32)) * d["valid"]
        hess = jnp.asarray(
            rs.randint(1, 4, N).astype(np.float32)) * d["valid"]
        gh_scale = jnp.asarray(np.float32([0.125, 0.25]))
    else:
        grad = jnp.asarray(rs.randn(N).astype(np.float32)) * d["valid"]
        hess = jnp.ones(N, jnp.float32) * 0.25 * d["valid"]
        gh_scale = None
    spec_kw = dict(spec_kw)  # callers reuse their dict across runs
    n_leaves = spec_kw.pop("num_leaves", 15)
    params = make_split_params(Config({"num_leaves": n_leaves, "max_bin": 63,
                                       "min_data_in_leaf": 5}))
    spec = GrowerSpec(num_leaves=n_leaves, num_bins=ds.max_num_bin,
                      max_depth=-1, **spec_kw)
    tree, rl = grow_tree(
        d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
        grad, hess, d["valid"], jnp.ones(F, bool), params, spec,
        valid=d["valid"], gh_scale=gh_scale,
    )
    return (np.asarray(tree.leaf_value), np.asarray(rl),
            np.asarray(tree.node_feature), np.asarray(tree.node_bin))


def test_fused_round_ladder_matches_fallback(interp):
    """Multi-width S-ladder (widths 8/32/48 at rounds_slots=48): the
    lax.switch dispatch across kernel widths must reproduce the XLA
    path's tree exactly."""
    import os

    import jax

    kw = dict(rounds_slots=48, has_cat=False, num_leaves=63)
    fused = _grow_case(kw)
    os.environ["LGBM_TPU_PALLAS_INTERPRET"] = "0"
    jax.clear_caches()
    fb = _grow_case(kw)
    np.testing.assert_allclose(fused[0], fb[0], atol=5e-4)
    np.testing.assert_array_equal(fused[2], fb[2])
    np.testing.assert_array_equal(fused[3], fb[3])


def test_fused_round_efb_matches_fallback(interp):
    """The fused kernel's in-kernel EFB decode (params cols 7-9) must
    match decode_feature_bins on a genuinely bundled dataset."""
    import os

    import jax

    import lightgbm_tpu as lgb

    rs = np.random.RandomState(21)
    n = HIST_BLK
    blocks = []
    for b in range(3):
        z = np.zeros((n, 6))
        idx = rs.randint(0, 6, n)
        z[np.arange(n), idx] = rs.rand(n) + 0.5
        on = rs.rand(n) < 0.3
        z[~on] = 0.0
        blocks.append(z)
    X = np.hstack([rs.randn(n, 2)] + blocks)
    w = rs.randn(X.shape[1])
    y = (X @ w + 0.3 * rs.randn(n) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "min_data_in_leaf": 5, "verbosity": -1,
              "tpu_growth_mode": "rounds", "tpu_round_slots": 8}

    def run():
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(dict(params), ds, num_boost_round=3)
        assert ds._binned.bundle_layout is not None  # bundling engaged
        return bst.predict(X)

    p_fused = run()
    os.environ["LGBM_TPU_PALLAS_INTERPRET"] = "0"
    jax.clear_caches()
    p_fb = run()
    np.testing.assert_allclose(p_fused, p_fb, atol=1e-5, rtol=1e-5)


def test_fused_round_categorical_matches_fallback(interp):
    """Categorical splits inside the fused kernel (per-slot category
    masks contracted against the row's own-bin one-hot) must reproduce
    the XLA path's trees through the train API."""
    import os

    import jax

    import lightgbm_tpu as lgb

    rs = np.random.RandomState(31)
    n = HIST_BLK
    Xc = rs.randint(0, 12, (n, 2)).astype(np.float64)
    Xn = rs.randn(n, 4)
    X = np.column_stack([Xc, Xn])
    y = ((Xc[:, 0] % 3 == 0).astype(float) * 2 + Xn[:, 0]
         + 0.3 * rs.randn(n) > 1).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "min_data_in_leaf": 5, "verbosity": -1,
              "categorical_feature": "0,1",
              "tpu_growth_mode": "rounds", "tpu_round_slots": 8}

    def run():
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(dict(params), ds, num_boost_round=3)
        return bst.predict(X)

    p_fused = run()
    os.environ["LGBM_TPU_PALLAS_INTERPRET"] = "0"
    jax.clear_caches()
    p_fb = run()
    np.testing.assert_allclose(p_fused, p_fb, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("quant,int8", [(False, False), (True, False),
                                        (True, True)])
def test_fused_round_grower_matches_fallback(interp, quant, int8):
    """The fused partition+histogram kernel (has_cat=False dispatches
    rounds.py onto pallas_hist._round_kernel) must reproduce the XLA
    path's tree EXACTLY: same splits, same partition, same leaves."""
    import os

    import jax

    kw = dict(rounds_slots=8, has_cat=False, quant=quant,
              quant_int8=int8, quant_levels=4 if quant else 0)
    fused = _grow_case(kw, quant=quant)
    os.environ["LGBM_TPU_PALLAS_INTERPRET"] = "0"
    jax.clear_caches()  # the grower jit baked the interpreted dispatch
    fb = _grow_case(kw, quant=quant)
    np.testing.assert_allclose(fused[0], fb[0], atol=5e-4)
    assert (fused[1] == fb[1]).mean() > 0.999
    np.testing.assert_array_equal(fused[2], fb[2])
    np.testing.assert_array_equal(fused[3], fb[3])


def test_take_and_segsum_interpret(interp, data):
    """take_cols / seg_sum one-hot contraction paths vs plain XLA."""
    N, F, B, bins, _ = data
    from lightgbm_tpu.learner.histogram import seg_sum, take_cols

    rs = np.random.RandomState(6)
    L = 31
    tab = jnp.asarray(rs.randn(3, L).astype(np.float32))
    idx = jnp.asarray(rs.randint(-1, L, N).astype(np.int32))  # -1 = dead
    out = np.asarray(take_cols(tab, idx))
    ref = np.where(np.asarray(idx)[None, :] >= 0,
                   np.asarray(tab)[:, np.clip(np.asarray(idx), 0, L - 1)],
                   0.0)
    np.testing.assert_allclose(out, ref, rtol=1e-6)

    vals = jnp.asarray(rs.randn(2, N).astype(np.float32))
    s = np.asarray(seg_sum(vals, idx, L))
    refsum = np.zeros((2, L), np.float32)
    ii = np.asarray(idx)
    for l in range(L):
        refsum[:, l] = np.asarray(vals)[:, ii == l].sum(axis=1)
    np.testing.assert_allclose(s, refsum, atol=1e-3, rtol=1e-5)


def test_nat_grower_with_interpreted_kernel(interp):
    """End-to-end: the natural-order rounds grower with the interpreted
    slot-packed kernel matches the einsum-fallback grower exactly."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params

    rs = np.random.RandomState(9)
    X = rs.randn(HIST_BLK, 6).astype(np.float32)
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5})
    ds = BinnedDataset.from_numpy(X, cfg)
    d = ds.device_arrays()
    N = ds.num_rows_padded()
    F = ds.num_used_features
    grad = jnp.asarray(rs.randn(N).astype(np.float32)) * d["valid"]
    hess = jnp.ones(N, jnp.float32) * 0.25 * d["valid"]
    params = make_split_params(Config({"num_leaves": 15, "max_bin": 63,
                                       "min_data_in_leaf": 5}))
    spec = GrowerSpec(num_leaves=15, num_bins=ds.max_num_bin, max_depth=-1,
                      rounds_slots=8)

    def run():
        tree, rl = grow_tree(
            d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
            grad, hess, d["valid"], jnp.ones(F, bool), params, spec,
            valid=d["valid"],
        )
        return np.asarray(tree.leaf_value), np.asarray(rl)

    lv_interp, rl_interp = run()
    import os

    import jax

    os.environ["LGBM_TPU_PALLAS_INTERPRET"] = "0"
    jax.clear_caches()  # the grower jit baked the interpreted dispatch
    lv_fb, rl_fb = run()
    np.testing.assert_allclose(lv_interp, lv_fb, atol=5e-4)
    assert (rl_interp == rl_fb).mean() > 0.999


# ------------------------------------------- int4 SWAR one-hot (ISSUE 12)
@pytest.mark.parametrize("B4", [16, 24, 32])
def test_hist_nat_int4_interpret_exact(interp, monkeypatch, data, B4):
    """Nibble-SWAR one-hot (8 bins per i32 lane, LGBM_TPU_INT4_OH=1):
    integer sums must equal the f32 fallback bit-for-bit, including bin
    counts that are not multiples of 8 (the packed-row padding)."""
    N, F, _, _, _ = data
    from lightgbm_tpu.learner.histogram import (
        build_gh8_quant,
        hist_nat_slots,
    )

    monkeypatch.setenv("LGBM_TPU_INT4_OH", "1")
    rs = np.random.RandomState(12)
    bins = jnp.asarray(rs.randint(0, B4, (F, N)).astype(np.int32))
    gq = jnp.asarray(rs.randint(-8, 9, N).astype(np.float32))
    hq = jnp.asarray(rs.randint(0, 17, N).astype(np.float32))
    gh8q = build_gh8_quant(gq, hq, jnp.ones(N, jnp.float32))
    S = 6
    slot = jnp.asarray(rs.randint(0, S + 1, N).astype(np.int32))
    out = hist_nat_slots(bins, gh8q, slot, S, B4, quant=True, int8=True,
                         oh_shift=0)
    ref = _hist_nat_fallback(bins, gh8q, slot, S, B4, quant=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_swar4_onehot_unpack_ordering():
    """The nibble-plane unpack (even/odd split + byte bitcasts + stack
    interleave) must place packed row j's nibble m at bin 8*j + m — a
    swapped interleave would score every odd bin into its even
    neighbor. pltpu.bitcast only evaluates inside a kernel, so the
    helper runs under an interpreted pallas_call."""
    import jax
    from jax.experimental import pallas as pl

    from lightgbm_tpu.learner.pallas_hist import _swar_onehot4

    B, blk = 16, 256
    rs = np.random.RandomState(13)
    bins_row = jnp.asarray(rs.randint(0, B, (1, blk)).astype(np.int32))

    def kernel(bins_ref, out_ref):
        out_ref[...] = _swar_onehot4(bins_ref[...], B, blk)

    oh = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, blk), jnp.int8),
        interpret=True,
    )(bins_row)
    expect = (np.arange(B)[:, None]
              == np.asarray(bins_row)[0][None, :]).astype(np.int8) * 8
    np.testing.assert_array_equal(np.asarray(oh), expect)


# -------------------------------------- chunked fused round (ISSUE 12)
def test_fused_round_chunked_matches_fallback(interp, monkeypatch):
    """When S exceeds the one-chunk VMEM schedule, hist_round re-streams
    the slot axis and composes disjoint per-chunk partition deltas; the
    chunked kernel must reproduce the XLA path's tree exactly. Forced
    by shrinking _round_s_max to 3 (rounds_slots=8 -> 3 chunks)."""
    import os
    import sys

    import jax

    # learner/__init__ re-exports the histogram FUNCTION, shadowing the
    # submodule on attribute import — go through sys.modules
    hist_mod = sys.modules["lightgbm_tpu.learner.histogram"]
    monkeypatch.setattr(hist_mod, "_round_s_max",
                        lambda *a, **k: 3)
    kw = dict(rounds_slots=8, has_cat=False, quant=True,
              quant_levels=4)
    fused = _grow_case(kw, quant=True)
    os.environ["LGBM_TPU_PALLAS_INTERPRET"] = "0"
    jax.clear_caches()
    fb = _grow_case(kw, quant=True)
    np.testing.assert_allclose(fused[0], fb[0], atol=5e-4)
    assert (fused[1] == fb[1]).mean() > 0.999
    np.testing.assert_array_equal(fused[2], fb[2])
    np.testing.assert_array_equal(fused[3], fb[3])
