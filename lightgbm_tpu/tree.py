"""Tree model: host representation, prediction, and device traversal.

Mirrors the reference array-based Tree (include/LightGBM/tree.h:26,
src/io/tree.cpp): internal node arrays (split_feature, threshold,
decision_type, left/right children with <0 = ~leaf encoding) and leaf
arrays. decision_type bit layout (tree.h:20-21):

  bit 0: categorical (1) / numerical (0)
  bit 1: default_left
  bits 2-3: missing type (0 None, 1 Zero, 2 NaN)

Thresholds are stored as real values; numerical decisions are
`value <= threshold` -> left. Categorical decisions test membership of
int(value) in a bitset (cat_threshold) -> left.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, List, Optional, Sequence

import numpy as np

from .binning import BinType, K_ZERO_THRESHOLD, MissingType

if TYPE_CHECKING:
    from .dataset import BinnedDataset
    from .learner.grower import TreeArrays

_CAT_MASK = 1
_DEFAULT_LEFT_MASK = 2


def _missing_type_of(dt: int) -> int:
    return (int(dt) >> 2) & 3


@dataclass
class Tree:
    """Host-side decision tree in the reference model-file layout."""

    num_leaves: int
    shrinkage: float = 1.0
    # internal nodes (num_leaves - 1 entries; may be 0 for a stump)
    split_feature: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    split_gain: np.ndarray = field(default_factory=lambda: np.zeros(0, np.float64))
    threshold: np.ndarray = field(default_factory=lambda: np.zeros(0, np.float64))
    decision_type: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    left_child: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    right_child: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    internal_value: np.ndarray = field(default_factory=lambda: np.zeros(0, np.float64))
    internal_weight: np.ndarray = field(default_factory=lambda: np.zeros(0, np.float64))
    internal_count: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int64))
    # leaves
    leaf_value: np.ndarray = field(default_factory=lambda: np.zeros(1, np.float64))
    leaf_weight: np.ndarray = field(default_factory=lambda: np.zeros(1, np.float64))
    leaf_count: np.ndarray = field(default_factory=lambda: np.zeros(1, np.int64))
    # categorical bitsets (tree.h cat_boundaries_/cat_threshold_)
    num_cat: int = 0
    cat_boundaries: np.ndarray = field(default_factory=lambda: np.zeros(1, np.int64))
    cat_threshold: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint32))
    is_linear: bool = False
    # linear leaves (tree.h leaf_const_/leaf_coeff_/leaf_features_):
    # output = leaf_const + sum(coeff * raw feature), falling back to
    # leaf_value when any leaf feature is NaN (tree.cpp:137-153)
    leaf_const: np.ndarray = field(default_factory=lambda: np.zeros(0, np.float64))
    leaf_features: List[List[int]] = field(default_factory=list)
    leaf_coeff: List[List[float]] = field(default_factory=list)

    # ------------------------------------------------------------------
    @staticmethod
    def from_arrays(arrays: "TreeArrays", dataset: "BinnedDataset", shrinkage: float) -> "Tree":
        """Convert device TreeArrays (used-feature indices, bin thresholds)
        to the host model (original feature indices, real thresholds)."""
        n_nodes = int(arrays.num_nodes)
        num_leaves = n_nodes + 1
        t = Tree(num_leaves=num_leaves, shrinkage=shrinkage)
        used = dataset.used_features
        mappers = dataset.mappers

        nf = np.asarray(arrays.node_feature[:n_nodes])
        nb = np.asarray(arrays.node_bin[:n_nodes])
        ndl = np.asarray(arrays.node_default_left[:n_nodes])
        ncat = np.asarray(arrays.node_cat[:n_nodes])
        ncat_mask = np.asarray(arrays.node_cat_mask[:n_nodes]) if ncat.any() else None

        t.split_feature = used[nf].astype(np.int32) if n_nodes else np.zeros(0, np.int32)
        t.split_gain = np.asarray(arrays.node_gain[:n_nodes], dtype=np.float64)
        t.left_child = np.asarray(arrays.node_left[:n_nodes], dtype=np.int32)
        t.right_child = np.asarray(arrays.node_right[:n_nodes], dtype=np.int32)
        t.internal_value = np.asarray(arrays.node_value[:n_nodes], dtype=np.float64)
        t.internal_weight = np.asarray(arrays.node_weight[:n_nodes], dtype=np.float64)
        t.internal_count = np.asarray(
            np.round(arrays.node_count[:n_nodes]), dtype=np.int64
        )
        t.leaf_value = np.asarray(arrays.leaf_value[:num_leaves], dtype=np.float64) * shrinkage
        t.leaf_weight = np.asarray(arrays.leaf_weight[:num_leaves], dtype=np.float64)
        t.leaf_count = np.asarray(np.round(arrays.leaf_count[:num_leaves]), dtype=np.int64)

        thresholds = np.zeros(n_nodes, np.float64)
        decision = np.zeros(n_nodes, np.int32)
        cat_boundaries = [0]
        cat_threshold: List[np.uint32] = []
        n_cat = 0
        for i in range(n_nodes):
            m = mappers[int(t.split_feature[i])]
            dt = 0
            if m.missing_type == MissingType.NAN:
                dt |= 2 << 2
            # NOTE: MissingType.ZERO is intentionally emitted as None: the
            # grower currently routes the zero bin numerically (by
            # threshold), so prediction must too; the reference's
            # zero-as-missing default-direction double scan is a pending
            # milestone (feature_histogram.hpp:832 NA_AS_MISSING path).
            if ncat[i]:
                dt |= _CAT_MASK
                # bitset over the left-going category VALUES (one for
                # one-vs-rest, several for sorted-subset splits —
                # tree.h cat_threshold_ layout)
                bins_left = np.nonzero(ncat_mask[i])[0]
                cat_vals = [
                    int(m.categories[bl])
                    for bl in bins_left
                    if bl < len(m.categories)
                ]
                # empty set degenerates to an all-right bitset (never a
                # valid split; kept loud-safe rather than guessing a bin)
                n_words = (max(cat_vals) // 32 + 1) if cat_vals else 1
                words = [0] * n_words
                for cv in cat_vals:
                    words[cv // 32] |= 1 << (cv % 32)
                thresholds[i] = float(n_cat)  # index into cat_boundaries
                cat_threshold.extend(np.uint32(w) for w in words)
                cat_boundaries.append(len(cat_threshold))
                n_cat += 1
            else:
                if ndl[i]:
                    dt |= _DEFAULT_LEFT_MASK
                thresholds[i] = m.bin_to_value(int(nb[i]))
            decision[i] = dt
        t.threshold = thresholds
        t.decision_type = decision
        t.num_cat = n_cat
        t.cat_boundaries = np.asarray(cat_boundaries, dtype=np.int64)
        t.cat_threshold = np.asarray(cat_threshold, dtype=np.uint32)
        return t

    # ------------------------------------------------------------------
    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        depth = np.zeros(len(self.left_child), np.int32)
        md = 1
        for i in range(len(self.left_child)):
            for c in (self.left_child[i], self.right_child[i]):
                if c >= 0:
                    depth[c] = depth[i] + 1
                    md = max(md, depth[c] + 1)
                else:
                    md = max(md, depth[i] + 1)
        return int(md)

    def _cat_in_bitset(self, node: int, values: np.ndarray) -> np.ndarray:
        ci = int(self.threshold[node])
        lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
        words = self.cat_threshold[lo:hi]
        iv = values.astype(np.int64)
        ok = (iv >= 0) & (iv < 32 * len(words)) & ~np.isnan(values)
        ivc = np.clip(iv, 0, max(0, 32 * len(words) - 1))
        bits = (words[ivc // 32] >> (ivc % 32).astype(np.uint32)) & 1
        return ok & (bits == 1)

    def go_left(self, node: int, x: np.ndarray) -> bool:
        """Scalar decision for one row at one node — the single source of
        truth for decision semantics shared with the vectorized walk below
        (tree.h Decision/CategoricalDecision)."""
        v = x[self.split_feature[node]]
        dt = int(self.decision_type[node])
        if dt & _CAT_MASK:
            if np.isnan(v):
                return False
            return bool(self._cat_in_bitset(node, np.asarray([v]))[0])
        missing_type = (dt >> 2) & 3
        default_left = bool(dt & _DEFAULT_LEFT_MASK)
        isna = np.isnan(v)
        if missing_type == 2:  # NaN as missing
            if isna:
                return default_left
        else:
            if isna:
                v = 0.0
            if missing_type == 1 and abs(v) <= K_ZERO_THRESHOLD:  # Zero as missing
                return default_left
        return bool(v <= self.threshold[node])

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Vectorized decision walk -> leaf index per row (Tree::Predict)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, np.int64)
        cur = np.zeros(n, np.int64)  # node ids; leaves become ~leaf
        active = np.ones(n, bool)
        while np.any(active):
            nodes = cur[active]
            feat = self.split_feature[nodes]
            x = X[active, feat]
            dt = self.decision_type[nodes]
            is_cat = (dt & _CAT_MASK) != 0
            go_left = np.zeros(len(nodes), bool)
            # numerical
            num_idx = ~is_cat
            if np.any(num_idx):
                xv = x[num_idx].astype(np.float64)
                nn = nodes[num_idx]
                thr = self.threshold[nn]
                mt = (dt[num_idx] >> 2) & 3
                dl = (dt[num_idx] & _DEFAULT_LEFT_MASK) != 0
                isna = np.isnan(xv)
                # Zero missing: NaN and 0 treated as missing (tree.cpp Decision)
                miss = np.where(mt == 2, isna, np.where(mt == 1, isna | (np.abs(xv) <= K_ZERO_THRESHOLD), np.zeros_like(isna)))
                xv = np.where(isna & (mt != 2), 0.0, xv)
                gl = np.where(miss, dl, xv <= thr)
                go_left[num_idx] = gl
            if np.any(is_cat):
                cn = nodes[is_cat]
                xv = x[is_cat].astype(np.float64)
                gl = np.zeros(len(cn), bool)
                for u in np.unique(cn):
                    mask = cn == u
                    gl[mask] = self._cat_in_bitset(int(u), xv[mask])
                go_left[is_cat] = gl
            nxt = np.where(go_left, self.left_child[nodes], self.right_child[nodes])
            cur[active] = nxt
            active = cur >= 0
        return ~cur  # leaf index

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaf = self.predict_leaf(X)
        if not self.is_linear:
            return self.leaf_value[leaf]
        return self.linear_leaf_outputs(X, leaf)

    def linear_leaf_outputs(self, X: np.ndarray, leaf: np.ndarray) -> np.ndarray:
        """Linear-leaf outputs per row (tree.cpp:137-153 PredictionFun
        with is_linear): const + coeffs . raw features, NaN -> leaf_value."""
        out = self.leaf_value[leaf].astype(np.float64).copy()
        for l in range(self.num_leaves):
            m = leaf == l
            if not np.any(m):
                continue
            feats = self.leaf_features[l] if l < len(self.leaf_features) else []
            const = self.leaf_const[l] if l < len(self.leaf_const) else 0.0
            if not feats:
                out[m] = const
                continue
            Xl = np.asarray(X, np.float64)[np.ix_(m, feats)]
            v = const + Xl @ np.asarray(self.leaf_coeff[l], np.float64)
            nanrow = np.isnan(Xl).any(axis=1)
            out[m] = np.where(nanrow, self.leaf_value[l], v)
        return out

    def fit_linear_leaves(self, row_leaf: np.ndarray, grad: np.ndarray,
                          hess: np.ndarray, raw: np.ndarray,
                          cat_features: set, linear_lambda: float,
                          shrinkage: float,
                          row_mask: "np.ndarray | None" = None) -> None:
        """Fit one ridge model per leaf on the leaf's PATH features
        (linear_tree_learner.cpp:255-358 CalculateLinear): accumulate
        X^T H X / X^T g over non-NaN leaf rows, solve
        coeffs = -(X^T H X + lambda I)^-1 X^T g, scale by shrinkage.
        Degenerate leaves (fewer usable rows than coefficients) keep the
        plain leaf_value as a constant."""
        L = self.num_leaves
        paths: List[List[int]] = [[] for _ in range(L)]

        def walk(node, feats):
            if node < 0:
                paths[~node] = feats
                return
            f = int(self.split_feature[node])
            nf = feats if (f in cat_features or f in feats) else feats + [f]
            walk(int(self.left_child[node]), nf)
            walk(int(self.right_child[node]), nf)

        if L > 1:
            walk(0, [])
        self.is_linear = True
        self.leaf_const = self.leaf_value.astype(np.float64).copy()
        self.leaf_features = [list(p) for p in paths]
        self.leaf_coeff = [[0.0] * len(p) for p in paths]
        raw = np.asarray(raw, np.float64)
        for leaf in range(L):
            feats = paths[leaf]
            k = len(feats)
            sel = row_leaf == leaf
            if row_mask is not None:  # in-bag rows only (bagging / GOSS)
                sel = sel & row_mask
            if k == 0 or not np.any(sel):
                continue
            Xl = raw[np.ix_(sel, feats)]
            ok = ~np.isnan(Xl).any(axis=1)
            if int(ok.sum()) < k + 1:
                continue
            Xa = np.concatenate(
                [Xl[ok], np.ones((int(ok.sum()), 1))], axis=1
            )
            g = np.asarray(grad, np.float64)[sel][ok]
            h = np.asarray(hess, np.float64)[sel][ok]
            A = (Xa.T * h) @ Xa
            A[np.arange(k), np.arange(k)] += linear_lambda
            b = Xa.T @ g
            try:
                coef = -np.linalg.solve(A, b)
            except np.linalg.LinAlgError:
                continue
            if not np.isfinite(coef).all():
                continue
            self.leaf_coeff[leaf] = [float(c) * shrinkage for c in coef[:k]]
            self.leaf_const[leaf] = float(coef[k]) * shrinkage

    def feature_importance_split(self, num_features: int) -> np.ndarray:
        imp = np.zeros(num_features)
        for i in range(len(self.split_feature)):
            if self.split_gain[i] > 0:
                imp[self.split_feature[i]] += 1
        return imp

    def feature_importance_gain(self, num_features: int) -> np.ndarray:
        imp = np.zeros(num_features)
        for i in range(len(self.split_feature)):
            if self.split_gain[i] > 0:
                imp[self.split_feature[i]] += self.split_gain[i]
        return imp


def tree_to_arrays(t: Tree, dataset: "BinnedDataset") -> "TreeArrays":
    """Inverse of Tree.from_arrays: host model tree -> device TreeArrays
    sized to the tree, for binned traversal (continued training seeds
    scores, DART drops of loaded trees).

    Thresholds map back through the dataset's bin boundaries; for models
    trained on THIS binning the round-trip is exact (thresholds are bin
    upper bounds), for foreign models the approximation is bounded by
    one bin width — the same resolution training itself sees.
    """
    import jax.numpy as jnp

    from .learner.grower import TreeArrays

    L = t.num_leaves
    n_nodes = L - 1
    B = dataset.max_num_bin
    used_of = {int(f): i for i, f in enumerate(dataset.used_features)}
    nf = np.zeros(max(n_nodes, 1), np.int32)
    nb = np.zeros(max(n_nodes, 1), np.int32)
    ndl = np.zeros(max(n_nodes, 1), bool)
    ncat = np.zeros(max(n_nodes, 1), bool)
    nmask = np.zeros((max(n_nodes, 1), B), bool)
    for i in range(n_nodes):
        f_orig = int(t.split_feature[i])
        m = dataset.mappers[f_orig]
        dt = int(t.decision_type[i])
        if f_orig not in used_of:
            # split feature is trivial (constant) in THIS dataset: every
            # row takes the same branch — resolve it host-side and encode
            # as an always-left / always-right numerical node on feature 0
            row = np.zeros(len(dataset.mappers))
            row[f_orig] = m.min_value
            go_l = bool(t.go_left(i, row))
            nf[i] = 0
            nb[i] = B + 1 if go_l else -1
            continue
        nf[i] = used_of[f_orig]
        if dt & _CAT_MASK:
            ncat[i] = True
            ci = int(t.threshold[i])
            lo, hi = int(t.cat_boundaries[ci]), int(t.cat_boundaries[ci + 1])
            words = t.cat_threshold[lo:hi]
            c2b = m._cat_to_bin or {}
            for cv, b in c2b.items():
                if cv // 32 < len(words) and (int(words[cv // 32]) >> (cv % 32)) & 1:
                    if b < B:
                        nmask[i, b] = True
        else:
            ndl[i] = bool(dt & _DEFAULT_LEFT_MASK)
            nb[i] = int(
                np.clip(
                    np.searchsorted(m.upper_bounds, t.threshold[i], side="left"),
                    0,
                    max(m.num_bin - 1, 0),
                )
            )
    z = np.zeros
    return TreeArrays(
        num_nodes=jnp.int32(n_nodes),
        node_feature=jnp.asarray(nf),
        node_bin=jnp.asarray(nb),
        node_gain=jnp.asarray(np.asarray(t.split_gain, np.float32) if n_nodes else z(1, np.float32)),
        node_default_left=jnp.asarray(ndl),
        node_cat=jnp.asarray(ncat),
        node_cat_mask=jnp.asarray(nmask),
        node_left=jnp.asarray(np.asarray(t.left_child, np.int32) if n_nodes else z(1, np.int32)),
        node_right=jnp.asarray(np.asarray(t.right_child, np.int32) if n_nodes else z(1, np.int32)),
        node_value=jnp.asarray(np.asarray(t.internal_value, np.float32) if n_nodes else z(1, np.float32)),
        node_weight=jnp.asarray(np.asarray(t.internal_weight, np.float32) if n_nodes else z(1, np.float32)),
        node_count=jnp.asarray(np.asarray(t.internal_count, np.float32) if n_nodes else z(1, np.float32)),
        leaf_value=jnp.asarray(np.asarray(t.leaf_value, np.float32)),
        leaf_weight=jnp.asarray(np.asarray(t.leaf_weight, np.float32)),
        leaf_count=jnp.asarray(np.asarray(t.leaf_count, np.float32)),
        leaf_depth=jnp.zeros(L, jnp.int32),
    )


def traverse_tree_bins(arrays: "TreeArrays", bins_fm, nan_bin, bundle=None,
                       has_cat: bool = True):
    """Device traversal of a grown tree over a BINNED matrix -> per-row leaf.

    Used to score validation sets each iteration (reference
    ScoreUpdater::AddScore via tree traversal). DEPTH-stepped: every row
    advances one level per pass, so the loop runs tree-depth times (not
    num_nodes times — 254 sequential passes at 255 leaves would dominate
    the fused iteration). Per pass, the rows' current-node parameters
    (feature column, threshold bin, default direction, children, NaN
    bin) come from ONE one-hot MXU contraction against a packed
    per-node table (take_cols — a (N,) take from an (L,) table costs
    ~1 ms per 1M rows on TPU, the contraction ~0.1 ms), and each row's
    split-feature bin is a masked select over the column axis. With
    `bundle` (EFB datasets) the matrix columns are bundles, decoded per
    row from small per-feature tables. `has_cat=False` (all-numerical
    dataset) statically skips the category-set test and its (L*B,)
    flat gather.
    """
    import jax.numpy as jnp
    from jax import lax

    from .learner.histogram import take_cols

    G, N = bins_fm.shape
    n_nodes = arrays.num_nodes
    max_nodes = arrays.node_feature.shape[0]

    # per-node derived columns (tiny (L-1,) gathers, once per tree)
    node_col = (arrays.node_feature if bundle is None
                else bundle.bundle_of[arrays.node_feature])
    node_nan = nan_bin[arrays.node_feature]
    pack = jnp.stack([
        node_col.astype(jnp.float32),  # 0: device bin column
        arrays.node_feature.astype(jnp.float32),  # 1: feature id (EFB)
        arrays.node_bin.astype(jnp.float32),  # 2
        arrays.node_default_left.astype(jnp.float32),  # 3
        arrays.node_cat.astype(jnp.float32),  # 4
        arrays.node_left.astype(jnp.float32),  # 5 (negative = ~leaf)
        arrays.node_right.astype(jnp.float32),  # 6
        node_nan.astype(jnp.float32),  # 7 (-1 = none)
    ])  # (8, max_nodes)

    def cond(s):
        it, row_node = s
        return (it < max_nodes) & jnp.any(row_node >= 0)

    def body(s):
        it, row_node = s
        k = jnp.maximum(row_node, 0)  # clamp: leaf rows produce dead lanes
        v = take_cols(pack, k)  # (8, N)
        col = v[0].astype(jnp.int32)
        f = v[1].astype(jnp.int32)
        # masked select of each row's split-feature bin over the column
        # axis: sum of G per-column selects (VPU), no 2D gather
        sel = col[None, :] == jnp.arange(G, dtype=jnp.int32)[:, None]  # (G, N)
        fbins = jnp.sum(jnp.where(sel, bins_fm, 0), axis=0)
        if bundle is not None:
            from .learner.bundle import decode_feature_bins

            fbins = decode_feature_bins(fbins, f, bundle)  # vector f
        fnan = v[7].astype(jnp.int32)
        num_go_left = (fbins <= v[2].astype(jnp.int32)) | (
            (v[3] > 0.5) & (fbins == fnan) & (fnan >= 0)
        )
        if has_cat:
            B = arrays.node_cat_mask.shape[1]
            cat_hit = arrays.node_cat_mask.reshape(-1)[k * B + fbins]
            go_left = jnp.where(v[4] > 0.5, cat_hit, num_go_left)
        else:
            go_left = num_go_left
        child = jnp.where(go_left, v[5], v[6]).astype(jnp.int32)
        at_internal = (row_node >= 0) & (row_node < n_nodes)
        row_node = jnp.where(at_internal, child, row_node)
        return it + 1, row_node

    row_node = jnp.where(n_nodes > 0, 0, -1) * jnp.ones(N, jnp.int32)
    _, row_node = lax.while_loop(cond, body, (jnp.int32(0), row_node))
    # all rows now at leaves (negative); a stump stays at node 0
    leaf = jnp.where(row_node < 0, ~row_node, 0)
    return leaf
