"""Bench-trajectory regression gate (Pass 6, docs/STATIC_ANALYSIS.md).

The repo's performance history is checked in as ``BENCH_r*.json``
(training trees/s) and ``BENCH_SERVE_r*.json`` (serving QPS / latency)
at the repo root. PR 6 made *static* cost regressions machine-checkable
(``cost_budget.json``); this pass does the same for the *measured*
numbers: the newest chip-verified point of each tracked series must not
regress beyond the pinned headroom in ``bench_budget.json``.

Eligibility rules (what counts as a trajectory point):

- a run whose ``platform`` is ``"tpu"`` contributes its own numbers;
- a CPU-fallback run contributes its carried-forward
  ``last_tpu_verified`` block — UNLESS that block is marked
  ``stale: true`` (the bench marks carried numbers stale when the run
  never touched the chip, so a dead TPU tunnel cannot keep shipping
  old numbers as fresh);
- entries with no parseable payload (``parsed: null`` from a crashed
  round) are skipped;
- points are deduplicated by round, a direct measurement beating a
  carried one for the same round.

Tracked series:

- ``train.trees_per_sec`` / ``train.quantized_trees_per_sec`` —
  higher is better, gate on a pinned minimum;
- ``serve.qps`` (higher better, min) and ``serve.p99_ms`` (lower
  better, max) — gated once a chip-verified serving point exists
  (bench_serve.py carries the same staleness semantics).

Budget posture matches cost_audit: a series WITH eligible points but
NO pin fails ("run --refresh-budgets"); a pin whose series lost all
eligible points fails (the evidence vanished); a series with neither
points nor pin is reported and passes (serving before its first chip
run). ``python -m lightgbm_tpu.analysis --refresh-budgets`` rewrites
``bench_budget.json`` from the current trajectory with
``_HEADROOM_FRAC`` slack and prints the old->new diff.
"""

from __future__ import annotations

import glob
import json
import os
import re
from pathlib import Path
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

_BUDGET_PATH = Path(__file__).with_name("bench_budget.json")
# allowed regression before the gate goes red: min = value * (1 - frac)
# for higher-better series, max = value * (1 + frac) for lower-better
_HEADROOM_FRAC = 0.20


class SeriesSpec(NamedTuple):
    group: str       # "train" | "serve"
    key: str         # budget key + point field name
    higher_better: bool
    unit: str


SERIES: Tuple[SeriesSpec, ...] = (
    SeriesSpec("train", "trees_per_sec", True, "trees/s"),
    SeriesSpec("train", "quantized_trees_per_sec", True, "trees/s"),
    SeriesSpec("serve", "qps", True, "req/s"),
    SeriesSpec("serve", "p99_ms", False, "ms"),
)


class BenchPoint(NamedTuple):
    round: int
    values: Dict[str, float]  # series key -> value
    source: str
    carried: bool             # from a last_tpu_verified block


class GateCheck(NamedTuple):
    name: str
    ok: bool
    detail: str


class GateResult(NamedTuple):
    ok: bool
    checks: Tuple[GateCheck, ...]

    def format(self) -> str:
        lines = [
            f"[{'ok' if c.ok else 'FAIL'}] {c.name}: {c.detail}"
            for c in self.checks
        ]
        return "\n".join(lines) if lines else "(no bench trajectory)"


# ------------------------------------------------------------ loading
def repo_root() -> Path:
    """BENCH artifacts live at the repo root (two levels above this
    package dir); fall back to cwd for installed-package invocations
    run from a checkout."""
    root = Path(__file__).resolve().parents[2]
    if list(root.glob("BENCH_r*.json")):
        return root
    return Path(os.getcwd())


def _round_of(path: str, payload: Dict[str, Any],
              fallback: Optional[int]) -> int:
    if isinstance(fallback, int):
        return fallback
    m = re.search(r"_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def _values_from(src: Dict[str, Any], fields: Dict[str, str]
                 ) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for key, field in fields.items():
        v = src.get(field)
        if isinstance(v, (int, float)) and v > 0:
            out[key] = float(v)
    return out


_TRAIN_FIELDS = {
    "trees_per_sec": "value",
    "quantized_trees_per_sec": "quantized_trees_per_sec",
}
_SERVE_FIELDS = {"qps": "qps", "p99_ms": "p99_ms"}


def _extract_point(path: str, payload: Dict[str, Any],
                   fields: Dict[str, str]) -> Optional[BenchPoint]:
    """One BENCH json -> its chip-verified point (or None)."""
    if payload.get("platform") == "tpu" and not payload.get("stale"):
        vals = _values_from(payload, fields)
        if vals:
            return BenchPoint(
                _round_of(path, payload, payload.get("round")),
                vals, os.path.basename(path), False,
            )
    ltv = payload.get("last_tpu_verified")
    if isinstance(ltv, dict) and not ltv.get("stale") \
            and ltv.get("platform", "tpu") == "tpu":
        vals = _values_from(ltv, fields)
        if vals:
            return BenchPoint(
                _round_of(path, ltv, ltv.get("round")),
                vals, os.path.basename(path), True,
            )
    return None


def _load_series(root: Path, pattern: str,
                 fields: Dict[str, str]) -> List[BenchPoint]:
    points: Dict[int, BenchPoint] = {}
    for path in sorted(glob.glob(str(root / pattern))):
        try:
            data = json.loads(Path(path).read_text())
        except (OSError, ValueError):
            continue
        # driver wrapper {"n", "cmd", "rc", "tail", "parsed"} or a bare
        # artifact (bench_serve.py writes the payload directly)
        payload = data.get("parsed") if "parsed" in data else data
        if not isinstance(payload, dict):
            continue  # crashed round: parsed is null
        if "parsed" in data and isinstance(data.get("n"), int) \
                and "round" not in payload:
            payload = dict(payload, round=data["n"])
        pt = _extract_point(path, payload, fields)
        if pt is None:
            continue
        prev = points.get(pt.round)
        # direct measurement beats a carried one for the same round
        if prev is None or (prev.carried and not pt.carried):
            points[pt.round] = pt
    return [points[r] for r in sorted(points)]


def load_trajectory(root: Optional[Path] = None
                    ) -> Dict[str, List[BenchPoint]]:
    root = Path(root) if root is not None else repo_root()
    return {
        "train": _load_series(root, "BENCH_r*.json", _TRAIN_FIELDS),
        "serve": _load_series(root, "BENCH_SERVE_r*.json", _SERVE_FIELDS),
    }


def newest_values(trajectory: Dict[str, List[BenchPoint]]
                  ) -> Dict[str, Dict[str, Any]]:
    """Per series: the newest eligible value (+ provenance)."""
    out: Dict[str, Dict[str, Any]] = {}
    for spec in SERIES:
        for pt in reversed(trajectory.get(spec.group, [])):
            if spec.key in pt.values:
                out[f"{spec.group}.{spec.key}"] = {
                    "value": pt.values[spec.key],
                    "round": pt.round,
                    "source": pt.source,
                    "carried": pt.carried,
                }
                break
    return out


# ------------------------------------------------------------- budget
def load_budget() -> Dict[str, Dict[str, Any]]:
    if _BUDGET_PATH.exists():
        return json.loads(_BUDGET_PATH.read_text())
    return {}


def _pin_from(spec: SeriesSpec, value: float, meta: Dict[str, Any]
              ) -> Dict[str, Any]:
    bound = (
        {"min": round(value * (1.0 - _HEADROOM_FRAC), 4)}
        if spec.higher_better
        else {"max": round(value * (1.0 + _HEADROOM_FRAC), 4)}
    )
    bound["pinned_from"] = {
        "value": value, "round": meta["round"], "source": meta["source"],
    }
    return bound


def refresh_budget(root: Optional[Path] = None
                   ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Rewrite bench_budget.json from the current trajectory; returns
    (old, new) for the --refresh-budgets diff. Series without eligible
    points keep their existing pin untouched (a broken tunnel must not
    silently unpin the gate)."""
    old = load_budget()
    newest = newest_values(load_trajectory(root))
    new = {k: dict(v) for k, v in old.items()}
    for spec in SERIES:
        name = f"{spec.group}.{spec.key}"
        meta = newest.get(name)
        if meta is not None:
            new[name] = _pin_from(spec, meta["value"], meta)
    _BUDGET_PATH.write_text(
        json.dumps(new, indent=2, sort_keys=True) + "\n"
    )
    return old, new


def format_budget_diff(old: Dict[str, Any], new: Dict[str, Any]) -> str:
    lines: List[str] = []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o == n:
            lines.append(f"  {name}: unchanged")
            continue
        for key in ("min", "max"):
            ov = (o or {}).get(key)
            nv = (n or {}).get(key)
            if ov != nv:
                lines.append(f"~ {name}.{key}: {ov} -> {nv}")
    return "\n".join(lines) if lines else "  (no pins)"


# --------------------------------------------------------------- gate
def run_gate(root: Optional[Path] = None,
             budget: Optional[Dict[str, Any]] = None) -> GateResult:
    trajectory = load_trajectory(root)
    newest = newest_values(trajectory)
    if budget is None:
        budget = load_budget()
    checks: List[GateCheck] = []
    for spec in SERIES:
        name = f"{spec.group}.{spec.key}"
        pin = budget.get(name)
        meta = newest.get(name)
        if pin is None and meta is None:
            checks.append(GateCheck(
                name, True,
                "no chip-verified points yet — unpinned (first chip "
                "run + --refresh-budgets will pin it)",
            ))
            continue
        if pin is None:
            checks.append(GateCheck(
                name, False,
                f"chip-verified point exists ({meta['value']} "
                f"{spec.unit} @ r{meta['round']}) but no pin — run "
                "`python -m lightgbm_tpu.analysis --refresh-budgets`",
            ))
            continue
        if meta is None:
            checks.append(GateCheck(
                name, False,
                "pinned but the trajectory has no eligible point left "
                "(BENCH files missing/stale?) — the gate refuses to "
                "pass on vanished evidence",
            ))
            continue
        v = meta["value"]
        src = (f"r{meta['round']} {meta['source']}"
               + (" carried" if meta["carried"] else ""))
        if spec.higher_better:
            floor = float(pin["min"])
            ok = v >= floor
            rel = "<" if not ok else ">="
            checks.append(GateCheck(
                name, ok,
                f"newest {v} {spec.unit} ({src}) {rel} pinned floor "
                f"{floor} (from {pin.get('pinned_from', {}).get('value')})",
            ))
        else:
            ceil = float(pin["max"])
            ok = v <= ceil
            rel = ">" if not ok else "<="
            checks.append(GateCheck(
                name, ok,
                f"newest {v} {spec.unit} ({src}) {rel} pinned ceiling "
                f"{ceil} (from {pin.get('pinned_from', {}).get('value')})",
            ))
    return GateResult(all(c.ok for c in checks), tuple(checks))
