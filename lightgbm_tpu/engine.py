"""Training entry points: train() and cv() (reference engine.py:109,627)."""

from __future__ import annotations

import collections
import copy
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from . import callback as callback_mod
from . import log
from .basic import Booster, Dataset
from .callback import CallbackEnv, EarlyStopException
from .config import Config, resolve_alias
from .obs.anomaly import AnomalyAbort
from .resilience import checkpoint as ckpt_mod
from .resilience import faultinject
from .resilience.faultinject import fault_point


def _resolve_num_boost_round(params: Dict[str, Any], num_boost_round: int) -> Tuple[Dict, int]:
    params = copy.deepcopy(params)
    for k in list(params.keys()):
        if resolve_alias(k) == "num_iterations":
            num_boost_round = int(params.pop(k))
    return params, num_boost_round


class _ObsHooks:
    """Flight recorder + anomaly sentinel wiring for train()'s two
    loops (docs/OBSERVABILITY.md "Flight recorder & anomaly policies").

    One record per boosting round: evals (with higher-better flags for
    the loss-spike sentinel), per-phase durations drained from the
    timer span sink, per-class tree stats when the round's host trees
    are materialized (fused: every chunk; eager sync: every round; the
    async fast path defers trees, so those records omit stats), gh
    norms, and chunk throughput. Every record is written+flushed before
    the sentinel sees it, so an ``anomaly_policy=abort`` trip can never
    lose the round that tripped it."""

    def __init__(self, recorder, sentinel):
        self.recorder = recorder
        self.sentinel = sentinel
        # records carry ABSOLUTE round indices so a resumed run's
        # truncate+append stream stays monotonic (engine sets this to
        # the checkpoint round on resume)
        self.round_offset = 0
        self._gbdt = None
        self._chunk_tps: Optional[float] = None
        self._step_durs: List[float] = []
        self._chunk_phases: Dict[str, float] = {}
        self._gh_rows: List[Tuple[float, float]] = []

    def bind(self, gbdt) -> None:
        self._gbdt = gbdt
        gbdt.recorder = self.recorder  # eager loops publish gh norms
        self.recorder.attach()

    # ------------------------------------------------------------------
    def _tree_stats(self, i: int):
        """Stats for iteration i's K class-trees, when materialized."""
        gbdt = self._gbdt
        if gbdt._pending:
            return None  # async fast path: host trees not yet fetched
        K = gbdt.num_class
        base = (gbdt._init_iters + i) * K
        models = gbdt._models
        if len(models) < base + K:
            return None
        from .obs.recorder import tree_stats

        return tree_stats(models[base: base + K])

    def _fill_evals(self, rec: Dict[str, Any], evals) -> None:
        # tuples are (dataset, metric, value, higher_better[, stdv]);
        # index access keeps custom-feval 5-tuples working too
        if not evals:
            return
        rec["evals"] = {
            f"{it[0]} {it[1]}": float(it[2]) for it in evals
        }
        rec["evals_hb"] = {
            f"{it[0]} {it[1]}": bool(it[3])
            for it in evals if len(it) > 3
        }

    def _emit(self, rec: Dict[str, Any]) -> None:
        self.recorder.record(rec)
        if self.sentinel is not None:
            self.sentinel.check(rec)  # abort policy raises AnomalyAbort

    # ------------------------------------------------------------------
    def start_chunk(self, n_records: int, chunk_seconds: float) -> None:
        """Fused chunk boundary: drain the span sink once and slice the
        ``round: fused step`` spans out; chunk-level scopes
        (dispatch/collect/materialize) ride the chunk's first record.

        One span covers one DISPATCH — a whole C-round lax.scan under
        chunk scanning — so the booster's ``_last_dispatch_rounds``
        apportions each span evenly across its rounds: records keep a
        per-round duration either way."""
        from .boosting import FUSED_ROUND_PHASE

        drained = self.recorder.drain_phases()
        spans = drained.pop(FUSED_ROUND_PHASE, [])
        per_dispatch = getattr(self._gbdt, "_last_dispatch_rounds", None)
        if not per_dispatch:
            per_dispatch = [1] * len(spans)
        durs: List[float] = []
        for dur, n_rounds in zip(spans, per_dispatch):
            durs.extend([dur / max(n_rounds, 1)] * n_rounds)
        self._step_durs = durs
        self._chunk_phases = {
            k: round(sum(v), 6) for k, v in drained.items()
        }
        K = self._gbdt.num_class
        self._chunk_tps = (
            n_records * K / chunk_seconds
            if n_records and chunk_seconds > 0 else None
        )
        self._gh_rows = list(self._gbdt._last_gh_rows)

    def _provenance(self) -> Dict[str, Any]:
        """Per-round training-path provenance: resolved histogram
        numerics plus the resolved tree learner, and the voting
        election footprint when the elected-columns-only wire is
        active (ISSUE 14 — lets recorder output distinguish the
        voting-on-rounds path from a full-histogram run)."""
        g = self._gbdt
        out: Dict[str, Any] = {
            # resolved histogram channel layout — numerics provenance
            # per round (the int-packed path changes per-tree math)
            "hist_dtype": getattr(g, "hist_dtype", None),
            "tree_learner": getattr(g, "tree_learner_resolved", None),
        }
        ec = getattr(g, "voting_elected_cols", None)
        if ec is not None:
            out["voting_elected_cols"] = ec
            out["voting_wire_bytes_est"] = getattr(
                g, "voting_wire_bytes_est", None
            )
        return out

    def fused_round(self, i: int, j: int, evals) -> None:
        from .boosting import FUSED_ROUND_PHASE

        rec: Dict[str, Any] = {
            "round": self.round_offset + i, "t_unix": time.time(),
            **self._provenance(),
        }
        if j < len(self._step_durs):
            rec["phases"] = {
                FUSED_ROUND_PHASE: round(self._step_durs[j], 6)
            }
        if j == 0 and self._chunk_phases:
            rec["chunk_phases"] = self._chunk_phases
        if self._chunk_tps is not None:
            rec["trees_per_sec"] = round(self._chunk_tps, 4)
        if j < len(self._gh_rows):
            rec["gnorm"], rec["hnorm"] = (
                round(self._gh_rows[j][0], 6),
                round(self._gh_rows[j][1], 6),
            )
        self._fill_evals(rec, evals)
        ts = self._tree_stats(i)
        if ts is not None:
            rec["trees"] = ts
        self._emit(rec)

    def eager_round(self, i: int, evals, iter_seconds: float) -> None:
        rec: Dict[str, Any] = {
            "round": self.round_offset + i, "t_unix": time.time(),
            **self._provenance(),
        }
        drained = self.recorder.drain_phases()
        if drained:
            rec["phases"] = {
                k: round(sum(v), 6) for k, v in drained.items()
            }
        if iter_seconds > 0:
            rec["trees_per_sec"] = round(
                self._gbdt.num_class / iter_seconds, 4
            )
        gh = self._gbdt._last_gh_norm
        if gh is not None:
            rec["gnorm"], rec["hnorm"] = round(gh[0], 6), round(gh[1], 6)
        self._fill_evals(rec, evals)
        ts = self._tree_stats(i)
        if ts is not None:
            rec["trees"] = ts
        self._emit(rec)

    def close(self) -> None:
        """Exception-safe teardown (train()'s finally): detaches the
        timer sink and flushes/closes the JSONL stream so an abort
        leaves no torn state behind. Also unhooks the booster — a kept
        training booster must not keep paying the gh-norm readbacks
        into a closed recorder."""
        if self._gbdt is not None:
            self._gbdt.recorder = None
        self.recorder.close()


def _make_obs_hooks(cfg, resume_bytes: Optional[int] = None
                    ) -> Optional[_ObsHooks]:
    """record_file / anomaly_policy config -> hooks (None = both off,
    the default: zero per-round overhead). ``resume_bytes`` is the
    checkpoint's captured record-stream offset: the recorder truncates
    the stream back to it and appends, so a resumed run's flight
    record carries each round exactly once."""
    path = cfg.record_file
    policy = cfg.anomaly_policy
    if not path and policy == "off":
        return None
    from .obs.anomaly import make_sentinel
    from .obs.recorder import FlightRecorder

    recorder = FlightRecorder(path or None, resume_bytes=resume_bytes)
    sentinel = make_sentinel(policy, recorder=recorder)
    return _ObsHooks(recorder, sentinel)


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[List[Dataset]] = None,
    valid_names: Optional[List[str]] = None,
    feval: Optional[Callable] = None,
    init_model: Optional[Union[str, Booster]] = None,
    keep_training_booster: bool = False,
    callbacks: Optional[List[Callable]] = None,
    fobj: Optional[Callable] = None,
) -> Booster:
    """Train a model (reference engine.py:109 lgb.train)."""
    params, num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    cfg_probe = Config(params)
    if cfg_probe.timetag:
        # runtime USE_TIMETAG switch (docs/OBSERVABILITY.md): phase
        # timing on without restarting the process
        from .timer import enable_timetag

        enable_timetag()
    if cfg_probe.objective == "none" and fobj is None:
        log.warning("Using custom objective requires fobj; objective=none trains nothing")
    # deterministic fault plans (fault_plan param / LGBMTPU_FAULT_PLAN
    # env, docs/RESILIENCE.md); disarmed = a single None check per round
    faultinject.configure(cfg_probe.fault_plan)
    # the raw caller-supplied callbacks, before ES/logging are appended:
    # an anomaly_policy=rollback retry must re-run train() with these
    # (the appended callbacks hold consumed state and would double up)
    user_callbacks = list(callbacks) if callbacks else []
    # early stopping via params (engine.py behavior)
    callbacks = list(callbacks) if callbacks else []
    if cfg_probe.early_stopping_round and cfg_probe.early_stopping_round > 0:
        callbacks.append(
            callback_mod.early_stopping(
                cfg_probe.early_stopping_round,
                first_metric_only=cfg_probe.first_metric_only,
                min_delta=cfg_probe.early_stopping_min_delta,
            )
        )
    if cfg_probe.verbosity >= 1 and not any(
        getattr(cb, "order", None) == 10 and not getattr(cb, "before_iteration", False)
        for cb in callbacks
    ):
        callbacks.append(callback_mod.log_evaluation(period=cfg_probe.metric_freq))

    # ---- crash-consistent resume (docs/RESILIENCE.md). A checkpoint
    # is adopted exactly like a user init_model: the model text rides
    # _continue_from, and because every sampling key is derived from
    # the ABSOLUTE iteration (boosting.py fold_in(seed, iteration)), a
    # resumed run replays the identical tree sequence — the final model
    # bit-matches an uninterrupted run (tests/test_resilience.py).
    ckpt_path = cfg_probe.checkpoint_file or ckpt_mod.default_path(
        cfg_probe.output_model
    )
    resume_offset = 0
    resume_rows: List[List[Tuple]] = []
    record_resume_bytes: Optional[int] = None
    if init_model is None and (cfg_probe.resume == "auto"
                               or cfg_probe.resume_from):
        found, state = ckpt_mod.find_resume_checkpoint(
            cfg_probe.resume, cfg_probe.resume_from, ckpt_path
        )
        if state is not None:
            fp = ckpt_mod.config_fingerprint(params)
            if state.get("fingerprint") and state["fingerprint"] != fp:
                log.warning(
                    f"Checkpoint {found} was written under a different "
                    f"training config (fingerprint {state['fingerprint']}"
                    f" != {fp}); resuming anyway — the combined model "
                    "will not bit-match a single uninterrupted run"
                )
            init_model = Booster(model_str=state["model"])
            resume_offset = state["engine_round"]
            resume_rows = ckpt_mod.truncate_eval_history(
                state.get("eval_history", ()), resume_offset
            )
            record_resume_bytes = state.get("record_offset")
            log.info(
                f"Resuming training from checkpoint {found} "
                f"(round {resume_offset})"
            )

    if cfg_probe.data_source == "chunked":
        # out-of-core plane active (docs/DATA_PLANE.md): surface the
        # resolved budget once at train level; per-chunk RSS lands in
        # the run manifest as manifest["data_plane"]
        from .data import DEFAULT_RAM_BUDGET_MB

        log.info(
            "data_source=chunked: host memory bounded by "
            f"ram_budget_mb={cfg_probe.ram_budget_mb or DEFAULT_RAM_BUDGET_MB}"
            " MB (per-chunk RSS recorded in the run manifest)"
        )

    booster = Booster(params=params, train_set=train_set)
    valid_sets = valid_sets or []
    valid_names = valid_names or []
    valid_contain_train = False
    for i, vs in enumerate(valid_sets):
        name = valid_names[i] if i < len(valid_names) else f"valid_{i}"
        if vs is train_set:
            valid_contain_train = True
            booster._train_data_name = name
            continue
        booster.add_valid(vs, name)

    if init_model is not None:
        ib = (
            init_model
            if isinstance(init_model, Booster)
            else Booster(model_file=init_model)
        )
        booster._continue_from(ib)

    cb_before = [cb for cb in callbacks if getattr(cb, "before_iteration", False)]
    cb_after = [cb for cb in callbacks if not getattr(cb, "before_iteration", False)]
    cb_before.sort(key=lambda cb: getattr(cb, "order", 0))
    cb_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # rounds are ABSOLUTE across resume: a checkpoint at round R leaves
    # `num_boost_round - R` rounds to run, and every callback / fault
    # site / snapshot sees `resume_offset + i` so the resumed half is
    # indistinguishable from the tail of an uninterrupted run
    total_rounds = num_boost_round
    num_boost_round = max(total_rounds - resume_offset, 0)

    snapshot_freq = cfg_probe.snapshot_freq
    ckpt_fingerprint = (
        ckpt_mod.config_fingerprint(params) if snapshot_freq > 0 else ""
    )
    # eval history through the current round rides in the checkpoint so
    # a resume can replay it into the stateful callbacks (early
    # stopping, record_evaluation) before new rounds run
    eval_history: List[List[Tuple]] = [list(r) for r in resume_rows]

    def _snapshot(done_iter: int, evals) -> None:
        """snapshot_freq model dumps during training (gbdt.cpp:258-262)
        plus the crash-consistent training checkpoint (resume=auto)."""
        if snapshot_freq <= 0:
            return
        abs_round = resume_offset + done_iter + 1
        # truncate-and-set keeps the history exactly `abs_round` rows
        eval_history[abs_round - 1:] = [[tuple(t) for t in (evals or [])]]
        if abs_round % snapshot_freq != 0:
            return
        out = f"{cfg_probe.output_model}.snapshot_iter_{abs_round}"
        # clamp explicitly (the fused path materializes whole chunks
        # before callbacks replay); done_iter counts NEW iterations —
        # offset by any init_model trees so snapshots keep them
        total = booster._gbdt._init_iters + done_iter + 1
        booster.save_model(out, num_iteration=total)
        log.info(f"Saved snapshot to {out}")
        record_offset = None
        if obs_hooks is not None and obs_hooks.recorder.path:
            # the round's record is written+flushed before _snapshot
            # runs, so the captured size covers rounds <= abs_round —
            # a resume truncates the stream back to exactly here
            try:
                record_offset = os.path.getsize(obs_hooks.recorder.path)
            except OSError:
                record_offset = None
        ckpt_mod.save_checkpoint(
            ckpt_path,
            booster.model_to_string(num_iteration=total),
            engine_round=abs_round,
            total_iters=total,
            eval_history=eval_history,
            record_offset=record_offset,
            fingerprint=ckpt_fingerprint,
        )

    # flight recorder + anomaly sentinels (record_file / anomaly_policy
    # params, docs/OBSERVABILITY.md); None when both are off
    obs_hooks = _make_obs_hooks(cfg_probe, record_resume_bytes)
    if obs_hooks is not None:
        obs_hooks.round_offset = resume_offset
        obs_hooks.bind(booster._gbdt)
    else:
        # an unrecorded run supersedes any earlier recorded run: a
        # manifest written after THIS run must not carry the previous
        # run's flight-record summary
        from .obs.recorder import clear_last_summary

        clear_last_summary()

    evaluation_result_list: List[Tuple] = (
        list(resume_rows[-1]) if resume_rows else []
    )
    i = -1
    if resume_offset > 0 and resume_rows:
        # replay the checkpointed learning curve into the STATEFUL
        # post-iteration callbacks (order >= 20: record_evaluation,
        # early_stopping) so their internal state matches an
        # uninterrupted run; log_evaluation (order 10) is skipped —
        # those rounds were already printed by the crashed run
        replay_cbs = [
            cb for cb in cb_after if getattr(cb, "order", 0) >= 20
        ]
        try:
            for r, row in enumerate(resume_rows):
                for cb in replay_cbs:
                    cb(CallbackEnv(booster, params, r, 0, total_rounds,
                                   list(row)))
        except EarlyStopException as e:
            # the crashed run would have stopped inside the
            # checkpointed prefix — nothing left to train
            booster.best_iteration = e.best_iteration + 1
            evaluation_result_list = e.best_score
            num_boost_round = 0
    use_fused = (
        fobj is None
        and feval is None
        and not cb_before
        and hasattr(booster._gbdt, "fused_eligible")
        and booster._gbdt.fused_eligible()
    )
    if not use_fused:
        # the sync path costs a ~100 ms host readback per iteration on
        # the TPU runtime — tell the user WHY they fell off the fused
        # loop instead of silently training slower (VERDICT r3 weak #5)
        if fobj is not None:
            why = "custom fobj"
        elif feval is not None:
            why = "custom feval"
        elif cb_before:
            why = "pre-iteration callbacks"
        elif hasattr(booster._gbdt, "fused_ineligible_reason"):
            why = booster._gbdt.fused_ineligible_reason() or "unknown"
        else:
            why = "unsupported booster"
        log.info(
            f"Using the per-iteration sync training loop ({why}); "
            "the fused device loop is faster on accelerators"
        )
    try:
        if use_fused:
            # fused device loop: rounds dispatched as C-round lax.scan
            # chunks (one executable launch per ladder rung;
            # boosting.fused_dispatch), zero host syncs; evals fetched
            # per chunk and callbacks replayed in order (identical
            # per-iteration semantics, delivered late)
            gbdt = booster._gbdt
            gbdt.train.name = booster._train_data_name
            gbdt.fused_start(track_train=valid_contain_train)
            chunk = gbdt._check_every
            done = 0
            stop = False
            from .obs.metrics import record_eval_values, record_training_round
            from .timer import global_timer as _gt

            while done < num_boost_round and not stop:
                n = min(chunk, num_boost_round - done)
                t_chunk = time.perf_counter()
                with _gt.scope("fused dispatch"):
                    gbdt.fused_dispatch(n)
                with _gt.scope("fused collect (readback)"):
                    records = gbdt.fused_collect()
                record_training_round(
                    len(records), len(records) * gbdt.num_class,
                    time.perf_counter() - t_chunk,
                )
                if obs_hooks is not None:
                    obs_hooks.start_chunk(
                        len(records), time.perf_counter() - t_chunk
                    )
                for j, evals in enumerate(records):
                    i = done + j
                    fault_point("round", resume_offset + i)
                    evaluation_result_list = evals
                    record_eval_values(evals)
                    if obs_hooks is not None:
                        obs_hooks.fused_round(i, j, evals)
                    _snapshot(i, evals)
                    try:
                        for cb in cb_after:
                            cb(CallbackEnv(booster, params,
                                           resume_offset + i, 0,
                                           total_rounds, evals))
                    except EarlyStopException as e:
                        booster.best_iteration = e.best_iteration + 1
                        evaluation_result_list = e.best_score
                        # truncate counts TOTAL iterations: keep loaded trees
                        gbdt.fused_truncate(gbdt._init_iters + i + 1)
                        stop = True
                        break
                done += max(len(records), 1)
                if gbdt._stopped:
                    # the sync path runs cb_after once for the stop iteration
                    # (whose eval equals the previous iteration's: the failed
                    # trees were rolled back) — replay that here too
                    if not stop and done < num_boost_round:
                        try:
                            for cb in cb_after:
                                cb(CallbackEnv(booster, params,
                                               resume_offset + done, 0,
                                               total_rounds,
                                               evaluation_result_list))
                        except EarlyStopException as e:
                            booster.best_iteration = e.best_iteration + 1
                            evaluation_result_list = e.best_score
                    break
        else:
            from .obs.metrics import record_eval_values, record_training_round

            for i in range(num_boost_round):
                fault_point("round", resume_offset + i)
                for cb in cb_before:
                    cb(CallbackEnv(booster, params, resume_offset + i, 0,
                                   total_rounds, None))
                t_iter = time.perf_counter()
                finished = booster.update(fobj=fobj)
                record_training_round(
                    1, booster._gbdt.num_class, time.perf_counter() - t_iter
                )

                evaluation_result_list = []
                if valid_contain_train:
                    evaluation_result_list.extend(booster.eval_train(feval))
                if booster._gbdt.valids:
                    evaluation_result_list.extend(booster.eval_valid(feval))
                record_eval_values(evaluation_result_list)
                if obs_hooks is not None:
                    obs_hooks.eager_round(
                        i, evaluation_result_list,
                        time.perf_counter() - t_iter,
                    )
                _snapshot(i, evaluation_result_list)
                try:
                    for cb in cb_after:
                        cb(CallbackEnv(booster, params, resume_offset + i,
                                       0, total_rounds,
                                       evaluation_result_list))
                except EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    evaluation_result_list = e.best_score
                    break
                if finished:
                    break

    except AnomalyAbort as anomaly:
        # anomaly_policy=rollback: restore the last good checkpoint and
        # retrain instead of discarding the run (docs/RESILIENCE.md
        # "Recovery policies"). The budget (anomaly_rollback_max)
        # decrements through the retry params so a deterministic
        # re-trip terminates; without a checkpoint it degrades to abort.
        if (cfg_probe.anomaly_policy == "rollback"
                and snapshot_freq > 0
                and cfg_probe.anomaly_rollback_max > 0
                and os.path.exists(ckpt_path)):
            if obs_hooks is not None:
                # flush/close now: the retry reopens the record stream
                # (truncate+append) and publishes its own summary
                obs_hooks.close()
                obs_hooks = None
            retry_params = copy.deepcopy(params)
            for k in list(retry_params):
                if resolve_alias(k) in (
                    "learning_rate", "resume", "resume_from",
                    "anomaly_rollback_max",
                ):
                    retry_params.pop(k)
            decay = cfg_probe.anomaly_rollback_lr_decay
            retry_params["learning_rate"] = cfg_probe.learning_rate * decay
            retry_params["resume_from"] = ckpt_path
            retry_params["anomaly_rollback_max"] = (
                cfg_probe.anomaly_rollback_max - 1
            )
            log.warning(
                f"anomaly rollback: {anomaly} — restoring checkpoint "
                f"{ckpt_path} and retraining with learning_rate="
                f"{retry_params['learning_rate']:g} "
                f"({cfg_probe.anomaly_rollback_max - 1} rollback(s) left)"
            )
            return train(
                retry_params, train_set, total_rounds,
                valid_sets=valid_sets, valid_names=valid_names,
                feval=feval, init_model=None,
                keep_training_booster=keep_training_booster,
                callbacks=user_callbacks, fobj=fobj,
            )
        raise
    finally:
        # exception-safe flush (anomaly abort, callback errors,
        # KeyboardInterrupt): detach the span sink and close the
        # JSONL stream so the flight record's tail stays parseable
        # and the run manifest can summarize it
        if obs_hooks is not None:
            obs_hooks.close()

    # flush the async training pipeline (fast-path pending device trees)
    booster._gbdt._materialize()
    # surface the run's sentinel verdict on the booster: the online
    # promotion gate (online/gate.py) reads trips from the refit result
    # directly instead of the module-global recorder summary
    if obs_hooks is not None and obs_hooks.sentinel is not None:
        booster.anomaly_summary = obs_hooks.sentinel.summary()
    # the stop condition is only detected every _check_every iterations on
    # the fast path; _materialize may have truncated blindly-trained
    # iterations — clamp iteration-derived state to the surviving models
    n_iters = booster._gbdt.num_trees() // booster._gbdt.num_class
    if booster.best_iteration > n_iters:
        booster.best_iteration = n_iters
    if n_iters < booster._gbdt._init_iters + i + 1:
        # truncation rolled back the blindly-trained iterations whose
        # scores produced the last eval — don't record stale values
        evaluation_result_list = []

    # record best score
    for item in evaluation_result_list or []:
        booster.best_score.setdefault(item[0], collections.OrderedDict())
        booster.best_score[item[0]][item[1]] = item[2]
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (reference engine.py:356)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> "CVBooster":
        self.boosters.append(booster)
        return self

    def __getattr__(self, name: str):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]

        return handler_function


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict, seed: int,
                  stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    if stratified and full_data.label is not None:
        label = np.asarray(full_data.label)
        folds = [[] for _ in range(nfold)]
        for cls in np.unique(label):
            idx = np.nonzero(label == cls)[0]
            if shuffle:
                rng.shuffle(idx)
            for i, chunk in enumerate(np.array_split(idx, nfold)):
                folds[i].extend(chunk.tolist())
        fold_idx = [np.asarray(sorted(f)) for f in folds]
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        fold_idx = [np.sort(c) for c in np.array_split(idx, nfold)]
    for i in range(nfold):
        test_idx = fold_idx[i]
        train_idx = np.setdiff1d(np.arange(num_data), test_idx, assume_unique=False)
        yield train_idx, test_idx


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    folds=None,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics=None,
    feval=None,
    init_model=None,
    fpreproc=None,
    seed: int = 0,
    callbacks=None,
    eval_train_metric: bool = False,
    return_cvbooster: bool = False,
    fobj: Optional[Callable] = None,
) -> Dict[str, Any]:
    """Cross-validation (reference engine.py:627)."""
    params, num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    if metrics is not None:
        params["metric"] = metrics
    cfg_probe = Config(params)
    if cfg_probe.objective in ("lambdarank", "rank_xendcg") and stratified:
        stratified = False

    if folds is not None:
        if hasattr(folds, "split"):
            fold_iter = list(folds.split(np.zeros(train_set.num_data()), train_set.label))
        else:
            fold_iter = list(folds)
    else:
        fold_iter = list(_make_n_folds(train_set, nfold, params, seed, stratified, shuffle))

    cvbooster = CVBooster()
    for train_idx, test_idx in fold_iter:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        if fpreproc is not None:
            tr, te, fold_params = fpreproc(tr, te, copy.deepcopy(params))
        else:
            fold_params = params
        bst = Booster(params=fold_params, train_set=tr)
        bst.add_valid(te, "valid")
        cvbooster.append(bst)

    callbacks = list(callbacks) if callbacks else []
    if cfg_probe.early_stopping_round and cfg_probe.early_stopping_round > 0:
        callbacks.append(
            callback_mod.early_stopping(
                cfg_probe.early_stopping_round,
                first_metric_only=cfg_probe.first_metric_only,
                min_delta=cfg_probe.early_stopping_min_delta,
            )
        )
    cb_before = sorted(
        (cb for cb in callbacks if getattr(cb, "before_iteration", False)),
        key=lambda cb: getattr(cb, "order", 0),
    )
    cb_after = sorted(
        (cb for cb in callbacks if not getattr(cb, "before_iteration", False)),
        key=lambda cb: getattr(cb, "order", 0),
    )

    # ---- fused cv (VERDICT r4 item 6): every fold's training rides the
    # chunked fused device loop, and because the traced step is
    # fold-agnostic (per-fold arrays are jit arguments, boosting.py
    # _FUSED_STEP_CACHE), fold 2..k reuse fold 1's trace+executable —
    # 5-fold cv pays ONE trace. Per-iteration aggregation/callbacks
    # replay from the per-chunk eval records exactly like engine.train.
    use_fused_cv = (
        fobj is None and feval is None and not cb_before
        and all(b._gbdt.fused_eligible() for b in cvbooster.boosters)
    )
    results = collections.defaultdict(list)

    def _cv_iteration(i: int, fold_evals) -> bool:
        """Aggregate one iteration's per-fold eval tuples into results,
        replay cb_after; returns True when early stopping fired (shared
        by the fused replay and the sync fold loop so semantics cannot
        drift)."""
        merged: Dict[Tuple[str, str, bool], List[float]] = (
            collections.OrderedDict()
        )
        for one in fold_evals:
            for dn, mn, v, hb in one:
                merged.setdefault((dn, mn, hb), []).append(v)
        agg = [
            ("cv_agg", f"{dn} {mn}", float(np.mean(vs)), hb,
             float(np.std(vs)))
            for (dn, mn, hb), vs in merged.items()
        ]
        for (dn, mn, hb), vs in merged.items():
            results[f"{dn} {mn}-mean"].append(float(np.mean(vs)))
            results[f"{dn} {mn}-stdv"].append(float(np.std(vs)))
        try:
            for cb in cb_after:
                cb(CallbackEnv(cvbooster, params, i, 0, num_boost_round,
                               agg))
        except EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for bst in cvbooster.boosters:
                bst.best_iteration = cvbooster.best_iteration
            for k in results:
                results[k] = results[k][: cvbooster.best_iteration]
            return True
        return False

    if use_fused_cv:
        for bst in cvbooster.boosters:
            bst._gbdt.fused_start(track_train=eval_train_metric)
        chunk = cvbooster.boosters[0]._gbdt._check_every
        done = 0
        stop = False
        while done < num_boost_round and not stop:
            n = min(chunk, num_boost_round - done)
            fold_records = []
            for bst in cvbooster.boosters:
                bst._gbdt.fused_dispatch(n)
            for bst in cvbooster.boosters:
                fold_records.append(bst._gbdt.fused_collect())
            n_done = min(len(r) for r in fold_records) if fold_records else 0
            for j in range(n_done):
                i = done + j
                if _cv_iteration(i, [recs[j] for recs in fold_records]):
                    # keep trees THROUGH the stop iteration (i+1),
                    # matching the sync fold loop and engine.train; only
                    # the chunk's blindly-trained tail drops
                    for bst in cvbooster.boosters:
                        bst._gbdt.fused_truncate(
                            bst._gbdt._init_iters + i + 1
                        )
                    stop = True
                    break
            n_recorded = done + n_done  # iterations with results rows
            done += max(n_done, 1)
            if not stop and any(
                b._gbdt._stopped for b in cvbooster.boosters
            ):
                # a fold hit the no-splittable-leaf stop mid-chunk: its
                # records (and results) end early — clamp EVERY fold's
                # trees to the recorded length so num_trees() always
                # agrees with the results lists
                for bst in cvbooster.boosters:
                    bst._gbdt.fused_truncate(
                        bst._gbdt._init_iters + n_recorded
                    )
                break
        for bst in cvbooster.boosters:
            bst._gbdt._materialize()
    else:
        for i in range(num_boost_round):
            for cb in cb_before:
                cb(CallbackEnv(cvbooster, params, i, 0, num_boost_round,
                               None))
            for bst in cvbooster.boosters:
                bst.update(fobj=fobj)
            fold_evals = []
            for bst in cvbooster.boosters:
                one = bst.eval_valid(feval)
                if eval_train_metric:
                    one = bst.eval_train(feval) + one
                fold_evals.append(one)
            if _cv_iteration(i, fold_evals):
                break
    out = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
