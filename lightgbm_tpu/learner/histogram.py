"""Feature-histogram construction.

The reference builds per-(leaf, feature) histograms of (sum_grad,
sum_hess, count) with sequential scatter loops on CPU
(src/io/dense_bin.hpp:99-174 ConstructHistogram) and shared-memory
atomics on CUDA (src/treelearner/cuda/cuda_histogram_constructor.cu).
A TPU has no vector scatter, so scatter-add becomes a one-hot
contraction. Two backends share one data layout:

- **Pallas TPU kernel** (`pallas_hist.hist_tpu`): the one-hot tile only
  ever lives in VMEM, the contraction rides the MXU. Requires the row
  count to be a multiple of `HIST_BLK`.
- **XLA einsum fallback** (CPU tests, virtual meshes, odd row counts):
  same math, one-hot materialized per small row block under `lax.scan`.

Layouts put the LONG (row) axis minor-most everywhere — TPU memory
tiles pad the last dim to 128 lanes, so a row-major (N, 28) bin matrix
would physically occupy 4.5x its nominal bytes. Hence: bins are
feature-major `(F, N)` int32; per-row channels `(8, N)` f32 with rows
`(g_hi, g_lo, h_hi, h_lo, count, 0, 0, 0)`; histograms are `(3, F, B)`
(channel leading, bins on lanes). The bf16x2 split (hi = bf16(x),
lo = x - hi) lets the MXU run in bf16 while the recombined histogram
keeps ~f32 accuracy — the padded channel slots are free because the
matmul M dim pads 3 -> 8 anyway. Gradient/hessian sums per bin are f32
like the reference's GPU path (gpu_hist_t, docs/GPU-Performance.rst).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

HIST_BLK = 2048  # pallas row-block; device row padding is a multiple of this
CH = 8
NAT_CH = 5  # useful gh channels packed per slot (g_hi, g_lo, h_hi, h_lo, cnt)


def _interpret_pallas() -> bool:
    """CI hook: LGBM_TPU_PALLAS_INTERPRET=1 runs the TPU kernels under
    the pallas interpreter on CPU so kernel drift is caught off-hardware
    (VERDICT r3 weak #8; the reference analog is running the CUDA tests'
    logic on the CPU build)."""
    import os

    return os.environ.get("LGBM_TPU_PALLAS_INTERPRET", "") == "1"


def _use_pallas() -> bool:
    if _interpret_pallas():
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _use_int4_oh() -> bool:
    """Opt-in for the experimental nibble-SWAR (int4) one-hot on the
    int8 histogram path (pallas_hist._swar_onehot4 has the evaluation
    verdict — kept behind LGBM_TPU_INT4_OH=1)."""
    import os

    return os.environ.get("LGBM_TPU_INT4_OH", "") == "1"


def build_gh8(grad: jax.Array, hess: jax.Array, count: jax.Array) -> jax.Array:
    """(N,) grad/hess/count (already masked) -> (8, N) bf16x2-split channels."""
    g_hi = grad.astype(jnp.bfloat16).astype(jnp.float32)
    g_lo = grad - g_hi
    h_hi = hess.astype(jnp.bfloat16).astype(jnp.float32)
    h_lo = hess - h_hi
    z = jnp.zeros_like(count)
    return jnp.stack([g_hi, g_lo, h_hi, h_lo, count, z, z, z])


def combine_ch(hist8: jax.Array) -> jax.Array:
    """(CH, F, B) accumulated channels -> (3, F, B) (grad, hess, count)."""
    return jnp.stack(
        [hist8[0] + hist8[1], hist8[2] + hist8[3], hist8[4]]
    )


def _hist_fallback(bins_fm: jax.Array, gh8: jax.Array, num_bins: int,
                   blk: int = 512) -> jax.Array:
    """One-hot einsum under lax.scan; any N (pads to a block multiple)."""
    F, N = bins_fm.shape
    gh3 = jnp.stack([gh8[0] + gh8[1], gh8[2] + gh8[3], gh8[4]])  # (3, N)
    if N % blk != 0:
        pad = blk - N % blk
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad)))
        gh3 = jnp.pad(gh3, ((0, 0), (0, pad)))
        N += pad
    nb = N // blk
    bb = bins_fm.reshape(F, nb, blk).transpose(1, 0, 2)  # (nb, F, blk)
    gg = gh3.reshape(3, nb, blk).transpose(1, 0, 2)  # (nb, 3, blk)
    iota = jnp.arange(num_bins, dtype=bins_fm.dtype)

    def body(acc, xs):
        b, g = xs  # (F, blk), (3, blk)
        onehot = (b[:, :, None] == iota).astype(jnp.float32)  # (F, blk, B)
        acc = acc + jnp.einsum(
            "frb,cr->cfb", onehot, g, preferred_element_type=jnp.float32
        )
        return acc, None

    init = jnp.zeros((3, F, num_bins), dtype=jnp.float32)
    hist, _ = lax.scan(body, init, (bb, gg))
    return hist


def histogram(bins_fm: jax.Array, gh8: jax.Array, num_bins: int) -> jax.Array:
    """(F, N) int32 bins + (8, N) channels -> (3, F, B) f32 histogram."""
    F, N = bins_fm.shape
    if _use_pallas() and N % HIST_BLK == 0 and N >= HIST_BLK:
        from .pallas_hist import hist_tpu

        return combine_ch(
            hist_tpu(bins_fm, gh8, num_bins, interpret=_interpret_pallas())
        )
    return _hist_fallback(bins_fm, gh8, num_bins)


def hist_slots(
    bins_fm: jax.Array,
    gh8: jax.Array,
    begins: jax.Array,
    counts: jax.Array,
    num_bins: int,
    num_slots: int,
    dense_visits: bool = False,
) -> jax.Array:
    """Per-slot histograms over contiguous row segments -> (S, 3, F, B).

    One data pass for ALL slots (the multi-leaf batched construction of
    the reference CUDA histogram kernel, cuda_histogram_constructor.cu —
    there one block per leaf, here one visit plan over sorted segments).
    Slots with counts == 0 return zeros. `dense_visits` doubles the
    visit budget for sharded runs where local segments can exceed N/2.
    """
    F, N = bins_fm.shape
    if _use_pallas() and N % HIST_BLK == 0 and N >= HIST_BLK:
        from .pallas_hist import hist_slots_tpu

        out = hist_slots_tpu(
            bins_fm, gh8, begins, counts, num_bins, num_slots,
            dense_visits=dense_visits, interpret=_interpret_pallas(),
        )  # (S+1, CH, F*B)
        out3 = jnp.stack(
            [out[:, 0] + out[:, 1], out[:, 2] + out[:, 3], out[:, 4]], axis=1
        ).reshape(num_slots + 1, 3, F, num_bins)[:num_slots]
        return jnp.where((counts > 0)[:, None, None, None], out3, 0.0)

    iota = jnp.arange(N, dtype=jnp.int32)

    def one(b, c):
        m = ((iota >= b) & (iota < b + c)).astype(jnp.float32)
        return _hist_fallback(bins_fm, gh8 * m[None, :], num_bins)

    return jax.vmap(one)(begins, counts)


def _hist_nat_fallback(bins_fm: jax.Array, gh8: jax.Array, slot: jax.Array,
                       num_slots: int, num_bins: int,
                       blk: int = 512, quant: bool = False) -> jax.Array:
    """XLA reference for hist_nat_slots: blocked one-hot einsum with an
    extra slot one-hot axis. Any N; CPU tests and odd row counts."""
    F, N = bins_fm.shape
    S = num_slots
    if quant:
        gh3 = gh8[:3]  # (g_int, h_int, count) — no hi/lo split
    else:
        gh3 = jnp.stack([gh8[0] + gh8[1], gh8[2] + gh8[3], gh8[4]])  # (3, N)
    if N % blk != 0:
        pad = blk - N % blk
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad)))
        gh3 = jnp.pad(gh3, ((0, 0), (0, pad)))
        slot = jnp.pad(slot, (0, pad), constant_values=S)
        N += pad
    nb = N // blk
    bb = bins_fm.reshape(F, nb, blk).transpose(1, 0, 2)  # (nb, F, blk)
    gg = gh3.reshape(3, nb, blk).transpose(1, 0, 2)  # (nb, 3, blk)
    ss = slot.reshape(nb, blk)
    iota_b = jnp.arange(num_bins, dtype=bins_fm.dtype)
    iota_s = jnp.arange(S, dtype=slot.dtype)

    def body(acc, xs):
        b, g, sl = xs  # (F, blk), (3, blk), (blk,)
        onehot = (b[:, :, None] == iota_b).astype(jnp.float32)  # (F, blk, B)
        slh = (sl[None, :] == iota_s[:, None]).astype(jnp.float32)  # (S, blk)
        acc = acc + jnp.einsum(
            "frb,cr,sr->scfb", onehot, g, slh,
            preferred_element_type=jnp.float32,
        )
        return acc, None

    init = jnp.zeros((S, 3, F, num_bins), jnp.float32)
    out, _ = lax.scan(body, init, (bb, gg, ss))
    return out


def build_gh8_quant(gq: jax.Array, hq: jax.Array, count: jax.Array) -> jax.Array:
    """Quantized-channel layout: (g_int, h_int, count, 0, ...). Integer
    levels (|g| <= num_grad_quant_bins/2 etc.) are exact in bf16, so the
    hi/lo split is unnecessary — 3 channels per slot instead of 5 packs
    48 slots per MXU pass (the TPU analog of the reference's int16
    histogram entries, bin.h:63-81)."""
    z = jnp.zeros_like(count)
    return jnp.stack([gq, hq, count, z, z, z, z, z])


def hist_nat_slots(
    bins_fm: jax.Array,  # (F, N) int32, NATURAL row order
    gh8: jax.Array,  # (8, N) f32 build_gh8 channels
    slot: jax.Array,  # (N,) int32 in [0, num_slots]; num_slots = trash
    num_slots: int,
    num_bins: int,
    quant: bool = False,  # gh8 built by build_gh8_quant (3 channels)
    int8: bool = False,  # quant levels within +/-127: s8 MXU, s32 sums
    oh_shift: int = 0,  # SWAR one-hot scale (int8_oh_shift policy)
) -> jax.Array:
    """Per-slot histograms keyed by a row->slot vector -> (S, 3, F, B).

    The natural-order multi-leaf construction: rows never move; each
    row's slot assignment selects which histogram it accumulates into.
    On TPU this is ONE pass of the slot-packed MXU kernel
    (pallas_hist.hist_nat_tpu) — the matmul M axis carries
    num_slots x NAT_CH channel rows, so up to ~25 slots cost the same
    wall time as a single-leaf histogram (the M=8 single-hist matmul
    leaves 120 of the MXU's 128 rows idle). Multi-leaf batching as in
    the reference CUDA kernel (cuda_histogram_constructor.cu:20) without
    its per-leaf row indices."""
    F, N = bins_fm.shape
    nat_ch = 3 if quant else NAT_CH
    # VMEM guard: chunk the slot axis so the kernel's grid-constant
    # output block stays within the scoped budget. Chip-calibrated
    # compile limits, post-NT-kernel (BENCH_NOTES r4): ch5 S=32
    # compiles / S=36 fails; ch3 S=64 compiles (6.06 ms; the pre-NT
    # kernel failed past 48 — removing the in-kernel transpose freed
    # scoped stack). The W tile, per-feature one-hots and
    # double-buffered inputs cost roughly 2x the output block again.
    # The byte formula guards wide feature sets; the empirical
    # per-channel-count cap guards the slot axis.
    per_slot = nat_ch * F * num_bins * 4
    s_cap, budget = _round_caps(nat_ch)
    use_i8 = bool(int8 and quant)
    # the persistent one-hot iota scratch is part of the kernel's VMEM
    # block schedule — charge it against the scoped budget
    budget = max(budget - _oh_scratch_bytes(num_bins, use_i8), 0)
    s_max = max(1, min(budget // max(per_slot, 1), s_cap))
    if (_use_pallas() and N % HIST_BLK == 0 and N >= HIST_BLK
            and per_slot <= budget):
        from .pallas_hist import hist_nat_tpu

        int4 = bool(use_i8 and _use_int4_oh())
        parts = []
        for c0 in range(0, num_slots, s_max):
            sc = min(s_max, num_slots - c0)
            if c0 == 0 and sc == num_slots:
                local = slot
            else:
                in_chunk = (slot >= c0) & (slot < c0 + sc)
                local = jnp.where(in_chunk, slot - c0, sc)
            out = hist_nat_tpu(
                bins_fm, gh8, local, sc, num_bins,
                interpret=_interpret_pallas(), nat_ch=nat_ch,
                int8=use_i8, oh_shift=oh_shift, int4=int4,
            )  # (sc*nat_ch, F*B)
            o = out.reshape(sc, nat_ch, F, num_bins)
            if quant:
                parts.append(o)
            else:
                parts.append(jnp.stack(
                    [o[:, 0] + o[:, 1], o[:, 2] + o[:, 3], o[:, 4]], axis=1
                ))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return _hist_nat_fallback(bins_fm, gh8, slot, num_slots, num_bins,
                              quant=quant)


def int8_oh_shift(n_rows: int, quant_levels: int) -> Optional[int]:
    """SWAR one-hot scale policy for the int8 histogram path.

    The SWAR one-hot bytes carry value 128 >> shift, so a histogram
    cell's s32 accumulator sees sums up to n_rows * level * (128 >>
    shift). Pick the cheapest shift (0 is ~2 VPU ops/vector cheaper
    than 4; 7 yields exact 1s like the compare path) that keeps the
    worst case under 2^31; None means even unscaled sums can overflow
    and the caller must not use int8 at all (ADVICE r4: a near-constant
    feature on a >16M-row dataset at max levels wraps silently — the
    reference's int32 buffers have the same bound, bin.h:63-81)."""
    levels = max(int(quant_levels), 1)
    for shift in (0, 4, 7):
        if n_rows * levels * (128 >> shift) < 2 ** 31:
            return shift
    return None


def rs_exact_ok(local_rows: int, n_ranks: int, quant_levels: int) -> bool:
    """Worst-case exactness bound for the int32 reduce-scatter wire
    (ADVICE r5 medium; same policy shape as int8_oh_shift).

    The rs wire ships per-rank integer histogram sums as int32 and the
    'quantized sums are exact, the wire is lossless' claim needs BOTH:

    - global: the mesh-wide hessian-channel cell sum reaches
      local_rows * n_ranks * quant_levels, which must stay under 2^31
      or the int32 reduction wraps silently (~8.4M global rows at 256
      levels — exactly the pod scale the path targets);
    - local: each rank accumulates its integer sums in f32 before the
      astype(int32) cast, so the per-rank worst case must stay within
      f32's exact-integer range 2^24 or the cast quantizes.

    False sends the caller to the f32 psum fallback (lossy-by-design,
    like the reference's f32 histogram mode). Static ints only — the
    decision is a trace-time constant, never a device value."""
    return rs_wire_dtype(local_rows, n_ranks, quant_levels) is not None


def rs_wire_dtype(local_rows: int, n_ranks: int,
                  quant_levels: int) -> "str | None":
    """Narrowest exact dtype for the reduce-scatter histogram wire
    (ROADMAP 3a; the reference's int16/int32 socket reducers,
    include/LightGBM/bin.h:63-81).

    - "int16" when the mesh-wide hessian-channel worst case
      local_rows * n_ranks * quant_levels stays under 2^15 — the
      per-rank partial AND the reduced global sum both fit int16, so
      the wire payload halves with no loss (the count channel is
      bounded by global rows, which the same product dominates);
    - "int32" under the wider bounds: global worst case under 2^31,
      and per-rank sums within f32's exact-integer range 2^24 (ranks
      accumulate in f32 before the integer cast);
    - None sends the caller to the f32 psum fallback (lossy-by-design,
      like the reference's f32 histogram mode).

    Static ints only — a trace-time constant, never a device value."""
    levels = max(int(quant_levels), 1)
    if local_rows * n_ranks * levels < 2 ** 15:
        return "int16"
    if (local_rows * n_ranks * levels < 2 ** 31
            and local_rows * levels < 2 ** 24):
        return "int32"
    return None


def _round_caps(nat_ch: int) -> tuple:
    """(slot cap, scoped-VMEM budget) for the slot-packed kernels —
    chip-calibrated compile limits shared by hist_nat_slots and the
    fused round kernel (see the comment in hist_nat_slots)."""
    return (32, int(4.6 * 2 ** 20)) if nat_ch >= 5 \
        else (64, int(5.7 * 2 ** 20))


def _oh_scratch_bytes(num_bins: int, int8: bool) -> int:
    """VMEM bytes of the kernels' persistent one-hot iota scratch
    (pallas_hist._oh_iota_shape): part of the explicit block schedule,
    so the slot-budget math must charge for it."""
    rows = -(-num_bins // 4) if int8 else num_bins
    return rows * HIST_BLK * 4


# the fused round kernel may chunk its slot axis (each chunk re-streams
# the bins/gh blocks, so the fan-out is capped — past this the
# non-fused path's separate passes are no worse)
_ROUND_MAX_CHUNKS = 4


def _round_s_max(num_feat: int, num_bins: int, quant: bool,
                 int8: bool) -> int:
    nat_ch = 3 if quant else NAT_CH
    s_cap, budget = _round_caps(nat_ch)
    budget = max(budget - _oh_scratch_bytes(num_bins, int8), 0)
    per_slot = nat_ch * num_feat * num_bins * 4
    if per_slot > budget:
        return 0
    return max(1, min(budget // max(per_slot, 1), s_cap))


def can_hist_round(n_rows: int, num_slots: int, num_feat: int,
                   num_bins: int, quant: bool,
                   int8: bool = False) -> bool:
    """Static gate for the fused round kernel (pallas path only). The
    slot axis may be CHUNKED (hist_round composes the disjoint
    per-chunk partition updates), so the gate requires one chunk to
    fit the scoped-VMEM schedule and caps the re-stream fan-out at
    _ROUND_MAX_CHUNKS."""
    s_max = _round_s_max(num_feat, num_bins, quant, int8)
    return (
        _use_pallas()
        and n_rows % HIST_BLK == 0
        and n_rows >= HIST_BLK
        and s_max > 0
        and num_slots <= _ROUND_MAX_CHUNKS * s_max
    )


def hist_round(
    bins_fm: jax.Array,  # (F, N) int32
    gh8: jax.Array,  # (CH, N) f32
    pleaf: jax.Array,  # (N,) int32 row -> leaf
    params: jax.Array,  # (S, 16) int32 per-slot split params
    col_onehot: jax.Array,  # (S, F) f32
    num_slots: int,
    num_bins: int,
    quant: bool = False,
    int8: bool = False,
    oh_shift: int = 0,
    efb: bool = False,
    cat_mask=None,
):
    """Fused round step -> ((S, 3, F, B) f32 histograms, (N,) new
    row->leaf). Callers must check can_hist_round first; histogram
    sums are exact (integer s32 on the int8 path, rescaled here).

    When S exceeds the one-chunk VMEM schedule, the slot axis is
    chunked: every chunk sees the ORIGINAL row->leaf vector and only
    its own slots' split params, so the per-chunk partition deltas
    touch disjoint rows (memberships are disjoint across slots) and
    compose by summation — pleaf_new = pleaf + sum(pleaf_chunk -
    pleaf). Histogram chunks concatenate along the slot axis."""
    from .pallas_hist import hist_round_tpu, _swar_divisor

    F, N = bins_fm.shape
    nat_ch = 3 if quant else NAT_CH
    use_int8 = bool(int8 and quant)
    s_max = _round_s_max(F, num_bins, quant, use_int8) or num_slots
    outs = []
    pl_new = None
    for c0 in range(0, num_slots, s_max):
        sc = min(s_max, num_slots - c0)
        out_c, pl_c = hist_round_tpu(
            bins_fm, gh8, pleaf, params[c0:c0 + sc],
            col_onehot[c0:c0 + sc], sc, num_bins, nat_ch,
            int8=use_int8, oh_shift=oh_shift, efb=efb,
            cat_mask=None if cat_mask is None else cat_mask[c0:c0 + sc],
            interpret=_interpret_pallas(),
        )
        outs.append(out_c.reshape(sc, nat_ch, F, num_bins))
        pl_new = pl_c if pl_new is None else pl_new + (pl_c - pleaf)
    o = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    if use_int8:
        o = o.astype(jnp.float32) * (1.0 / _swar_divisor(oh_shift))
    if quant:
        return o, pl_new
    o3 = jnp.stack([o[:, 0] + o[:, 1], o[:, 2] + o[:, 3], o[:, 4]], axis=1)
    return o3, pl_new


# the take/seg_sum kernels materialize an (L, HIST_BLK) f32 one-hot
# tile in VMEM per grid step; num_leaves may legally reach 131072
# (config.h num_leaves check), at which point the tile alone (131072 x
# 2048 x 4 = 1 GB) dwarfs the ~16 MB scoped budget and Mosaic compile
# fails where plain XLA take/scatter worked (ADVICE r4 medium). Cap the
# one-hot tile + in/out blocks at a conservative 8 MB -> L <= ~960.
_TAKE_L_CAP = (8 * 2 ** 20) // (HIST_BLK * 4)


def take_cols(tab: jax.Array, idx: jax.Array) -> jax.Array:
    """(k, L) table, (N,) int32 indices -> (k, N) tab[:, idx].

    TPU: one-hot MXU contraction (pallas take_small_tpu, ~0.1 ms at 1M
    rows) while L fits the VMEM one-hot tile (_TAKE_L_CAP); elsewhere
    (large L, unaligned N, no TPU): plain take. Negative / >= L indices
    return 0 on both paths."""
    N = idx.shape[0]
    L = tab.shape[1]
    if (_use_pallas() and N % HIST_BLK == 0 and N >= HIST_BLK
            and L <= _TAKE_L_CAP):
        from .pallas_hist import take_small_tpu

        return take_small_tpu(tab, idx, interpret=_interpret_pallas())
    out = jnp.take(tab, jnp.clip(idx, 0, L - 1), axis=1)
    return jnp.where(((idx >= 0) & (idx < L))[None, :], out, 0.0)


def seg_sum(vals: jax.Array, idx: jax.Array, num_out: int) -> jax.Array:
    """(k, N) values + (N,) int32 indices -> (k, num_out) per-index
    column sums. TPU: one-hot MXU contraction (pallas seg_sum_tpu)
    while num_out fits the VMEM one-hot tile (_TAKE_L_CAP); elsewhere:
    XLA scatter-add. Out-of-range indices are dropped on both paths."""
    k, N = vals.shape
    if (_use_pallas() and N % HIST_BLK == 0 and N >= HIST_BLK
            and num_out <= _TAKE_L_CAP):
        from .pallas_hist import seg_sum_tpu

        return seg_sum_tpu(vals, idx, num_out,
                           interpret=_interpret_pallas())
    in_range = (idx >= 0) & (idx < num_out)
    safe = jnp.where(in_range, idx, num_out)  # num_out -> dropped
    return jnp.zeros((k, num_out), vals.dtype).at[:, safe].add(
        jnp.where(in_range[None, :], vals, 0.0), mode="drop"
    )


def gather_rows(bins_fm: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather rows (lane axis) by index -> (F, len(idx)). Out-of-range
    idx (pad slots) fill with bin 0; callers zero their gh so those rows
    contribute nothing."""
    return jnp.take(bins_fm, idx, axis=1, mode="fill", fill_value=0)


def gather_gh8(gh8: jax.Array, idx: jax.Array) -> jax.Array:
    return jnp.take(gh8, idx, axis=1, mode="fill", fill_value=0.0)


def hist_capacities(n_rows: int, min_cap: int = HIST_BLK) -> tuple:
    """Static ladder of gather-buffer sizes: N/2, N/4, ... >= min_cap,
    each rounded up to a HIST_BLK multiple. The smaller child always
    fits in N/2; deep (small) leaves use the small buffers so histogram
    cost tracks leaf size."""

    def _round(c: int) -> int:
        return ((c + HIST_BLK - 1) // HIST_BLK) * HIST_BLK

    caps = []
    c = n_rows // 2
    while c >= min_cap:
        caps.append(_round(c))
        c //= 2
    if not caps:
        caps.append(_round(max(n_rows // 2, 1)))
    return tuple(caps)


def root_sums(gh8: jax.Array, axis_name: Optional[str] = None) -> jax.Array:
    """(sum_grad, sum_hess, count) over all in-bag rows. Globally reduced
    over the data mesh axis when present (reference
    data_parallel_tree_learner.cpp:169-221 root allreduce)."""
    s8 = jnp.sum(gh8, axis=1)
    s = jnp.stack([s8[0] + s8[1], s8[2] + s8[3], s8[4]])
    if axis_name is not None:
        s = lax.psum(s, axis_name)
    return s
