"""Phase-timer subsystem (reference USE_TIMETAG, utils/common.h:979)."""

from __future__ import annotations

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.timer import Timer, global_timer


def test_timer_accumulates_and_summarizes():
    t = Timer()
    t.enabled = True
    with t.scope("phase a"):
        pass
    with t.scope("phase a"):
        pass
    with t.scope("phase b", block=True):
        pass
    s = t.summary()
    assert s["phase a"][1] == 2
    assert s["phase b"][1] == 1
    assert all(v[0] >= 0 for v in s.values())
    t.reset()
    assert not t.summary()


def test_training_records_phases(capsys):
    was = global_timer.enabled
    global_timer.enabled = True
    global_timer.reset()
    try:
        rs = np.random.RandomState(0)
        X = rs.randn(600, 4)
        y = (X[:, 0] > 0).astype(float)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                  ds, num_boost_round=3)
        s = global_timer.summary()
        assert "dataset construct (binning)" in s
        assert any("dispatch" in k for k in s)
        global_timer.print_summary()
    finally:
        global_timer.enabled = was
        global_timer.reset()


def test_timer_enable_disable_runtime():
    """enable()/disable() flip timing without a process restart (the
    runtime analog of the reference's compile-time USE_TIMETAG)."""
    t = Timer()
    t.enabled = False
    with t.scope("off"):
        pass
    assert "off" not in t.summary()
    t.enable(summary_at_exit=False)
    assert t.enabled
    with t.scope("on"):
        pass
    assert "on" in t.summary()
    t.disable()
    with t.scope("off again"):
        pass
    assert "off again" not in t.summary()


def test_timetag_param_enables_global_timer():
    """The `timetag` config/CLI param turns the global phase timer on
    for a training run — no env var, no restart."""
    was = global_timer.enabled
    global_timer.disable()
    global_timer.reset()
    try:
        rs = np.random.RandomState(0)
        X = rs.randn(400, 4)
        y = (X[:, 0] > 0).astype(float)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1, "timetag": True},
                  ds, num_boost_round=2)
        assert global_timer.enabled
        assert global_timer.summary()  # phases were recorded
    finally:
        global_timer.enabled = was
        global_timer.reset()


def test_block_scope_barrier_syncs_every_local_device(monkeypatch):
    """The block=True barrier flushes EVERY local device (the old hack
    synced a single op on the default device only)."""
    import jax

    from lightgbm_tpu import timer as timer_mod

    seen = []
    orig = jax.device_put

    def spy(x, device=None, *args, **kwargs):
        seen.append(device)
        return orig(x, device, *args, **kwargs)

    monkeypatch.setattr(jax, "device_put", spy)
    timer_mod._sync_devices()
    synced = [d for d in seen if d is not None]
    assert len(synced) == len(jax.local_devices())
    assert set(synced) == set(jax.local_devices())

    # and scope(block=True) routes through the same barrier
    seen.clear()
    t = Timer()
    t.enabled = True
    with t.scope("sync", block=True):
        pass
    assert len([d for d in seen if d is not None]) == \
        len(jax.local_devices())
