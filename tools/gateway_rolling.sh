#!/usr/bin/env bash
# Zero-downtime rolling restart of a task=gateway fleet
# (docs/RESILIENCE.md "Serving gateway" — the runbook this script
# automates, end to end, on the CPU backend):
#
#   1. train a tiny model and start N task=serve backends + the
#      task=gateway front end;
#   2. run a continuous client against the GATEWAY for the whole
#      exercise, counting every non-200;
#   3. roll each backend in turn: SIGTERM (readyz flips 503, the
#      gateway health loop deregisters it, in-flight requests finish,
#      clean exit) -> restart on the same port -> wait until the
#      gateway routes to it again;
#   4. assert the client saw ZERO failures across the whole roll;
#   5. drain the gateway itself (SIGTERM): new work sheds 503
#      error_kind=shutdown, in-flight finishes, clean exit.
#
# Usage: tools/gateway_rolling.sh [N_BACKENDS]   (default 3)
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

N="${1:-3}"
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

python - "$WORK" <<'EOF'
import sys
import numpy as np

work = sys.argv[1]
rs = np.random.RandomState(0)
X = rs.randn(800, 5)
y = (X[:, 0] + X[:, 1] > 0).astype(int)
np.savetxt(f"{work}/train.csv",
           np.column_stack([y, X]), delimiter=",", fmt="%.6g")
EOF

python -m lightgbm_tpu task=train "data=$WORK/train.csv" \
    objective=binary num_leaves=15 num_trees=10 verbosity=-1 \
    "output_model=$WORK/model.txt"

python - "$WORK" "$N" <<'EOF'
import json
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

work, n_backends = sys.argv[1], int(sys.argv[2])


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


import os
import tempfile

# readiness-gated warmup is the load-bearing runbook step: with
# serve_warmup=true the registry precompiles every bucket BEFORE the
# HTTP listener binds, so /readyz green implies warm — the gateway
# never routes live traffic onto a cold restarted process (a cold
# first score would stall past the client deadline and shed 503).
# The persistent compile cache makes each restart's re-warm a cache
# hit instead of a recompile.
_env = dict(os.environ)
_env.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "lgbmtpu_gateway_rolling_cache"))


def spawn_backend(port):
    return subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "task=serve",
         f"input_model={work}/model.txt", f"serve_port={port}",
         "serve_buckets=16,64", "serve_warmup=true", "verbosity=-1"],
        env=_env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def wait_ready(url, proc, timeout=300):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f"process at {url} died "
                             f"rc={proc.returncode}")
        try:
            with urllib.request.urlopen(url + "/readyz", timeout=2) as r:
                if r.status == 200:
                    return
        except OSError:
            pass
        time.sleep(0.2)
    raise SystemExit(f"{url} never became ready")


ports = [free_port() for _ in range(n_backends)]
urls = [f"http://127.0.0.1:{p}" for p in ports]
procs = [spawn_backend(p) for p in ports]
for u, p in zip(urls, procs):
    wait_ready(u, p)

gw_port = free_port()
gw_url = f"http://127.0.0.1:{gw_port}"
gw = subprocess.Popen(
    [sys.executable, "-m", "lightgbm_tpu", "task=gateway",
     f"gateway_backends={','.join(urls)}", f"gateway_port={gw_port}",
     "gateway_health_interval_s=0.25", "gateway_retries=3",
     "gateway_backoff_base_s=0.02", "verbosity=-1"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
wait_ready(gw_url, gw)

rows = [[0.1 * i] * 5 for i in range(4)]


def score(timeout=30):
    req = urllib.request.Request(
        gw_url + "/v1/score",
        data=json.dumps({"rows": rows, "deadline_ms": 20000}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# warm every backend through the gateway before the roll
for _ in range(3 * n_backends):
    status, resp = score(timeout=300)
    assert status == 200 and resp["ok"], resp

failures = []
count = [0]
stop = threading.Event()


def client():
    while not stop.is_set():
        try:
            status, resp = score()
            if status != 200:
                failures.append((status, resp))
        except Exception as e:  # noqa: BLE001 — any client error is a failure
            failures.append(repr(e))
        else:
            count[0] += 1


threads = [threading.Thread(target=client, daemon=True)
           for _ in range(3)]
for t in threads:
    t.start()

# roll every backend: SIGTERM -> clean exit -> restart -> ready again
for i, (port, url) in enumerate(zip(ports, urls)):
    procs[i].send_signal(signal.SIGTERM)
    rc = procs[i].wait(timeout=120)
    assert rc == 0, f"backend {url} drain exited rc={rc}"
    procs[i] = spawn_backend(port)
    wait_ready(url, procs[i])
    # let the gateway's health loop fold it back into the pool
    time.sleep(1.0)
    print(f"gateway_rolling: rolled backend {i + 1}/{n_backends} "
          f"({url})", flush=True)

time.sleep(1.0)
stop.set()
for t in threads:
    t.join(timeout=60)
assert not failures, f"client-visible failures during roll: {failures[:5]}"
print(f"gateway_rolling: OK — {count[0]} requests, 0 failures "
      f"across a full roll of {n_backends} backends", flush=True)

# finally: drain the gateway itself
gw.send_signal(signal.SIGTERM)
rc = gw.wait(timeout=120)
assert rc == 0, f"gateway drain exited rc={rc}"
try:
    score(timeout=5)
    raise SystemExit("gateway still answering after drain")
except OSError:
    pass
print("gateway_rolling: OK — gateway drained clean (rc=0)", flush=True)

for p in procs:
    p.terminate()
for p in procs:
    try:
        p.wait(timeout=30)
    except subprocess.TimeoutExpired:
        p.kill()
EOF
