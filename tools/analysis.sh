#!/usr/bin/env bash
# CI wiring for the static analysis suite (docs/STATIC_ANALYSIS.md):
# trace-safety lint, serving concurrency lint, jaxpr invariant audits,
# the XLA cost/memory + collective wire-bytes audits, the
# BENCH-trajectory regression gate, and the SPMD scaling-contract
# auditor (Pass 7, scale_budget.json) over the FULL D in {1,2,4,8}
# mesh ladder — every pass registered in analysis/passes.py. (Tier-1
# tests only run the tiny D in {1,2} subset; this script is where the
# 4/8 rungs get exercised.) Strict mode: any unsuppressed finding or
# failed contract/budget/trajectory pin exits nonzero.
#
# Budget maintenance (run + review + commit the diff):
#   tools/analysis.sh --update-budget     # jaxpr_budget.json
#   tools/analysis.sh --refresh-budgets   # cost_budget.json + bench_budget.json
#                                         #   + scale_budget.json (+ diffs)
#
# The python entry point forces jax onto a cpu 8-device mesh itself, so
# this is safe on hosts whose ambient JAX_PLATFORMS points at real
# accelerators.
set -euo pipefail
cd "$(dirname "$0")/.."
if [[ "$*" == *--update-budget* || "$*" == *--refresh-budgets* ]]; then
  exec python -m lightgbm_tpu.analysis "$@"
fi
exec python -m lightgbm_tpu.analysis --strict "$@"
