"""Multi-host distributed training (reference src/network/ socket
cluster -> jax.distributed multi-controller; SURVEY §2.8).

Spawns two REAL processes connected by jax.distributed (Gloo CPU
collectives standing in for DCN), each holding half the rows
(pre_partition), allgathering binning samples, and growing one tree
through the data-parallel grower — both ranks must produce the
identical tree (the reference's lockstep guarantee)."""

import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Root cause of the long-standing "two pre-existing multihost failures"
# (docs/DESIGN_DECISIONS.md "Multihost tests xfail ..."): some jaxlib
# builds ship an XLA:CPU backend without cross-process collective
# support, and jax.distributed workers then die inside
# multihost_utils.process_allgather with exactly this error. That is an
# environment limitation, not a regression — xfail on the signature so
# the tier-1 gate stops carrying silent known-failures, while ANY other
# worker failure (real lockstep/parity breaks) still fails loudly.
# strict=False: on a jaxlib with Gloo CPU collectives the tests run
# and must pass.
#
# The signature drifts across jaxlib releases ("aren't implemented" vs
# "are not supported", capitalization, backend spelling), so match a
# small family of variants rather than one exact string — but ONLY
# this family: any other worker error still fails loudly.
_ENV_LIMIT_PATTERNS = (
    r"[Mm]ultiprocess computations? aren'?t implemented on the CPU "
    r"backend",
    r"[Mm]ulti[- ]?process (computations?|collectives?) (are not|aren'?t) "
    r"(supported|implemented) on (the )?(CPU|cpu)",
    r"[Cc]ross-process collectives? (are not|aren'?t) "
    r"(supported|implemented).*(CPU|cpu)",
)


def _env_limit_match(out: str):
    import re

    for pat in _ENV_LIMIT_PATTERNS:
        m = re.search(pat, out)
        if m:
            return m.group(0)
    return None


def _xfail_if_env_limited(outs) -> None:
    hits = [_env_limit_match(out) for out in outs]
    if any(hits):
        sig = next(h for h in hits if h)
        pytest.xfail(
            f"jaxlib CPU backend lacks cross-process collectives "
            f"({sig!r}); see docs/DESIGN_DECISIONS.md"
        )


@pytest.mark.timeout(600)
def test_two_process_data_parallel_lockstep():
    worker = Path(__file__).parent / "_multihost_worker.py"
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        outs.append(out)
    _xfail_if_env_limited(outs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out[-2000:]}"
        assert "MULTIHOST_OK" in out, out[-2000:]
    # both ranks report the same tree
    lines = [
        next(ln for ln in out.splitlines() if ln.startswith("MULTIHOST_OK"))
        for out in outs
    ]
    sig = [ln.split("nodes=")[1] for ln in lines]
    assert sig[0] == sig[1], lines


def test_two_process_full_train_api(tmp_path):
    """run_distributed (the dask _train analog): 2 real processes, full
    lgb.train — global binning, per-iteration eval, early stopping,
    rank-0 save — byte-identical models on both ranks (VERDICT r3 #7)."""
    worker = Path(__file__).parent / "_multihost_train_worker.py"
    port = _free_port()
    out_model = tmp_path / "dist_model.txt"
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port),
             str(out_model)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost train worker timed out")
        outs.append(out)
    _xfail_if_env_limited(outs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out[-3000:]}"
        assert "MULTIHOST_TRAIN_OK" in out, out[-3000:]
    lines = [
        next(ln for ln in out.splitlines()
             if ln.startswith("MULTIHOST_TRAIN_OK"))
        for out in outs
    ]
    sigs = [dict(kv.split("=") for kv in ln.split()[1:]) for ln in lines]
    assert sigs[0]["model"] == sigs[1]["model"], lines  # identical models
    assert sigs[0]["best_it"] == sigs[1]["best_it"], lines
    assert float(sigs[0]["auc"]) > 0.9, lines
    l1 = [
        next(ln for ln in out.splitlines()
             if ln.startswith("MULTIHOST_L1_OK"))
        for out in outs
    ]
    l1s = [dict(kv.split("=") for kv in ln.split()[1:]) for ln in l1]
    assert l1s[0]["model"] == l1s[1]["model"], l1  # renewal objective too
    assert out_model.exists()  # rank-0 save landed
    # the saved model loads and predicts in THIS process
    import lightgbm_tpu as lgb

    bst = lgb.Booster(model_file=out_model)
    assert np.isfinite(bst.predict(np.zeros((2, 8)))).all()
