"""lgb.cv (reference engine.py:627): fused chunked per-fold training
with ONE shared traced step across folds (VERDICT r4 item 6)."""

from __future__ import annotations

import numpy as np

import lightgbm_tpu as lgb


def _problem(n=4000, f=6, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    w = rs.randn(f)
    y = ((X @ w + 0.3 * rs.randn(n)) > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "metric": "auc",
          "verbosity": -1, "min_data_in_leaf": 5}


def test_cv_basic_and_single_trace():
    from lightgbm_tpu.boosting import _FUSED_STEP_CACHE

    X, y = _problem()
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    _FUSED_STEP_CACHE.clear()
    res = lgb.cv(dict(PARAMS), ds, num_boost_round=8, nfold=4,
                 stratified=False)
    assert len(res["valid auc-mean"]) == 8
    assert len(res["valid auc-stdv"]) == 8
    assert res["valid auc-mean"][-1] > 0.85
    # the memoized fused step: 4 folds, ONE trace
    assert len(_FUSED_STEP_CACHE) == 1


def test_cv_matches_sync_fold_loop():
    """The fused chunked cv must aggregate the same per-iteration
    numbers as a hand-rolled sync fold loop (same folds, same seeds)."""
    X, y = _problem(seed=3)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    res = lgb.cv(dict(PARAMS), ds, num_boost_round=5, nfold=3,
                 stratified=False, seed=7)

    from lightgbm_tpu.engine import _make_n_folds

    ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
    folds = list(_make_n_folds(ds2, 3, dict(PARAMS), 7, False, True))
    per_iter = [[] for _ in range(5)]
    for tr_idx, te_idx in folds:
        tr = ds2.subset(tr_idx)
        te = ds2.subset(te_idx)
        bst = lgb.Booster(params=dict(PARAMS), train_set=tr)
        bst.add_valid(te, "valid")
        bst._gbdt._force_sync = True
        for i in range(5):
            bst.update()
            per_iter[i].append(bst.eval_valid()[0][2])
    ref_means = [float(np.mean(v)) for v in per_iter]
    np.testing.assert_allclose(res["valid auc-mean"], ref_means,
                               rtol=1e-5, atol=1e-6)


def test_cv_early_stopping_and_cvbooster():
    X, y = _problem(seed=5)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    res = lgb.cv(dict(PARAMS, early_stopping_round=3,
                      early_stopping_min_delta=0.2), ds,
                 num_boost_round=50, nfold=3, stratified=False,
                 return_cvbooster=True)
    cvb = res["cvbooster"]
    assert 1 <= cvb.best_iteration < 47  # the stop actually fired
    assert len(res["valid auc-mean"]) == cvb.best_iteration
    assert len(cvb.boosters) == 3
    # every fold keeps trees THROUGH the stop iteration (best + k),
    # matching the sync fold loop
    for b in cvb.boosters:
        assert b.num_trees() == cvb.best_iteration + 3


def test_cv_eval_train_metric():
    X, y = _problem(seed=8)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    res = lgb.cv(dict(PARAMS), ds, num_boost_round=4, nfold=3,
                 stratified=False, eval_train_metric=True)
    assert any(k.startswith("training ") for k in res), list(res)
    assert any(k.startswith("valid ") for k in res), list(res)


def test_cv_custom_feval_falls_back_to_sync():
    """Custom feval can't ride the fused device loop; cv must still
    work through the per-iteration sync path."""
    X, y = _problem(seed=9)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)

    def feval(preds, eval_data):
        lab = eval_data.get_label()
        return "half_err", float(np.mean((preds > 0.5) != lab)), False

    res = lgb.cv(dict(PARAMS, metric="none"), ds, num_boost_round=3,
                 nfold=3, stratified=False, feval=feval)
    assert "valid half_err-mean" in res, list(res)
    assert len(res["valid half_err-mean"]) == 3
