// Build shim for the parity harness: the reference's vendored
// fast_double_parser submodule is not checked out in this image. Same
// API, strtod-backed (slower, equally precise).
#ifndef FAST_DOUBLE_PARSER_SHIM_H_
#define FAST_DOUBLE_PARSER_SHIM_H_
#include <cstdlib>
#include <clocale>

namespace fast_double_parser {
inline const char* parse_number(const char* p, double* out) {
  char* end = nullptr;
  double v = std::strtod(p, &end);
  if (end == p) return nullptr;
  *out = v;
  return end;
}
}  // namespace fast_double_parser
#endif
