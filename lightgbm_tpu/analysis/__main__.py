"""CLI: `python -m lightgbm_tpu.analysis [--strict] [...]`.

Runs every registered analysis pass (passes.PASSES — trace-safety
lint, concurrency lint, jaxpr invariant audits, XLA cost/memory +
wire-bytes audits) and prints a combined report. `--strict` (the CI /
tier-1 hook mode) exits 1 on any unsuppressed finding or failed
contract; the default mode reports and exits 0.

Budget maintenance:
  --update-budget     rewrite jaxpr_budget.json (+25% headroom)
  --refresh-budgets   rewrite cost_budget.json (+25% headroom on cost
                      metrics, EXACT wire bytes), bench_budget.json,
                      and scale_budget.json (EXACT per-rung pins over
                      the full D-ladder), printing an old->new diff
                      of each for review

The jax-backed audits need a multi-device CPU mesh; this entry point
forces `jax_platforms=cpu` with 8 virtual devices (same as
tests/conftest.py) so a bare invocation never touches real
accelerators.
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_cpu_mesh() -> None:
    """cpu + 8 virtual devices BEFORE any backend initializes (package
    import already loaded jax, but the backend is lazy — mirror the
    conftest.py override)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    from .passes import PASSES

    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="static analysis suite: "
        + "; ".join(f"{p.name} = {p.doc}" for p in PASSES.values())
        + " (docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any violation / failed contract")
    ap.add_argument("--lint-only", action="store_true",
                    help="only the AST passes (no jax backend needed)")
    ap.add_argument("--audit-only", action="store_true",
                    help="only the jaxpr/cost audits (skip the AST lints)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset of passes to run "
                    f"(registered: {', '.join(PASSES)})")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed lint findings")
    ap.add_argument("--update-budget", action="store_true",
                    help="rewrite jaxpr_budget.json from current sizes "
                    "(+25%% headroom); review the diff before commit")
    ap.add_argument("--refresh-budgets", action="store_true",
                    help="rewrite cost_budget.json from current compiles "
                    "(+25%% headroom, exact wire bytes) and print the diff")
    ap.add_argument("--package", default=None,
                    help="package directory to lint (default: the "
                    "installed lightgbm_tpu package)")
    args = ap.parse_args(argv)

    if args.passes is not None:
        names = [n.strip() for n in args.passes.split(",") if n.strip()]
        if not names:
            # an empty selection must not report a vacuous clean run
            ap.error("--passes got an empty selection; registered: "
                     + ", ".join(PASSES))
    elif args.lint_only:
        names = [n for n, p in PASSES.items() if not p.needs_jax]
    elif args.audit_only:
        names = [n for n, p in PASSES.items() if p.needs_jax]
    else:
        names = list(PASSES)

    if any(PASSES[n].needs_jax for n in names if n in PASSES) \
            or args.update_budget or args.refresh_budgets:
        _force_cpu_mesh()

    if args.update_budget or args.refresh_budgets:
        # budget maintenance still reports contract health: a FAILing
        # non-budget contract (wire dtype, callbacks, f64) during a
        # refresh must not hide behind "budgets updated" under --strict
        failed = False
        if args.update_budget:
            from .jaxpr_audit import run_audits

            results = run_audits(update_budget=True)
            for r in results:
                print(r.format())
            failed |= not all(r.ok for r in results)
            print("jaxpr_budget.json updated")
        if args.refresh_budgets:
            from .cost_audit import (
                format_budget_diff,
                refresh_budgets,
                run_cost_audits,
            )

            old, new = refresh_budgets()
            print("cost_budget.json updated:")
            print(format_budget_diff(old, new))
            results = run_cost_audits()
            failed |= not all(r.ok for r in results)
            for r in results:
                if not r.ok:
                    print(r.format())
            # bench trajectory pins ride the same refresh flow
            from . import bench_gate

            bold, bnew = bench_gate.refresh_budget()
            print("bench_budget.json updated:")
            print(bench_gate.format_budget_diff(bold, bnew))
            gate = bench_gate.run_gate()
            failed |= not gate.ok
            if not gate.ok:
                print(gate.format())
            # scaling-contract pins too (full D-ladder, exact)
            from .scale_audit import (
                format_scale_diff,
                refresh_scale_budget,
                run_scale_audits,
            )

            sold, snew = refresh_scale_budget()
            print("scale_budget.json updated:")
            print(format_scale_diff(sold, snew))
            sresults = run_scale_audits()
            failed |= not all(r.ok for r in sresults)
            for r in sresults:
                if not r.ok:
                    print(r.format())
        if failed:
            print("analysis: FAIL (budgets updated, but contracts are "
                  "red)" if args.strict else
                  "analysis: contract violations found (non-strict: "
                  "exit 0)")
            return 1 if args.strict else 0
        return 0

    from .passes import run_passes

    results = run_passes(names, pkg_root=args.package,
                         show_suppressed=args.show_suppressed)
    for r in results:
        print(f"== {r.name} ==")
        print(r.report)
    failed = not all(r.ok for r in results)
    if failed:
        print("analysis: FAIL" if args.strict else
              "analysis: violations found (non-strict: exit 0)")
        return 1 if args.strict else 0
    print("analysis: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
