"""Serving benchmark: QPS + latency percentiles for the scoring path.

Prints ONE JSON line and writes it to BENCH_SERVE_rNN.json next to the
training BENCH files, so serving performance is tracked
round-over-round exactly like training throughput (ROADMAP item 4; the
artifact always carries "qps", "p50_ms", "p99_ms").

Three phases, one artifact — the comparison is same-run so the two
sides share the trained model, the process, and the machine state:

1. **baseline** — the model in a single-replica ModelRegistry, one
   closed-loop client calling ``registry.predict`` directly (no
   queue).  This is the floor a naive deployment gets.
2. **loaded** (the headline "qps"/"p99_ms") — the same model behind
   ``replicas`` predictor replicas with the continuous-batching
   MicroBatcher front (``registry.batcher``); pipelined async clients
   keep a window of futures outstanding so requests coalesce into
   shared padded device calls.  A fixed probe batch is scored through
   BOTH paths and compared bit-for-bit ("bit_identical") — the speedup
   must not come from answering a different question.
   "speedup_x" = loaded/baseline QPS.
3. **fleet** — the same booster loaded under ``fleet_size`` names into
   a ModelFleet whose HBM ``capacity`` is smaller than the fleet, then
   scored round-robin so LRU paging churns; per-model p99 and the
   pager's counters land in "fleet".
4. **gateway** — cross-process scale-out (docs/RESILIENCE.md "Serving
   gateway"): the same model behind 1 vs N real ``task=serve`` backend
   processes fronted by an in-process Gateway; tenants are fan-out
   loaded and a Zipfian-skewed tenant replay is fired by concurrent
   clients. Per-config QPS/p50/p99 plus the hedge/retry/breaker
   counters read back from the MERGED ``/metrics`` snapshot land in
   "gateway"; "scaleout_x" = many-backend / one-backend QPS.

The dispatcher's own observability (queue depth, padded-row waste,
coalesce ratio — what /metrics exports) is snapshotted per phase into
"dispatcher" so the benchmark numbers and the metrics numbers can be
cross-checked.

Env overrides: BENCH_SERVE_TRAIN_ROWS, BENCH_SERVE_FEATURES,
BENCH_SERVE_TREES, BENCH_SERVE_LEAVES, BENCH_SERVE_REQUESTS,
BENCH_SERVE_BATCH (rows per request — 1 by default: the online-request
shape continuous batching exists for), BENCH_SERVE_THREADS
(loaded-phase clients), BENCH_SERVE_WINDOW (outstanding futures per
client), BENCH_SERVE_BASE_REQUESTS, BENCH_SERVE_REPLICAS,
BENCH_SERVE_FLEET_MODELS, BENCH_SERVE_FLEET_CAPACITY,
BENCH_SERVE_FLEET_REQUESTS, BENCH_SERVE_GATEWAY_BACKENDS
(comma-separated backend counts to compare, default "1,4"; empty
skips the phase), BENCH_SERVE_GATEWAY_REQUESTS,
BENCH_SERVE_GATEWAY_THREADS, BENCH_SERVE_GATEWAY_TENANTS,
BENCH_SERVE_GATEWAY_ZIPF (skew exponent),
BENCH_SERVE_OUT (explicit output path),
BENCH_SERVE_DIR (output directory, default: repo root),
BENCH_RUN_DIR / BENCH_MANIFEST_OUT (run-manifest location — the
manifest lives under the tmp run dir, never the repo root).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
SCHEMA = "lightgbm-tpu/bench-serve/v1"

# last builder-verified ON-CHIP serving measurement — the same
# carry-forward semantics bench.py uses for training throughput: when
# a run lands off-chip, this rides along marked `stale: true` so the
# bench gate (analysis/bench_gate.py) never reads a carried number as
# fresh. None until the first chip serving run lands; update it there
# and re-pin with `python -m lightgbm_tpu.analysis --refresh-budgets`.
LAST_TPU_VERIFIED = None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _pct(sorted_vals, p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(p * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _lat_summary(latencies, wall: float, batch: int) -> dict:
    lat = sorted(latencies)
    done = len(lat)
    return {
        "qps": round(done / wall, 2) if wall > 0 else 0.0,
        "p50_ms": round(1e3 * _pct(lat, 0.50), 4),
        "p95_ms": round(1e3 * _pct(lat, 0.95), 4),
        "p99_ms": round(1e3 * _pct(lat, 0.99), 4),
        "mean_ms": round(1e3 * sum(lat) / done, 4) if lat else 0.0,
        "rows_per_sec": round(done * batch / wall, 1) if wall > 0 else 0.0,
        "requests": done,
        "wall_s": round(wall, 3),
    }


def _fire(predict, n_requests: int, n_threads: int, batch: int,
          n_feat: int) -> dict:
    """Closed-loop clients: n_threads threads each fire their share of
    n_requests calls to ``predict(rows)``; returns the latency summary."""
    latencies: list = []
    lock = threading.Lock()
    per_thread = max(n_requests // max(n_threads, 1), 1)

    def worker(seed: int) -> None:
        wrs = np.random.RandomState(seed)
        mine = []
        for _ in range(per_thread):
            rows = wrs.randn(batch, n_feat).astype(np.float32)
            t = time.perf_counter()
            predict(rows)
            mine.append(time.perf_counter() - t)
        with lock:
            latencies.extend(mine)

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(n_threads)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return _lat_summary(latencies, time.perf_counter() - t0, batch)


def _fire_pipelined(submit, n_requests: int, n_threads: int, window: int,
                    batch: int, n_feat: int) -> dict:
    """Pipelined async clients: each thread keeps up to ``window``
    futures outstanding (submit without blocking, collect the oldest
    once the window fills) so the continuous-batching queue stays fed.
    Latency is submit→completion per request."""
    latencies: list = []
    lock = threading.Lock()
    per_thread = max(n_requests // max(n_threads, 1), 1)

    def worker(seed: int) -> None:
        wrs = np.random.RandomState(seed)
        mine: list = []
        outstanding: list = []

        def collect(pair) -> None:
            t_submit, fut = pair
            fut.result()
            mine.append(time.perf_counter() - t_submit)

        for _ in range(per_thread):
            rows = wrs.randn(batch, n_feat).astype(np.float32)
            outstanding.append((time.perf_counter(), submit(rows)))
            if len(outstanding) >= window:
                collect(outstanding.pop(0))
        for pair in outstanding:
            collect(pair)
        with lock:
            latencies.extend(mine)

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(n_threads)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return _lat_summary(latencies, time.perf_counter() - t0, batch)


def _serve_counters() -> dict:
    """Summed lgbmtpu_serve_* counter values from the metrics registry
    (labels collapsed) — diffed around a phase to attribute traffic."""
    from lightgbm_tpu.obs.metrics import default_registry

    out: dict = {}
    for name, by_label in default_registry().snapshot().items():
        if name.startswith(("lgbmtpu_serve_", "lgbmtpu_fleet_")):
            out[name] = sum(by_label.values())
    return out


def _dispatcher_view(before: dict, after: dict, rows_scored: int) -> dict:
    """The observability view of one phase: coalescing efficiency and
    padding waste derived from the /metrics counters."""
    d = {k: after.get(k, 0.0) - before.get(k, 0.0)
         for k in after}
    drains = d.get("lgbmtpu_serve_coalesced_batch_rows_count", 0.0)
    coalesced = d.get("lgbmtpu_serve_coalesced_requests_total", 0.0)
    padded = d.get("lgbmtpu_serve_padded_rows_total", 0.0)
    calls = d.get("lgbmtpu_serve_bucket_dispatch_total", 0.0)
    return {
        "device_calls": int(calls),
        "coalesced_requests": int(coalesced),
        "coalesce_ratio": round(coalesced / drains, 3) if drains else 0.0,
        "padded_rows": int(padded),
        "padding_waste_frac": round(
            padded / (padded + rows_scored), 4
        ) if rows_scored else 0.0,
        "queue_depth": after.get("lgbmtpu_serve_queue_depth", 0.0),
    }


def _counter_family(merged: dict, name: str) -> dict:
    fam = (merged.get("metrics") or {}).get(name) or {}
    return {k: v for k, v in (fam.get("values") or {}).items()}


# the resilience counters the gateway phase reports per config
_GW_FAMILIES = (
    "lgbmtpu_gateway_hedges_total",
    "lgbmtpu_gateway_retries_total",
    "lgbmtpu_gateway_breaker_transitions_total",
    "lgbmtpu_gateway_attempts_total",
)


def _diff_counters(cur: dict, floor: dict) -> dict:
    """Per-config view of process-cumulative counters: cur - floor,
    zero rows dropped (label keys render identically in the registry
    snapshot and the merged pane)."""
    out = {}
    for k, v in cur.items():
        d = float(v) - float(floor.get(k, 0.0))
        if d:
            out[k] = int(d) if d.is_integer() else d
    return out


def _gateway_phase(model_file: str, model_str: str, n_feat: int,
                   batch: int) -> dict | None:
    """Phase 4: 1 vs N real task=serve backend processes behind an
    in-process Gateway, Zipfian tenant replay, counters read back from
    the merged /metrics snapshot. Returns None when disabled
    (BENCH_SERVE_GATEWAY_BACKENDS empty)."""
    import socket
    import subprocess
    import urllib.request

    from lightgbm_tpu.serving.gateway import Gateway

    spec = os.environ.get("BENCH_SERVE_GATEWAY_BACKENDS", "1,4")
    counts = [int(x) for x in spec.split(",") if x.strip()]
    if not counts:
        return None
    n_requests = _env_int("BENCH_SERVE_GATEWAY_REQUESTS", 600)
    n_threads = _env_int("BENCH_SERVE_GATEWAY_THREADS", 6)
    n_tenants = _env_int("BENCH_SERVE_GATEWAY_TENANTS", 4)
    zipf_a = float(os.environ.get("BENCH_SERVE_GATEWAY_ZIPF", "1.2"))

    # Zipf-by-rank tenant weights: tenant r gets 1/(r+1)^a of the
    # traffic — the skew multi-tenant serving actually sees
    tenants = [f"tenant{t:02d}" for t in range(n_tenants)]
    w = np.array([1.0 / (r + 1) ** zipf_a for r in range(n_tenants)])
    w /= w.sum()
    replay = np.random.RandomState(11).choice(n_tenants,
                                              size=n_requests, p=w)

    env = dict(os.environ)
    # restart/re-spawn compiles become cache hits across backends
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
        tempfile.gettempdir(), "lgbmtpu_bench_gateway_cache"))

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn(port: int):
        return subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu", "task=serve",
             f"input_model={model_file}", f"serve_port={port}",
             "serve_buckets=16,64", "serve_warmup=true",
             "verbosity=-1"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

    def wait_ready(url: str, proc, timeout: float = 600.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(f"bench backend died "
                                   f"rc={proc.returncode}")
            try:
                with urllib.request.urlopen(url + "/readyz",
                                            timeout=2) as r:
                    if r.status == 200:
                        return
            except OSError:
                pass
            time.sleep(0.2)
        raise RuntimeError(f"bench backend at {url} never ready")

    rs = np.random.RandomState(3)
    rows = rs.randn(batch, n_feat).astype(np.float32).tolist()
    configs: dict = {}
    for k in counts:
        ports = [free_port() for _ in range(k)]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        procs = [spawn(p) for p in ports]
        gw = None
        try:
            for u, p in zip(urls, procs):
                wait_ready(u, p)
            gw = Gateway(urls, retries=3, backoff_base_s=0.02,
                         health_interval_s=0.5, hedge_budget=0.1,
                         attempt_timeout_s=60.0)
            gw.start(wait_ready_s=30.0)
            # the gateway records into the bench process's registry, so
            # counters are cumulative across configs: floor them here
            # and report per-config deltas
            from lightgbm_tpu.obs.metrics import default_registry
            snap = default_registry().snapshot()
            floor = {name: dict(snap.get(name) or {})
                     for name in _GW_FAMILIES}
            for t in tenants:
                status, resp = gw.handle("load", {
                    "model": t, "model_str": model_str,
                    "num_features": n_feat})
                if status != 200:
                    raise RuntimeError(f"tenant load failed: {resp}")
            # warm every (tenant, backend) pair off the clock
            for _ in range(2 * k):
                for t in tenants:
                    status, _ = gw.handle("score",
                                          {"model": t, "rows": rows})
                    if status != 200:
                        raise RuntimeError("warmup score failed")
            lat: list = []
            lat_lock = threading.Lock()
            failures = [0]
            cursor = [0]

            def worker() -> None:
                local: list = []
                while True:
                    with lat_lock:
                        i = cursor[0]
                        if i >= n_requests:
                            break
                        cursor[0] += 1
                    t0 = time.perf_counter()
                    status, _resp = gw.handle("score", {
                        "model": tenants[replay[i]], "rows": rows,
                        "deadline_ms": 60000})
                    dt = time.perf_counter() - t0
                    if status == 200:
                        local.append(dt)
                    else:
                        with lat_lock:
                            failures[0] += 1
                with lat_lock:
                    lat.extend(local)

            t0 = time.perf_counter()
            threads = [threading.Thread(target=worker)
                       for _ in range(n_threads)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            summary = _lat_summary(lat, wall, batch)
            summary["threads"] = n_threads
            summary["failures"] = failures[0]
            # resilience counters come from the MERGED /metrics pane —
            # the same single-pane view operators scrape
            merged = gw.merged_metrics()
            summary["merged_processes"] = merged.get("processes")
            for label, fam in (("hedges", _GW_FAMILIES[0]),
                               ("retries", _GW_FAMILIES[1]),
                               ("breaker_transitions", _GW_FAMILIES[2]),
                               ("attempts", _GW_FAMILIES[3])):
                summary[label] = _diff_counters(
                    _counter_family(merged, fam), floor[fam])
            configs[f"backends_{k}"] = summary
        finally:
            if gw is not None:
                gw.stop()
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
    lo, hi = min(counts), max(counts)
    out = {
        "requests": n_requests,
        "threads": n_threads,
        "tenants": n_tenants,
        "zipf_a": zipf_a,
        "configs": configs,
    }
    if lo != hi:
        base_qps = configs[f"backends_{lo}"]["qps"]
        out["scaleout_x"] = (
            round(configs[f"backends_{hi}"]["qps"] / base_qps, 2)
            if base_qps else 0.0)
    return out


def run_bench() -> dict:
    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelFleet, ModelRegistry

    train_rows = _env_int("BENCH_SERVE_TRAIN_ROWS", 20000)
    n_feat = _env_int("BENCH_SERVE_FEATURES", 16)
    n_trees = _env_int("BENCH_SERVE_TREES", 50)
    n_leaves = _env_int("BENCH_SERVE_LEAVES", 31)
    n_requests = _env_int("BENCH_SERVE_REQUESTS", 8192)
    base_requests = _env_int("BENCH_SERVE_BASE_REQUESTS", 256)
    batch = _env_int("BENCH_SERVE_BATCH", 1)
    n_threads = _env_int("BENCH_SERVE_THREADS", 8)
    window = _env_int("BENCH_SERVE_WINDOW", 128)
    replicas = _env_int("BENCH_SERVE_REPLICAS", 2)
    fleet_models = _env_int("BENCH_SERVE_FLEET_MODELS", 6)
    fleet_capacity = _env_int("BENCH_SERVE_FLEET_CAPACITY", 4)
    fleet_requests = _env_int("BENCH_SERVE_FLEET_REQUESTS", 60)

    rs = np.random.RandomState(0)
    X = rs.randn(train_rows, n_feat).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    t0 = time.perf_counter()
    bst = lgb.train(
        {"objective": "binary", "num_leaves": n_leaves, "verbosity": -1},
        ds, num_boost_round=n_trees,
    )
    train_s = time.perf_counter() - t0
    probe = rs.randn(64, n_feat).astype(np.float32)
    warm = rs.randn(batch, n_feat).astype(np.float32)

    # ---- phase 1: single replica, direct path, one closed-loop client
    # (raw margins on both sides: the comparison measures serving, not
    # the objective's output transform)
    baseline_reg = ModelRegistry(warmup=True)
    baseline_reg.load("bench", bst, num_features=n_feat)
    for _ in range(3):  # compiles + first-dispatch costs off the clock
        baseline_reg.predict("bench", warm, raw_score=True)
        baseline_reg.predict("bench", probe, raw_score=True)
    baseline = _fire(
        lambda rows: baseline_reg.predict("bench", rows, raw_score=True),
        base_requests, 1, batch, n_feat,
    )
    baseline["threads"] = 1
    baseline_pred = np.asarray(baseline_reg.predict("bench", probe))

    # ---- phase 2: N replicas + continuous batching, pipelined clients
    loaded_reg = ModelRegistry(warmup=True, replicas=replicas)
    loaded_reg.load("bench", bst, num_features=n_feat)
    batcher = loaded_reg.batcher("bench")
    for _ in range(3):
        batcher.submit(warm).result()
        loaded_reg.predict("bench", probe, via_queue=True)
    before = _serve_counters()
    loaded = _fire_pipelined(
        batcher.submit, n_requests, n_threads, window, batch, n_feat,
    )
    loaded["threads"] = n_threads
    dispatcher = _dispatcher_view(
        before, _serve_counters(), loaded["requests"] * batch)
    # the speedup must answer the SAME question: probe scored through
    # the coalescing multi-replica path must match the direct baseline
    # bit for bit
    loaded_pred = np.asarray(
        loaded_reg.predict("bench", probe, via_queue=True))
    bit_identical = bool(np.array_equal(baseline_pred, loaded_pred))
    speedup = (round(loaded["qps"] / baseline["qps"], 2)
               if baseline["qps"] else 0.0)

    # ---- phase 3: multi-tenant fleet with LRU paging churn
    fleet = ModelFleet(capacity=fleet_capacity)
    names = [f"bench{i:02d}" for i in range(fleet_models)]
    for name in names:
        fleet.load(name, bst, num_features=n_feat)
    per_model: dict = {name: [] for name in names}
    t0 = time.perf_counter()
    for i in range(fleet_requests):
        name = names[i % len(names)]
        rows = rs.randn(batch, n_feat).astype(np.float32)
        t = time.perf_counter()
        fleet.predict(name, rows)
        per_model[name].append(time.perf_counter() - t)
    fleet_wall = time.perf_counter() - t0
    fstats = fleet.fleet_stats()
    fleet_result = {
        "fleet_size": fleet_models,
        "capacity": fleet_capacity,
        "resident": fstats.get("resident"),
        "pages_in": fstats.get("pages_in"),
        "evictions": fstats.get("evictions"),
        "qps": round(fleet_requests / fleet_wall, 2) if fleet_wall else 0.0,
        "per_model_p99_ms": {
            name: round(1e3 * _pct(sorted(v), 0.99), 4)
            for name, v in per_model.items()
        },
    }
    fleet.close()

    # ---- phase 4: cross-process scale-out behind the gateway
    gateway_result = None
    try:
        with tempfile.NamedTemporaryFile(
                mode="w", suffix=".txt", delete=False) as f:
            model_file = f.name
            f.write(bst.model_to_string())
        try:
            gateway_result = _gateway_phase(
                model_file, bst.model_to_string(), n_feat, batch)
        finally:
            os.unlink(model_file)
    except Exception as e:  # noqa: BLE001 — scale-out phase must not sink the artifact
        gateway_result = {"error": f"{type(e).__name__}: {e}"}

    result = {
        "schema": SCHEMA,
        "metric": "serve_score_qps",
        **loaded,  # headline qps/p50/p99 = the replicated, batched path
        "batch_rows": batch,
        "via_queue": True,
        "window": window,
        "replicas": replicas,
        "baseline": baseline,
        "speedup_x": speedup,
        "bit_identical": bit_identical,
        "dispatcher": dispatcher,
        "fleet_size": fleet_models,
        "models": names,
        "fleet": fleet_result,
        "gateway": gateway_result,
        "model": {"trees": n_trees, "leaves": n_leaves,
                  "features": n_feat, "train_rows": train_rows,
                  "train_s": round(train_s, 2)},
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        # the observability view of the same run (LatencyStats ring —
        # what /metrics and the stats op report)
        "stats": loaded_reg.stats().get("bench", {}),
        "created_unix": time.time(),
        "run_id": f"{int(time.time())}-{os.getpid()}",
    }
    if LAST_TPU_VERIFIED:
        # same staleness rule as bench.py: carried chip numbers are
        # fresh only when THIS run actually executed on the chip
        result["last_tpu_verified"] = dict(
            LAST_TPU_VERIFIED, stale=result["platform"] != "tpu"
        )
    return result


def _next_out_path() -> str:
    if os.environ.get("BENCH_SERVE_OUT"):
        return os.environ["BENCH_SERVE_OUT"]
    out_dir = os.environ.get("BENCH_SERVE_DIR", REPO)
    rounds = [0]
    for p in glob.glob(os.path.join(out_dir, "BENCH_SERVE_r*.json")):
        m = re.search(r"BENCH_SERVE_r(\d+)\.json$", p)
        if m:
            rounds.append(int(m.group(1)))
    return os.path.join(out_dir, f"BENCH_SERVE_r{max(rounds) + 1:02d}.json")


def _manifest_path(out: str) -> str:
    """Run manifests live under the tmp run dir (BENCH_RUN_DIR — the
    same dir bench.py uses), never the repo root: the repo root once
    grew a stale checked-in manifest. The path is stamped into the
    artifact so the trajectory point still traces back to what ran;
    BENCH_MANIFEST_OUT overrides for archival."""
    if os.environ.get("BENCH_MANIFEST_OUT"):
        return os.environ["BENCH_MANIFEST_OUT"]
    run_dir = os.environ.get("BENCH_RUN_DIR") or os.path.join(
        tempfile.gettempdir(), "lightgbm_tpu_bench"
    )
    try:
        os.makedirs(run_dir, exist_ok=True)
    except OSError:
        run_dir = tempfile.gettempdir()
    m = re.search(r"BENCH_SERVE_r(\d+)\.json$", out)
    name = (f"run_manifest_serve_r{m.group(1)}.json" if m
            else "run_manifest_serve.json")
    return os.path.join(run_dir, name)


def main() -> int:
    result = run_bench()
    out = _next_out_path()
    # provenance link: a run manifest (config + device topology +
    # metrics snapshot) under the run dir, path stamped into the json
    # so the trajectory point traces back to what ran
    mpath = _manifest_path(out)
    try:
        from lightgbm_tpu.obs.manifest import write_manifest

        write_manifest(mpath, extra={
            "bench": "serve", "run_id": result["run_id"],
            "artifact": out,
        })
        result["run_manifest"] = mpath
    except Exception as e:  # noqa: BLE001 — provenance must not kill the bench
        sys.stderr.write(f"[bench_serve] run manifest not written: {e}\n")
    with open(out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    result["artifact"] = out
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
