"""Round-5 probe: decompose the slot-packed histogram pass's fixed cost.

Times, on a live chip (in-jit fori_loop methodology — block_until_ready
does not sync under axon, see BENCH_NOTES.md):

- the current int8 S=48 pass (baseline);
- one-hot-build-free variant (constant one-hot: isolates compare+cast);
- matmul-free variant (compares only: isolates the MXU cost);
- bins stored s8 / i16 instead of i32 (lighter VMEM tiles + packed
  VPU compares, if Mosaic packs them);
- a fused-partition prototype: the same pass ALSO computing per-row
  go_left/pleaf_new in-kernel from per-slot split params (does the
  round's 2.2 ms fbins select + partition update for free?).

Prints one JSON line per measurement.
"""

import json
import sys
import time
import functools

import numpy as np

sys.path.insert(0, "/root/repo")


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from lightgbm_tpu.learner.histogram import build_gh8_quant, CH

    print(json.dumps({"platform": jax.devices()[0].platform}), flush=True)

    rs = np.random.RandomState(0)
    F, B = 28, 256
    N = 61 * 16384
    blk = 2048
    bins_np = rs.randint(0, 255, (F, N)).astype(np.int32)
    bins = jnp.asarray(bins_np)
    bins8 = jnp.asarray((bins_np - 128).astype(np.int8))
    bins16 = jnp.asarray(bins_np.astype(np.int16))
    ones = jnp.ones(N, jnp.float32)
    gh8q = build_gh8_quant(
        jnp.asarray(rs.randint(-2, 3, N).astype(np.float32)),
        jnp.asarray(rs.randint(0, 5, N).astype(np.float32)),
        ones,
    )
    R = 20

    def timed(make_body):
        def loop():
            def body(_, acc):
                return make_body(acc)

            return lax.fori_loop(0, R, body, jnp.float32(0.0))

        f = jax.jit(loop)
        float(f())
        t0 = time.time()
        float(f())
        return (time.time() - t0) / R

    def base_body(acc):
        gh = gh8q + acc * 0.0
        return acc + gh[0, 0]

    t_base = timed(base_body)
    print(json.dumps({"metric": "baseline_chain_ms",
                      "value": round(t_base * 1e3, 3)}), flush=True)

    # ---------------- variant kernels ----------------
    def nat_kernel(bins_ref, gh_ref, slot_ref, out_ref, *, S, nat_ch,
                   mode, bdt):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        slot = slot_ref[0, :]
        gh = gh_ref[...]
        iota_s = lax.broadcasted_iota(jnp.int32, (S, blk), 0)
        sl32 = (slot[None, :] == iota_s).astype(jnp.int32)
        g32 = gh[:nat_ch, :].astype(jnp.int32)
        W = (sl32[:, None, :] * g32[None, :, :]).reshape(
            S * nat_ch, blk).astype(jnp.int8)
        if bdt == "i8":
            iota_bT = (lax.broadcasted_iota(jnp.int32, (B, blk), 0)
                       - 128).astype(jnp.int8)
        elif bdt == "i16":
            iota_bT = lax.broadcasted_iota(jnp.int32, (B, blk), 0).astype(
                jnp.int16)
        else:
            iota_bT = lax.broadcasted_iota(jnp.int32, (B, blk), 0)
        for f in range(F):
            if mode == "nooh":
                # constant one-hot: no compare, same matmul
                ohT = jnp.ones((B, blk), jnp.int8)
            else:
                ohT = (bins_ref[f:f + 1, :] == iota_bT).astype(jnp.int8)
            if mode == "nomm":
                out_ref[0:1, f * B:(f + 1) * B] += lax.dot_general(
                    W[0:1], ohT, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32)
            else:
                out_ref[:, f * B:(f + 1) * B] += lax.dot_general(
                    W, ohT, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32)

    def run_nat(tag, S, nat_ch, mode, bdt, bins_in):
        nb = N // blk
        Fb = bins_in.shape[0]
        kern = functools.partial(nat_kernel, S=S, nat_ch=nat_ch, mode=mode,
                                 bdt=bdt)
        call = pl.pallas_call(
            kern,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((Fb, blk), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((CH, blk), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((S * nat_ch, F * B), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((S * nat_ch, F * B), jnp.int32),
        )
        slot = jnp.asarray(rs.randint(0, S + 1, N).astype(np.int32))

        def body(acc):
            gh = gh8q + acc * 0.0
            out = call(bins_in, gh, slot.reshape(1, N))
            return acc + out[0, 0].astype(jnp.float32)

        try:
            t = timed(body) - t_base
            print(json.dumps({
                "metric": tag, "ms": round(t * 1e3, 3),
                "per_split_ms": round(t * 1e3 / S, 4),
            }), flush=True)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"metric": tag, "error": str(e)[-300:]}),
                  flush=True)

    for S in (1, 48):
        run_nat(f"int8_S{S}_i32bins", S, 3, "full", "i32", bins)
        run_nat(f"int8_S{S}_noonehot", S, 3, "nooh", "i32", bins)
        run_nat(f"int8_S{S}_nomatmul", S, 3, "nomm", "i32", bins)
        run_nat(f"int8_S{S}_s8bins", S, 3, "full", "i8", bins8)
        run_nat(f"int8_S{S}_i16bins", S, 3, "full", "i16", bins16)


if __name__ == "__main__":
    main()
