"""Observability layer (lightgbm_tpu/obs, docs/OBSERVABILITY.md):
metrics registry + Prometheus exposition, trace-event export, run
manifests, bench_serve artifact, and the no-callback re-audit."""

from __future__ import annotations

import importlib.util
import json
import re
import threading
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import boosting, log
from lightgbm_tpu.obs import tracing
from lightgbm_tpu.obs.metrics import MetricsRegistry, default_registry

REPO = Path(__file__).resolve().parents[1]


def _train(params, X, y, rounds=5):
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    p = {"verbosity": -1, **params}
    return lgb.train(p, ds, num_boost_round=rounds)


# ----------------------------------------------------------------- metrics
def test_registry_counter_gauge_histogram():
    r = MetricsRegistry(enabled=True)
    c = r.counter("c_total", "a counter", labels=("op",))
    c.inc(op="score")
    c.inc(2.5, op="score")
    c.inc(op="load")
    assert c.value(op="score") == 3.5
    assert c.value(op="load") == 1.0
    with pytest.raises(ValueError):
        c.inc(-1, op="score")  # counters are monotone
    with pytest.raises(ValueError):
        c.inc(1, bad_label="x")  # undeclared label

    g = r.gauge("g")
    g.set(4.0)
    g.inc()
    g.dec(2.0)
    assert g.value() == 3.0

    h = r.histogram("h_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    s = h.state()
    assert s["count"] == 3 and s["counts"] == [1, 2]
    assert abs(s["sum"] - 5.55) < 1e-9

    # re-registration returns the same object; mismatch raises
    assert r.counter("c_total", labels=("op",)) is c
    with pytest.raises(ValueError):
        r.gauge("c_total")
    with pytest.raises(ValueError):
        r.counter("c_total", labels=("other",))


def test_registry_disabled_is_noop_and_reset():
    r = MetricsRegistry(enabled=False)
    c = r.counter("c_total")
    c.inc()
    assert c.value() == 0.0
    r.enable()
    c.inc()
    assert c.value() == 1.0
    r.reset()
    assert c.value() == 0.0


_PROM_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$'
)


def _parse_prom(text):
    """Parse text exposition into {(name, frozenset(labels)): value},
    asserting every non-comment line matches the format."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        assert m, f"invalid exposition line: {line!r}"
        labels = frozenset(
            re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                       m.group(2) or "")
        )
        out[(m.group(1), labels)] = float(m.group(3))
    return out


def test_metrics_endpoint_matches_registry_stats(rng):
    """/metrics exposition parses, and the scraped serving-latency
    values agree with ModelRegistry.stats() — one LatencyStats ring
    behind both readers (the dedupe contract)."""
    import urllib.request

    from lightgbm_tpu.serving import ModelRegistry, serve_http

    X = rng.randn(500, 4)
    bst = _train({"objective": "regression", "num_leaves": 15},
                 X, X[:, 0] + X[:, 1])
    reg = ModelRegistry()
    reg.load("obs", bst)
    httpd = serve_http(reg, port=0, block=False)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        body = json.dumps({"rows": X[:32].tolist(), "model": "obs"}).encode()
        req = urllib.request.Request(
            base + "/v1/score", data=body,
            headers={"Content-Type": "application/json"},
        )
        for _ in range(3):
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read())["ok"]

        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["ok"] and "obs" in health["models"]

        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            scraped = _parse_prom(r.read().decode())
        stats = reg.stats()["obs"]

        entry = frozenset({("entry", "serve:obs")})
        assert scraped[("lgbmtpu_serve_requests_total", entry)] == \
            stats["count"]
        assert scraped[("lgbmtpu_serve_rows_total", entry)] == stats["rows"]
        for stat in ("p50", "p95", "p99", "mean"):
            key = ("lgbmtpu_serve_latency_ms",
                   frozenset({("entry", "serve:obs"), ("stat", stat)}))
            assert scraped[key] == pytest.approx(stats[f"{stat}_ms"])
        # the serve-loop op counter rode the same scrape
        score_ops = [
            v for (name, labels), v in scraped.items()
            if name == "lgbmtpu_serve_protocol_requests_total"
            and ("op", "score") in labels
        ]
        assert score_ops and score_ops[0] >= 3
        # bucket-ladder dispatch accounting is present for this entry
        assert any(
            name == "lgbmtpu_serve_bucket_dispatch_total"
            and ("entry", "serve:obs") in labels
            for (name, labels) in scraped
        )
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_latency_stats_reset_and_shared_ring():
    from lightgbm_tpu.timer import latency_stats

    s = latency_stats("obs-reset-test")
    s.observe(0.010, rows=8)
    assert s.snapshot()["count"] == 1
    s.reset()
    snap = s.snapshot()
    assert snap["count"] == 0 and snap["rows"] == 0 and snap["p99_ms"] == 0
    # same name -> same object (the one-source-of-truth registry)
    assert latency_stats("obs-reset-test") is s


# ----------------------------------------------------------------- tracing
def test_trace_export_fused_round_spans(rng, tmp_path):
    """Chrome trace-event JSON loads and carries one fused-round span
    per DISPATCH: a 4-round training is one chunk-scan launch (one
    span covering all 4 rounds); with tpu_chunk_scan=off it
    degenerates to the historical one-span-per-round stream."""
    X = rng.randn(400, 4)
    y = (X[:, 0] > 0).astype(np.float32)
    path = tmp_path / "trace.json"
    jsonl = tmp_path / "trace.jsonl"
    with tracing.tracing(chrome_path=str(path),
                         jsonl_path=str(jsonl)) as rec:
        _train({"objective": "binary", "num_leaves": 7}, X, y, rounds=4)
    data = json.loads(path.read_text())
    assert "traceEvents" in data
    spans = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    for e in spans:
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert "pid" in e and "tid" in e and e["name"]
    fused = [e for e in spans if e["name"] == boosting.FUSED_ROUND_PHASE]
    assert len(fused) == 1  # 4 rounds = one chunk dispatch
    # the JSONL log carries the same events one-per-line
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert sum(1 for e in lines
               if e.get("name") == boosting.FUSED_ROUND_PHASE) == 1
    assert rec.events()  # recorder still readable after export
    # per-round dispatch keeps the one-span-per-round stream
    path2 = tmp_path / "trace_off.json"
    with tracing.tracing(chrome_path=str(path2)):
        _train({"objective": "binary", "num_leaves": 7,
                "tpu_chunk_scan": "off"}, X, y, rounds=4)
    data2 = json.loads(path2.read_text())
    fused2 = [e for e in data2["traceEvents"]
              if e.get("ph") == "X"
              and e["name"] == boosting.FUSED_ROUND_PHASE]
    assert len(fused2) == 4


def test_trace_eager_path_has_every_round_phase(rng):
    """The eager (non-fused) training loop emits a span for EVERY
    per-round phase: gradients, grow, score update."""
    X = rng.randn(400, 4)
    y = (X[:, 0] > 0).astype(np.float32)

    def cb(env):
        return None

    cb.before_iteration = True  # pre-iteration callbacks force non-fused
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    with tracing.tracing() as rec:
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, ds, num_boost_round=3, callbacks=[cb])
    names = {e["name"] for e in rec.events() if e.get("ph") == "X"}
    for phase in boosting.ROUND_PHASES:
        assert phase in names, f"missing per-round phase span {phase!r}"


_VALID_PH = {"X", "B", "E", "i", "I", "C", "M", "b", "e", "n", "s", "t", "f"}


def _validate_chrome_trace(data):
    """Schema-validate a Chrome trace-event export: required fields per
    phase type, numeric timestamps, and B/E begin/end events paired per
    (pid, tid, name)."""
    assert "traceEvents" in data
    open_stacks = {}
    for e in data["traceEvents"]:
        ph = e.get("ph")
        assert ph in _VALID_PH, f"unknown phase type {ph!r}: {e}"
        assert e.get("name"), f"event missing name: {e}"
        assert "pid" in e, f"event missing pid: {e}"
        if ph != "M":  # metadata events carry no timestamp
            assert isinstance(e.get("ts"), (int, float)), e
            assert "tid" in e or ph == "C", f"event missing tid: {e}"
        if ph == "X":
            assert isinstance(e.get("dur"), (int, float)) and e["dur"] >= 0
        if ph == "B":
            open_stacks.setdefault((e["pid"], e["tid"]), []).append(e["name"])
        if ph == "E":
            stack = open_stacks.get((e["pid"], e["tid"]))
            assert stack, f"E event without matching B: {e}"
            stack.pop()
    dangling = {k: v for k, v in open_stacks.items() if v}
    assert not dangling, f"unclosed B events: {dangling}"


def test_trace_export_schema_valid(rng, tmp_path):
    """The full Chrome export passes trace-event schema validation
    (required ph/ts/pid/tid/name fields, paired B/E or complete X),
    including instant + counter + metadata events."""
    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float32)
    path = tmp_path / "trace.json"
    with tracing.tracing(chrome_path=str(path)) as rec:
        _train({"objective": "binary", "num_leaves": 7}, X, y, rounds=2)
        rec.add_instant("checkpoint", {"k": 1})
        rec.add_counter("queue", {"depth": 3.0})
    _validate_chrome_trace(json.loads(path.read_text()))


def test_trace_validation_catches_unpaired_begin():
    """The validator itself is red-to-green: a B without its E fails."""
    bad = {"traceEvents": [
        {"name": "x", "ph": "B", "ts": 0.0, "pid": 1, "tid": 1},
    ]}
    with pytest.raises(AssertionError, match="unclosed B"):
        _validate_chrome_trace(bad)


# -------------------------------------------------------------- aggregate
def test_two_registry_snapshot_merge():
    """ACCEPTANCE: two independent registries (the two-process stand-in
    on the collective-less CPU backend) merge host-side — counters sum,
    gauges sum with min/max spread, no jax collective anywhere."""
    from lightgbm_tpu.obs import aggregate

    r1 = MetricsRegistry(enabled=True)
    r2 = MetricsRegistry(enabled=True)
    for i, r in enumerate((r1, r2)):
        r.counter("fleet_rounds_total", "rounds", labels=("entry",)).inc(
            10 * (i + 1), entry="train")
        r.gauge("fleet_trees_per_sec", "tps").set(5.0 * (i + 1))
    snaps = [
        aggregate.snapshot_dict(r, process=i)
        for i, r in enumerate((r1, r2))
    ]
    merged = aggregate.merge(snaps)
    assert merged["processes"] == 2
    ctr = merged["metrics"]["fleet_rounds_total"]
    assert ctr["values"]['{entry="train"}'] == 30.0
    assert "min" not in ctr  # counters are additive, no spread
    g = merged["metrics"]["fleet_trees_per_sec"]
    assert g["values"][""] == 15.0  # fleet throughput = sum
    assert g["min"][""] == 5.0 and g["max"][""] == 10.0


def test_snapshot_file_roundtrip_and_merge(tmp_path):
    from lightgbm_tpu.obs import aggregate

    r1 = MetricsRegistry(enabled=True)
    r1.counter("c_total").inc(3)
    p1 = tmp_path / "metrics_rank00000.json"
    aggregate.write_snapshot(str(p1), r1, process=0)
    snap = aggregate.read_snapshot(str(p1))
    assert snap["metrics"]["c_total"]["kind"] == "counter"
    merged = aggregate.merge_files([str(p1)])
    assert merged["metrics"]["c_total"]["values"][""] == 3.0
    # a non-snapshot json is rejected loudly
    bad = tmp_path / "other.json"
    bad.write_text("{}")
    with pytest.raises(ValueError, match="not a metrics snapshot"):
        aggregate.read_snapshot(str(bad))


@pytest.mark.slow
def test_prometheus_parse_and_http_pull_merge(rng):
    """Fleet aggregation's HTTP leg: scrape two /metrics bodies (one
    live worker endpoint + one rendered registry) and merge them —
    exactly what a multi-replica serving fleet view does."""
    from lightgbm_tpu.obs import aggregate
    from lightgbm_tpu.serving import ModelRegistry, serve_http

    X = rng.randn(400, 4)
    bst = _train({"objective": "regression", "num_leaves": 7},
                 X, X[:, 0])
    reg = ModelRegistry()
    reg.load("agg", bst)
    reg.predict("agg", X[:16].astype(np.float32))
    httpd = serve_http(reg, port=0, block=False)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        pulled = aggregate.pull_snapshot(url, process=0)
        assert any(
            name.startswith("lgbmtpu_") for name in pulled["metrics"]
        )
        # parse a rendered exposition as the "second worker"
        local = aggregate.parse_prometheus(
            default_registry().render_prometheus(), process=1
        )
        merged = aggregate.merge([pulled, local])
        assert merged["processes"] == 2
        # the pulled sample and the local sample describe the same
        # registry here, so the merged counter is exactly double
        name = "lgbmtpu_serve_rows_total"
        key = '{entry="serve:agg"}'
        assert merged["metrics"][name]["values"][key] == \
            2 * pulled["metrics"][name]["values"][key]
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_multihost_fleet_snapshot_files(tmp_path):
    """parallel.multihost's fleet helpers: write this process's
    snapshot, merge the directory — file-based, no collectives."""
    from lightgbm_tpu.obs.metrics import default_registry
    from lightgbm_tpu.parallel.multihost import (
        merged_fleet_snapshot,
        write_metrics_snapshot,
    )

    default_registry().counter("fleet_probe_total").inc(2)
    path = write_metrics_snapshot(str(tmp_path))
    assert Path(path).name == "metrics_rank00000.json"
    merged = merged_fleet_snapshot(str(tmp_path))
    assert merged["metrics"]["fleet_probe_total"]["values"][""] >= 2.0
    with pytest.raises(FileNotFoundError):
        merged_fleet_snapshot(str(tmp_path / "empty"))


def test_obs_report_renders(tmp_path, capsys):
    """tools/obs_report.py renders snapshots + recorder streams."""
    import importlib.util as ilu

    from lightgbm_tpu.obs import aggregate

    r = MetricsRegistry(enabled=True)
    r.counter("c_total").inc(1)
    snap = tmp_path / "metrics_rank00000.json"
    aggregate.write_snapshot(str(snap), r, process=0)
    rec = tmp_path / "run.jsonl"
    rec.write_text(
        json.dumps({"schema": "lightgbm-tpu/flight-record/v1"}) + "\n"
        + json.dumps({"round": 0, "evals": {"v l2": 1.0},
                      "trees_per_sec": 2.0}) + "\n"
    )
    spec = ilu.spec_from_file_location(
        "obs_report", REPO / "tools" / "obs_report.py"
    )
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--snapshots", str(snap), "--recorder", str(rec)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "fleet metrics" in out and "c_total" in out
    assert "flight record" in out and "round 0" in out


# ---------------------------------------------------------------- manifest
def test_run_manifest_schema_and_static_wire_budget(rng, tmp_path):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.obs.manifest import SCHEMA, write_manifest

    X = rng.randn(300, 4)
    bst = _train({"objective": "regression", "num_leaves": 7}, X, X[:, 0])
    cfg = Config({"objective": "regression", "num_leaves": 7})
    out = tmp_path / "manifest.json"
    m = write_manifest(str(out), config=cfg, booster=bst,
                       extra={"note": "test"})
    on_disk = json.loads(out.read_text())
    assert on_disk["schema"] == SCHEMA
    assert m["config"]["resolved"]["objective"] == "regression"
    assert m["devices"]["device_count"] >= 1
    assert {"jaxpr_traces", "backend_compiles"} <= set(m["compile"])
    assert m["model"]["num_trees"] == bst.num_trees()
    # static wire pins ride along verbatim from cost_budget.json
    budget = json.loads(
        (REPO / "lightgbm_tpu" / "analysis" / "cost_budget.json").read_text()
    )
    static = m["collectives"]["static_budget_wire_bytes"]
    assert static == {k: v["wire_bytes"] for k, v in budget.items()}
    assert m["collectives"]["runtime_wire_bytes_estimate"] >= 0


def test_data_parallel_runtime_wire_counter(rng):
    """tree_learner=data training ticks the runtime collective
    wire-bytes counter (the manifest's runtime side)."""
    reg = default_registry()
    c = reg.counter("lgbmtpu_collective_wire_bytes_total",
                    labels=("entry",))
    before = c.value(entry="data_parallel_grow")
    X = rng.randn(600, 4)
    y = (X[:, 0] > 0).astype(np.float32)
    _train({"objective": "binary", "num_leaves": 7,
            "tree_learner": "data"}, X, y, rounds=3)
    after = c.value(entry="data_parallel_grow")
    assert after > before


# ------------------------------------------------------------ re-audit
def test_instrumentation_added_no_host_callbacks():
    """All audited jaxpr entries stay callback-free: the observability
    layer is host-side only (acceptance criterion)."""
    from lightgbm_tpu.analysis.jaxpr_audit import run_audits

    results = run_audits()
    checked = 0
    for r in results:
        for c in r.contracts:
            if c.name == "no_host_callbacks":
                checked += 1
                assert c.ok, f"{r.name}: {c.detail}"
    assert checked >= 4  # every hot entry still audited


# -------------------------------------------------------------- analysis
def test_obs_modules_in_analysis_scan():
    """The strict gate's AST passes (lint + concurrency) cover the new
    obs/ modules — same file set for both (iter_package_modules)."""
    from lightgbm_tpu.analysis.lint import iter_package_modules

    files, root = iter_package_modules()
    rel = {p.relative_to(root).as_posix() for p in files}
    for mod in ("obs/__init__.py", "obs/metrics.py", "obs/tracing.py",
                "obs/manifest.py", "obs/recorder.py", "obs/anomaly.py",
                "obs/aggregate.py"):
        assert mod in rel, f"{mod} escaped the analysis scan"


# ------------------------------------------------------------------- log
def test_log_debug_routes_to_debug_method():
    calls = []

    class L:
        def info(self, m):
            calls.append(("info", m))

        def warning(self, m):
            calls.append(("warning", m))

        def debug(self, m):
            calls.append(("debug", m))

    prev = (log._logger, log._info_method, log._warning_method,
            log._debug_method, log._VERBOSITY)
    try:
        log.register_logger(L())
        log.set_verbosity(2)
        log.debug("d")
        log.info("i")
        log.warning("w")
        assert [c[0] for c in calls] == ["debug", "info", "warning"]
    finally:
        (log._logger, log._info_method, log._warning_method,
         log._debug_method) = prev[:4]
        log.set_verbosity(prev[4])


def test_log_debug_falls_back_to_info_method():
    calls = []

    class L:
        def info(self, m):
            calls.append(("info", m))

        warning = info

    prev = (log._logger, log._info_method, log._warning_method,
            log._debug_method, log._VERBOSITY)
    try:
        log.register_logger(L())
        log.set_verbosity(2)
        log.debug("d")
        assert calls and calls[0][0] == "info"
        with pytest.raises(TypeError):
            log.register_logger(L(), debug_method_name="nope")
    finally:
        (log._logger, log._info_method, log._warning_method,
         log._debug_method) = prev[:4]
        log.set_verbosity(prev[4])


def test_log_fatal_only_verbosity_respected_for_registered_logger():
    calls = []

    class L:
        def info(self, m):
            calls.append(m)

        warning = info
        debug = info

    prev = (log._logger, log._info_method, log._warning_method,
            log._debug_method, log._VERBOSITY)
    try:
        log.register_logger(L())
        log.set_verbosity(-1)  # fatal-only
        log.debug("d")
        log.info("i")
        log.warning("w")
        assert calls == []
        with pytest.raises(log.LightGBMError):
            log.fatal("boom")
    finally:
        (log._logger, log._info_method, log._warning_method,
         log._debug_method) = prev[:4]
        log.set_verbosity(prev[4])


# ------------------------------------------------------------ bench_serve
def test_bench_serve_writes_artifact(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_SERVE_DIR", str(tmp_path))
    monkeypatch.setenv("BENCH_SERVE_TRAIN_ROWS", "400")
    monkeypatch.setenv("BENCH_SERVE_FEATURES", "4")
    monkeypatch.setenv("BENCH_SERVE_TREES", "5")
    monkeypatch.setenv("BENCH_SERVE_LEAVES", "7")
    monkeypatch.setenv("BENCH_SERVE_REQUESTS", "8")
    monkeypatch.setenv("BENCH_SERVE_BATCH", "16")
    monkeypatch.setenv("BENCH_SERVE_THREADS", "2")
    spec = importlib.util.spec_from_file_location(
        "bench_serve", REPO / "bench_serve.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0
    files = list(tmp_path.glob("BENCH_SERVE_r*.json"))
    assert len(files) == 1
    data = json.loads(files[0].read_text())
    for key in ("qps", "p50_ms", "p99_ms"):
        assert key in data and data[key] >= 0
    assert data["requests"] == 8
    assert data["stats"].get("count", 0) >= 1


@pytest.mark.slow
def test_bench_serve_provenance_and_carry_forward(tmp_path, monkeypatch):
    """Satellite: bench_serve stamps run_id + run-manifest path into
    its artifact and carries last_tpu_verified with bench.py's stale
    semantics (off-chip run -> stale: true, ignored by the gate)."""
    for k, v in (("BENCH_SERVE_DIR", str(tmp_path)),
                 ("BENCH_SERVE_TRAIN_ROWS", "400"),
                 ("BENCH_SERVE_FEATURES", "4"),
                 ("BENCH_SERVE_TREES", "3"), ("BENCH_SERVE_LEAVES", "7"),
                 ("BENCH_SERVE_REQUESTS", "4"),
                 ("BENCH_SERVE_BATCH", "8"),
                 ("BENCH_SERVE_THREADS", "1")):
        monkeypatch.setenv(k, v)
    spec = importlib.util.spec_from_file_location(
        "bench_serve_prov", REPO / "bench_serve.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.LAST_TPU_VERIFIED = {
        "qps": 5000.0, "p99_ms": 1.0, "platform": "tpu", "round": 9,
    }
    assert mod.main() == 0
    artifact = next(tmp_path.glob("BENCH_SERVE_r*.json"))
    data = json.loads(artifact.read_text())
    assert data["run_id"]
    mpath = Path(data["run_manifest"])
    assert mpath.name.startswith("run_manifest_serve_r")
    manifest = json.loads(mpath.read_text())
    assert manifest["extra"]["run_id"] == data["run_id"]
    assert manifest["extra"]["artifact"] == str(artifact)
    # this run ran off-chip -> the carried chip numbers are stale
    assert data["platform"] != "tpu"
    assert data["last_tpu_verified"]["stale"] is True
    # ...and therefore contribute NOTHING to the gate's trajectory
    from lightgbm_tpu.analysis.bench_gate import load_trajectory

    assert load_trajectory(tmp_path)["serve"] == []


def test_bench_train_manifest_stamp(tmp_path, monkeypatch):
    """bench.py's provenance hook: run manifest written, path + run id
    folded into the partial state the final JSON reports."""
    spec = importlib.util.spec_from_file_location(
        "bench_prov", REPO / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setenv("BENCH_MANIFEST_OUT",
                       str(tmp_path / "manifest.json"))
    mod._STATE["run_id"] = "test-run"
    mod.write_run_manifest({"objective": "binary", "num_leaves": 7})
    assert mod._STATE["run_manifest"] == str(tmp_path / "manifest.json")
    m = json.loads((tmp_path / "manifest.json").read_text())
    assert m["extra"]["run_id"] == "test-run"
    assert m["config"]["explicit"]["objective"] == "binary"
    out = mod._final_json()
    assert out["run_id"] == "test-run"
    assert out["run_manifest"] == str(tmp_path / "manifest.json")


# --------------------------------------------------------------- profile
def test_cli_profile_dir_and_manifest(tmp_path, rng):
    """profile_dir + run_manifest through the CLI: span trace +
    manifest land in the directory (jax.profiler capture is
    best-effort on CPU)."""
    from lightgbm_tpu.cli import main as cli_main

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(int)
    data = tmp_path / "train.csv"
    np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.6g")
    prof = tmp_path / "prof"
    model = tmp_path / "model.txt"
    manifest = tmp_path / "manifest.json"
    rc = cli_main([
        "task=train", f"data={data}", "objective=binary",
        "num_leaves=7", "num_trees=3", "verbosity=-1",
        f"output_model={model}", f"profile_dir={prof}",
        f"run_manifest={manifest}",
    ])
    assert rc == 0
    trace = json.loads((prof / "trace_events.json").read_text())
    names = {e.get("name") for e in trace["traceEvents"]}
    assert boosting.FUSED_ROUND_PHASE in names
    m = json.loads(manifest.read_text())
    assert m["extra"]["task"] == "train"
    assert (prof / "run_manifest.json").exists()
