"""Tree-learner correctness: oracle split search, gather/masked histogram
equivalence, and distributed-vs-serial lockstep."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.learner.grower import GrowerSpec, grow_tree
from lightgbm_tpu.learner.histogram import build_gh8, histogram
from lightgbm_tpu.learner.split import SplitParams, best_split


def _params(**kw):
    d = dict(
        lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=1.0,
        min_sum_hessian_in_leaf=0.0, min_gain_to_split=0.0,
        max_delta_step=0.0, path_smooth=0.0, cat_smooth=10.0,
        cat_l2=10.0, min_data_per_group=100.0,
        cegb_tradeoff=1.0, cegb_penalty_split=0.0,
        feature_fraction_bynode=1.0,
    )
    ints = dict(max_cat_threshold=32, max_cat_to_onehot=4)
    for k in list(kw):
        if k in ints:
            ints[k] = kw.pop(k)
    d.update(kw)
    return SplitParams(
        **{k: jnp.float32(v) for k, v in d.items()},
        **{k: jnp.int32(v) for k, v in ints.items()},
    )


def _mk_problem(n=1024, F=4, B=16, seed=0):
    rs = np.random.RandomState(seed)
    bins = rs.randint(0, B, size=(F, n)).astype(np.int32)
    grad = rs.randn(n).astype(np.float32)
    hess = (0.5 + rs.rand(n)).astype(np.float32)
    return bins, grad, hess


def _oracle_best_gain(bins, grad, hess, B, l2=0.0, min_data=1):
    """Exhaustive numpy search over (feature, threshold): the split-gain
    formula of the reference (feature_histogram.hpp GetSplitGains with
    no L1/constraints): GL^2/(HL+l2) + GR^2/(HR+l2) - G^2/(H+l2)."""
    F, n = bins.shape
    G, H = grad.sum(), hess.sum()
    parent = G * G / (H + l2)
    best = -np.inf
    for f in range(F):
        for t in range(B - 1):
            left = bins[f] <= t
            cl = left.sum()
            if cl < min_data or n - cl < min_data:
                continue
            GL, HL = grad[left].sum(), hess[left].sum()
            GR, HR = G - GL, H - HL
            gain = GL * GL / (HL + l2) + GR * GR / (HR + l2) - parent
            best = max(best, gain)
    return best


def test_best_split_matches_oracle():
    B = 16
    bins, grad, hess = _mk_problem(B=B)
    F, n = bins.shape
    gh8 = build_gh8(
        jnp.asarray(grad), jnp.asarray(hess), jnp.ones(n, jnp.float32)
    )
    bins_rm = jnp.asarray(bins)
    hist = histogram(bins_rm, gh8, B)  # (3, F, B)
    # each feature's histogram partitions all rows -> per-feature totals
    np.testing.assert_allclose(
        np.asarray(hist[0]).sum(axis=1), np.full(F, grad.sum()), rtol=1e-4
    )
    rec = best_split(
        hist,
        jnp.float32(grad.sum()),
        jnp.float32(hess.sum()),
        jnp.float32(n),
        jnp.full(F, B, jnp.int32),
        jnp.full(F, -1, jnp.int32),
        jnp.zeros(F, jnp.int32),
        jnp.zeros(F, bool),
        _params(),
    )
    oracle = _oracle_best_gain(bins, grad, hess, B)
    assert float(rec.gain) == pytest.approx(oracle, rel=1e-4)


def _grow(bins, grad, hess, spec):
    F, n = bins.shape
    bins_rm = jnp.asarray(bins)
    args = (
        bins_rm,
        jnp.full(F, -1, jnp.int32),
        jnp.full(F, spec.num_bins, jnp.int32),
        jnp.zeros(F, jnp.int32),
        jnp.zeros(F, bool),
        jnp.asarray(grad),
        jnp.asarray(hess),
        jnp.ones(n, jnp.float32),
        jnp.ones(F, bool),
        _params(min_data_in_leaf=5.0),
        spec,
    )
    return grow_tree(*args)


def test_gather_hist_equals_masked_hist():
    bins, grad, hess = _mk_problem(n=2048, F=5, B=32, seed=3)
    spec_g = GrowerSpec(num_leaves=15, num_bins=32, max_depth=-1, gather_hist=True)
    spec_m = spec_g._replace(gather_hist=False)
    tg, rlg = _grow(bins, grad, hess, spec_g)
    tm, rlm = _grow(bins, grad, hess, spec_m)
    assert int(tg.num_nodes) == int(tm.num_nodes)
    np.testing.assert_array_equal(np.asarray(rlg), np.asarray(rlm))
    np.testing.assert_array_equal(np.asarray(tg.node_feature), np.asarray(tm.node_feature))
    np.testing.assert_array_equal(np.asarray(tg.node_bin), np.asarray(tm.node_bin))
    np.testing.assert_allclose(
        np.asarray(tg.leaf_value), np.asarray(tm.leaf_value), rtol=1e-4, atol=1e-6
    )


def test_data_parallel_matches_serial():
    from lightgbm_tpu.parallel import DataParallelGrower, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    bins, grad, hess = _mk_problem(n=4096, F=6, B=32, seed=5)
    F, n = bins.shape
    bins_rm = jnp.asarray(bins)
    spec = GrowerSpec(num_leaves=15, num_bins=32, max_depth=-1)
    params = _params(min_data_in_leaf=5.0)
    common = (
        jnp.full(F, -1, jnp.int32), jnp.full(F, 32, jnp.int32),
        jnp.zeros(F, jnp.int32), jnp.zeros(F, bool),
        jnp.asarray(grad), jnp.asarray(hess), jnp.ones(n, jnp.float32),
        jnp.ones(F, bool), params,
    )
    t_serial, rl_serial = grow_tree(
        bins_rm, *common[:-1], common[-1], spec, valid=jnp.ones(n, jnp.float32)
    )

    mesh = make_mesh(jax.devices()[:8])
    dp = DataParallelGrower(mesh, spec)
    t_dp, rl_dp = dp(
        bins_rm, *common, jnp.ones(n, jnp.float32)
    )
    assert int(t_dp.num_nodes) == int(t_serial.num_nodes)
    np.testing.assert_array_equal(
        np.asarray(t_dp.node_feature), np.asarray(t_serial.node_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(t_dp.node_bin), np.asarray(t_serial.node_bin)
    )
    np.testing.assert_allclose(
        np.asarray(t_dp.leaf_value), np.asarray(t_serial.leaf_value),
        rtol=1e-3, atol=1e-5,
    )
    np.testing.assert_array_equal(np.asarray(rl_dp), np.asarray(rl_serial))


def test_permuted_partition_matches_flat():
    """The permuted-segment grower (production) and the flat row->leaf
    grower (reference formulation) must produce identical trees and row
    assignments."""
    bins, grad, hess = _mk_problem(n=2048, F=5, B=32, seed=11)
    spec_p = GrowerSpec(num_leaves=15, num_bins=32, max_depth=-1, partition="permuted")
    spec_f = spec_p._replace(partition="flat")
    tp, rlp = _grow(bins, grad, hess, spec_p)
    tf, rlf = _grow(bins, grad, hess, spec_f)
    assert int(tp.num_nodes) == int(tf.num_nodes)
    np.testing.assert_array_equal(np.asarray(tp.node_feature), np.asarray(tf.node_feature))
    np.testing.assert_array_equal(np.asarray(tp.node_bin), np.asarray(tf.node_bin))
    np.testing.assert_array_equal(np.asarray(rlp), np.asarray(rlf))
    np.testing.assert_allclose(
        np.asarray(tp.leaf_value), np.asarray(tf.leaf_value), rtol=1e-4, atol=1e-6
    )


def test_permuted_partition_with_bagging_and_padding():
    """Out-of-bag rows follow the partition; padding rows stay leaf -1."""
    bins, grad, hess = _mk_problem(n=1024, F=4, B=16, seed=13)
    n = 1024
    rs = np.random.RandomState(1)
    bag = (rs.rand(n) < 0.7).astype(np.float32)
    vld = np.ones(n, np.float32)
    vld[-100:] = 0.0  # fake padding tail
    bag = bag * vld
    spec_p = GrowerSpec(num_leaves=7, num_bins=16, max_depth=-1, partition="permuted")
    spec_f = spec_p._replace(partition="flat")
    F = 4
    args = lambda spec: grow_tree(
        jnp.asarray(bins),
        jnp.full(F, -1, jnp.int32), jnp.full(F, 16, jnp.int32),
        jnp.zeros(F, jnp.int32), jnp.zeros(F, bool),
        jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(bag),
        jnp.ones(F, bool), _params(min_data_in_leaf=5.0), spec,
        jnp.asarray(vld),
    )
    tp, rlp = args(spec_p)
    tf, rlf = args(spec_f)
    assert int(tp.num_nodes) == int(tf.num_nodes)
    np.testing.assert_array_equal(np.asarray(rlp), np.asarray(rlf))
    assert np.all(np.asarray(rlp)[-100:] == -1)
