#!/usr/bin/env bash
# End-to-end serving smoke test (docs/SERVING.md): train a tiny model
# through the CLI, start the task=serve JSONL loop, score a batch
# through it, and assert parity against Booster.predict on the same
# model file; then bring up the HTTP transport and assert /healthz +
# /metrics Prometheus exposition (docs/OBSERVABILITY.md); then a fleet
# smoke — ~100 models hot-loaded under serve_fleet=true with a small
# residency capacity, scored so the LRU pager churns, one hot-swap,
# one device-TreeSHAP contrib request, and a /metrics scrape asserting
# per-model series; finally an online-loop smoke — task=loop serving
# v0 over HTTP while /v1/ingest streams microbatches, one gated
# promotion to v1, and a /metrics scrape asserting the promotion +
# ingest counters (docs/RESILIENCE.md "Online loop"). Runs on the CPU
# backend so it is safe anywhere.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

python - "$WORK" <<'EOF'
import sys
import numpy as np

work = sys.argv[1]
rs = np.random.RandomState(0)
X = rs.randn(800, 5)
y = (X[:, 0] + X[:, 1] > 0).astype(int)
np.savetxt(f"{work}/train.csv",
           np.column_stack([y, X]), delimiter=",", fmt="%.6g")
np.savetxt(f"{work}/score.csv", X[:64, :], delimiter=",", fmt="%.6g")
EOF

python -m lightgbm_tpu task=train "data=$WORK/train.csv" \
    objective=binary num_leaves=15 num_trees=10 verbosity=-1 \
    "output_model=$WORK/model.txt"

python - "$WORK" <<'EOF'
import io
import json
import subprocess
import sys

import numpy as np

work = sys.argv[1]
rows = np.loadtxt(f"{work}/score.csv", delimiter=",").tolist()
reqs = "\n".join(json.dumps(r) for r in [
    {"op": "ping"},
    {"op": "score", "model": "default", "rows": rows},
    {"op": "stats"},
    {"op": "quit"},
])
proc = subprocess.run(
    [sys.executable, "-m", "lightgbm_tpu", "task=serve",
     f"input_model={work}/model.txt", "serve_buckets=16,64",
     "verbosity=-1"],
    input=reqs, capture_output=True, text=True, timeout=300,
)
assert proc.returncode == 0, proc.stderr[-2000:]
resp = [json.loads(l) for l in proc.stdout.splitlines()
        if l.startswith("{")]
assert resp[0]["pong"], resp[0]
served = np.asarray(resp[1]["pred"])
assert resp[2]["stats"]["default"]["count"] >= 1

# parity vs the Python API on the same model file
import lightgbm_tpu as lgb

bst = lgb.Booster(model_file=f"{work}/model.txt")
host = bst.predict(np.asarray(rows))
err = float(np.abs(served - host).max())
assert err < 1e-5, f"serve/host mismatch: {err}"
print(f"serve_smoke: OK ({len(rows)} rows scored, max |diff| {err:.2e})")
EOF

# HTTP transport: /healthz liveness + /readyz readiness + /metrics
# Prometheus exposition (docs/OBSERVABILITY.md) — scrape after scoring
# and assert the exposition carries the serving counters. Liveness and
# readiness are split endpoints (docs/RESILIENCE.md "Serving
# gateway"): the gateway routes traffic on /readyz only.
python - "$WORK" <<'EOF2'
import json
import socket
import subprocess
import sys
import time
import urllib.request

work = sys.argv[1]
s = socket.socket()
s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]
s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "lightgbm_tpu", "task=serve",
     f"input_model={work}/model.txt", f"serve_port={port}",
     "serve_buckets=16,64", "verbosity=-1"],
    stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
)
base = f"http://127.0.0.1:{port}"
try:
    for _ in range(240):
        if proc.poll() is not None:
            raise SystemExit(f"serve exited early: {proc.stderr.read()[-2000:]}")
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=2) as r:
                assert json.loads(r.read())["ok"]
            break
        except OSError:
            time.sleep(0.5)
    else:
        raise SystemExit("serve_http never became healthy")
    # readiness: model loaded + queue under cap + heartbeat fresh
    with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
        ready = json.loads(r.read())
    assert r.status == 200 and ready["ok"], ready
    assert ready["models"] >= 1, ready
    req = urllib.request.Request(
        base + "/v1/score",
        data=json.dumps({"rows": [[0.0] * 5, [1.0] * 5]}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        assert json.loads(r.read())["ok"]
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        ctype = r.headers["Content-Type"]
        text = r.read().decode()
    assert ctype.startswith("text/plain"), ctype
    assert "lgbmtpu_serve_requests_total" in text, text[:500]
    assert "lgbmtpu_serve_protocol_requests_total" in text, text[:500]
    assert "# TYPE" in text
    print("serve_smoke http: OK (/healthz + /metrics exposition)")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
EOF2

# Fleet smoke (docs/SERVING.md "Fleet serving"): ~100 tenants behind
# one HTTP fleet with residency capacity << fleet size. Asserts: every
# model scores correctly cold or resident, resident stays under the
# cap while evictions climb, hot-swap + contrib work under the fleet,
# and /metrics carries per-model series + the pager gauges.
python - "$WORK" <<'EOF3'
import json
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np

work = sys.argv[1]
FLEET = 100
CAPACITY = 12
s = socket.socket()
s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]
s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "lightgbm_tpu", "task=serve",
     f"input_model={work}/model.txt", f"serve_port={port}",
     "serve_fleet=true", f"serve_fleet_capacity={CAPACITY}",
     "serve_buckets=16,64", "verbosity=-1"],
    stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
)
base = f"http://127.0.0.1:{port}"


def post(path, body, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


try:
    for _ in range(240):
        if proc.poll() is not None:
            raise SystemExit(
                f"fleet serve exited early: {proc.stderr.read()[-2000:]}")
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=2) as r:
                assert json.loads(r.read())["ok"]
            break
        except OSError:
            time.sleep(0.5)
    else:
        raise SystemExit("fleet serve_http never became healthy")

    model_str = open(f"{work}/model.txt").read()
    import lightgbm_tpu as lgb

    bst = lgb.Booster(model_str=model_str)
    rows = np.loadtxt(f"{work}/score.csv", delimiter=",")[:16]
    host = bst.predict(rows)

    for i in range(FLEET):
        out = post("/v1/load", {"model": f"tenant{i:03d}",
                                "model_str": model_str,
                                "deadline_ms": 10000})
        assert out["ok"] and out["version"] == 1, out
    # score every tenant: only CAPACITY can be resident, so this sweep
    # forces ~FLEET-CAPACITY LRU page-outs and every cold hit re-pages
    for i in range(FLEET):
        out = post(f"/v1/score", {"model": f"tenant{i:03d}",
                                  "rows": rows.tolist()})
        err = float(np.abs(np.asarray(out["pred"]) - host).max())
        assert err < 1e-5, f"tenant{i:03d} mismatch: {err}"

    with urllib.request.urlopen(base + "/v1/fleet", timeout=30) as r:
        fl = json.loads(r.read())["fleet"]
    assert fl["models"] >= FLEET, fl  # +1: the CLI's input_model tenant
    assert fl["capacity"] == CAPACITY, fl
    assert fl["resident"] <= CAPACITY < FLEET, fl
    assert fl["evictions"] >= FLEET - CAPACITY, fl
    assert fl["pages_in"] >= FLEET, fl

    # hot-swap one tenant to a fresh version and roll it back
    out = post("/v1/load", {"model": "tenant000", "model_str": model_str})
    assert out["version"] == 2, out
    out = post("/v1/score", {"model": "tenant000", "rows": rows.tolist()})
    assert out["ok"], out
    out = post("/v1/rollback", {"model": "tenant000"})
    assert out["active"] == 1, out

    # device TreeSHAP through the fleet: contributions sum to the
    # booster's raw score per row
    out = post("/v1/contrib", {"model": "tenant001",
                               "rows": rows.tolist()})
    contrib = np.asarray(out["pred"])
    assert contrib.shape == (len(rows), rows.shape[1] + 1), contrib.shape
    raw = bst.predict(rows, raw_score=True)
    serr = float(np.abs(contrib.sum(axis=1) - raw).max())
    assert serr < 1e-3, f"contrib row-sum mismatch: {serr}"

    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert 'model="tenant000"' in text, text[:500]
    assert "lgbmtpu_fleet_page_events_total" in text
    assert "lgbmtpu_fleet_resident_models" in text
    print(f"serve_smoke fleet: OK ({FLEET} tenants, capacity {CAPACITY}, "
          f"resident {fl['resident']}, pages_in {fl['pages_in']}, "
          f"evictions {fl['evictions']})")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
EOF3

# Online-loop smoke (docs/RESILIENCE.md "Online loop"): task=loop
# serves v0 while /v1/ingest spools labeled microbatches; the loop
# refits, gates on the holdout shard, and promotes v1; /healthz shows
# the loop's durable progress and /metrics carries the promotion,
# ingest, and loop-progress series tools/chaos.sh and dashboards key
# on.
python - "$WORK" <<'EOF4'
import json
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np

work = sys.argv[1]
rs = np.random.RandomState(17)
HX = rs.randn(200, 5)
Hy = (HX[:, 0] + HX[:, 1] > 0).astype(float)
np.savetxt(f"{work}/holdout.csv", np.column_stack([Hy, HX]),
           delimiter=",", fmt="%.6g")
s = socket.socket()
s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]
s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "lightgbm_tpu", "task=loop",
     f"input_model={work}/model.txt", f"valid_data={work}/holdout.csv",
     f"serve_port={port}", "objective=binary", "metric=auc",
     "num_leaves=15", f"loop_dir={work}/loop", "loop_min_rows=64",
     "loop_rounds=4", "loop_gate_margin=0.02", "loop_poll_s=0.1",
     "serve_buckets=16,64", "verbosity=-1"],
    stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
)
base = f"http://127.0.0.1:{port}"


def post(path, body, timeout=60):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


try:
    for _ in range(240):
        if proc.poll() is not None:
            raise SystemExit(
                f"loop serve exited early: {proc.stderr.read()[-2000:]}")
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=2) as r:
                hz = json.loads(r.read())
            assert hz["ok"]
            break
        except OSError:
            time.sleep(0.5)
    else:
        raise SystemExit("loop serve_http never became healthy")
    # /healthz carries the loop's durable state from the first reply
    assert hz["health"]["loop"]["version"] == 0, hz

    # stream two labeled microbatches through the ingest op
    for seed in (61, 62):
        rb = np.random.RandomState(seed)
        X = rb.randn(40, 5)
        y = (X[:, 0] + X[:, 1] > 0).astype(float)
        out = post("/v1/ingest", {"rows": X.tolist(),
                                  "labels": y.tolist()})
        assert out["ok"] and out["rows"] == 40, out

    # await the gated promotion (durable state drives /healthz)
    for _ in range(600):
        if proc.poll() is not None:
            raise SystemExit(
                f"loop serve died: {proc.stderr.read()[-2000:]}")
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            hz = json.loads(r.read())
        if hz["health"]["loop"]["version"] >= 1:
            break
        time.sleep(0.5)
    else:
        raise SystemExit("online loop never promoted v1")
    assert hz["health"]["loop"]["counts"]["promoted"] >= 1, hz

    # v1 serves
    out = post("/v1/score", {"rows": HX[:4].tolist()})
    assert out["ok"], out

    # the promotion/ingest/progress counters are on /metrics
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert ('lgbmtpu_promotion_events_total{outcome="promoted"}'
            in text), text[:800]
    assert "lgbmtpu_ingest_batches_total" in text
    assert "lgbmtpu_ingest_rows_total" in text
    assert "lgbmtpu_online_version" in text
    print(f"serve_smoke online loop: OK (promoted v1 after 2 ingest "
          f"batches, cycle {hz['health']['loop']['cycle']})")
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
EOF4
