"""Parameter system: names, aliases, defaults, and validation.

The reference keeps ~180 parameters as annotated fields of a single Config
struct (include/LightGBM/config.h:40-1324) and generates the alias table and
k=v parser from the annotations (src/io/config_auto.cpp, src/io/config.cpp).
Here the same information is data-driven: `_PARAMS` is the schema, `Config`
resolves aliases (ParameterAlias::KeyAliasTransform equivalent), coerces
types, applies constraint checks, and keeps unknown keys as pass-through
(the reference warns on unknown parameters).

Parameter names and aliases are replicated verbatim so that reference-style
param dicts (`lgb.train(params, ...)`) work unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from . import log

# name -> (default, type, aliases, check)
# type is one of: bool, int, float, str, "list_int", "list_float", "list_str"
# check is a predicate on the coerced value (None = no check).
_P = Tuple[Any, Any, Tuple[str, ...], Optional[Callable[[Any], bool]]]

_pos = lambda v: v > 0
_nonneg = lambda v: v >= 0
_frac = lambda v: 0.0 < v <= 1.0

# Bucket ladder of padded serving batch shapes (serving/dispatch.py,
# docs/SERVING.md). Powers of 4: at most ~2 rungs per decade of batch
# size, worst-case padding waste 4x on the smallest rung, amortized
# ~1.6x. Canonical HERE (config is a leaf module) so the config table
# and serving.dispatch.DEFAULT_BUCKETS cannot drift.
DEFAULT_SERVE_BUCKETS = (16, 64, 256, 1024, 4096)

# Chunk ladder for the fused boosting loop's lax.scan dispatches
# (boosting.fused_dispatch): a dispatch of n rounds is greedily
# decomposed over these rung lengths, largest-first, so any
# num_boost_round / early-stop chunk size compiles at most len(ladder)
# scan executables — same pow2-ladder idiom as the serve buckets
# above. A tail shorter than the smallest rung still dispatches the
# smallest rung; rounds past the `it_end` limit are masked on device
# and sliced off at materialize, so truncation stays exact without a
# bespoke (retracing) chunk length. Canonical HERE (config is a leaf
# module) so boosting and the analysis suite cannot drift.
DEFAULT_CHUNK_LADDER = (4, 16, 64)

_PARAMS: Dict[str, _P] = {
    # ---- Core parameters (config.h "Core Parameters") ----
    "config": ("", str, ("config_file",), None),
    "task": ("train", str, ("task_type",), None),
    "objective": ("regression", str, ("objective_type", "app", "application", "loss"), None),
    "boosting": ("gbdt", str, ("boosting_type", "boost"), None),
    "data_sample_strategy": ("bagging", str, (), None),
    "data": ("", str, ("train", "train_data", "train_data_file", "data_filename"), None),
    "valid": ("", "list_str", ("test", "valid_data", "valid_data_file", "test_data", "test_data_file", "valid_filenames"), None),
    "num_iterations": (100, int, ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round", "num_rounds", "nrounds", "num_boost_round", "n_estimators", "max_iter"), _nonneg),
    "learning_rate": (0.1, float, ("shrinkage_rate", "eta"), _pos),
    "num_leaves": (31, int, ("num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"), lambda v: 1 < v <= 131072),
    "tree_learner": ("serial", str, ("tree", "tree_type", "tree_learner_type"), None),
    "num_threads": (0, int, ("num_thread", "nthread", "nthreads", "n_jobs"), None),
    "device_type": ("tpu", str, ("device",), None),
    "seed": (None, int, ("random_seed", "random_state"), None),
    "deterministic": (False, bool, (), None),
    # ---- Learning control ----
    "force_col_wise": (False, bool, (), None),
    "force_row_wise": (False, bool, (), None),
    "histogram_pool_size": (-1.0, float, ("hist_pool_size",), None),
    "max_depth": (-1, int, (), None),
    "min_data_in_leaf": (20, int, ("min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf"), _nonneg),
    "min_sum_hessian_in_leaf": (1e-3, float, ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian", "min_child_weight"), _nonneg),
    "bagging_fraction": (1.0, float, ("sub_row", "subsample", "bagging"), _frac),
    "pos_bagging_fraction": (1.0, float, ("pos_sub_row", "pos_subsample", "pos_bagging"), _frac),
    "neg_bagging_fraction": (1.0, float, ("neg_sub_row", "neg_subsample", "neg_bagging"), _frac),
    "bagging_freq": (0, int, ("subsample_freq",), None),
    "bagging_seed": (3, int, ("bagging_fraction_seed",), None),
    "bagging_by_query": (False, bool, (), None),
    "feature_fraction": (1.0, float, ("sub_feature", "colsample_bytree"), _frac),
    "feature_fraction_bynode": (1.0, float, ("sub_feature_bynode", "colsample_bynode"), _frac),
    "feature_fraction_seed": (2, int, (), None),
    "extra_trees": (False, bool, ("extra_tree",), None),
    "extra_seed": (6, int, (), None),
    "early_stopping_round": (0, int, ("early_stopping_rounds", "early_stopping", "n_iter_no_change"), None),
    "early_stopping_min_delta": (0.0, float, (), _nonneg),
    "first_metric_only": (False, bool, (), None),
    "max_delta_step": (0.0, float, ("max_tree_output", "max_leaf_output"), None),
    "lambda_l1": (0.0, float, ("reg_alpha", "l1_regularization"), _nonneg),
    "lambda_l2": (0.0, float, ("reg_lambda", "lambda", "l2_regularization"), _nonneg),
    "linear_lambda": (0.0, float, (), _nonneg),
    "min_gain_to_split": (0.0, float, ("min_split_gain",), _nonneg),
    "drop_rate": (0.1, float, ("rate_drop",), lambda v: 0.0 <= v <= 1.0),
    "max_drop": (50, int, (), None),
    "skip_drop": (0.5, float, (), lambda v: 0.0 <= v <= 1.0),
    "xgboost_dart_mode": (False, bool, (), None),
    "uniform_drop": (False, bool, (), None),
    "drop_seed": (4, int, (), None),
    "top_rate": (0.2, float, (), lambda v: 0.0 <= v <= 1.0),
    "other_rate": (0.1, float, (), lambda v: 0.0 <= v <= 1.0),
    "min_data_per_group": (100, int, (), _pos),
    "max_cat_threshold": (32, int, (), _pos),
    "cat_l2": (10.0, float, (), _nonneg),
    "cat_smooth": (10.0, float, (), _nonneg),
    "max_cat_to_onehot": (4, int, (), _pos),
    "top_k": (20, int, ("topk",), _pos),
    "monotone_constraints": ((), "list_int", ("mc", "monotone_constraint", "monotonic_cst"), None),
    "monotone_constraints_method": ("basic", str, ("monotone_constraining_method", "mc_method"), None),
    "monotone_penalty": (0.0, float, ("monotone_splits_penalty", "ms_penalty", "mc_penalty"), _nonneg),
    "feature_contri": ((), "list_float", ("feature_contrib", "fc", "fp", "feature_penalty"), None),
    "forcedsplits_filename": ("", str, ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits"), None),
    "refit_decay_rate": (0.9, float, (), lambda v: 0.0 <= v <= 1.0),
    "cegb_tradeoff": (1.0, float, (), _nonneg),
    "cegb_penalty_split": (0.0, float, (), _nonneg),
    "cegb_penalty_feature_lazy": ((), "list_float", (), None),
    "cegb_penalty_feature_coupled": ((), "list_float", (), None),
    "path_smooth": (0.0, float, (), _nonneg),
    "interaction_constraints": ("", str, (), None),
    "verbosity": (1, int, ("verbose",), None),
    "use_quantized_grad": (False, bool, (), None),
    "num_grad_quant_bins": (4, int, (), None),
    "quant_train_renew_leaf": (False, bool, (), None),
    "stochastic_rounding": (True, bool, (), None),
    # ---- IO / dataset ----
    "linear_tree": (False, bool, ("linear_trees",), None),
    "max_bin": (255, int, ("max_bins",), lambda v: v > 1),
    "max_bin_by_feature": ((), "list_int", (), None),
    "min_data_in_bin": (3, int, (), _pos),
    "bin_construct_sample_cnt": (200000, int, ("subsample_for_bin",), _pos),
    "data_random_seed": (1, int, ("data_seed",), None),
    "is_enable_sparse": (True, bool, ("is_sparse", "enable_sparse", "sparse"), None),
    "enable_bundle": (True, bool, ("is_enable_bundle", "bundle"), None),
    "use_missing": (True, bool, (), None),
    "zero_as_missing": (False, bool, (), None),
    "feature_pre_filter": (True, bool, (), None),
    "pre_partition": (False, bool, ("is_pre_partition",), None),
    "two_round": (False, bool, ("two_round_loading", "use_two_round_loading"), None),
    "header": (False, bool, ("has_header",), None),
    "label_column": ("", str, ("label",), None),
    "weight_column": ("", str, ("weight",), None),
    "group_column": ("", str, ("group", "group_id", "query_column", "query", "query_id"), None),
    "ignore_column": ("", str, ("ignore_feature", "blacklist"), None),
    "categorical_feature": ("", str, ("cat_feature", "categorical_column", "cat_column", "categorical_features"), None),
    "forcedbins_filename": ("", str, (), None),
    # ---- out-of-core data plane (lightgbm_tpu/data, docs/DATA_PLANE.md) ----
    # memory = legacy in-RAM construction; chunked = spool the input to
    # a disk-backed chunk store and stream two-pass binning + the
    # device push, bounding host memory by ram_budget_mb instead of
    # dataset size
    "data_source": ("memory", str, (),
                    lambda v: v in ("memory", "chunked")),
    # host RAM budget (MB) for the data plane: chunk sizing, prefetch
    # depth, and the single over-budget warning path (0 = 1024, the
    # legacy two_round >1GB text-size threshold)
    "ram_budget_mb": (0, int, (), _nonneg),
    # fixed rows per spool chunk; 0 = derived from ram_budget_mb
    "data_chunk_rows": (0, int, (), _nonneg),
    # spool directory for chunk stores; empty = self-cleaning temp dir
    "data_spool_dir": ("", str, (), None),
    "save_binary": (False, bool, ("is_save_binary", "is_save_binary_file"), None),
    "precise_float_parser": (False, bool, (), None),
    "parser_config_file": ("", str, (), None),
    # ---- Predict ----
    "start_iteration_predict": (0, int, (), None),
    "num_iteration_predict": (-1, int, (), None),
    "predict_raw_score": (False, bool, ("is_predict_raw_score", "predict_rawscore", "raw_score"), None),
    "predict_leaf_index": (False, bool, ("is_predict_leaf_index", "leaf_index"), None),
    "predict_contrib": (False, bool, ("is_predict_contrib", "contrib"), None),
    "predict_disable_shape_check": (False, bool, (), None),
    "pred_early_stop": (False, bool, (), None),
    "pred_early_stop_freq": (10, int, (), None),
    "pred_early_stop_margin": (10.0, float, (), None),
    "output_result": ("LightGBM_predict_result.txt", str, ("predict_result", "prediction_result", "predict_name", "pred_name", "name_pred"), None),
    # ---- Convert/model ----
    "convert_model_language": ("", str, (), None),
    "convert_model": ("gbdt_prediction.cpp", str, ("convert_model_file",), None),
    "input_model": ("", str, ("model_input", "model_in"), None),
    "output_model": ("LightGBM_model.txt", str, ("model_output", "model_out"), None),
    "saved_feature_importance_type": (0, int, (), None),
    "snapshot_freq": (-1, int, ("save_period",), None),
    # ---- Objective ----
    "num_class": (1, int, ("num_classes",), _pos),
    "is_unbalance": (False, bool, ("unbalance", "unbalanced_sets"), None),
    "scale_pos_weight": (1.0, float, (), _pos),
    "sigmoid": (1.0, float, (), _pos),
    "boost_from_average": (True, bool, (), None),
    "reg_sqrt": (False, bool, (), None),
    "alpha": (0.9, float, (), _pos),
    "fair_c": (1.0, float, (), _pos),
    "poisson_max_delta_step": (0.7, float, (), _pos),
    "tweedie_variance_power": (1.5, float, (), lambda v: 1.0 <= v < 2.0),
    "lambdarank_truncation_level": (30, int, (), _pos),
    "lambdarank_norm": (True, bool, (), None),
    "label_gain": ((), "list_float", (), None),
    "lambdarank_position_bias_regularization": (0.0, float, (), _nonneg),
    "objective_seed": (5, int, (), None),
    # ---- Metric ----
    "metric": ((), "list_str", ("metrics", "metric_types"), None),
    "metric_freq": (1, int, ("output_freq",), _pos),
    "is_provide_training_metric": (False, bool, ("training_metric", "is_training_metric", "train_metric"), None),
    "eval_at": ((1, 2, 3, 4, 5), "list_int", ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"), None),
    "multi_error_top_k": (1, int, (), _pos),
    "auc_mu_weights": ((), "list_float", (), None),
    # ---- Network (config.h "Network Parameters") ----
    "num_machines": (1, int, ("num_machine",), _pos),
    "local_listen_port": (12400, int, ("local_port", "port"), _pos),
    "time_out": (120, int, (), _pos),
    "machine_list_filename": ("", str, ("machine_list_file", "machine_list", "mlist"), None),
    "machines": ("", str, ("workers", "nodes"), None),
    # ---- GPU/device (accepted for compat; TPU build maps these onto the mesh) ----
    "gpu_platform_id": (-1, int, (), None),
    "gpu_device_id": (-1, int, (), None),
    "gpu_use_dp": (False, bool, (), None),
    "num_gpu": (1, int, (), _pos),
    # ---- TPU-specific extensions (not in reference) ----
    "tpu_row_block": (0, int, (), _nonneg),  # 0 = auto; rows per histogram matmul block
    # round-batched growth: split every positive-gain leaf per device
    # step (multi-leaf histograms + one sort per round). Faster on TPU,
    # but once num_leaves binds the tree differs from exact leaf-wise
    # greedy (best-first); off by default for reference parity.
    "tpu_growth_rounds": (False, bool, (), None),
    # growth strategy: "exact" = sequential best-first (reference-exact
    # trees); "rounds" = natural-order round-batched growth (rounds.py:
    # top-k positive-gain leaves split per device step, slot-packed MXU
    # histograms, no row movement — ~an order of magnitude faster on
    # TPU, deviates from exact best-first only when num_leaves binds);
    # "auto" (default) = rounds on TPU hardware unless the config
    # requires another grower (tree_learner=feature rides the flat
    # feature-parallel path), exact otherwise — so CPU test/parity
    # runs keep reference-exact trees. Voting-parallel, forced splits,
    # per-node extras and all monotone methods ride rounds.
    "tpu_growth_mode": ("auto", str, (),
                        lambda v: v in ("auto", "rounds", "exact")),
    # max leaves split per round in rounds mode; 0 = auto (25 = 5 gh
    # channels x 25 slots filling the MXU's 128-row matmul axis; 42
    # under use_quantized_grad's 3 integer channels)
    "tpu_round_slots": (0, int, (), _nonneg),
    # internal histogram-channel dtype policy (docs/DESIGN_DECISIONS.md
    # "Histogram numerics"): "bf16x2" = 5-channel hi/lo split (exact
    # f32 sums); "int16"/"int8" = discretize g/h per round to 256/127
    # integer levels and accumulate 3 narrow channels (scales recovered
    # before gain/leaf math, true-gradient leaf renewal keeps the
    # public semantics); "auto" = int16 on the rounds growth path,
    # bf16x2 otherwise. "float32" is accepted as a legacy synonym for
    # bf16x2. Under use_quantized_grad the quantized-API levels govern
    # and this param is ignored.
    "tpu_hist_dtype": ("auto", str, ("hist_dtype",),
                       lambda v: v in ("auto", "float32", "bf16x2",
                                       "int16", "int8")),
    # fused-loop round chunking: "auto" (default) = dispatch boosting
    # rounds as C-round lax.scan chunks over the DEFAULT_CHUNK_LADDER
    # (one executable launch per chunk — the all-device inner loop);
    # "off" = the historical one-jit-dispatch-per-round loop, kept as
    # the bit-parity baseline for tests and the bench.py `chunk_scan`
    # segment. Both paths share one traced step body, so models and
    # eval records are bit-identical either way.
    "tpu_chunk_scan": ("auto", str, (),
                       lambda v: v in ("auto", "off")),
    # USE_DEBUG split validation (serial_tree_learner.h:174 CheckSplit):
    # recompute leaf counts/hessian sums from the partition each
    # iteration and fatal on drift; forces the sync loop
    "tpu_debug_check_split": (False, bool, (), None),
    "tpu_mesh_axes": ("data", str, (), None),
    # ---- serving (task=serve; lightgbm_tpu/serving, docs/SERVING.md) ----
    # 0 = JSONL loop over stdin/stdout; >0 = HTTP on that port
    "serve_port": (0, int, (), _nonneg),
    "serve_host": ("127.0.0.1", str, (), None),
    # bucket ladder of padded batch shapes (bounds compiles per model)
    "serve_buckets": (DEFAULT_SERVE_BUCKETS, "list_int", (), None),
    "serve_warmup": (True, bool, (), None),  # precompile every bucket
    "serve_model_name": ("default", str, (), None),
    # serving degradation knobs (docs/RESILIENCE.md): default deadline
    # applied to queued (via_queue) scoring requests, 0 = none; row cap
    # on the microbatch queue, 0 = unbounded (over-cap submits fast-fail
    # with QueueOverflow -> HTTP 503 + Retry-After)
    "serve_deadline_ms": (0.0, float, (), _nonneg),
    "serve_queue_cap": (0, int, (), _nonneg),
    # N predictor replicas per loaded model (round-robined over the
    # local devices; the MicroBatcher drains through all of them —
    # continuous batching). Ignored under a multi-device mesh.
    "serve_replicas": (1, int, (), _pos),
    # multi-tenant fleet serving (serving/fleet.py): models resident
    # as stacked forest tables with LRU HBM paging; capacity = max
    # models resident at once, slots = stack depth per shape family
    "serve_fleet": (False, bool, (), None),
    "serve_fleet_capacity": (32, int, (), _pos),
    "serve_fleet_slots": (8, int, (), _pos),
    # hardened HTTP transport (server.py): per-connection socket
    # timeout (a stalled client answers 408 instead of pinning a
    # handler thread) and the request-body byte cap (413 over it)
    "serve_socket_timeout_s": (30.0, float, (), _pos),
    "serve_max_body_mb": (64.0, float, (), _pos),
    # ---- serving gateway (task=gateway; serving/gateway.py,
    # docs/RESILIENCE.md "Serving gateway") ----
    # comma-separated backend base URLs (e.g.
    # "http://127.0.0.1:8101,http://127.0.0.1:8102"); the gateway
    # spreads traffic over them with least-outstanding balancing
    "gateway_backends": ("", str, (), None),
    "gateway_port": (8100, int, (), _nonneg),
    "gateway_host": ("127.0.0.1", str, (), None),
    # retry rounds for idempotent ops (full-jitter backoff between)
    "gateway_retries": (2, int, (), _nonneg),
    "gateway_backoff_base_s": (0.05, float, (), _pos),
    # hedging: fire a duplicate score/contrib attempt once the primary
    # outlives this rolling latency quantile; budget caps hedges to
    # this fraction of traffic (0 disables hedging)
    "gateway_hedge_quantile": (0.95, float, (), _pos),
    "gateway_hedge_budget": (0.05, float, (), _nonneg),
    # per-backend circuit breaker: consecutive failures to trip, and
    # the open->half_open cooldown
    "gateway_breaker_failures": (5, int, (), _pos),
    "gateway_breaker_cooldown_s": (2.0, float, (), _pos),
    # default per-request deadline budget when the client sends none
    # (0 = no deadline); expired work sheds 503 + Retry-After
    "gateway_deadline_ms": (0.0, float, (), _nonneg),
    # backend /readyz probe cadence and SIGTERM drain budget
    "gateway_health_interval_s": (1.0, float, (), _pos),
    "gateway_drain_timeout_s": (30.0, float, (), _pos),
    # ---- observability (lightgbm_tpu/obs, docs/OBSERVABILITY.md) ----
    # runtime switch for the phase timer (the env LIGHTGBM_TPU_TIMETAG
    # analog of the reference's compile-time USE_TIMETAG) — no restart
    # needed
    "timetag": (False, bool, (), None),
    # capture a jax.profiler trace + host span trace + run manifest
    # into this directory (span names align via jax.named_scope)
    "profile_dir": ("", str, (), None),
    # write a run-manifest JSON (config/topology/compiles/wire bytes)
    # to this path after the task finishes
    "run_manifest": ("", str, ("manifest_file",), None),
    # flight recorder (obs/recorder.py): stream one JSONL record per
    # boosting round (phases, learning curve, tree stats, trees/s) to
    # this path; summarized into the run manifest
    "record_file": ("", str, ("flight_record",), None),
    # anomaly sentinels over the flight-record stream
    # (obs/anomaly.py): off = sentinels don't run; warn = log + metrics
    # counter + trace instant per trip; abort = additionally raise
    # AnomalyAbort (the recorder and manifest still flush); rollback =
    # restore the last snapshot_freq checkpoint and retrain (optionally
    # with a shrunken learning_rate) instead of aborting
    "anomaly_policy": ("off", str, (),
                       lambda v: v in ("off", "warn", "abort", "rollback")),
    # ---- resilience (lightgbm_tpu/resilience, docs/RESILIENCE.md) ----
    # crash-consistent checkpoint/resume: snapshot_freq>0 additionally
    # maintains ONE rolling checkpoint (model text + round index + eval
    # history + flight-record offset, written atomically). resume=auto
    # restarts train() from it when present; resume_from= names an
    # explicit checkpoint file (missing -> error). The resumed model
    # bit-matches the uninterrupted run.
    "resume": ("off", str, (), lambda v: v in ("off", "auto")),
    "resume_from": ("", str, (), None),
    # rolling checkpoint path; empty = <output_model>.ckpt
    "checkpoint_file": ("", str, (), None),
    # anomaly_policy=rollback: learning_rate multiplier applied on each
    # rollback retrain, and how many rollbacks before giving up
    "anomaly_rollback_lr_decay": (1.0, float, (), _pos),
    "anomaly_rollback_max": (2, int, (), _nonneg),
    # deterministic fault plan (resilience/faultinject.py), e.g.
    # "round:7:kill;serve_request:2:delay:0.25"; empty = env
    # LGBMTPU_FAULT_PLAN, else disarmed (zero overhead)
    "fault_plan": ("", str, (), None),
    # ---- online train-and-serve loop (task=loop; lightgbm_tpu/online,
    # docs/RESILIENCE.md "Online loop") ----
    # durable loop directory: state file, ingest spool, versioned
    # model texts, heartbeats, event provenance
    "loop_dir": ("online_loop", str, (), None),
    # minimum spooled rows before a refit cycle runs
    "loop_min_rows": (64, int, (), _pos),
    # NEW boosting rounds per refit (the delta spliced onto v(n))
    "loop_rounds": (10, int, (), _pos),
    # metric-gate slack in the first metric's worse direction
    "loop_gate_margin": (0.0, float, (), _nonneg),
    # verdict cycles before task=loop exits; 0 = run until interrupted
    "loop_max_cycles": (0, int, (), _nonneg),
    # idle poll interval while waiting for ingest
    "loop_poll_s": (0.5, float, (), _pos),
}

# alias -> canonical name
_ALIASES: Dict[str, str] = {}
for _name, (_d, _t, _al, _c) in _PARAMS.items():
    for _a in _al:
        _ALIASES[_a] = _name

_BOOL_TRUE = {"true", "1", "yes", "on", "t", "y", "+"}
_BOOL_FALSE = {"false", "0", "no", "off", "f", "n", "-"}

# objective name aliases (objective_function.cpp factory + config.h docs)
OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg", "xendcg_mart": "rank_xendcg",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def _coerce(name: str, typ: Any, value: Any) -> Any:
    if name == "interaction_constraints" and isinstance(value, (list, tuple)):
        # the reference Python package accepts a list of lists and
        # serializes it to the "[0,1,2],[3,4]" config-string form
        # (basic.py _param_dict_to_str)
        return ",".join(
            "[" + ",".join(str(int(i)) for i in g) + "]" for g in value
        )
    if typ is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, (int, float)):
            return bool(value)
        s = str(value).strip().lower()
        if s in _BOOL_TRUE:
            return True
        if s in _BOOL_FALSE:
            return False
        raise ValueError(f"cannot parse {value!r} as bool for parameter {name}")
    if typ is int:
        if value is None:
            return None
        return int(float(value)) if isinstance(value, str) else int(value)
    if typ is float:
        return float(value)
    if typ is str:
        return str(value).strip()
    if typ in ("list_int", "list_float", "list_str"):
        elem = {"list_int": int, "list_float": float, "list_str": str}[typ]
        if isinstance(value, str):
            value = [v for v in value.replace(";", ",").split(",") if v != ""]
        if not isinstance(value, (list, tuple)):
            value = [value]
        return tuple(elem(v) for v in value)
    raise AssertionError(f"unknown param type {typ}")


# Parameters that bind to the DATASET at construction time (binning /
# bundling / raw retention). Only these leak from a shared Dataset into
# later boosters — a booster's own params (objective, extra_trees, ...)
# must never pollute a Dataset reused by the next training
# (reference: Dataset params vs Booster params are separate configs).
DATASET_PARAMS = frozenset({
    "max_bin", "max_bin_by_feature", "min_data_in_bin",
    "bin_construct_sample_cnt", "data_random_seed", "use_missing",
    "zero_as_missing", "enable_bundle", "feature_pre_filter",
    "forcedbins_filename",
    "categorical_feature", "linear_tree", "tpu_row_block",
    "monotone_constraints", "header", "label_column", "weight_column",
    "group_column", "ignore_column", "two_round", "pre_partition",
    "data_source", "ram_budget_mb", "data_chunk_rows", "data_spool_dir",
})


def resolve_alias(key: str) -> str:
    """ParameterAlias::KeyAliasTransform equivalent: alias -> canonical name."""
    k = key.strip().lower()
    return _ALIASES.get(k, k)


def parse_kv_config(text: str) -> Dict[str, str]:
    """Parse `k=v` lines (CLI config file format, src/io/config.cpp KV2Map).

    '#' starts a comment; first occurrence of a key wins
    (Config::KeepFirstValues semantics).
    """
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        if "=" not in line:
            log.warning(f"Unknown config line: {line!r}")
            continue
        k, v = line.split("=", 1)
        k = k.strip()
        if k and k not in out:
            out[k] = v.strip()
    return out


class Config:
    """Resolved parameter set. Attribute access for canonical names."""

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        self._values: Dict[str, Any] = {n: d for n, (d, _t, _a, _c) in _PARAMS.items()}
        self._raw: Dict[str, Any] = {}
        self.pass_through: Dict[str, Any] = {}
        if params:
            self.update(params)

    def update(self, params: Dict[str, Any]) -> None:
        resolved: Dict[str, Any] = {}
        for k, v in params.items():
            name = resolve_alias(k)
            if name in resolved and resolved[name] != v:
                log.warning(f"{k} is set with conflicting values, using {resolved[name]}")
                continue
            resolved[name] = v
        for name, v in resolved.items():
            if name not in _PARAMS:
                self.pass_through[name] = v
                continue
            default, typ, _aliases, check = _PARAMS[name]
            try:
                cv = _coerce(name, typ, v)
            except (ValueError, TypeError) as e:
                log.fatal(f"Parameter {name}: {e}")
            if check is not None and cv is not None and not check(cv):
                log.fatal(f"Parameter {name}={cv} violates its constraint")
            self._values[name] = cv
            self._raw[name] = v
        self._post_process()

    def _post_process(self) -> None:
        v = self._values
        # objective alias normalization; rmse/l2_root sets reg_sqrt (config logic)
        obj = str(v["objective"]).lower()
        if obj in ("l2_root", "root_mean_squared_error", "rmse"):
            v["reg_sqrt"] = True
        if obj in OBJECTIVE_ALIASES:
            v["objective"] = OBJECTIVE_ALIASES[obj]
        if v["objective"] in ("multiclass", "multiclassova") and v["num_class"] <= 1:
            log.fatal("num_class must be >1 for multiclass objectives")
        if v["objective"] not in ("multiclass", "multiclassova") and v["num_class"] != 1 \
                and v["objective"] != "none":
            log.fatal(f"num_class must be 1 for objective {v['objective']}")
        if v["boosting"] in ("goss",):
            # boosting=goss is a deprecated spelling of gbdt + goss sampling
            v["boosting"] = "gbdt"
            v["data_sample_strategy"] = "goss"
        if v["seed"] is not None:
            # seed overrides the individual component seeds (config.h:seed docs)
            base = int(v["seed"])
            if "bagging_seed" not in self._raw:
                v["bagging_seed"] = base + 3
            if "feature_fraction_seed" not in self._raw:
                v["feature_fraction_seed"] = base + 2
            if "drop_seed" not in self._raw:
                v["drop_seed"] = base + 4
            if "data_random_seed" not in self._raw:
                v["data_random_seed"] = base + 1
            if "extra_seed" not in self._raw:
                v["extra_seed"] = base + 6
            if "objective_seed" not in self._raw:
                v["objective_seed"] = base + 5
        log.set_verbosity(v["verbosity"])

    def __getattr__(self, name: str) -> Any:
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(name)

    def __contains__(self, name: str) -> bool:
        return name in self._values

    def set_explicitly(self, name: str) -> bool:
        """Whether the user explicitly set this parameter (vs default)."""
        return name in self._raw

    def to_dict(self) -> Dict[str, Any]:
        d = dict(self._values)
        d.update(self.pass_through)
        return d

    def explicit_params(self) -> Dict[str, Any]:
        d = dict(self._raw)
        d.update(self.pass_through)
        return d

    @property
    def num_model_per_iteration(self) -> int:
        """K trees per boosting iteration (gbdt.cpp:101 NumModelPerIteration).

        Custom objectives (objective=none) with num_class>1 also train
        num_class trees per iteration (the caller supplies K*N gradients).
        """
        if self._values["objective"] in ("multiclass", "multiclassova", "none"):
            return int(self._values["num_class"])
        return 1


# ---------------------------------------------------------------------------
# honest parameter surface: accepted-but-not-yet-implemented params warn
# loudly instead of silently doing nothing (VERDICT r2 weak #5; swept
# again for VERDICT r5 missing #2 — every entry here was verified
# unreferenced outside this file). Format: (name, inactive value, why).
# ---------------------------------------------------------------------------
_UNIMPLEMENTED = (
    ("histogram_pool_size", -1.0,
     "histograms are device-resident; there is no host pool to cap"),
    ("force_col_wise", False,
     "the device bin matrix is always feature-major"),
    ("force_row_wise", False,
     "the device bin matrix is always feature-major"),
    ("is_enable_sparse", True,
     "sparse inputs always bin through the CSR path; there is no "
     "dense/sparse bin switch to disable"),
    ("precise_float_parser", False,
     "the text parsers always parse at full float64 precision"),
    ("parser_config_file", "",
     "custom parser plugins are not supported"),
    ("saved_feature_importance_type", 0,
     "saved models always carry split-count importances"),
    ("gpu_platform_id", -1,
     "OpenCL/CUDA device selection does not apply to the TPU backend; "
     "use device_type and the JAX mesh"),
    ("gpu_device_id", -1,
     "OpenCL/CUDA device selection does not apply to the TPU backend"),
    ("gpu_use_dp", False,
     "device histograms are f32 (int32 under use_quantized_grad); "
     "there is no double-precision GPU path"),
    ("num_gpu", 1,
     "accelerator count comes from the JAX mesh, not num_gpu"),
    ("num_threads", 0,
     "host-side work is numpy/BLAS-threaded; the device does the rest"),
    ("deterministic", False,
     "training is already deterministic for a fixed seed and mesh"),
    ("feature_contri", (),
     "per-feature split-gain multipliers are not implemented"),
    ("predict_disable_shape_check", False,
     "predict always validates the feature count"),
    ("time_out", 120,
     "the cluster handshake timeout is managed by jax.distributed"),
)


def parse_interaction_constraints(s: str, num_features: int):
    """Parse the reference's interaction_constraints string
    ("[0,1,2],[2,3]" — groups of ORIGINAL feature indices; config.h
    interaction_constraints) into a list of int lists."""
    s = (s or "").strip()
    if not s:
        return []
    import re

    groups = []
    for m in re.finditer(r"\[([^\]]*)\]", s):
        body = m.group(1).strip()
        if not body:
            continue
        idxs = []
        for tok in body.split(","):
            tok = tok.strip()
            if not tok:
                continue
            i = int(tok)
            if i < 0 or i >= num_features:
                from . import log

                log.fatal(
                    f"interaction_constraints index {i} out of range "
                    f"[0, {num_features})"
                )
            idxs.append(i)
        if idxs:
            groups.append(idxs)
    return groups


def warn_unimplemented(cfg: "Config") -> None:
    """Emit one warning per param set away from its inactive value but
    having no effect in this build; called once per training run."""
    from . import log

    for name, inactive, msg in _UNIMPLEMENTED:
        v = getattr(cfg, name, inactive)
        if isinstance(v, tuple):
            active = len(v) > 0
        else:
            active = v != inactive
        if active:
            log.warning(f"{name} is set but has no effect: {msg}")
    if cfg.monotone_constraints_method not in ("basic", "intermediate",
                                               "advanced"):
        log.warning(
            f"monotone_constraints_method={cfg.monotone_constraints_method} "
            "is unknown; using 'basic' (interval inheritance)"
        )
    elif (cfg.monotone_constraints_method == "advanced"
          and cfg.tpu_growth_mode == "exact"):
        log.warning(
            "monotone_constraints_method=advanced rides the rounds "
            "grower (per-leaf range-overlap refinement of the "
            "opposite-subtree extrema, monotone_constraints.hpp:858); "
            "tpu_growth_mode=exact uses the intermediate formulation"
        )
