"""Capped-exponential retry/backoff — the ONE copy in the repo.

bench.py's backend probe grew the first inline backoff loop (the axon
tunnel wedges transiently and clears on a later attempt); the fleet
scrape (obs/aggregate.pull_snapshot) and the multi-host cluster join
(parallel/multihost.init_distributed) need the identical policy for
the identical reason — a transient connect failure must not condemn a
whole run on first strike. This module factors the schedule and the
retry loop so there is exactly one implementation (ISSUE 10 satellite:
no third copy).

Deliberately PURE STDLIB with no package-relative imports: bench.py
must load it via ``importlib`` from the file path *before* jax (and
therefore before ``lightgbm_tpu.__init__``) can be imported — probing
the backend from a jax-polluted parent process is exactly the hang the
probe exists to avoid.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence, Tuple, Type


def backoff_delay(attempt: int, base_s: float = 0.5,
                  cap_s: float = 120.0) -> float:
    """Delay before retry number ``attempt`` (1-based): base * 2^(n-1),
    capped. attempt=1 -> base, attempt=2 -> 2*base, ... (bench.py's
    historical 10s/20s/40s/.../120s schedule is base_s=10, cap_s=120)."""
    if attempt < 1:
        raise ValueError(f"attempt is 1-based, got {attempt}")
    return min(float(base_s) * (2.0 ** (attempt - 1)), float(cap_s))


def full_jitter_delay(attempt: int, base_s: float = 0.5,
                      cap_s: float = 120.0,
                      rand: Optional[Callable[[], float]] = None) -> float:
    """AWS-style "full jitter" on the same capped-exponential
    schedule: uniform in [0, backoff_delay(attempt)]. Decorrelates
    retry storms — N clients that failed together do NOT retry
    together (the serving gateway's retry policy; tests pass a seeded
    ``rand`` for determinism)."""
    if rand is None:
        import random

        rand = random.random
    return rand() * backoff_delay(attempt, base_s, cap_s)


def retry_call(
    fn: Callable,
    *,
    retries: int = 3,
    base_s: float = 0.5,
    cap_s: float = 120.0,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    retriable: Optional[Callable[[BaseException], bool]] = None,
    describe: str = "operation",
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()``; on a retriable failure sleep the capped-exponential
    delay and try again, up to ``retries`` additional attempts.

    A failure is retried when it is an instance of ``retry_on`` AND
    (when given) ``retriable(exc)`` returns True — the predicate is how
    pull_snapshot retries transient URLErrors but not HTTP 4xx, which
    would fail identically forever. The last failure propagates
    unchanged so callers keep their typed exceptions. ``on_retry``
    observes each scheduled retry (attempt number, delay, exception) —
    loggers hook in there; this module deliberately has none.
    """
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            if attempt > retries or (retriable is not None
                                     and not retriable(e)):
                raise
            delay = backoff_delay(attempt, base_s, cap_s)
            if on_retry is not None:
                on_retry(attempt, delay, e)
            sleep(delay)


def delays(retries: int, base_s: float = 0.5,
           cap_s: float = 120.0) -> Sequence[float]:
    """The full schedule as a list (for logs/tests): retries=3,
    base_s=10 -> [10.0, 20.0, 40.0]."""
    return [backoff_delay(a, base_s, cap_s) for a in range(1, retries + 1)]
