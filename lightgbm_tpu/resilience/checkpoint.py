"""Crash-consistent training checkpoints (docs/RESILIENCE.md).

``snapshot_freq`` historically wrote model dumps nothing could resume
from (engine.py `_snapshot`, mirroring reference gbdt.cpp:258-262).
This module extends that cadence into a SINGLE rolling checkpoint file
carrying everything engine.train needs to restart at the last good
round and reproduce the uninterrupted run bit for bit:

- the model text (repr() float round-trip — exact), including any
  init_model trees;
- ``engine_round``: how many NEW boosting rounds this train() call had
  completed when the checkpoint was cut;
- the eval history (one row per round), replayed into fresh
  early-stopping/record callbacks on resume so stateful callbacks see
  the identical sequence the uninterrupted run saw;
- the flight-record byte offset, so a resumed run truncates the JSONL
  stream back to the checkpoint and appends — no duplicated or torn
  round records;
- a config fingerprint (warn-only on mismatch: anomaly rollback
  legitimately resumes with a shrunken learning_rate).

Atomicity: serialize to ``<path>.tmp`` in the same directory, flush +
fsync, then ``os.replace`` — a reader sees the old checkpoint or the
new one, never a torn file. A SIGKILL between any two instructions
loses at most the rounds since the last checkpoint. The training-side
RNG needs no state here: every sampling decision (bagging, GOSS,
feature_fraction, quantization) is keyed on the ABSOLUTE iteration via
``jax.random.fold_in`` (sample_strategy.py), so adopting the model at
round r continues the identical stream.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .errors import CheckpointError

SCHEMA = "lightgbm-tpu/checkpoint/v1"


def atomic_write_json(path: str, state: Dict[str, Any]) -> str:
    """The crash-consistency primitive every durable state file in the
    package shares: serialize to ``<path>.tmp`` in the same directory,
    flush + fsync, then ``os.replace``. A reader sees the old file or
    the new one, never a torn write (the abandoned ``.tmp`` of a crash
    mid-write is ignored by every loader)."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def default_path(output_model: str) -> str:
    """The rolling checkpoint path for a run: ``<output_model>.ckpt``."""
    return f"{output_model}.ckpt"


def config_fingerprint(params: Dict[str, Any]) -> str:
    """Stable digest of the caller's params (resume/rollback keys and
    learning_rate excluded — rollback shrinks it on purpose). Warn-only
    on mismatch, but it catches the silent killer: resuming a run under
    a different objective or tree shape."""
    skip = {
        "resume", "resume_from", "checkpoint_file", "learning_rate",
        "anomaly_policy", "anomaly_rollback_lr_decay",
        "anomaly_rollback_max", "fault_plan",
    }
    items = sorted(
        (str(k), str(v)) for k, v in params.items()
        if str(k) not in skip
    )
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def save_checkpoint(
    path: str,
    model_str: str,
    *,
    engine_round: int,
    total_iters: int,
    eval_history: Sequence[Sequence[Tuple]] = (),
    record_offset: Optional[int] = None,
    fingerprint: str = "",
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Atomically publish one checkpoint (tmp + fsync + os.replace)."""
    state: Dict[str, Any] = {
        "schema": SCHEMA,
        "engine_round": int(engine_round),
        "total_iters": int(total_iters),
        "fingerprint": fingerprint,
        # tuples -> lists is fine: the replay consumer indexes by
        # position, and json round-trips value types exactly
        "eval_history": [
            [list(t) for t in row] for row in eval_history
        ],
        "model": model_str,
    }
    if record_offset is not None:
        state["record_offset"] = int(record_offset)
    if extra:
        state.update(extra)
    return atomic_write_json(path, state)


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Read a checkpoint back; raises CheckpointError on a torn or
    alien file (absent files are the CALLER's decision — resume=auto
    treats them as 'start fresh', resume_from= as an error)."""
    try:
        with open(path) as f:
            state = json.load(f)
    except OSError as e:
        raise CheckpointError(f"cannot read checkpoint {path}: {e}") from e
    except json.JSONDecodeError as e:
        raise CheckpointError(
            f"checkpoint {path} is corrupt (torn write outside the "
            f"atomic protocol?): {e}"
        ) from e
    if state.get("schema") != SCHEMA:
        raise CheckpointError(
            f"checkpoint {path} has schema {state.get('schema')!r}, "
            f"expected {SCHEMA!r}"
        )
    for key in ("engine_round", "total_iters", "model"):
        if key not in state:
            raise CheckpointError(f"checkpoint {path} is missing {key!r}")
    state["eval_history"] = [
        [tuple(t) for t in row] for row in state.get("eval_history", [])
    ]
    return state


def find_resume_checkpoint(
    resume: str, resume_from: str, ckpt_path: str
) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
    """Resolve the (path, state) to resume from, or (None, None) for a
    fresh start. ``resume_from`` (explicit path) must exist and load;
    ``resume=auto`` loads the run's rolling checkpoint when present and
    readable — a corrupt auto checkpoint is surfaced, not skipped
    (silently retraining from scratch hides the data loss)."""
    if resume_from:
        return resume_from, load_checkpoint(resume_from)
    if resume == "auto" and os.path.exists(ckpt_path):
        return ckpt_path, load_checkpoint(ckpt_path)
    return None, None


def truncate_eval_history(
    history: List[List[Tuple]], rounds: int
) -> List[List[Tuple]]:
    """Clamp a history to the first ``rounds`` rounds (a checkpoint
    must never carry evals from rounds after its own cut)."""
    return list(history[: max(int(rounds), 0)])
