"""Scoring server: JSONL request loop + optional HTTP front end.

Two transports over ONE request vocabulary (docs/SERVING.md):

- ``ScoringServer`` — line-delimited JSON over a pair of streams
  (stdin/stdout under ``task=serve serve_port=0``). One request per
  line, one response line per request. This is the testable core and
  what tools/serve_smoke.sh drives end to end.
- ``serve_http`` — a stdlib ThreadingHTTPServer mapping
  ``POST /v1/<op>`` to the same handler (no new dependencies). Each
  request runs on its own thread; score requests carrying
  ``"queue": true`` additionally coalesce through the model's
  MicroBatcher into shared padded device calls. ``GET /metrics``
  serves Prometheus text exposition from the obs metrics registry and
  ``GET /healthz`` answers liveness probes (docs/OBSERVABILITY.md).

Request ops:
  {"op": "score", "model": "m", "rows": [[...], ...],
   "raw_score": false, "num_iteration": -1, "pred_leaf": false}
  {"op": "contrib", "model": "m", "rows": [[...], ...]}  # SHAP values
  {"op": "load", "model": "m", "path": "model.txt"}   # or "model_str";
   fleet registries also honor "deadline_ms" / "queue_cap" QoS here
  {"op": "swap", "model": "m", "version": 2}
  {"op": "rollback", "model": "m"}
  {"op": "models"} / {"op": "stats"} / {"op": "ping"} / {"op": "quit"}
  {"op": "fleet"}  # fleet residency stats (ModelFleet registries)
  {"op": "ingest", "rows": [[...], ...], "labels": [...],
   "weights": [...]}  # spool a labeled microbatch for the online
   loop (task=loop attaches the sink; docs/SERVING.md "Ingest op")

Responses: {"ok": true, ...} or {"ok": false, "error": "..."}; scores
ride as nested lists, latency from timer.latency_stats rides in
"stats".
"""

from __future__ import annotations

import json
from typing import Any, Dict, IO, Optional

import numpy as np

from .. import log
from ..obs.metrics import default_registry, record_request_op
from ..resilience.errors import (
    DeadlineExceeded,
    InjectedFault,
    QueueOverflow,
    ShutdownError,
)
from ..resilience.faultinject import fault_point
from .registry import ModelRegistry

# typed failure -> HTTP status (the JSONL transport carries the same
# "error_kind" field; docs/RESILIENCE.md "Serving degradation")
ERROR_STATUS = {
    "overloaded": 503,  # queue admission rejected: retry later
    "deadline": 504,    # expired waiting in the microbatch queue
    "shutdown": 503,    # server draining: retry against a peer
    "fault": 500,       # injected / unexpected scoring fault
}


def _error_kind(e: Exception) -> Optional[str]:
    if isinstance(e, QueueOverflow):
        return "overloaded"
    if isinstance(e, DeadlineExceeded):
        return "deadline"
    if isinstance(e, ShutdownError):
        return "shutdown"
    if isinstance(e, InjectedFault):
        return "fault"
    return None


def handle_request(registry: ModelRegistry, req: Dict[str, Any]) -> Dict[str, Any]:
    """One request dict -> one response dict (shared by both transports).
    Every request counts into the obs metrics registry by op — the
    serve-loop counter /metrics and the stats op both read."""
    resp = _handle_request(registry, req)
    record_request_op(str(req.get("op", "score")), bool(resp.get("ok")))
    return resp


def _handle_request(registry: ModelRegistry, req: Dict[str, Any]) -> Dict[str, Any]:
    op = req.get("op", "score")
    try:
        # chaos-test hook: a planned fault can delay or fail the Nth
        # request here (fault_plan "serve_request:N:..."), exercising
        # the exact degradation paths production failures would take
        fault_point("serve_request")
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "models":
            return {"ok": True, "models": registry.models()}
        if op == "stats":
            return {"ok": True, "stats": registry.stats()}
        if op == "load":
            src = req.get("model_str") or req.get("path")
            if not src:
                raise ValueError("load needs 'path' or 'model_str'")
            kwargs: Dict[str, Any] = {
                "warmup": req.get("warmup"),
                "num_features": req.get("num_features"),
            }
            # per-tenant QoS rides the load op (fleet registries honor
            # it; the plain registry would reject unknown kwargs)
            for k in ("deadline_ms", "queue_cap"):
                if req.get(k) is not None:
                    kwargs[k] = req[k]
            v = registry.load(req.get("model", "default"), src, **kwargs)
            return {"ok": True, "version": v}
        if op == "swap":
            registry.swap(req["model"], int(req["version"]))
            return {"ok": True, "active": int(req["version"])}
        if op == "rollback":
            v = registry.rollback(req["model"])
            return {"ok": True, "active": v}
        if op in ("score", "contrib"):
            rows = np.asarray(req["rows"], np.float32)
            dl_ms = req.get("deadline_ms")
            pred = registry.predict(
                req.get("model", "default"), rows,
                raw_score=bool(req.get("raw_score", False)),
                start_iteration=int(req.get("start_iteration", 0)),
                num_iteration=int(req.get("num_iteration", -1)),
                pred_leaf=bool(req.get("pred_leaf", False)),
                pred_contrib=(op == "contrib"
                              or bool(req.get("pred_contrib", False))),
                via_queue=bool(req.get("queue", False)),
                version=req.get("version"),
                deadline_s=(float(dl_ms) / 1000.0
                            if dl_ms is not None else None),
            )
            return {"ok": True, "pred": np.asarray(pred).tolist()}
        if op == "fleet":
            if not hasattr(registry, "fleet_stats"):
                raise ValueError("not a fleet registry")
            return {"ok": True, "fleet": registry.fleet_stats()}
        if op == "ingest":
            # durable microbatch spool for the online loop; the sink is
            # attached by OnlineLoop.attach (same duck-typed-attribute
            # pattern as the fleet op above)
            sink = getattr(registry, "ingest_sink", None)
            if sink is None:
                raise ValueError(
                    "no online loop attached (task=loop owns ingest)")
            out = sink.append(req["rows"], req["labels"],
                              req.get("weights"))
            return {"ok": True, **out}
        if op == "quit":
            return {"ok": True, "quit": True}
        raise ValueError(f"unknown op {op!r}")
    except Exception as e:  # noqa: BLE001 — a bad request must not kill serving
        resp = {"ok": False, "op": op, "error": f"{type(e).__name__}: {e}"}
        kind = _error_kind(e)
        if kind is not None:
            resp["error_kind"] = kind
        if isinstance(e, QueueOverflow):
            resp["retry_after_s"] = e.retry_after_s
        return resp


class ScoringServer:
    """JSONL loop over (in_stream, out_stream)."""

    def __init__(self, registry: Optional[ModelRegistry] = None):
        self.registry = registry if registry is not None else ModelRegistry()

    def serve(self, in_stream: IO[str], out_stream: IO[str]) -> int:
        """Read one JSON request per line until EOF or op=quit; returns
        the number of requests handled."""
        handled = 0
        for line in in_stream:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError as e:
                resp: Dict[str, Any] = {
                    "ok": False, "error": f"bad json: {e}"
                }
            else:
                resp = handle_request(self.registry, req)
            out_stream.write(json.dumps(resp) + "\n")
            out_stream.flush()
            handled += 1
            if resp.get("quit"):
                break
        return handled


def readiness(registry: ModelRegistry,
              draining: Optional[Any] = None) -> Dict[str, Any]:
    """The /readyz verdict (liveness is /healthz: "the process is
    up"). Ready means: not draining, >=1 model loaded, microbatch
    queue depth under the admission cap, and — when an online loop is
    attached — its heartbeat fresh. The serving gateway routes traffic
    on THIS verdict only (docs/RESILIENCE.md "Serving gateway")."""
    out: Dict[str, Any] = {
        "ok": False, "role": "backend",
        "draining": bool(draining is not None and draining.is_set()),
    }
    if out["draining"]:
        out["reason"] = "draining"
        return out
    # registry.models() directly — NOT _handle_request, which passes
    # the serve_request fault site: a chaos plan's hit counters must
    # count real protocol requests, never health probes
    try:
        models = registry.models()
    except Exception as e:  # noqa: BLE001 — a broken registry is "not ready", not a crash
        out["reason"] = f"registry: {type(e).__name__}: {e}"
        return out
    out["models"] = len(models or {})
    if not models:
        out["reason"] = "no models loaded"
        return out
    cap = int(getattr(registry, "queue_cap", 0) or 0)
    depths = default_registry().snapshot().get(
        "lgbmtpu_serve_queue_depth") or {}
    depth = int(max(depths.values(), default=0))
    out["queue_depth"] = depth
    out["queue_cap"] = cap
    if cap > 0 and depth >= cap:
        out["reason"] = "queue at admission cap"
        return out
    probe = getattr(registry, "health_probe", None)
    if probe is not None:
        try:
            health = probe()
        except Exception as e:  # noqa: BLE001 — probe must not kill /readyz
            health = {"healthy": False,
                      "error": f"{type(e).__name__}: {e}"}
        out["health"] = health
        if not health.get("healthy", True):
            out["reason"] = "loop heartbeat stale"
            return out
    out["ok"] = True
    return out


def serve_http(registry: ModelRegistry, port: int,
               host: str = "127.0.0.1", block: bool = True,
               socket_timeout_s: float = 30.0,
               max_body_mb: float = 64.0,
               draining: Optional[Any] = None):
    """HTTP server: POST /v1/<op> with the same JSON bodies ("op"
    inferred from the path); GET /v1/models, /v1/stats, /healthz,
    /readyz (liveness vs readiness — the gateway registers on
    readiness only), /metrics (Prometheus text exposition).
    port=0 binds an ephemeral port. With block=True (the task=serve
    mode) returns only when the process is interrupted; block=False
    returns the bound httpd immediately (serve it from your own
    thread; tests do this) — call .shutdown() to stop.

    Hardened transport: every accepted connection carries a
    ``socket_timeout_s`` timeout (a stalled or dead peer times out
    instead of pinning a handler thread forever; the stall answers
    408), and request bodies are bounded by ``max_body_mb`` (413 over
    the cap). ``draining`` is an optional threading.Event the SIGTERM
    path sets: readiness flips false so the gateway stops routing
    here, while in-flight requests finish (cli._task_serve)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    max_body = int(float(max_body_mb) * 1024 * 1024)

    class Handler(BaseHTTPRequestHandler):
        # per-connection socket timeout (BaseRequestHandler.setup
        # applies it): the slow-client hardening
        timeout = float(socket_timeout_s)

        def _reply(self, resp: Dict[str, Any], code: int = 200) -> None:
            body = json.dumps(resp).encode()
            if code == 200 and not resp.get("ok", True):
                # typed resilience failures map to their own statuses
                # (503 overloaded / 504 deadline); anything else is a
                # handler error; explicit codes (404) win
                code = ERROR_STATUS.get(resp.get("error_kind"), 400)
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if resp.get("error_kind") == "overloaded":
                self.send_header(
                    "Retry-After",
                    str(max(int(resp.get("retry_after_s", 1)), 1)),
                )
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path in ("/healthz", "/health"):
                # registry read, NOT the request handler: a liveness
                # probe must not inflate the op="models" protocol
                # counter nor consume fault-plan hits (chaos plans
                # count real protocol requests only)
                try:
                    listing = sorted(registry.models() or {})
                except Exception:  # noqa: BLE001 — liveness is "process up", not "registry ok"
                    listing = []
                payload: Dict[str, Any] = {
                    "ok": True,
                    "models": listing,
                }
                # loop/worker liveness (resilience.health_report via
                # OnlineLoop.health): an operator sees a wedged refit
                # loop from the same endpoint that reports serving
                # health. "ok" stays serving-liveness; the loop's own
                # verdict rides in "health"["healthy"].
                probe = getattr(registry, "health_probe", None)
                if probe is not None:
                    try:
                        payload["health"] = probe()
                    except Exception as e:  # noqa: BLE001 — probe must not kill /healthz
                        payload["health"] = {
                            "healthy": False,
                            "error": f"{type(e).__name__}: {e}",
                        }
                self._reply(payload)
            elif self.path == "/readyz":
                ready = readiness(registry, draining)
                self._reply(ready, 200 if ready["ok"] else 503)
            elif self.path == "/metrics":
                # Prometheus text exposition (docs/OBSERVABILITY.md):
                # scrape-time samples from the same registry + latency
                # rings the stats op reports
                body = default_registry().render_prometheus().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/v1/models":
                self._reply(handle_request(registry, {"op": "models"}))
            elif self.path == "/v1/stats":
                self._reply(handle_request(registry, {"op": "stats"}))
            elif self.path == "/v1/fleet":
                self._reply(handle_request(registry, {"op": "fleet"}))
            else:
                self._reply({"ok": False, "error": "not found"}, 404)

        def do_POST(self):  # noqa: N802 — http.server API
            try:
                n = int(self.headers.get("Content-Length", 0))
            except ValueError:
                self._reply({"ok": False,
                             "error": "bad Content-Length"}, 400)
                return
            if n > max_body:
                # bounded body read: refuse before reading, so an
                # oversize (or lying) client cannot balloon the heap
                self._reply({"ok": False,
                             "error": f"body over {max_body} bytes"}, 413)
                return
            try:
                raw = self.rfile.read(n)
            except (OSError, TimeoutError) as e:
                # stalled client: the per-connection socket timeout
                # fired mid-body — answer 408 and free the thread
                self._reply({"ok": False, "error": f"body read: {e}"},
                            408)
                return
            try:
                req = json.loads(raw or b"{}")
            except json.JSONDecodeError as e:
                self._reply({"ok": False, "error": f"bad json: {e}"}, 400)
                return
            if self.path.startswith("/v1/"):
                req.setdefault("op", self.path[len("/v1/"):])
            if draining is not None and draining.is_set():
                # stop ACCEPTING new work; in-flight requests on other
                # threads run to completion (the SIGTERM drain
                # contract; gateway peers retry elsewhere on the 503)
                self._reply({"ok": False, "op": req.get("op"),
                             "error": "server draining",
                             "error_kind": "shutdown",
                             "retry_after_s": 1.0})
                return
            if req.get("op") == "quit":  # no remote shutdown over HTTP
                self._reply({"ok": False, "error": "quit is stdio-only"}, 400)
                return
            self._reply(handle_request(registry, req))

        def log_message(self, fmt, *args):  # route through package log
            log.debug(f"serve http: {fmt % args}")

    httpd = ThreadingHTTPServer((host, port), Handler)
    # drain contract: ThreadingMixIn only TRACKS (and joins at
    # server_close) non-daemon handler threads — with the stock
    # daemon_threads=True a SIGTERM drain would drop in-flight
    # responses at process exit. Exit latency stays bounded by the
    # per-connection socket timeout above.
    httpd.daemon_threads = False
    log.info(f"serving on http://{host}:{httpd.server_address[1]}/v1")
    if not block:
        return httpd
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return httpd
