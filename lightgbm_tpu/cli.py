"""Config-file-driven command line application.

Reference surface: src/main.cpp:14 + src/application/application.cpp —
`lightgbm config=train.conf [k=v ...]` with tasks train / predict /
save_binary / convert_model / refit (config.h:35 TaskType). Parameter
layering matches Application::LoadParameters (application.cpp:53-89):
command-line pairs first, then `config=` file lines (k = v, `#`
comments), FIRST occurrence of a key wins (config.cpp KeepFirstValues).

Run as `python -m lightgbm_tpu config=train.conf` (or the bin/lightgbm
wrapper). The reference's example train.conf files run unmodified.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from . import log


def parse_kv_args(argv: List[str]) -> Dict[str, str]:
    """argv 'k=v' pairs + config= file lines; first occurrence wins."""
    params: Dict[str, str] = {}

    def add(k: str, v: str) -> None:
        k = k.strip()
        v = v.strip().strip('"').strip("'")
        if k and k not in params:
            params[k] = v

    for arg in argv:
        if "=" in arg:
            k, v = arg.split("=", 1)
            add(k, v)
    cfg = params.get("config", "")
    if cfg:
        if not Path(cfg).exists():
            log.fatal(f"config file {cfg} does not exist")
        for line in Path(cfg).read_text().splitlines():
            if "#" in line:
                line = line[: line.index("#")]
            line = line.strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            add(k, v)
    params.pop("config", None)
    return params


_DATA_KEYS = (
    "header", "label_column", "weight_column", "group_column",
    "ignore_column", "categorical_feature",
)


def _mappers_equal(a, b) -> bool:
    if len(a) != len(b):
        return False
    for ma, mb in zip(a, b):
        if (
            ma.num_bin != mb.num_bin
            or ma.bin_type != mb.bin_type
            or ma.categories != mb.categories
            or not np.array_equal(ma.upper_bounds, mb.upper_bounds)
        ):
            return False
    return True


def _load_dataset(params: Dict[str, str], path: str, reference=None):
    """Text or .bin cache -> lgb.Dataset (constructed)."""
    from . import Dataset
    from .parsers import is_binary_file, load_binary, load_text_file

    if is_binary_file(path):
        log.info(f"Loading binary dataset cache {path}")
        binned = load_binary(path)
        if reference is not None:
            # a valid set must share the training set's bin mappers
            # (reference DatasetLoader::LoadFromFileAlignWithOtherDataset);
            # a cache binned independently would silently corrupt eval
            reference.construct()
            if not _mappers_equal(binned.mappers, reference._binned.mappers):
                log.fatal(
                    f"binary cache {path} was binned with different bin "
                    "mappers than the training data; rebuild it with "
                    "task=save_binary against this training set"
                )
        return Dataset.from_binned(binned)

    loaded = load_text_file(
        path,
        header=str(params.get("header", "false")).lower() in ("true", "1"),
        label_column=params.get("label_column", 0),
        weight_column=params.get("weight_column", ""),
        group_column=params.get("group_column", ""),
        ignore_column=params.get("ignore_column", ""),
        categorical_feature=params.get("categorical_feature", ""),
    )
    train_params = {
        k: v for k, v in params.items() if k not in _DATA_KEYS
    }
    ds = Dataset(
        loaded["X"],
        label=loaded["label"],
        weight=loaded["weight"],
        group=loaded["group"],
        init_score=loaded["init_score"],
        feature_name=loaded["feature_names"] or "auto",
        categorical_feature=loaded["categorical_feature"] or "auto",
        params=train_params,
        reference=reference,
        free_raw_data=False,
    )
    return ds


def _task_train(params: Dict[str, str]) -> None:
    from . import train as lgb_train
    from .config import Config

    data_path = params.get("data", "")
    if not data_path:
        log.fatal("No training/prediction data, application quit")
    t0 = time.time()
    ds = _load_dataset(params, data_path)
    ds.construct()
    log.info(
        f"Loaded {ds.num_data()} rows x {ds.num_feature()} features "
        f"from {data_path} in {time.time()-t0:.1f}s"
    )

    if str(params.get("is_save_binary_file", params.get("save_binary", "false"))).lower() in ("true", "1"):
        from .parsers import save_binary

        save_binary(ds._binned, data_path + ".bin")
        log.info(f"Saved binary cache to {data_path}.bin")

    valid_sets = []
    valid_names = []
    vpaths = [v for v in str(params.get("valid_data", params.get("valid", ""))).split(",") if v]
    for i, vp in enumerate(vpaths):
        vs = _load_dataset(params, vp, reference=ds)
        valid_sets.append(vs)
        valid_names.append(f"valid_{i + 1}")  # reference naming: valid_1, ...

    cfg = Config(dict(params))
    if str(params.get("is_training_metric", params.get("train_metric", "false"))).lower() in ("true", "1"):
        valid_sets = [ds] + valid_sets
        valid_names = ["training"] + valid_names

    num_rounds = cfg.num_iterations
    init_model = cfg.input_model  # resolves model_in/model_input aliases
    booster = lgb_train(
        dict(params), ds, num_boost_round=num_rounds,
        valid_sets=valid_sets, valid_names=valid_names,
        init_model=init_model or None,
    )
    out = params.get("output_model", "LightGBM_model.txt")
    booster.save_model(out)
    log.info(f"Finished training; model saved to {out}")


def _task_predict(params: Dict[str, str]) -> None:
    from . import Booster
    from .parsers import load_text_file

    data_path = params.get("data", "")
    model_path = params.get("input_model", "LightGBM_model.txt")
    if not data_path:
        log.fatal("No training/prediction data, application quit")
    if not Path(model_path).exists():
        log.fatal(f"input model {model_path} does not exist")
    bst = Booster(model_file=model_path)
    loaded = load_text_file(
        data_path,
        header=str(params.get("header", "false")).lower() in ("true", "1"),
        label_column=params.get("label_column", 0),
        weight_column=params.get("weight_column", ""),
        group_column=params.get("group_column", ""),
        ignore_column=params.get("ignore_column", ""),
    )
    raw = str(params.get("predict_raw_score", "false")).lower() in ("true", "1")
    leaf = str(params.get("predict_leaf_index", "false")).lower() in ("true", "1")
    contrib = str(params.get("predict_contrib", "false")).lower() in ("true", "1")
    es_kwargs = {}
    if str(params.get("pred_early_stop", "false")).lower() in ("true", "1"):
        es_kwargs = {
            "pred_early_stop": True,
            "pred_early_stop_freq": int(params.get("pred_early_stop_freq", 10)),
            "pred_early_stop_margin": float(
                params.get("pred_early_stop_margin", 10.0)
            ),
        }
    pred = bst.predict(
        loaded["X"], raw_score=raw, pred_leaf=leaf, pred_contrib=contrib,
        **es_kwargs,
    )
    out = params.get("output_result", "LightGBM_predict_result.txt")
    pred2 = np.atleast_2d(pred.T).T  # (N, K) even for 1-D
    np.savetxt(out, pred2, delimiter="\t", fmt="%.9g")
    log.info(f"Finished prediction; results saved to {out}")


def _task_save_binary(params: Dict[str, str]) -> None:
    from .parsers import save_binary

    data_path = params.get("data", "")
    if not data_path:
        log.fatal("No training/prediction data, application quit")
    ds = _load_dataset(params, data_path)
    ds.construct()
    out = params.get("output_model", data_path + ".bin")
    save_binary(ds._binned, out)
    log.info(f"Finished saving binary dataset cache to {out}")


def _task_convert_model(params: Dict[str, str]) -> None:
    """task=convert_model (application.cpp:223 ConvertModel): model ->
    if-else C++ source. convert_model_language=cpp is the only language
    the reference supports too (config.h)."""
    from . import Booster
    from .model_io import model_to_if_else

    lang = params.get("convert_model_language", "cpp")
    if lang not in ("", "cpp"):
        log.fatal(f"convert_model_language={lang} is not supported (cpp only)")
    model_path = params.get("input_model", "LightGBM_model.txt")
    if not Path(model_path).exists():
        log.fatal(f"input model {model_path} does not exist")
    bst = Booster(model_file=model_path)
    out = params.get("convert_model", "gbdt_prediction.cpp")
    Path(out).write_text(
        model_to_if_else(
            bst._gbdt.models, bst._gbdt.num_class,
            average_output=bool(getattr(bst._gbdt, "average_output", False)),
        )
    )
    log.info(f"Finished converting model to if-else code at {out}")


def _task_refit(params: Dict[str, str]) -> None:
    """task=refit (config.h:35 kRefitTree): recompute the existing
    model's leaf values from new data (Booster.refit)."""
    from . import Booster
    from .parsers import load_text_file

    data_path = params.get("data", "")
    model_path = params.get("input_model", "LightGBM_model.txt")
    if not data_path:
        log.fatal("No training/prediction data, application quit")
    if not Path(model_path).exists():
        log.fatal(f"input model {model_path} does not exist")
    bst = Booster(model_file=model_path, params=dict(params))
    loaded = load_text_file(
        data_path,
        header=str(params.get("header", "false")).lower() in ("true", "1"),
        label_column=params.get("label_column", 0),
        weight_column=params.get("weight_column", ""),
        group_column=params.get("group_column", ""),
        ignore_column=params.get("ignore_column", ""),
    )
    new_bst = bst.refit(
        loaded["X"], loaded["label"],
        decay_rate=float(params.get("refit_decay_rate", 0.9)),
        weight=loaded["weight"], group=loaded["group"],
    )
    out = params.get("output_model", "LightGBM_model.txt")
    new_bst.save_model(out)
    log.info(f"Finished the refit task; new model saved to {out}")


def _task_serve(params: Dict[str, str]) -> None:
    """task=serve: load input_model into the serving registry and run
    the scoring loop (lightgbm_tpu/serving, docs/SERVING.md). With
    serve_port=0 (default) speaks line-delimited JSON over
    stdin/stdout — one request per line, one response line each; with
    serve_port>0 runs the HTTP front end on that port. More models can
    be loaded/hot-swapped at runtime through the protocol's
    load/swap/rollback ops."""
    import jax

    from .config import Config
    from .serving import ModelRegistry, ScoringServer, serve_http

    t0 = time.time()
    cfg = Config(dict(params))
    model_path = params.get("input_model", "LightGBM_model.txt")
    if not Path(model_path).exists():
        log.fatal(f"input model {model_path} does not exist")
    prev_logger = (log._logger, log._info_method, log._warning_method,
                   log._debug_method)
    if cfg.serve_port == 0:
        # stdio mode: the protocol owns stdout — framework logs move to
        # stderr BEFORE anything (registry load, mesh setup) can emit,
        # so an info line can never corrupt a JSON response (restored on
        # exit: the logger is process-global state and in-process
        # callers must not inherit the reroute)
        class _StderrLogger:
            @staticmethod
            def info(msg: str) -> None:
                print(msg, file=sys.stderr, flush=True)

            warning = info

        log.register_logger(_StderrLogger)
    try:
        mesh = None
        if jax.device_count() > 1:
            from .parallel.data_parallel import make_mesh

            mesh = make_mesh(axis_name=cfg.tpu_mesh_axes.split(",")[0])
            log.info(
                f"serving rows sharded over {jax.device_count()} devices"
            )
        # chaos testing: a fault plan from config/env arms the
        # serve_request / device_put sites (docs/RESILIENCE.md)
        from .resilience import faultinject

        faultinject.configure(cfg.fault_plan)
        if cfg.serve_fleet:
            # multi-tenant fleet: capacity-bounded HBM residency with
            # LRU paging instead of a table set per model
            # (serving/fleet.py, docs/SERVING.md "Fleet serving")
            from .serving import ModelFleet

            registry = ModelFleet(
                mesh=mesh, buckets=cfg.serve_buckets,
                warmup=cfg.serve_warmup,
                deadline_s=cfg.serve_deadline_ms / 1000.0,
                queue_cap=cfg.serve_queue_cap,
                capacity=cfg.serve_fleet_capacity,
                slots_per_family=cfg.serve_fleet_slots,
            )
        else:
            registry = ModelRegistry(
                mesh=mesh, buckets=cfg.serve_buckets,
                warmup=cfg.serve_warmup,
                deadline_s=cfg.serve_deadline_ms / 1000.0,
                queue_cap=cfg.serve_queue_cap,
                replicas=cfg.serve_replicas,
            )
        registry.load(cfg.serve_model_name, model_path)
        if cfg.serve_port > 0:
            import signal
            import threading

            # SIGTERM = graceful drain: readiness flips false (the
            # gateway stops routing here), new POSTs shed 503
            # shutdown, in-flight requests finish (server_close joins
            # handler threads), then the process exits — the backend
            # half of tools/gateway_rolling.sh
            draining = threading.Event()  # lint: allow[per-call-lock] — one per process, shared with every handler thread
            httpd = serve_http(
                registry, cfg.serve_port, cfg.serve_host, block=False,
                socket_timeout_s=cfg.serve_socket_timeout_s,
                max_body_mb=cfg.serve_max_body_mb, draining=draining)

            def _drain(signum, frame):  # noqa: ARG001 — signal API
                draining.set()
                # shutdown() must run off the serve_forever thread
                threading.Thread(target=httpd.shutdown,
                                 daemon=True).start()

            try:
                signal.signal(signal.SIGTERM, _drain)
            except ValueError:
                pass  # not the main thread (in-process callers)
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                httpd.server_close()
        else:
            n = ScoringServer(registry).serve(sys.stdin, sys.stdout)
            print(f"[serve] handled {n} requests", file=sys.stderr)
        # summary logged HERE, while the stdio reroute is still
        # registered: in stdio mode the protocol owns stdout to EOF, so
        # main() must not append its own line after the logger restore
        log.info(f"Finished, elapsed {time.time()-t0:.2f} seconds")
    finally:
        (log._logger, log._info_method, log._warning_method,
         log._debug_method) = prev_logger


def _task_gateway(params: Dict[str, str]) -> None:
    """task=gateway: the resilient serving gateway
    (serving/gateway.py, docs/RESILIENCE.md "Serving gateway") — a
    host-side HTTP front end spreading traffic over the ``task=serve``
    backend processes named by ``gateway_backends=`` (comma-separated
    base URLs). Least-outstanding balancing over /readyz-passing
    backends, full-jitter retries and latency-triggered hedging for
    idempotent ops, per-backend circuit breakers, end-to-end deadline
    propagation, and SIGTERM graceful drain. ``GET /metrics`` serves
    the MERGED fleet exposition (gateway + every live backend)."""
    import signal
    import threading

    from .config import Config
    from .resilience import faultinject
    from .serving.gateway import Gateway, gateway_http

    t0 = time.time()
    cfg = Config(dict(params))
    # chaos testing: arm the gw_* sites before any request flows
    faultinject.configure(cfg.fault_plan)
    urls = [u.strip() for u in str(cfg.gateway_backends).split(",")
            if u.strip()]
    if not urls:
        log.fatal("task=gateway needs gateway_backends= "
                  "(comma-separated backend base URLs)")
    gw = Gateway(
        urls,
        retries=cfg.gateway_retries,
        backoff_base_s=cfg.gateway_backoff_base_s,
        hedge_quantile=cfg.gateway_hedge_quantile,
        hedge_budget=cfg.gateway_hedge_budget,
        breaker_failures=cfg.gateway_breaker_failures,
        breaker_cooldown_s=cfg.gateway_breaker_cooldown_s,
        default_deadline_ms=cfg.gateway_deadline_ms,
        health_interval_s=cfg.gateway_health_interval_s,
        attempt_timeout_s=cfg.serve_socket_timeout_s,
    )
    gw.start()
    httpd = gateway_http(
        gw, cfg.gateway_port, cfg.gateway_host, block=False,
        max_body_mb=cfg.serve_max_body_mb,
        socket_timeout_s=cfg.serve_socket_timeout_s)

    def _drain(signum, frame):  # noqa: ARG001 — signal API
        def _go() -> None:
            # deregister (readyz 503) + shed new work, finish
            # in-flight, then stop the listener
            gw.drain(cfg.gateway_drain_timeout_s)
            httpd.shutdown()

        threading.Thread(target=_go, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _drain)
    except ValueError:
        pass  # not the main thread (in-process callers)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        gw.stop()
        httpd.server_close()
    log.info(f"Finished, elapsed {time.time()-t0:.2f} seconds")


def _task_loop(params: Dict[str, str]) -> None:
    """task=loop: the online train-and-serve loop (lightgbm_tpu/online,
    docs/RESILIENCE.md "Online loop"). Serves the promoted model on the
    configured transport while verdict cycles refit / gate / promote
    from microbatches spooled through the ``ingest`` op. ``valid_data=``
    names the fixed holdout shard the gate judges on; v0 comes from
    ``input_model=`` if it exists, else is trained from ``data=``, and
    a loop_dir that already holds state resumes from it regardless."""
    import threading

    from .config import Config
    from .online import OnlineLoop, state_path
    from .resilience import faultinject
    from .serving import ModelRegistry, ScoringServer, serve_http

    t0 = time.time()
    cfg = Config(dict(params))
    # chaos testing: arm loop_* / serve_request sites before anything
    faultinject.configure(cfg.fault_plan)

    from .parsers import load_text_file

    vpath = str(params.get("valid_data", params.get("valid", ""))
                ).split(",")[0]
    if not vpath:
        log.fatal("task=loop needs valid_data= (the holdout shard the "
                  "promotion gate judges on)")
    loaded = load_text_file(
        vpath,
        header=str(params.get("header", "false")).lower() in ("true", "1"),
        label_column=params.get("label_column", 0),
        weight_column=params.get("weight_column", ""),
        group_column=params.get("group_column", ""),
        ignore_column=params.get("ignore_column", ""),
        categorical_feature=params.get("categorical_feature", ""),
    )
    holdout = (loaded["X"], loaded["label"], loaded["weight"])

    init_model = None
    if not Path(state_path(cfg.loop_dir)).exists():
        model_path = params.get("input_model", "")
        if model_path and Path(model_path).exists():
            init_model = model_path
        elif params.get("data"):
            from . import train as lgb_train

            ds = _load_dataset(params, params["data"])
            log.info(f"task=loop: training v0 from {params['data']}")
            init_model = lgb_train(dict(params), ds,
                                   num_boost_round=cfg.num_iterations)
        else:
            log.fatal("task=loop needs input_model= or data= to seed v0 "
                      "(or an existing loop_dir to resume)")

    loop = OnlineLoop(dict(params), holdout, initial_model=init_model)
    registry = ModelRegistry(
        buckets=cfg.serve_buckets, warmup=cfg.serve_warmup,
        deadline_s=cfg.serve_deadline_ms / 1000.0,
        queue_cap=cfg.serve_queue_cap, replicas=cfg.serve_replicas,
    )
    loop.attach(registry, cfg.serve_model_name)

    if cfg.serve_port > 0:
        httpd = serve_http(registry, cfg.serve_port, cfg.serve_host,
                           block=False)
        server_thread = threading.Thread(
            target=httpd.serve_forever, name="lgb-loop-http", daemon=True)
        server_thread.start()
        try:
            n = loop.run()
            log.info(f"task=loop: {n} verdict cycle(s) complete")
        finally:
            httpd.shutdown()
            httpd.server_close()
        log.info(f"Finished, elapsed {time.time()-t0:.2f} seconds")
        return

    # stdio mode: the JSONL protocol owns stdout to EOF (same logger
    # reroute as task=serve); the loop drives from a background thread
    # and stops when the request stream ends
    prev_logger = (log._logger, log._info_method, log._warning_method,
                   log._debug_method)

    class _StderrLogger:
        @staticmethod
        def info(msg: str) -> None:
            print(msg, file=sys.stderr, flush=True)

        warning = info

    log.register_logger(_StderrLogger)
    try:
        loop_thread = threading.Thread(
            target=loop.run, name="lgb-online-loop", daemon=True)
        loop_thread.start()
        n = ScoringServer(registry).serve(sys.stdin, sys.stdout)
        loop.stop_event.set()
        loop_thread.join(timeout=60.0)
        print(f"[loop] handled {n} requests", file=sys.stderr)
        log.info(f"Finished, elapsed {time.time()-t0:.2f} seconds")
    finally:
        (log._logger, log._info_method, log._warning_method,
         log._debug_method) = prev_logger


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    params = parse_kv_args(argv)
    # device_type=cpu (alias device=cpu, reference config.h device_type)
    # steers the run onto the CPU backend. Set at the jax-config level:
    # the ambient axon plugin force-sets jax_platforms at interpreter
    # start, so an env var cannot override it from outside.
    device = params.get("device_type", params.get("device", ""))
    if device == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    if not params:
        print(
            "usage: python -m lightgbm_tpu config=<file> [key=value ...]\n"
            "tasks: train (default), predict, save_binary, "
            "convert_model, refit, serve, gateway, loop",
            file=sys.stderr,
        )
        return 1
    task = params.get("task", "train")
    # ---- observability hooks (docs/OBSERVABILITY.md): runtime phase
    # timing, jax.profiler + span capture, and the run manifest
    def _truthy(v: Any) -> bool:
        return str(v).strip().lower() in ("true", "1", "yes", "on")

    if _truthy(params.get("timetag", "")):
        from .timer import enable_timetag

        enable_timetag()
    profile_dir = str(params.get("profile_dir", "")).strip()
    manifest_path = str(
        params.get("run_manifest", params.get("manifest_file", ""))
    ).strip()
    rec = None
    if profile_dir or manifest_path:
        # start compile-event counting now so the manifest's numbers
        # cover the whole run
        from .analysis.retrace import ensure_installed

        ensure_installed()
    if profile_dir:
        import jax

        from .obs import tracing

        os.makedirs(profile_dir, exist_ok=True)
        rec = tracing.start_tracing()
        try:
            jax.profiler.start_trace(profile_dir)
        except Exception as e:  # noqa: BLE001 — span capture still works
            log.warning(f"jax.profiler trace capture unavailable: {e}")
    t0 = time.time()
    try:
        if task == "train":
            _task_train(params)
        elif task in ("predict", "prediction", "test"):
            _task_predict(params)
        elif task == "save_binary":
            _task_save_binary(params)
        elif task == "convert_model":
            _task_convert_model(params)
        elif task in ("refit", "refit_tree"):
            _task_refit(params)
        elif task == "serve":
            _task_serve(params)  # logs its own protocol-safe summary
            return 0
        elif task == "gateway":
            _task_gateway(params)  # logs its own summary
            return 0
        elif task == "loop":
            _task_loop(params)  # logs its own protocol-safe summary
            return 0
        else:
            log.fatal(f"Unknown task {task}")
        log.info(f"Finished, elapsed {time.time()-t0:.2f} seconds")
        return 0
    finally:
        # export failures must never mask the task's own error; and
        # after task=serve the stdio protocol has owned stdout to EOF —
        # export log lines go to stderr so a strict JSONL consumer
        # never sees a non-JSON line on the response stream
        prev_logger = None
        if task in ("serve", "gateway", "loop") \
                and (profile_dir or manifest_path):
            prev_logger = (log._logger, log._info_method,
                           log._warning_method, log._debug_method)

            class _ExportStderrLogger:
                @staticmethod
                def info(msg: str) -> None:
                    print(msg, file=sys.stderr, flush=True)

                warning = info

            log.register_logger(_ExportStderrLogger)
        try:
            if profile_dir:
                import jax

                from .obs import tracing

                try:
                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 — trace may not have started
                    pass
                tracing.stop_tracing()
                if rec is not None:
                    try:
                        rec.write_chrome(
                            os.path.join(profile_dir, "trace_events.json")
                        )
                        rec.write_jsonl(
                            os.path.join(profile_dir, "trace_events.jsonl")
                        )
                    except OSError as e:
                        log.warning(f"trace export failed: {e}")
            if profile_dir or manifest_path:
                try:
                    from .config import Config
                    from .obs.manifest import write_manifest

                    cfg = Config(dict(params))
                    targets = [p for p in (
                        manifest_path,
                        os.path.join(profile_dir, "run_manifest.json")
                        if profile_dir else "",
                    ) if p]
                    for p in targets:
                        write_manifest(p, config=cfg, extra={"task": task})
                except Exception as e:  # noqa: BLE001 — incl. config fatals
                    log.warning(f"run manifest not written: {e}")
        finally:
            if prev_logger is not None:
                (log._logger, log._info_method, log._warning_method,
                 log._debug_method) = prev_logger


if __name__ == "__main__":
    sys.exit(main())
