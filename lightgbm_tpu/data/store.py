"""Disk-backed chunked columnar store (docs/DATA_PLANE.md "Chunk
format").

A spool directory holds fixed-row-count chunks of feature columns:

    spool/
      manifest.json          # atomic (tmp + fsync + os.replace)
      chunk_000000.npz       # "cols" (F, rows) + optional 1-D metadata
      chunk_000001.npz
      ...

Durability contract (the resilience/checkpoint.py pattern applied to
bulk data): a chunk is written to ``<name>.tmp``, fsynced, verified by
re-read (byte size + crc32), atomically renamed, and only THEN listed
in the manifest — which is itself rewritten atomically after every
commit. kill -9 at any instant leaves either a complete committed
prefix (resumable via :meth:`ChunkStore.resume`) or an ignored ``.tmp``
straggler; it never leaves a chunk the manifest believes in but the
disk does not have. Reads re-verify size + crc before deserializing,
so a truncated or bit-flipped chunk fails loudly with its chunk index
and byte offset instead of feeding garbage into binning.

Chunks are columnar ((F, rows), features major) so pass-2 binning
reads each feature as one contiguous row — the transpose happens once
at spool time, not once per feature per pass.
"""

from __future__ import annotations

import os
import zlib
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from .. import log
from ..resilience.checkpoint import atomic_write_json

MANIFEST_SCHEMA = "lightgbm-tpu/chunk-store/v1"
MANIFEST_NAME = "manifest.json"
DEFAULT_CHUNK_ROWS = 65536

# optional per-row metadata arrays carried alongside the feature chunk
# (O(N) scalars; the reference's Metadata columns)
_META_KEYS = ("label", "weight", "init_score", "position", "qid")


class ChunkStoreError(Exception):
    """Malformed spool directory / misuse of the store API."""


class ChunkIntegrityError(ChunkStoreError):
    """A chunk file failed size/crc verification — fails the read
    loudly with chunk index + byte offset, never feeds garbage on."""


def _crc_and_size(path: Path) -> Tuple[int, int]:
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            crc = zlib.crc32(block, crc)
            size += len(block)
    return crc & 0xFFFFFFFF, size


class ChunkStore:
    """One spool directory of fixed-row-count columnar chunks.

    ``kind`` is "raw" (float feature columns, pre-binning) or "binned"
    ((G, rows) packed bin columns, the pass-2 output). The row count of
    every chunk except the last equals ``chunk_rows`` — readers derive
    global row offsets from that invariant (and the manifest records
    ``row0`` per chunk explicitly as a cross-check).
    """

    def __init__(self, root: Path, manifest: Dict[str, Any],
                 writable: bool = False):
        self.root = Path(root)
        self.manifest = manifest
        self.writable = writable
        self._buf: List[Dict[str, np.ndarray]] = []
        self._buf_rows = 0

    # ------------------------------------------------------------ open
    @classmethod
    def create(cls, root, n_features: int, chunk_rows: int = 0,
               kind: str = "raw", value_dtype: str = "float64",
               feature_names: Optional[List[str]] = None,
               extra: Optional[Dict[str, Any]] = None) -> "ChunkStore":
        root = Path(root)
        root.mkdir(parents=True, exist_ok=True)
        mpath = root / MANIFEST_NAME
        if mpath.exists():
            raise ChunkStoreError(
                f"refusing to create over an existing spool at {root} "
                "(open/resume it, or point data_spool_dir elsewhere)"
            )
        manifest = {
            "schema": MANIFEST_SCHEMA,
            "kind": kind,
            "n_features": int(n_features),
            "chunk_rows": int(chunk_rows or DEFAULT_CHUNK_ROWS),
            "value_dtype": value_dtype,
            "feature_names": list(feature_names or []),
            "total_rows": 0,
            "complete": False,
            "chunks": [],
            "extra": dict(extra or {}),
        }
        store = cls(root, manifest, writable=True)
        store._write_manifest()
        return store

    @classmethod
    def open(cls, root) -> "ChunkStore":
        root = Path(root)
        mpath = root / MANIFEST_NAME
        if not mpath.exists():
            raise ChunkStoreError(f"no chunk-store manifest at {mpath}")
        import json

        manifest = json.loads(mpath.read_text())
        if manifest.get("schema") != MANIFEST_SCHEMA:
            raise ChunkStoreError(
                f"{mpath}: schema {manifest.get('schema')!r} is not "
                f"{MANIFEST_SCHEMA!r}"
            )
        return cls(root, manifest, writable=False)

    @classmethod
    def resume(cls, root) -> "ChunkStore":
        """Reopen an interrupted spool for appending: the committed
        chunk prefix is kept, ``.tmp`` stragglers from the crashed
        writer are discarded, and the caller continues from
        ``total_rows``."""
        store = cls.open(root)
        if store.manifest["complete"]:
            raise ChunkStoreError(
                f"spool at {root} is already finalized; nothing to resume"
            )
        for straggler in store.root.glob("*.tmp"):
            log.warning(
                f"chunk store {store.root}: discarding uncommitted "
                f"{straggler.name} left by an interrupted writer"
            )
            straggler.unlink()
        store.writable = True
        return store

    # ------------------------------------------------------ properties
    @property
    def kind(self) -> str:
        return self.manifest["kind"]

    @property
    def n_features(self) -> int:
        return int(self.manifest["n_features"])

    @property
    def chunk_rows(self) -> int:
        return int(self.manifest["chunk_rows"])

    @property
    def total_rows(self) -> int:
        return int(self.manifest["total_rows"])

    @property
    def num_chunks(self) -> int:
        return len(self.manifest["chunks"])

    @property
    def complete(self) -> bool:
        return bool(self.manifest["complete"])

    def spool_bytes(self) -> int:
        return sum(int(c["bytes"]) for c in self.manifest["chunks"])

    def has_meta(self, key: str) -> bool:
        return any(key in c.get("keys", ()) for c in self.manifest["chunks"])

    # --------------------------------------------------------- writing
    def _chunk_path(self, idx: int) -> Path:
        return self.root / f"chunk_{idx:06d}.npz"

    def _write_manifest(self) -> None:
        atomic_write_json(str(self.root / MANIFEST_NAME), self.manifest)

    def _commit_chunk(self, arrays: Dict[str, np.ndarray], rows: int) -> None:
        idx = self.num_chunks
        path = self._chunk_path(idx)
        tmp = Path(str(path) + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        crc, size = _crc_and_size(tmp)
        os.replace(tmp, path)
        dir_fd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self.manifest["chunks"].append({
            "file": path.name,
            "row0": self.total_rows,
            "rows": int(rows),
            "bytes": int(size),
            "crc32": int(crc),
            "keys": sorted(arrays),
        })
        self.manifest["total_rows"] = self.total_rows + int(rows)
        self._write_manifest()

    def append_rows(self, X: np.ndarray, **meta: Optional[np.ndarray]
                    ) -> None:
        """Append a (rows, F) row-major block (plus aligned 1-D metadata
        arrays from ``label/weight/init_score/position/qid``). Blocks
        are re-cut to the store's fixed chunk_rows internally; at most
        one chunk of rows is ever buffered in memory."""
        if not self.writable:
            raise ChunkStoreError("store opened read-only")
        if self.complete:
            raise ChunkStoreError("store already finalized")
        X = np.asarray(X)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[1] != self.n_features:
            raise ChunkStoreError(
                f"block has {X.shape[1]} features, store has "
                f"{self.n_features}"
            )
        bad = set(meta) - set(_META_KEYS)
        if bad:
            raise ChunkStoreError(f"unknown metadata keys {sorted(bad)}")
        entry = {"X": X}
        for k, v in meta.items():
            if v is None:
                continue
            v = np.asarray(v).ravel()
            if len(v) != X.shape[0]:
                raise ChunkStoreError(
                    f"metadata {k!r} has {len(v)} rows, block has "
                    f"{X.shape[0]}"
                )
            entry[k] = v
        self._buf.append(entry)
        self._buf_rows += X.shape[0]
        while self._buf_rows >= self.chunk_rows:
            self._flush_chunk(self.chunk_rows)

    def append_binned(self, bins: np.ndarray) -> None:
        """Append one pre-cut (G, rows) binned chunk verbatim (pass 2
        keeps raw-chunk boundaries, so no re-cutting is needed)."""
        if not self.writable:
            raise ChunkStoreError("store opened read-only")
        if self.kind != "binned":
            raise ChunkStoreError("append_binned on a non-binned store")
        self._commit_chunk({"bins": np.ascontiguousarray(bins)},
                           bins.shape[1])

    def _flush_chunk(self, rows: int) -> None:
        """Cut exactly `rows` rows off the buffer into one committed
        chunk (columnar)."""
        take: List[Dict[str, np.ndarray]] = []
        need = rows
        while need > 0:
            entry = self._buf[0]
            n = entry["X"].shape[0]
            if n <= need:
                take.append(self._buf.pop(0))
                need -= n
            else:
                take.append({k: v[:need] for k, v in entry.items()})
                self._buf[0] = {k: v[need:] for k, v in entry.items()}
                need = 0
        self._buf_rows -= rows
        X = (take[0]["X"] if len(take) == 1
             else np.concatenate([t["X"] for t in take], axis=0))
        arrays: Dict[str, np.ndarray] = {
            # columnar: features major, rows on the contiguous axis
            "cols": np.ascontiguousarray(X.T),
        }
        for k in _META_KEYS:
            if any(k in t for t in take):
                if not all(k in t for t in take):
                    raise ChunkStoreError(
                        f"metadata {k!r} supplied for some appended "
                        "blocks but not others"
                    )
                arrays[k] = np.concatenate([t[k] for t in take])
        self._commit_chunk(arrays, rows)

    def finalize(self) -> "ChunkStore":
        """Flush the tail chunk and mark the spool complete."""
        if not self.writable:
            raise ChunkStoreError("store opened read-only")
        if self._buf_rows:
            self._flush_chunk(self._buf_rows)
        self.manifest["complete"] = True
        self._write_manifest()
        return self

    # --------------------------------------------------------- reading
    def chunk_meta(self, idx: int) -> Dict[str, Any]:
        return self.manifest["chunks"][idx]

    def read_chunk(self, idx: int) -> Dict[str, np.ndarray]:
        """Read + verify one chunk. Size and crc32 are checked against
        the manifest BEFORE deserializing; failures raise
        :class:`ChunkIntegrityError` naming the chunk index and the
        byte offset where the file stops matching expectations."""
        meta = self.chunk_meta(idx)
        path = self.root / meta["file"]
        if not path.exists():
            raise ChunkIntegrityError(
                f"chunk {idx} ({path}) is missing from the spool "
                f"(manifest expects {meta['bytes']} bytes)"
            )
        actual = path.stat().st_size
        expected = int(meta["bytes"])
        if actual != expected:
            raise ChunkIntegrityError(
                f"chunk {idx} ({path}) truncated/corrupt at byte "
                f"offset {min(actual, expected)}: expected {expected} "
                f"bytes, found {actual}"
            )
        crc, _size = _crc_and_size(path)
        if crc != int(meta["crc32"]):
            raise ChunkIntegrityError(
                f"chunk {idx} ({path}) corrupt: crc32 {crc:#010x} != "
                f"manifest {int(meta['crc32']):#010x} over byte offsets "
                f"[0, {expected})"
            )
        try:
            with np.load(path, allow_pickle=False) as z:
                return {k: z[k] for k in z.files}
        except Exception as e:  # noqa: BLE001 — degrade to the loud path
            raise ChunkIntegrityError(
                f"chunk {idx} ({path}) passed crc but failed to "
                f"deserialize: {e}"
            ) from e

    def iter_chunks(self) -> Iterator[Tuple[int, int, Dict[str, np.ndarray]]]:
        """Yield (chunk_idx, row0, arrays) sequentially. Exactly one
        chunk's arrays are referenced by the iterator at a time."""
        for idx in range(self.num_chunks):
            meta = self.chunk_meta(idx)
            yield idx, int(meta["row0"]), self.read_chunk(idx)

    def gather_meta(self, key: str) -> Optional[np.ndarray]:
        """Concatenate one per-row metadata column across chunks
        (labels/weights are O(N) scalars — in-RAM by design, matching
        the reference's metadata handling)."""
        if not self.has_meta(key):
            return None
        parts = []
        for idx in range(self.num_chunks):
            arrays = self.read_chunk(idx)
            if key not in arrays:
                raise ChunkStoreError(
                    f"metadata {key!r} present in some chunks but "
                    f"missing from chunk {idx}"
                )
            parts.append(arrays[key])
        return np.concatenate(parts)


class SpooledData:
    """Handle to a raw spool that flows through the Dataset/sklearn API
    in place of a numpy matrix (dask.py routes partitions into one of
    these; basic.Dataset.construct recognizes it and takes the chunked
    path without ever concatenating on the host)."""

    def __init__(self, store: ChunkStore):
        if store.kind != "raw":
            raise ChunkStoreError("SpooledData wraps a raw store")
        self.store = store

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.store.total_rows, self.store.n_features)

    def __len__(self) -> int:
        return self.store.total_rows


# ---------------------------------------------------------------------------
# spoolers: numpy / row-block iterators / delimited text
# ---------------------------------------------------------------------------
def spool_numpy(X: np.ndarray, root, chunk_rows: int,
                **meta: Optional[np.ndarray]) -> ChunkStore:
    """Spool an in-RAM matrix chunk-wise (slices, no copy of X)."""
    X = np.asarray(X)
    if X.dtype not in (np.float32, np.float64):
        X = X.astype(np.float64)
    store = ChunkStore.create(
        root, n_features=X.shape[1], chunk_rows=chunk_rows,
        value_dtype=str(X.dtype),
    )
    for lo in range(0, X.shape[0], chunk_rows):
        sl = slice(lo, lo + chunk_rows)
        store.append_rows(
            X[sl], **{k: (None if v is None else np.asarray(v)[sl])
                      for k, v in meta.items()},
        )
    return store.finalize()


def spool_blocks(blocks: Iterable[np.ndarray], root, chunk_rows: int,
                 n_features: Optional[int] = None) -> ChunkStore:
    """Spool any iterator of (rows, F) blocks. n_features is taken from
    the first block when not given."""
    it = iter(blocks)
    store: Optional[ChunkStore] = None
    for block in it:
        block = np.asarray(block)
        if block.ndim == 1:
            block = block.reshape(1, -1)
        if store is None:
            store = ChunkStore.create(
                root,
                n_features=(n_features if n_features is not None
                            else block.shape[1]),
                chunk_rows=chunk_rows,
            )
        store.append_rows(block)
    if store is None:
        raise ChunkStoreError("cannot spool an empty block iterator")
    return store.finalize()


def spool_text_file(path, root, chunk_rows: int, *,
                    header: bool = False, label_column: Any = 0,
                    weight_column: Any = "", group_column: Any = "",
                    ignore_column: Any = "",
                    ) -> Tuple[ChunkStore, List[str]]:
    """Spool a delimited text file (CSV/TSV) through the parsers'
    sequential chunk reader: one pass, host memory O(chunk). Label /
    weight / query columns land as per-chunk metadata arrays. Returns
    (finalized store, feature names). LibSVM is not supported on this
    path (the caller falls back to the whole-file loader)."""
    from ..parsers import (
        _read_lines,
        _resolve_column,
        _resolve_columns,
        detect_format,
        iter_text_chunks,
    )

    p = Path(path)
    if not p.exists():
        log.fatal(f"data file {path} does not exist")
    sample_lines = _read_lines(p, 5)
    fmt = detect_format(
        sample_lines[1:] if header and len(sample_lines) > 1
        else sample_lines
    )
    if fmt == "libsvm":
        raise ChunkStoreError(
            "chunked spooling supports delimited formats; LibSVM needs "
            "the whole-file loader"
        )
    delim = "\t" if fmt == "tsv" else ","
    names: List[str] = []
    skip = 0
    if header:
        names = [c.strip() for c in sample_lines[0].split(delim)]
        skip = 1
    ncol = len(sample_lines[skip].split(delim))
    lbl_idx = _resolve_column(label_column, names)
    w_idx = _resolve_column(weight_column, names)
    g_idx = _resolve_column(group_column, names)
    ign = set(_resolve_columns(ignore_column, names))
    drop = {i for i in (lbl_idx, w_idx, g_idx) if i is not None} | ign
    keep = [i for i in range(ncol) if i not in drop]
    feat_names = [names[i] for i in keep] if names else []

    store = ChunkStore.create(
        root, n_features=len(keep), chunk_rows=chunk_rows,
        feature_names=feat_names,
        extra={"source": str(p)},
    )
    for chunk in iter_text_chunks(p, delim, skip, chunk_rows):
        store.append_rows(
            chunk[:, keep],
            label=chunk[:, lbl_idx] if lbl_idx is not None else None,
            weight=chunk[:, w_idx] if w_idx is not None else None,
            qid=chunk[:, g_idx] if g_idx is not None else None,
        )
    return store.finalize(), feat_names
