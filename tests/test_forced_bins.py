"""forcedbins_filename: user-forced bin boundaries (reference
src/io/dataset_loader.cpp GetForcedBins + bin.cpp forced-bounds path)
must actually change bin-edge construction — the key was accepted but
unwired before this test existed (VERDICT r5 missing #2)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import (
    BinMapper,
    find_bin_bounds_forced,
    load_forced_bins,
)
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.log import LightGBMError


def _write(tmp_path, entries):
    p = tmp_path / "forced.json"
    p.write_text(json.dumps(entries))
    return str(p)


def test_forced_bounds_change_bin_edges(tmp_path, rng):
    X = rng.randn(3000, 3)
    path = _write(tmp_path, [
        {"feature": 0, "bin_upper_bound": [-0.5, 0.0, 0.5]},
    ])
    plain = BinnedDataset.from_numpy(X, Config({"max_bin": 16}))
    forced = BinnedDataset.from_numpy(
        X, Config({"max_bin": 16, "forcedbins_filename": path})
    )
    ub = forced.mappers[0].upper_bounds
    for b in (-0.5, 0.0, 0.5):
        assert np.any(np.isclose(ub, b)), (b, ub)
    assert not np.array_equal(plain.mappers[0].upper_bounds, ub)
    # untouched features bin identically
    np.testing.assert_array_equal(
        plain.mappers[1].upper_bounds, forced.mappers[1].upper_bounds
    )
    # the forced edge really partitions: values either side of 0.5 land
    # in different bins
    m = forced.mappers[0]
    lo, hi = m.values_to_bins(np.asarray([0.499])), \
        m.values_to_bins(np.asarray([0.501]))
    assert lo[0] != hi[0]


def test_forced_bounds_respect_max_bin(rng):
    vals = rng.randn(5000)
    bounds = find_bin_bounds_forced(vals, 5000, 8, 3,
                                    [-1.0, -0.5, 0.0, 0.5, 1.0])
    assert len(bounds) <= 8
    assert np.isposinf(bounds[-1])
    for b in (-1.0, -0.5, 0.0, 0.5, 1.0):
        assert any(np.isclose(bounds, b)), bounds
    assert bounds == sorted(bounds)


def test_forced_bins_with_nan_missing(rng):
    vals = rng.randn(2000)
    vals[rng.rand(2000) < 0.1] = np.nan
    m = BinMapper.from_sample(vals, 2000, max_bin=16, forced_bounds=[0.0])
    assert any(np.isclose(m.upper_bounds, 0.0))
    # NaN bin still reserved on top
    assert m.nan_bin == m.num_bin - 1


def test_forced_bins_end_to_end_training(tmp_path, rng):
    X = rng.randn(2000, 3)
    y = (X[:, 0] > 0.25).astype(float)
    path = _write(tmp_path, [{"feature": 0, "bin_upper_bound": [0.25]}])
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "forcedbins_filename": path, "max_bin": 8},
        lgb.Dataset(X, label=y, free_raw_data=False),
        num_boost_round=5,
    )
    # with the true decision boundary forced as a bin edge, the first
    # split threshold can sit exactly on it
    thresholds = np.concatenate(
        [t.threshold[t.decision_type == 0] for t in bst._gbdt.models]
    )
    assert np.any(np.isclose(thresholds, 0.25, atol=1e-12)), thresholds
    from sklearn.metrics import roc_auc_score

    assert roc_auc_score(y, bst.predict(X)) > 0.95


def test_forced_bins_file_errors(tmp_path):
    with pytest.raises(LightGBMError):
        load_forced_bins(str(tmp_path / "nope.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(LightGBMError):
        load_forced_bins(str(bad))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps([
        {"feature": 0, "bin_upper_bound": [1.0]},
        {"feature": 99, "bin_upper_bound": [1.0]},  # out of range: skip
        {"bin_upper_bound": [1.0]},  # malformed: skip
    ]))
    out = load_forced_bins(str(ok), num_total_features=3)
    assert out == {0: [1.0]}


def test_unwired_params_warn():
    """The accepted-but-unwired sweep (VERDICT r5 missing #2): params
    with no effect in this build must WARN when set away from their
    inactive value, and every _UNIMPLEMENTED entry must really be
    unreferenced outside config.py."""
    import os
    import re

    from lightgbm_tpu import log
    from lightgbm_tpu.config import _UNIMPLEMENTED, warn_unimplemented

    msgs = []

    class _Cap:
        @staticmethod
        def info(m):
            msgs.append(m)

        warning = info

    log.register_logger(_Cap)
    try:
        warn_unimplemented(Config({"force_col_wise": True, "num_gpu": 4}))
    finally:
        log._logger = None  # restore the default print logger
    assert any("force_col_wise" in m for m in msgs)
    assert any("num_gpu" in m for m in msgs)

    # the sweep itself: no _UNIMPLEMENTED key is referenced in package
    # code outside config.py (if one becomes wired, drop it there)
    import lightgbm_tpu

    pkg = os.path.dirname(lightgbm_tpu.__file__)
    sources = []
    for root, _dirs, files in os.walk(pkg):
        for f in files:
            if f.endswith(".py") and f != "config.py":
                sources.append(open(os.path.join(root, f)).read())
    blob = "\n".join(sources)
    for name, _inactive, _why in _UNIMPLEMENTED:
        assert not re.search(rf"\b(cfg|config|c)\.{name}\b", blob), (
            f"{name} is referenced in package code but listed as "
            "unimplemented"
        )


def test_forced_bins_sparse_implicit_zero_mass(rng):
    """The sparse path samples only EXPLICIT values; the implicit-zero
    mass (total_sample_cnt - len(values)) must still count toward
    forced-segment budgets — and toward the greedy packer's totals —
    or a 90%-zero feature bins from 10% of its data."""
    import scipy.sparse as sp

    from lightgbm_tpu.binning import find_bin_bounds_forced

    explicit = rng.uniform(1.0, 5.0, 100)
    bounds = find_bin_bounds_forced(explicit, 1000, 16, 3, [0.5])
    # the zero-containing segment (-inf, 0.5] holds 900 of 1000 samples
    # even though `values` has none: it must still get real budget, and
    # 0.5 stays a bin edge
    assert any(np.isclose(bounds, 0.5))
    # the nonzero segment cannot eat nearly the whole ladder: its share
    # is ~100/1000 of the remaining budget
    above = [b for b in bounds if b > 0.5 and np.isfinite(b)]
    assert len(above) <= 4, bounds

    # end to end through the CSR constructor
    import json
    import tempfile

    X = sp.random(2000, 3, density=0.1, random_state=1,
                  data_rvs=lambda n: rng.uniform(1, 5, n)).tocsr()
    fb = tempfile.NamedTemporaryFile("w", suffix=".json", delete=False)
    json.dump([{"feature": 0, "bin_upper_bound": [0.5]}], fb)
    fb.close()
    ds = lgb.Dataset(X, label=rng.randn(2000), free_raw_data=False,
                     params={"forcedbins_filename": fb.name,
                             "max_bin": 16})
    ds.construct()
    assert any(np.isclose(ds._binned.mappers[0].upper_bounds, 0.5))


def test_forced_bins_non_list_json_is_fatal(tmp_path):
    bad = tmp_path / "obj.json"
    bad.write_text(json.dumps({"feature": 0, "bin_upper_bound": [1.0]}))
    with pytest.raises(LightGBMError):
        load_forced_bins(str(bad))
