"""Durable microbatch spool between the serving transports and the loop.

The serving ``ingest`` op (serving/server.py) appends labeled
microbatches here; the refit side consumes them from a byte offset the
loop checkpoints (online/state.py). The spool is the loop's write-ahead
log: one JSON line per microbatch, appended with flush + fsync, so an
accepted batch (the op replied ok) survives a SIGKILL and is either
consumed by exactly one verdict or replayed after a crash — offsets
only advance inside the loop's atomic state write.

Torn tails are the reader's problem by design: a crash mid-append can
leave a partial last line, and ``read_from`` stops at the last COMPLETE
line without advancing past the tear (the next append re-extends the
file; the partial line is never parsed because appends are atomic at
the OS level only for short writes, which we do not rely on).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics

SPOOL_NAME = "ingest.jsonl"


def spool_path(loop_dir: str) -> str:
    return os.path.join(loop_dir, SPOOL_NAME)


class IngestSpool:
    """Append-only JSONL microbatch spool; thread-safe (the append side
    runs on serving request threads, the read side on the loop)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    # -------------------------------------------------------------- write
    def append(self, rows: List[List[float]], labels: List[float],
               weights: Optional[List[float]] = None) -> Dict[str, Any]:
        """Validate + durably append one microbatch; returns
        ``{"rows": n, "offset": end}`` (end = spool size after the
        append, the offset a consumer would resume from)."""
        n = len(rows)
        if n == 0:
            raise ValueError("ingest: empty microbatch")
        if len(labels) != n:
            raise ValueError(
                f"ingest: {n} rows but {len(labels)} labels"
            )
        width = len(rows[0])
        for r in rows:
            if len(r) != width:
                raise ValueError("ingest: ragged rows in microbatch")
        batch: Dict[str, Any] = {
            "rows": [[float(v) for v in r] for r in rows],
            "labels": [float(v) for v in labels],
        }
        if weights is not None:
            if len(weights) != n:
                raise ValueError(
                    f"ingest: {n} rows but {len(weights)} weights"
                )
            batch["weights"] = [float(v) for v in weights]
        line = json.dumps(batch) + "\n"
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line)
                f.flush()
                os.fsync(f.fileno())
                end = f.tell()
        obs_metrics.record_ingest(n)
        return {"rows": n, "offset": int(end)}

    # --------------------------------------------------------------- read
    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def read_from(self, offset: int) -> Tuple[List[Dict[str, Any]], int]:
        """All complete microbatches at byte ``offset`` onward, plus the
        offset after the last complete line (the next resume point). A
        torn tail (crash mid-append) is left unconsumed."""
        batches: List[Dict[str, Any]] = []
        end = int(offset)
        try:
            with open(self.path, "rb") as f:
                f.seek(int(offset))
                data = f.read()
        except OSError:
            return batches, end
        pos = 0
        while True:
            nl = data.find(b"\n", pos)
            if nl < 0:
                break  # incomplete tail — not consumed
            line = data[pos:nl]
            pos = nl + 1
            if not line.strip():
                end = int(offset) + pos
                continue
            try:
                batch = json.loads(line)
            except json.JSONDecodeError:
                # a torn line followed by a newline can only come from
                # writes outside this class; stop rather than skip data
                break
            batches.append(batch)
            end = int(offset) + pos
        return batches, end


def stack_batches(batches: List[Dict[str, Any]]):
    """Concatenate spool batches into (X, y, w) numpy arrays (w is None
    when no batch carried weights; batches with and without weights mix
    as weight-1 rows)."""
    import numpy as np

    xs, ys, ws = [], [], []
    any_w = any("weights" in b for b in batches)
    for b in batches:
        xs.append(np.asarray(b["rows"], dtype=np.float64))
        ys.append(np.asarray(b["labels"], dtype=np.float64))
        if any_w:
            ws.append(np.asarray(
                b.get("weights", [1.0] * len(b["labels"])),
                dtype=np.float64))
    X = np.concatenate(xs, axis=0)
    y = np.concatenate(ys, axis=0)
    w = np.concatenate(ws, axis=0) if any_w else None
    return X, y, w
