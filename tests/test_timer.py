"""Phase-timer subsystem (reference USE_TIMETAG, utils/common.h:979)."""

from __future__ import annotations

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.timer import Timer, global_timer


def test_timer_accumulates_and_summarizes():
    t = Timer()
    t.enabled = True
    with t.scope("phase a"):
        pass
    with t.scope("phase a"):
        pass
    with t.scope("phase b", block=True):
        pass
    s = t.summary()
    assert s["phase a"][1] == 2
    assert s["phase b"][1] == 1
    assert all(v[0] >= 0 for v in s.values())
    t.reset()
    assert not t.summary()


def test_training_records_phases(capsys):
    was = global_timer.enabled
    global_timer.enabled = True
    global_timer.reset()
    try:
        rs = np.random.RandomState(0)
        X = rs.randn(600, 4)
        y = (X[:, 0] > 0).astype(float)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                  ds, num_boost_round=3)
        s = global_timer.summary()
        assert "dataset construct (binning)" in s
        assert any("dispatch" in k for k in s)
        global_timer.print_summary()
    finally:
        global_timer.enabled = was
        global_timer.reset()
