"""EFB feature bundling (reference dataset.cpp:111 FindGroups,
:250 FastFeatureBundling)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.bundling import bundle_features, find_groups
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset


def _sparse_onehotish(n=4000, blocks=4, width=8, seed=3):
    """Blocks of mutually-exclusive columns (one-hot) + 2 dense columns."""
    rs = np.random.RandomState(seed)
    cols = []
    for b in range(blocks):
        z = np.zeros((n, width))
        idx = rs.randint(0, width, n)
        z[np.arange(n), idx] = rs.rand(n) + 0.5
        # sparsify: most rows all-zero in this block
        on = rs.rand(n) < 0.25
        z[~on] = 0.0
        cols.append(z)
    dense = rs.randn(n, 2)
    X = np.hstack([dense] + cols)
    w = rs.randn(X.shape[1])
    y = (X @ w + 0.3 * rs.randn(n) > 0).astype(np.float64)
    return X, y


def test_find_groups_merges_exclusive():
    # three perfectly exclusive sparse features -> one group
    n = 10000
    rs = np.random.RandomState(0)
    owner = rs.randint(0, 3, n)
    bins = np.zeros((3, n), dtype=np.int32)
    for f in range(3):
        bins[f, owner == f] = rs.randint(1, 5, int((owner == f).sum()))
    groups = find_groups(bins, [5, 5, 5], [0, 0, 0], [False] * 3, 256)
    assert len(groups) == 1
    assert sorted(groups[0]) == [0, 1, 2]


def test_find_groups_keeps_dense_apart():
    n = 5000
    rs = np.random.RandomState(1)
    bins = rs.randint(0, 10, (2, n)).astype(np.int32)  # dense everywhere
    groups = find_groups(bins, [10, 10], [0, 0], [False, False], 256)
    assert len(groups) == 2


def test_bundle_roundtrip_exact():
    """Merged columns decode back to the original bins exactly when
    conflicts are zero."""
    n = 8000
    rs = np.random.RandomState(2)
    owner = rs.randint(0, 4, n)
    X = np.zeros((n, 4))
    for f in range(4):
        m = owner == f
        X[m, f] = rs.rand(int(m.sum())) * 3 + 0.5
    y = (X.sum(1) > 1.0).astype(np.float64)
    cfg = Config({"max_bin": 63})
    ds = BinnedDataset.from_numpy(X, cfg, label=y)
    assert ds.bundle_layout is not None
    assert ds.bins.shape[0] < 4

    import jax.numpy as jnp

    from lightgbm_tpu.learner.bundle import decode_feature_bins

    binfo = ds._bundle_info()
    merged = jnp.asarray(ds.bins.astype(np.int32))
    # re-bin each original feature and compare with the decode
    nobundle = BinnedDataset.from_numpy(
        X, Config({"max_bin": 63, "enable_bundle": False}), label=y
    )
    for i in range(4):
        col = merged[int(binfo.bundle_of[i])]
        dec = np.asarray(decode_feature_bins(col, jnp.int32(i), binfo))
        np.testing.assert_array_equal(dec, nobundle.bins[i])


def test_efb_training_matches_unbundled():
    X, y = _sparse_onehotish()
    params = dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
                  learning_rate=0.2, verbosity=-1, metric="binary_logloss")
    preds = {}
    for bundle in (True, False):
        p = dict(params, enable_bundle=bundle)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(p, ds, num_boost_round=10)
        preds[bundle] = bst.predict(X)
    # conflict-free bundling is structurally exact; leaf values differ
    # only by f32 summation order (the most-freq bin is recovered by
    # subtraction, expand_hist) — same splits, near-identical predictions
    np.testing.assert_allclose(preds[True], preds[False], rtol=2e-3, atol=2e-4)


def test_efb_valid_set_and_model_io(tmp_path):
    Xall, yall = _sparse_onehotish(n=6000, seed=5)
    X, y = Xall[:4000], yall[:4000]
    Xv, yv = Xall[4000:], yall[4000:]
    params = dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
                  verbosity=-1, metric="auc")
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    vs = lgb.Dataset(Xv, label=yv, reference=ds, free_raw_data=False)
    rec = {}
    bst = lgb.train(
        params, ds, num_boost_round=8, valid_sets=[vs], valid_names=["v"],
        callbacks=[lgb.record_evaluation(rec)],
    )
    assert rec["v"]["auc"][-1] > 0.7
    path = str(tmp_path / "efb_model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(
        bst.predict(Xv), bst2.predict(Xv), rtol=1e-6, atol=1e-7
    )


def test_find_groups_cat_founded_group_stays_dedicated():
    """A sparse NUMERIC feature must not merge into a group founded by a
    categorical feature (ADVICE r3): build_layout would offset-encode
    the categorical column, breaking bin==category identity."""
    n = 10000
    rs = np.random.RandomState(1)
    owner = rs.randint(0, 2, n)
    bins = np.zeros((2, n), dtype=np.int32)
    # feature 0: sparse categorical; feature 1: sparse numeric, exclusive
    bins[0, owner == 0] = rs.randint(1, 6, int((owner == 0).sum()))
    bins[1, owner == 1] = rs.randint(1, 6, int((owner == 1).sum()))
    groups = find_groups(bins, [6, 6], [0, 0], [True, False], 256)
    for g in groups:
        if 0 in g:
            assert g == [0], f"categorical group was merged into: {g}"
