"""XLA cost/memory auditor + collective wire-bytes accounting
(analysis/cost_audit.py): wire accounting red-to-green on deliberately
widened payloads, budget contracts, the budget/entry consistency
meta-tests, and the pass registry the --strict gate runs."""

import json
from pathlib import Path

import pytest

from lightgbm_tpu.analysis.cost_audit import (
    CostSummary,
    audit_cost,
    collect_wire,
)

REPO = Path(__file__).resolve().parents[1]
BUDGETS = REPO / "lightgbm_tpu" / "analysis"


def _wire_jaxpr(widen: bool):
    from tests.test_static_analysis import _wire_fixture_jaxpr

    return _wire_fixture_jaxpr(widen)


def _summary(wire=(), **kw) -> CostSummary:
    base = dict(flops=100, bytes_accessed=200, temp_bytes=300,
                output_bytes=40, argument_bytes=50)
    base.update(kw)
    return CostSummary(wire=tuple(wire), **base)


# ------------------------------------------------------- wire account
def test_collect_wire_reads_payload_bytes():
    """The per-shard psum_scatter payload: (16, 8) int32 over 8 shards
    -> a (16, 1) int32 reduce_scatter operand = 64 bytes."""
    wire = collect_wire(_wire_jaxpr(widen=False))
    rs = [w for w in wire if w.prim == "reduce_scatter"]
    assert len(rs) == 1, wire
    assert rs[0].dtype == "int32" and rs[0].nbytes == 16 * 4, rs
    assert sum(w.nbytes for w in wire) == rs[0].nbytes


def test_widened_collective_payload_fails_wire_audit():
    """ACCEPTANCE: f32 in place of int32 on the quant reduce fails the
    wire audit — the dtype leg catches the same-itemsize f32 swap, and
    the exact byte budget catches any payload growth (the int16-era
    budget makes today's int32 wire read as the 2x regression it
    would be)."""
    int32_summary = _summary(wire=collect_wire(_wire_jaxpr(widen=False)))
    f32_summary = _summary(wire=collect_wire(_wire_jaxpr(widen=True)))
    budget = {"flops": 1000, "bytes_accessed": 1000, "temp_bytes": 1000,
              "output_bytes": 1000, "wire_bytes": int32_summary.wire_bytes}

    green = audit_cost(int32_summary, budget, "int32", wire_dtype="int32")
    assert green.ok, green.format()

    red = audit_cost(f32_summary, budget, "widened", wire_dtype="int32")
    assert not red.ok, red.format()
    bad = [c for c in red.contracts if not c.ok]
    assert any(c.name == "wire_int32" for c in bad), red.format()

    # the ROADMAP 3a ratchet: once the budget pins the halved int16
    # wire, an int32 payload EXCEEDS it byte-for-byte
    int16_era = dict(budget, wire_bytes=int32_summary.wire_bytes // 2)
    regressed = audit_cost(int32_summary, int16_era, "post-flip",
                           wire_dtype="int16")
    assert not regressed.ok
    names = {c.name for c in regressed.contracts if not c.ok}
    assert "wire_bytes" in names and "wire_int16" in names, \
        regressed.format()


# ------------------------------------------------------ cost budgets
def test_cost_budget_red_to_green():
    s = _summary()
    roomy = {"flops": 1000, "bytes_accessed": 1000, "temp_bytes": 1000,
             "output_bytes": 1000, "wire_bytes": 0}
    assert audit_cost(s, roomy, "roomy").ok

    tiny = dict(roomy, temp_bytes=299)
    r = audit_cost(s, tiny, "tiny")
    assert not r.ok
    assert any(c.name == "temp_bytes" and not c.ok for c in r.contracts)

    # a missing budget (entry or key) is a FAILURE, not a skip
    assert not audit_cost(s, None, "nobudget").ok
    partial = {k: v for k, v in roomy.items() if k != "flops"}
    r2 = audit_cost(s, partial, "partial")
    assert not r2.ok
    assert any(c.name == "flops" and not c.ok for c in r2.contracts)


def test_refresh_budgets_headroom_and_diff(monkeypatch, tmp_path):
    """--refresh-budgets writes +25% headroom on cost metrics, EXACT
    wire bytes, and the diff formatter reports per-metric deltas."""
    from lightgbm_tpu.analysis import cost_audit

    path = tmp_path / "cost_budget.json"
    monkeypatch.setattr(cost_audit, "_BUDGET_PATH", path)
    from lightgbm_tpu.analysis.cost_audit import WireRecord

    stub = _summary(
        wire=[WireRecord("reduce_scatter", (16,), "int32", 64)],
        flops=1000,
    )
    monkeypatch.setattr(cost_audit, "compile_entry", lambda name: stub)
    old, new = cost_audit.refresh_budgets()
    assert old == {}
    written = json.loads(path.read_text())
    assert set(written) == set(cost_audit.ENTRIES)
    for b in written.values():
        assert b["flops"] == 1250       # ceil(1000 * 1.25)
        assert b["wire_bytes"] == 64    # exact, no headroom
    diff = cost_audit.format_budget_diff(old, new)
    assert "flops: None -> 1250" in diff
    # unchanged refresh reads as unchanged
    old2, new2 = cost_audit.refresh_budgets()
    assert "unchanged" in cost_audit.format_budget_diff(old2, new2)


# -------------------------------------------------- consistency meta
def test_every_entry_has_both_budgets():
    """Meta-test: ENTRIES, jaxpr_budget.json and cost_budget.json agree
    key-for-key — no orphan budgets, no unbudgeted entries. (An entry
    added without budgets would fail its audits too, but this fails
    FAST and names the missing side.)"""
    from lightgbm_tpu.analysis.jaxpr_audit import ENTRIES

    jaxpr = json.loads((BUDGETS / "jaxpr_budget.json").read_text())
    cost = json.loads((BUDGETS / "cost_budget.json").read_text())
    assert set(jaxpr) == set(ENTRIES), (
        f"jaxpr_budget.json keys {sorted(jaxpr)} != entries "
        f"{sorted(ENTRIES)} — run --update-budget / prune orphans"
    )
    assert set(cost) == set(ENTRIES), (
        f"cost_budget.json keys {sorted(cost)} != entries "
        f"{sorted(ENTRIES)} — run --refresh-budgets / prune orphans"
    )
    required = {"flops", "bytes_accessed", "temp_bytes", "output_bytes",
                "wire_bytes"}
    for name, b in cost.items():
        assert required <= set(b), f"{name} budget missing {required - set(b)}"


def test_scale_budget_consistent_with_mesh_entries():
    """Meta-test for Pass 7's pins: scale_budget.json keys == the
    mesh-bearing entries == the declared SCALE_ENTRIES specs, and
    every entry pins every rung of the full ladder with every budget
    key (a missing rung would let the 4/8 legs rot while tier-1 only
    exercises {1, 2})."""
    from lightgbm_tpu.analysis.jaxpr_audit import mesh_entry_names
    from lightgbm_tpu.analysis.scale_audit import (
        _BUDGET_KEYS,
        LADDER,
        SCALE_ENTRIES,
    )

    scale = json.loads((BUDGETS / "scale_budget.json").read_text())
    mesh = set(mesh_entry_names())
    assert set(scale) == mesh, (
        f"scale_budget.json keys {sorted(scale)} != mesh entries "
        f"{sorted(mesh)} — run --refresh-budgets / prune orphans"
    )
    assert set(SCALE_ENTRIES) == mesh, (
        f"SCALE_ENTRIES {sorted(SCALE_ENTRIES)} != mesh entries "
        f"{sorted(mesh)} — declare a ScaleSpec for every mesh entry"
    )
    for name, pins in scale.items():
        assert set(pins) == {str(d) for d in LADDER}, (
            f"{name} pins rungs {sorted(pins)} != ladder {LADDER}"
        )
        for d, pin in pins.items():
            assert set(pin) == set(_BUDGET_KEYS), (
                f"{name}[D={d}] keys {sorted(pin)}"
            )


def test_strict_gate_runs_every_registered_pass(monkeypatch, capsys):
    """Meta-test: `--strict` exercises ALL registered auditors — stub
    every pass runner, drive the real CLI main(), and assert each got
    called (the gate cannot silently shed a pass)."""
    from lightgbm_tpu.analysis import __main__ as cli
    from lightgbm_tpu.analysis import passes

    ran = []

    def stub(name):
        def run(pkg_root, show_suppressed):
            ran.append(name)
            return passes.PassResult(name, True, f"{name} ok")
        return run

    for name, p in passes.PASSES.items():
        monkeypatch.setitem(passes.PASSES, name, p._replace(run=stub(name)))
    monkeypatch.setattr(cli, "_force_cpu_mesh", lambda: None)
    rc = cli.main(["--strict"])
    assert rc == 0
    assert set(ran) == set(passes.PASSES)
    assert "analysis: clean" in capsys.readouterr().out

    # a failing pass flips the strict exit code
    bad = passes.PASSES["cost"]._replace(
        run=lambda pkg_root, show_suppressed: passes.PassResult(
            "cost", False, "cost FAIL"
        )
    )
    monkeypatch.setitem(passes.PASSES, "cost", bad)
    assert cli.main(["--strict"]) == 1
    assert cli.main([]) == 0  # non-strict reports but exits 0


def test_run_passes_rejects_unknown_names():
    from lightgbm_tpu.analysis.passes import PASSES, run_passes

    with pytest.raises(KeyError, match="nope"):
        run_passes(["nope"])
    assert set(PASSES) == {"lint", "concurrency", "jaxpr", "cost",
                           "bench_gate", "scale"}


# ------------------------------------------------------ real entries
def test_serving_entry_cost_audit_green(cost_audit):
    """One real lower+compile through the fixture (serving_forest is
    the cheap entry, ~1 s); the full five-entry sweep is the slow CLI
    test + test_all_entries_green below."""
    results = cost_audit(names=["serving_forest"])
    assert [r.name for r in results] == ["serving_forest"]


@pytest.mark.slow
def test_all_entries_cost_audit_green(cost_audit):
    cost_audit()


def test_unknown_entry_name_raises():
    from lightgbm_tpu.analysis.cost_audit import run_cost_audits

    with pytest.raises(KeyError, match="typo_entry"):
        run_cost_audits(names=["typo_entry"])
