#!/usr/bin/env bash
# Chaos suite runner (docs/RESILIENCE.md): every test marked `chaos` —
# deterministic fault injection (resilience/faultinject.py) driving
# crash-at-round-N + resume bit-match, SIGKILL'd subprocess resume,
# serving deadline expiry / queue admission 503s / device-fault host
# fallback, anomaly rollback recovery, and the online-loop fault
# matrix (tests/test_online.py): a fault plan at every loop phase —
# kill mid-refit (loop_refit:0:kill), crash between eval and promote
# (loop_promote:0:kill), delayed ingest (loop_ingest:0:delay:…), and a
# poisoned-label microbatch — must leave a restart serving the last
# PERSISTED promotion, in-process and for the SIGKILL'd task=loop CLI.
# The serving-gateway matrix (tests/test_gateway.py) rides here too:
# kill -9 a backend under concurrent load with ZERO client-visible
# failures + breaker open -> half_open -> closed recovery, SIGTERM
# drain finishing in-flight work, and hedging overtaking a stalled
# attempt (gw_slow_backend delay plan).
#
# The fast chaos tests also run inside the tier-1 gate (they carry no
# `slow` mark); this entry point runs the FULL chaos set, including the
# slow SIGKILL subprocess matrices, in isolation:
#
#   tools/chaos.sh                 # all chaos tests
#   tools/chaos.sh -k sigkill      # extra pytest args pass through
#   tools/chaos.sh -k loop         # just the online-loop fault matrix
#
# Forced onto the CPU backend: fault injection and recovery must work
# exactly when the accelerator is the thing that broke.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
  -p no:cacheprovider "$@"
