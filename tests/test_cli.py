"""CLI application + text parsers + .bin dataset cache.

The reference's example train.conf files must run unmodified
(application.cpp:34; north-star entry-point parity), prediction output
must match the Python API, and the .bin cache must round-trip."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
REF = Path(os.environ.get("REFERENCE_DIR", "/root/reference"))

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import main as cli_main, parse_kv_args
from lightgbm_tpu.parsers import (
    detect_format,
    is_binary_file,
    load_binary,
    load_text_file,
    save_binary,
)


def test_parse_kv_args_layering(tmp_path):
    conf = tmp_path / "c.conf"
    conf.write_text(
        "num_leaves = 31  # comment\n# full comment line\nmetric = auc\n"
        "learning_rate=0.2\n"
    )
    # CLI pairs win over config file pairs (first occurrence wins)
    p = parse_kv_args([f"config={conf}", "num_leaves=7", "task=train"])
    assert p["num_leaves"] == "7"
    assert p["metric"] == "auc"
    assert p["learning_rate"] == "0.2"
    assert p["task"] == "train"
    assert "config" not in p


def test_detect_format():
    assert detect_format(["1\t2.0\t3.5", "0\t1.0\t2.5"]) == "tsv"
    assert detect_format(["1,2.0,3.5"]) == "csv"
    assert detect_format(["1 1:0.5 4:2.0", "0 2:1.0"]) == "libsvm"


def test_load_libsvm(tmp_path):
    f = tmp_path / "d.svm"
    f.write_text("1 0:0.5 2:2.0\n0 1:1.5\n1 2:3.0\n")
    out = load_text_file(str(f))
    np.testing.assert_array_equal(out["label"], [1, 0, 1])
    assert out["X"].shape == (3, 3)
    assert out["X"][0, 0] == 0.5 and out["X"][1, 1] == 1.5 and out["X"][2, 2] == 3.0


def test_load_tsv_with_sidecars(tmp_path):
    f = tmp_path / "d.tsv"
    rs = np.random.RandomState(0)
    data = np.column_stack([rs.randint(0, 2, 20), rs.randn(20, 3)])
    np.savetxt(f, data, delimiter="\t", fmt="%.6f")
    np.savetxt(tmp_path / "d.tsv.weight", rs.rand(20), fmt="%.4f")
    np.savetxt(tmp_path / "d.tsv.query", [12, 8], fmt="%d")
    out = load_text_file(str(f))
    assert out["X"].shape == (20, 3)
    assert out["weight"].shape == (20,)
    np.testing.assert_array_equal(out["group"], [12, 8])


def test_cli_train_and_predict_match_api(tmp_path):
    rs = np.random.RandomState(5)
    X = rs.randn(500, 6)
    w = rs.randn(6)
    y = ((X @ w + 0.3 * rs.randn(500)) > 0).astype(float)
    np.savetxt(tmp_path / "train.tsv", np.column_stack([y, X]),
               delimiter="\t", fmt="%.6f")
    conf = tmp_path / "train.conf"
    conf.write_text(
        "task = train\nobjective = binary\ndata = train.tsv\n"
        "num_trees = 10\nnum_leaves = 15\nmetric = auc\n"
        "output_model = model.txt\nverbosity = -1\n"
    )
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        assert cli_main([f"config={conf}"]) == 0
        assert (tmp_path / "model.txt").exists()
        assert cli_main([
            "task=predict", "data=train.tsv", "input_model=model.txt",
            "output_result=pred.txt",
        ]) == 0
    finally:
        os.chdir(cwd)
    pred_cli = np.loadtxt(tmp_path / "pred.txt")
    bst = lgb.Booster(model_file=tmp_path / "model.txt")
    np.testing.assert_allclose(pred_cli, bst.predict(X), rtol=1e-6, atol=1e-9)
    from sklearn.metrics import roc_auc_score

    assert roc_auc_score(y, pred_cli) > 0.8


@pytest.mark.skipif(
    not (REF / "examples" / "binary_classification" / "train.conf").exists(),
    reason="reference examples unavailable",
)
def test_reference_example_conf_runs_unmodified(tmp_path):
    ex = REF / "examples" / "binary_classification"
    for f in ("binary.train", "binary.test", "train.conf"):
        (tmp_path / f).write_bytes((ex / f).read_bytes())
    # sidecar weight files like the reference example layout
    (tmp_path / "binary.train.weight").write_bytes(
        (ex / "binary.train.weight").read_bytes()
    )
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = cli_main(["config=train.conf", "num_trees=5",
                       "is_training_metric=false"])
    finally:
        os.chdir(cwd)
    assert rc == 0
    assert (tmp_path / "LightGBM_model.txt").exists()
    bst = lgb.Booster(model_file=tmp_path / "LightGBM_model.txt")
    assert bst.num_trees() == 5


def test_binary_cache_roundtrip(tmp_path):
    rs = np.random.RandomState(7)
    X = np.column_stack([rs.randint(0, 10, 300), rs.randn(300, 4)])
    y = rs.randn(300)
    ds = lgb.Dataset(X, label=y, weight=rs.rand(300),
                     categorical_feature=[0], free_raw_data=False)
    ds.construct()
    path = str(tmp_path / "data.bin")
    save_binary(ds._binned, path)
    assert is_binary_file(path)
    assert not is_binary_file(__file__)
    b2 = load_binary(path)
    np.testing.assert_array_equal(b2.bins, ds._binned.bins)
    np.testing.assert_array_equal(b2.metadata.label, ds._binned.metadata.label)
    np.testing.assert_array_equal(b2.metadata.weight, ds._binned.metadata.weight)
    assert b2.num_data == 300
    assert [m.num_bin for m in b2.mappers] == [m.num_bin for m in ds._binned.mappers]
    assert b2.mappers[0].categories == ds._binned.mappers[0].categories

    # training from the cache matches training from raw data
    p = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    b_raw = lgb.train(dict(p), ds, num_boost_round=5)
    ds2 = lgb.Dataset.from_binned(b2)
    b_cache = lgb.train(dict(p), ds2, num_boost_round=5)
    np.testing.assert_allclose(
        b_cache.predict(X[:50]), b_raw.predict(X[:50]), rtol=1e-6
    )


def test_cli_convert_model_compiles_and_matches(tmp_path):
    """task=convert_model (GBDT::SaveModelToIfElse): the generated
    if-else C++ must COMPILE and reproduce the booster's raw scores."""
    import ctypes
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    rs = np.random.RandomState(0)
    X = rs.randn(1500, 6)
    X[rs.rand(1500, 6) < 0.05] = np.nan
    y = ((np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1])) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=5)
    model = tmp_path / "m.txt"
    bst.save_model(model)

    out_cpp = tmp_path / "pred.cpp"
    from lightgbm_tpu.cli import main as cli_main

    rc = cli_main([
        "task=convert_model", f"input_model={model}",
        f"convert_model={out_cpp}",
    ])
    assert rc == 0 and out_cpp.exists()
    so = tmp_path / "pred.so"
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", str(out_cpp), "-o", str(so)],
        check=True,
    )
    lib = ctypes.CDLL(str(so))
    lib.Predict.argtypes = [ctypes.POINTER(ctypes.c_double),
                            ctypes.POINTER(ctypes.c_double)]
    expect = bst.predict(X[:50], raw_score=True)
    got = np.zeros(50)
    for i in range(50):
        row = np.ascontiguousarray(X[i], dtype=np.float64)
        out = (ctypes.c_double * 1)()
        lib.Predict(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), out)
        got[i] = out[0]
    np.testing.assert_allclose(got, expect, rtol=1e-12, atol=1e-12)


def test_cli_refit_task(tmp_path):
    """task=refit (config.h:35): leaf values recomputed from new data."""
    rs = np.random.RandomState(1)
    X = rs.randn(2000, 5)
    y = ((X[:, 0] - X[:, 1]) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=5)
    model = tmp_path / "m.txt"
    bst.save_model(model)
    # new data, tab-separated, label first (reference example format)
    X2 = rs.randn(1500, 5)
    y2 = ((X2[:, 0] - X2[:, 1]) > 0).astype(float)
    dpath = tmp_path / "refit.tsv"
    np.savetxt(dpath, np.column_stack([y2, X2]), delimiter="\t", fmt="%.8g")
    out_model = tmp_path / "refitted.txt"
    from lightgbm_tpu.cli import main as cli_main

    rc = cli_main([
        "task=refit", f"data={dpath}", f"input_model={model}",
        f"output_model={out_model}", "verbosity=-1",
    ])
    assert rc == 0 and out_model.exists()
    b2 = lgb.Booster(model_file=out_model)
    # same tree STRUCTURE, different leaf values
    assert b2.num_trees() == bst.num_trees()
    p_old = bst.predict(X2)
    p_new = b2.predict(X2)
    assert not np.allclose(p_old, p_new)
    from sklearn.metrics import roc_auc_score

    assert roc_auc_score(y2, p_new) > 0.85


def test_cli_serve_task_stdio(tmp_path, monkeypatch, capsys):
    """task=serve: JSONL scoring loop over stdin/stdout (serving
    registry behind the CLI; docs/SERVING.md). Parity with the Python
    API on the same model file."""
    import io
    import json

    rs = np.random.RandomState(5)
    X = rs.randn(600, 4)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=y, free_raw_data=False), num_boost_round=4,
    )
    model = tmp_path / "m.txt"
    bst.save_model(str(model))

    reqs = [
        {"op": "ping"},
        {"op": "score", "rows": X[:3].tolist()},
        {"op": "models"},
        {"op": "quit"},
    ]
    monkeypatch.setattr(
        "sys.stdin", io.StringIO("\n".join(json.dumps(r) for r in reqs))
    )
    rc = cli_main([
        "task=serve", f"input_model={model}", "serve_warmup=false",
        "serve_buckets=8,32", "verbosity=-1",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    resp = [json.loads(l) for l in lines]
    assert resp[0]["pong"]
    np.testing.assert_allclose(resp[1]["pred"], bst.predict(X[:3]),
                               rtol=1e-5, atol=1e-6)
    assert resp[2]["models"]["default"]["active"] == 1
    assert resp[3]["quit"]
