import os, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N, F, B = 1_048_576, 28, 256
from lightgbm_tpu.learner.histogram import build_gh8
from lightgbm_tpu.learner.pallas_hist import hist_tpu

rs = np.random.RandomState(0)
bins = jnp.asarray(rs.randint(0, B-1, size=(F, N)).astype(np.int32))
gh8 = jnp.asarray(rs.randn(8, N).astype(np.float32))

def bench(name, jitted, *args, iters=1):
    r = jitted(*args); jax.block_until_ready(r)
    t0 = time.time(); r = jitted(*args); jax.block_until_ready(r)
    dt = time.time() - t0
    print(f"{name}: {dt/iters*1000:.3f} ms/iter (total {dt*1000:.1f})")

# real pallas hist cost: carry-dependence that can't be simplified away
@jax.jit
def hist5(b, g):
    def body(i, acc):
        h = hist_tpu(b, g * (1.0 + acc[0, 0] * 1e-30), B)
        return acc + h[:, 0, :1]
    return lax.fori_loop(0, 5, body, jnp.zeros((8, 1), jnp.float32))
bench("pallas hist full-N (real, x5)", hist5, bins, gh8, iters=5)

# single call
one = jax.jit(lambda b, g: hist_tpu(b, g, B))
bench("pallas hist full-N single", one, bins, gh8)

# loop floor: trivial arithmetic body
@jax.jit
def loop_arith(x):
    def body(i, a): return a * 1.0000001 + 1.0
    return lax.fori_loop(0, 1000, body, x)
bench("fori_loop 1000 trivial-arith iters", loop_arith, jnp.float32(0.0), iters=1000)

# loop floor: small-array dynamic update body
@jax.jit
def loop_upd(x):
    def body(i, a): return a.at[i % 255].set(a[i % 255] + 1.0)
    return lax.fori_loop(0, 1000, body, x)
bench("fori_loop 1000 small-dynupd iters", loop_upd, jnp.zeros(255, jnp.float32), iters=1000)

# loop floor with a medium body (~30 small ops)
@jax.jit
def loop_med(x):
    def body(i, a):
        for _ in range(10):
            a = a * 1.0000001
            a = a.at[i % 255].set(a[(i+1) % 255] + 1.0)
            a = jnp.roll(a, 1)
        return a
    return lax.fori_loop(0, 200, body, x)
bench("fori_loop 200 medium-body iters", loop_med, jnp.zeros(255, jnp.float32), iters=200)

# dispatch latency: tiny jit called 100x from host
tiny = jax.jit(lambda x: x + 1.0)
x = jnp.float32(0.0); tiny(x)
jax.block_until_ready(tiny(x))
t0 = time.time()
for _ in range(100):
    x = tiny(x)
jax.block_until_ready(x)
print(f"host-dispatch tiny jit: {(time.time()-t0)/100*1000:.3f} ms/call")

# device_get latency of a tiny array
y = jnp.zeros(16, jnp.float32)
jax.block_until_ready(y)
t0 = time.time()
for _ in range(20):
    _ = jax.device_get(y)
print(f"device_get tiny: {(time.time()-t0)/20*1000:.3f} ms/call")

# elementwise full-N pass (bandwidth check)
ew = jax.jit(lambda g: g * 1.5 + 1.0)
bench("elementwise (8,N) f32", ew, gh8)
