"""Native C++ text-ingest (lightgbm_tpu/native/fastparse.cpp) vs the
NumPy fallback parsers — same matrices, byte-for-byte semantics."""

import numpy as np
import pytest

from lightgbm_tpu import native


@pytest.fixture(scope="module")
def lib_ok():
    if native.get_lib() is None:
        pytest.skip("no native toolchain available")
    return True


def test_parse_csv_matches_numpy(tmp_path, lib_ok):
    rs = np.random.RandomState(0)
    X = rs.randn(500, 7)
    X[rs.rand(500, 7) < 0.05] = np.nan
    p = tmp_path / "data.csv"
    with open(p, "w") as f:
        for row in X:
            f.write(",".join("" if np.isnan(v) else format(v, ".17g") for v in row))
            f.write("\n")
    out = native.parse_delim(str(p), ",", 0)
    assert out is not None and out.shape == X.shape
    np.testing.assert_allclose(out, X, rtol=1e-15, equal_nan=True)


def test_parse_tsv_with_header_and_crlf(tmp_path, lib_ok):
    p = tmp_path / "data.tsv"
    with open(p, "wb") as f:
        f.write(b"a\tb\tc\r\n")
        f.write(b"1\t2.5\t-3e2\r\n")
        f.write(b"4\tNA\t6\r\n")
    out = native.parse_delim(str(p), "\t", 1)
    expect = np.array([[1, 2.5, -300.0], [4, np.nan, 6]])
    np.testing.assert_allclose(out, expect, equal_nan=True)


def test_parse_libsvm(tmp_path, lib_ok):
    p = tmp_path / "data.svm"
    with open(p, "w") as f:
        f.write("1 0:1.5 3:2.0\n")
        f.write("0 1:-4.25\n")
        f.write("1\n")  # empty feature row
    labels, X = native.parse_libsvm(str(p))
    np.testing.assert_allclose(labels, [1, 0, 1])
    expect = np.zeros((3, 4))
    expect[0, 0] = 1.5
    expect[0, 3] = 2.0
    expect[1, 1] = -4.25
    np.testing.assert_allclose(X, expect)


def test_cli_data_path_uses_native(tmp_path, lib_ok):
    """End to end through load_text_file: same Dataset either way."""
    from lightgbm_tpu.parsers import load_text_file

    rs = np.random.RandomState(1)
    X = rs.randn(300, 4)
    y = (X[:, 0] > 0).astype(float)
    p = tmp_path / "train.csv"
    with open(p, "w") as f:
        for yy, row in zip(y, X):
            f.write(",".join([format(yy, ".17g")] + [format(v, ".17g") for v in row]) + "\n")
    out = load_text_file(str(p))
    assert out["X"].shape == (300, 4)
    np.testing.assert_allclose(out["label"], y)


def test_parse_libsvm_skips_qid_tokens(tmp_path, lib_ok):
    """`qid:3` must not alias onto feature 0 (ADVICE r3): the index part
    of a token must be all digits in both the max-index scan and the
    fill pass."""
    p = tmp_path / "rank.svm"
    with open(p, "w") as f:
        f.write("2 qid:1 0:0.5 2:1.5\n")
        f.write("1 qid:1 1:-2.0\n")
        f.write("0 qid:2 0:7.0\n")
    labels, X = native.parse_libsvm(str(p))
    np.testing.assert_allclose(labels, [2, 1, 0])
    expect = np.zeros((3, 3))
    expect[0, 0] = 0.5
    expect[0, 2] = 1.5
    expect[1, 1] = -2.0
    expect[2, 0] = 7.0
    np.testing.assert_allclose(X, expect)  # qid values NOT in column 0


def test_parse_delim_rejects_malformed(tmp_path, lib_ok):
    """Unparseable tokens / ragged rows fail the native parse (rc != 0
    -> None) instead of silently training on NaN-filled data; the
    np.loadtxt fallback raises on the same files (ADVICE r3)."""
    bad_token = tmp_path / "tok.csv"
    with open(bad_token, "w") as f:
        f.write("1.0,2.0,3.0\n")
        f.write("4.0,oops,6.0\n")
    assert native.parse_delim(str(bad_token), ",", 0) is None

    ragged = tmp_path / "ragged.csv"
    with open(ragged, "w") as f:
        f.write("1.0,2.0,3.0\n")
        f.write("4.0,5.0\n")
        f.write("4.0,5.0,6.0,7.0\n")
    assert native.parse_delim(str(ragged), ",", 0) is None

    # NA tokens and empty fields remain fine (explicitly supported)
    ok = tmp_path / "ok.csv"
    with open(ok, "w") as f:
        f.write("1.0,NA,3.0\n")
        f.write("4.0,,nan\n")
    out = native.parse_delim(str(ok), ",", 0)
    np.testing.assert_allclose(
        out, [[1.0, np.nan, 3.0], [4.0, np.nan, np.nan]], equal_nan=True
    )
