"""Thread-safe metrics registry: counters / gauges / histograms with
labels, plus Prometheus text exposition.

The reference's observability is a timer table printed at exit
(utils/common.h:979 USE_TIMETAG) — enough for a batch trainer, not for
a serving system or for tracking throughput round-over-round. This
registry is the production analog: any module records named metrics
(host-side only — NEVER from inside traced code; the no-callback jaxpr
contract in analysis/jaxpr_audit.py stays the proof), and exporters
read one consistent snapshot:

- ``render_prometheus()`` — text exposition (format 0.0.4), served
  from the serving HTTP transport's ``/metrics`` route (server.py);
- ``snapshot()`` — plain dicts for the run manifest (manifest.py) and
  tests.

Collectors bridge existing stat objects without duplicating state:
``timer.LatencyStats`` registers a collector that derives its samples
from the SAME ring ``ModelRegistry.stats()`` reports, so the
percentile a scrape sees and the percentile the stats op returns can
never disagree (the one-source-of-truth contract, parity-tested in
tests/test_obs.py).

Cost model: recording is a dict upsert under a per-metric lock —
nanoseconds against the ms-scale regions being counted. When the
registry is disabled (env LIGHTGBM_TPU_METRICS=0, or ``disable()``)
every record call is a single attribute check.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Callable, Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

# default histogram bucket bounds (seconds-flavored, Prometheus style)
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Sample(NamedTuple):
    """One exposition sample (collectors yield these)."""

    name: str
    kind: str  # "counter" | "gauge"
    help: str
    labels: Tuple[Tuple[str, str], ...]
    value: float


def _escape_label(v: str) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _render_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    """Base: one named metric family with a fixed label-name set."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 label_names: Sequence[str], registry: "MetricsRegistry"):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._registry = registry
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[k]) for k in self.label_names)

    def _pairs(self, key: Tuple[str, ...]) -> Tuple[Tuple[str, str], ...]:
        return tuple(zip(self.label_names, key))

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class Counter(_Metric):
    """Monotonically increasing count."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels: Any) -> None:
        if not self._registry.enabled:
            return
        if value < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + float(value)

    def value(self, **labels: Any) -> float:
        k = self._key(labels)
        with self._lock:
            return float(self._values.get(k, 0.0))

    def samples(self) -> List[Sample]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            Sample(self.name, self.kind, self.help, self._pairs(k), v)
            for k, v in items
        ]


class Gauge(_Metric):
    """Point-in-time value (queue depth, trees/s, ...)."""

    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        if not self._registry.enabled:
            return
        k = self._key(labels)
        with self._lock:
            self._values[k] = float(value)

    def inc(self, value: float = 1.0, **labels: Any) -> None:
        if not self._registry.enabled:
            return
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + float(value)

    def dec(self, value: float = 1.0, **labels: Any) -> None:
        self.inc(-value, **labels)

    def value(self, **labels: Any) -> float:
        k = self._key(labels)
        with self._lock:
            return float(self._values.get(k, 0.0))

    samples = Counter.samples  # same flat shape


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 label_names: Sequence[str], registry: "MetricsRegistry",
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, help_text, label_names, registry)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name!r} needs at least one bucket")

    def observe(self, value: float, **labels: Any) -> None:
        if not self._registry.enabled:
            return
        k = self._key(labels)
        v = float(value)
        with self._lock:
            state = self._values.get(k)
            if state is None:
                state = {"counts": [0] * len(self.buckets),
                         "sum": 0.0, "count": 0}
                self._values[k] = state
            for i, b in enumerate(self.buckets):
                if v <= b:
                    state["counts"][i] += 1
            state["sum"] += v
            state["count"] += 1

    def state(self, **labels: Any) -> Dict[str, Any]:
        k = self._key(labels)
        with self._lock:
            s = self._values.get(k)
            if s is None:
                return {"counts": [0] * len(self.buckets),
                        "sum": 0.0, "count": 0}
            return {"counts": list(s["counts"]), "sum": s["sum"],
                    "count": s["count"]}

    def samples(self) -> List[Sample]:
        with self._lock:
            items = sorted(
                (k, {"counts": list(s["counts"]), "sum": s["sum"],
                     "count": s["count"]})
                for k, s in self._values.items()
            )
        out: List[Sample] = []
        for k, s in items:
            pairs = self._pairs(k)
            cum = 0
            for b, c in zip(self.buckets, s["counts"]):
                cum = c  # counts are already cumulative per-bucket
                out.append(Sample(
                    self.name + "_bucket", self.kind, self.help,
                    pairs + (("le", _fmt(b)),), float(cum),
                ))
            out.append(Sample(
                self.name + "_bucket", self.kind, self.help,
                pairs + (("le", "+Inf"),), float(s["count"]),
            ))
            out.append(Sample(self.name + "_sum", self.kind, self.help,
                              pairs, float(s["sum"])))
            out.append(Sample(self.name + "_count", self.kind, self.help,
                              pairs, float(s["count"])))
        return out


class MetricsRegistry:
    """Named metric families + scrape-time collectors."""

    def __init__(self, enabled: Optional[bool] = None):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], Iterable[Sample]]] = []
        if enabled is None:
            enabled = os.environ.get(
                "LIGHTGBM_TPU_METRICS", "1"
            ) not in ("0", "false", "off")
        self.enabled = bool(enabled)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    # ------------------------------------------------------------------
    def _get_or_create(self, cls, name: str, help_text: str,
                       labels: Sequence[str], **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_text, labels, self, **kw)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls) or m.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind} with "
                f"labels {m.label_names}"
            )
        return m

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labels,
                                   buckets=buckets)

    def register_collector(
        self, fn: Callable[[], Iterable[Sample]]
    ) -> None:
        """Register a scrape-time sample source (e.g. a LatencyStats
        bridge). The callable runs on every render/snapshot."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(
        self, fn: Callable[[], Iterable[Sample]]
    ) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    # ------------------------------------------------------------------
    def samples(self) -> List[Sample]:
        """Every current sample (metrics + collectors) — the public
        scrape view obs.aggregate serializes for host-side fleet
        merging (each Sample carries its kind, so the merger knows
        counters sum and gauges don't)."""
        return self._all_samples()

    def _all_samples(self) -> List[Sample]:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        out: List[Sample] = []
        for m in metrics:
            out.extend(m.samples())
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception as e:  # noqa: BLE001 — one bad collector must not kill the scrape
                from .. import log

                log.warning(f"metrics collector {fn!r} failed: {e}")
        return out

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{metric name: {rendered label string: value}} over every
        metric and collector — the manifest/test view."""
        out: Dict[str, Dict[str, float]] = {}
        for s in self._all_samples():
            out.setdefault(s.name, {})[_render_labels(s.labels)] = s.value
        return out

    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4 (one scrape body)."""
        samples = self._all_samples()
        # group by family: histogram sample names share the base
        # metric's HELP/TYPE header
        by_family: "Dict[str, List[Sample]]" = {}
        family_meta: Dict[str, Tuple[str, str]] = {}
        for s in samples:
            fam = s.name
            for suffix in ("_bucket", "_sum", "_count"):
                if s.kind == "histogram" and fam.endswith(suffix):
                    fam = fam[: -len(suffix)]
                    break
            by_family.setdefault(fam, []).append(s)
            family_meta.setdefault(fam, (s.kind, s.help))
        lines: List[str] = []
        for fam in sorted(by_family):
            kind, help_text = family_meta[fam]
            if help_text:
                lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} {kind}")
            for s in by_family[fam]:
                lines.append(
                    f"{s.name}{_render_labels(s.labels)} {_fmt(s.value)}"
                )
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every recorded value (metric objects survive; tests)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


# ---------------------------------------------------------------- bridges
# Small helpers the instrumented modules call, so hot seams carry one
# obs call instead of registry plumbing (and the concurrency-linted
# serving modules never manipulate foreign locks inline).

_latency_bridged: Dict[str, Any] = {}
_latency_lock = threading.Lock()


def register_latency_collector(name: str, stats: Any,
                               model: Optional[str] = None) -> None:
    """Expose a timer.LatencyStats on /metrics. Samples derive from the
    same ``snapshot()`` the serving stats op reports — one ring, every
    reader (the dedupe contract for serving latency). ``model`` adds a
    ``{model=...}`` label for fleet tenants (one series set per model;
    see docs/OBSERVABILITY.md for the cardinality contract)."""
    with _latency_lock:
        if name in _latency_bridged:
            return
        _latency_bridged[name] = stats

    def collect() -> List[Sample]:
        snap = stats.snapshot()
        lab = (("entry", name),)
        if model is not None:
            lab = lab + (("model", model),)
        out = [
            Sample("lgbmtpu_serve_requests_total", "counter",
                   "requests observed by the latency ring", lab,
                   float(snap["count"])),
            Sample("lgbmtpu_serve_rows_total", "counter",
                   "rows scored", lab, float(snap["rows"])),
            Sample("lgbmtpu_serve_rows_per_sec", "gauge",
                   "lifetime rows/second", lab,
                   float(snap["rows_per_sec"])),
            Sample("lgbmtpu_serve_busy_frac", "gauge",
                   "fraction of uptime spent scoring", lab,
                   float(snap["busy_frac"])),
        ]
        for stat in ("mean_ms", "p50_ms", "p95_ms", "p99_ms"):
            out.append(Sample(
                "lgbmtpu_serve_latency_ms", "gauge",
                "request latency over the recent window (ms)",
                lab + (("stat", stat[:-3]),), float(snap[stat]),
            ))
        return out

    _default.register_collector(collect)


def record_training_round(n_iters: int, n_trees: int,
                          seconds: float) -> None:
    """One dispatched training chunk (or one sync iteration)."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_train_iterations_total",
              "boosting iterations completed").inc(n_iters)
    r.counter("lgbmtpu_train_trees_total",
              "trees trained (iterations x classes)").inc(n_trees)
    if seconds > 0:
        r.gauge("lgbmtpu_train_trees_per_sec",
                "trees/second over the most recent chunk"
                ).set(n_trees / seconds)
        r.histogram("lgbmtpu_train_chunk_seconds",
                    "wall seconds per dispatched training chunk"
                    ).observe(seconds)


def record_eval_values(evals) -> None:
    """Per-round evaluation results as labeled gauges: every
    ``(dataset, metric, value, higher_better)`` tuple the training loop
    produces (the same rows ``callback.record_evaluation`` collects)
    lands on ``lgbmtpu_eval_metric{dataset,metric}`` — learning curves
    on /metrics with no custom callback (docs/OBSERVABILITY.md)."""
    r = _default
    if not r.enabled or not evals:
        return
    g = r.gauge("lgbmtpu_eval_metric",
                "most recent per-round evaluation metric value",
                labels=("dataset", "metric"))
    for item in evals:
        ds_name, metric, value = item[0], item[1], item[2]
        g.set(float(value), dataset=ds_name, metric=metric)


def record_bucket_dispatch(entry: str, bucket: int, rows: int) -> None:
    """One padded device call through the serving shape ladder."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_serve_bucket_dispatch_total",
              "device calls per shape-ladder rung",
              labels=("entry", "bucket")).inc(
        1, entry=entry, bucket=bucket)
    r.counter("lgbmtpu_serve_padded_rows_total",
              "zero rows added to pad requests up to their rung",
              labels=("entry",)).inc(max(bucket - rows, 0), entry=entry)


def record_queue_depth(entry: str, depth: int) -> None:
    r = _default
    if not r.enabled:
        return
    r.gauge("lgbmtpu_serve_queue_depth",
            "requests waiting in the microbatch queue",
            labels=("entry",)).set(depth, entry=entry)


def record_coalesce(entry: str, n_requests: int, rows: int) -> None:
    """One microbatch drain: n_requests coalesced into one call."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_serve_coalesced_requests_total",
              "requests coalesced through the microbatch queue",
              labels=("entry",)).inc(n_requests, entry=entry)
    r.histogram("lgbmtpu_serve_coalesced_batch_rows",
                "rows per coalesced device call", labels=("entry",),
                buckets=(1, 4, 16, 64, 256, 1024, 4096)
                ).observe(rows, entry=entry)


def record_host_fallback(entry: str) -> None:
    """One serving chunk scored by the host tree-walker after a device
    scoring fault (docs/RESILIENCE.md "Serving degradation")."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_serve_host_fallback_total",
              "chunks degraded to the host tree-walker after a device "
              "scoring fault",
              labels=("entry",)).inc(1, entry=entry)


def record_serve_rejection(entry: str, kind: str) -> None:
    """A serving request rejected before scoring: queue overflow
    (admission control) or deadline expiry."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_serve_rejected_total",
              "requests rejected by admission control or deadline "
              "expiry, by kind",
              labels=("entry", "kind")).inc(1, entry=entry, kind=kind)


def record_registry_event(event: str, model: str) -> None:
    """Model-registry lifecycle: load / swap / rollback / unload."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_registry_events_total",
              "model registry lifecycle events",
              labels=("event", "model")).inc(1, event=event, model=model)


def record_fleet_page(model: str, event: str) -> None:
    """Fleet HBM paging: ``page_in`` / ``evict`` / ``warmup`` for one
    tenant (serving/fleet.py LRU residency)."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_fleet_page_events_total",
              "fleet HBM paging events, by model and kind",
              labels=("model", "event")).inc(1, model=model, event=event)


def record_fleet_resident(resident: int, capacity: int) -> None:
    """Current fleet residency vs the configured HBM capacity."""
    r = _default
    if not r.enabled:
        return
    r.gauge("lgbmtpu_fleet_resident_models",
            "models currently resident in device memory").set(resident)
    r.gauge("lgbmtpu_fleet_capacity_models",
            "configured fleet residency capacity").set(capacity)


def record_request_op(op: str, ok: bool) -> None:
    """One protocol request through handle_request (both transports)."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_serve_protocol_requests_total",
              "protocol requests handled, by op",
              labels=("op",)).inc(1, op=op)
    if not ok:
        r.counter("lgbmtpu_serve_protocol_errors_total",
                  "protocol requests answered with ok=false",
                  labels=("op",)).inc(1, op=op)


def record_promotion_event(outcome: str) -> None:
    """One online-loop gate verdict: ``promoted`` (gate passed, registry
    swapped), ``rejected`` (holdout metric regressed), ``rolled_back``
    (anomaly sentinel tripped during the refit — poisoned microbatch
    auto-revert). online/loop.py (docs/RESILIENCE.md "Online loop")."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_promotion_events_total",
              "online-loop promotion gate verdicts, by outcome",
              labels=("outcome",)).inc(1, outcome=outcome)


def record_ingest(rows: int) -> None:
    """One microbatch appended to the online ingest spool."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_ingest_batches_total",
              "microbatches accepted through the ingest op").inc(1)
    r.counter("lgbmtpu_ingest_rows_total",
              "rows accepted through the ingest op").inc(rows)


def record_loop_progress(version: int, cycle: int, offset: int) -> None:
    """Online-loop liveness gauges: promoted version, verdict cycles,
    and spool bytes consumed."""
    r = _default
    if not r.enabled:
        return
    r.gauge("lgbmtpu_online_version",
            "currently promoted online-loop model version").set(version)
    r.gauge("lgbmtpu_online_cycles_total",
            "online-loop verdict cycles completed").set(cycle)
    r.gauge("lgbmtpu_online_ingest_offset_bytes",
            "ingest spool bytes consumed through the last verdict"
            ).set(offset)


def record_collective_wire(entry: str, nbytes: int) -> None:
    """Host-side estimate of collective payload bytes dispatched (the
    runtime twin of analysis/cost_budget.json's static wire pins)."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_collective_wire_bytes_total",
              "estimated collective payload bytes dispatched",
              labels=("entry",)).inc(nbytes, entry=entry)


# gateway bridges (serving/gateway.py). Label/naming conventions in
# docs/OBSERVABILITY.md "Gateway metrics": outcome is the GATEWAY
# verdict (ok/failed/shed/deadline/unavailable/drain/fanout_partial),
# result is one ATTEMPT's fate (ok/5xx/error/cancelled), breaker state
# renders as a numeric gauge (0 closed / 1 half_open / 2 open) plus a
# transitions counter.
_BREAKER_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}


def record_gateway_request(op: str, outcome: str, seconds: float) -> None:
    """One client request through Gateway.handle, end to end."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_gateway_requests_total",
              "gateway client requests, by op and outcome",
              labels=("op", "outcome")).inc(1, op=op, outcome=outcome)
    r.histogram("lgbmtpu_gateway_request_seconds",
                "gateway end-to-end request latency (incl. retries "
                "and hedges)", labels=("op",)).observe(seconds, op=op)


def record_gateway_attempt(backend: str, result: str) -> None:
    """One backend attempt (primary, retry, or hedge)."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_gateway_attempts_total",
              "backend attempts, by backend and result",
              labels=("backend", "result")).inc(
        1, backend=backend, result=result)


def record_gateway_retry() -> None:
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_gateway_retries_total",
              "retry rounds scheduled (full-jitter backoff)").inc(1)


def record_gateway_hedge(outcome: str) -> None:
    """Hedge verdicts: ``fired`` / ``won`` / ``denied_budget`` /
    ``no_backend``."""
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_gateway_hedges_total",
              "hedged-attempt verdicts, by outcome",
              labels=("outcome",)).inc(1, outcome=outcome)


def record_gateway_breaker(backend: str, state: str) -> None:
    """Breaker transition: new state as a coded gauge + a counter."""
    r = _default
    if not r.enabled:
        return
    r.gauge("lgbmtpu_gateway_breaker_state",
            "circuit state per backend (0 closed, 1 half_open, 2 open)",
            labels=("backend",)).set(
        _BREAKER_STATE_CODE.get(state, -1), backend=backend)
    r.counter("lgbmtpu_gateway_breaker_transitions_total",
              "breaker transitions, by backend and destination state",
              labels=("backend", "to")).inc(1, backend=backend, to=state)


def record_gateway_pool(alive: int, ready: int, total: int) -> None:
    r = _default
    if not r.enabled:
        return
    r.gauge("lgbmtpu_gateway_backends_alive",
            "backends answering HTTP at the last probe sweep"
            ).set(alive)
    r.gauge("lgbmtpu_gateway_backends_ready",
            "backends passing /readyz at the last probe sweep"
            ).set(ready)
    r.gauge("lgbmtpu_gateway_backends_total",
            "configured backend slots").set(total)


def record_native_build(seconds: float, ok: bool) -> None:
    r = _default
    if not r.enabled:
        return
    r.counter("lgbmtpu_native_builds_total",
              "native fastparse toolchain builds",
              labels=("result",)).inc(1, result="ok" if ok else "failed")
    r.gauge("lgbmtpu_native_build_seconds",
            "wall seconds of the most recent native build").set(seconds)
