"""Fault tolerance for training and serving (docs/RESILIENCE.md).

Four legs, one failure-handling contract across both halves of the
stack:

- ``checkpoint`` — crash-consistent (tmp + os.replace) training
  checkpoints behind ``snapshot_freq``, consumed by engine.train's
  ``resume=auto`` / ``resume_from=`` params; the resumed model
  bit-matches an uninterrupted run.
- ``faultinject`` — deterministic, config/env-driven fault plans
  (raise/kill/delay at named host-side sites); zero overhead when
  disarmed, statically audited to never reach traced code.
- ``errors`` — the typed failure vocabulary (DeadlineExceeded,
  QueueOverflow, ShutdownError, InjectedFault, CheckpointError) the
  serving degradation paths raise and the HTTP transport maps to
  status codes.
- ``backoff`` + ``heartbeat`` — the single retry-with-backoff helper
  (bench.py probe, fleet scrape, cluster join) and per-worker
  heartbeat files with worker-death detection for run_distributed.
"""

from .backoff import backoff_delay, delays, retry_call
from .errors import (
    CheckpointError,
    DeadlineExceeded,
    InjectedFault,
    QueueOverflow,
    ResilienceError,
    ShutdownError,
)
from .faultinject import FaultPlan, arm, configure, disarm, fault_point
from .heartbeat import HeartbeatWriter, health_report, read_heartbeats

__all__ = [
    "CheckpointError",
    "DeadlineExceeded",
    "FaultPlan",
    "HeartbeatWriter",
    "InjectedFault",
    "QueueOverflow",
    "ResilienceError",
    "ShutdownError",
    "arm",
    "backoff_delay",
    "configure",
    "delays",
    "disarm",
    "fault_point",
    "health_report",
    "read_heartbeats",
    "retry_call",
]
