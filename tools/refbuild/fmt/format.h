// Build shim for the parity harness: minimal fmt::format_to_n covering
// exactly the format strings LightGBM's common.h uses ("{}", "{:g}",
// "{:.17g}"), backed by snprintf. The vendored fmt submodule is not
// checked out in this image.
#ifndef FMT_FORMAT_SHIM_H_
#define FMT_FORMAT_SHIM_H_
#include <cstdio>
#include <cstring>
#include <type_traits>

namespace fmt {
struct format_to_n_result_shim {
  size_t size;
};

inline format_to_n_result_shim format_to_n(char* buf, size_t n,
                                           const char* f, double v) {
  int w;
  if (std::strcmp(f, "{:.17g}") == 0) {
    w = std::snprintf(buf, n, "%.17g", v);
  } else if (std::strcmp(f, "{:g}") == 0) {
    w = std::snprintf(buf, n, "%g", v);
  } else {
    w = std::snprintf(buf, n, "%.17g", v);
  }
  return {static_cast<size_t>(w < 0 ? n + 1 : w)};
}

inline format_to_n_result_shim format_to_n(char* buf, size_t n,
                                           const char* f, float v) {
  return format_to_n(buf, n, f, static_cast<double>(v));
}

template <typename T,
          typename std::enable_if<std::is_integral<T>::value, int>::type = 0>
inline format_to_n_result_shim format_to_n(char* buf, size_t n,
                                           const char*, T v) {
  int w = std::snprintf(buf, n, "%lld", static_cast<long long>(v));
  return {static_cast<size_t>(w < 0 ? n + 1 : w)};
}
}  // namespace fmt
#endif
