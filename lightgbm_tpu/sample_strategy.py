"""Row sampling strategies: bagging and GOSS.

Reference: include/LightGBM/sample_strategy.h:23 + src/boosting/bagging.hpp
+ src/boosting/goss.hpp. The reference materializes index lists
(bag_data_indices) via ParallelPartitionRunner; on TPU the natural form is
a per-row {0,1} mask multiplied into the gradient channels — rows outside
the bag contribute nothing to histograms or counts, while the partition
step still routes them (their scores stay correct).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config


class SampleStrategy:
    """Produces (mask, grad, hess) per iteration."""

    def __init__(self, config: Config, num_data: int):
        self.config = config
        self.num_data = num_data
        self._cached_mask: Optional[jax.Array] = None

    @property
    def is_hessian_change(self) -> bool:
        return False

    def sample(
        self, iter_num: int, grad: jax.Array, hess: jax.Array, valid: jax.Array,
        label: Optional[jax.Array],
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Returns (mask, grad, hess); grad/hess may be rescaled (GOSS)."""
        return valid, grad, hess


def _exact_fraction_mask(u, eligible, frac):
    """Select exactly round(frac * #eligible) rows: the rows whose
    uniform draw is below the k-th smallest among eligible rows. The
    reference samples exact counts (bagging.hpp via
    ParallelPartitionRunner); a plain Bernoulli mask would make bag
    sizes binomial."""
    n_elig = jnp.sum(eligible)
    k = jnp.round(n_elig * frac).astype(jnp.int32)
    ue = jnp.where(eligible, u, jnp.inf)
    sorted_u = jnp.sort(ue)
    # threshold = k-th smallest (k>=1); k==0 selects nothing
    thr = sorted_u[jnp.maximum(k - 1, 0)]
    return eligible & (u <= thr) & (k > 0)


class BaggingStrategy(SampleStrategy):
    """bagging_fraction/bagging_freq (+ pos/neg fractions, + query-level
    bagging_by_query) with EXACT bag sizes, masks regenerated every
    `bagging_freq` iterations (bagging.hpp:30)."""

    def __init__(self, config: Config, num_data: int, group=None):
        super().__init__(config, num_data)
        c = config
        self.use_pos_neg = (
            c.pos_bagging_fraction < 1.0 or c.neg_bagging_fraction < 1.0
        )
        self.enabled = c.bagging_freq > 0 and (
            c.bagging_fraction < 1.0 or self.use_pos_neg
        )
        self.by_query = bool(c.bagging_by_query)
        self._row_query = None
        if self.by_query and self.use_pos_neg:
            from . import log

            log.warning(
                "bagging_by_query ignores pos/neg_bagging_fraction; "
                "using row-level pos/neg bagging instead"
            )
            self.by_query = False
        if self.by_query:
            if group is None:
                from . import log

                log.warning(
                    "bagging_by_query requires query groups; using row-level bagging"
                )
                self.by_query = False
            else:
                g = np.asarray(group, dtype=np.int64)
                self._num_queries = len(g)
                self._row_query = jnp.asarray(
                    np.repeat(np.arange(len(g), dtype=np.int32), g)
                )

    def sample(self, iter_num, grad, hess, valid, label):
        """iter_num may be a host int or a traced int32 (fused loop): the
        mask is a pure function of the bagging window, so the reference's
        cached-mask-per-freq-window behavior falls out of keying the RNG
        on (iter // bagging_freq) with no host state."""
        c = self.config
        if not self.enabled:
            return valid, grad, hess
        it = jnp.asarray(iter_num, jnp.int32)
        window = (it // c.bagging_freq) * c.bagging_freq
        key = jax.random.fold_in(jax.random.key(c.bagging_seed), window)
        if self.by_query:
            # sample exact round(frac * Q) whole queries (bagging.hpp
            # bagging_by_query)
            uq = jax.random.uniform(key, (self._num_queries,))
            qsel = _exact_fraction_mask(
                uq, jnp.ones(self._num_queries, bool), c.bagging_fraction
            )
            n = self._row_query.shape[0]
            rowsel = jnp.zeros(valid.shape, bool).at[:n].set(
                qsel[self._row_query]
            )
            return rowsel.astype(jnp.float32) * valid, grad, hess
        u = jax.random.uniform(key, valid.shape)
        elig = valid > 0
        if self.use_pos_neg and label is not None:
            pos = _exact_fraction_mask(
                u, elig & (label > 0), c.pos_bagging_fraction
            )
            neg = _exact_fraction_mask(
                u, elig & (label <= 0), c.neg_bagging_fraction
            )
            mask = pos | neg
        else:
            mask = _exact_fraction_mask(u, elig, c.bagging_fraction)
        return mask.astype(jnp.float32) * valid, grad, hess


class GOSSStrategy(SampleStrategy):
    """Gradient one-side sampling (goss.hpp): keep the top_rate fraction by
    |g*h|, sample other_rate of the rest and amplify their grad/hess by
    (1-top_rate)/other_rate. No sampling during the first 1/learning_rate
    iterations (goss.hpp:33)."""

    @property
    def is_hessian_change(self) -> bool:
        return True

    def sample(self, iter_num, grad, hess, valid, label):
        c = self.config
        warmup = int(1.0 / c.learning_rate) + 1
        it = jnp.asarray(iter_num, jnp.int32)

        def _goss(_):
            w = jnp.abs(grad * hess) * valid
            n_valid = jnp.sum(valid)
            top_n = jnp.maximum((n_valid * c.top_rate).astype(jnp.int32), 1)
            # threshold = top_n-th largest weight
            sorted_w = jnp.sort(w)[::-1]
            thr = sorted_w[jnp.minimum(top_n, w.shape[0] - 1)]
            top_mask = w > thr
            rest = (~top_mask) & (valid > 0)
            key = jax.random.fold_in(jax.random.key(c.bagging_seed * 7919), it)
            p_rest = c.other_rate / max(1e-12, 1.0 - c.top_rate)
            rand_mask = jax.random.uniform(key, w.shape) < p_rest
            sampled = rest & rand_mask
            amp = (1.0 - c.top_rate) / max(c.other_rate, 1e-12)
            mult = top_mask.astype(jnp.float32) + sampled.astype(jnp.float32) * amp
            mask = (top_mask | sampled).astype(jnp.float32) * valid
            return mask, grad * mult, hess * mult

        def _no_sample(_):
            return valid, grad, hess

        if isinstance(iter_num, (int, np.integer)):
            # host path: avoid tracing/compiling the unused branch
            return _goss(None) if iter_num >= warmup else _no_sample(None)
        from jax import lax

        return lax.cond(it >= warmup, _goss, _no_sample, None)


def create_sample_strategy(config: Config, num_data: int, group=None) -> SampleStrategy:
    """Factory (reference sample_strategy.cpp:15)."""
    if config.data_sample_strategy == "goss":
        return GOSSStrategy(config, num_data)
    return BaggingStrategy(config, num_data, group=group)
