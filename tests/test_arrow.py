"""Arrow ingest (reference include/LightGBM/arrow.h, c_api.cpp:1645,
tests/python_package_test/test_arrow.py patterns): pyarrow Tables and
arrays feed Dataset/predict like numpy."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import lightgbm_tpu as lgb


def _table(n=800, seed=0):
    rs = np.random.RandomState(seed)
    cols = {f"f{i}": rs.randn(n) for i in range(5)}
    y = ((cols["f0"] + cols["f1"] + 0.3 * rs.randn(n)) > 0).astype(np.float64)
    return pa.table(cols), pa.array(y), np.column_stack(list(cols.values())), y


def test_dataset_from_arrow_table_matches_numpy():
    table, ay, X, y = _table()
    d_arrow = lgb.Dataset(table, label=ay, free_raw_data=False)
    d_numpy = lgb.Dataset(X, label=y, free_raw_data=False)
    params = dict(objective="binary", num_leaves=15, verbosity=-1)
    b1 = lgb.train(params, d_arrow, num_boost_round=5)
    b2 = lgb.train(params, d_numpy, num_boost_round=5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X), rtol=1e-6)
    # feature names come from the table
    assert b1.feature_name()[:2] == ["f0", "f1"]


def test_arrow_nulls_become_nan():
    t = pa.table({
        "a": pa.array([1.0, None, 3.0, None, 5.0] * 40),
        "b": pa.array(list(np.arange(200.0))),
    })
    y = np.arange(200.0)
    ds = lgb.Dataset(t, label=y, free_raw_data=False)
    ds.construct()
    assert ds._binned.num_data == 200


def test_predict_on_arrow_table():
    table, ay, X, y = _table(seed=3)
    ds = lgb.Dataset(table, label=ay, free_raw_data=False)
    bst = lgb.train(
        dict(objective="regression", num_leaves=7, verbosity=-1),
        ds, num_boost_round=3,
    )
    np.testing.assert_allclose(
        bst.predict(table), bst.predict(X), rtol=1e-12
    )


def test_arrow_weight_and_group():
    rs = np.random.RandomState(5)
    n_q, docs = 40, 5
    n = n_q * docs
    X = rs.randn(n, 4)
    y = rs.randint(0, 3, n).astype(np.float64)
    ds = lgb.Dataset(
        pa.table({f"c{i}": X[:, i] for i in range(4)}),
        label=pa.array(y),
        weight=pa.array(np.ones(n)),
        group=pa.array(np.full(n_q, docs, np.int64)),
        free_raw_data=False,
    )
    bst = lgb.train(
        {"objective": "lambdarank", "num_leaves": 7, "min_data_in_leaf": 3,
         "verbosity": -1},
        ds, num_boost_round=3,
    )
    assert bst.num_trees() == 3
