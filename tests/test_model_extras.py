"""pred_contrib (TreeSHAP), dump_model (JSON), and refit.

Modeled on reference tests/python_package_test/test_engine.py
(test_predict_contrib, test_refit) and test_basic.py dump checks.
"""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def binary_problem():
    rs = np.random.RandomState(7)
    X = rs.randn(600, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rs.randn(600) > 0).astype(float)
    return X, y


@pytest.fixture(scope="module")
def binary_booster(binary_problem):
    X, y = binary_problem
    ds = lgb.Dataset(X, label=y)
    return lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1},
        ds, num_boost_round=15,
    )


def test_pred_contrib_additivity(binary_booster, binary_problem):
    X, _ = binary_problem
    raw = binary_booster.predict(X[:80], raw_score=True)
    contrib = binary_booster.predict(X[:80], pred_contrib=True)
    assert contrib.shape == (80, X.shape[1] + 1)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-9, atol=1e-9)
    # at least the dominant feature must receive nonzero attribution
    assert np.abs(contrib[:, 0]).max() > 0


def test_pred_contrib_multiclass():
    rs = np.random.RandomState(3)
    X = rs.randn(300, 4)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "verbosity": -1},
        ds, num_boost_round=8,
    )
    contrib = bst.predict(X[:40], pred_contrib=True)
    assert contrib.shape == (40, 3 * (4 + 1))
    raw = bst.predict(X[:40], raw_score=True)  # (40, 3)
    per_class = contrib.reshape(40, 3, 5).sum(axis=2)
    np.testing.assert_allclose(per_class, raw, rtol=1e-9, atol=1e-9)


def test_dump_model_structure(binary_booster):
    d = binary_booster.dump_model()
    assert d["name"] == "tree"
    assert d["num_class"] == 1
    assert len(d["tree_info"]) == 15
    t0 = d["tree_info"][0]
    assert t0["num_leaves"] >= 2
    root = t0["tree_structure"]
    assert root["decision_type"] in ("<=", "==")
    assert "left_child" in root and "right_child" in root
    json.dumps(d)  # serializable end to end
    # walk to a leaf
    node = root
    while "leaf_index" not in node:
        node = node["left_child"]
    assert "leaf_value" in node


def test_refit(binary_booster, binary_problem):
    X, y = binary_problem
    before = binary_booster.predict(X[:20])
    new_bst = binary_booster.refit(X, 1.0 - y, decay_rate=0.0)
    after_orig = binary_booster.predict(X[:20])
    np.testing.assert_allclose(before, after_orig)  # original untouched
    flipped = new_bst.predict(X[:20])
    # refit on inverted labels must push predictions the other way
    assert np.corrcoef(before, flipped)[0, 1] < 0.5


def test_pred_early_stop():
    """prediction_early_stop.cpp: rows with a confident margin stop
    accumulating trees; with a huge margin threshold predictions match
    the full walk exactly."""
    import numpy as np

    import lightgbm_tpu as lgb

    rs = np.random.RandomState(3)
    X = rs.randn(800, 5)
    y = ((X[:, 0] + 0.2 * rs.randn(800)) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=40)
    full = bst.predict(X)
    # threshold so large nothing stops -> identical
    same = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                       pred_early_stop_margin=1e9)
    np.testing.assert_allclose(same, full, rtol=0, atol=0)
    # aggressive margin: predictions approximate but rank-correlated
    fast = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                       pred_early_stop_margin=2.0)
    assert not np.allclose(fast, full)
    assert np.corrcoef(fast, full)[0, 1] > 0.95
    # classification preserved for confident rows
    agree = ((fast > 0.5) == (full > 0.5)).mean()
    assert agree > 0.95, agree


def test_pred_early_stop_multiclass():
    import numpy as np

    import lightgbm_tpu as lgb

    rs = np.random.RandomState(5)
    X = rs.randn(900, 4)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    ds = lgb.Dataset(X, label=y.astype(float), free_raw_data=False)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1}, ds,
                    num_boost_round=20)
    full = bst.predict(X)
    fast = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=3,
                       pred_early_stop_margin=3.0)
    assert (np.argmax(fast, 1) == np.argmax(full, 1)).mean() > 0.95
