#!/usr/bin/env python3
"""Render merged observability artifacts for humans.

Merges any combination of
- per-process metrics snapshots (obs/aggregate.py files, written by
  `parallel.multihost.write_metrics_snapshot` or
  `obs.aggregate.write_snapshot`),
- live worker `/metrics` endpoints (HTTP pull),
- flight-record JSONL streams (`record_file=` runs),
- a run manifest,
into one fleet report on stdout. Host-side only — no jax import, no
collectives — so it runs anywhere the files are visible.

Examples:
  python tools/obs_report.py --snapshots /shared/obs/metrics_rank*.json
  python tools/obs_report.py --url http://worker0:8080 --url http://worker1:8080
  python tools/obs_report.py --recorder run0.jsonl run1.jsonl
  python tools/obs_report.py --manifest prof/run_manifest.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.obs import aggregate  # noqa: E402
from lightgbm_tpu.obs import recorder as rec_mod  # noqa: E402


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else f"{v:.6g}"


def render_metrics(merged: dict) -> str:
    lines = [f"== fleet metrics ({merged.get('processes', '?')} "
             "process(es)) =="]
    for name in sorted(merged.get("metrics", {})):
        fam = merged["metrics"][name]
        for key in sorted(fam.get("values", {})):
            v = fam["values"][key]
            spread = ""
            mn = fam.get("min", {}).get(key)
            mx = fam.get("max", {}).get(key)
            if mn is not None and mx is not None and mn != mx:
                spread = f"  [min {_fmt(mn)} / max {_fmt(mx)}]"
            lines.append(f"  {name}{key} {_fmt(v)}{spread}")
    return "\n".join(lines)


def render_recorder(rows: list) -> str:
    lines = [f"== flight record ({len(rows)} round(s) merged) =="]
    if not rows:
        return lines[0]
    first, last = rows[0], rows[-1]
    for label, row in (("first", first), ("last", last)):
        ev = ", ".join(
            f"{k}={_fmt(v)}" for k, v in (row.get("evals") or {}).items()
        ) or "(no evals)"
        tps = row.get("trees_per_sec")
        tail = f"  {_fmt(tps)} trees/s" if tps else ""
        lines.append(f"  {label} round {row['round']}: {ev}{tail}")
    disagree = [r["round"] for r in rows if r.get("evals_disagree")]
    if disagree:
        lines.append(
            f"  !! eval disagreement across ranks at rounds {disagree}"
        )
    return "\n".join(lines)


def render_manifest(m: dict) -> str:
    lines = ["== run manifest =="]
    dev = m.get("devices", {})
    lines.append(
        f"  backend {dev.get('backend')} x{dev.get('device_count')} "
        f"({dev.get('process_count', 1)} process(es))"
    )
    fr = m.get("flight_recorder")
    if fr:
        lines.append(
            f"  flight record: {fr.get('rounds')} rounds -> "
            f"{fr.get('path') or '(memory only)'}"
        )
        if fr.get("anomalies"):
            lines.append(f"  anomaly trips: {fr['anomalies']}")
    col = m.get("collectives", {})
    if col:
        lines.append(
            "  runtime wire bytes "
            f"{col.get('runtime_wire_bytes_estimate')} vs static pins "
            f"{col.get('static_budget_wire_bytes')}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--snapshots", nargs="*", default=[],
                    help="metrics snapshot files (globs ok)")
    ap.add_argument("--url", action="append", default=[],
                    help="worker base URL (or /metrics URL) to pull")
    ap.add_argument("--recorder", nargs="*", default=[],
                    help="flight-record JSONL files to merge by round")
    ap.add_argument("--manifest", default=None,
                    help="run manifest JSON to summarize")
    args = ap.parse_args(argv)

    shown = False
    paths = [p for pat in args.snapshots for p in sorted(glob.glob(pat))]
    snaps = [aggregate.read_snapshot(p) for p in paths]
    snaps += [
        aggregate.pull_snapshot(u, process=i)
        for i, u in enumerate(args.url)
    ]
    if snaps:
        print(render_metrics(aggregate.merge(snaps)))
        shown = True
    if args.recorder:
        streams = [rec_mod.read_stream(p) for p in args.recorder]
        print(render_recorder(aggregate.merge_recorder_streams(streams)))
        shown = True
    if args.manifest:
        with open(args.manifest) as f:
            print(render_manifest(json.load(f)))
        shown = True
    if not shown:
        ap.error("nothing to render: pass --snapshots/--url/--recorder/"
                 "--manifest")
    return 0


if __name__ == "__main__":
    sys.exit(main())
