"""Promotion gate: holdout device-eval + anomaly verdict.

A candidate v(n+1) is promoted only if BOTH hold:

- **metric gate** — on the held-out shard, the candidate's first
  configured metric is no worse than the incumbent's (within
  ``loop_gate_margin``, signed by the metric's ``higher_better``);
  the remaining configured metrics are evaluated and recorded but do
  not veto (operator dashboards, not gates);
- **anomaly gate** — zero anomaly-sentinel trips during the refit
  (obs/anomaly.py): a poisoned microbatch that spikes the loss or
  produces NaN leaves auto-reverts to v(n) (outcome ``rolled_back``)
  instead of reaching the metric comparison.

Metrics run ON DEVICE via device_metrics.DeviceEvalSet — the same
traced evaluators the training loop uses per round — over the raw
scores of the serving TensorForest, so the gate's arithmetic is the
audited no-callback jaxpr (analysis entry ``online_holdout_eval``),
not a host reimplementation. Ranking metrics (ndcg/map need query
groups) are not gate-eligible; configure a pointwise/auc metric for
the loop.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def make_holdout_evaluator(cfg, label, weight=None, num_class: int = 1):
    """Resolve the config's metric list against the device
    implementations and build the traced evaluator.

    Returns ``(names, higher_better, fn)`` with ``fn(score (K, N)) ->
    (m,) f32`` jit-compiled once per loop (labels are baked in — the
    holdout shard is fixed for the life of the loop)."""
    import jax
    import jax.numpy as jnp

    from ..device_metrics import DeviceEvalSet, supported_names
    from ..metrics import create_metrics

    metric_objs = create_metrics(cfg)
    if not metric_objs:
        raise ValueError(
            "online loop: no metric configured and the objective has no "
            "default — set metric= so the promotion gate can judge"
        )
    sup = supported_names(metric_objs)
    if sup is None:
        raise ValueError(
            "online loop: configured metrics "
            f"{[m.name for m in metric_objs]} are not device-evaluable "
            "(ranking metrics need query groups); the promotion gate "
            "requires device metrics"
        )
    names, hb = sup
    n = int(np.asarray(label).shape[0])
    label_dev = jnp.asarray(np.asarray(label), jnp.float32)
    valid = jnp.ones((n,), jnp.float32)
    w_dev = None
    if weight is not None:
        w_dev = jnp.asarray(np.asarray(weight), jnp.float32)
    ev = DeviceEvalSet(cfg, list(names), list(hb), label_dev, w_dev,
                       valid, num_class)
    fn = jax.jit(ev.__call__)
    return list(names), list(hb), fn


def raw_margins(booster, X: np.ndarray) -> np.ndarray:
    """v's raw scores on X as (K, N) f32 — scored through the serving
    TensorForest (one fused device call), the same arithmetic the
    registry serves. Used both as the gate's eval input and as the
    ``init_score`` handed to the next refit."""
    score = booster.predict(np.asarray(X), raw_score=True, device="device")
    score = np.asarray(score, dtype=np.float32)
    if score.ndim == 1:
        return score[None, :]
    return score.T.copy()  # predict returns (N, K); eval wants (K, N)


def evaluate(fn, score_kn: np.ndarray) -> List[float]:
    """Run the traced evaluator over a (K, N) score block."""
    import jax.numpy as jnp

    vals = fn(jnp.asarray(score_kn, jnp.float32))
    return [float(v) for v in np.asarray(vals)]


def decide(
    cand: List[float],
    incumbent: Optional[List[float]],
    names: List[str],
    higher_better: List[bool],
    margin: float,
    anomaly_trips: Dict[str, int],
) -> Tuple[str, str]:
    """The verdict: ``("promoted"|"rejected"|"rolled_back", reason)``.

    The FIRST metric decides (the same convention early stopping uses
    for its decision metric); ``margin`` loosens the comparison in the
    metric's worse direction. No incumbent baseline (first promotion
    after a fresh start) passes the metric gate by definition.
    """
    trips = {k: v for k, v in (anomaly_trips or {}).items() if v}
    if trips:
        return "rolled_back", f"anomaly sentinel tripped during refit: {trips}"
    if incumbent is not None:
        c, i = float(cand[0]), float(incumbent[0])
        ok = c >= i - margin if higher_better[0] else c <= i + margin
        if not ok:
            word = "fell" if higher_better[0] else "rose"
            return "rejected", (
                f"holdout {names[0]} {word}: candidate {c:.6g} vs "
                f"incumbent {i:.6g} (margin {margin:g})"
            )
    return "promoted", f"holdout {names[0]} ok: {float(cand[0]):.6g}"


# ---------------------------------------------------------------- audit
def trace_holdout_eval(n: int = 256, num_class: int = 1) -> Any:
    """Jaxpr of the gate's evaluator for the static audit
    (analysis/jaxpr_audit ENTRIES['online_holdout_eval']): auc +
    binary_logloss over a deterministic synthetic holdout — labels are
    arange-parity so the traced shapes and budgets are stable."""
    import jax
    import jax.numpy as jnp

    from ..config import Config
    from ..device_metrics import DeviceEvalSet

    cfg = Config({"objective": "binary",
                  "metric": ["auc", "binary_logloss"]})
    label = jnp.asarray((np.arange(n) % 2).astype(np.float32))
    ev = DeviceEvalSet(cfg, ["auc", "binary_logloss"], [True, False],
                       label, None, jnp.ones((n,), jnp.float32),
                       num_class)
    return jax.make_jaxpr(ev.__call__)(
        jax.ShapeDtypeStruct((num_class, n), jnp.float32)
    )
