"""Leveled logging with a pluggable callback.

Mirrors the reference logger (include/LightGBM/utils/log.h:88): levels
Debug/Info/Warning/Fatal keyed off the `verbosity` (alias `verbose`)
config value, with a registerable redirection callback
(log.h:97, python-package basic.py register_logger).
"""

from __future__ import annotations

import logging
import sys
from typing import Any, Callable, Optional

_logger: Optional[Any] = None
_info_method = "info"
_warning_method = "warning"
_debug_method: Optional[str] = None

# verbosity: <0 Fatal only, 0 Warning, 1 Info (default), >=2 Debug.
# The gate applies before any emission path — a REGISTERED logger is
# filtered exactly like the default stream output (fatal-only
# verbosity silences info/warning/debug for both; log.h:88 keys every
# sink off the same level).
_VERBOSITY = 1


class LightGBMError(Exception):
    """Error raised by lightgbm_tpu (reference: include/LightGBM/utils/log.h Fatal)."""


def register_logger(
    logger: Any,
    info_method_name: str = "info",
    warning_method_name: str = "warning",
    debug_method_name: Optional[str] = None,
) -> None:
    """Redirect framework log output to a custom logger object.

    Debug lines route to `debug_method_name` when given, else to a
    callable ``debug`` attribute when the logger has one (the
    stdlib-logging shape), else through the info method."""
    global _logger, _info_method, _warning_method, _debug_method
    if not callable(getattr(logger, info_method_name, None)):
        raise TypeError(f"logger has no callable method {info_method_name!r}")
    if not callable(getattr(logger, warning_method_name, None)):
        raise TypeError(f"logger has no callable method {warning_method_name!r}")
    if debug_method_name is not None and not callable(
        getattr(logger, debug_method_name, None)
    ):
        raise TypeError(f"logger has no callable method {debug_method_name!r}")
    _logger = logger
    _info_method = info_method_name
    _warning_method = warning_method_name
    if debug_method_name is not None:
        _debug_method = debug_method_name
    elif callable(getattr(logger, "debug", None)):
        _debug_method = "debug"
    else:
        _debug_method = None


def set_verbosity(v: int) -> None:
    global _VERBOSITY
    _VERBOSITY = int(v)


def _emit(msg: str, warning: bool = False, debug: bool = False) -> None:
    if _logger is not None:
        if debug and _debug_method is not None:
            method = _debug_method
        elif warning:
            method = _warning_method
        else:
            method = _info_method
        getattr(_logger, method)(msg)
    else:
        print(msg, file=sys.stderr if warning else sys.stdout, flush=True)


def debug(msg: str) -> None:
    if _VERBOSITY >= 2:
        _emit(f"[LightGBM-TPU] [Debug] {msg}", debug=True)


def info(msg: str) -> None:
    if _VERBOSITY >= 1:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def warning(msg: str) -> None:
    if _VERBOSITY >= 0:
        _emit(f"[LightGBM-TPU] [Warning] {msg}", warning=True)


def fatal(msg: str) -> None:
    raise LightGBMError(msg)
