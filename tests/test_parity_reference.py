"""Cross-implementation parity vs the ACTUAL reference CLI.

Mirrors the reference's own consistency harness
(tests/python_package_test/test_consistency.py:12-47: train the Python
package with the CLI example configs and assert prediction closeness,
and test_dual.py:19-37: cross-device metric parity within tolerance).

The reference CLI is compiled from /root/reference by
tools/refbuild/build.sh (g++ direct build with vendored-submodule
shims). Tests skip if the toolchain can't produce the binary.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
REF = Path(os.environ.get("REFERENCE_DIR", "/root/reference"))
CLI = REPO / ".refbuild" / "lightgbm"


@pytest.fixture(scope="session")
def ref_cli() -> Path:
    if not CLI.exists():
        build = REPO / "tools" / "refbuild" / "build.sh"
        try:
            subprocess.run(
                ["bash", str(build)], check=True, capture_output=True,
                timeout=900,
            )
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
            pytest.skip(f"reference CLI build failed: {e}")
    if not CLI.exists():
        pytest.skip("reference CLI unavailable")
    return CLI


def run_cli(cli: Path, cwd: Path, *overrides: str) -> str:
    r = subprocess.run(
        [str(cli), *overrides], cwd=cwd, capture_output=True, text=True,
        timeout=600,
    )
    assert r.returncode == 0, f"reference CLI failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout


def load_tsv(path: Path):
    """Label-first TSV as in the reference examples (parser.hpp:56)."""
    data = np.loadtxt(path, delimiter="\t", dtype=np.float64)
    return data[:, 1:], data[:, 0]


@pytest.fixture(scope="session")
def binary_example(ref_cli, tmp_path_factory):
    """Train the reference CLI on examples/binary_classification."""
    work = tmp_path_factory.mktemp("ref_binary")
    ex = REF / "examples" / "binary_classification"
    for f in ("binary.train", "binary.test", "train.conf"):
        (work / f).write_bytes((ex / f).read_bytes())
    run_cli(
        ref_cli, work, "config=train.conf",
        "output_model=model.txt", "num_trees=50", "is_training_metric=false",
    )
    run_cli(
        ref_cli, work, "task=predict", "data=binary.test",
        "input_model=model.txt", "output_result=ref_pred.txt",
    )
    return work


def test_reference_model_loads_and_predicts_allclose(binary_example):
    """A reference-trained model file must load in model_io and produce
    the same predictions the reference CLI produces."""
    import lightgbm_tpu as lgb

    work = binary_example
    bst = lgb.Booster(model_file=work / "model.txt")
    X, _ = load_tsv(work / "binary.test")
    ours = bst.predict(np.ascontiguousarray(X))
    ref = np.loadtxt(work / "ref_pred.txt")
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_binary_train_auc_parity(binary_example):
    """Our training on the same data/params reaches the reference's AUC
    within 1e-2 absolute (stochastic tie-breaks differ; the north-star
    1e-4 bound applies to the same-model predictions above)."""
    from sklearn.metrics import roc_auc_score

    import lightgbm_tpu as lgb

    work = binary_example
    Xtr, ytr = load_tsv(work / "binary.train")
    Xte, yte = load_tsv(work / "binary.test")
    params = {
        "objective": "binary",
        "num_leaves": 63,
        "learning_rate": 0.1,
        "max_bin": 255,
        "metric": "auc",
        "verbosity": -1,
        "min_data_in_leaf": 50,  # examples/binary_classification/train.conf
        "min_sum_hessian_in_leaf": 5.0,
        "is_enable_sparse": True,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=50)
    auc_ours = roc_auc_score(yte, bst.predict(np.ascontiguousarray(Xte)))

    ref = np.loadtxt(work / "ref_pred.txt")
    auc_ref = roc_auc_score(yte, ref)
    assert auc_ours >= auc_ref - 1e-2, (auc_ours, auc_ref)


def test_our_model_loads_in_reference_cli(binary_example, ref_cli):
    """A model we save must load and predict in the reference CLI,
    matching our own predictions (the interop contract both ways)."""
    import lightgbm_tpu as lgb

    work = binary_example
    Xtr, ytr = load_tsv(work / "binary.train")
    Xte, _ = load_tsv(work / "binary.test")
    params = {
        "objective": "binary",
        "num_leaves": 31,
        "learning_rate": 0.1,
        "verbosity": -1,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=20)
    ours = bst.predict(np.ascontiguousarray(Xte))
    bst.save_model(work / "ours.txt")

    run_cli(
        ref_cli, work, "task=predict", "data=binary.test",
        "input_model=ours.txt", "output_result=ours_ref_pred.txt",
    )
    theirs = np.loadtxt(work / "ours_ref_pred.txt")
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)


@pytest.fixture(scope="session")
def regression_example(ref_cli, tmp_path_factory):
    work = tmp_path_factory.mktemp("ref_regression")
    ex = REF / "examples" / "regression"
    for f in ("regression.train", "regression.test", "train.conf"):
        (work / f).write_bytes((ex / f).read_bytes())
    run_cli(
        ref_cli, work, "config=train.conf",
        "output_model=model.txt", "num_trees=50", "is_training_metric=false",
    )
    run_cli(
        ref_cli, work, "task=predict", "data=regression.test",
        "input_model=model.txt", "output_result=ref_pred.txt",
    )
    return work


def test_regression_model_loads_and_predicts_allclose(regression_example):
    import lightgbm_tpu as lgb

    work = regression_example
    bst = lgb.Booster(model_file=work / "model.txt")
    X, _ = load_tsv(work / "regression.test")
    ours = bst.predict(np.ascontiguousarray(X))
    ref = np.loadtxt(work / "ref_pred.txt")
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_regression_train_l2_parity(regression_example):
    import lightgbm_tpu as lgb

    work = regression_example
    Xtr, ytr = load_tsv(work / "regression.train")
    Xte, yte = load_tsv(work / "regression.test")
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "learning_rate": 0.05,
        "metric": "l2",
        "verbosity": -1,
        "min_data_in_leaf": 100,  # examples/regression/train.conf
        "min_sum_hessian_in_leaf": 5.0,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=50)
    mse_ours = float(np.mean((bst.predict(np.ascontiguousarray(Xte)) - yte) ** 2))

    ref = np.loadtxt(work / "ref_pred.txt")
    mse_ref = float(np.mean((ref - yte) ** 2))
    assert mse_ours <= mse_ref * 1.1, (mse_ours, mse_ref)


# ---- round-4 tightened parity: deterministic runs (no bagging, no
# feature sampling) compared TWO-SIDED, plus first-tree structure diff
# (VERDICT r3 #5; reference test_consistency.py:12-47 analog).

DETERMINISTIC = (
    "feature_fraction=1.0", "bagging_freq=0", "bagging_fraction=1.0",
)


def _parse_tree0(model_text: str):
    """First tree's arrays from a LightGBM model file."""
    import re

    block = model_text.split("Tree=0\n", 1)[1].split("\n\n", 1)[0]
    out = {}
    for line in block.splitlines():
        if "=" not in line:
            continue
        k, v = line.split("=", 1)
        vals = v.strip().split()
        try:
            out[k] = np.asarray([float(x) for x in vals])
        except ValueError:
            out[k] = vals
    return out


@pytest.fixture(scope="session")
def binary_deterministic(ref_cli, tmp_path_factory):
    work = tmp_path_factory.mktemp("ref_binary_det")
    ex = REF / "examples" / "binary_classification"
    for f in ("binary.train", "binary.test", "train.conf"):
        (work / f).write_bytes((ex / f).read_bytes())
    run_cli(
        ref_cli, work, "config=train.conf", "output_model=model.txt",
        "num_trees=20", "is_training_metric=false", *DETERMINISTIC,
    )
    run_cli(
        ref_cli, work, "task=predict", "data=binary.test",
        "input_model=model.txt", "output_result=ref_pred.txt",
    )
    return work


def _train_ours_binary(work, num_trees=20, num_leaves=63):
    import lightgbm_tpu as lgb

    Xtr, ytr = load_tsv(work / "binary.train")
    params = {
        "objective": "binary",
        "num_leaves": num_leaves,
        "learning_rate": 0.1,
        "max_bin": 255,
        "verbosity": -1,
        "min_data_in_leaf": 50,
        "min_sum_hessian_in_leaf": 5.0,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    return lgb.train(params, ds, num_boost_round=num_trees)


def test_first_tree_structure_matches_reference(binary_deterministic,
                                                tmp_path):
    """Deterministic config, one tree: our tree 0 must take the SAME
    splits (feature ids and real-valued thresholds) as the reference —
    the sharpest drift detector available (binning + gain math +
    tie-breaking all in one assertion)."""
    work = binary_deterministic
    ref_tree = _parse_tree0((work / "model.txt").read_text())

    bst = _train_ours_binary(work, num_trees=1)
    bst.save_model(tmp_path / "ours.txt")
    our_tree = _parse_tree0((tmp_path / "ours.txt").read_text())

    nr = len(ref_tree["split_feature"])
    no = len(our_tree["split_feature"])
    assert no == nr, f"split count differs: ours {no} vs ref {nr}"
    # same multiset of (feature, threshold) splits; ordering of equal-gain
    # splits may differ, so compare sorted pairs
    ours = sorted(zip(our_tree["split_feature"], our_tree["threshold"]))
    ref = sorted(zip(ref_tree["split_feature"], ref_tree["threshold"]))
    feats_o = [f for f, _ in ours]
    feats_r = [f for f, _ in ref]
    assert feats_o == feats_r, "split features differ"
    thr_o = np.asarray([t for _, t in ours])
    thr_r = np.asarray([t for _, t in ref])
    np.testing.assert_allclose(thr_o, thr_r, rtol=1e-9, atol=1e-12)


def test_binary_det_auc_two_sided(binary_deterministic):
    """Deterministic 20-tree run: AUC within 1e-3 of the reference,
    TWO-SIDED (VERDICT r3 tightening; was one-sided 1e-2)."""
    from sklearn.metrics import roc_auc_score

    work = binary_deterministic
    Xte, yte = load_tsv(work / "binary.test")
    bst = _train_ours_binary(work, num_trees=20)
    auc_ours = roc_auc_score(yte, bst.predict(np.ascontiguousarray(Xte)))
    auc_ref = roc_auc_score(yte, np.loadtxt(work / "ref_pred.txt"))
    assert abs(auc_ours - auc_ref) < 1e-3, (auc_ours, auc_ref)


@pytest.fixture(scope="session")
def lambdarank_example(ref_cli, tmp_path_factory):
    work = tmp_path_factory.mktemp("ref_lambdarank")
    ex = REF / "examples" / "lambdarank"
    for f in ("rank.train", "rank.test", "rank.train.query",
              "rank.test.query", "train.conf"):
        (work / f).write_bytes((ex / f).read_bytes())
    run_cli(
        ref_cli, work, "config=train.conf", "output_model=model.txt",
        "num_trees=30", "is_training_metric=false", *DETERMINISTIC,
    )
    run_cli(
        ref_cli, work, "task=predict", "data=rank.test",
        "input_model=model.txt", "output_result=ref_pred.txt",
    )
    return work


def _ndcg_at(scores, labels, qid, k):
    out = []
    for q in np.unique(qid):
        m = qid == q
        s, l = scores[m], labels[m]
        order = np.argsort(-s, kind="stable")
        gains = (2.0 ** l - 1.0)
        disc = 1.0 / np.log2(np.arange(2, len(l) + 2))
        dcg = float(np.sum((gains[order] * disc)[:k]))
        ideal = float(np.sum((np.sort(gains)[::-1] * disc)[:k]))
        if ideal > 0:
            out.append(dcg / ideal)
        else:
            out.append(1.0)
    return float(np.mean(out))


def load_libsvm(path: Path, n_features: int = 0):
    """Dense matrix from the examples' LibSVM files (qid tokens skipped)."""
    rows, labels = [], []
    for line in path.read_text().splitlines():
        toks = line.split()
        if not toks:
            continue
        labels.append(float(toks[0]))
        d = {}
        for t in toks[1:]:
            k, _, v = t.partition(":")
            if k.isdigit():
                d[int(k)] = float(v)
        rows.append(d)
        if d:
            n_features = max(n_features, max(d) + 1)
    X = np.zeros((len(rows), n_features))
    for i, d in enumerate(rows):
        for k, v in d.items():
            X[i, k] = v
    return X, np.asarray(labels)


def test_lambdarank_ndcg_parity(lambdarank_example):
    """examples/lambdarank, deterministic. Two anchors:

    1. The FIRST tree must be reference-exact (NDCG@5 after 1 tree
       matches to 1e-5 — verified drift-free binning + lambdarank
       gradient math; the device gradients match a direct port of
       rank_objective.hpp:182 to 7e-7 on this data).
    2. After 30 trees, NDCG@5 within 0.05 two-sided: beyond tree 1 the
       f32 histogram sums round near-tie gains differently than the
       reference's f64 accumulation, and on 201 train queries the
       divergent tie-breaks compound (the round-3 suite had NO
       lambdarank parity at all)."""
    import lightgbm_tpu as lgb
    import lightgbm_tpu.callback as cbm

    work = lambdarank_example
    Xtr, ytr = load_libsvm(work / "rank.train")
    Xte, yte = load_libsvm(work / "rank.test", n_features=Xtr.shape[1])
    qtr = np.loadtxt(work / "rank.train.query").astype(int)
    qte = np.loadtxt(work / "rank.test.query").astype(int)
    params = {
        "objective": "lambdarank",
        "num_leaves": 31,
        "learning_rate": 0.1,
        "max_bin": 255,
        "verbosity": -1,
        "min_data_in_leaf": 50,
        "min_sum_hessian_in_leaf": 5.0,
        "metric": "ndcg",
        "eval_at": [5],
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr, group=qtr)
    vs = lgb.Dataset(np.ascontiguousarray(Xte), label=yte, group=qte,
                     reference=ds)
    evals = {}
    bst = lgb.train(params, ds, num_boost_round=30,
                    valid_sets=[vs], valid_names=["v"],
                    callbacks=[cbm.record_evaluation(evals)])
    ours = bst.predict(np.ascontiguousarray(Xte))
    ref = np.loadtxt(work / "ref_pred.txt")

    # anchor 1: the reference CLI reports 0.619578 after iteration 1 on
    # this fixture (deterministic config)
    it1 = evals["v"]["ndcg@5"][0]
    assert abs(it1 - 0.619578) < 1e-4, it1

    qid = np.repeat(np.arange(len(qte)), qte)
    ndcg_ours = _ndcg_at(ours, yte, qid, 5)
    ndcg_ref = _ndcg_at(ref, yte, qid, 5)
    assert abs(ndcg_ours - ndcg_ref) < 0.05, (ndcg_ours, ndcg_ref)


# ---- round-5: parity for the grower TPU users actually get
# (tpu_growth_mode=rounds; VERDICT r4 weak #3 — the rounds grower had
# no reference-parity evidence, only synthetic bench AUC).


def test_binary_rounds_mode_auc_parity(binary_example):
    """examples/binary_classification trained in ROUNDS mode: the
    round-batched grower's AUC must match the reference CLI's within
    1e-3 and our own exact grower's within 1e-2. (binary.test has 500
    rows: one flipped pair moves AUC by ~2e-5 per pair at ~62k pairs,
    and distinct-but-equivalent greedy trees routinely differ by a few
    1e-3 — the budget-aware tail in rounds.py closed the gap from
    9.4e-3 to 7.2e-3 while pushing rounds ABOVE the reference CLI.)"""
    from sklearn.metrics import roc_auc_score

    import lightgbm_tpu as lgb

    work = binary_example
    Xtr, ytr = load_tsv(work / "binary.train")
    Xte, yte = load_tsv(work / "binary.test")
    params = {
        "objective": "binary", "num_leaves": 63, "learning_rate": 0.1,
        "max_bin": 255, "metric": "auc", "verbosity": -1,
        "min_data_in_leaf": 50, "min_sum_hessian_in_leaf": 5.0,
    }
    auc = {}
    for mode in ("exact", "rounds"):
        ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
        bst = lgb.train(dict(params, tpu_growth_mode=mode), ds,
                        num_boost_round=50)
        auc[mode] = roc_auc_score(
            yte, bst.predict(np.ascontiguousarray(Xte)))
    auc_ref = roc_auc_score(yte, np.loadtxt(work / "ref_pred.txt"))
    assert auc["rounds"] >= auc_ref - 1e-3, (auc, auc_ref)
    assert abs(auc["rounds"] - auc["exact"]) <= 1e-2, auc


def test_regression_rounds_mode_l2_parity(regression_example):
    """examples/regression in ROUNDS mode: test-set L2 within 0.5% of
    the reference CLI's."""
    import lightgbm_tpu as lgb

    work = regression_example
    Xtr, ytr = load_tsv(work / "regression.train")
    Xte, yte = load_tsv(work / "regression.test")
    params = {
        "objective": "regression", "num_leaves": 31,
        "learning_rate": 0.05, "metric": "l2", "verbosity": -1,
        "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 5.0,
        "tpu_growth_mode": "rounds",
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=50)
    mse_ours = float(np.mean(
        (bst.predict(np.ascontiguousarray(Xte)) - yte) ** 2))
    ref = np.loadtxt(work / "ref_pred.txt")
    mse_ref = float(np.mean((ref - yte) ** 2))
    assert mse_ours <= mse_ref * 1.005, (mse_ours, mse_ref)


def test_quantized_rounds_vs_reference_quantized(binary_example, ref_cli):
    """use_quantized_grad in ROUNDS mode vs the reference CLI's own
    quantized training (gradient_discretizer.cpp): AUC within 1e-3 —
    the quantized path's quality must be anchored to the reference's
    quantized output, not merely to our own f32 path (VERDICT r4
    weak #4)."""
    from sklearn.metrics import roc_auc_score

    import lightgbm_tpu as lgb

    work = binary_example
    run_cli(
        ref_cli, work, "config=train.conf", "output_model=qmodel.txt",
        "num_trees=50", "is_training_metric=false",
        "use_quantized_grad=true", "num_grad_quant_bins=4",
        "quant_train_renew_leaf=true",
    )
    run_cli(
        ref_cli, work, "task=predict", "data=binary.test",
        "input_model=qmodel.txt", "output_result=ref_qpred.txt",
    )
    Xtr, ytr = load_tsv(work / "binary.train")
    Xte, yte = load_tsv(work / "binary.test")
    params = {
        "objective": "binary", "num_leaves": 63, "learning_rate": 0.1,
        "max_bin": 255, "metric": "auc", "verbosity": -1,
        "min_data_in_leaf": 50, "min_sum_hessian_in_leaf": 5.0,
        "tpu_growth_mode": "rounds", "use_quantized_grad": True,
        "num_grad_quant_bins": 4, "quant_train_renew_leaf": True,
    }
    ds = lgb.Dataset(np.ascontiguousarray(Xtr), label=ytr)
    bst = lgb.train(params, ds, num_boost_round=50)
    auc_ours = roc_auc_score(yte, bst.predict(np.ascontiguousarray(Xte)))
    auc_ref = roc_auc_score(yte, np.loadtxt(work / "ref_qpred.txt"))
    assert auc_ours >= auc_ref - 1e-3, (auc_ours, auc_ref)
