"""Text data loading: CSV / TSV / LibSVM parsers with format
autodetection, label/weight/group/ignore column handling, metadata
sidecar files, and a binned-dataset binary cache.

Reference surface: src/io/parser.cpp (CSVParser parser.hpp:18,
TSVParser :56, LibSVMParser :93, autodetection parser.cpp), the
DatasetLoader text pipeline (dataset_loader.cpp:210 LoadFromFile) and
its sidecar metadata loading (src/io/metadata.cpp: <data>.weight,
<data>.query / <data>.group, <data>.init), and the binary dataset cache
(Dataset::SaveBinaryFile dataset.h:700, loader fast path
dataset_loader.cpp:424). TPU-first deviation: parsing is a host-side
numpy pipeline producing one dense float32 matrix (the device wants one
padded feature-major bin matrix anyway); the .bin cache stores the
ALREADY-BINNED dataset (mappers + bin matrix + metadata) as an npz, so
a cached load skips both parsing and GreedyFindBin — bin once, train
many, as the reference recommends for Criteo-scale runs.
"""

from __future__ import annotations

import io
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import log

BIN_MAGIC = "lightgbm_tpu.bin.v1"


# ---------------------------------------------------------------------------
# format detection (reference parser.cpp GetParserType)
# ---------------------------------------------------------------------------
def detect_format(sample_lines: List[str]) -> str:
    """Return 'libsvm' | 'tsv' | 'csv' from a handful of data lines."""
    for line in sample_lines:
        if re.search(r"\d+:[\d.eE+-]+", line) and ":" in line.split()[-1]:
            return "libsvm"
    tabs = sum(line.count("\t") for line in sample_lines)
    commas = sum(line.count(",") for line in sample_lines)
    if tabs >= commas and tabs > 0:
        return "tsv"
    if commas > 0:
        return "csv"
    return "tsv"  # single-column / space-separated fallback


def _read_lines(path: Path, limit: Optional[int] = None) -> List[str]:
    out = []
    with open(path, "r") as f:
        for i, line in enumerate(f):
            if limit is not None and i >= limit:
                break
            line = line.strip("\r\n")
            if line:
                out.append(line)
    return out


def _parse_delim(path: Path, delim: str, header: bool) -> Tuple[np.ndarray, List[str]]:
    names: List[str] = []
    skip = 0
    if header:
        first = _read_lines(path, 1)[0]
        names = [c.strip() for c in first.split(delim)]
        skip = 1
    # native C++ fast path (native/fastparse.cpp, the reference's
    # src/io/parser.cpp CSVParser equivalent); numpy fallback otherwise
    from . import native

    data = native.parse_delim(str(path), delim, skip)
    if data is None:
        data = np.loadtxt(
            path, delimiter=delim, skiprows=skip, dtype=np.float64, ndmin=2,
        )
    return data, names


def _parse_libsvm(path: Path) -> Tuple[np.ndarray, np.ndarray]:
    """LibSVM 'label idx:val ...' -> (label, dense matrix); 0-based or
    1-based indices both appear in the wild — indices are used as-is
    (reference LibSVMParser keeps raw indices)."""
    from . import native

    res = native.parse_libsvm(str(path))
    if res is not None:
        return res
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = -1
    with open(path) as f:
        for line in f:
            parts = line.strip().split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            row: Dict[int, float] = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                k, v = tok.split(":", 1)
                idx = int(k)
                row[idx] = float(v)
                max_idx = max(max_idx, idx)
            rows.append(row)
    X = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for i, row in enumerate(rows):
        for k, v in row.items():
            X[i, k] = v
    return np.asarray(labels), X


def _resolve_column(spec: Any, names: List[str]) -> Optional[int]:
    """Column spec: int index, 'name:<col>' or '<int>' (config.h
    label_column semantics)."""
    if spec is None or spec == "":
        return None
    s = str(spec)
    if s.startswith("name:"):
        nm = s[5:]
        if nm not in names:
            log.fatal(f"column name {nm} not found in header")
        return names.index(nm)
    return int(s)


def _resolve_columns(spec: Any, names: List[str]) -> List[int]:
    if spec is None or spec == "":
        return []
    s = str(spec)
    if s.startswith("name:"):
        return [names.index(n) for n in s[5:].split(",") if n in names]
    return [int(c) for c in s.split(",") if c != ""]


def load_text_file(
    path: str,
    *,
    header: bool = False,
    label_column: Any = 0,
    weight_column: Any = "",
    group_column: Any = "",
    ignore_column: Any = "",
    categorical_feature: Any = "",
) -> Dict[str, Any]:
    """Parse a text data file into {X, label, weight, group,
    feature_names, categorical_feature} (host numpy).

    Sidecar files (reference metadata.cpp LoadWeights/LoadQueryBoundaries
    /LoadInitialScore): <path>.weight (one per row), <path>.query or
    <path>.group (rows per query), <path>.init (initial scores).
    """
    p = Path(path)
    if not p.exists():
        log.fatal(f"data file {path} does not exist")
    sample = _read_lines(p, 5)
    fmt = detect_format(sample[1:] if header and len(sample) > 1 else sample)

    weight = None
    group = None
    init_score = None
    if fmt == "libsvm":
        label, X = _parse_libsvm(p)
        names: List[str] = []
    else:
        delim = "\t" if fmt == "tsv" else ","
        data, names = _parse_delim(p, delim, header)
        lbl_idx = _resolve_column(label_column, names)
        w_idx = _resolve_column(weight_column, names)
        g_idx = _resolve_column(group_column, names)
        ign = set(_resolve_columns(ignore_column, names))

        label = data[:, lbl_idx] if lbl_idx is not None else np.zeros(len(data))
        weight = data[:, w_idx] if w_idx is not None else None
        qid = data[:, g_idx] if g_idx is not None else None
        drop = {i for i in (lbl_idx, w_idx, g_idx) if i is not None} | ign
        keep = [i for i in range(data.shape[1]) if i not in drop]
        X = data[:, keep]
        names = [names[i] for i in keep] if names else []
        if qid is not None:
            # query id column -> per-query row counts (contiguous runs)
            runs = np.flatnonzero(np.diff(qid)) + 1
            group = np.diff(np.concatenate([[0], runs, [len(qid)]])).astype(np.int64)

    # ---- sidecars
    wf = Path(str(p) + ".weight")
    if weight is None and wf.exists():
        weight = np.loadtxt(wf, dtype=np.float64, ndmin=1)
    qf = Path(str(p) + ".query")
    gf = Path(str(p) + ".group")
    if group is None:
        if qf.exists():
            group = np.loadtxt(qf, dtype=np.int64, ndmin=1)
        elif gf.exists():
            group = np.loadtxt(gf, dtype=np.int64, ndmin=1)
    inf = Path(str(p) + ".init")
    if inf.exists():
        init_score = np.loadtxt(inf, dtype=np.float64, ndmin=1)

    cats = _resolve_columns(categorical_feature, names)
    return {
        "X": X,
        "label": label,
        "weight": weight,
        "group": group,
        "init_score": init_score,
        "feature_names": names or None,
        "categorical_feature": cats or None,
    }


# ---------------------------------------------------------------------------
# streamed two-round loading (reference dataset_loader.cpp:210 two_round
# + :1399 two-pass extract): host memory stays O(chunk), never O(file)
# ---------------------------------------------------------------------------
def iter_text_chunks(path: Path, delim: str, skip: int,
                     chunk_rows: int = 65536):
    """Yield (n_rows, float64 matrix) chunks of a delimited text file.
    One sequential read; memory is bounded by chunk_rows lines."""
    buf: List[str] = []
    with open(path, "r") as f:
        for _ in range(skip):
            f.readline()
        for line in f:
            line = line.strip("\r\n")
            if not line:
                continue
            buf.append(line)
            if len(buf) >= chunk_rows:
                yield np.loadtxt(io.StringIO("\n".join(buf)),
                                 delimiter=delim, dtype=np.float64,
                                 ndmin=2)
                buf = []
    if buf:
        yield np.loadtxt(io.StringIO("\n".join(buf)), delimiter=delim,
                         dtype=np.float64, ndmin=2)


def scan_text_file(path: Path, delim: str, skip: int, n_sample: int,
                   seed: int, keep_cols: List[int],
                   small_cols: List[Optional[int]],
                   chunk_rows: int = 65536):
    """Pass 1 of two_round loading: ONE sequential read that
    reservoir-samples `n_sample` feature rows (Algorithm R,
    vectorized per chunk — the reference's SampleTextData equivalent,
    dataset_loader.cpp:1399) and collects the per-row metadata columns
    in full (they are O(N) scalars, not O(N x F)).

    Returns (total_rows, sample (n, F), [per-col metadata arrays])."""
    rng = np.random.RandomState(seed)
    reservoir: Optional[np.ndarray] = None
    seen = 0
    meta_parts: List[List[np.ndarray]] = [[] for _ in small_cols]
    for chunk in iter_text_chunks(path, delim, skip, chunk_rows):
        m = len(chunk)
        feats = chunk[:, keep_cols]
        for j, c in enumerate(small_cols):
            if c is not None:
                meta_parts[j].append(chunk[:, c].copy())
        if reservoir is None:
            reservoir = np.empty((n_sample, feats.shape[1]), np.float64)
        fill = min(max(n_sample - seen, 0), m)
        if fill:
            reservoir[seen:seen + fill] = feats[:fill]
        if m > fill:
            # rows seen+fill+1 .. seen+m: accept with prob n/(index),
            # replacing a uniform slot — exactly Algorithm R
            idx = np.arange(seen + fill + 1, seen + m + 1)
            accept = rng.rand(m - fill) < (n_sample / idx)
            nacc = int(accept.sum())
            if nacc:
                slots = rng.randint(0, n_sample, nacc)
                reservoir[slots] = feats[fill:][accept]
        seen += m
    if seen == 0:
        log.fatal(f"data file {path} has no data rows")
    metas = [
        (np.concatenate(p) if p else None) for p in meta_parts
    ]
    return seen, reservoir[: min(n_sample, seen)], metas


def load_text_file_two_round(
    path: str,
    config,
    *,
    header: bool = False,
    label_column: Any = 0,
    weight_column: Any = "",
    group_column: Any = "",
    ignore_column: Any = "",
    categorical_feature: Any = "",
    chunk_rows: int = 65536,
) -> Dict[str, Any]:
    """Streamed (two_round) load: pass 1 samples + counts, pass 2
    bins chunk by chunk into the int bin matrix — the full float
    matrix never exists in host memory (the Criteo-scale path:
    176 GB text -> the binned matrix per host). Delimited formats
    only; LibSVM falls back to the whole-file loader."""
    from .dataset import BinnedDataset, Metadata, bin_chunk

    p = Path(path)
    if not p.exists():
        log.fatal(f"data file {path} does not exist")
    sample_lines = _read_lines(p, 5)
    fmt = detect_format(
        sample_lines[1:] if header and len(sample_lines) > 1
        else sample_lines
    )
    if fmt == "libsvm":
        log.warning(
            "two_round streaming supports delimited formats; LibSVM "
            "falls back to whole-file loading"
        )
        return None
    delim = "\t" if fmt == "tsv" else ","
    names: List[str] = []
    skip = 0
    if header:
        names = [c.strip() for c in sample_lines[0].split(delim)]
        skip = 1
    ncol = len(sample_lines[skip].split(delim))
    lbl_idx = _resolve_column(label_column, names)
    w_idx = _resolve_column(weight_column, names)
    g_idx = _resolve_column(group_column, names)
    ign = set(_resolve_columns(ignore_column, names))
    drop = {i for i in (lbl_idx, w_idx, g_idx) if i is not None} | ign
    keep = [i for i in range(ncol) if i not in drop]
    feat_names = [names[i] for i in keep] if names else []

    total, sample, (label, weight, qid) = scan_text_file(
        p, delim, skip, min(config.bin_construct_sample_cnt, 10 ** 9),
        config.data_random_seed, keep, [lbl_idx, w_idx, g_idx],
        chunk_rows=chunk_rows,
    )
    cats = _resolve_columns(categorical_feature, feat_names)
    proto = BinnedDataset.from_numpy(
        sample, config, categorical_feature=cats or None,
        feature_names=feat_names or None,
    )
    G = proto.bins.shape[0]
    dtype = proto.bins.dtype
    bins = np.empty((G, total), dtype=dtype)
    row0 = 0
    for chunk in iter_text_chunks(p, delim, skip, chunk_rows):
        sub = bin_chunk(proto, chunk[:, keep], dtype)
        bins[:, row0:row0 + len(chunk)] = sub
        row0 += len(chunk)

    group = None
    if qid is not None:
        runs = np.flatnonzero(np.diff(qid)) + 1
        group = np.diff(
            np.concatenate([[0], runs, [len(qid)]])
        ).astype(np.int64)
    init_score = None
    wf = Path(str(p) + ".weight")
    if weight is None and wf.exists():
        weight = np.loadtxt(wf, dtype=np.float64, ndmin=1)
    qf, gf = Path(str(p) + ".query"), Path(str(p) + ".group")
    if group is None and qf.exists():
        group = np.loadtxt(qf, dtype=np.int64, ndmin=1)
    elif group is None and gf.exists():
        group = np.loadtxt(gf, dtype=np.int64, ndmin=1)
    inf = Path(str(p) + ".init")
    if inf.exists():
        init_score = np.loadtxt(inf, dtype=np.float64, ndmin=1)

    meta = Metadata(
        label=(np.asarray(label, np.float32)
               if label is not None else np.zeros(total, np.float32)),
        weight=(np.asarray(weight, np.float32)
                if weight is not None else None),
        group=group,
        init_score=(np.asarray(init_score, np.float64)
                    if init_score is not None else None),
        position=None,
    )
    meta.check(total)
    binned = BinnedDataset(
        bins=bins,
        mappers=proto.mappers,
        used_features=proto.used_features,
        num_data=total,
        metadata=meta,
        feature_names=list(proto.feature_names),
        max_num_bin=proto.max_num_bin,
        row_block=proto.row_block,
        monotone_constraints=proto.monotone_constraints,
        raw_data=None,
        bundle_layout=proto.bundle_layout,
        bundle_expand=proto.bundle_expand,
    )
    return {"binned": binned, "feature_names": feat_names or None,
            "categorical_feature": cats or None}


# ---------------------------------------------------------------------------
# binned dataset binary cache (.bin)
# ---------------------------------------------------------------------------
def save_binary(binned, path: str) -> None:
    """Serialize a constructed BinnedDataset (reference SaveBinaryFile,
    dataset.h:700). Stores bin matrix + per-feature mappers + metadata;
    loading skips parsing and FindBin entirely."""
    from .binning import BinMapper, BinType, MissingType

    m = binned.metadata
    mapper_blobs = []
    for mp in binned.mappers:
        mapper_blobs.append(dict(
            upper_bounds=np.asarray(mp.upper_bounds, np.float64),
            bin_type=int(mp.bin_type.value),
            missing_type=int(mp.missing_type.value),
            categories=np.asarray(mp.categories, np.int64),
            num_bin=mp.num_bin,
            is_trivial=int(mp.is_trivial),
            min_value=mp.min_value,
            max_value=mp.max_value,
            most_freq_bin=mp.most_freq_bin,
            default_bin=mp.default_bin,
        ))
    import pickle

    # streamed (disk-backed) datasets hold a (G, 0) placeholder; pull
    # the real matrix back chunk-wise (warns through the budget path)
    bins_matrix = (
        binned.materialize_bins()
        if hasattr(binned, "materialize_bins")
        else binned.bins
    )
    fh = open(path, "wb")  # np.savez appends .npz to bare paths
    np.savez_compressed(
        fh,
        magic=BIN_MAGIC,
        bins=bins_matrix,
        used_features=np.asarray(binned.used_features, np.int64),
        label=np.asarray(m.label, np.float64) if m.label is not None else np.zeros(0),
        has_label=m.label is not None,
        weight=np.asarray(m.weight, np.float64) if m.weight is not None else np.zeros(0),
        has_weight=m.weight is not None,
        group=np.asarray(m.group, np.int64) if m.group is not None else np.zeros(0, np.int64),
        has_group=m.group is not None,
        init_score=np.asarray(m.init_score, np.float64) if m.init_score is not None else np.zeros(0),
        has_init=m.init_score is not None,
        feature_names=np.asarray(binned.feature_names, dtype=object) if binned.feature_names else np.zeros(0, dtype=object),
        mappers=np.frombuffer(pickle.dumps(mapper_blobs), dtype=np.uint8),
        num_data=binned.num_data,
        row_block=binned.row_block,
        mono=(
            np.asarray(binned.monotone_constraints, np.int8)
            if binned.monotone_constraints is not None
            else np.zeros(0, np.int8)
        ),
    )
    fh.close()


def is_binary_file(path: str) -> bool:
    if not os.path.exists(path):
        return False
    try:
        with np.load(path, allow_pickle=True) as z:
            return str(z.get("magic", "")) == BIN_MAGIC
    except Exception:  # noqa: BLE001 — any non-npz file is "not a cache"
        return False


def load_binary(path: str):
    """Load a .bin cache back into a BinnedDataset."""
    import pickle

    from .binning import BinMapper, BinType, MissingType
    from .dataset import BinnedDataset, Metadata

    with np.load(path, allow_pickle=True) as z:
        if str(z["magic"]) != BIN_MAGIC:
            log.fatal(f"{path} is not a lightgbm_tpu binary dataset")
        mapper_blobs = pickle.loads(z["mappers"].tobytes())
        mappers = []
        for b in mapper_blobs:
            mp = BinMapper(
                upper_bounds=b["upper_bounds"],
                bin_type=BinType(b["bin_type"]),
                missing_type=MissingType(b["missing_type"]),
                categories=tuple(int(c) for c in b["categories"]),
                num_bin=int(b["num_bin"]),
                most_freq_bin=int(b["most_freq_bin"]),
                default_bin=int(b["default_bin"]),
                is_trivial=bool(b["is_trivial"]),
                min_value=float(b["min_value"]),
                max_value=float(b["max_value"]),
            )
            if mp.bin_type == BinType.CATEGORICAL:
                mp._cat_to_bin = {int(c): i for i, c in enumerate(mp.categories)}
            mappers.append(mp)
        meta = Metadata(
            label=z["label"] if bool(z["has_label"]) else None,
            weight=z["weight"] if bool(z["has_weight"]) else None,
            group=z["group"] if bool(z["has_group"]) else None,
            init_score=z["init_score"] if bool(z["has_init"]) else None,
        )
        names = [str(n) for n in z["feature_names"]] if len(z["feature_names"]) else None
        used = np.asarray(z["used_features"], np.int64)
        max_num_bin = max((mappers[f].num_bin for f in used), default=1)
        mono = np.asarray(z["mono"], np.int8) if "mono" in z and len(z["mono"]) else None
        ds = BinnedDataset(
            bins=np.asarray(z["bins"]),  # keep the stored narrow dtype
            mappers=mappers,
            used_features=used,
            metadata=meta,
            num_data=int(z["num_data"]),
            feature_names=names or [f"Column_{i}" for i in range(len(mappers))],
            max_num_bin=max_num_bin,
            row_block=int(z["row_block"]),
            monotone_constraints=mono,
        )
        return ds
