"""Named per-phase accumulating timers (the reference's USE_TIMETAG
subsystem: Timer/FunctionTimer, utils/common.h:979-1043, global_timer
printed at exit, per-phase instrumentation across the tree learner and
network layers — SURVEY §5).

TPU adaptation: phases are HOST-side regions (dispatch, collect,
binning, eval). Device work inside jit is asynchronous, so a scope that
must include device completion passes `block=True` to synchronize
before stopping the clock (used by bench/profilers, off in production
paths). Scopes also enter `jax.profiler.TraceAnnotation`-compatible
`jax.named_scope` so traces collected with jax.profiler line up with
the same names.

Enable summary-at-exit with env LIGHTGBM_TPU_TIMETAG=1 (the analog of
the reference's compile-time USE_TIMETAG), or call
`global_timer.print_summary()` directly.
"""

from __future__ import annotations

import atexit
import os
import time
from contextlib import contextmanager
from typing import Dict, Iterator, Optional


class Timer:
    """Accumulating named stopwatches (reference utils/common.h:979)."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._cnt: Dict[str, int] = {}
        self.enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")

    @contextmanager
    def scope(self, name: str, block: bool = False) -> Iterator[None]:
        """Time a region; with block=True waits for device completion
        (jax.block_until_ready on nothing — a full device sync) before
        stopping, so the region includes its dispatched work."""
        if not self.enabled:
            yield
            return
        import jax

        t0 = time.perf_counter()
        with jax.named_scope(name.replace(" ", "_")):
            yield
        if block:
            try:
                (jax.device_put(0) + 0).block_until_ready()
            except Exception:  # noqa: BLE001 — never break the timed path
                pass
        dt = time.perf_counter() - t0
        self._acc[name] = self._acc.get(name, 0.0) + dt
        self._cnt[name] = self._cnt.get(name, 0) + 1

    def summary(self) -> Dict[str, tuple]:
        return {
            k: (self._acc[k], self._cnt[k])
            for k in sorted(self._acc, key=lambda k: -self._acc[k])
        }

    def print_summary(self) -> None:
        """common.h:1012 — per-phase totals at exit."""
        from . import log

        if not self._acc:
            return
        log.info("LightGBM-TPU phase timings:")
        for name, (acc, cnt) in self.summary().items():
            log.info(f"  {name}: {acc:.3f}s ({cnt} calls)")

    def reset(self) -> None:
        self._acc.clear()
        self._cnt.clear()


global_timer = Timer()

if global_timer.enabled:
    atexit.register(global_timer.print_summary)
