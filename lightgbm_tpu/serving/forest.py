"""Tensorized forest predictor: the trained model as device tables.

The host predictors (tree.py vectorized walk, native/ fp_predict) walk
pointer-shaped trees row by row; on TPU that shape is hostile — the
win comes from giving every (row, tree) lane the same dense program.
This module lifts the flat per-tree arrays (the same layout
``native.PackedModel`` packs for the C++ walker: feature index,
threshold, decision type, children, leaf values, categorical bitsets,
linear-leaf coefficients) into rectangular ``(T, max_nodes)`` /
``(T, max_leaves)`` tables and traverses **all rows x all trees in
lockstep** under one ``jit``:

- per level, every lane's node parameters come from ONE packed-table
  gather (``take_cols`` — the MXU one-hot contraction training's
  validation traversal already uses, histogram.py:380);
- each lane's split-feature value is a ``take_along_axis`` row gather;
- the loop is a ``lax.while_loop`` bounded by the forest's max depth
  (every lane advances one level per pass, like traverse_tree_bins);
- per-class accumulation is a single ``(N, T) @ (T, K)`` one-hot
  matmul, with a ``(T,)`` weight vector implementing
  ``start_iteration`` / ``num_iteration`` truncation WITHOUT a
  retrace (the weights are an argument, not a static).

Decision semantics mirror ``tree.py`` ``Tree.go_left`` bit for bit
(missing types None/Zero/NaN, default direction, categorical bitsets,
linear-leaf NaN fallback); the parity tests in
tests/test_serving.py assert agreement with the native walker across
model families. Tables ride the jit boundary as ARGUMENTS, so two
models with the same (T, M, L) shapes share one executable — hot-swap
in the registry does not recompile.

Fleet extensions (serving/fleet.py, docs/SERVING.md "Fleet serving"):

- ``pad_forest_tables`` pads a model's tables out to a shape-family's
  dimensions so many models can share ONE stacked executable;
- ``stacked_forest_apply`` scores slot ``s`` of an ``(S, ...)``-stacked
  table set — the model index is a traced argument, so paging a model
  in or out of its HBM slot never recompiles;
- ``pack_contrib_tables`` + ``contrib_apply`` are the device TreeSHAP:
  per-leaf root-to-leaf paths with host-precomputed cover ("zero")
  fractions, row-dependent {0,1} "one" fractions from the same split
  decisions the predictor uses, and the reference's extend/unwind
  permutation-weight DP run in lockstep over every (row, tree, leaf)
  lane (host ``shap.py`` is the parity oracle).

All tables are f32/int32: the scoring jaxprs carry the same
no-f64 / no-host-callback contracts as the training entry points
(analysis/jaxpr_audit.py ``serving_forest`` / ``serving_fleet_stack``
/ ``serving_contrib`` entries).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

# reference include/LightGBM/bin.h kZeroThreshold (tree.h Decision) —
# the zero-as-missing band, shared with the host walk via binning
from ..binning import K_ZERO_THRESHOLD as _K_ZERO


def pack_forest_tables(models, num_class: int) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Host packing: list of Tree -> rectangular numpy tables + static
    metadata. The numpy side of the split so the jit side is pure
    device math (and so the audit can trace it from shapes alone)."""
    T = len(models)
    K = max(int(num_class), 1)
    n_nodes = [max(t.num_leaves - 1, 0) for t in models]
    M = max(n_nodes + [1])
    L = max([t.num_leaves for t in models] + [1])
    depth = max([t.max_depth() for t in models] + [1])

    feature = np.zeros((T, M), np.int32)
    threshold = np.zeros((T, M), np.float32)
    miss_type = np.zeros((T, M), np.int32)
    default_left = np.zeros((T, M), bool)
    is_cat = np.zeros((T, M), bool)
    # padding nodes route straight to leaf 0 so a runaway lane terminates
    left = np.full((T, M), -1, np.int32)
    right = np.full((T, M), -1, np.int32)
    leaf_value = np.zeros((T, L), np.float32)
    cat_lo = np.zeros((T, M), np.int32)
    cat_nw = np.zeros((T, M), np.int32)
    catw_parts: List[np.ndarray] = []
    wbase = 0
    any_cat = False
    any_linear = any(t.is_linear for t in models)
    Ck = 1
    if any_linear:
        Ck = max(
            (len(f) for t in models if t.is_linear for f in t.leaf_features),
            default=1,
        ) or 1
    leaf_const = np.zeros((T, L), np.float32)
    leaf_nf = np.zeros((T, L), np.int32)
    leaf_feat = np.zeros((T, L, Ck), np.int32)
    leaf_coeff = np.zeros((T, L, Ck), np.float32)
    init_node = np.zeros(T, np.int32)
    max_feature = -1

    for ti, t in enumerate(models):
        n = n_nodes[ti]
        if n == 0:
            init_node[ti] = -1  # stump: lane starts AT leaf 0 (~0 == -1)
        else:
            feature[ti, :n] = t.split_feature[:n]
            # directed f64->f32 cast: never round a threshold UP across
            # its f64 value, or an exactly-f32 feature value in
            # (thr, f32(thr)] would flip from right to left vs the f64
            # host walker — a whole-leaf divergence, not 1e-5 noise
            thr64 = np.asarray(t.threshold[:n], np.float64)
            t32 = thr64.astype(np.float32)
            up = t32.astype(np.float64) > thr64
            t32[up] = np.nextafter(t32[up], np.float32(-np.inf))
            threshold[ti, :n] = t32
            dt = np.asarray(t.decision_type[:n], np.int64)
            miss_type[ti, :n] = (dt >> 2) & 3
            default_left[ti, :n] = (dt & 2) != 0
            is_cat[ti, :n] = (dt & 1) != 0
            left[ti, :n] = t.left_child[:n]
            right[ti, :n] = t.right_child[:n]
            max_feature = max(max_feature, int(np.max(t.split_feature[:n])))
            cat_k = np.flatnonzero(is_cat[ti, :n])
            if len(cat_k):
                any_cat = True
                cb = np.asarray(t.cat_boundaries, np.int64)
                words = np.asarray(t.cat_threshold, np.uint32)
                catw_parts.append(words)
                ci = np.asarray(t.threshold, np.float64)[cat_k].astype(np.int64)
                cat_lo[ti, cat_k] = wbase + cb[ci]
                cat_nw[ti, cat_k] = cb[ci + 1] - cb[ci]
                wbase += len(words)
        lv = np.asarray(t.leaf_value, np.float32)
        leaf_value[ti, : len(lv)] = lv
        leaf_const[ti, : len(lv)] = lv  # non-linear: lin path == leaf_value
        if t.is_linear:
            lc = np.asarray(t.leaf_const, np.float32)
            leaf_const[ti, : len(lc)] = lc
            for li, feats in enumerate(t.leaf_features):
                k = len(feats)
                leaf_nf[ti, li] = k
                if k:
                    leaf_feat[ti, li, :k] = feats
                    leaf_coeff[ti, li, :k] = np.asarray(
                        t.leaf_coeff[li], np.float32
                    )
                    max_feature = max(max_feature, max(feats))

    catw = (
        np.concatenate(catw_parts).astype(np.uint32)
        if catw_parts else np.zeros(1, np.uint32)
    )
    # per-node packed parameter table for the single take_cols gather:
    # every field is exact in f32 (ints < 2^24, thresholds already f32)
    pack = np.stack([
        feature.reshape(-1).astype(np.float32),       # 0
        threshold.reshape(-1),                        # 1
        miss_type.reshape(-1).astype(np.float32),     # 2
        default_left.reshape(-1).astype(np.float32),  # 3
        is_cat.reshape(-1).astype(np.float32),        # 4
        left.reshape(-1).astype(np.float32),          # 5
        right.reshape(-1).astype(np.float32),         # 6
        cat_lo.reshape(-1).astype(np.float32),        # 7
        cat_nw.reshape(-1).astype(np.float32),        # 8
    ])
    class_onehot = np.zeros((T, K), np.float32)
    class_onehot[np.arange(T), np.arange(T) % K] = 1.0

    tables = {
        "pack": pack,                         # (9, T*M) f32
        "catw": catw.view(np.int32),          # (W,) int32 bit-patterns
        "leaf_value": leaf_value,             # (T, L) f32
        "leaf_const": leaf_const,             # (T, L) f32
        "leaf_nf": leaf_nf,                   # (T, L) int32
        "leaf_feat": leaf_feat,               # (T, L, Ck) int32
        "leaf_coeff": leaf_coeff,             # (T, L, Ck) f32
        "init_node": init_node,               # (T,) int32
        "class_onehot": class_onehot,         # (T, K) f32
    }
    meta = {
        "num_trees": T, "num_class": K, "max_nodes": M, "max_leaves": L,
        "max_depth": int(depth), "has_cat": bool(any_cat),
        "linear": bool(any_linear), "max_feature": int(max_feature),
    }
    return tables, meta


def _go_left(v, x, catw, has_cat: bool):
    """Split decision for gathered node params ``v`` (9, *S) against
    gathered feature values ``x`` (*S) — the ONE implementation of
    ``tree.py Tree.go_left`` on device, shared by the traversal loop
    and the TreeSHAP path evaluation so their decisions can never
    drift apart."""
    import jax.numpy as jnp
    from jax import lax

    thr = v[1]
    mt = v[2].astype(jnp.int32)
    dl = v[3] > 0.5
    isna = jnp.isnan(x)
    # missing != NaN: NaN behaves as 0.0 (tree.h Decision)
    xv = jnp.where(isna & (mt != 2), 0.0, x)
    miss = jnp.where(
        mt == 2, isna, (mt == 1) & (jnp.abs(xv) <= _K_ZERO)
    )
    go_left = jnp.where(miss, dl, xv <= thr)
    if has_cat:
        nw = v[8].astype(jnp.int32)
        iv = jnp.nan_to_num(x, nan=-1.0, posinf=-1.0, neginf=-1.0)
        iv = iv.astype(jnp.int32)
        ok = (~isna) & (iv >= 0) & (iv < 32 * nw)
        widx = v[7].astype(jnp.int32) + jnp.maximum(iv, 0) // 32
        W = catw.shape[0]
        w = catw[jnp.clip(widx, 0, W - 1)]
        bit = lax.shift_right_logical(w, jnp.maximum(iv, 0) % 32) & 1
        go_left = jnp.where(v[4] > 0.5, ok & (bit == 1), go_left)
    return go_left


def forest_apply(tables, X, tree_w, *, has_cat: bool = True,
                 linear: bool = False, max_depth: int = 0):
    """Device traversal: (N, F) rows x all T trees -> per-class raw
    scores (N, K) and per-tree leaf indices (N, T).

    `tables` is the pack_forest_tables pytree (jnp arrays); `tree_w`
    is the (T,) f32 per-tree weight implementing iteration truncation.
    Pure jax — jit/shard_map wrapping happens in TensorForest.
    """
    import jax.numpy as jnp
    from jax import lax

    from ..learner.histogram import take_cols

    T, L = tables["leaf_value"].shape
    M = tables["pack"].shape[1] // T
    N = X.shape[0]
    tpos = (jnp.arange(T, dtype=jnp.int32) * M)[None, :]  # (1, T)
    cur0 = jnp.broadcast_to(tables["init_node"][None, :], (N, T))
    # every lane descends one edge per pass, so the forest's max depth
    # (pack_forest_tables meta) bounds the loop tighter than the node
    # count; <=0 falls back to M
    bound = M if max_depth <= 0 else min(int(max_depth), M)

    def cond(s):
        it, cur = s
        return (it < bound) & jnp.any(cur >= 0)

    def body(s):
        it, cur = s
        node = jnp.maximum(cur, 0)  # leaf lanes compute a dead decision
        flat = (tpos + node).reshape(-1)  # (N*T,)
        v = take_cols(tables["pack"], flat)  # (9, N*T)
        v = v.reshape(9, N, T)
        f = v[0].astype(jnp.int32)
        x = jnp.take_along_axis(X, f, axis=1)  # (N, T)
        go_left = _go_left(v, x, tables["catw"], has_cat)
        child = jnp.where(go_left, v[5], v[6]).astype(jnp.int32)
        cur = jnp.where(cur >= 0, child, cur)
        return it + 1, cur

    _, cur = lax.while_loop(cond, body, (jnp.int32(0), cur0))
    leaf = jnp.where(cur < 0, ~cur, 0)  # (N, T)
    lflat = (jnp.arange(T, dtype=jnp.int32) * L)[None, :] + leaf
    val = tables["leaf_value"].reshape(-1)[lflat]  # (N, T)
    if linear:
        Ck = tables["leaf_feat"].shape[2]
        const = tables["leaf_const"].reshape(-1)[lflat]
        nf = tables["leaf_nf"].reshape(-1)[lflat]
        fidx = tables["leaf_feat"].reshape(-1, Ck)[lflat]    # (N, T, Ck)
        co = tables["leaf_coeff"].reshape(-1, Ck)[lflat]
        xg = X[jnp.arange(N, dtype=jnp.int32)[:, None, None], fidx]
        kmask = jnp.arange(Ck, dtype=jnp.int32)[None, None, :] < nf[..., None]
        contrib = jnp.sum(jnp.where(kmask, co * xg, 0.0), axis=-1)
        anynan = jnp.any(kmask & jnp.isnan(xg), axis=-1)
        # linear semantics (tree.cpp:137-153): const + coeffs . x,
        # rows with NaN in a used feature fall back to leaf_value
        val = jnp.where(anynan, val, const + contrib)
    score = (val * tree_w[None, :]) @ tables["class_onehot"]  # (N, K)
    return score, leaf


def stacked_forest_apply(stack, slot, X, tree_w, *, has_cat: bool = True,
                         linear: bool = False, max_depth: int = 0):
    """Score one slot of an (S, ...)-stacked table set: the fleet's
    scoring entry. ``slot`` is a TRACED int32 scalar (a dynamic index,
    not a static), so every resident model of a shape family scores
    through one executable per bucket — paging a model into or out of
    its HBM slot never recompiles (serving/fleet.py)."""
    tables = {k: v[slot] for k, v in stack.items()}
    return forest_apply(tables, X, tree_w, has_cat=has_cat,
                        linear=linear, max_depth=max_depth)


def pad_forest_tables(tables, meta, *, num_trees: int, max_nodes: int,
                      max_leaves: int, cat_words: int, lin_feats: int):
    """Pad one model's host tables out to a shape family's dimensions
    (all targets >= the model's own) so models of one family can share
    a stacked executable. Padding reuses the packer's inert encodings:
    children -1 (straight to leaf 0), init_node -1 (stump at leaf 0),
    zero leaf values and zero class-onehot rows, so padded trees score
    exactly 0 under any tree-weight vector."""
    T, M = meta["num_trees"], meta["max_nodes"]
    L = meta["max_leaves"]
    K = tables["class_onehot"].shape[1]
    Ck = tables["leaf_feat"].shape[2]
    W = tables["catw"].shape[0]
    T2, M2, L2 = int(num_trees), int(max_nodes), int(max_leaves)
    W2, Ck2 = int(cat_words), int(lin_feats)
    if (T2, M2, L2, W2, Ck2) < (T, M, L, W, Ck):
        raise ValueError("pad targets must cover the model's own dims")
    pack = np.zeros((9, T2, M2), np.float32)
    pack[5:7] = -1.0  # padding nodes route straight to leaf 0
    pack[:, :T, :M] = np.asarray(tables["pack"]).reshape(9, T, M)
    catw = np.zeros(W2, np.int32)
    catw[:W] = np.asarray(tables["catw"])
    init_node = np.full(T2, -1, np.int32)
    init_node[:T] = np.asarray(tables["init_node"])
    class_onehot = np.zeros((T2, K), np.float32)
    class_onehot[:T] = np.asarray(tables["class_onehot"])

    def grow(a, shape):
        out = np.zeros(shape, a.dtype)
        out[tuple(slice(0, s) for s in a.shape)] = a
        return out

    out = {
        "pack": pack.reshape(9, T2 * M2),
        "catw": catw,
        "leaf_value": grow(np.asarray(tables["leaf_value"]), (T2, L2)),
        "leaf_const": grow(np.asarray(tables["leaf_const"]), (T2, L2)),
        "leaf_nf": grow(np.asarray(tables["leaf_nf"]), (T2, L2)),
        "leaf_feat": grow(np.asarray(tables["leaf_feat"]), (T2, L2, Ck2)),
        "leaf_coeff": grow(np.asarray(tables["leaf_coeff"]),
                           (T2, L2, Ck2)),
        "init_node": init_node,
        "class_onehot": class_onehot,
    }
    meta2 = dict(meta, num_trees=T2, max_nodes=M2, max_leaves=L2)
    return out, meta2


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def pack_contrib_tables(models, num_class: int):
    """Host packing for device TreeSHAP: per (tree, leaf), the
    root-to-leaf path as node ids + directions, the path's UNIQUE
    features with their cover ("zero") fractions — everything about
    the recursion that does not depend on the scored row. The
    row-dependent half (the {0,1} "one" fractions) falls out of the
    same per-node split decisions the predictor makes.

    Duplicate features on a path collapse into one slot whose zero
    fraction is the product of its edges' cover ratios and whose one
    fraction is the AND of its edges' hot indicators — exactly the
    reference's unwind-and-re-extend semantics (shap.py _tree_shap).
    Paths pad with (zero=1, one=1) dummy slots to one uniform length;
    such a slot leaves every other feature's permutation weight
    unchanged and contributes nothing itself (one - zero == 0), so the
    device DP runs a single static depth. Path dims quantize to powers
    of two so nearby-depth models share the contrib executable."""
    T = len(models)
    K = max(int(num_class), 1)
    n_nodes = [max(t.num_leaves - 1, 0) for t in models]
    M = max(n_nodes + [1])
    L = max([t.num_leaves for t in models] + [1])

    paths: Dict[Tuple[int, int], List[Tuple[int, int, float, int]]] = {}
    expect = np.zeros(T, np.float32)
    for ti, t in enumerate(models):
        lv = np.asarray(t.leaf_value, np.float64)
        if t.num_leaves == 1:
            expect[ti] = lv[0]
            continue
        cnt_in = np.asarray(t.internal_count, np.float64)
        cnt_lf = np.asarray(t.leaf_count, np.float64)
        total = cnt_in[0]
        expect[ti] = (
            float(np.dot(cnt_lf[: t.num_leaves] / total,
                         lv[: t.num_leaves]))
            if total > 0 else float(np.mean(lv[: t.num_leaves]))
        )

        def count(n: int) -> float:
            return cnt_in[n] if n >= 0 else cnt_lf[~n]

        # iterative DFS: (node, edges so far); edge = (node, dir,
        # cover ratio, feature)
        stack: List[Tuple[int, List[Tuple[int, int, float, int]]]] = [
            (0, [])
        ]
        while stack:
            node, edges = stack.pop()
            if node < 0:
                paths[(ti, ~node)] = edges
                continue
            w = count(node)
            f = int(t.split_feature[node])
            for child, d in ((int(t.left_child[node]), 1),
                             (int(t.right_child[node]), 0)):
                r = count(child) / w if w > 0 else 0.0
                stack.append((child, edges + [(node, d, r, f)]))

    E = _pow2(max([len(e) for e in paths.values()] + [1]))
    P = _pow2(max(
        [len({f for _, _, _, f in e}) for e in paths.values()] + [1]
    ))
    nodes = np.full((T, L, E), -1, np.int32)
    dirs = np.zeros((T, L, E), np.float32)
    slot_oh = np.zeros((T, L, E, P), np.float32)
    zero = np.ones((T, L, P), np.float32)
    feat = np.zeros((T, L, P), np.int32)
    for (ti, li), edges in paths.items():
        slots: Dict[int, int] = {}
        for e, (node, d, r, f) in enumerate(edges):
            s = slots.setdefault(f, len(slots))
            nodes[ti, li, e] = ti * M + node
            dirs[ti, li, e] = d
            slot_oh[ti, li, e, s] = 1.0
            zero[ti, li, s] *= r
            feat[ti, li, s] = f
    tables = {
        "nodes": nodes,          # (T, L, E) int32, flat t*M+node, pad -1
        "dirs": dirs,            # (T, L, E) f32, 1 = path goes left
        "slot_oh": slot_oh,      # (T, L, E, P) f32 edge -> feature slot
        "zero": zero,            # (T, L, P) f32 cover fractions, pad 1
        "feat": feat,            # (T, L, P) int32 feature ids, pad 0
        "expect": expect,        # (T,) f32 cover-weighted mean output
        "tree_class": (np.arange(T, dtype=np.int32) % K),  # (T,)
    }
    cmeta = {"path_edges": int(E), "path_feats": int(P),
             "max_nodes": M, "max_leaves": L}
    return tables, cmeta


def contrib_apply(tables, ctables, X, tree_w, *, has_cat: bool = True):
    """Device TreeSHAP: (N, F) rows -> (N, K*(F+1)) contributions in
    Booster.predict(pred_contrib=True) layout (per class: F feature
    columns then the expected-value bias column; rows sum to the raw
    score). Mirrors host shap.py: one split decision per (row, node),
    per-leaf one/zero fractions, then the reference's extend /
    unwound-sum permutation-weight DP over every (row, tree, leaf)
    lane at one static path depth."""
    import jax.numpy as jnp

    T, L = tables["leaf_value"].shape
    M = tables["pack"].shape[1] // T
    N, F = X.shape
    K = tables["class_onehot"].shape[1]
    E = ctables["nodes"].shape[2]
    P = ctables["zero"].shape[2]
    tw = tree_w.astype(jnp.float32)

    # the split decision at EVERY node (the traversal evaluates only
    # the visited one; SHAP weighs both branches of every path)
    v = tables["pack"].reshape(9, 1, T * M)
    f_all = tables["pack"][0].astype(jnp.int32)          # (T*M,)
    x_all = jnp.take(X, f_all, axis=1)                   # (N, T*M)
    gl = _go_left(v, x_all, tables["catw"], has_cat)     # (N, T*M)

    nodes = ctables["nodes"]
    nid = jnp.maximum(nodes, 0).reshape(-1)
    g = jnp.take(gl, nid, axis=1).reshape(N, T, L, E)
    follows = jnp.where(nodes[None] < 0, True,
                        g == (ctables["dirs"][None] > 0.5))
    miss = (~follows).astype(jnp.float32)                # (N, T, L, E)
    # a slot is "hot" (one fraction 1) iff the row follows the path at
    # every edge splitting on that slot's feature
    o = (jnp.einsum("ntle,tlep->ntlp", miss,
                    ctables["slot_oh"]) == 0).astype(jnp.float32)
    z = ctables["zero"]                                  # (T, L, P)

    # extend DP (shap.py _extend): permutation weights w[0..P] per
    # (row, tree, leaf) lane, all P slots extended at static depth
    w = [jnp.ones((N, T, L), jnp.float32)]
    for i in range(1, P + 1):
        one = o[..., i - 1]
        zr = z[None, :, :, i - 1]
        w.append(jnp.zeros((N, T, L), jnp.float32))
        d1 = float(i + 1)
        for j in range(i - 1, -1, -1):
            w[j + 1] = w[j + 1] + one * w[j] * ((j + 1) / d1)
            w[j] = zr * w[j] * ((i - j) / d1)

    # per-slot unwound sums (shap.py _unwound_sum at depth P) -> phi
    lv = tables["leaf_value"]
    d1 = float(P + 1)
    deltas = []
    for i in range(P):
        one = o[..., i]
        zr = z[None, :, :, i]
        zsafe = jnp.maximum(zr, 1e-12)
        hot = one > 0.5
        nxt = w[P]
        total = jnp.zeros((N, T, L), jnp.float32)
        for j in range(P - 1, -1, -1):
            tmp = nxt * (d1 / (j + 1))
            cold = (w[j] / zsafe) * (d1 / (P - j))
            total = total + jnp.where(hot, tmp, cold)
            nxt = jnp.where(hot, w[j] - tmp * zr * ((P - j) / d1), nxt)
        deltas.append(total * (one - zr) * lv[None] * tw[None, :, None])
    delta = jnp.stack(deltas, axis=-1)                   # (N, T, L, P)

    cols = (ctables["tree_class"][:, None, None] * (F + 1)
            + ctables["feat"])                           # (T, L, P)
    out = jnp.zeros((N, K * (F + 1)), jnp.float32)
    out = out.at[:, cols.reshape(-1)].add(delta.reshape(N, -1))
    bias = (tw * ctables["expect"]) @ tables["class_onehot"]  # (K,)
    bcols = (jnp.arange(K, dtype=jnp.int32) + 1) * (F + 1) - 1
    out = out.at[:, bcols].add(jnp.broadcast_to(bias[None], (N, K)))
    return out


def replicate_forest(forest: "TensorForest", device) -> "TensorForest":
    """A shallow copy of a (non-mesh) forest with its tables committed
    to ``device``. jit runs committed-input computations on the
    inputs' device, so N replicas score concurrently on N devices —
    each device compiles the shared entry once per bucket, and the
    replicas stay bit-identical (same tables, same program)."""
    import copy

    import jax

    if forest.mesh is not None:
        raise ValueError("replicate_forest needs a single-device forest")
    rep = copy.copy(forest)
    rep.tables = {
        k: jax.device_put(v, device) for k, v in forest.tables.items()
    }
    rep._ctables = None  # contrib tables re-pack on the replica's device
    return rep


_APPLY_JIT = None
_STACK_JIT = None
_CONTRIB_JIT = None


def _stacked_apply_jit():
    """Shared jit of stacked_forest_apply — every same-shaped
    ForestStack scores through one executable per bucket."""
    global _STACK_JIT
    if _STACK_JIT is None:
        import jax

        _STACK_JIT = jax.jit(
            stacked_forest_apply,
            static_argnames=("has_cat", "linear", "max_depth"),
        )
    return _STACK_JIT


def _contrib_apply_jit():
    """Shared jit of contrib_apply — same-shaped models (incl. the
    quantized path dims) share the TreeSHAP executable."""
    global _CONTRIB_JIT
    if _CONTRIB_JIT is None:
        import jax

        _CONTRIB_JIT = jax.jit(
            contrib_apply, static_argnames=("has_cat",)
        )
    return _CONTRIB_JIT


def _forest_apply_jit():
    """Shared module-level jit of forest_apply (lazy so importing the
    package never initializes a backend): every non-mesh TensorForest
    scores through this ONE callable, so same-shaped tables — model
    hot-swaps, registry versions — reuse one executable per bucket."""
    global _APPLY_JIT
    if _APPLY_JIT is None:
        import jax

        _APPLY_JIT = jax.jit(
            forest_apply, static_argnames=("has_cat", "linear", "max_depth")
        )
    return _APPLY_JIT


class TensorForest:
    """A trained forest compiled to device tables + a scoring callable.

    ``mesh=None`` (or a 1-device mesh) uses the shared module-level jit
    — model hot-swaps with identical table shapes reuse the executable.
    With a multi-device mesh the row axis is sharded over
    ``axis_name`` through the same ``shard_map_compat`` seam training
    uses (tables replicated); callers must pad rows to a multiple of
    the mesh size (``BucketDispatcher`` aligns its ladder for this).
    """

    def __init__(self, models, num_class: int = 1,
                 average_output: bool = False, mesh=None,
                 axis_name: str = "data"):
        import jax
        import jax.numpy as jnp

        if not models:
            raise ValueError("TensorForest needs at least one tree")
        tables, meta = pack_forest_tables(models, num_class)
        self.meta = meta
        # retained for lazy contrib packing (references, not copies)
        self._models = list(models)
        self._ctables = None
        # while_loop bound: true max depth rounded UP to a power of two
        # — max_depth is a static jit arg, so quantizing keeps the
        # hot-swap executable-reuse property for same-shaped models
        # with nearby depths (any bound >= true depth is correct)
        d = max(int(meta["max_depth"]), 1)
        self._depth_bound = 1 << (d - 1).bit_length()
        self.num_class = meta["num_class"]
        self.num_trees = meta["num_trees"]
        self.average_output = bool(average_output)
        self.max_feature = meta["max_feature"]
        self.mesh = None
        self.axis_name = axis_name
        n_dev = 1
        if mesh is not None and int(np.prod(mesh.devices.shape)) > 1:
            self.mesh = mesh
            n_dev = int(np.prod(mesh.devices.shape))
        self.num_devices = n_dev
        if self.mesh is None:
            self.tables = {k: jnp.asarray(v) for k, v in tables.items()}
            self._fn = _forest_apply_jit()
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.data_parallel import shard_map_compat

            rep = NamedSharding(self.mesh, P())
            self.tables = {
                k: jax.device_put(jnp.asarray(v), rep)
                for k, v in tables.items()
            }
            has_cat, linear = meta["has_cat"], meta["linear"]
            max_depth = self._depth_bound

            def fn(tables, X, tree_w):
                return forest_apply(tables, X, tree_w,
                                    has_cat=has_cat, linear=linear,
                                    max_depth=max_depth)

            tspec = jax.tree.map(lambda _: P(), self.tables)
            self._sharded = jax.jit(shard_map_compat(
                fn, mesh=self.mesh,
                in_specs=(tspec, P(axis_name, None), P()),
                out_specs=(P(axis_name, None), P(axis_name, None)),
                check_vma=False,
            ))
            self._fn = None

    # ------------------------------------------------------------------
    @classmethod
    def from_booster(cls, booster, mesh=None) -> "TensorForest":
        g = booster._gbdt
        return cls(
            list(g.models), g.num_class,
            average_output=bool(getattr(g, "average_output", False)),
            mesh=mesh,
        )

    @property
    def jit_entry(self):
        """The jitted scoring callable — hand this to retrace_guard
        entry_points to assert the compile-per-bucket contract."""
        return self._sharded if self.mesh is not None else self._fn

    def _tree_weights(self, start_iteration: int,
                      num_iteration: int) -> Tuple[np.ndarray, int, int]:
        K = self.num_class
        n_iters = self.num_trees // K
        end = n_iters if num_iteration <= 0 else min(
            n_iters, start_iteration + num_iteration
        )
        tw = np.zeros(self.num_trees, np.float32)
        tw[start_iteration * K: end * K] = 1.0
        return tw, start_iteration, end

    def _check_width(self, X: np.ndarray) -> None:
        if X.shape[1] <= self.max_feature:
            # keep the host walk's error semantics (tree.py predict_leaf
            # raises IndexError on narrow input)
            raise IndexError(
                f"input has {X.shape[1]} features but the model "
                f"references feature {self.max_feature}"
            )

    def apply(self, X, tree_w):
        """Raw device call on an already-padded f32 row block."""
        import jax.numpy as jnp

        tw = jnp.asarray(tree_w, jnp.float32)
        if self.mesh is not None:
            return self._sharded(self.tables, X, tw)
        return self._fn(
            self.tables, X, tw,
            has_cat=self.meta["has_cat"], linear=self.meta["linear"],
            max_depth=self._depth_bound,
        )

    def predict_raw(self, X: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1) -> np.ndarray:
        """(K, N) raw margins, matching GBDT.predict_raw layout."""
        import jax.numpy as jnp

        X = np.asarray(X, np.float32)
        self._check_width(X)
        tw, start, end = self._tree_weights(start_iteration, num_iteration)
        N = X.shape[0]
        pad = (-N) % max(self.num_devices, 1)
        if pad:
            X = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)])
        score, _ = self.apply(jnp.asarray(X), tw)
        out = np.asarray(score)[:N].T.astype(np.float64)  # (K, N)
        if self.average_output and end > start:
            out /= end - start
        return out

    def predict_leaf(self, X: np.ndarray, start_iteration: int = 0,
                     num_iteration: int = -1) -> np.ndarray:
        """(N, used_trees) leaf indices (Booster.predict pred_leaf)."""
        import jax.numpy as jnp

        X = np.asarray(X, np.float32)
        self._check_width(X)
        tw, start, end = self._tree_weights(start_iteration, num_iteration)
        N = X.shape[0]
        pad = (-N) % max(self.num_devices, 1)
        if pad:
            X = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)])
        _, leaf = self.apply(jnp.asarray(X), tw)
        K = self.num_class
        return np.asarray(leaf)[:N, start * K: end * K].astype(np.int64)

    # -------------------------------------------------------- contrib
    def contrib_tables(self):
        """Lazy device TreeSHAP tables: packed on the first contrib
        request only — explanation traffic pays for its own HBM.
        Fleet eviction drops the reference (serving/fleet.py) and a
        later request re-packs from the retained host trees."""
        import jax.numpy as jnp

        if self._ctables is None:
            ct, cmeta = pack_contrib_tables(self._models, self.num_class)
            self._ctables = (
                {k: jnp.asarray(v) for k, v in ct.items()}, cmeta
            )
        return self._ctables

    def drop_contrib_tables(self) -> None:
        self._ctables = None

    def apply_contrib(self, X, tree_w):
        """Raw device TreeSHAP on an already-padded f32 row block:
        (N, K*(F+1)) where F is the padded input width."""
        import jax.numpy as jnp

        ct, _ = self.contrib_tables()
        tw = jnp.asarray(tree_w, jnp.float32)
        return _contrib_apply_jit()(
            self.tables, ct, X, tw, has_cat=self.meta["has_cat"]
        )

    def predict_contrib(self, X: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1) -> np.ndarray:
        """(N, K*(F+1)) SHAP contributions in Booster.predict
        (pred_contrib=True) layout; host shap.py is the oracle."""
        import jax.numpy as jnp

        X = np.asarray(X, np.float32)
        self._check_width(X)
        tw, start, end = self._tree_weights(start_iteration, num_iteration)
        N, F = X.shape
        out = np.asarray(
            self.apply_contrib(jnp.asarray(X), tw)
        )[:N].astype(np.float64)
        if self.average_output and end > start:
            out /= end - start
        return out
