"""GBDT boosting driver (reference src/boosting/gbdt.cpp).

Owns the training loop state: per-dataset device scores, the objective,
the sampling strategy, and the growing list of trees. Each iteration:

  gradients (device, objective)  ->  sampling mask (bagging/GOSS)
  ->  grow_tree (jit; one call per class-tree)  ->  leaf renewal for
  percentile objectives (RenewTreeOutput, objective_function.h:55)
  ->  score updates: train via the partition vector
  (score_updater.hpp AddScore fast path), valid via device tree
  traversal  ->  host Tree for the model list.

Boost-from-average follows gbdt.cpp:327-445: the initial score is added
to all scorers before the first iteration and folded into the first
tree's leaf values afterwards (Tree::AddBias), so saved models are
self-contained.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from . import log
from .config import Config
from .dataset import BinnedDataset
from .learner import GrowerSpec, grow_tree, make_split_params
from .learner.grower import TreeArrays, add_score
from .metrics import Metric, create_metrics
from .objectives import ObjectiveFunction, create_objective
from .sample_strategy import create_sample_strategy
from .timer import global_timer as _gt
from .tree import Tree, traverse_tree_bins

# canonical per-round host phase names (docs/OBSERVABILITY.md): the
# eager loops (fast/sync) emit the three phases each iteration; the
# fused loop — whose phases live inside one jit — emits one span per
# DISPATCH. Under chunk scanning (tpu_chunk_scan=auto, the default) a
# dispatch is a C-round lax.scan, so the span covers the whole chunk;
# _ObsHooks divides it by the dispatch's round count (the booster's
# _last_dispatch_rounds) to keep per-round record durations. With
# tpu_chunk_scan=off each dispatch is one round, as historically.
# obs.tracing records these as trace-event spans and jax.profiler
# traces carry the same names via jax.named_scope.
ROUND_PHASES = (
    "round: gradients",
    "round: grow",
    "round: score update",
)
FUSED_ROUND_PHASE = "round: fused step"


@dataclass
class _ScoreSet:
    dataset: BinnedDataset
    score: Any  # (K, Npad) device f32
    name: str
    metrics: List[Metric] = field(default_factory=list)


# process-level fused-step memo (cv folds / repeated trains reuse one
# traced+compiled step; see _build_fused) and the lightweight metric
# name records fused_collect reads. LRU-capped: each jitted step's
# closure pins its first booster's device arrays (bin matrix, scores),
# so an unbounded dict would grow without limit across a parameter
# sweep — 8 entries covers cv + realistic repeated-train patterns.
from collections import OrderedDict as _OrderedDict

_FUSED_STEP_CACHE: "_OrderedDict[Any, Any]" = _OrderedDict()
_FUSED_STEP_CACHE_MAX = 8

# objective attributes that hold FOLD-VARYING values read inside traced
# gradient code: device label/weight arrays, MAPE's label-derived
# weights, and is_unbalance's label-count-derived class weights. The
# fused step rebinds these from its `data` argument during tracing so
# the memoized executable is fold-agnostic (anything outside this list
# that varies per fold must gate memo_ok instead).
_OBJ_FOLD_ATTRS = ("label", "weight", "_label_weight", "_pos_w", "_neg_w")

# device-array objective attributes OUTSIDE the rebind list that are
# legitimately excluded, each with the gate that keeps the fused memo
# safe. Everything else holding a jax.Array fails _audit_fold_attrs
# loudly (ADVICE r5 item 3); the static twin of this check is
# analysis/jaxpr_audit.audit_fold_attrs.
_OBJ_FOLD_EXEMPT = {
    "_pos_biases": "lambdarank position debiasing sets has_host_state, "
                   "which makes the booster fused-ineligible entirely",
}


def _audit_fold_attrs(objective) -> None:
    """Build-time assertion: a fold-varying device array outside
    _OBJ_FOLD_ATTRS would be baked into the memoized fused step as a
    constant and silently reuse another booster's fold data. Fail
    loudly instead — run only when memo_ok (the cache-sharing case).
    Scans pytree LEAVES so device arrays hiding inside containers
    (tuples, dicts, NamedTuples) are caught too."""
    import jax

    def holds_device_array(v) -> bool:
        return any(
            isinstance(leaf, jax.Array)
            for leaf in jax.tree_util.tree_leaves(v)
        )

    extra = sorted(
        a for a, v in vars(objective).items()
        if a not in _OBJ_FOLD_ATTRS
        and a not in _OBJ_FOLD_EXEMPT
        and holds_device_array(v)
    )
    if extra:
        log.fatal(
            f"objective {type(objective).__name__} holds device-array "
            f"attribute(s) {extra} outside _OBJ_FOLD_ATTRS: the fused "
            "step memo would bake them into a cached executable and "
            "share them across cv folds / repeated trains. Add them to "
            "_OBJ_FOLD_ATTRS (rebind per fold) or _OBJ_FOLD_EXEMPT "
            "(with the gate that makes the memo safe)."
        )


class _EvalNames(NamedTuple):
    names: List[str]
    higher_better: List[bool]


class _PendingChunk(NamedTuple):
    """One chunk-scan dispatch awaiting readback: ``trees`` is a tuple
    of K TreeArrays whose every field is stacked ``(C, ...)`` by the
    scan. Only the first ``n_active`` rounds are real; the tail past
    the dispatch's ``it_end`` is an algebraic no-op on device (zeroed
    leaf values, frozen iteration counter) and is sliced off on the
    host at materialize, never entering the model list."""

    trees: Any
    n_rounds: int
    n_active: int


# device_trees placeholder for rounds living inside a not-yet-fetched
# _PendingChunk; _materialize() replaces it with (host TreeArrays,
# None). Every consumer of device_trees content materializes first
# (fused_truncate, rollback via the `models` property, refit/splice),
# so a None read here is a loud bug, not a silent wrong answer.
_PENDING_SLOT: Tuple[Any, Any] = (None, None)


def _pick_chunk(rounds_left: int, ladder: Sequence[int]) -> int:
    """Largest ladder rung that fits, else the smallest rung (the
    masked-tail dispatch). Greedy decomposition over a fixed ladder
    bounds distinct scan executables at len(ladder) for ANY round
    count — the retrace-guard contract."""
    for c in sorted(ladder, reverse=True):
        if c <= rounds_left:
            return c
    return min(ladder)


class _FusedProgram:
    """Traced programs for one fused-step memo key: the raw step body,
    its per-round jit, and lazily-built C-round lax.scan chunk jits
    (one per ladder rung actually dispatched). Cached in
    _FUSED_STEP_CACHE, so the memo key effectively grows the chunk
    length through ``chunks`` — cv folds and repeated trains share the
    scan executables exactly like they share the per-round step."""

    def __init__(self, step_fn, donate):
        import jax

        self.step_fn = step_fn
        self.step = jax.jit(step_fn, donate_argnums=donate)
        self._donate = donate
        self.chunks: Dict[int, Any] = {}

    def chunk_body(self, length: int):
        """Un-jitted C-round chunk callable: scans the per-round step,
        stacking the K per-round tree pytrees to (C, ...) and the eval
        rows to (C, E). Exposed un-jitted so the analysis suite can
        make_jaxpr it (the `fused_chunk_scan` entry)."""
        from jax import lax

        step_fn = self.step_fn

        def chunk(state, data):
            def body(st, _):
                st2, trees, eval_row = step_fn(st, data)
                return st2, (trees, eval_row)

            new_state, (trees, eval_mat) = lax.scan(
                body, state, xs=None, length=length
            )
            return new_state, trees, eval_mat

        return chunk

    def chunk(self, length: int):
        import jax

        fn = self.chunks.get(length)
        if fn is None:
            fn = jax.jit(self.chunk_body(length),
                         donate_argnums=self._donate)
            self.chunks[length] = fn
        return fn


def _obj_grads(objective, score, it):
    """Call an objective's gradient fn, passing the iteration to
    stochastic objectives (rank_xendcg redraws its perturbation each
    iteration; everything else ignores it)."""
    if getattr(objective, "needs_iter", False):
        return objective.get_gradients(score, it)
    return objective.get_gradients(score)


def _jit_traverse():
    import jax

    return jax.jit(traverse_tree_bins)


def _load_forced_splits(path: str, ds: "BinnedDataset"):
    """Read a forcedsplits json into a BFS plan (ForceSplits,
    serial_tree_learner.cpp:627): each node {feature, threshold,
    left?, right?}; thresholds map to bins via the feature's mapper.
    Returns a learner ForcedSplits or None on any problem (warned)."""
    import json as _json

    import jax.numpy as jnp

    from .binning import BinType
    from .learner.permuted import ForcedSplits

    try:
        with open(path) as f:
            root = _json.load(f)
    except (OSError, ValueError) as e:
        log.warning(f"cannot read forcedsplits_filename {path}: {e}")
        return None
    used_pos = {int(f): i for i, f in enumerate(ds.used_features)}
    from collections import deque

    leaves, feats, bins_ = [], [], []
    q = deque([(root, 0)])
    i = 0
    while q:
        node, leaf = q.popleft()
        if not isinstance(node, dict) or "feature" not in node:
            continue
        f_orig = int(node["feature"])
        if f_orig not in used_pos:
            log.warning(
                f"forced split on unused/trivial feature {f_orig}; "
                "skipping this branch"
            )
            continue
        m = ds.mappers[f_orig]
        if m.bin_type == BinType.CATEGORICAL:
            log.warning(
                "forced splits on categorical features are not supported; "
                f"skipping feature {f_orig}"
            )
            continue
        thr = float(node.get("threshold", 0.0))
        b = int(np.searchsorted(m.upper_bounds, thr, side="left"))
        b = min(b, max(m.num_bin - 2, 0))
        leaves.append(leaf)
        feats.append(used_pos[f_orig])
        bins_.append(b)
        new_leaf = i + 1  # right child's leaf id (Tree::Split numbering)
        if isinstance(node.get("left"), dict):
            q.append((node["left"], leaf))
        if isinstance(node.get("right"), dict):
            q.append((node["right"], new_leaf))
        i += 1
    if not leaves:
        return None
    return ForcedSplits(
        leaf=jnp.asarray(leaves, jnp.int32),
        feature=jnp.asarray(feats, jnp.int32),
        bin=jnp.asarray(bins_, jnp.int32),
        n=jnp.int32(len(leaves)),
    )


class GBDT:
    """Training driver (reference gbdt.h:37)."""

    def __init__(self, config: Config, train_set: Optional[BinnedDataset]):
        import jax.numpy as jnp

        from ._cache import ensure_compile_cache

        ensure_compile_cache()
        self.config = config
        self.train_set = train_set
        self.objective: Optional[ObjectiveFunction] = create_objective(config)
        self.num_class = config.num_model_per_iteration
        self.shrinkage_rate = config.learning_rate
        self.average_output = False  # RF mode divides prediction by #iters
        self._models: List[Tree] = []  # flat, iteration-major (models_[it*K + k])
        self.device_trees: List[Tuple[TreeArrays, Any]] = []  # (arrays w/ final leaf values, None)
        self.iter_ = 0
        self.best_iteration = -1
        self.valids: List[_ScoreSet] = []
        self._traverse = _jit_traverse()
        # flight-recorder hooks (obs/recorder.py): engine.train installs
        # a recorder here when record_file/anomaly_policy is configured;
        # the loops then publish gh norms (eager: _prepare_gradients;
        # fused: the eval-row tail collected into _last_gh_rows)
        self.recorder = None
        self._last_gh_norm: Optional[Tuple[float, float]] = None
        self._last_gh_rows: List[Tuple[float, float]] = []
        # ---- async training pipeline (the TPU analog of the reference's
        # synchronous per-iteration loop): under the axon runtime any
        # device->host readback both costs a ~70ms sync AND permanently
        # degrades dispatch latency, so the fast path materializes host
        # trees lazily in batches (one device_get) and checks the
        # "no splittable leaf" stop condition only every _check_every
        # iterations. DART/RF and leaf-renewal objectives need per-iter
        # host work and force the synchronous path.
        self._pending: List[Any] = []  # TreeArrays (per-round) / _PendingChunk
        self._pending_meta: List[Tuple[int, float, float]] = []  # (k, bias, shrinkage)
        # dispatch-count probe: executable launches issued by
        # fused_dispatch (one per chunk under chunk scanning, one per
        # round with tpu_chunk_scan=off) + the host seconds spent
        # issuing them — read by tests and bench.py's chunk_scan
        # segment. _last_dispatch_rounds holds the round count of each
        # dispatch in the most recent chunk so _ObsHooks can expand
        # per-dispatch spans into per-round durations.
        self.fused_dispatch_count = 0
        self._dispatch_host_s = 0.0
        self._last_dispatch_rounds: List[int] = []
        self._stopped = False
        # aligned to max(config.DEFAULT_CHUNK_LADDER) so a full driver
        # chunk dispatches as ONE top-rung lax.scan (50 used to shred
        # into 16+16+16+4 and the 64 rung never fired)
        self._check_every = 64
        self._force_sync = False
        self._force_sync_reason: Optional[str] = None
        self._init_iters = 0  # loaded iterations under continued training
        # resolved histogram channel layout (tpu_hist_dtype policy);
        # overwritten below when a train_set selects the real path
        self.hist_dtype = "bf16x2"
        self._hist_levels = 0
        self._int_packed = False

        if train_set is None:
            return  # prediction-only booster (model loaded from file)

        from .config import warn_unimplemented

        warn_unimplemented(config)
        # true-gradient leaf renewal bypasses the grower's monotone
        # interval clamp and path smoothing — refuse the combination
        # rather than silently violate a declared constraint. The same
        # guard gates the internal int-packed path's always-on renewal
        # (_grow_maybe_quantized).
        self._true_renew_ok = not (
            config.path_smooth > 0
            or (train_set.monotone_constraints is not None
                and np.any(train_set.monotone_constraints != 0))
        )
        self._quant_renew_ok = True
        if config.use_quantized_grad and config.quant_train_renew_leaf \
                and not self._true_renew_ok:
            self._quant_renew_ok = False
            log.warning(
                "quant_train_renew_leaf is disabled: true-gradient leaf "
                "renewal would bypass monotone constraints / path_smooth"
            )

        # ---- tree learner selection (reference tree_learner.cpp:17-59):
        # "data"/"voting" route growth through the sharded grower over a
        # 1-D device mesh (rows sharded, histograms psum'd over ICI —
        # data_parallel_tree_learner.cpp:286). Voting's top-k election
        # exists to cap socket bytes; on a TPU mesh the histogram reduce
        # is an XLA collective riding ICI, so both configs use the same
        # reduction (identical results to "data" by construction).
        self._mesh = None
        self._dp = None
        self._parallel_mode = None  # None | "data" | "feature"
        import jax

        n_dev = jax.device_count()
        if config.tree_learner in ("data", "voting") and n_dev > 1:
            from .learner.histogram import HIST_BLK
            from .parallel.data_parallel import make_mesh

            if config.tree_learner == "voting":
                log.info(
                    f"tree_learner=voting: top-{config.top_k} local-gain "
                    "vote elects features per round (per split on the "
                    "exact oracle); only elected columns are psum'd "
                    "across the mesh "
                    "(voting_parallel_tree_learner.cpp semantics)"
                )
            self._mesh = make_mesh()
            self._parallel_mode = "data"
            blk = HIST_BLK
            if HIST_BLK % n_dev != 0 or jax.devices()[0].platform == "tpu":
                blk = HIST_BLK * n_dev  # per-shard rows stay pallas-aligned
            train_set.ensure_row_block(blk)
            if jax.process_count() > 1:
                # pre-partitioned ranks hold UNEVEN shards; NamedSharding
                # tiles evenly, so every rank pads to the cluster-wide
                # max — AFTER the final row_block is set above (padded
                # counts are row_block multiples, identical across
                # ranks, so their max is too)
                from jax.experimental import multihost_utils

                padded = np.asarray(train_set.num_rows_padded(), np.int64)
                target = int(np.max(
                    multihost_utils.process_allgather(padded)
                ))
                train_set.ensure_min_padded_rows(target)
        elif config.tree_learner == "feature" and n_dev > 1:
            if train_set.bundle_layout is not None:
                log.warning(
                    "tree_learner=feature requires EFB off (feature == "
                    "column); falling back to serial growth. Set "
                    "enable_bundle=false."
                )
            else:
                from .parallel.data_parallel import make_mesh

                self._mesh = make_mesh(axis_name="feature")
                self._parallel_mode = "feature"
                log.info(
                    f"tree_learner=feature: {len(train_set.used_features)} "
                    f"features sharded over {n_dev} devices "
                    "(feature_parallel_tree_learner.cpp semantics)"
                )
        # objective/strategy init AFTER ensure_row_block: they cache
        # padded per-row arrays and must see the final row padding
        if self.objective is not None:
            self.objective.init(train_set)
        self.strategy = create_sample_strategy(
            config, train_set.num_data, group=train_set.metadata.group
        )
        self.dev = train_set.device_arrays()
        from .binning import BinType

        cat_subset = any(
            m.bin_type == BinType.CATEGORICAL
            and m.num_bin > config.max_cat_to_onehot
            for m in train_set.used_mappers()
        )
        # voting composes with EFB: the election unit is the bundle
        # column (permuted.py voting block), so no bundle guard here
        use_voting = (
            config.tree_learner == "voting" and self._mesh is not None
        )
        # ---- per-node extras: extra_trees, feature_fraction_bynode,
        # interaction constraints, CEGB (permuted sequential path only)
        from .config import parse_interaction_constraints

        groups = parse_interaction_constraints(
            config.interaction_constraints, len(train_set.mappers)
        )
        self._group_mat = None
        n_groups = 0
        if groups:
            used_pos = {int(f): i for i, f in enumerate(train_set.used_features)}
            gm = np.zeros((len(groups), len(train_set.used_features)), bool)
            for gi, gr in enumerate(groups):
                for f in gr:
                    if f in used_pos:
                        gm[gi, used_pos[f]] = True
            self._group_mat = jnp.asarray(gm)
            n_groups = len(groups)
        self._cegb_info = None
        use_cegb = (
            config.cegb_penalty_split > 0.0
            or len(config.cegb_penalty_feature_coupled) > 0
            or len(config.cegb_penalty_feature_lazy) > 0
        )
        if use_cegb:
            from .learner.grower import CegbInfo

            fu = len(train_set.used_features)

            def _pen(t):
                if not t:
                    return np.zeros(fu, np.float32)
                if len(t) != len(train_set.mappers):
                    log.fatal(
                        "cegb_penalty_feature_* must have one entry per feature"
                    )
                return np.asarray(
                    [t[int(f)] for f in train_set.used_features], np.float32
                )

            self._cegb_info = CegbInfo(
                coupled=jnp.asarray(_pen(config.cegb_penalty_feature_coupled)),
                lazy=jnp.asarray(_pen(config.cegb_penalty_feature_lazy)),
                used=jnp.zeros(fu, bool),
            )
            if len(config.cegb_penalty_feature_coupled) > 0:
                # coupled costs are charged once per feature MODEL-WIDE
                # (is_feature_used_in_split_); the fused loop cannot see
                # cross-iteration feature usage, so run synchronously
                self._force_sync = True
                self._force_sync_reason = (
                    "coupled CEGB penalties track model-wide feature use"
                )
        # forced splits (forcedsplits_filename, serial_tree_learner.cpp
        # ForceSplits): read the BFS plan once; leaf ids at application
        # time are precomputed (left child keeps the parent id, right
        # child gets i+1 — Tree::Split numbering)
        self._forced = None
        n_forced = 0
        if config.forcedsplits_filename:
            self._forced = _load_forced_splits(
                config.forcedsplits_filename, train_set
            )
            if self._forced is not None:
                n_forced = int(self._forced.leaf.shape[0])
        # voting + forced splits compose on the rounds grower: the
        # forced plan's bundle columns are pinned into every election,
        # so the prescribed features always carry globally-reduced
        # sums (rounds.py vote_reduce; the old warn-and-disable guard
        # predates the election pinning)
        if config.tpu_debug_check_split:
            self._force_sync = True  # the check reads back per iteration
            self._force_sync_reason = "tpu_debug_check_split reads back per iteration"
        if config.linear_tree:
            # leaf ridge fits run host-side per iteration (the reference
            # solves with Eigen on CPU too, linear_tree_learner.cpp:344)
            self._force_sync = True
            self._force_sync_reason = "linear_tree leaf fits run on host"
            if train_set.raw_data is None:
                log.fatal(
                    "linear_tree requires raw feature values; construct "
                    "the Dataset with linear_tree in its params"
                )
        use_extra = config.extra_trees
        use_bynode = config.feature_fraction_bynode < 1.0
        if (use_extra or use_bynode or use_cegb or n_groups) and (
            self._parallel_mode == "feature"
        ):
            log.warning(
                "extra_trees / feature_fraction_bynode / cegb / interaction"
                "_constraints are not supported with tree_learner=feature; "
                "ignoring them"
            )
            use_extra = use_bynode = use_cegb = False
            n_groups = 0
            self._cegb_info = self._group_mat = None
        if n_forced and self._parallel_mode == "feature":
            # the feature-parallel grower rides the flat partition which
            # has no forced-split support — dropping the plan (with a
            # warning) beats crashing at the first iteration
            log.warning(
                "forcedsplits_filename is not supported with "
                "tree_learner=feature; ignoring the forced-split plan"
            )
            self._forced = None
            n_forced = 0
        self._node_key = (
            jax.random.key(config.extra_seed) if (use_extra or use_bynode)
            else None
        )
        # ---- monotone constraint method: 1 = intermediate (both the
        # sequential permuted grower — per-split recompute — and the
        # rounds grower — per-round recompute + conflict guard);
        # 2 = advanced (per-leaf range-overlap refinement of the
        # opposite-subtree extrema, rounds grower only). Both exclude
        # per-node extras, forced splits and voting (the re-search
        # ignores their per-node state / election masks).
        mono_any = (
            train_set.monotone_constraints is not None
            and np.any(np.asarray(train_set.monotone_constraints) != 0)
        )
        mono_mode = 0
        if mono_any:
            mono_mode = {"intermediate": 1, "advanced": 2}.get(
                config.monotone_constraints_method, 0
            )
        if mono_mode and (use_extra or use_bynode or use_cegb or n_groups
                          or n_forced or use_voting
                          or self._parallel_mode == "feature"):
            log.warning(
                "monotone_constraints_method=intermediate/advanced is "
                "incompatible with per-node extras / forced splits / "
                "voting / tree_learner=feature; falling back to "
                "method=basic"
            )
            mono_mode = 0
        # ---- growth strategy (tpu_growth_mode): natural-order
        # round-batched growth is the single production grower
        # (ISSUE 14). Monotone constraints (basic / intermediate /
        # advanced), per-node extras (extra_trees /
        # feature_fraction_bynode / CEGB / interaction constraints),
        # voting-parallel (per-round election, elected columns only on
        # the wire) and forced splits all ride it; only
        # feature-parallel still requires the flat grower, and the
        # sequential permuted grower remains as the reference-exact
        # parity oracle behind tpu_growth_mode=exact.
        rounds_ok = self._parallel_mode != "feature"
        mode = config.tpu_growth_mode
        try:
            on_tpu = jax.devices()[0].platform == "tpu"
        except Exception:  # noqa: BLE001
            on_tpu = False
        if mode == "auto":
            use_rounds = on_tpu and rounds_ok
        else:
            use_rounds = mode == "rounds"
            if use_rounds and not rounds_ok:
                log.warning(
                    "tpu_growth_mode=rounds is incompatible with "
                    "tree_learner=feature; falling back to exact "
                    "sequential growth"
                )
                use_rounds = False
        if mono_mode == 2 and not use_rounds:
            # the advanced range-overlap refinement lives in the rounds
            # grower's per-round state; the sequential oracle implements
            # intermediate only
            log.warning(
                "monotone_constraints_method=advanced rides the rounds "
                "grower only (tpu_growth_mode=rounds); using "
                "method=intermediate on the sequential path"
            )
            mono_mode = 1
        if use_voting and n_forced and not use_rounds:
            # the sequential oracle cannot pin forced columns into its
            # per-split election (stale non-elected histogram columns
            # would corrupt the forced splits; permuted.py raises on the
            # combination) — keep the forced plan and drop the election,
            # the pre-unification fallback
            log.warning(
                "tree_learner=voting with forcedsplits_filename composes "
                "on the rounds grower (tpu_growth_mode=rounds pins the "
                "forced columns into every election); the sequential "
                "exact path runs with the election disabled"
            )
            use_voting = False
        # histogram channel-dtype policy (tpu_hist_dtype, ISSUE 12): on
        # the rounds path the DEFAULT (unquantized-API) trainer also
        # discretizes g/h per round to narrow integer levels and rides
        # the 3-channel slot-packed histogram kernels; f32 scales are
        # recovered before gain/leaf math and leaf outputs are renewed
        # from the true gradients, so the public semantics stay put.
        from .learner.quantize import resolve_hist_dtype

        self.hist_dtype, self._hist_levels, hd_warn = resolve_hist_dtype(
            config.tpu_hist_dtype, config.use_quantized_grad,
            config.num_grad_quant_bins, use_rounds, on_tpu=on_tpu,
        )
        if hd_warn and config.set_explicitly("tpu_hist_dtype"):
            log.warning(hd_warn)
        # int-packed channels on the default path (no public quant API)
        int_packed = self._hist_levels > 0
        self._int_packed = int_packed
        self.spec = GrowerSpec(
            num_leaves=config.num_leaves,
            num_bins=train_set.max_num_bin,
            max_depth=config.max_depth,
            axis_name="data" if self._parallel_mode == "data" else None,
            cat_subset=cat_subset,
            efb=train_set.bundle_layout is not None,
            col_bins=train_set.col_bins,
            # the PERMUTED batched mode still excludes per-node extras,
            # monotone intermediate, voting and forced splits
            # (permuted.py raises); the natural-order rounds grower is
            # the path that supports them
            rounds=(config.tpu_growth_rounds and not use_rounds
                    and rounds_ok and not mono_mode
                    and not use_voting and not n_forced
                    and not (use_extra or use_bynode or use_cegb
                             or n_groups)),
            # slot defaults are chip-tuned END TO END (BENCH_NOTES r4):
            # quant ch3 S=48 beat both 42 (0.258 vs 0.302 ms/split) and
            # 64 (10.06 vs 9.83 trees/s); non-quant S=32 measured
            # SLOWER than 25 end to end (4.39 vs 4.75 — wider passes
            # waste width on candidate-limited rounds) so 25 stays
            rounds_slots=(
                min(config.tpu_round_slots
                    or (48 if (config.use_quantized_grad or int_packed)
                        else 25),
                    config.num_leaves)
                if use_rounds else 0
            ),
            # int levels must be bf16-exact (integers <= 256); larger
            # num_grad_quant_bins rides the dequantized 5-channel path.
            # The internal hist_dtype policy (int_packed) reuses the same
            # 3-channel integer machinery with its own level count.
            quant=bool(use_rounds
                       and ((config.use_quantized_grad
                             and config.num_grad_quant_bins <= 256)
                            or int_packed)),
            # levels within int8 range (g <= bins/2, h <= bins): the
            # kernel runs s8 x s8 -> s32 on the MXU. rounds.py further
            # gates on histogram.int8_oh_shift finding a SWAR scale
            # whose worst-case s32 cell sum cannot overflow (ADVICE r4)
            quant_int8=bool(use_rounds
                            and ((config.use_quantized_grad
                                  and config.num_grad_quant_bins <= 127)
                                 or (int_packed
                                     and self._hist_levels <= 127))),
            quant_levels=(config.num_grad_quant_bins
                          if config.use_quantized_grad
                          else self._hist_levels),
            mono_mode=mono_mode,
            voting_k=config.top_k if use_voting else 0,
            extra_trees=use_extra,
            ff_bynode=use_bynode,
            cegb=use_cegb,
            n_groups=n_groups,
            n_forced=n_forced,
            has_cat=any(
                m.bin_type == BinType.CATEGORICAL
                for m in train_set.used_mappers()
            ),
        )
        self.params = make_split_params(config)
        # ---- provenance for the flight recorder / run manifest
        # (docs/OBSERVABILITY.md): which learner family actually trains
        # after mode resolution, and the voting election footprint
        g_dev = int(self.dev["bins"].shape[0])
        self.tree_learner_resolved = (
            "voting" if use_voting
            else self._parallel_mode if self._parallel_mode in (
                "data", "feature")
            else "serial"
        )
        self.voting_elected_cols = (
            min(2 * config.top_k + n_forced, g_dev) if use_voting else None
        )
        # per-tree wire estimate; refined by the data-parallel grower's
        # voting-aware wire_bytes_per_tree once it exists (below)
        self.voting_wire_bytes_est = None
        self.train = _ScoreSet(
            train_set,
            self._init_score_arr(train_set),
            "training",
            [m for m in create_metrics(config)],
        )
        meta = train_set.metadata
        for m in self.train.metrics:
            m.init(meta.label, meta.weight, meta.group)
        self._boosted_from_average = False
        self._init_scores = [0.0] * self.num_class
        self._feat_rng = np.random.RandomState(config.feature_fraction_seed)
        self._label_dev = (
            jnp.asarray(train_set.padded(meta.label)) if meta.label is not None else None
        )
        if self._parallel_mode == "data":
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .parallel.data_parallel import DataParallelGrower

            if jax.process_count() > 1:
                # multi-controller cluster: the fused loop closes over
                # the dataset arrays, which is illegal for arrays
                # spanning non-addressable devices — ride the sync path
                # (every jit takes the global arrays as arguments).
                self._force_sync = True
                self._force_sync_reason = (
                    "multi-process runs synchronize per iteration"
                )
                if self.config.bagging_freq > 0 and \
                        self.config.bagging_fraction < 1.0:
                    log.warning(
                        "bagging under multi-host training is not yet "
                        "global-row aware; disabling bagging"
                    )
                    self.config.bagging_freq = 0

            self._dp = DataParallelGrower(self._mesh, self.spec)
            if use_voting:
                self.voting_wire_bytes_est = self._dp.wire_bytes_per_tree(
                    int(self.dev["bins"].shape[0])
                )
            self.dev = self._dp.shard_inputs(self.dev)
            # free the unsharded device copies — this booster reads only
            # self.dev for the train set; other boosters re-push fresh
            train_set.invalidate_device_cache()
            if jax.process_count() > 1:
                from .parallel.multihost import global_rows

                self.train.score = global_rows(
                    np.asarray(self.train.score), self._mesh, axis=1
                )
                if self._label_dev is not None:
                    self._label_dev = global_rows(
                        np.asarray(self._label_dev), self._mesh, axis=0
                    )
                # objective per-row device arrays follow the same global
                # row sharding (each rank contributed its shard). The
                # HOST statistics (_bfs_label & friends) must cache
                # BEFORE the swap: afterwards np.asarray on the global
                # arrays would raise (non-addressable shards)
                o = self.objective
                if o is not None:
                    o._bfs_label()
                    o._np_weight()
                    if getattr(o, "_label_weight", None) is not None:
                        o._bfs_label_weight()
                    for attr in ("label", "weight", "_label_weight"):
                        a = getattr(o, attr, None)
                        if a is not None:
                            setattr(o, attr, global_rows(
                                np.asarray(a), self._mesh, axis=0
                            ))
            else:
                row = NamedSharding(self._mesh, P(None, "data"))
                self.train.score = jax.device_put(self.train.score, row)
                if self._label_dev is not None:
                    self._label_dev = jax.device_put(
                        self._label_dev, NamedSharding(self._mesh, P("data"))
                    )
        elif self._parallel_mode == "feature":
            from .parallel.feature_parallel import FeatureParallelGrower

            self._dp = FeatureParallelGrower(self._mesh, self.spec)
            self.dev = self._dp.shard_inputs(self.dev)
            train_set.invalidate_device_cache()

    # ------------------------------------------------------------------
    def _record_collective_wire(self, n_trees: int) -> None:
        """Runtime collective wire accounting (docs/OBSERVABILITY.md):
        count the estimated histogram-reduce payload for n_trees
        freshly dispatched trees. Called only from host-side loop code
        — never inside a trace, where it would tick once per compile
        instead of once per dispatch."""
        if self._dp is None or self._parallel_mode != "data":
            return
        fn = getattr(self._dp, "wire_bytes_per_tree", None)
        if fn is None:
            return
        from .obs.metrics import record_collective_wire

        record_collective_wire(
            "data_parallel_grow",
            fn(int(self.dev["bins"].shape[0])) * n_trees,
        )

    # ------------------------------------------------------------------
    def _renewal_setup(self):
        """(alpha, weights) for device percentile leaf renewal, or
        (None, None) when the objective doesn't renew. MAPE renews with
        its label-derived weights (regression_objective.hpp:641)."""
        import jax.numpy as jnp

        o = self.objective
        if o is None or not o.is_renew_tree_output:
            return None, None
        alpha = float(o.renew_percentile())
        w = getattr(o, "_label_weight", None)
        if w is None:
            w = o.weight
        if w is None:
            w = jnp.ones(self.train_set.num_rows_padded(), jnp.float32)
        return alpha, w

    def _quantize(self, gk, hk, it, k, num_bins=None):
        """use_quantized_grad: discretize this tree's gradients to
        INTEGER levels + scales (gradient_discretizer.cpp
        DiscretizeGradients); traceable. `num_bins` overrides the
        public quant level count (the internal hist_dtype policy passes
        its own 256/127)."""
        import jax

        from .learner.quantize import discretize_gradients_int

        c = self.config
        key = jax.random.fold_in(
            jax.random.key(c.data_random_seed), it * self.num_class + k
        )
        return discretize_gradients_int(
            gk, hk, key, num_bins or c.num_grad_quant_bins,
            c.stochastic_rounding,
        )

    def _grow_int_packed(self, gk, hk, mask, feat_mask, valid, it, k,
                         bins=None, tables=None):
        """Internal hist_dtype=int16/int8 policy (ISSUE 12): the default
        API path discretizes g/h to self._hist_levels integer levels,
        accumulates 3 narrow channels through the rounds grower's
        spec.quant machinery (scales recovered before gain math), and
        renews leaf outputs from the TRUE gradients so the public
        semantics stay within stochastic-rounding noise of bf16x2."""
        gq, hq, scale = self._quantize(gk, hk, it, k,
                                       num_bins=self._hist_levels)
        arrays, row_leaf = self._grow(
            gq, hq, mask, feat_mask, valid, it, k, gh_scale=scale,
            bins=bins, tables=tables,
        )
        if self._true_renew_ok:
            from .learner.quantize import renew_leaf_with_true_gradients

            arrays = arrays._replace(
                leaf_value=renew_leaf_with_true_gradients(
                    arrays.leaf_value, row_leaf, gk, hk, mask,
                    self.params, self.spec.num_leaves,
                )
            )
        return arrays, row_leaf

    def _grow_maybe_quantized(self, gk, hk, mask, feat_mask, valid, it, k,
                              bins=None, tables=None):
        """One tree: quantize gradients first when use_quantized_grad
        (all paths — fast, fused, sync/DART, RF — share this so none can
        silently skip quantization), optionally renewing leaf outputs
        with the true gradients afterward."""
        c = self.config
        if not c.use_quantized_grad:
            if self._int_packed and self.spec.quant:
                return self._grow_int_packed(
                    gk, hk, mask, feat_mask, valid, it, k,
                    bins=bins, tables=tables,
                )
            return self._grow(gk, hk, mask, feat_mask, valid, it, k,
                              bins=bins, tables=tables)
        gq, hq, scale = self._quantize(gk, hk, it, k)
        if self.spec.quant:
            # rounds grower consumes the integer levels directly: exact
            # int histogram sums in 3 channels/slot (48 slots/MXU pass)
            arrays, row_leaf = self._grow(
                gq, hq, mask, feat_mask, valid, it, k, gh_scale=scale,
                bins=bins, tables=tables,
            )
        else:
            arrays, row_leaf = self._grow(
                gq * scale[0], hq * scale[1], mask, feat_mask, valid, it, k,
                bins=bins, tables=tables,
            )
        if c.quant_train_renew_leaf and self._quant_renew_ok:
            from .learner.quantize import renew_leaf_with_true_gradients

            arrays = arrays._replace(
                leaf_value=renew_leaf_with_true_gradients(
                    arrays.leaf_value, row_leaf, gk, hk, mask,
                    self.params, self.spec.num_leaves,
                )
            )
        return arrays, row_leaf

    def _apply_renewal(self, arrays, row_leaf, score_k, mask, renew_alpha,
                       renew_w, label=None):
        """Device percentile leaf refit (shared by fast + fused paths).
        `label` overrides the captured label array (the fused step
        passes its traced jit-argument copy)."""
        from .learner.renewal import renew_leaf_values

        resid = (self._label_dev if label is None else label) - score_k
        return arrays._replace(
            leaf_value=renew_leaf_values(
                arrays.leaf_value, row_leaf, resid, renew_w * mask,
                renew_alpha, self.spec.num_leaves,
            )
        )

    # ------------------------------------------------------------------
    def _grow(self, gk, hk, mask, feat_mask, valid, it=0, k=0, gh_scale=None,
              bins=None, tables=None):
        """Grow one tree on the training set — serial, or sharded over the
        data mesh when tree_learner=data/voting (lockstep trees on every
        shard, reference data_parallel_tree_learner.cpp). Traceable: used
        both eagerly and inside the fused jit step (it may be traced).
        `bins` / `tables` override the training bin matrix and the small
        per-feature tables — the fused step passes its traced
        jit-argument copies so the executable neither embeds the matrix
        as a constant nor bakes fold-specific tables into the trace."""
        import jax

        d = self.dev if bins is None else dict(self.dev, bins=bins)
        if tables is not None:
            d = dict(d, **tables)
        rng_key = None
        if self._node_key is not None:
            rng_key = jax.random.fold_in(
                self._node_key, it * self.num_class + k
            )
        if self._dp is not None:
            return self._dp(
                d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
                gk, hk, mask, feat_mask, self.params, valid,
                d.get("bundle"), rng_key, self._group_mat, self._cegb_info,
                self._forced, gh_scale,
            )
        return grow_tree(
            d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
            gk, hk, mask, feat_mask, self.params, self.spec, valid=valid,
            bundle=d.get("bundle"), rng_key=rng_key,
            group_mat=self._group_mat, cegb=self._cegb_info,
            forced=self._forced, gh_scale=gh_scale,
        )

    # ------------------------------------------------------------------
    def _init_score_arr(self, ds: BinnedDataset):
        import jax.numpy as jnp

        npad = ds.num_rows_padded()
        score = np.zeros((self.num_class, npad), dtype=np.float32)
        init = ds.metadata.init_score
        if init is not None:
            init = np.asarray(init, dtype=np.float32)
            if init.size == ds.num_data * self.num_class:
                score[:, : ds.num_data] = init.reshape(self.num_class, ds.num_data)
            else:
                score[:, : ds.num_data] = init[None, :]
        return jnp.asarray(score)

    def add_valid(self, valid_set: BinnedDataset, name: str) -> None:
        ss = _ScoreSet(
            valid_set,
            self._init_score_arr(valid_set),
            name,
            [m for m in create_metrics(self.config)],
        )
        meta = valid_set.metadata
        for m in ss.metrics:
            m.init(meta.label, meta.weight, meta.group)
        self.valids.append(ss)

    @property
    def has_init_score(self) -> bool:
        return self.train_set.metadata.init_score is not None

    # ------------------------------------------------------------------
    @property
    def models(self) -> List[Tree]:
        self._materialize()
        return self._models

    @models.setter
    def models(self, value: List[Tree]) -> None:
        self._pending = []
        self._pending_meta = []
        self._models = value

    def _materialize(self) -> None:
        """Fetch all pending device trees in ONE device_get and convert to
        host Trees; detects the reference's stop condition (an iteration
        where no class-tree could split, gbdt.cpp:429-452) after the fact
        and drops that iteration and everything behind it."""
        if not self._pending:
            return
        import jax
        import jax.numpy as jnp

        from .timer import global_timer as _gt

        with _gt.scope("materialize host trees (readback)"):
            fetched = jax.device_get(self._pending)
        meta = self._pending_meta
        self._pending = []
        self._pending_meta = []
        K = self.num_class
        base = len(self._models)  # device_trees index of host[0]
        # flatten chunk-scan dispatches into the per-class-tree stream
        # the loop below expects: a _PendingChunk holds K TreeArrays
        # stacked (C, ...) — slice out each LIVE round (masked tail
        # rounds past it_end were never appended to device_trees/meta)
        host: List[Any] = []
        for item in fetched:
            if isinstance(item, _PendingChunk):
                for r in range(item.n_active):
                    for a in item.trees:
                        host.append(
                            jax.tree.map(lambda x, _r=r: x[_r], a)
                        )
            else:
                host.append(item)
        # chunk dispatches park _PENDING_SLOT placeholders in
        # device_trees; back-fill them with the host-sliced arrays so
        # rollback paths (stop detection below, fused_truncate,
        # rollback_one_iter) can traverse them. Re-wrap as jnp arrays:
        # device_trees entries are contractually jax (set_leaf_output
        # edits them with .at[].set, scoring restacks them).
        for j, a in enumerate(host):
            if self.device_trees[base + j] is _PENDING_SLOT:
                self.device_trees[base + j] = (
                    jax.tree.map(jnp.asarray, a), None
                )
        for i0 in range(0, len(host), K):
            group = host[i0 : i0 + K]
            if all(int(a.num_nodes) == 0 for a in group):
                if base + i0 == 0:
                    # first-ever iteration has no splits: keep K constant
                    # trees carrying the bias (sync path / gbdt.cpp:429-441
                    # keep the len==K model set)
                    for a, (k, bias, shrink) in zip(group, meta[i0 : i0 + K]):
                        if (
                            abs(bias) < 1e-15
                            and self.objective is not None
                            and not self.config.boost_from_average
                            and not self.has_init_score
                        ):
                            bias = self.objective.boost_from_score(k)
                            if abs(bias) > 1e-15:
                                self.train.score = self.train.score.at[k].add(bias)
                                for vs in self.valids:
                                    vs.score = vs.score.at[k].add(bias)
                        t = Tree(num_leaves=1, shrinkage=1.0)
                        t.leaf_value = np.array([bias], np.float64)
                        self._models.append(t)
                    i0 += K
                # roll back score contributions of any blindly-trained
                # later iterations that DID split (possible under bagging)
                for j in range(i0, len(host)):
                    if int(host[j].num_nodes) == 0:
                        continue
                    arrays, _ = self.device_trees[base + j]
                    k = meta[j][0]
                    leaf = self._traverse(
                        arrays, self.dev["bins"], self.dev["nan_bin"],
                        self.dev.get("bundle"),
                    )
                    self.train.score = self.train.score.at[k].add(
                        -arrays.leaf_value[leaf]
                    )
                    for vs in self.valids:
                        vdev = vs.dataset.device_arrays()
                        vleaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"], vdev.get("bundle"))
                        vs.score = vs.score.at[k].add(-arrays.leaf_value[vleaf])
                log.warning(
                    "Stopped training because there are no more leaves that meet the split requirements"
                )
                del self.device_trees[len(self._models) :]
                self.iter_ = len(self._models) // K
                self._stopped = True
                return
            for a, (k, bias, shrink) in zip(group, meta[i0 : i0 + K]):
                n_nodes = int(a.num_nodes)
                if n_nodes > 0:
                    # device leaf_value already carries shrinkage + bias
                    tree = Tree.from_arrays(a, self.train_set, 1.0)
                    tree.shrinkage = shrink
                else:
                    tree = Tree(num_leaves=1, shrinkage=1.0)
                    tree.leaf_value = np.array([bias], np.float64)
                self._models.append(tree)

    def train_one_iter(
        self, grad: Optional[np.ndarray] = None, hess: Optional[np.ndarray] = None
    ) -> bool:
        """One boosting iteration; returns True when training should stop
        (no splittable leaf), matching GBDT::TrainOneIter (gbdt.cpp:352)."""
        if self._stopped:
            return True
        # leaf renewal runs on device (learner/renewal.py), so renewal
        # objectives ride the fast path too; custom fobj with a renewal
        # objective still renews (the reference's UpdateOneIterCustom
        # calls RenewTreeOutput as well)
        fast = not self._force_sync and (
            grad is not None or self.objective is not None
        )
        if fast:
            return self._train_one_iter_fast(grad, hess)
        return self._train_one_iter_sync(grad, hess)

    def _prepare_gradients(self, grad, hess):
        """Shared per-iteration prep: boost-from-average on the first
        iteration (gbdt.cpp:327), then objective gradients at the current
        score — or padding of caller-supplied custom grad/hess.
        Returns (grad_dev (K, Np), hess_dev (K, Np), init_scores)."""
        import jax.numpy as jnp

        K = self.num_class
        ds = self.train_set
        init_scores = [0.0] * K
        if grad is None or hess is None:
            if self.objective is None:
                log.fatal("custom objective requires explicit grad/hess")
            if (
                not self._models
                and not self._pending
                and self.config.boost_from_average
                and not self.has_init_score
            ):
                for k in range(K):
                    init = self.objective.boost_from_score(k)
                    if abs(init) > 1e-15:
                        init_scores[k] = init
                        self.train.score = self.train.score.at[k].add(init)
                        for vs in self.valids:
                            vs.score = vs.score.at[k].add(init)
                        log.info(f"Start training from score {init:f}")
            score = self.train.score if K > 1 else self.train.score[0]
            g, h = _obj_grads(self.objective, score, self.iter_)
            grad_dev = jnp.reshape(g, (K, -1)).astype(jnp.float32)
            hess_dev = jnp.reshape(h, (K, -1)).astype(jnp.float32)
        else:
            grad = np.asarray(grad, dtype=np.float32).reshape(K, ds.num_data)
            hess = np.asarray(hess, dtype=np.float32).reshape(K, ds.num_data)
            npad = ds.num_rows_padded()
            gp = np.zeros((K, npad), np.float32)
            hp = np.zeros((K, npad), np.float32)
            gp[:, : ds.num_data] = grad
            hp[:, : ds.num_data] = hess
            grad_dev, hess_dev = jnp.asarray(gp), jnp.asarray(hp)
        # flight-recorder gh summaries (eager loops only — the fused
        # step computes its own inside the trace). Host-side float()
        # syncs, so this runs ONLY when a recorder/sentinel is active;
        # the default path stays readback-free.
        if getattr(self, "recorder", None) is not None:
            self._last_gh_norm = (
                float(jnp.sqrt(jnp.sum(grad_dev * grad_dev))),
                float(jnp.sqrt(jnp.sum(hess_dev * hess_dev))),
            )
        return grad_dev, hess_dev, init_scores

    def _train_one_iter_fast(
        self, grad: Optional[np.ndarray] = None, hess: Optional[np.ndarray] = None
    ) -> bool:
        """Sync-free iteration: no device->host reads; host trees and the
        stop check are deferred to _materialize()."""
        import jax
        import jax.numpy as jnp

        K = self.num_class
        with _gt.scope(ROUND_PHASES[0]):
            grad_dev, hess_dev, init_scores = self._prepare_gradients(
                grad, hess
            )
        renew_alpha, renew_w = self._renewal_setup()

        one = jnp.float32(1.0)
        for k in range(K):
            with _gt.scope(ROUND_PHASES[1]):
                gk, hk = grad_dev[k], hess_dev[k]
                mask, gk, hk = self.strategy.sample(
                    self.iter_, gk, hk, self.dev["valid"], self._label_dev
                )
                feat_mask = self._sample_features(k=k)
                arrays, row_leaf = self._grow_maybe_quantized(
                    gk, hk, mask, feat_mask, self.dev["valid"], self.iter_, k
                )
                ok = (arrays.num_nodes > 0).astype(jnp.float32)
                if renew_alpha is not None:
                    arrays = self._apply_renewal(
                        arrays, row_leaf, self.train.score[k], mask,
                        renew_alpha, renew_w,
                    )
                lv = arrays.leaf_value * (self.shrinkage_rate * ok)
            with _gt.scope(ROUND_PHASES[2]):
                # score updates use the UNBIASED shrunk leaf values — the
                # score already received init_scores[k] at BoostFromAverage
                # (mirrors _train_one_iter_sync; adding the bias here too
                # would double-count it)
                self.train.score = self.train.score.at[k].set(
                    add_score(self.train.score[k], row_leaf, lv, one)
                )
                for vs in self.valids:
                    vdev = vs.dataset.device_arrays()
                    leaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"], vdev.get("bundle"))
                    vs.score = vs.score.at[k].set(
                        add_score(vs.score[k], leaf, lv, one)
                    )
                if abs(init_scores[k]) > 1e-15:
                    # AddBias (gbdt.cpp:424-426): only the STORED tree
                    # carries the boost-from-average bias
                    lv = lv + init_scores[k] * ok
                arrays = arrays._replace(leaf_value=lv)
                self.device_trees.append((arrays, None))
                self._pending.append(arrays)
                self._pending_meta.append(
                    (k, init_scores[k], self.shrinkage_rate)
                )
                # start the device->host copies now so _materialize is
                # ~free
                jax.tree.map(lambda a: a.copy_to_host_async(), arrays)

        self._record_collective_wire(K)
        self.iter_ += 1
        if self.iter_ % self._check_every == 0:
            self._materialize()
            return self._stopped
        return False

    def _train_one_iter_sync(
        self, grad: Optional[np.ndarray] = None, hess: Optional[np.ndarray] = None
    ) -> bool:
        import jax.numpy as jnp

        import time as _time

        K = self.num_class
        ds = self.train_set
        self._materialize()  # keep model list ordering if modes ever mix
        with _gt.scope(ROUND_PHASES[0]):
            grad_dev, hess_dev, init_scores = self._prepare_gradients(
                grad, hess
            )

        should_continue = False
        for k in range(K):
            with _gt.scope(ROUND_PHASES[1]):
                gk, hk = grad_dev[k], hess_dev[k]
                mask, gk, hk = self.strategy.sample(
                    self.iter_, gk, hk, self.dev["valid"], self._label_dev
                )
                feat_mask = self._sample_features(k=k)
                arrays, row_leaf = self._grow_maybe_quantized(
                    gk, hk, mask, feat_mask, self.dev["valid"], self.iter_, k
                )
            if self.config.tpu_debug_check_split:
                self._check_split(arrays, row_leaf, hk, mask)
            t_up = _time.perf_counter()
            n_nodes = int(arrays.num_nodes)
            if n_nodes > 0:
                should_continue = True
                if self._cegb_info is not None:
                    # charge coupled costs: mark this tree's features used
                    # model-wide (is_feature_used_in_split_)
                    used = self._cegb_info.used
                    nf = np.asarray(arrays.node_feature[:n_nodes])
                    used = used.at[jnp.asarray(nf)].set(True)
                    self._cegb_info = self._cegb_info._replace(used=used)
                if (
                    self.objective is not None
                    and self.objective.is_renew_tree_output
                ):
                    arrays = self._renew_tree_output(arrays, row_leaf, k, mask)
                # host tree applies shrinkage itself; device copy carries
                # the final (shrunk) leaf values for score updates
                tree = Tree.from_arrays(arrays, ds, self.shrinkage_rate)
                final_leaf = arrays.leaf_value * self.shrinkage_rate
                arrays = arrays._replace(leaf_value=final_leaf)
                one = jnp.float32(1.0)
                if self.config.linear_tree:
                    # fit ridge models on each leaf's path features
                    # (linear_tree_learner.cpp CalculateLinear) and apply
                    # per-row linear outputs to the scores
                    from .binning import BinType

                    n = ds.num_data
                    rl = np.asarray(row_leaf)[:n]
                    cat_set = {
                        int(f)
                        for f in ds.used_features
                        if ds.mappers[int(f)].bin_type == BinType.CATEGORICAL
                    }
                    tree.fit_linear_leaves(
                        rl, np.asarray(gk)[:n], np.asarray(hk)[:n],
                        ds.raw_data, cat_set, self.config.linear_lambda,
                        self.shrinkage_rate,
                        row_mask=np.asarray(mask)[:n] > 0,
                    )
                    vals = tree.linear_leaf_outputs(ds.raw_data, rl)
                    out = np.zeros(ds.num_rows_padded(), np.float32)
                    out[:n] = vals
                    self.train.score = self.train.score.at[k].add(
                        jnp.asarray(out)
                    )
                    for vs in self.valids:
                        vraw = vs.dataset.raw_data
                        vn = vs.dataset.num_data
                        vleafs = tree.predict_leaf(vraw)
                        vvals = tree.linear_leaf_outputs(vraw, vleafs)
                        vout = np.zeros(vs.dataset.num_rows_padded(), np.float32)
                        vout[:vn] = vvals
                        vs.score = vs.score.at[k].add(jnp.asarray(vout))
                else:
                    self.train.score = self.train.score.at[k].set(
                        add_score(self.train.score[k], row_leaf, final_leaf, one)
                    )
                    for vs in self.valids:
                        vdev = vs.dataset.device_arrays()
                        leaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"], vdev.get("bundle"))
                        vs.score = vs.score.at[k].set(
                            add_score(vs.score[k], leaf, final_leaf, one)
                        )
                if abs(init_scores[k]) > 1e-15:
                    # AddBias: the stored tree (host AND device) carries the
                    # boost-from-average bias; the score got it separately at
                    # BoostFromAverage, so score == sum(stored trees) exactly
                    # (matters for DART drops, gbdt.cpp:424-426)
                    tree.leaf_value = tree.leaf_value + init_scores[k]
                    if tree.is_linear:
                        tree.leaf_const = tree.leaf_const + init_scores[k]
                    arrays = arrays._replace(
                        leaf_value=arrays.leaf_value + init_scores[k]
                    )
                self.device_trees.append((arrays, None))
                self.models.append(tree)
            else:
                # stump: constant tree (gbdt.cpp:429-441)
                bias = 0.0
                if len(self.models) < K:
                    if (
                        self.objective is not None
                        and not self.config.boost_from_average
                        and not self.has_init_score
                    ):
                        bias = self.objective.boost_from_score(k)
                        self.train.score = self.train.score.at[k].add(bias)
                        for vs in self.valids:
                            vs.score = vs.score.at[k].add(bias)
                    else:
                        bias = init_scores[k]
                t = Tree(num_leaves=1, shrinkage=1.0)
                t.leaf_value = np.array([bias], np.float64)
                self.models.append(t)
                self.device_trees.append((arrays, None))
            _gt.add(ROUND_PHASES[2], _time.perf_counter() - t_up,
                    start=t_up)

        if not should_continue:
            log.warning(
                "Stopped training because there are no more leaves that meet the split requirements"
            )
            if len(self.models) > K:
                for _ in range(K):
                    self.models.pop()
                    self.device_trees.pop()
            return True
        self._record_collective_wire(K)
        self.iter_ += 1
        return False

    # ------------------------------------------------------------------
    # Fused device loop ("fast path v2"): ONE jit dispatch per iteration
    # covering gradients -> sampling -> growth -> score updates -> metric
    # evaluation, with zero host readbacks. Trees and per-iteration metric
    # vectors accumulate as device handles; the engine fetches a whole
    # chunk in one device_get and replays callbacks host-side. This is
    # the TPU reformulation of GBDT::Train (gbdt.cpp:245): the loop body
    # is identical, only the host/device boundary moved from "every op"
    # to "every chunk" because a single readback costs ~100ms on this
    # runtime.
    def fused_eligible(self) -> bool:
        return self.fused_ineligible_reason() is None

    def fused_ineligible_reason(self) -> Optional[str]:
        """None when the fused loop applies; otherwise a one-line reason
        (surfaced by engine.train so users know WHY they are on the
        slower per-iteration sync path)."""
        if self._force_sync:
            return (
                self._force_sync_reason
                or "this configuration requires the per-iteration sync loop"
            )
        if self.objective is None:
            return "no built-in objective (custom fobj)"
        if not getattr(self.objective, "is_device_gradients", True):
            return f"objective {self.objective.name} computes host gradients"
        if getattr(self.objective, "has_host_state", False):
            # e.g. lambdarank position-bias factors: cross-iteration
            # host-held state the fused trace could not update
            return (
                f"objective {self.objective.name} keeps cross-iteration "
                "host state (e.g. position debiasing)"
            )
        from .device_metrics import supported_names

        for ss in [self.train] + self.valids:
            if supported_names(ss.metrics) is None:
                return (
                    f"metric(s) {ss.metrics and [m.name for m in ss.metrics]}"
                    " have no device implementation"
                )
        return None

    def _build_fused(self, track_train: bool):
        import jax
        import jax.numpy as jnp

        from .device_metrics import DeviceEvalSet, supported_names

        K = self.num_class
        ds = self.train_set
        c = self.config
        # ---- every per-fold array rides the `data` jit ARGUMENT so the
        # traced step is fold-agnostic: cv folds and repeated trains
        # with identical shapes+config reuse ONE trace+executable
        # (VERDICT r4 item 6 — each Booster used to pay ~7 s trace +
        # ~20 s compile-cache deserialize). Big matrices additionally
        # must be args so they are not embedded as constants (152 MB
        # jit_step, round 4). NOT donated: callers keep their handles.
        sets = ([self.train] if track_train else []) + self.valids
        eval_specs = []  # (set name, metric names, higher_better, group)
        eval_arrs = []  # per set: label/weight/valid device arrays
        for ss in sets:
            names, hb = supported_names(ss.metrics)
            # the train set's device arrays are self.dev (sharded under a
            # mesh); don't re-push an unsharded copy through the cache
            dev = self.dev if ss is self.train else ss.dataset.device_arrays()
            meta = ss.dataset.metadata
            label = jnp.asarray(ss.dataset.padded(meta.label))
            weight = (
                jnp.asarray(ss.dataset.padded(meta.weight))
                if meta.weight is not None
                else None
            )
            eval_specs.append((ss.name, tuple(names), tuple(hb), meta.group))
            eval_arrs.append(
                {"label": label, "weight": weight, "valid": dev["valid"]}
            )
        self._f_eval_sets = [(nm, _EvalNames(list(n), list(h)))
                             for nm, n, h, _g in eval_specs]
        n_valid_sets = len(self.valids)
        vdevs = [vs.dataset.device_arrays() for vs in self.valids]
        frac = c.feature_fraction
        F = ds.num_used_features
        n_feat = max(1, int(np.ceil(frac * F))) if frac < 1.0 else F
        objective = self.objective
        strategy = self.strategy
        # all-numerical datasets statically skip the category-set test
        # in the per-iteration valid traversal (hot: runs inside step)
        traverse = partial(traverse_tree_bins, has_cat=self.spec.has_cat)
        renew_alpha, renew_w = self._renewal_setup()
        track_train_eval = track_train
        # flight recorder / sentinels configured -> the step also
        # returns gh norms on the eval-row tail (static at build time;
        # part of the memo key through the config string)
        want_gh = bool(
            getattr(c, "record_file", "")
            or getattr(c, "anomaly_policy", "off") != "off"
        )
        # memo eligibility must be known BEFORE tracing: ranking groups
        # (ndcg/map layouts, lambdarank) need CONCRETE label/group at
        # construction and therefore bake fold data into the trace
        memo_ok = (
            all(g is None for *_x, g in eval_specs)
            and self.train_set.metadata.group is None
            and self._forced is None
            and not getattr(self.strategy, "by_query", False)
            and self._dp is None
        )
        if memo_ok:
            # the memoized executable outlives this booster — every
            # fold-varying device attr must be in the rebind list
            _audit_fold_attrs(objective)
        closure_evals = None
        if not memo_ok:
            closure_evals = [
                DeviceEvalSet(c, list(spec[1]), list(spec[2]),
                              ea["label"], ea["weight"], ea["valid"], K,
                              group=spec[3])
                for spec, ea in zip(eval_specs, eval_arrs)
            ]

        def step(state, data):
            score = state["score"]
            vscores = state["vscores"]
            it = state["it"]
            shrink = state["shrink"]
            init_vec = state["init"]
            # chunk-scan activity mask: a round is live unless the
            # no-splittable-leaf stop already fired (`stopped`, sticky)
            # or it lies past this dispatch's round budget (`it_end`,
            # the masked tail of a ladder-rung scan). Inactive rounds
            # are algebraic no-ops — zeroed leaf values freeze every
            # score and `it` stops advancing, so RNG streams and state
            # re-align bit-exactly with the per-round loop at the next
            # dispatch boundary.
            stopped = state["stopped"]
            active = jnp.logical_and(
                jnp.logical_not(stopped), it < data["it_end"]
            )
            actf = active.astype(jnp.float32)
            s_for_grad = score if K > 1 else score[0]
            # fold-varying objective attributes arrive as args: rebind
            # the traced values around the gradient call (restored right
            # after, so no tracer leaks outlive the trace)
            saved = {a: getattr(objective, a)
                     for a in data["obj_arrs"]}
            for a, v in data["obj_arrs"].items():
                setattr(objective, a, v)
            try:
                g, h = _obj_grads(objective, s_for_grad, it)
            finally:
                for a, v in saved.items():
                    setattr(objective, a, v)
            if memo_ok:
                evals = [
                    DeviceEvalSet(c, list(spec[1]), list(spec[2]),
                                  ea["label"], ea["weight"], ea["valid"],
                                  K, group=spec[3])
                    for spec, ea in zip(eval_specs, data["eval_arrs"])
                ]
            else:
                evals = closure_evals
            grad = jnp.reshape(g, (K, -1)).astype(jnp.float32)
            hess = jnp.reshape(h, (K, -1)).astype(jnp.float32)
            valid_mask = data["valid"]
            trees = []
            grew = []  # per-class split indicators (pre-mask)
            for k in range(K):
                gk, hk = grad[k], hess[k]
                mask, gk, hk = strategy.sample(
                    it, gk, hk, valid_mask, data["obj_arrs"]["label"]
                )
                if frac < 1.0:
                    fkey = jax.random.fold_in(
                        jax.random.key(c.feature_fraction_seed), it * K + k
                    )
                    feat_mask = jax.random.permutation(fkey, F) < n_feat
                else:
                    feat_mask = jnp.ones(F, dtype=bool)
                arrays, row_leaf = self._grow_maybe_quantized(
                    gk, hk, mask, feat_mask, valid_mask, it, k,
                    bins=data["bins"], tables=data["tables"],
                )
                grew.append(arrays.num_nodes > 0)
                # `actf` folds the activity mask in: post-stop / masked-
                # tail rounds store zeroed leaf values (ok=0), so every
                # score update and rollback subtraction below is an
                # exact 0.0 and the carried state stays frozen
                ok = (arrays.num_nodes > 0).astype(jnp.float32) * actf
                if renew_alpha is not None:
                    # percentile leaf refit on device (RenewTreeOutput,
                    # gbdt.cpp:418 — before shrinkage, in-bag rows only)
                    arrays = self._apply_renewal(
                        arrays, row_leaf, score[k], mask, renew_alpha,
                        data["renew_w"],
                        label=data["obj_arrs"]["label"],
                    )
                lv = arrays.leaf_value * (shrink * ok)
                one = jnp.float32(1.0)
                score = score.at[k].set(
                    add_score(score[k], row_leaf, lv, one)
                )
                new_vs = []
                for vi in range(n_valid_sets):
                    vleaf = traverse(
                        arrays, data["vbins"][vi],
                        data["vtables"][vi]["nan_bin"],
                        data["vtables"][vi].get("bundle"),
                    )
                    new_vs.append(
                        vscores[vi].at[k].set(
                            add_score(vscores[vi][k], vleaf, lv, one)
                        )
                    )
                vscores = tuple(new_vs)
                # stored tree carries the boost-from-average bias on
                # the first iteration only (AddBias, gbdt.cpp:424);
                # the score got it at fused_start
                lv_stored = lv + init_vec[k] * ok * (it == 0)
                trees.append(arrays._replace(leaf_value=lv_stored))
            # metric evaluation entirely on device
            eval_scores = ([score] if track_train_eval else []) + list(vscores)
            rows = [f(s) for f, s in zip(evals, eval_scores)]
            eval_row = (
                # `rows` is a host list: truthiness = len, not a tracer
                jnp.concatenate(rows) if rows else jnp.zeros(0, jnp.float32)  # lint: allow[tracer-branch]
            )
            # gradient/hessian norm summaries ride the eval row's tail
            # (two scalars; fused_collect slices them off) so the
            # flight recorder gets per-round gh norms from the fused
            # loop with zero extra readbacks (docs/OBSERVABILITY.md).
            # Gated on the recorder config so the DEFAULT step keeps
            # its exact trace — persistent compile-cache entries and
            # the step memo stay valid for non-recorded runs.
            if want_gh:
                gh_row = jnp.stack([
                    jnp.sqrt(jnp.sum(grad * grad)),
                    jnp.sqrt(jnp.sum(hess * hess)),
                ])
                eval_row = jnp.concatenate([eval_row, gh_row])
            # the reference's stop condition (no class-tree could split,
            # gbdt.cpp:429-452) carried as a sticky device mask: once an
            # ACTIVE round grows K stumps, every later round in this and
            # any subsequent chunk is a no-op. `it` advances only on
            # active rounds so the fold_in(seed, it*K+k) RNG streams of
            # masked tail rounds are never consumed — the next chunk
            # replays them bit-exactly as live rounds.
            all_stump = jnp.logical_not(
                jnp.any(jnp.stack(grew))
            )
            new_state = {
                "score": score,
                "vscores": vscores,
                "it": it + active.astype(jnp.int32),
                "shrink": shrink,
                "init": init_vec,
                "stopped": jnp.logical_or(
                    stopped, jnp.logical_and(active, all_stump)
                ),
            }
            return new_state, tuple(trees), eval_row

        self._f_data = {
            "bins": self.dev["bins"],
            "vbins": [vd["bins"] for vd in vdevs],
            "tables": {k: self.dev[k] for k in
                       ("nan_bin", "num_bins", "mono", "is_cat")},
            "vtables": [
                {"nan_bin": vd["nan_bin"], "bundle": vd.get("bundle")}
                for vd in vdevs
            ],
            "valid": self.dev["valid"],
            "obj_arrs": {
                a: (jnp.float32(v) if isinstance(v, float) else v)
                for a in _OBJ_FOLD_ATTRS
                for v in [getattr(objective, a, None)]
                if v is not None
            },
            "renew_w": renew_w,
            "eval_arrs": eval_arrs,
            # absolute round limit for the current dispatch; overwritten
            # by fused_dispatch before every launch. Rides `data` (not
            # the carry) so a ladder-rung scan of ANY requested length
            # reuses one executable — the masked tail handles the rest.
            "it_end": jnp.int32(0),
        }
        if self.dev.get("bundle") is not None:
            self._f_data["tables"]["bundle"] = self.dev["bundle"]

        # ---- process-level step memo: reuse the traced+compiled step
        # across Boosters (cv folds, repeated trains) when nothing
        # STATIC differs. The key covers the full resolved config, the
        # grower spec, objective/strategy classes, and the (state, data)
        # pytree structure with shapes+dtypes.
        key = None
        if memo_ok:
            data_fp = jax.tree.map(
                lambda a: (getattr(a, "shape", None),
                           str(getattr(a, "dtype", type(a)))),
                self._f_data,
            )
            key = (
                type(self).__name__, K, track_train, self.spec,
                type(objective).__name__, type(strategy).__name__,
                str(sorted((k2, str(v)) for k2, v in c._values.items())),
                str(eval_specs), str(data_fp), n_valid_sets,
            )
            cached = _FUSED_STEP_CACHE.get(key)
            if cached is not None:
                _FUSED_STEP_CACHE.move_to_end(key)  # LRU touch
                self._f_program = cached
                self._f_step = cached.step
                return
        # donate the loop state on accelerators (scores are the big
        # per-iteration buffers); NOT on CPU — XLA:CPU donation has
        # produced heap corruption under this runtime (malloc-internal
        # segfaults mid-suite, always under a fused_dispatch frame —
        # the documented VERDICT r5 item 5 fragility), and CPU runs are
        # tests/CI where the extra score copy is noise
        donate = () if jax.default_backend() == "cpu" else (0,)
        self._f_program = _FusedProgram(step, donate)
        self._f_step = self._f_program.step
        if key is not None:
            _FUSED_STEP_CACHE[key] = self._f_program
            while len(_FUSED_STEP_CACHE) > _FUSED_STEP_CACHE_MAX:
                _FUSED_STEP_CACHE.popitem(last=False)

    def fused_start(self, track_train: bool) -> None:
        """Initialize the device loop state; performs BoostFromAverage."""
        import jax.numpy as jnp

        K = self.num_class
        init_scores = [0.0] * K
        if (
            not self._models
            and not self._pending
            and self.config.boost_from_average
            and not self.has_init_score
        ):
            for k in range(K):
                init = self.objective.boost_from_score(k)
                if abs(init) > 1e-15:
                    init_scores[k] = init
                    self.train.score = self.train.score.at[k].add(init)
                    for vs in self.valids:
                        vs.score = vs.score.at[k].add(init)
                    log.info(f"Start training from score {init:f}")
        self._init_scores = init_scores
        self._build_fused(track_train)
        self._fstate = {
            "score": self.train.score,
            "vscores": tuple(vs.score for vs in self.valids),
            "it": jnp.int32(self.iter_),
            "shrink": jnp.float32(self.shrinkage_rate),
            "init": jnp.asarray(np.asarray(init_scores, np.float32)),
            "stopped": jnp.asarray(False),
        }
        # entries are (device rows, n_active): a per-round (E,) row with
        # n_active=None, or a chunk's (C, E) stack whose first n_active
        # rows are live — fused_collect slices on the host
        self._f_evals: List[Tuple[Any, Optional[int]]] = []
        self._last_dispatch_rounds = []

    def fused_dispatch(self, n: int) -> None:
        """Dispatch n fused iterations without any host synchronization.

        Default (``tpu_chunk_scan=auto``): n is greedily decomposed over
        the ``config.DEFAULT_CHUNK_LADDER`` rungs, largest-first, and
        each rung launches ONE jitted ``lax.scan`` of the per-round step
        — one executable launch and one host pytree unpack per CHUNK
        instead of per round, the all-device inner loop of ROADMAP item
        2. A remainder shorter than the smallest rung still dispatches
        that rung: rounds at or past ``it_end`` are algebraic no-ops on
        device (zeroed leaf values, frozen scores/``it``) and their
        stacked outputs are sliced off at materialize, so truncation is
        exact and no chunk size ever retraces. ``tpu_chunk_scan=off``
        keeps the historical one-dispatch-per-round loop as the
        bit-parity baseline.

        The ``FUSED_ROUND_PHASE`` span covers one DISPATCH (a whole
        chunk by default) and only its async host cost — device time
        lands in "fused collect"; per-dispatch round counts land in
        ``_last_dispatch_rounds`` so the flight recorder can apportion
        the span across rounds.
        """
        import time as _time

        import jax.numpy as jnp

        if n <= 0:
            return
        K = self.num_class
        t0 = _time.perf_counter()
        self._last_dispatch_rounds = []
        self._f_data["it_end"] = jnp.int32(self.iter_ + n)
        if getattr(self.config, "tpu_chunk_scan", "auto") == "off":
            for _ in range(n):
                with _gt.scope(FUSED_ROUND_PHASE):
                    self._fstate, trees, eval_row = self._f_step(
                        self._fstate, self._f_data
                    )
                self.fused_dispatch_count += 1
                self._last_dispatch_rounds.append(1)
                for k, arrays in enumerate(trees):
                    self.device_trees.append((arrays, None))
                    self._pending.append(arrays)
                    self._pending_meta.append(
                        (k, self._init_scores[k] if self.iter_ == 0 else 0.0,
                         self.shrinkage_rate)
                    )
                self._f_evals.append((eval_row, None))
                self.iter_ += 1
        else:
            from .config import DEFAULT_CHUNK_LADDER

            left = n
            while left > 0:
                length = _pick_chunk(left, DEFAULT_CHUNK_LADDER)
                n_act = min(length, left)
                chunk_fn = self._f_program.chunk(length)
                with _gt.scope(FUSED_ROUND_PHASE):
                    self._fstate, trees, eval_mat = chunk_fn(
                        self._fstate, self._f_data
                    )
                self.fused_dispatch_count += 1
                self._last_dispatch_rounds.append(n_act)
                self._pending.append(_PendingChunk(trees, length, n_act))
                for _r in range(n_act):
                    for k in range(K):
                        self.device_trees.append(_PENDING_SLOT)
                        self._pending_meta.append(
                            (k,
                             self._init_scores[k] if self.iter_ == 0 else 0.0,
                             self.shrinkage_rate)
                        )
                    self.iter_ += 1
                self._f_evals.append((eval_mat, n_act))
                left -= n_act
        self._dispatch_host_s += _time.perf_counter() - t0
        self._record_collective_wire(n * K)
        # keep canonical score handles current (no sync; handle reassign)
        self.train.score = self._fstate["score"]
        for vs, s in zip(self.valids, self._fstate["vscores"]):
            vs.score = s

    def fused_collect(self) -> List[List[Tuple[str, str, float, bool]]]:
        """One chunk boundary: fetch eval rows + materialize trees.
        Returns per-iteration evaluation tuple lists (possibly truncated
        when the no-splittable-leaf stop condition fired mid-chunk)."""
        import jax

        n_iter_before = len(self._models) // self.num_class
        evals = self._f_evals
        self._f_evals = []
        rows: List[np.ndarray] = []
        if evals:
            # ONE batched readback over per-round (E,) rows and chunked
            # (C, E) stacks alike; chunk stacks are host-sliced to their
            # live rounds (the masked tail never produced real evals)
            fetched = jax.device_get([e for e, _na in evals])
            for got, (_e, n_act) in zip(fetched, evals):
                got = np.asarray(got)
                if got.ndim == 1:
                    rows.append(got)
                else:
                    rows.extend(got[:n_act])
        mat = (
            np.stack(rows) if rows else np.zeros((0, 0), np.float32)
        )
        self._materialize()
        n_iter_after = len(self._models) // self.num_class
        produced = n_iter_after - n_iter_before
        records: List[List[Tuple[str, str, float, bool]]] = []
        gh_rows: List[Tuple[float, float]] = []
        for r in range(min(produced, mat.shape[0])):
            row = mat[r]
            out: List[Tuple[str, str, float, bool]] = []
            j = 0
            for name, des in self._f_eval_sets:
                for mname, hb in zip(des.names, des.higher_better):
                    out.append((name, mname, float(row[j]), hb))
                    j += 1
            records.append(out)
            # the step appends [gnorm, hnorm] after the metric columns
            # (see _build_fused) — slice them off for the recorder
            if row.shape[0] >= j + 2:
                gh_rows.append((float(row[j]), float(row[j + 1])))
        self._last_gh_rows = gh_rows
        return records

    def fused_truncate(self, n_iters: int) -> None:
        """Drop models beyond n_iters iterations (early stop fired before
        the chunk boundary; matches reference stop-at-callback timing).
        Rolls the dropped trees' contributions back out of the train and
        valid scores so booster state stays consistent with the stored
        model (same contract as rollback_one_iter)."""
        K = self.num_class
        self._materialize()
        for mi in range(n_iters * K, len(self.device_trees)):
            arrays, _ = self.device_trees[mi]
            k = mi % K
            if self._models[mi].num_leaves > 1:
                leaf = self._traverse(arrays, self.dev["bins"], self.dev["nan_bin"], self.dev.get("bundle"))
                self.train.score = self.train.score.at[k].add(
                    -arrays.leaf_value[leaf]
                )
                for vs in self.valids:
                    vdev = vs.dataset.device_arrays()
                    vleaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"], vdev.get("bundle"))
                    vs.score = vs.score.at[k].add(-arrays.leaf_value[vleaf])
        del self._models[n_iters * K:]
        del self.device_trees[n_iters * K:]
        self.iter_ = min(self.iter_, n_iters)

    # ------------------------------------------------------------------
    def _sample_features(self, it=None, k: int = 0):
        """Per-tree feature_fraction mask (ColSampler, col_sampler.hpp:20).
        Keyed on (feature_fraction_seed, iter*K + k) so the sync and fused
        paths draw identical masks for the same iteration."""
        import jax
        import jax.numpy as jnp

        F = self.train_set.num_used_features
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return jnp.ones(F, dtype=bool)
        n = max(1, int(np.ceil(frac * F)))
        if it is None:
            it = self.iter_
        fkey = jax.random.fold_in(
            jax.random.key(self.config.feature_fraction_seed),
            it * self.num_class + k,
        )
        return jax.random.permutation(fkey, F) < n

    def _check_split(self, arrays, row_leaf, hk, mask) -> None:
        """USE_DEBUG split validation (serial_tree_learner.h:174
        CheckSplit / cuda_single_gpu_tree_learner.hpp:72
        CheckSplitValid): recompute per-leaf counts and hessian sums
        from the PARTITION (row->leaf) and assert they match the
        histogram-derived tree arrays — catches kernel/partition drift
        at the iteration it happens. Sync path only
        (tpu_debug_check_split=true)."""
        from .parallel.multihost import host_global_array

        L = self.spec.num_leaves
        rl = host_global_array(row_leaf)
        m = host_global_array(mask)
        h = host_global_array(hk)
        n_nodes = int(arrays.num_nodes)
        if n_nodes <= 0:
            return
        ok = (rl >= 0) & (m > 0)
        cnt = np.bincount(rl[ok], minlength=L).astype(np.float64)
        hw = (h * m).astype(np.float64)  # the grower sums hess * mask
        hsum = np.bincount(rl[ok], weights=hw[ok], minlength=L)
        if self.config.use_quantized_grad:
            # quantized growth sums DISCRETIZED hessians; only the
            # partition counts are comparable against raw hk
            hsum = None
        t_cnt = np.asarray(arrays.leaf_count, np.float64)
        t_h = np.asarray(arrays.leaf_weight, np.float64)
        nl = n_nodes + 1
        if not np.allclose(cnt[:nl], t_cnt[:nl], atol=0.5):
            bad = int(np.argmax(np.abs(cnt[:nl] - t_cnt[:nl])))
            log.fatal(
                f"CheckSplit: leaf {bad} partition count {cnt[bad]} != "
                f"histogram-derived count {t_cnt[bad]} "
                f"(iteration {self.iter_})"
            )
        if hsum is not None and not np.allclose(
            hsum[:nl], t_h[:nl], rtol=1e-3, atol=1e-3
        ):
            bad = int(np.argmax(np.abs(hsum[:nl] - t_h[:nl])))
            log.fatal(
                f"CheckSplit: leaf {bad} partition hessian sum "
                f"{hsum[bad]} != histogram-derived {t_h[bad]} "
                f"(iteration {self.iter_})"
            )

    def _renew_tree_output(
        self, arrays: TreeArrays, row_leaf, k: int, mask, resid=None
    ) -> TreeArrays:
        """Percentile leaf refit for l1/huber/quantile/mape
        (RegressionL1loss::RenewTreeOutput). RF passes its own residuals
        (label - init score, rf.hpp residual_getter)."""
        import jax
        import jax.numpy as jnp

        ds = self.train_set
        if jax.process_count() > 1:
            # global-row view: fetch the sharded arrays whole and build
            # label/weight in the same process-concatenated PADDED
            # layout (padding rows carry mask 0, so `bag` drops them)
            from .parallel.multihost import gather_host_rows, host_global_array

            rl = host_global_array(row_leaf)
            bag = host_global_array(mask) > 0
            label = gather_host_rows(
                ds.padded(ds.metadata.label).astype(np.float64)
            )
            if resid is None:
                score = host_global_array(
                    self.train.score[k]
                ).astype(np.float64)
                resid = label - score
            if ds.metadata.weight is not None:
                w = gather_host_rows(
                    ds.padded(ds.metadata.weight).astype(np.float64)
                )
            else:
                w = np.ones(len(label))
            if hasattr(self.objective, "_label_weight"):  # mape
                w = host_global_array(
                    self.objective._label_weight
                ).astype(np.float64)
        else:
            n = ds.num_data
            rl = np.asarray(row_leaf)[:n]
            bag = np.asarray(mask)[:n] > 0
            label = np.asarray(ds.metadata.label, dtype=np.float64)
            if resid is None:
                score = np.asarray(self.train.score[k])[:n].astype(np.float64)
                resid = label - score
            w = (
                np.asarray(ds.metadata.weight, dtype=np.float64)
                if ds.metadata.weight is not None
                else np.ones(n)
            )
            if hasattr(self.objective, "_label_weight"):  # mape
                w = np.asarray(self.objective._label_weight)[:n].astype(np.float64)
        alpha = self.objective.renew_percentile()
        lv = np.asarray(arrays.leaf_value).copy()
        n_leaves = int(arrays.num_nodes) + 1
        for leaf in range(n_leaves):
            sel = (rl == leaf) & bag
            if not np.any(sel):
                continue
            r, ww = resid[sel], w[sel]
            order = np.argsort(r)
            cw = np.cumsum(ww[order])
            t = alpha * cw[-1]
            idx = min(int(np.searchsorted(cw, t)), len(r) - 1)
            lv[leaf] = r[order][idx]
        return arrays._replace(leaf_value=jnp.asarray(lv))

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:462)."""
        if self.iter_ <= 0:
            return
        K = self.num_class
        for k in reversed(range(K)):
            tree = self.models.pop()
            arrays, _ = self.device_trees.pop()
            if tree.num_leaves > 1:
                leaf = self._traverse(arrays, self.dev["bins"], self.dev["nan_bin"], self.dev.get("bundle"))
                self.train.score = self.train.score.at[k].add(-arrays.leaf_value[leaf])
                for vs in self.valids:
                    vdev = vs.dataset.device_arrays()
                    vleaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"], vdev.get("bundle"))
                    vs.score = vs.score.at[k].add(-arrays.leaf_value[vleaf])
            else:
                # stump: its constant (boost-from-score bias) was added to
                # the scores directly — remove it too
                bias = float(tree.leaf_value[0])
                if abs(bias) > 1e-15:
                    self.train.score = self.train.score.at[k].add(-bias)
                    for vs in self.valids:
                        vs.score = vs.score.at[k].add(-bias)
        self.iter_ -= 1

    # ------------------------------------------------------------------
    def eval_set(self, ss: _ScoreSet) -> List[Tuple[str, str, float, bool]]:
        n = ss.dataset.num_data
        score = np.asarray(ss.score)[:, :n].astype(np.float64)
        s = score if self.num_class > 1 else score[0]
        out = []
        for m in ss.metrics:
            for name, val, hb in m.eval(s):
                out.append((ss.name, name, val, hb))
        return out

    def eval_train(self):
        return self.eval_set(self.train)

    def eval_valid(self):
        out = []
        for vs in self.valids:
            out.extend(self.eval_set(vs))
        return out

    def get_score(self, ss: _ScoreSet) -> np.ndarray:
        n = ss.dataset.num_data
        return np.asarray(ss.score)[:, :n].astype(np.float64)

    # ------------------------------------------------------------------
    def num_trees(self) -> int:
        return len(self.models)

    def current_iteration(self) -> int:
        return self.iter_

    def _single_row_predictor(self, start: int, end: int):
        """Packed low-latency predictor (c_api.cpp:66
        SingleRowPredictorInner): all trees' node arrays stacked into
        (T, M) matrices ONCE, so a single row walks every tree in
        lockstep with ~max_depth vectorized steps instead of T Python
        dispatches. Numeric splits only; categorical / linear models
        return None (batch path). Cached per (start, end, model count)."""
        key = (start, end, len(self.models))
        cached = getattr(self, "_srp_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from .tree import _CAT_MASK

        K = self.num_class
        models = [self.models[it * K + k]
                  for it in range(start, end) for k in range(K)]
        if not models or any(
            t.is_linear or (np.asarray(t.decision_type) & _CAT_MASK).any()
            for t in models
        ):
            self._srp_cache = (key, None)
            return None
        T = len(models)
        M = max(max(t.num_leaves - 1, 1) for t in models)
        L = max(t.num_leaves for t in models)
        feat = np.zeros((T, M), np.int64)
        thr = np.zeros((T, M), np.float64)
        mt = np.zeros((T, M), np.int8)  # missing type
        dl = np.zeros((T, M), bool)  # default left
        lc = np.zeros((T, M), np.int64)
        rc = np.zeros((T, M), np.int64)
        lv = np.zeros((T, L), np.float64)
        cls = np.zeros(T, np.int64)
        cur0 = np.zeros(T, np.int64)
        for t, m in enumerate(models):
            n = max(m.num_leaves - 1, 0)
            if n == 0:
                cur0[t] = -1  # stump: straight to leaf 0
            else:
                feat[t, :n] = m.split_feature[:n]
                thr[t, :n] = m.threshold[:n]
                dt = np.asarray(m.decision_type[:n], np.int64)
                mt[t, :n] = (dt >> 2) & 3
                dl[t, :n] = (dt & 2) != 0
                lc[t, :n] = m.left_child[:n]
                rc[t, :n] = m.right_child[:n]
            lv[t, : m.num_leaves] = m.leaf_value[: m.num_leaves]
            cls[t] = t % K
        srp = dict(feat=feat, thr=thr, mt=mt, dl=dl, lc=lc, rc=rc, lv=lv,
                   cls=cls, cur0=cur0, T=T, K=K)
        self._srp_cache = (key, srp)
        return srp

    def _predict_one_packed(self, srp, x: np.ndarray) -> np.ndarray:
        """One row through the packed predictor -> (K,) raw margins."""
        tidx = np.arange(srp["T"])
        cur = srp["cur0"].copy()
        active = cur >= 0
        while active.any():
            nodes = np.where(active, cur, 0)
            f = srp["feat"][tidx, nodes]
            v = x[f]
            m = srp["mt"][tidx, nodes]
            isna = np.isnan(v)
            miss = np.where(m == 2, isna,
                            (m == 1) & (isna | (np.abs(v) <= 1e-35)))
            v = np.where(isna & (m != 2), 0.0, v)
            gl = np.where(miss, srp["dl"][tidx, nodes],
                          v <= srp["thr"][tidx, nodes])
            nxt = np.where(gl, srp["lc"][tidx, nodes], srp["rc"][tidx, nodes])
            cur = np.where(active, nxt, cur)
            active = cur >= 0
        vals = srp["lv"][tidx, ~cur]
        out = np.zeros(srp["K"])
        np.add.at(out, srp["cls"], vals)
        return out

    def predict_raw(
        self,
        X: np.ndarray,
        start_iteration: int = 0,
        num_iteration: int = -1,
        early_stop: Optional[Tuple[int, float]] = None,
    ) -> np.ndarray:
        """Raw margin prediction over host trees (gbdt_prediction.cpp).

        early_stop = (freq, margin_threshold) enables the reference's
        per-row prediction early stop (prediction_early_stop.cpp): every
        freq iterations, rows whose margin — 2|p| for binary/regression,
        top1-top2 for multiclass — exceeds the threshold stop
        accumulating further trees (vectorized over rows here)."""
        X = np.asarray(X, dtype=np.float64)
        K = self.num_class
        n_iters = len(self.models) // K
        end = n_iters if num_iteration <= 0 else min(n_iters, start_iteration + num_iteration)
        out = np.zeros((K, X.shape[0]))
        if early_stop is None and X.shape[0] <= 4:
            # latency path: a handful of rows costs less through the
            # packed lockstep walk than through T per-tree dispatches
            srp = self._single_row_predictor(start_iteration, end)
            if srp is not None:
                for r in range(X.shape[0]):
                    out[:, r] = self._predict_one_packed(srp, X[r])
                if self.average_output and end > start_iteration:
                    out /= end - start_iteration
                return out
        if early_stop is None:
            # batch fast path: the native threaded walker does ~50M
            # row-trees/s vs ~1.4M for the numpy level walk; linear-leaf
            # trees keep the host path (per-leaf ridge outputs)
            if (X.shape[0] > 256
                    and not any(t.is_linear for t in self.models)):
                from . import native

                pm = self._packed_model()
                if pm is not None:
                    X = np.ascontiguousarray(X)  # once, not per class
                    ok = True
                    for k in range(K):
                        idx = np.arange(start_iteration, end) * K + k
                        res = native.predict_packed(
                            pm, X, idx.astype(np.int32)
                        )
                        if res is None:
                            ok = False
                            break
                        out[k] = res
                    if ok:
                        if self.average_output and end > start_iteration:
                            out /= end - start_iteration
                        return out
                    out[:] = 0.0  # partial fill must not double-count
            for it in range(start_iteration, end):
                for k in range(K):
                    out[k] += self.models[it * K + k].predict(X)
        else:
            freq, margin_thr = early_stop
            active = np.ones(X.shape[0], bool)
            Xa = X  # resliced only when rows deactivate
            for it in range(start_iteration, end):
                for k in range(K):
                    out[k][active] += self.models[it * K + k].predict(Xa)
                if (it - start_iteration + 1) % max(freq, 1) == 0:
                    if K >= 2:
                        part = np.partition(out[:, active], K - 2, axis=0)
                        margin = part[K - 1] - part[K - 2]
                    else:
                        margin = 2.0 * np.abs(out[0][active])
                    keep = margin <= margin_thr
                    idx = np.flatnonzero(active)
                    active[idx[~keep]] = False
                    if not active.any():
                        break
                    Xa = X[active]
        if self.average_output and end > start_iteration:
            out /= end - start_iteration
        return out

    def _packed_model(self):
        """Flat native-predictor arrays; rebuilt per call (packing is
        ~ms against the walk it accelerates, and models mutate in place
        through refit/set_leaf_output/rollback so caching would need
        invalidation hooks at every mutation site)."""
        try:
            from . import native

            if native.get_lib() is None:
                return None
            return native.PackedModel(self.models)
        except Exception:  # noqa: BLE001 — fall back to the host walk
            return None

    def predict(self, X, start_iteration=0, num_iteration=-1, raw_score=False,
                early_stop=None):
        raw = self.predict_raw(X, start_iteration, num_iteration,
                               early_stop=early_stop)
        if not raw_score and self.objective is not None:
            raw = self.objective.convert_output(raw)
        if self.num_class == 1:
            return raw[0]
        return raw.T  # (N, K)

    def predict_leaf_index(self, X, start_iteration=0, num_iteration=-1):
        X = np.asarray(X, dtype=np.float64)
        K = self.num_class
        n_iters = len(self.models) // K
        end = n_iters if num_iteration <= 0 else min(n_iters, start_iteration + num_iteration)
        cols = []
        for it in range(start_iteration, end):
            for k in range(K):
                cols.append(self.models[it * K + k].predict_leaf(X))
        return np.stack(cols, axis=1) if cols else np.zeros((X.shape[0], 0), np.int64)

    def predict_contrib(self, X, start_iteration=0, num_iteration=-1):
        """SHAP feature contributions (tree.h:140 PredictContrib)."""
        from .shap import predict_contrib

        X = np.asarray(X, dtype=np.float64)
        nf = self.train_set.num_total_features if self.train_set else len(
            getattr(self, "feature_names", []) or []
        )
        if nf == 0:
            nf = max((int(np.max(t.split_feature)) for t in self.models
                      if len(t.split_feature)), default=-1) + 1
            nf = max(nf, X.shape[1])
        return predict_contrib(
            self.models, X, nf, self.num_class, start_iteration,
            num_iteration, self.average_output,
        )

    def refit(self, X: np.ndarray, label: np.ndarray, weight=None, group=None) -> None:
        """Refit leaf values of the existing tree structures on new data
        (gbdt.cpp:266 RefitTree + tree_learner FitByExistingTree): walk
        each model tree over the new rows, recompute leaf outputs from
        the objective's gradients at the progressively-updated score, and
        blend with refit_decay_rate."""
        import jax.numpy as jnp

        X = np.asarray(X, dtype=np.float64)
        label = np.asarray(label, dtype=np.float32)
        N = X.shape[0]
        K = self.num_class
        c = self.config
        decay = c.refit_decay_rate
        lam = c.lambda_l2

        # leaf assignment of every (row, model tree) on the new data
        leaf_pred = self.predict_leaf_index(X)  # (N, num_models)

        # a minimal dataset shim so a fresh objective can init on the new
        # data (no padding needed: gradients run in plain numpy here)
        from .dataset import Metadata
        from .objectives import create_objective

        md = Metadata(
            label=label,
            weight=None if weight is None else np.asarray(weight, np.float32),
            group=None if group is None else np.asarray(group, np.int32),
        )

        class _Shim:
            metadata = md
            num_data = N

            @staticmethod
            def padded(arr, fill: float = 0.0, dtype=np.float32):
                return np.asarray(arr, dtype)

        obj = create_objective(c)
        if obj is None:
            log.fatal("Cannot refit without an objective function")
        obj.init(_Shim())

        score = np.zeros((K, N), np.float64)
        for it in range(len(self.models) // K):
            gs, hs = _obj_grads(obj, jnp.asarray(
                score if K > 1 else score[0], jnp.float32), it)
            gs = np.asarray(gs, np.float64).reshape(K, N)
            hs = np.asarray(hs, np.float64).reshape(K, N)
            for k in range(K):
                mi = it * K + k
                t = self.models[mi]
                g, h = gs[k], hs[k]
                leaves = leaf_pred[:, mi]
                sum_g = np.bincount(leaves, weights=g, minlength=t.num_leaves)
                sum_h = np.bincount(leaves, weights=h, minlength=t.num_leaves)
                shrink = t.shrinkage
                # full CalculateSplittedLeafOutput: L1 soft-threshold +
                # max_delta_step clip (feature_histogram.hpp, mirrored by
                # learner/split.py leaf_output)
                tg = np.sign(sum_g) * np.maximum(np.abs(sum_g) - c.lambda_l1, 0.0)
                new_out = np.where(
                    sum_h + lam > 1e-15, -tg / (sum_h + lam), 0.0
                )
                if c.max_delta_step > 0.0:
                    new_out = np.clip(new_out, -c.max_delta_step, c.max_delta_step)
                new_out = new_out * shrink
                # cover stats (leaf_count/internal_count) stay as trained,
                # like the reference's FitByExistingTree
                t.leaf_value = decay * t.leaf_value + (1.0 - decay) * new_out
                score[k] += t.leaf_value[leaves]
        # keep device copies consistent (device leaf_value mirrors the
        # final host leaf_value, see train_one_iter)
        for mi, (arrays, aux) in enumerate(self.device_trees):
            if mi < len(self.models):
                lv = arrays.leaf_value
                host = np.zeros(lv.shape, np.float32)
                n = min(len(host), len(self.models[mi].leaf_value))
                host[:n] = self.models[mi].leaf_value[:n]
                self.device_trees[mi] = (
                    arrays._replace(leaf_value=jnp.asarray(host)), aux
                )

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        nf = self.train_set.num_total_features if self.train_set else (
            max((int(np.max(t.split_feature)) for t in self.models if len(t.split_feature)), default=-1) + 1
        )
        imp = np.zeros(nf)
        for t in self.models:
            if importance_type == "gain":
                imp += t.feature_importance_gain(nf)
            else:
                imp += t.feature_importance_split(nf)
        return imp


# ======================================================================
class DART(GBDT):
    """DART: Dropouts meet Multiple Additive Regression Trees
    (reference src/boosting/dart.hpp:23).

    Before each iteration a random subset of past iterations is dropped:
    their score contributions are removed so gradients see the reduced
    ensemble, the new tree is trained with shrinkage lr/(1+k), and the
    dropped trees are permanently renormalized by k/(k+1) (xgboost mode:
    lr/(lr+k) and k/(lr+k)) — dart.hpp DroppingTrees/Normalize.
    """

    def __init__(self, config: Config, train_set: Optional[BinnedDataset]):
        super().__init__(config, train_set)
        self._force_sync = True  # dropout mutates past trees every iter
        self._force_sync_reason = "DART dropout mutates past trees every iteration"
        self._tree_weight: List[float] = []  # per-iteration weights
        self._sum_weight = 0.0
        self._pending_drops: Optional[List[int]] = None

    def _tree_score_delta(self, ss: _ScoreSet, arrays: TreeArrays, k: int, scale: float):
        """score[k] += scale * tree(arrays) over dataset ss."""
        import jax.numpy as jnp

        dev = ss.dataset.device_arrays()
        leaf = self._traverse(arrays, dev["bins"], dev["nan_bin"], dev.get("bundle"))
        ss.score = ss.score.at[k].set(
            add_score(ss.score[k], leaf, arrays.leaf_value, jnp.float32(scale))
        )

    def _select_drops(self) -> List[int]:
        c = self.config
        # drop decisions are a pure function of (drop_seed, iter_), not
        # of a sequential stream: a crash-resumed process has consumed
        # zero draws, so stream position can never survive a restart —
        # per-iteration keying is what makes DART resume deterministic
        # (mirrors the fold_in(seed, iter) keying of bagging RNG)
        rng = np.random.RandomState(
            (int(c.drop_seed) * 2654435761 + self.iter_) % (2 ** 32)
        )
        if rng.rand() < c.skip_drop or self.iter_ == 0:
            return []
        drops: List[int] = []
        if not c.uniform_drop:
            inv_avg = len(self._tree_weight) / max(self._sum_weight, 1e-300)
            rate = c.drop_rate
            if c.max_drop > 0:
                rate = min(rate, c.max_drop * inv_avg / max(self._sum_weight, 1e-300))
            for i in range(self.iter_):
                if rng.rand() < rate * self._tree_weight[i] * inv_avg:
                    drops.append(i)
                    if len(drops) >= c.max_drop > 0:
                        break
        else:
            rate = c.drop_rate
            if c.max_drop > 0:
                rate = min(rate, c.max_drop / max(1, self.iter_))
            for i in range(self.iter_):
                if rng.rand() < rate:
                    drops.append(i)
                    if len(drops) >= c.max_drop > 0:
                        break
        return drops

    def before_gradients(self) -> None:
        """Apply the per-iteration dropout to the train score (the
        reference does this lazily in GetTrainingScore, dart.hpp:80-86,
        so custom-objective gradients also see the dropped ensemble).
        Idempotent within one iteration."""
        if self._pending_drops is not None:
            return
        c = self.config
        K = self.num_class
        drops = self._select_drops()
        k_drop = float(len(drops))

        # drop: remove contributions from the TRAIN score only (valid
        # scores are corrected during normalize, dart.hpp Normalize)
        for i in drops:
            for k in range(K):
                arrays, _ = self.device_trees[i * K + k]
                if int(arrays.num_nodes) > 0:
                    self._tree_score_delta(self.train, arrays, k, -1.0)

        if not c.xgboost_dart_mode:
            self.shrinkage_rate = c.learning_rate / (1.0 + k_drop)
        else:
            self.shrinkage_rate = (
                c.learning_rate if not drops
                else c.learning_rate / (c.learning_rate + k_drop)
            )
        self._pending_drops = drops

    def train_one_iter(self, grad=None, hess=None) -> bool:
        c = self.config
        K = self.num_class
        self.before_gradients()
        drops = self._pending_drops or []
        self._pending_drops = None
        k_drop = float(len(drops))

        ret = super().train_one_iter(grad, hess)
        if ret:
            # aborted: restore the dropped trees so the train score again
            # matches the stored ensemble
            for i in drops:
                for k in range(K):
                    arrays, _ = self.device_trees[i * K + k]
                    if int(arrays.num_nodes) > 0:
                        self._tree_score_delta(self.train, arrays, k, 1.0)
            return ret

        # normalize dropped trees: permanent weight factor + score fixes
        if drops:
            if not c.xgboost_dart_mode:
                factor = k_drop / (k_drop + 1.0)  # new_weight = w * factor
                valid_delta = -1.0 / (k_drop + 1.0)  # valid: w -> w*factor
            else:
                factor = k_drop / (k_drop + c.learning_rate)
                valid_delta = -c.learning_rate / (k_drop + c.learning_rate)
            for i in drops:
                for k in range(K):
                    arrays, aux = self.device_trees[i * K + k]
                    if int(arrays.num_nodes) == 0:
                        continue
                    for vs in self.valids:
                        self._tree_score_delta(vs, arrays, k, valid_delta)
                    # train score currently lacks the tree entirely
                    self._tree_score_delta(self.train, arrays, k, factor)
                    new_arrays = arrays._replace(leaf_value=arrays.leaf_value * factor)
                    self.device_trees[i * K + k] = (new_arrays, aux)
                    self.models[i * K + k].leaf_value = (
                        self.models[i * K + k].leaf_value * factor
                    )
                    self.models[i * K + k].shrinkage *= factor
                if not c.uniform_drop:
                    if not c.xgboost_dart_mode:
                        self._sum_weight -= self._tree_weight[i] / (k_drop + 1.0)
                    else:
                        self._sum_weight -= self._tree_weight[i] / (k_drop + c.learning_rate)
                    self._tree_weight[i] *= factor
        if not c.uniform_drop:
            self._tree_weight.append(self.shrinkage_rate)
            self._sum_weight += self.shrinkage_rate
        return False


# ======================================================================
class RF(GBDT):
    """Random-forest mode (reference src/boosting/rf.hpp:25): no
    shrinkage, gradients computed once from the constant init score,
    prediction is the average over trees (average_output)."""

    def __init__(self, config: Config, train_set: Optional[BinnedDataset]):
        c = config
        if train_set is not None:
            if c.data_sample_strategy == "bagging":
                bag_ok = c.bagging_freq > 0 and 0.0 < c.bagging_fraction < 1.0
                feat_ok = 0.0 < c.feature_fraction < 1.0
                if not (bag_ok or feat_ok):
                    log.fatal(
                        "RF mode requires bagging (bagging_freq>0, bagging_fraction in (0,1)) "
                        "or feature_fraction in (0,1)"
                    )
        super().__init__(config, train_set)
        self._force_sync = True  # per-iter running-average score updates
        self._force_sync_reason = "random forest averages scores per iteration"
        self.average_output = True
        self.shrinkage_rate = 1.0
        if train_set is None:
            return
        if self.objective is None:
            log.fatal("RF mode does not support custom objective functions")
        # boosting one time: constant init score -> fixed gradients (rf.hpp Boosting)
        import jax.numpy as jnp

        K = self.num_class
        npad = train_set.num_rows_padded()
        self._rf_init_scores = [
            (self.objective.boost_from_score(k) if c.boost_from_average else 0.0)
            for k in range(K)
        ]
        const = jnp.asarray(
            np.repeat(np.asarray(self._rf_init_scores, np.float32)[:, None], npad, axis=1)
        )
        score = const if K > 1 else const[0]
        g, h = _obj_grads(self.objective, score, 0)
        self._rf_grad = jnp.reshape(g, (K, -1)).astype(jnp.float32)
        self._rf_hess = jnp.reshape(h, (K, -1)).astype(jnp.float32)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        import jax.numpy as jnp

        if grad is not None or hess is not None:
            log.fatal("RF mode does not support custom objective functions")
        K = self.num_class
        ds = self.train_set
        m = float(self.iter_)  # trees already averaged into the score
        for k in range(K):
            gk, hk = self._rf_grad[k], self._rf_hess[k]
            mask, gk, hk = self.strategy.sample(
                self.iter_, gk, hk, self.dev["valid"], self._label_dev
            )
            feat_mask = self._sample_features(k=k)
            arrays, row_leaf = self._grow_maybe_quantized(
                gk, hk, mask, feat_mask, self.dev["valid"], self.iter_, k
            )
            n_nodes = int(arrays.num_nodes)
            if n_nodes > 0 and self._cegb_info is not None:
                import jax.numpy as jnp

                nf = np.asarray(arrays.node_feature[:n_nodes])
                self._cegb_info = self._cegb_info._replace(
                    used=self._cegb_info.used.at[jnp.asarray(nf)].set(True)
                )
            init_k = self._rf_init_scores[k]
            if n_nodes > 0:
                if self.objective is not None and self.objective.is_renew_tree_output:
                    label = np.asarray(ds.metadata.label, dtype=np.float64)
                    arrays = self._renew_tree_output(
                        arrays, row_leaf, k, mask, resid=label - init_k
                    )
                tree = Tree.from_arrays(arrays, ds, 1.0)
                # AddBias: each tree is a standalone predictor incl. init
                tree.leaf_value = tree.leaf_value + init_k
                arrays = arrays._replace(leaf_value=arrays.leaf_value + init_k)
            else:
                tree = Tree(num_leaves=1, shrinkage=1.0)
                tree.leaf_value = np.array([init_k], np.float64)
                arrays = arrays._replace(
                    leaf_value=arrays.leaf_value.at[0].set(init_k)
                )
            # running average: score = (score*m + tree)/(m+1)  (rf.hpp
            # MultiplyScore/UpdateScore/MultiplyScore sequence)
            sc = self.train.score[k] * m
            sc = add_score(sc, row_leaf, arrays.leaf_value, jnp.float32(1.0))
            self.train.score = self.train.score.at[k].set(sc / (m + 1.0))
            for vs in self.valids:
                vdev = vs.dataset.device_arrays()
                leaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"], vdev.get("bundle"))
                vsc = vs.score[k] * m
                vsc = add_score(vsc, leaf, arrays.leaf_value, jnp.float32(1.0))
                vs.score = vs.score.at[k].set(vsc / (m + 1.0))
            self.models.append(tree)
            self.device_trees.append((arrays, None))
        self.iter_ += 1
        return False

    def rollback_one_iter(self) -> None:
        if self.iter_ <= 0:
            return
        K = self.num_class
        m = float(self.iter_)
        for k in reversed(range(K)):
            self.models.pop()
            arrays, _ = self.device_trees.pop()
            leaf = self._traverse(arrays, self.dev["bins"], self.dev["nan_bin"], self.dev.get("bundle"))
            sc = self.train.score[k] * m - arrays.leaf_value[leaf]
            self.train.score = self.train.score.at[k].set(sc / (m - 1.0) if m > 1 else sc * 0)
            for vs in self.valids:
                vdev = vs.dataset.device_arrays()
                vleaf = self._traverse(arrays, vdev["bins"], vdev["nan_bin"], vdev.get("bundle"))
                vsc = vs.score[k] * m - arrays.leaf_value[vleaf]
                vs.score = vs.score.at[k].set(vsc / (m - 1.0) if m > 1 else vsc * 0)
        self.iter_ -= 1


def splice_continued(base: GBDT, delta: GBDT) -> GBDT:
    """Graft a continuation's trees onto the model it warm-started from.

    The online loop's init_score handoff (docs/RESILIENCE.md): the
    candidate v(n+1) is trained as a FRESH booster over the microbatch
    with ``init_score`` = v(n)'s raw margins, so the delta trees encode
    only the residual on top of v(n). Raw scores add, therefore
    ``base.models + delta.models`` scores exactly v(n+1) — no
    ``_continue_from`` replay of every historical tree per cycle
    (that is O(total trees); this splice is O(new trees)). Mutates and
    returns ``base``.
    """
    if base.num_class != delta.num_class:
        raise ValueError(
            f"cannot splice: num_tree_per_iteration mismatch "
            f"({base.num_class} vs {delta.num_class})"
        )
    if base.average_output or delta.average_output:
        raise ValueError(
            "cannot splice averaged (rf) models: predictions divide by "
            "iteration count, so tree lists do not compose by append"
        )
    combined = list(base.models) + list(delta.models)
    if len(combined) % base.num_class:
        raise ValueError(
            f"cannot splice: {len(combined)} trees is not a whole number "
            f"of {base.num_class}-tree iterations"
        )
    base.models = combined  # setter also clears any pending device trees
    base.iter_ = len(combined) // base.num_class
    return base


# ---------------------------------------------------------------------------
# analysis-suite tracing hooks (analysis/jaxpr_audit `fused_chunk_scan`)

_TRACE_CHUNK_GBDT: Optional["GBDT"] = None
_TRACE_CHUNK_JAXPRS: Dict[int, Any] = {}


def _trace_chunk_gbdt() -> "GBDT":
    """Tiny synthetic regression booster shared by the chunk-scan trace
    entries (one per C). Pinned to the rounds grower so the audited
    program is the TPU-default scan body, and kept minuscule — the
    entry's eqn/cost budgets gate structure, not scale."""
    global _TRACE_CHUNK_GBDT
    if _TRACE_CHUNK_GBDT is None:
        from .basic import Booster, Dataset

        rs = np.random.RandomState(0)
        x = rs.randn(256, 8).astype(np.float64)
        y = x @ rs.randn(8) + 0.1 * rs.randn(256)
        ds = Dataset(x, label=y, free_raw_data=False,
                     params={"min_data_in_leaf": 4, "max_bin": 15})
        bst = Booster(
            params={
                "objective": "regression", "num_leaves": 7,
                "min_data_in_leaf": 4, "max_bin": 15,
                "tpu_growth_mode": "rounds", "verbosity": -1,
            },
            train_set=ds,
        )
        g = bst._gbdt
        g.fused_start(track_train=False)
        _TRACE_CHUNK_GBDT = g
    return _TRACE_CHUNK_GBDT


def trace_fused_chunk(length: int = 4):
    """ClosedJaxpr of one C-round chunk-scan dispatch (the fused mega-
    entry). The scan body is traced ONCE regardless of C — length is a
    jaxpr param — so the analysis C-invariance audit can assert equal
    eqn counts across two lengths to catch accidental unrolling, and
    the committed eqn/flops/bytes budgets must not scale with C."""
    got = _TRACE_CHUNK_JAXPRS.get(length)
    if got is None:
        import jax

        g = _trace_chunk_gbdt()
        chunk = g._f_program.chunk_body(length)
        got = jax.make_jaxpr(chunk)(g._fstate, g._f_data)
        _TRACE_CHUNK_JAXPRS[length] = got
    return got


def create_boosting(config: Config, train_set: Optional[BinnedDataset]) -> GBDT:
    """Boosting factory (reference src/boosting/boosting.cpp:40)."""
    b = config.boosting
    if b == "gbdt":
        return GBDT(config, train_set)
    if b == "dart":
        return DART(config, train_set)
    if b == "rf":
        return RF(config, train_set)
    log.fatal(f"Unknown boosting type {b}")
