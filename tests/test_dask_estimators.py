"""Dask-surface estimators (reference dask.py DaskLGBMClassifier etc.).

dask itself is not installed in this image; the estimators materialize
any object exposing .compute() (verified with a stand-in) and train on
the device mesh — see lightgbm_tpu/dask.py module docstring for the
design mapping."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


class _FakeCollection:
    """Stand-in for dask.array: wraps a numpy array behind .compute()."""

    def __init__(self, arr):
        self._arr = arr
        self.computed = 0

    def compute(self):
        self.computed += 1
        return self._arr


@pytest.fixture(scope="module")
def xy():
    rs = np.random.RandomState(3)
    X = rs.randn(300, 5)
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    return X, y


def test_classifier_materializes(xy):
    X, y = xy
    dx, dy = _FakeCollection(X), _FakeCollection(y)
    clf = lgb.DaskLGBMClassifier(n_estimators=5, num_leaves=7, verbosity=-1)
    clf.fit(dx, dy)
    assert dx.computed == 1 and dy.computed == 1
    pred = clf.predict(_FakeCollection(X[:20]))
    assert pred.shape == (20,)
    proba = clf.predict_proba(X[:20])
    assert proba.shape == (20, 2)
    acc = (clf.predict(X) == y).mean()
    assert acc > 0.9


def test_regressor_and_plain_numpy(xy):
    X, y = xy
    reg = lgb.DaskLGBMRegressor(n_estimators=5, num_leaves=7, verbosity=-1)
    reg.fit(X, y)  # plain numpy passes through
    assert np.isfinite(reg.predict(X[:10])).all()


def test_ranker_with_group(xy):
    X, y = xy
    yi = (y * 3).astype(int)
    rk = lgb.DaskLGBMRanker(n_estimators=4, num_leaves=7, verbosity=-1)
    rk.fit(_FakeCollection(X), _FakeCollection(yi), group=[100, 100, 100])
    assert np.isfinite(rk.predict(X[:10])).all()


def test_client_property(xy):
    clf = lgb.DaskLGBMClassifier(n_estimators=2, verbosity=-1)
    with pytest.raises(AttributeError):
        _ = clf.client_
    sentinel = object()
    clf2 = lgb.DaskLGBMClassifier(n_estimators=2, client=sentinel,
                                  verbosity=-1)
    assert clf2.client_ is sentinel


def test_eval_set_materialized(xy):
    X, y = xy
    clf = lgb.DaskLGBMClassifier(n_estimators=4, num_leaves=7, verbosity=-1)
    ew = _FakeCollection(np.ones(64))
    clf.fit(
        _FakeCollection(X), _FakeCollection(y),
        eval_set=[(_FakeCollection(X[:64]), _FakeCollection(y[:64]))],
        eval_sample_weight=[ew],
    )
    assert clf.evals_result_
    assert ew.computed == 1  # per-eval-set list entries materialize too
