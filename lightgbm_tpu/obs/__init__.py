"""Unified observability layer (docs/OBSERVABILITY.md).

Three pieces, all host-side (never inside jit — the no-callback jaxpr
contract in analysis/jaxpr_audit.py is re-audited over the
instrumented entries):

- ``metrics`` — a thread-safe **metrics registry** (counters / gauges /
  histograms with labels) with Prometheus text exposition, served from
  the serving HTTP transport's ``/metrics`` route;
- ``tracing`` — **span tracing** layered on ``timer.Timer`` +
  ``jax.named_scope``, exportable as Chrome trace-event JSON
  (Perfetto) and a JSONL event log, name-aligned with ``jax.profiler``
  traces captured via the ``profile_dir`` CLI param;
- ``manifest`` — per-run **manifest JSON**: config, device topology,
  compile counts (retrace guard), phase timings, metrics snapshot, and
  runtime collective wire bytes vs the static ``cost_budget.json``
  pins;
- ``recorder`` — the **flight recorder**: one JSONL record per
  boosting round (phases, learning curve, tree stats, throughput),
  enabled via the ``record_file=`` config/CLI param;
- ``anomaly`` — **sentinels** over the flight-record stream (NaN/Inf,
  loss spikes, throughput collapse, dead rounds) behind the
  ``anomaly_policy=off|warn|abort`` knob;
- ``aggregate`` — **fleet aggregation**: merges per-process registry
  snapshots and recorder streams host-side (files / ``/metrics``
  pulls, explicitly no jax collectives).
"""

from . import aggregate, anomaly, manifest, metrics, recorder, tracing
from .anomaly import AnomalyAbort, AnomalySentinel
from .recorder import FlightRecorder
from .manifest import build_manifest, write_manifest
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Sample,
    default_registry,
)

# NOTE: tracing's context manager is reached as `tracing.tracing(...)`
# — re-exporting the function here would shadow the submodule name.
from .tracing import TraceRecorder, span, start_tracing, stop_tracing

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Sample",
    "default_registry",
    "TraceRecorder",
    "span",
    "start_tracing",
    "stop_tracing",
    "metrics",
    "tracing",
    "manifest",
    "recorder",
    "anomaly",
    "aggregate",
    "AnomalyAbort",
    "AnomalySentinel",
    "FlightRecorder",
    "build_manifest",
    "write_manifest",
]
