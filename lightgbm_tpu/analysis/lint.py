"""Trace-safety AST linter: JAX hazards inside jitted/traced code paths.

Pure-stdlib (ast + re): the linter code itself never touches jax.
(Reaching it through `lightgbm_tpu.analysis` still imports the parent
package, which does import jax — load this file directly, e.g. via
importlib from its path, for a truly jax-free environment.)

The analysis has three layers:

1. **Traced-scope discovery.** A function is *traced* when it is
   jit-decorated (`@jax.jit`, `@partial(jax.jit, ...)`), passed to a
   tracing combinator (`jax.jit(f)`, `lax.while_loop`, `lax.scan`,
   `lax.cond`/`switch`, `jax.vmap`, `shard_map`, `pl.pallas_call`,
   `jax.grad`, ...), nested inside a traced function, or reachable
   from a traced function through the package call graph (a traced
   caller makes its callees traced — `boosting.step` reaches the
   whole learner). Cross-module edges resolve through `from .x import
   f` style imports and `self.method` calls.

2. **Device-value taint.** Within a traced function, parameters are
   tracers unless the jit decorator marks them static
   (`static_argnames`) or their annotation is a plainly-host type;
   results of `jnp.*`/`lax.*`/`jax.random.*` calls are device values;
   taint propagates through arithmetic, indexing, tuple packing and
   helper calls. `.shape`/`.ndim`/`.dtype`/`len()` and `is`/`is not`
   comparisons are static and STOP taint — `if x is None` or
   `if a.ndim == 1` never fires a rule.

3. **Rules** (table below) fire on hazardous uses of tainted values.
   Intentional sites carry a suppression comment on the flagged line
   (or the line above):  `# lint: allow[rule-id]` — or file-wide in
   the first 10 lines:   `# lint: allow-file[rule-id]`.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple


class Rule(NamedTuple):
    id: str
    summary: str


RULES: Dict[str, Rule] = {}


def _register(rule_id: str, summary: str) -> str:
    RULES[rule_id] = Rule(rule_id, summary)
    return rule_id


TRACER_CAST = _register(
    "tracer-cast",
    "float()/int()/bool() applied to a traced device value (forces a "
    "host sync / ConcretizationTypeError inside jit)",
)
NP_ON_TRACER = _register(
    "np-on-tracer",
    "numpy function applied to a traced device value (silently "
    "materializes the tracer or raises at trace time)",
)
TRACER_BRANCH = _register(
    "tracer-branch",
    "Python control flow (if/while/and/or/assert/ternary) on a traced "
    "device value — use lax.cond/jnp.where, or hoist the decision to "
    "trace time",
)
HOST_SYNC = _register(
    "host-sync",
    ".item()/.tolist()/block_until_ready()/device_get on a device "
    "value in traced or hot-loop code (a ~100 ms round-trip on the "
    "axon runtime, and permanent dispatch-latency damage)",
)
MUTABLE_DEFAULT = _register(
    "mutable-default",
    "mutable default argument — shared across calls, and a stale-state "
    "hazard when the function is traced more than once",
)
DEVICE_CLOSURE = _register(
    "device-closure",
    "jitted function closes over a device array — the value is baked "
    "into the compiled executable as a constant (stale across cache "
    "reuse, and bloats the serialized executable)",
)


class Finding(NamedTuple):
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool

    def format(self) -> str:
        sup = "  [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}{sup}"


# attribute reads that yield STATIC (host) values even on a tracer
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "sharding", "aval", "weak_type",
    "itemsize", "nbytes",
}
# method calls on a tracer that return device values (keep taint)
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# parameter names that are static by package convention (specs/configs
# carried through traced helpers without annotations)
_STATIC_PARAM_NAMES = {
    "self", "cls", "spec", "config", "cfg", "axis_name", "ax",
    "num_slots", "num_bins", "num_out", "min_cap", "n_ranks",
}
# annotations that mark a parameter as a host value
_HOST_ANNOTATIONS = {
    "int", "str", "bool", "float", "bytes", "GrowerSpec", "Config",
    "BinnedDataset", "Mesh", "tuple", "Tuple", "dict", "Dict", "list",
    "List", "Path", "Callable", "type",
}
# jax combinators whose function-valued arguments become traced scopes;
# value = indices of function-valued positional args ("*" = all)
_TRACING_COMBINATORS = {
    "jit": (0,), "vmap": (0,), "pmap": (0,), "grad": (0,),
    "value_and_grad": (0,), "checkpoint": (0,), "remat": (0,),
    "custom_jvp": (0,), "custom_vjp": (0,), "named_call": (0,),
    "while_loop": (0, 1), "fori_loop": (2,), "scan": (0,),
    "cond": (1, 2, 3), "switch": "*", "associative_scan": (0,),
    "shard_map": (0,), "pallas_call": (0,), "map": (0,),
}

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\[([a-zA-Z0-9_,\- ]+)\]")
_ALLOW_FILE_RE = re.compile(r"#\s*lint:\s*allow-file\[([a-zA-Z0-9_,\- ]+)\]")


def scan_allow_comments(src: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """`# lint: allow[rule]` / `# lint: allow-file[rule]` markers of a
    source text: ({line: rule ids}, file-wide rule ids). Shared by this
    linter and concurrency_lint.py so suppression syntax stays ONE
    thing."""
    allow_lines: Dict[int, Set[str]] = {}
    allow_file: Set[str] = set()
    for i, line in enumerate(src.splitlines(), start=1):
        m = _ALLOW_RE.search(line)
        if m:
            allow_lines[i] = {
                r.strip() for r in m.group(1).split(",") if r.strip()
            }
        if i <= 10:
            m = _ALLOW_FILE_RE.search(line)
            if m:
                allow_file |= {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
    return allow_lines, allow_file


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ModuleInfo:
    """Per-module symbol tables feeding the cross-module call graph."""

    def __init__(self, name: str, tree: ast.Module, src: str, path: str,
                 is_package: bool = False):
        self.name = name  # dotted module name inside the package
        # True for package __init__ modules: their dotted name has no
        # trailing module segment, so relative imports resolve one
        # level differently (from .x import f in pkg/__init__.py means
        # pkg.x, not pkg's parent .x)
        self.is_package = is_package
        self.tree = tree
        self.path = path
        self.lines = src.splitlines()
        # alias -> canonical root ("np", "jnp", "lax", "jax", "partial",
        # "shard_map", "pl", ...)
        self.aliases: Dict[str, str] = {}
        # imported function name -> (module, name) — cross-module edges
        self.imports: Dict[str, Tuple[str, str]] = {}
        # qualname -> ast.FunctionDef for every def in the module
        self.functions: Dict[str, ast.AST] = {}
        # class name -> {method name -> qualname}
        self.classes: Dict[str, Dict[str, str]] = {}
        # NamedTuple-ish classes holding jax.Array fields
        self.device_containers: Set[str] = set()
        self.allow_lines: Dict[int, Set[str]] = {}
        self.allow_file: Set[str] = set()
        self._scan_comments(src)
        self._scan_top(tree)

    def _scan_comments(self, src: str) -> None:
        self.allow_lines, self.allow_file = scan_allow_comments(src)

    def _scan_top(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    root = a.name.split(".")[0]
                    alias = a.asname or root
                    if a.name in ("jax.numpy",):
                        self.aliases[alias] = "jnp"
                    elif root == "numpy":
                        self.aliases[alias] = "np"
                    elif root == "jax":
                        self.aliases[alias] = "jax"
                    elif root == "functools":
                        self.aliases[alias] = "functools"
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    alias = a.asname or a.name
                    if mod == "jax" and a.name == "numpy":
                        self.aliases[alias] = "jnp"
                    elif mod == "jax" and a.name == "lax":
                        self.aliases[alias] = "lax"
                    elif mod == "jax" and a.name == "jit":
                        self.aliases[alias] = "jit"
                    elif mod == "functools" and a.name == "partial":
                        self.aliases[alias] = "partial"
                    elif mod.endswith("shard_map") and a.name == "shard_map":
                        self.aliases[alias] = "shard_map"
                    elif mod == "jax.experimental" and a.name == "pallas":
                        self.aliases[alias] = "pl"
                    elif a.name == "numpy":
                        self.aliases[alias] = "np"
                    elif node.level > 0 or mod.startswith("lightgbm_tpu"):
                        # package-relative import: record the edge target
                        self.imports[alias] = (self._resolve_rel(node), a.name)

    def _resolve_rel(self, node: ast.ImportFrom) -> str:
        """Absolute dotted module for a relative import. For a package
        __init__ the stripped '.__init__' segment counts as the level-1
        hop, so `from .x import f` stays inside the package."""
        mod = node.module or ""
        if node.level == 0:
            return mod
        parts = self.name.split(".")
        drop = node.level - (1 if self.is_package else 0)
        base = parts[: len(parts) - drop] if drop > 0 else parts
        return ".".join(base + ([mod] if mod else []))

    def root_of(self, node: ast.AST) -> Optional[str]:
        """Canonical root ('jnp', 'np', 'lax', 'jax', ...) of a dotted
        expression, through import aliases."""
        d = _dotted(node)
        if d is None:
            return None
        head = d.split(".")[0]
        canon = self.aliases.get(head)
        if canon == "jax" and d.startswith((f"{head}.numpy",)):
            return "jnp"
        return canon if canon is not None else None


class _FnInfo(NamedTuple):
    module: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    cls: Optional[str]
    static_params: Tuple[str, ...]  # from jit static_argnames/nums


def _is_namedtuple_class(node: ast.ClassDef) -> bool:
    for b in node.bases:
        d = _dotted(b) or ""
        if d.split(".")[-1] == "NamedTuple":
            return True
    return False


def _ann_mentions_array(ann: ast.AST) -> bool:
    return "Array" in ast.unparse(ann) if ann is not None else False


class _Linter:
    """Package-wide analysis over a set of parsed modules."""

    def __init__(self, modules: Dict[str, _ModuleInfo]):
        self.modules = modules
        self.findings: List[Finding] = []
        # (module, qualname) -> _FnInfo
        self.fns: Dict[Tuple[str, str], _FnInfo] = {}
        self.traced: Set[Tuple[str, str]] = set()
        self.device_containers: Set[str] = set()
        for mi in modules.values():
            self._collect_fns(mi)
        self.device_containers |= {
            c for mi in modules.values() for c in mi.device_containers
        }

    # ------------------------------------------------------------------
    # collection
    def _collect_fns(self, mi: _ModuleInfo) -> None:
        def visit(node: ast.AST, prefix: str, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    mi.functions[qn] = child
                    static = self._jit_static_params(mi, child)
                    self.fns[(mi.name, qn)] = _FnInfo(
                        mi.name, qn, child, cls, static
                    )
                    if cls is not None:
                        mi.classes.setdefault(cls, {})[child.name] = qn
                    visit(child, qn + ".", cls)
                elif isinstance(child, ast.ClassDef):
                    if _is_namedtuple_class(child):
                        has_arr = any(
                            isinstance(s, ast.AnnAssign)
                            and _ann_mentions_array(s.annotation)
                            for s in child.body
                        )
                        if has_arr:
                            mi.device_containers.add(child.name)
                    visit(child, child.name + ".", child.name)

        visit(mi.tree, "", None)

    def _jit_decorators(self, mi: _ModuleInfo, fn: ast.AST) -> List[ast.AST]:
        out = []
        for dec in getattr(fn, "decorator_list", []):
            if self._is_jit_expr(mi, dec):
                out.append(dec)
        return out

    def _is_jit_expr(self, mi: _ModuleInfo, node: ast.AST) -> bool:
        """node is jax.jit / jit / partial(jax.jit, ...) / jax.jit(...)"""
        d = _dotted(node)
        if d is not None:
            root = mi.aliases.get(d.split(".")[0])
            return (root == "jit") or (root == "jax" and d.endswith(".jit"))
        if isinstance(node, ast.Call):
            fd = _dotted(node.func)
            if fd is not None:
                root = mi.aliases.get(fd.split(".")[0])
                if root == "partial" or fd.endswith("partial"):
                    return bool(node.args) and self._is_jit_expr(
                        mi, node.args[0]
                    )
                return self._is_jit_expr(mi, node.func)
        return False

    def _jit_static_params(self, mi: _ModuleInfo, fn: ast.AST) -> Tuple[str, ...]:
        """static_argnames/static_argnums named by a jit decorator."""
        names: List[str] = []
        for dec in self._jit_decorators(mi, fn):
            if not isinstance(dec, ast.Call):
                continue
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Constant) and isinstance(c.value, str):
                            names.append(c.value)
                elif kw.arg == "static_argnums":
                    idxs = [
                        c.value for c in ast.walk(kw.value)
                        if isinstance(c, ast.Constant)
                        and isinstance(c.value, int)
                    ]
                    params = [a.arg for a in fn.args.args]
                    for i in idxs:
                        if 0 <= i < len(params):
                            names.append(params[i])
        return tuple(names)

    # ------------------------------------------------------------------
    # traced-scope discovery
    def discover_traced(self) -> None:
        roots: Set[Tuple[str, str]] = set()
        for (mod, qn), fi in self.fns.items():
            mi = self.modules[mod]
            if self._jit_decorators(mi, fi.node):
                roots.add((mod, qn))
        # functions passed to tracing combinators anywhere in each module
        for mi in self.modules.values():
            for call in ast.walk(mi.tree):
                if not isinstance(call, ast.Call):
                    continue
                tgt = self._combinator_slots(mi, call)
                if tgt is None:
                    continue
                slots = range(len(call.args)) if tgt == "*" else tgt
                for i in slots:
                    if i >= len(call.args):
                        continue
                    for ref in self._fn_refs(mi, call.args[i]):
                        roots.add(ref)
        # propagate caller -> callee and outer -> nested to fixpoint
        traced = set(roots)
        changed = True
        while changed:
            changed = False
            for key in list(traced):
                fi = self.fns.get(key)
                if fi is None:
                    continue
                for callee in self._callees(fi):
                    if callee in self.fns and callee not in traced:
                        traced.add(callee)
                        changed = True
                for (mod, qn) in self.fns:
                    if mod == key[0] and qn.startswith(key[1] + ".") \
                            and (mod, qn) not in traced:
                        traced.add((mod, qn))
                        changed = True
        self.traced = traced

    def _combinator_slots(self, mi: _ModuleInfo, call: ast.Call):
        d = _dotted(call.func)
        if d is None:
            return None
        head, leaf = d.split(".")[0], d.split(".")[-1]
        root = mi.aliases.get(head)
        if leaf in _TRACING_COMBINATORS and (
            root in ("jax", "lax", "jit", "shard_map", "pl")
            or head == leaf  # direct `from x import while_loop` style
        ):
            # plain builtins named `map` must not count
            if leaf == "map" and root != "lax":
                return None
            return _TRACING_COMBINATORS[leaf]
        return None

    def _fn_refs(self, mi: _ModuleInfo, node: ast.AST):
        """(module, qualname) candidates a function-valued expression
        refers to — names, lists of names, partial(name, ...)."""
        out: List[Tuple[str, str]] = []
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                if n.id in mi.functions:
                    out.append((mi.name, n.id))
                elif n.id in mi.imports:
                    out.append(mi.imports[n.id])
                else:
                    # nested defs: qualname suffix match in this module
                    for qn in mi.functions:
                        if qn.split(".")[-1] == n.id:
                            out.append((mi.name, qn))
            elif isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name):
                if n.value.id == "self":
                    for cls, meths in mi.classes.items():
                        if n.attr in meths:
                            out.append((mi.name, meths[n.attr]))
        return out

    def _callees(self, fi: _FnInfo):
        mi = self.modules[fi.module]
        out: Set[Tuple[str, str]] = set()
        for n in ast.walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Name):
                if f.id in mi.imports:
                    out.add(mi.imports[f.id])
                elif f.id in mi.functions:
                    out.add((mi.name, f.id))
                else:
                    for qn in mi.functions:  # nested / sibling defs
                        if qn.split(".")[-1] == f.id:
                            out.add((mi.name, qn))
            elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                if f.value.id == "self" and fi.cls is not None:
                    meths = mi.classes.get(fi.cls, {})
                    if f.attr in meths:
                        out.add((mi.name, meths[f.attr]))
                elif f.value.id in mi.imports:
                    # module-object import: from . import histogram
                    out.add((mi.imports[f.value.id][0] + "."
                             + mi.imports[f.value.id][1], f.attr))
        return out

    # ------------------------------------------------------------------
    # rules
    def run(self) -> List[Finding]:
        self.discover_traced()
        for mi in self.modules.values():
            module_env: Set[str] = set()
            self._scan_mutable_defaults(mi)
            # module-level device constants (rare; seed closure taint)
            for stmt in mi.tree.body:
                if isinstance(stmt, ast.Assign):
                    if self._expr_tainted(mi, stmt.value, module_env):
                        for t in stmt.targets:
                            module_env |= self._target_names(t)
            for (mod, qn), fi in sorted(self.fns.items()):
                if mod != mi.name:
                    continue
                # only analyze top-level-of-their-nesting functions here;
                # nested defs are analyzed inline with the parent env
                if "." in qn and self._parent_is_fn(mi, qn):
                    continue
                self._analyze_fn(mi, fi, dict.fromkeys(module_env, True))
        self.findings.sort(key=lambda f: (f.path, f.line, f.col))
        return self.findings

    def _parent_is_fn(self, mi: _ModuleInfo, qn: str) -> bool:
        parent = qn.rsplit(".", 1)[0]
        return parent in mi.functions

    def _scan_mutable_defaults(self, mi: _ModuleInfo) -> None:
        for qn, fn in mi.functions.items():
            for d in list(getattr(fn.args, "defaults", [])) + [
                k for k in getattr(fn.args, "kw_defaults", []) if k
            ]:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in ("list", "dict", "set")
                ):
                    self._emit(mi, MUTABLE_DEFAULT, d,
                               f"function {qn!r} has a mutable default")

    # ---- taint -------------------------------------------------------
    def _param_tainted(self, fi: _FnInfo, arg: ast.arg,
                       has_literal_default: bool) -> bool:
        if arg.arg in _STATIC_PARAM_NAMES or arg.arg in fi.static_params:
            return False
        ann = arg.annotation
        if ann is not None:
            txt = ast.unparse(ann)
            leaf = txt.split("[")[0].split(".")[-1]
            if _ann_mentions_array(ann) or leaf in self.device_containers \
                    or leaf in ("SplitParams", "SplitRecord", "TreeArrays"):
                return True
            # any other annotation (QueryLayout, BundleInfo, ...) is a
            # named host type: the package convention is that tracer
            # params are annotated `jax.Array` or a device container
            return False
        # unannotated: literal defaults are static flags by convention
        return not has_literal_default

    def _seed_params(self, fi: _FnInfo, env: Dict[str, bool]) -> None:
        a = fi.node.args
        pos = list(a.posonlyargs) + list(a.args)
        n_def = len(a.defaults)
        for i, arg in enumerate(pos):
            has_def = i >= len(pos) - n_def
            d = a.defaults[i - (len(pos) - n_def)] if has_def else None
            lit = isinstance(d, ast.Constant)
            env[arg.arg] = self._param_tainted(fi, arg, lit)
        for arg, d in zip(a.kwonlyargs, a.kw_defaults):
            env[arg.arg] = self._param_tainted(
                fi, arg, isinstance(d, ast.Constant)
            )
        if a.vararg is not None:
            env[a.vararg.arg] = True
        if a.kwarg is not None:
            env[a.kwarg.arg] = True

    def _target_names(self, t: ast.AST) -> Set[str]:
        """Names BOUND by an assignment target: `self.x = v` binds no
        name (it mutates self), `a, (b, *c) = v` binds a, b, c."""
        out: Set[str] = set()
        stack = [t]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name):
                out.add(n.id)
            elif isinstance(n, (ast.Tuple, ast.List)):
                stack.extend(n.elts)
            elif isinstance(n, ast.Starred):
                stack.append(n.value)
        return out

    def _expr_tainted(self, mi: _ModuleInfo, node: ast.AST,
                      env, record=None, traced: bool = False) -> bool:
        """Taint of an expression; `record` (a list) collects rule hits
        as (rule, node, message) while evaluating — only when inside a
        traced scope."""
        tainted = set(k for k, v in env.items() if v) \
            if isinstance(env, dict) else set(env)

        def is_t(n: ast.AST) -> bool:
            if n is None:
                return False
            if isinstance(n, ast.Name):
                return n.id in tainted
            if isinstance(n, ast.Attribute):
                if n.attr in _STATIC_ATTRS:
                    return False
                return is_t(n.value)
            if isinstance(n, ast.Subscript):
                return is_t(n.value) or is_t(n.slice)
            if isinstance(n, ast.Call):
                return self._call_tainted(mi, n, is_t, record, traced)
            if isinstance(n, ast.BinOp):
                return is_t(n.left) or is_t(n.right)
            if isinstance(n, ast.UnaryOp):
                if isinstance(n.op, ast.Not) and is_t(n.operand):
                    if record is not None and traced:
                        record.append((TRACER_BRANCH, n,
                                       "`not` on a device value calls "
                                       "__bool__ on a tracer"))
                return is_t(n.operand)
            if isinstance(n, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in n.ops):
                    return False  # identity checks are host-static
                return is_t(n.left) or any(is_t(c) for c in n.comparators)
            if isinstance(n, ast.BoolOp):
                hit = [v for v in n.values[:-1] if is_t(v)]
                if hit and record is not None and traced:
                    record.append((TRACER_BRANCH, hit[0],
                                   "and/or short-circuits on a device "
                                   "value (implicit __bool__); use & | "
                                   "or jnp.logical_*"))
                return any(is_t(v) for v in n.values)
            if isinstance(n, ast.IfExp):
                if is_t(n.test) and record is not None and traced:
                    record.append((TRACER_BRANCH, n.test,
                                   "ternary condition is a device value; "
                                   "use jnp.where / lax.cond"))
                return is_t(n.body) or is_t(n.orelse) or is_t(n.test)
            if isinstance(n, (ast.Tuple, ast.List, ast.Set)):
                return any(is_t(e) for e in n.elts)
            if isinstance(n, ast.Dict):
                return any(is_t(v) for v in n.values if v is not None)
            if isinstance(n, ast.Starred):
                return is_t(n.value)
            if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
                return any(is_t(g.iter) for g in n.generators) \
                    or is_t(n.elt)
            if isinstance(n, ast.DictComp):
                return any(is_t(g.iter) for g in n.generators) \
                    or is_t(n.key) or is_t(n.value)
            if isinstance(n, ast.NamedExpr):
                return is_t(n.value)
            return False

        return is_t(node)

    def _call_tainted(self, mi: _ModuleInfo, n: ast.Call, is_t,
                      record, traced: bool) -> bool:
        args_tainted = any(is_t(a) for a in n.args) or any(
            is_t(k.value) for k in n.keywords
        )
        fd = _dotted(n.func)
        root = mi.root_of(n.func) if fd else None
        leaf = fd.split(".")[-1] if fd else None
        # device producers
        if root in ("jnp", "lax"):
            return True
        if root == "jax" and fd is not None and (
            ".random." in fd or ".nn." in fd
            or leaf in ("device_put", "fold_in")
        ):
            return True
        if root == "jax" and leaf in ("device_get",):
            if traced and args_tainted and record is not None:
                record.append((HOST_SYNC, n,
                               "jax.device_get inside traced code"))
            return False
        # casts
        if isinstance(n.func, ast.Name) and n.func.id in ("float", "int",
                                                          "bool", "complex"):
            if args_tainted:
                if traced and record is not None:
                    record.append((TRACER_CAST, n,
                                   f"{n.func.id}() on a device value"))
                return False
            return False
        if isinstance(n.func, ast.Name) and n.func.id in (
            "len", "isinstance", "hasattr", "getattr", "range", "print",
            "repr", "str", "type", "id",
        ):
            return False
        # numpy on tracers
        if root == "np":
            if args_tainted:
                if traced and record is not None:
                    record.append((NP_ON_TRACER, n,
                                   f"{fd}(...) applied to a device value"))
                return False
            return False
        # method calls on device values
        if isinstance(n.func, ast.Attribute):
            meth = n.func.attr
            recv_t = is_t(n.func.value)
            if meth in _SYNC_METHODS and (recv_t or traced):
                if record is not None and (traced or recv_t):
                    record.append((HOST_SYNC, n,
                                   f".{meth}() forces a device->host sync"))
                return False
            if recv_t:
                return True  # .astype/.sum/.reshape/... keep taint
        # everything else: taint-through on arguments
        return args_tainted

    # ---- per-function analysis --------------------------------------
    def _analyze_fn(self, mi: _ModuleInfo, fi: _FnInfo,
                    outer_env: Dict[str, bool]) -> None:
        traced = (fi.module, fi.qualname) in self.traced
        env: Dict[str, bool] = dict(outer_env)
        if traced:
            self._seed_params(fi, env)
        else:
            for a in list(fi.node.args.args) + list(fi.node.args.kwonlyargs):
                env[a.arg] = False
        body = list(fi.node.body)
        # fixpoint over assignments (loops may use later-assigned names)
        for _ in range(4):
            before = dict(env)
            self._collect_assign_taint(mi, fi, body, env, traced)
            if env == before:
                break
        # now walk statements firing rules
        self._walk_stmts(mi, fi, body, env, traced)
        # immediate nested defs analyzed with this env (they recurse)
        for n in self._walk_scope(fi.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = self._find_qn(mi, n)
                if qn is None:
                    continue
                sub = self.fns[(mi.name, qn)]
                self._analyze_fn(mi, sub, env)
        self._check_device_closures(mi, fi, env)

    def _find_qn(self, mi: _ModuleInfo, node: ast.AST) -> Optional[str]:
        for qn, f in mi.functions.items():
            if f is node:
                return qn
        return None

    @staticmethod
    def _walk_scope(fn_node: ast.AST):
        """ast.walk that does NOT descend into nested function/class
        scopes (their assignments must not leak into this scope)."""
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _collect_assign_taint(self, mi, fi, body, env, traced) -> None:
        fn_node = fi.node
        for n in self._walk_scope(fn_node):
            if isinstance(n, ast.Assign):
                t = self._expr_tainted(mi, n.value, env, None, traced)
                for tgt in n.targets:
                    self._assign_target(mi, tgt, n.value, t, env, traced)
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                t = self._expr_tainted(mi, n.value, env, None, traced)
                for name in self._target_names(n.target):
                    env[name] = env.get(name, False) or t
            elif isinstance(n, ast.AugAssign):
                t = self._expr_tainted(mi, n.value, env, None, traced)
                for name in self._target_names(n.target):
                    env[name] = env.get(name, False) or t
            elif isinstance(n, ast.For):
                t = self._expr_tainted(mi, n.iter, env, None, traced)
                for name in self._target_names(n.target):
                    env[name] = env.get(name, False) or t
            elif isinstance(n, ast.NamedExpr):
                t = self._expr_tainted(mi, n.value, env, None, traced)
                for name in self._target_names(n.target):
                    env[name] = env.get(name, False) or t
            elif isinstance(n, ast.withitem) and n.optional_vars is not None:
                for name in self._target_names(n.optional_vars):
                    env.setdefault(name, False)

    def _assign_target(self, mi, tgt, value, tainted, env, traced) -> None:
        """Tuple-unpack aware: `G, N = x.shape` stays host-static."""
        if isinstance(tgt, ast.Tuple) and isinstance(value, ast.Attribute) \
                and value.attr in _STATIC_ATTRS:
            for name in self._target_names(tgt):
                env[name] = env.get(name, False)
            return
        for name in self._target_names(tgt):
            env[name] = env.get(name, False) or tainted

    def _walk_stmts(self, mi, fi, body, env, traced) -> None:
        fn_node = fi.node

        def fire(hits):
            for rule, node, msg in hits:
                self._emit(mi, rule, node, msg)

        for n in self._walk_scope(fn_node):
            if not traced:
                continue
            hits: List[tuple] = []
            if isinstance(n, (ast.If, ast.While)):
                if self._expr_tainted(mi, n.test, env, hits, traced):
                    hits.append((
                        TRACER_BRANCH, n.test,
                        "Python branch on a device value; use jnp.where/"
                        "lax.cond or hoist to trace time",
                    ))
            elif isinstance(n, ast.Assert):
                if self._expr_tainted(mi, n.test, env, hits, traced):
                    hits.append((TRACER_BRANCH, n.test,
                                 "assert on a device value"))
            elif isinstance(n, (ast.Assign, ast.AugAssign, ast.Return,
                                ast.Expr, ast.AnnAssign)):
                val = getattr(n, "value", None)
                if val is not None:
                    self._expr_tainted(mi, val, env, hits, traced)
            # dedupe by (rule, line, col)
            seen = set()
            uniq = []
            for h in hits:
                k = (h[0], h[1].lineno, h[1].col_offset)
                if k not in seen:
                    seen.add(k)
                    uniq.append(h)
            fire(uniq)

    def _check_device_closures(self, mi, fi, env) -> None:
        """jax.jit(f) / @jit defs capturing tainted outer names."""
        for n in ast.walk(fi.node):
            target = None
            site = None
            if isinstance(n, ast.Call) and self._is_jit_expr(mi, n) \
                    and isinstance(n, ast.Call) and n.args:
                refs = self._fn_refs(mi, n.args[0])
                if refs:
                    target = refs[0]
                    site = n
            if target is None:
                continue
            t_fi = self.fns.get(target)
            if t_fi is None or t_fi.node is fi.node:
                continue
            free = self._free_names(t_fi.node)
            captured = sorted(name for name in free if env.get(name, False))
            if captured:
                self._emit(
                    mi, DEVICE_CLOSURE, site,
                    f"jitted {target[1].split('.')[-1]!r} closes over "
                    f"device value(s) {', '.join(captured)} — baked into "
                    "the executable as constants; pass them as arguments",
                )

    def _free_names(self, fn: ast.AST) -> Set[str]:
        bound: Set[str] = {a.arg for a in fn.args.args}
        bound |= {a.arg for a in fn.args.kwonlyargs}
        if fn.args.vararg:
            bound.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            bound.add(fn.args.kwarg.arg)
        loads: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    bound.add(n.id)
                else:
                    loads.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fn:
                bound.add(n.name)
        import builtins

        return {x for x in loads - bound if not hasattr(builtins, x)}

    # ------------------------------------------------------------------
    def _emit(self, mi: _ModuleInfo, rule: str, node: ast.AST,
              message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        sup = rule in mi.allow_file or any(
            rule in mi.allow_lines.get(ln, ())
            for ln in (line, line - 1)
        )
        self.findings.append(
            Finding(rule, mi.path, line, col, message, sup)
        )


# ----------------------------------------------------------------------
# public API
def _module_name_for(path: Path, pkg_root: Path) -> str:
    rel = path.relative_to(pkg_root.parent).with_suffix("")
    return ".".join(rel.parts)


def lint_paths(paths: Sequence[Path], pkg_root: Path) -> List[Finding]:
    modules: Dict[str, _ModuleInfo] = {}
    for p in paths:
        src = p.read_text()
        tree = ast.parse(src, filename=str(p))
        name = _module_name_for(p, pkg_root)
        is_pkg = name.endswith(".__init__")
        if is_pkg:
            name = name[: -len(".__init__")]
        modules[name] = _ModuleInfo(name, tree, src, str(p),
                                    is_package=is_pkg)
    return _Linter(modules).run()


def iter_package_modules(pkg_root: Optional[str] = None,
                         exclude=("analysis",)) -> Tuple[List[Path], Path]:
    """(module files, package root) for a package-wide lint; `exclude`
    names subpackage or module stems skipped (the analyzers
    themselves, by default). With no pkg_root the INSTALLED
    lightgbm_tpu package is located — never a CWD-relative guess,
    which would lint nothing from another directory and report a
    vacuously clean result. Shared by this linter and
    concurrency_lint.py so the two --strict AST passes can never scan
    different file sets."""
    if pkg_root is None:
        import lightgbm_tpu

        root = Path(lightgbm_tpu.__file__).resolve().parent
    else:
        root = Path(pkg_root).resolve()
    files = [
        p for p in sorted(root.rglob("*.py"))
        if not any(part in exclude for part in
                   p.relative_to(root).parts)
    ]
    if not files:
        raise FileNotFoundError(
            f"no Python modules under {root} — wrong pkg_root? a clean "
            "lint over zero files would be meaningless"
        )
    return files, root


def lint_package(pkg_root: Optional[str] = None,
                 exclude=("analysis",)) -> List[Finding]:
    """Lint every module of the package (see iter_package_modules for
    root resolution and exclusion semantics)."""
    files, root = iter_package_modules(pkg_root, exclude)
    return lint_paths(files, root)


def lint_source(src: str, name: str = "fixture",
                module: str = "lightgbm_tpu._fixture") -> List[Finding]:
    """Lint a single in-memory module (test fixtures)."""
    tree = ast.parse(src, filename=name)
    mi = _ModuleInfo(module, tree, src, name)
    return _Linter({module: mi}).run()


def format_findings(findings: Sequence[Finding],
                    show_suppressed: bool = False,
                    label: str = "lint") -> str:
    lines = [
        f.format() for f in findings if show_suppressed or not f.suppressed
    ]
    active = sum(1 for f in findings if not f.suppressed)
    sup = len(findings) - active
    lines.append(
        f"{label}: {active} violation(s), {sup} suppressed"
    )
    return "\n".join(lines)
