#!/bin/bash
# Probe the TPU tunnel in a loop (solo client); the moment it answers,
# run the full bench and save the artifact. The axon tunnel wedges with
# ~10-minute init hangs (see BENCH_NOTES.md) — patience is the fix.
cd /root/repo || exit 1
for i in $(seq 1 60); do
  echo "[watch] probe attempt $i at $(date)"
  if timeout 600 python -c 'import jax,jax.numpy as jnp; x=jnp.ones((256,256),jnp.bfloat16); (x@x).block_until_ready(); print("probe OK:", jax.devices()[0].platform)'; then
    echo "[watch] tunnel live; running bench at $(date)"
    BENCH_BUDGET=${BENCH_BUDGET:-3000} BENCH_TREES=${BENCH_TREES:-100} \
      BENCH_PROBE_TIMEOUT=600 python bench.py \
      > /root/repo/bench_r4_tpu.json 2> /root/repo/bench_r4_tpu.log
    echo "[watch] bench rc=$?"
    cat /root/repo/bench_r4_tpu.json
    echo "[watch] microbench at $(date)"
    timeout 1200 python tools/tpu_microbench.py \
      > /root/repo/microbench_r4.json 2> /root/repo/microbench_r4.log
    echo "[watch] microbench rc=$?"
    exit 0
  fi
  sleep 30
done
echo "[watch] gave up after $i attempts"
