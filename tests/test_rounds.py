"""Round-batched growth mode (tpu_growth_rounds) and the multi-slot
histogram used by it (reference CUDA all-leaves batching,
cuda_histogram_constructor.cu)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import BinnedDataset
from lightgbm_tpu.learner import GrowerSpec, grow_tree, make_split_params


def _grow(ds, params, spec, seed=3):
    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    d = ds.device_arrays()
    N = ds.num_rows_padded()
    F = ds.num_used_features
    grad = jnp.asarray(rs.randn(N).astype(np.float32)) * d["valid"]
    hess = (jnp.ones(N, jnp.float32) * 0.25) * d["valid"]
    return grow_tree(
        d["bins"], d["nan_bin"], d["num_bins"], d["mono"], d["is_cat"],
        grad, hess, d["valid"], jnp.ones(F, bool), params, spec,
        valid=d["valid"],
    )


@pytest.fixture(scope="module")
def small_ds():
    rs = np.random.RandomState(11)
    X = rs.randn(4096, 8).astype(np.float32)
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5})
    return BinnedDataset.from_numpy(X, cfg)


def test_rounds_matches_greedy_unbound_budget(small_ds):
    """With a non-binding leaf budget, round-batched growth IS greedy:
    both split exactly the positive-gain leaves. Covers the legacy
    permuted rounds prelude AND the natural-order rounds grower
    (rounds.py)."""
    cfg = Config({"num_leaves": 512, "max_bin": 63, "min_data_in_leaf": 40,
                  "min_gain_to_split": 0.5})
    params = make_split_params(cfg)
    vals = {}
    variants = {
        "seq": dict(),
        "permuted_rounds": dict(rounds=True),
        "nat_rounds": dict(rounds_slots=25),
        "nat_rounds_small_k": dict(rounds_slots=4),
    }
    for name, kw in variants.items():
        spec = GrowerSpec(num_leaves=512, num_bins=small_ds.max_num_bin,
                          max_depth=-1, **kw)
        tree, row_leaf = _grow(small_ds, params, spec)
        rl = np.asarray(row_leaf)[: small_ds.num_data]
        vals[name] = np.asarray(tree.leaf_value)[rl]
    for name in variants:
        np.testing.assert_allclose(vals[name], vals["seq"], atol=1e-5,
                                   err_msg=name)


def test_nat_rounds_tree_consistency(small_ds):
    """Natural-order rounds with a BOUND budget: internally consistent
    tree, full budget used, positive gains."""
    cfg = Config({"num_leaves": 31, "max_bin": 63, "min_data_in_leaf": 5})
    params = make_split_params(cfg)
    spec = GrowerSpec(num_leaves=31, num_bins=small_ds.max_num_bin,
                      max_depth=-1, rounds_slots=25)
    tree, row_leaf = _grow(small_ds, params, spec)
    nn = int(tree.num_nodes)
    assert nn == 30
    rl = np.asarray(row_leaf)[: small_ds.num_data]
    lc = np.bincount(rl, minlength=31).astype(float)
    np.testing.assert_allclose(lc, np.asarray(tree.leaf_count))
    assert (np.asarray(tree.node_gain)[:nn] > 0).all()


def test_nat_rounds_max_depth(small_ds):
    cfg = Config({"num_leaves": 64, "max_bin": 63, "min_data_in_leaf": 5})
    params = make_split_params(cfg)
    spec = GrowerSpec(num_leaves=64, num_bins=small_ds.max_num_bin,
                      max_depth=3, rounds_slots=25)
    tree, _ = _grow(small_ds, params, spec)
    assert int(tree.num_nodes) <= 7
    assert int(np.max(np.asarray(tree.leaf_depth))) <= 3


def test_growth_mode_via_train_api():
    rs = np.random.RandomState(5)
    X = rs.randn(3000, 6)
    y = (X[:, 0] + X[:, 1] ** 2 + 0.3 * rs.randn(3000) > 1).astype(float)
    from sklearn.metrics import roc_auc_score

    preds = {}
    for mode in ("exact", "rounds"):
        params = dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
                      verbosity=-1, tpu_growth_mode=mode)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(params, ds, num_boost_round=5)
        preds[mode] = bst.predict(X)
        assert roc_auc_score(y, preds[mode]) > 0.85


def test_hist_nat_slots_matches_bruteforce():
    import jax.numpy as jnp

    from lightgbm_tpu.learner.histogram import build_gh8, hist_nat_slots

    rs = np.random.RandomState(0)
    N, F, B, S = 4096, 4, 31, 6
    bins = jnp.asarray(rs.randint(0, B, (F, N)).astype(np.int32))
    grad = rs.randn(N).astype(np.float32)
    hess = (rs.rand(N) + 0.5).astype(np.float32)
    gh8 = build_gh8(jnp.asarray(grad), jnp.asarray(hess),
                    jnp.ones(N, jnp.float32))
    slot = rs.randint(0, S + 1, N).astype(np.int32)  # S = trash slot
    out = np.asarray(hist_nat_slots(bins, gh8, jnp.asarray(slot), S, B))
    bn = np.asarray(bins)
    gh3 = np.stack([grad, hess, np.ones(N, np.float32)])
    for s in range(S):
        m = slot == s
        for f in range(F):
            for c in range(3):
                ref = np.bincount(bn[f][m], weights=gh3[c][m], minlength=B)[:B]
                np.testing.assert_allclose(out[s, c, f], ref, atol=2e-4,
                                           rtol=1e-4)


def test_rounds_tree_consistency(small_ds):
    """Bound budget: tree differs from greedy but must be internally
    consistent (partition counts == leaf counts, positive gains, full
    budget used)."""
    cfg = Config({"num_leaves": 31, "max_bin": 63, "min_data_in_leaf": 5})
    params = make_split_params(cfg)
    spec = GrowerSpec(num_leaves=31, num_bins=small_ds.max_num_bin,
                      max_depth=-1, rounds=True)
    tree, row_leaf = _grow(small_ds, params, spec)
    nn = int(tree.num_nodes)
    assert nn == 30
    rl = np.asarray(row_leaf)[: small_ds.num_data]
    lc = np.bincount(rl, minlength=31).astype(float)
    np.testing.assert_allclose(lc, np.asarray(tree.leaf_count))
    assert (np.asarray(tree.node_gain)[:nn] > 0).all()


def test_rounds_via_train_api():
    rs = np.random.RandomState(5)
    X = rs.randn(3000, 6)
    y = (X[:, 0] + X[:, 1] ** 2 + 0.3 * rs.randn(3000) > 1).astype(float)
    preds = {}
    for rounds in (False, True):
        params = dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
                      verbosity=-1, tpu_growth_rounds=rounds)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(params, ds, num_boost_round=5)
        preds[rounds] = bst.predict(X)
    # different growth order, but both must learn the signal
    from sklearn.metrics import roc_auc_score

    assert roc_auc_score(y, preds[True]) > 0.85
    assert roc_auc_score(y, preds[False]) > 0.85


def test_hist_slots_matches_masked():
    import jax.numpy as jnp

    from lightgbm_tpu.learner.histogram import build_gh8, hist_slots, histogram

    rs = np.random.RandomState(0)
    N, F, B, S = 4096, 4, 31, 5
    bins = jnp.asarray(rs.randint(0, B, (F, N)).astype(np.int32))
    gh8 = build_gh8(
        jnp.asarray(rs.randn(N).astype(np.float32)),
        jnp.asarray((rs.rand(N) + 0.5).astype(np.float32)),
        jnp.ones(N, jnp.float32),
    )
    begins = jnp.asarray(np.int32([0, 500, 1500, 2000, 0]))
    counts = jnp.asarray(np.int32([500, 1000, 300, 2000, 0]))
    out = hist_slots(bins, gh8, begins, counts, B, S)
    assert out.shape == (S, 3, F, B)
    for s in range(S):
        b, c = int(begins[s]), int(counts[s])
        if c == 0:
            np.testing.assert_allclose(np.asarray(out[s]), 0.0)
            continue
        ref = histogram(bins[:, b : b + c], gh8[:, b : b + c], B)
        np.testing.assert_allclose(
            np.asarray(out[s]), np.asarray(ref), atol=1e-4, rtol=1e-4
        )


def test_rounds_forced_splits_match_exact(tmp_path):
    """forcedsplits_filename on the rounds grower (ISSUE 14): the
    forced phase applies exactly one plan split per round (so
    Tree::Split leaf numbering matches the BFS plan), then best-gain
    growth resumes. With a non-binding leaf budget both growers are
    greedy past the forced prefix, so the full model must match the
    sequential exact oracle."""
    import json as _json

    rs = np.random.RandomState(7)
    X = rs.randn(4000, 6)
    y = (1.2 * X[:, 0] + X[:, 1] ** 2 + 0.3 * rs.randn(4000) > 0.8
         ).astype(float)
    p = tmp_path / "forced.json"
    p.write_text(_json.dumps({
        "feature": 0, "threshold": 0.0,
        "left": {"feature": 1, "threshold": 0.5},
    }))
    preds, models = {}, {}
    for mode in ("exact", "rounds"):
        params = dict(objective="binary", num_leaves=256,
                      min_data_in_leaf=40, min_gain_to_split=0.5,
                      verbosity=-1, tpu_growth_mode=mode,
                      forcedsplits_filename=str(p))
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(params, ds, num_boost_round=3)
        preds[mode] = bst.predict(X)
        models[mode] = bst._gbdt.models
    for t in models["rounds"]:
        assert int(t.split_feature[0]) == 0  # the forced root split
    np.testing.assert_allclose(preds["rounds"], preds["exact"],
                               rtol=1e-4, atol=1e-5)


def test_grower_capability_matrix_raises(small_ds):
    """The combinations that remain genuinely unsupported after the
    grower unification must still raise instead of silently training
    wrong (ISSUE 14 satellite): the sequential oracle rejects
    voting x forced, and the rounds grower rejects a forced spec with
    no plan and monotone intermediate/advanced combined with voting or
    forced splits."""
    cfg = Config({"num_leaves": 8, "max_bin": 63, "min_data_in_leaf": 5})
    params = make_split_params(cfg)
    B = small_ds.max_num_bin

    # sequential oracle: voting + forced splits
    spec = GrowerSpec(num_leaves=8, num_bins=B, max_depth=-1,
                      voting_k=2, n_forced=1)
    with pytest.raises(ValueError, match="sequential oracle"):
        _grow(small_ds, params, spec)

    # rounds grower: spec.n_forced without the forced= plan
    spec = GrowerSpec(num_leaves=8, num_bins=B, max_depth=-1,
                      rounds_slots=4, n_forced=1)
    with pytest.raises(ValueError, match="forced"):
        _grow(small_ds, params, spec)

    # rounds grower: monotone intermediate/advanced x voting / forced
    for combo in (dict(voting_k=2, axis_name=None),
                  dict(n_forced=1)):
        spec = GrowerSpec(num_leaves=8, num_bins=B, max_depth=-1,
                          rounds_slots=4, mono_mode=2, **combo)
        with pytest.raises(ValueError, match="monotone"):
            _grow(small_ds, params, spec)


def _extras_problem(n=3000, f=8, seed=11):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    w = rs.randn(f)
    y = X @ w + 0.5 * np.sin(2 * X[:, 0]) + 0.2 * rs.randn(n)
    return X, y


@pytest.mark.parametrize("extra", [
    {"extra_trees": True},
    {"feature_fraction_bynode": 0.6},
    {"cegb_penalty_split": 0.05, "cegb_tradeoff": 1.0},
])
def test_rounds_per_node_extras_quality(extra):
    """extra_trees / feature_fraction_bynode / CEGB on the rounds fast
    path (VERDICT r4 item 4 — these configs used to fall back to the
    ~30x-slower sequential grower). Quality must stay in family with
    the exact grower's."""
    import lightgbm_tpu as lgb

    X, y = _extras_problem()
    mse = {}
    for mode in ("exact", "rounds"):
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        bst = lgb.train(
            dict({"objective": "regression", "num_leaves": 31,
                  "verbosity": -1, "learning_rate": 0.15,
                  "min_data_in_leaf": 5, "tpu_growth_mode": mode}, **extra),
            ds, num_boost_round=15,
        )
        mse[mode] = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse["rounds"] <= mse["exact"] * 1.3, (extra, mse)
    assert mse["rounds"] < 0.5 * float(np.var(y)), (extra, mse)


def test_rounds_interaction_constraints_structural():
    """Interaction constraints on the rounds path: every root-to-leaf
    path's feature set must fit inside ONE declared group (ColSampler
    interaction filtering semantics)."""
    import lightgbm_tpu as lgb

    X, y = _extras_problem(f=6)
    groups = [[0, 1, 2], [3, 4, 5]]
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "interaction_constraints": "[0,1,2],[3,4,5]",
         "min_data_in_leaf": 5, "tpu_growth_mode": "rounds"},
        ds, num_boost_round=10,
    )
    model = bst.dump_model()

    def walk(node, path):
        if "split_feature" not in node:
            return
        p2 = path | {node["split_feature"]}
        assert any(p2 <= set(g) for g in groups), p2
        walk(node["left_child"], p2)
        walk(node["right_child"], p2)

    for t in model["tree_info"]:
        walk(t["tree_structure"], set())
