"""Leveled logging with a pluggable callback.

Mirrors the reference logger (include/LightGBM/utils/log.h:88): levels
Debug/Info/Warning/Fatal keyed off the `verbosity` (alias `verbose`)
config value, with a registerable redirection callback
(log.h:97, python-package basic.py register_logger).
"""

from __future__ import annotations

import logging
import sys
from typing import Any, Callable, Optional

_logger: Optional[Any] = None
_info_method = "info"
_warning_method = "warning"

# verbosity: <0 Fatal only, 0 Warning, 1 Info (default), >=2 Debug
_VERBOSITY = 1


class LightGBMError(Exception):
    """Error raised by lightgbm_tpu (reference: include/LightGBM/utils/log.h Fatal)."""


def register_logger(
    logger: Any, info_method_name: str = "info", warning_method_name: str = "warning"
) -> None:
    """Redirect framework log output to a custom logger object."""
    global _logger, _info_method, _warning_method
    if not callable(getattr(logger, info_method_name, None)):
        raise TypeError(f"logger has no callable method {info_method_name!r}")
    if not callable(getattr(logger, warning_method_name, None)):
        raise TypeError(f"logger has no callable method {warning_method_name!r}")
    _logger = logger
    _info_method = info_method_name
    _warning_method = warning_method_name


def set_verbosity(v: int) -> None:
    global _VERBOSITY
    _VERBOSITY = int(v)


def _emit(msg: str, warning: bool = False) -> None:
    if _logger is not None:
        getattr(_logger, _warning_method if warning else _info_method)(msg)
    else:
        print(msg, file=sys.stderr if warning else sys.stdout, flush=True)


def debug(msg: str) -> None:
    if _VERBOSITY >= 2:
        _emit(f"[LightGBM-TPU] [Debug] {msg}")


def info(msg: str) -> None:
    if _VERBOSITY >= 1:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def warning(msg: str) -> None:
    if _VERBOSITY >= 0:
        _emit(f"[LightGBM-TPU] [Warning] {msg}", warning=True)


def fatal(msg: str) -> None:
    raise LightGBMError(msg)
