"""Exclusive Feature Bundling (EFB) — host-side preprocessing.

Re-creates the behavior of the reference's bundling pass
(src/io/dataset.cpp:111 FindGroups, :250 FastFeatureBundling): sparse,
(nearly) mutually-exclusive features are merged into one bin column so
the per-column histogram cost drops from O(#features) to O(#bundles).

TPU formulation: the device bin matrix stays ONE dense feature-major
int matrix — bundling just shrinks its leading axis. Each bundle
column stores, per row, the offset-shifted bin of whichever member
feature is away from its most-frequent bin (0 = "every member at its
most-frequent bin"). Split finding still runs per ORIGINAL feature:
bundle histograms are expanded back to per-feature layout with a
gather, and each feature's most-frequent-bin slot is recovered from the
leaf totals minus the stored bins — exactly the reference's
FixHistogram trick (include/LightGBM/dataset.h:768), which exists for
the same reason (the most-frequent bin is not stored).

Grouping mirrors FindGroups' greedy pass: features ordered by
non-default count descending (dense first), each placed in the first
group where the conflict count stays within the global budget
(total_rows / 10000) and half the feature's own non-default count,
with a per-group merged-width cap so the uniform device bin axis does
not grow.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .binning import BinMapper, BinType

# reference dataset.cpp FindGroups constants
MAX_SEARCH_GROUP = 100


class BundleLayout(NamedTuple):
    """Host description of the feature -> bundle-column mapping.

    All per-feature arrays are indexed by USED-feature position (the
    grower's feature axis). Singleton columns store original bins
    directly (mfb == -1, off_lo == 0).
    """

    groups: List[List[int]]  # used-feature positions per bundle column
    bundle_of: np.ndarray  # (F,) int32 — device column of each feature
    off_lo: np.ndarray  # (F,) int32 — merged-range start within the column
    mfb: np.ndarray  # (F,) int32 — excluded most-freq bin; -1 = stored direct
    col_bins: int  # uniform device bin-axis size B' (max column width)

    @property
    def num_columns(self) -> int:
        return len(self.groups)

    def is_trivial(self) -> bool:
        """True when every group is a singleton (no merging happened)."""
        return all(len(g) == 1 for g in self.groups)


def find_groups(
    bins: np.ndarray,  # (F, N) full binned matrix (used features)
    num_bins: Sequence[int],
    most_freq: Sequence[int],
    is_cat: Sequence[bool],
    max_group_bins: int,
) -> List[List[int]]:
    """Greedy conflict-bounded grouping (reference FindGroups semantics).

    Categorical features never merge (their bin identity is a category;
    the sorted-subset scan assumes a dedicated column).
    """
    F, N = bins.shape
    budget = N // 10000  # single_val_max_conflict_cnt
    nd_masks = [bins[f] != most_freq[f] for f in range(F)]
    nd_cnt = np.array([int(m.sum()) for m in nd_masks])
    # dense first, like FastFeatureBundling's sort by non-zero count
    order = np.argsort(-nd_cnt, kind="stable")

    groups: List[List[int]] = []
    group_mask: List[np.ndarray] = []
    group_bins: List[int] = []
    group_conflict: List[int] = []
    group_has_cat: List[bool] = []
    for f in order:
        f = int(f)
        width = int(num_bins[f]) - 1  # mfb slot excluded once merged
        placed = False
        if not is_cat[f] and nd_cnt[f] < N:  # fully-dense features never merge
            # cap the candidate-group search like the reference
            # (max_search_group, dataset.cpp:117) — without it, wide
            # sparse data pays O(F x G x N) host preprocessing
            searched = 0
            for gid in range(len(groups)):
                if searched >= MAX_SEARCH_GROUP:
                    break
                # a group founded by a categorical feature stays a
                # dedicated column both ways: the categorical never
                # merges INTO a group, and no numeric feature merges
                # into ITS group (build_layout would offset-encode the
                # categorical column, breaking the bin==category
                # identity the sorted-subset scan relies on)
                if group_has_cat[gid]:
                    continue
                if group_bins[gid] + width > max_group_bins:
                    continue
                rest = budget - group_conflict[gid]
                if rest < 0:
                    continue
                searched += 1
                cnt = int(np.sum(group_mask[gid] & nd_masks[f]))
                if cnt <= rest and cnt <= nd_cnt[f] // 2:
                    groups[gid].append(f)
                    group_mask[gid] |= nd_masks[f]
                    group_bins[gid] += width
                    group_conflict[gid] += cnt
                    placed = True
                    break
        if not placed:
            groups.append([f])
            group_mask.append(nd_masks[f].copy())
            # a solo feature keeps its full bin range (incl. mfb)
            group_bins.append(1 + width)
            group_conflict.append(0)
            group_has_cat.append(bool(is_cat[f]))
    return groups


def find_groups_sparse(
    nd_rows: List[Optional[np.ndarray]],  # sorted non-default row ids, or
    # None when the feature must stay a dedicated column
    num_bins: Sequence[int],
    n_rows: int,
    max_group_bins: int,
) -> List[List[int]]:
    """find_groups over SPARSE features: conflicts are sorted-index
    intersections, group occupancy a sorted union — no (F, N) boolean
    masks are ever materialized (the CSR ingestion path;
    dataset.cpp:111 FindGroups semantics otherwise). Features whose
    nd_rows is None (categorical, dense, most-freq bin != zero bin)
    found singleton groups that accept no members."""
    F = len(nd_rows)
    budget = n_rows // 10000
    cnts = np.array(
        [n_rows if r is None else len(r) for r in nd_rows], np.int64
    )
    order = np.argsort(-cnts, kind="stable")

    groups: List[List[int]] = []
    group_rows: List[Optional[np.ndarray]] = []
    group_bins: List[int] = []
    group_conflict: List[int] = []
    for f in order:
        f = int(f)
        width = int(num_bins[f]) - 1
        placed = False
        if nd_rows[f] is not None and cnts[f] < n_rows:
            searched = 0
            for gid in range(len(groups)):
                if searched >= MAX_SEARCH_GROUP:
                    break
                if group_rows[gid] is None:
                    continue  # founded by an unmergeable feature
                if group_bins[gid] + width > max_group_bins:
                    continue
                rest = budget - group_conflict[gid]
                if rest < 0:
                    continue
                searched += 1
                cnt = np.intersect1d(
                    group_rows[gid], nd_rows[f], assume_unique=True
                ).size
                if cnt <= rest and cnt <= cnts[f] // 2:
                    groups[gid].append(f)
                    group_rows[gid] = np.union1d(group_rows[gid], nd_rows[f])
                    group_bins[gid] += width
                    group_conflict[gid] += cnt
                    placed = True
                    break
        if not placed:
            groups.append([f])
            group_rows.append(
                nd_rows[f] if nd_rows[f] is not None else None
            )
            group_bins.append(1 + width)
            group_conflict.append(0)
    return groups


def build_layout(
    groups: List[List[int]],
    num_bins: Sequence[int],
) -> BundleLayout:
    F = len(num_bins)
    bundle_of = np.zeros(F, np.int32)
    off_lo = np.zeros(F, np.int32)
    mfb = np.full(F, -1, np.int32)
    col_bins = 1
    for gid, feats in enumerate(groups):
        if len(feats) == 1:
            f = feats[0]
            bundle_of[f] = gid
            col_bins = max(col_bins, int(num_bins[f]))
            continue
        off = 1  # merged bin 0 = all members at their most-freq bin
        for f in feats:
            bundle_of[f] = gid
            off_lo[f] = off
            off += int(num_bins[f]) - 1
        col_bins = max(col_bins, off)
    return BundleLayout(
        groups=groups,
        bundle_of=bundle_of,
        off_lo=off_lo,
        mfb=np.full(F, -1, np.int32),  # filled by encode()
        col_bins=col_bins,
    )


def encode(
    bins: np.ndarray,  # (F, N) per-feature bins
    layout: BundleLayout,
    num_bins: Sequence[int],
    most_freq: Sequence[int],
    dtype=np.int32,
) -> Tuple[np.ndarray, BundleLayout]:
    """Merge per-feature bin columns into bundle columns.

    Conflicting rows (two members away from default — within the
    counted budget) resolve to the LAST member written, matching the
    reference's push-order overwrite.
    """
    F, N = bins.shape
    G = layout.num_columns
    out = np.zeros((G, N), dtype=dtype)
    mfb = np.full(F, -1, np.int32)
    for gid, feats in enumerate(layout.groups):
        if len(feats) == 1:
            out[gid] = bins[feats[0]]
            continue
        col = out[gid]
        for f in feats:
            m = int(most_freq[f])
            mfb[f] = m
            b = bins[f]
            nd = b != m
            shifted = b[nd].astype(np.int64) - (b[nd] > m)
            col[nd] = (layout.off_lo[f] + shifted).astype(dtype)
    return out, layout._replace(mfb=mfb)


def build_expand_idx(
    layout: BundleLayout, num_bins: Sequence[int], feat_bins: int
) -> np.ndarray:
    """(F, feat_bins) flat gather index into the (G * col_bins) bundle
    histogram for each (feature, bin); -1 marks the most-freq slot
    (recovered by subtraction) and out-of-range bins."""
    F = len(num_bins)
    Bc = layout.col_bins
    idx = np.full((F, feat_bins), -1, np.int32)
    for f in range(F):
        g = int(layout.bundle_of[f])
        nb = int(num_bins[f])
        m = int(layout.mfb[f])
        for b in range(nb):
            if m < 0:  # direct storage
                idx[f, b] = g * Bc + b
            elif b != m:
                idx[f, b] = g * Bc + int(layout.off_lo[f]) + b - (b > m)
    return idx


def bundle_features(
    bins: np.ndarray,
    mappers: List[BinMapper],
    max_bin: int,
    dtype=np.int32,
) -> Optional[Tuple[np.ndarray, BundleLayout, np.ndarray]]:
    """Full EFB pass over the binned (used-feature) matrix.

    Returns (merged_bins (G, N), layout, expand_idx (F, Bf)) or None
    when no merging is possible (all groups singleton) — caller keeps
    the plain per-feature matrix with zero overhead.
    """
    num_bins = [m.num_bin for m in mappers]
    most_freq = [m.most_freq_bin for m in mappers]
    is_cat = [m.bin_type == BinType.CATEGORICAL for m in mappers]
    max_group_bins = max(max_bin + 1, 256)
    groups = find_groups(bins, num_bins, most_freq, is_cat, max_group_bins)
    if all(len(g) == 1 for g in groups):
        return None
    layout = build_layout(groups, num_bins)
    merged, layout = encode(bins, layout, num_bins, most_freq, dtype)
    feat_bins = max(num_bins)
    expand_idx = build_expand_idx(layout, num_bins, feat_bins)
    return merged, layout, expand_idx
