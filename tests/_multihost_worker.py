"""Worker for the 2-process multi-host test (spawned by
test_multihost.py). Each process holds HALF the rows (pre_partition
semantics), binning samples are allgathered so mappers are identical,
and the data-parallel grower runs over the 2-process global mesh —
its psums ride the cross-process (Gloo, stand-in for DCN) collectives.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# pytest's conftest exports an 8-virtual-device XLA_FLAGS; this worker
# needs exactly ONE local device per process (2-process global mesh)
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from lightgbm_tpu.parallel import multihost

    got = multihost.init_distributed(
        machines=",".join(f"127.0.0.1:{int(port) + i}" for i in range(nproc)),
        machine_rank=rank,
    )
    assert got == rank == jax.process_index()
    assert jax.device_count() == nproc

    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import BinnedDataset
    from lightgbm_tpu.learner import GrowerSpec, make_split_params
    from lightgbm_tpu.learner.histogram import HIST_BLK
    from lightgbm_tpu.parallel.data_parallel import DataParallelGrower, make_mesh

    # ---- per-rank row shard of one logical dataset (pre_partition)
    rs = np.random.RandomState(0)
    n_total, f = 4096, 6
    X_all = rs.randn(n_total, f).astype(np.float64)
    w = rs.randn(f)
    y_all = ((X_all @ w + 0.3 * rs.randn(n_total)) > 0).astype(np.float32)
    lo, hi = rank * n_total // nproc, (rank + 1) * n_total // nproc
    X_loc, y_loc = X_all[lo:hi], y_all[lo:hi]

    # ---- distributed binning: identical mappers everywhere
    sample = multihost.allgather_binning_sample(X_loc)
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 5,
                  "tpu_row_block": HIST_BLK})
    ref = BinnedDataset.from_numpy(sample, cfg)
    ds = BinnedDataset.from_numpy(X_loc, cfg, label=y_loc, reference=ref)

    mesh = make_mesh()
    spec = GrowerSpec(num_leaves=15, num_bins=ds.max_num_bin, max_depth=-1)
    grower = DataParallelGrower(mesh, spec)
    params = make_split_params(cfg)

    # ---- global arrays from local shards
    npad_loc = ds.num_rows_padded()
    bins_loc = np.zeros((ds.num_used_features, npad_loc), np.int32)
    bins_loc[:, : ds.num_data] = ds.bins
    valid_loc = np.zeros(npad_loc, np.float32)
    valid_loc[: ds.num_data] = 1.0
    ylab = np.zeros(npad_loc, np.float32)
    ylab[: ds.num_data] = y_loc

    bins_g = multihost.global_rows(bins_loc, mesh, axis=1)
    valid_g = multihost.global_rows(valid_loc, mesh)
    label_g = multihost.global_rows(ylab, mesh)

    um = ds.used_mappers()
    rep = lambda a: jax.device_put(  # noqa: E731 — replicated small tables
        a, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    )
    nan_bin = rep(np.asarray([m.nan_bin for m in um], np.int32))
    num_bins = rep(np.asarray([m.num_bin for m in um], np.int32))
    mono = rep(np.zeros(ds.num_used_features, np.int32))
    is_cat = rep(np.zeros(ds.num_used_features, bool))
    feat_mask = rep(np.ones(ds.num_used_features, bool))

    @jax.jit
    def step(score, bins, label, valid):
        p = jax.nn.sigmoid(score)
        g = (p - label) * valid
        h = jnp.maximum(p * (1.0 - p), 1e-6) * valid
        return grower._fn(
            bins, nan_bin, num_bins, mono, is_cat, g, h, valid, feat_mask,
            params, valid, None, None, None, None, None, None,
        )

    score = multihost.global_rows(np.zeros(npad_loc, np.float32), mesh)
    tree, row_leaf = step(score, bins_g, label_g, valid_g)

    n_nodes = int(tree.num_nodes)
    lv = np.asarray(tree.leaf_value)[: n_nodes + 1]
    feats = np.asarray(tree.node_feature)[:n_nodes]
    # identical trees on every process (lockstep from psum'd histograms)
    from jax.experimental import multihost_utils

    all_lv = np.asarray(multihost_utils.process_allgather(jnp.asarray(lv)))
    assert np.allclose(all_lv, all_lv[0], atol=1e-6), "ranks diverged"
    print(
        f"MULTIHOST_OK rank={rank} nodes={n_nodes} "
        f"feat0={int(feats[0])} lv0={lv[0]:.6f}",
        flush=True,
    )


if __name__ == "__main__":
    main()
