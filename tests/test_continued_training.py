"""Continued training (init_model) + snapshot_freq
(reference boosting.h:311 input_model, gbdt.cpp:258-262 snapshots)."""

from __future__ import annotations

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=2000, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    w = rs.randn(6)
    y = ((X @ w + 0.4 * rs.randn(n)) > 0).astype(float)
    return X, y


PARAMS = {
    "objective": "binary",
    "num_leaves": 15,
    "learning_rate": 0.2,
    "verbosity": -1,
}


def test_split_training_equals_one_shot():
    """5 + 5 rounds via init_model == 10 rounds straight: score seeding
    through binned traversal is exact for our own models."""
    X, y = _problem()
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    full = lgb.train(dict(PARAMS), ds, num_boost_round=10)

    ds1 = lgb.Dataset(X, label=y, free_raw_data=False)
    first = lgb.train(dict(PARAMS), ds1, num_boost_round=5)
    ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
    second = lgb.train(dict(PARAMS), ds2, num_boost_round=5, init_model=first)

    assert second.num_trees() == 10
    np.testing.assert_allclose(
        second.predict(X[:300]), full.predict(X[:300]), rtol=1e-5, atol=1e-6
    )


def test_init_model_from_file(tmp_path):
    X, y = _problem(seed=3)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    first = lgb.train(dict(PARAMS), ds, num_boost_round=4)
    path = tmp_path / "m.txt"
    first.save_model(path)

    ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
    second = lgb.train(dict(PARAMS), ds2, num_boost_round=3,
                       init_model=str(path))
    assert second.num_trees() == 7
    # logloss should not get worse by continuing
    from sklearn.metrics import log_loss

    l1 = log_loss(y, first.predict(X))
    l2 = log_loss(y, second.predict(X))
    assert l2 <= l1 + 1e-6


def test_continued_training_with_valid_and_early_stop():
    X, y = _problem(seed=5)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    first = lgb.train(dict(PARAMS), ds, num_boost_round=3)
    ds2 = lgb.Dataset(X, label=y, free_raw_data=False)
    vs = lgb.Dataset(X[:400], label=y[:400], reference=ds2, free_raw_data=False)
    second = lgb.train(
        {**PARAMS, "metric": "binary_logloss"}, ds2, num_boost_round=5,
        valid_sets=[vs], valid_names=["v"], init_model=first,
    )
    assert second.num_trees() == 8
    assert np.isfinite(second.predict(X[:10])).all()


def test_snapshot_freq(tmp_path):
    X, y = _problem(seed=7)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    out = tmp_path / "snap_model.txt"
    lgb.train(
        {**PARAMS, "snapshot_freq": 3, "output_model": str(out)},
        ds, num_boost_round=7,
    )
    s3 = lgb.Booster(model_file=f"{out}.snapshot_iter_3")
    s6 = lgb.Booster(model_file=f"{out}.snapshot_iter_6")
    assert s3.num_trees() == 3
    assert s6.num_trees() == 6


def test_cli_continued_training(tmp_path):
    import os

    from lightgbm_tpu.cli import main as cli_main

    X, y = _problem(seed=9)
    np.savetxt(tmp_path / "train.tsv", np.column_stack([y, X]),
               delimiter="\t", fmt="%.6f")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        assert cli_main(["task=train", "objective=binary", "data=train.tsv",
                         "num_trees=4", "num_leaves=7", "verbosity=-1",
                         "output_model=m1.txt"]) == 0
        assert cli_main(["task=train", "objective=binary", "data=train.tsv",
                         "num_trees=3", "num_leaves=7", "verbosity=-1",
                         "input_model=m1.txt", "output_model=m2.txt"]) == 0
    finally:
        os.chdir(cwd)
    m2 = lgb.Booster(model_file=tmp_path / "m2.txt")
    assert m2.num_trees() == 7
