"""Objective functions: gradients/hessians as jitted device functions.

Reimplements the reference objective layer
(include/LightGBM/objective_function.h:19, src/objective/*.hpp) with the
same math, factory names and aliases (objective_function.cpp:22). Each
objective produces per-row (grad, hess) from the current score on device
— the TPU analog of the CUDA objectives (src/objective/cuda/) that keep
the boosting state device-resident.

Scores/labels are padded row vectors; padding rows produce garbage
gradients that the grower masks out via its validity channel.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import log
from .config import Config
from .dataset import BinnedDataset, Metadata


class ObjectiveFunction:
    """Base objective (reference objective_function.h:19).

    Fold-attr contract (ADVICE r5 item 3): any attribute holding a
    DEVICE array that varies per dataset/fold and is read inside
    get_gradients must be listed in boosting._OBJ_FOLD_ATTRS (the
    fused step rebinds those per fold) or in _OBJ_FOLD_EXEMPT with the
    gate that keeps the memoized step safe. Both the build-time check
    (boosting._audit_fold_attrs) and the static auditor
    (analysis/jaxpr_audit.audit_fold_attrs) fail loudly otherwise —
    an unlisted attr would be baked into a cached executable and
    silently share fold data across boosters."""

    name = "custom"
    num_class = 1
    is_ranking = False
    # objectives that refit leaf outputs with residual percentiles
    # (objective_function.h:55 IsRenewTreeOutput)
    is_renew_tree_output = False
    # get_gradients is pure jax (traceable into the fused device loop);
    # host-loop objectives (lambdarank) override to False
    is_device_gradients = True

    def __init__(self, config: Config):
        self.config = config
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None

    def init(self, dataset: BinnedDataset) -> None:
        meta = dataset.metadata
        if meta.label is None:
            log.fatal(f"objective {self.name} requires labels")
        self.check_label(meta.label)
        self.label = jnp.asarray(dataset.padded(meta.label))
        self.weight = (
            jnp.asarray(dataset.padded(meta.weight))
            if meta.weight is not None
            else None
        )
        self._meta = meta
        self._num_data = dataset.num_data

    def check_label(self, label: np.ndarray) -> None:
        pass

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, score: np.ndarray) -> np.ndarray:
        """Raw score -> prediction space (sigmoid/exp/softmax)."""
        return score

    def _w(self, g: jax.Array, h: jax.Array) -> Tuple[jax.Array, jax.Array]:
        if self.weight is not None:
            return g * self.weight, h * self.weight
        return g, h

    def _bfs_label(self):
        """Host label for init-score statistics — GLOBAL across the
        process cluster: under multi-host training every rank must
        derive the SAME boost_from_average value (the reference's
        BoostFromAverage is computed after the network allreduce,
        gbdt.cpp); gathered lazily and cached."""
        if getattr(self, "_g_label", None) is None:
            from .parallel.multihost import gather_host_rows

            self._g_label = gather_host_rows(
                np.asarray(self.label)[: self._num_data]
            )
        return self._g_label

    def _np_weight(self):
        """Host weights truncated to real rows (None when unweighted),
        globally gathered like _bfs_label."""
        if self.weight is None:
            return None
        if getattr(self, "_g_weight", None) is None:
            from .parallel.multihost import gather_host_rows

            self._g_weight = gather_host_rows(
                np.asarray(self.weight)[: self._num_data]
            )
        return self._g_weight

    def _bfs_label_weight(self):
        """Objective-derived per-row weights (e.g. MAPE), gathered."""
        if getattr(self, "_g_label_weight", None) is None:
            from .parallel.multihost import gather_host_rows

            self._g_label_weight = gather_host_rows(
                np.asarray(self._label_weight)[: self._num_data]
            )
        return self._g_label_weight


# ---------------------------------------------------------------- regression
class RegressionL2(ObjectiveFunction):
    """reference regression_objective.hpp RegressionL2loss."""

    name = "regression"

    def init(self, dataset: BinnedDataset) -> None:
        super().init(dataset)
        if self.config.reg_sqrt:
            lab = np.asarray(self.label)
            self.label = jnp.sign(jnp.asarray(lab)) * jnp.sqrt(jnp.abs(jnp.asarray(lab)))

    def get_gradients(self, score):
        return self._w(score - self.label, jnp.ones_like(score))

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        w = self._np_weight()
        return float(np.average(lab, weights=w))

    def convert_output(self, score):
        if self.config.reg_sqrt:
            return np.sign(score) * score * score
        return score


class RegressionL1(RegressionL2):
    name = "regression_l1"
    is_renew_tree_output = True

    def get_gradients(self, score):
        return self._w(jnp.sign(score - self.label), jnp.ones_like(score))

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        w = self._np_weight()
        if w is None:
            return float(np.percentile(lab, 50))
        return _weighted_percentile(lab, w, 0.5)

    def renew_percentile(self) -> float:
        return 0.5


class Huber(RegressionL2):
    name = "huber"
    is_renew_tree_output = True

    def get_gradients(self, score):
        d = score - self.label
        a = jnp.float32(self.config.alpha)
        g = jnp.where(jnp.abs(d) <= a, d, jnp.sign(d) * a)
        return self._w(g, jnp.ones_like(score))

    def renew_percentile(self) -> float:
        return 0.5


class Fair(RegressionL2):
    name = "fair"

    def get_gradients(self, score):
        d = score - self.label
        c = jnp.float32(self.config.fair_c)
        return self._w(c * d / (jnp.abs(d) + c), c * c / (jnp.abs(d) + c) ** 2)

    def boost_from_score(self, class_id: int) -> float:
        return 0.0


class Poisson(RegressionL2):
    name = "poisson"

    def check_label(self, label):
        if np.any(label < 0):
            log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, score):
        mds = jnp.float32(self.config.poisson_max_delta_step)
        return self._w(jnp.exp(score) - self.label, jnp.exp(score + mds))

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        return float(np.log(max(np.average(lab, weights=self._np_weight()), 1e-20)))

    def convert_output(self, score):
        return np.exp(score)


class Quantile(RegressionL2):
    name = "quantile"
    is_renew_tree_output = True

    def get_gradients(self, score):
        a = jnp.float32(self.config.alpha)
        g = jnp.where(score > self.label, 1.0 - a, -a)
        return self._w(g, jnp.ones_like(score))

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        w = self._np_weight()
        if w is None:
            return float(np.percentile(lab, self.config.alpha * 100))
        return _weighted_percentile(lab, w, self.config.alpha)

    def renew_percentile(self) -> float:
        return float(self.config.alpha)


class MAPE(RegressionL2):
    name = "mape"
    is_renew_tree_output = True

    def init(self, dataset):
        super().init(dataset)
        lab = np.asarray(self.label)
        lw = 1.0 / np.maximum(1.0, np.abs(lab))
        if self.weight is not None:
            lw = lw * np.asarray(self.weight)
        self._label_weight = jnp.asarray(lw.astype(np.float32))

    def get_gradients(self, score):
        g = jnp.sign(score - self.label) * self._label_weight
        return g, self._label_weight

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        w = self._bfs_label_weight()
        return _weighted_percentile(lab, w, 0.5)

    def renew_percentile(self) -> float:
        return 0.5


class Gamma(Poisson):
    name = "gamma"

    def get_gradients(self, score):
        return self._w(
            1.0 - self.label * jnp.exp(-score), self.label * jnp.exp(-score)
        )


class Tweedie(Poisson):
    name = "tweedie"

    def get_gradients(self, score):
        rho = jnp.float32(self.config.tweedie_variance_power)
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        g = -self.label * e1 + e2
        h = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._w(g, h)


# ---------------------------------------------------------------- binary
class Binary(ObjectiveFunction):
    """reference binary_objective.hpp: labels {0,1} -> {-1,+1}, sigmoid
    scaling, is_unbalance / scale_pos_weight label weighting."""

    name = "binary"

    def check_label(self, label):
        u = np.unique(label)
        if not np.all(np.isin(u, [0, 1])):
            log.fatal("[binary]: labels must be 0 or 1")

    def init(self, dataset):
        super().init(dataset)
        lab = self._bfs_label()
        cnt_pos = float(np.sum(lab == 1))
        cnt_neg = float(np.sum(lab == 0))
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                self._pos_w, self._neg_w = 1.0, cnt_pos / cnt_neg
            else:
                self._pos_w, self._neg_w = cnt_neg / cnt_pos, 1.0
        else:
            self._pos_w = float(self.config.scale_pos_weight)
            self._neg_w = 1.0
        self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg

    def get_gradients(self, score):
        sig = jnp.float32(self.config.sigmoid)
        y = self.label  # 0/1
        p = jax.nn.sigmoid(sig * score)
        lw = jnp.where(y > 0, self._pos_w, self._neg_w)
        g = (p - y) * sig * lw
        h = p * (1.0 - p) * sig * sig * lw
        return self._w(g, h)

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        w = (
            self._np_weight()
            if self.weight is not None
            else np.ones_like(lab)
        )
        lw = np.where(lab > 0, self._pos_w, self._neg_w) * w
        pavg = float(np.sum(lab * lw) / max(np.sum(lw), 1e-20))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)) / self.config.sigmoid)

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.config.sigmoid * score))


# ---------------------------------------------------------------- multiclass
class MulticlassSoftmax(ObjectiveFunction):
    """reference multiclass_objective.hpp MulticlassSoftmax."""

    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class

    def check_label(self, label):
        if np.any(label < 0) or np.any(label >= self.num_class):
            log.fatal("[multiclass]: label must be in [0, num_class)")

    def get_gradients(self, score):
        # score: (K, N)
        p = jax.nn.softmax(score, axis=0)
        y = jax.nn.one_hot(self.label.astype(jnp.int32), self.num_class).T
        g = p - y
        h = 2.0 * p * (1.0 - p)  # reference factor 2
        if self.weight is not None:
            g = g * self.weight[None, :]
            h = h * self.weight[None, :]
        return g, h

    def convert_output(self, score):
        e = np.exp(score - np.max(score, axis=0, keepdims=True))
        return e / np.sum(e, axis=0, keepdims=True)


class MulticlassOVA(ObjectiveFunction):
    """One-vs-all: K independent sigmoid binaries (multiclass_objective.hpp)."""

    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class

    def get_gradients(self, score):
        sig = jnp.float32(self.config.sigmoid)
        y = jax.nn.one_hot(self.label.astype(jnp.int32), self.num_class).T
        p = jax.nn.sigmoid(sig * score)
        g = (p - y) * sig
        h = p * (1.0 - p) * sig * sig
        if self.weight is not None:
            g = g * self.weight[None, :]
            h = h * self.weight[None, :]
        return g, h

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        p = float(np.mean(lab == class_id))
        p = min(max(p, 1e-15), 1.0 - 1e-15)
        return float(np.log(p / (1.0 - p)) / self.config.sigmoid)

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.config.sigmoid * score))


# ---------------------------------------------------------------- xentropy
class CrossEntropy(ObjectiveFunction):
    """reference xentropy_objective.hpp: labels in [0,1]."""

    name = "cross_entropy"

    def check_label(self, label):
        if np.any(label < 0) or np.any(label > 1):
            log.fatal("[cross_entropy]: labels must be in [0, 1]")

    def get_gradients(self, score):
        p = jax.nn.sigmoid(score)
        return self._w(p - self.label, p * (1.0 - p))

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        pavg = float(np.average(lab, weights=self._np_weight()))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-score))


class CrossEntropyLambda(ObjectiveFunction):
    """reference xentropy_objective.hpp:185 CrossEntropyLambda
    (alias xentlambda): weighted cross-entropy via the normalized
    exponential parameterization; with unit weights it reduces to
    plain cross-entropy."""

    name = "cross_entropy_lambda"

    def check_label(self, label):
        if np.any(label < 0) or np.any(label > 1):
            log.fatal("[cross_entropy_lambda]: labels must be in [0, 1]")

    def init(self, dataset):
        super().init(dataset)
        if self.weight is not None:
            wmin = float(np.asarray(self.weight)[: self._num_data].min())
            if wmin <= 0:
                log.fatal("[cross_entropy_lambda]: at least one weight is non-positive")

    def get_gradients(self, score):
        if self.weight is None:
            z = jax.nn.sigmoid(score)
            return z - self.label, z * (1.0 - z)
        # reference computes in f64; on-device f32 needs stable forms and
        # a saturation clamp (|s|>30 the loss is flat to f32 precision
        # anyway): softplus/sigmoid instead of raw exp, which overflows
        # at s>~88 and collapses z below its clamp at very negative s
        w = self.weight
        y = self.label
        sc = jnp.clip(score, -30.0, 30.0)
        epf = jnp.exp(sc)
        hhat = jax.nn.softplus(sc)
        z = 1.0 - jnp.exp(-w * hhat)
        g = (1.0 - y / jnp.maximum(z, 1e-15)) * w * jax.nn.sigmoid(sc)
        c = 1.0 / jnp.maximum(1.0 - z, 1e-15)
        a = w * jax.nn.sigmoid(sc) * jax.nn.sigmoid(-sc)
        d2 = jnp.maximum(c - 1.0, 1e-15)
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        h = a * (1.0 + y * b)
        return g, h

    def boost_from_score(self, class_id: int) -> float:
        lab = self._bfs_label()
        havg = float(np.average(lab, weights=self._np_weight()))
        return float(np.log(max(np.expm1(havg), 1e-15)))

    def convert_output(self, score):
        # the "normalized exponential parameter" lambda, not a probability;
        # logaddexp = stable softplus (log1p(exp(s)) overflows at s>~709)
        return np.logaddexp(0.0, score)


# ---------------------------------------------------------------- ranking
class LambdaRank(ObjectiveFunction):
    """reference rank_objective.hpp LambdarankNDCG, device-resident.

    Per-query sorting, pairwise delta-NDCG lambdas, truncation level and
    norm all run on device via the padded (Q, M) query layout
    (learner/ranking.py) — one traced function, fused-loop eligible,
    vs the reference's per-query OpenMP loop (rank_objective.hpp:63-92).
    """

    name = "lambdarank"
    is_ranking = True
    is_device_gradients = True

    def init(self, dataset):
        super().init(dataset)
        if self._meta.group is None:
            log.fatal("lambdarank requires query group information")
        from .learner.ranking import (
            build_query_layout,
            check_label_range,
            default_label_gain,
            inverse_max_dcg,
            lambdarank_gradients,
        )

        label = np.asarray(self._meta.label)
        npad = len(np.asarray(self.label))
        self._layout = build_query_layout(self._meta.group, npad)
        gains = list(self.config.label_gain)
        if not gains:
            gains = list(default_label_gain(int(label.max())))
        check_label_range(label, len(gains))
        self._label_gain = np.asarray(gains, dtype=np.float64)
        self._trunc = int(self.config.lambdarank_truncation_level)
        self._norm = bool(self.config.lambdarank_norm)
        self._sigmoid = float(self.config.sigmoid)
        if self._sigmoid <= 0:
            log.fatal(f"Sigmoid param {self._sigmoid} should be greater than zero")
        imd = inverse_max_dcg(label, self._layout, self._label_gain, self._trunc)

        label_dev = jnp.asarray(self.label, jnp.float32)
        gain_dev = jnp.asarray(self._label_gain, jnp.float32)
        imd_dev = jnp.asarray(imd, jnp.float32)
        layout = self._layout
        sig, trunc, norm = self._sigmoid, self._trunc, self._norm

        weight_dev = self.weight

        def _raw(score):
            g, h = lambdarank_gradients(
                layout, score, label_dev, gain_dev, imd_dev, sig, trunc, norm
            )
            # per-document weights (RankingObjective::GetGradients
            # rank_objective.hpp:84-90 multiplies lambdas and hessians)
            if weight_dev is not None:
                g = g * weight_dev
                h = h * weight_dev
            return g, h

        def _grads(score):
            g, h = _raw(score)
            # tiny hessian floor keeps leaf outputs finite on degenerate
            # queries (all-equal labels contribute zero hessian)
            return g, jnp.maximum(h, 2e-7)

        # jitted: non-fused callers run this eagerly every iteration —
        # tracing once embeds the (Q, M) layout as a device constant
        # instead of re-uploading it per call
        self._grads = jax.jit(_grads)

        # ---- position-bias debiasing (rank_objective.hpp:55-98,302):
        # scores are adjusted by a per-position bias factor before the
        # lambda computation, and the factors take a Newton-Raphson step
        # from the accumulated lambdas/hessians each iteration. The
        # factors are cross-iteration HOST state, so this objective
        # leaves the fused loop when positions are present.
        self._pos_biases = None
        pos = self._meta.position
        if pos is not None:
            pos = np.asarray(pos, np.int64)
            P = int(pos.max()) + 1
            posp = np.zeros(npad, np.int64)
            posp[: len(pos)] = pos
            positions = jnp.asarray(posp.astype(np.int32))
            valid_rows = jnp.asarray(
                (np.arange(npad) < len(pos)).astype(np.float32)
            )
            reg = jnp.float32(
                self.config.lambdarank_position_bias_regularization
            )
            lr = jnp.float32(self.config.learning_rate)
            self._pos_biases = jnp.zeros(P, jnp.float32)
            self.has_host_state = True

            def _grads_pos(score, biases):
                adj = score + biases[positions]
                g, h = _raw(adj)
                # UpdatePositionBiasFactors: Newton step on the utility
                # derivatives w.r.t. each position's bias factor
                d1 = jnp.zeros(P).at[positions].add(-g * valid_rows)
                d2 = jnp.zeros(P).at[positions].add(-h * valid_rows)
                cnt = jnp.zeros(P).at[positions].add(valid_rows)
                d1 = d1 - biases * reg * cnt
                d2 = d2 - reg * cnt
                new_biases = biases + lr * d1 / (jnp.abs(d2) + 0.001)
                return g, jnp.maximum(h, 2e-7), new_biases

            self._grads_pos = jax.jit(_grads_pos)

    def get_gradients(self, score):
        if self._pos_biases is not None:
            g, h, self._pos_biases = self._grads_pos(score, self._pos_biases)
            return g, h
        return self._grads(score)

    @property
    def position_biases(self):
        """Learned per-position bias factors (None without positions)."""
        return self._pos_biases

    def convert_output(self, score):
        return score


def _weighted_percentile(values: np.ndarray, weights: np.ndarray, alpha: float) -> float:
    order = np.argsort(values)
    v, w = values[order], weights[order]
    cw = np.cumsum(w)
    threshold = alpha * cw[-1]
    idx = int(np.searchsorted(cw, threshold))
    return float(v[min(idx, len(v) - 1)])




class RankXENDCG(ObjectiveFunction):
    """reference rank_objective.hpp RankXENDCG: per-query softmax scores
    against a stochastically perturbed 2^label ground-truth distribution,
    with the three-term gradient series of the XE-NDCG loss. Fresh
    uniforms are drawn per (iteration, document) — keyed RNG instead of
    the reference's per-query stateful generators, so the whole gradient
    stays one traced device function (fused-loop eligible)."""

    name = "rank_xendcg"
    is_ranking = True
    is_device_gradients = True
    needs_iter = True

    def check_label(self, label):
        if np.any(label < 0):
            log.fatal("[rank_xendcg]: relevance labels must be non-negative")

    def init(self, dataset):
        super().init(dataset)
        if self._meta.group is None:
            log.fatal("rank_xendcg requires query group information")
        from .learner.ranking import build_query_layout

        npad = len(np.asarray(self.label))
        layout = build_query_layout(self._meta.group, npad)
        qdoc = jnp.asarray(layout.qdoc)
        qvalid = jnp.asarray(layout.qvalid)
        label_dev = jnp.asarray(self.label, jnp.float32)
        weight_dev = self.weight
        seed = int(self.config.objective_seed)
        eps = 1e-15
        NEG = jnp.float32(-1e30)

        def _grads(score, it):
            s = jnp.where(qvalid, score[jnp.clip(qdoc, 0, npad - 1)], NEG)
            lb = jnp.where(qvalid, label_dev[jnp.clip(qdoc, 0, npad - 1)], 0.0)
            rho = jax.nn.softmax(s, axis=1)  # Common::Softmax per query
            key = jax.random.fold_in(jax.random.key(seed), it)
            u = jax.random.uniform(key, qvalid.shape)
            phi = jnp.where(qvalid, jnp.exp2(jnp.floor(lb)) - u, 0.0)
            inv_den = 1.0 / jnp.maximum(
                jnp.sum(phi, axis=1, keepdims=True), eps
            )
            t1 = -phi * inv_den + rho
            p2 = t1 / jnp.maximum(1.0 - rho, eps)
            sum1 = jnp.sum(jnp.where(qvalid, p2, 0.0), axis=1, keepdims=True)
            t2 = rho * (sum1 - p2)
            p3 = t2 / jnp.maximum(1.0 - rho, eps)
            sum2 = jnp.sum(jnp.where(qvalid, p3, 0.0), axis=1, keepdims=True)
            lam = t1 + t2 + rho * (sum2 - p3)
            hess = rho * (1.0 - rho)
            multi = (jnp.sum(qvalid, axis=1, keepdims=True) > 1)
            ok = qvalid & multi
            lam = jnp.where(ok, lam, 0.0)
            hess = jnp.where(ok, hess, 0.0)
            g = jnp.zeros(npad, jnp.float32).at[qdoc.reshape(-1)].add(
                lam.reshape(-1), mode="drop"
            )
            h = jnp.zeros(npad, jnp.float32).at[qdoc.reshape(-1)].add(
                hess.reshape(-1), mode="drop"
            )
            if weight_dev is not None:
                g = g * weight_dev
                h = h * weight_dev
            return g, jnp.maximum(h, 2e-7)

        self._grads = jax.jit(_grads)

    def get_gradients(self, score, it=0):
        return self._grads(score, jnp.asarray(it, jnp.int32))

    def convert_output(self, score):
        return score


_OBJECTIVES: Dict[str, type] = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": MAPE,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": Binary,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "lambdarank": LambdaRank,
    "rank_xendcg": RankXENDCG,
    "cross_entropy_lambda": CrossEntropyLambda,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Factory (reference objective_function.cpp:22)."""
    name = config.objective
    if name == "none":
        return None
    if name not in _OBJECTIVES:
        log.fatal(f"Unknown objective type name: {name}")
    return _OBJECTIVES[name](config)
