"""Device percentile leaf renewal (learner/renewal.py) must match the
host numpy renewal (sync path) — l1/huber/quantile/mape now ride the
fast and fused loops (RenewTreeOutput, regression_objective.hpp:251)."""

from __future__ import annotations

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=2000, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6)
    w = rs.randn(6)
    y = X @ w + 0.5 * rs.standard_cauchy(n)  # heavy tails: renewal matters
    return X, y


def _train(params, X, y, sync: bool, rounds=10, weight=None):
    ds = lgb.Dataset(X, label=y, weight=weight, free_raw_data=False)
    bst = lgb.Booster(params=dict(params), train_set=ds)
    if sync:
        bst._gbdt._force_sync = True
    for _ in range(rounds):
        bst.update()
    bst._gbdt._materialize()
    return bst


@pytest.mark.parametrize("objective", ["regression_l1", "quantile", "mape", "huber"])
def test_device_renewal_matches_host(objective):
    X, y = _problem()
    params = {
        "objective": objective,
        "num_leaves": 15,
        "learning_rate": 0.2,
        "verbosity": -1,
        "min_data_in_leaf": 10,
    }
    b_sync = _train(params, X, y, sync=True)
    b_fast = _train(params, X, y, sync=False)
    np.testing.assert_allclose(
        b_fast.predict(X[:200]), b_sync.predict(X[:200]), rtol=1e-5, atol=1e-6
    )


def test_device_renewal_weighted():
    X, y = _problem(seed=3)
    rs = np.random.RandomState(4)
    weight = 0.2 + rs.rand(len(y))
    params = {
        "objective": "quantile",
        "alpha": 0.7,
        "num_leaves": 7,
        "verbosity": -1,
    }
    b_sync = _train(params, X, y, sync=True, weight=weight)
    b_fast = _train(params, X, y, sync=False, weight=weight)
    np.testing.assert_allclose(
        b_fast.predict(X[:200]), b_sync.predict(X[:200]), rtol=1e-5, atol=1e-6
    )


def test_l1_rides_fused_loop_and_learns():
    X, y = _problem(seed=7)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {
        "objective": "regression_l1",
        "metric": "l1",
        "num_leaves": 15,
        "learning_rate": 0.3,
        "verbosity": -1,
    }
    bst = lgb.train(dict(params), ds, num_boost_round=30,
                    valid_sets=[ds], valid_names=["t"])
    assert bst._gbdt.fused_eligible()
    mae0 = np.mean(np.abs(y - np.median(y)))
    mae = np.mean(np.abs(y - bst.predict(X)))
    assert mae < 0.7 * mae0, (mae, mae0)


def test_renewal_with_bagging_matches():
    X, y = _problem(seed=11)
    params = {
        "objective": "regression_l1",
        "num_leaves": 7,
        "bagging_fraction": 0.7,
        "bagging_freq": 1,
        "verbosity": -1,
    }
    b_sync = _train(params, X, y, sync=True, rounds=8)
    b_fast = _train(params, X, y, sync=False, rounds=8)
    np.testing.assert_allclose(
        b_fast.predict(X[:100]), b_sync.predict(X[:100]), rtol=1e-5, atol=1e-6
    )
