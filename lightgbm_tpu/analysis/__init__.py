"""Trace-safety static analysis suite (the USE_DEBUG build analog).

The reference ships a `USE_DEBUG` build whose internal assertions
(`CheckSplit`, serial_tree_learner.h:174) catch learner drift at the
iteration it happens. Our failure modes are different — silent
retraces, dtype widening on the int32 quantized wire, stale device
constants baked into cached traced steps — and every one of them is
detectable BEFORE runtime by inspecting source ASTs and jaxprs. Three
cooperating passes (docs/STATIC_ANALYSIS.md):

- `lint`             AST linter for JAX hazards inside traced code paths
- `concurrency_lint` AST lock-discipline linter for the threaded
                     serving layer (unlocked shared writes, lock-order
                     inversions, per-call primitives, blocking under
                     a lock)
- `jaxpr_audit`      abstract-traces the hot entry points and asserts
                     machine-checkable contracts (quant wire dtype, no
                     host callbacks, executable-size budgets)
- `cost_audit`       lowers-and-COMPILES the same entries on CPU and
                     checks XLA cost/memory analysis + collective
                     wire-bytes against checked-in budgets
                     (cost_budget.json)
- `retrace`          runtime jit-cache-miss guard (context manager +
                     pytest fixture) with `jax.checking_leaks` wired in
- `passes`           the registry every `--strict` run must exercise

Run `python -m lightgbm_tpu.analysis --strict` (CI hook), or use the
pieces directly:

    from lightgbm_tpu.analysis import lint_package, run_audits
    from lightgbm_tpu.analysis.concurrency_lint import concurrency_lint_package
    from lightgbm_tpu.analysis.retrace import retrace_guard
"""

from .concurrency_lint import (
    CONCURRENCY_RULES,
    concurrency_lint_package,
    concurrency_lint_source,
)
from .lint import Finding, RULES, lint_package, lint_source, format_findings

__all__ = [
    "Finding",
    "RULES",
    "CONCURRENCY_RULES",
    "lint_package",
    "lint_source",
    "concurrency_lint_package",
    "concurrency_lint_source",
    "format_findings",
    "run_audits",
    "run_cost_audits",
]


def run_audits(*args, **kwargs):
    """Lazy forward to jaxpr_audit.run_audits (imports jax)."""
    from .jaxpr_audit import run_audits as _run

    return _run(*args, **kwargs)


def run_cost_audits(*args, **kwargs):
    """Lazy forward to cost_audit.run_cost_audits (imports + compiles
    under jax)."""
    from .cost_audit import run_cost_audits as _run

    return _run(*args, **kwargs)
