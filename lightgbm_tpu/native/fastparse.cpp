// Native text-ingest kernel for the data loader.
//
// The reference's DatasetLoader reads training text through C++ parsers
// (src/io/parser.cpp CSV/TSV/LibSVM + PipelineReader); this is the
// TPU build's equivalent native front-end: a small C++17 shared
// library, loaded via ctypes (lightgbm_tpu/native/__init__.py), that
// turns delimited text / LibSVM into dense row-major double matrices.
// Parsing is parallelized over line ranges with std::thread (the
// reference parallelizes by OpenMP rows, dataset_loader.cpp).
//
// Plain C ABI on purpose: no Python.h, no pybind11 — the caller owns
// NumPy allocation and copies out of the returned malloc'd buffer.

#include <algorithm>
#include <cctype>
#include <cerrno>
#include <cmath>
#include <limits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  ~FileBuf() { std::free(data); }
};

bool read_file(const char* path, FileBuf* out) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out->data = static_cast<char*>(std::malloc(static_cast<size_t>(sz) + 1));
  if (!out->data) {
    std::fclose(f);
    return false;
  }
  size_t rd = std::fread(out->data, 1, static_cast<size_t>(sz), f);
  std::fclose(f);
  out->size = rd;
  out->data[rd] = '\0';
  return true;
}

// line start offsets (excluding trailing empty line)
std::vector<size_t> line_starts(const char* s, size_t n) {
  std::vector<size_t> starts;
  size_t i = 0;
  while (i < n) {
    starts.push_back(i);
    const char* nl = static_cast<const char*>(std::memchr(s + i, '\n', n - i));
    if (!nl) break;
    i = static_cast<size_t>(nl - s) + 1;
  }
  return starts;
}

size_t line_end(const char* s, size_t n, size_t start) {
  const char* nl =
      static_cast<const char*>(std::memchr(s + start, '\n', n - start));
  size_t e = nl ? static_cast<size_t>(nl - s) : n;
  while (e > start && (s[e - 1] == '\r')) --e;
  return e;
}

// `bad` (optional): set to true when the token is non-empty, not a
// recognized missing-value token, and not fully numeric — callers use
// it to fail the whole parse so the Python fallback (np.loadtxt, which
// RAISES on such tokens) keeps native and fallback behavior aligned.
double parse_field(const char* b, const char* e, bool* bad = nullptr) {
  while (b < e && std::isspace(static_cast<unsigned char>(*b))) ++b;
  while (e > b && std::isspace(static_cast<unsigned char>(e[-1]))) --e;
  if (b == e) return std::nan("");
  if ((e - b) <= 4) {
    // na / nan / null / none / ? (Common::AtofPrecise missing tokens)
    char buf[5];
    int k = 0;
    for (const char* p = b; p < e; ++p)
      buf[k++] = static_cast<char>(std::tolower(static_cast<unsigned char>(*p)));
    buf[k] = '\0';
    if (!std::strcmp(buf, "na") || !std::strcmp(buf, "nan") ||
        !std::strcmp(buf, "null") || !std::strcmp(buf, "none") ||
        !std::strcmp(buf, "?"))
      return std::nan("");
  }
  char* endp = nullptr;
  std::string tmp(b, e);  // strtod needs NUL termination
  double v = std::strtod(tmp.c_str(), &endp);
  if (endp == tmp.c_str() || *endp != '\0') {
    if (bad) *bad = true;
    return std::nan("");
  }
  return v;
}

int n_threads_for(size_t rows) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  size_t by_rows = rows / 4096 + 1;
  return static_cast<int>(by_rows < hw ? by_rows : hw);
}

}  // namespace

extern "C" {

// Parse a delimited numeric file into a dense row-major matrix.
// Returns 0 on success; caller frees *out with fp_free.
int fp_parse_delim(const char* path, char delim, int skip_rows,
                   double** out, int64_t* out_rows, int64_t* out_cols) {
  FileBuf fb;
  if (!read_file(path, &fb)) return 1;
  std::vector<size_t> starts = line_starts(fb.data, fb.size);
  // drop skipped header rows and blank trailing lines
  size_t first = static_cast<size_t>(skip_rows) < starts.size()
                     ? static_cast<size_t>(skip_rows)
                     : starts.size();
  // skip BLANK lines entirely (np.loadtxt semantics — the numpy
  // fallback must see the same row set)
  std::vector<size_t> rows_;
  for (size_t i = first; i < starts.size(); ++i) {
    if (line_end(fb.data, fb.size, starts[i]) > starts[i])
      rows_.push_back(starts[i]);
  }
  int64_t n_rows = static_cast<int64_t>(rows_.size());
  if (n_rows == 0) return 2;

  // column count from the first data row
  size_t e0 = line_end(fb.data, fb.size, rows_[0]);
  int64_t n_cols = 1;
  for (size_t i = rows_[0]; i < e0; ++i)
    if (fb.data[i] == delim) ++n_cols;

  double* mat = static_cast<double*>(
      std::malloc(sizeof(double) * static_cast<size_t>(n_rows * n_cols)));
  if (!mat) return 3;

  int nt = n_threads_for(static_cast<size_t>(n_rows));
  std::vector<std::thread> threads;
  std::vector<int> errs(static_cast<size_t>(nt), 0);
  auto work = [&](int tid) {
    int64_t lo = n_rows * tid / nt, hi = n_rows * (tid + 1) / nt;
    bool bad = false;
    for (int64_t r = lo; r < hi && !bad; ++r) {
      size_t b = rows_[static_cast<size_t>(r)];
      size_t e = line_end(fb.data, fb.size, b);
      int64_t c = 0;
      size_t fs = b;
      for (size_t i = b; i <= e; ++i) {
        if (i == e || fb.data[i] == delim) {
          if (c < n_cols)
            mat[r * n_cols + c] = parse_field(fb.data + fs, fb.data + i, &bad);
          ++c;
          fs = i + 1;
        }
      }
      // field-count mismatch = malformed file: fail the parse so the
      // caller falls back to np.loadtxt, which raises (no silent
      // NaN-padding / truncation on the native path only)
      if (c != n_cols) bad = true;
    }
    if (bad) errs[static_cast<size_t>(tid)] = 1;
  };
  for (int t = 0; t < nt; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
  for (int err : errs) {
    if (err) {
      std::free(mat);
      return 4;
    }
  }

  *out = mat;
  *out_rows = n_rows;
  *out_cols = n_cols;
  return 0;
}

// Parse LibSVM ("label idx:val idx:val ...", 0- or 1-based indices kept
// as-is) into a dense (rows, max_idx+1) matrix of zeros + a label vec.
int fp_parse_libsvm(const char* path, double** out, double** out_label,
                    int64_t* out_rows, int64_t* out_cols) {
  FileBuf fb;
  if (!read_file(path, &fb)) return 1;
  std::vector<size_t> starts = line_starts(fb.data, fb.size);
  while (!starts.empty() &&
         line_end(fb.data, fb.size, starts.back()) == starts.back())
    starts.pop_back();
  int64_t n_rows = static_cast<int64_t>(starts.size());
  if (n_rows == 0) return 2;

  // pass 1 (parallel): max feature index per thread
  int nt = n_threads_for(static_cast<size_t>(n_rows));
  std::vector<int64_t> maxidx(static_cast<size_t>(nt), -1);
  {
    std::vector<std::thread> threads;
    auto scan = [&](int tid) {
      int64_t lo = n_rows * tid / nt, hi = n_rows * (tid + 1) / nt;
      int64_t mx = -1;
      for (int64_t r = lo; r < hi; ++r) {
        size_t b = starts[static_cast<size_t>(r)];
        size_t e = line_end(fb.data, fb.size, b);
        for (size_t i = b; i < e; ++i) {
          if (fb.data[i] == ':') {
            size_t j = i;
            while (j > b && std::isdigit(static_cast<unsigned char>(
                                fb.data[j - 1])))
              --j;
            // index part must be non-empty, all digits from the token
            // start (skip qid:/cost: style tokens — strtoll("qid")
            // would otherwise alias them onto feature 0, diverging
            // from the numpy fallback which raises on int("qid"))
            if (j == i) continue;
            if (j > b && !std::isspace(static_cast<unsigned char>(
                             fb.data[j - 1])))
              continue;
            int64_t idx = std::strtoll(std::string(fb.data + j, fb.data + i).c_str(),
                                       nullptr, 10);
            if (idx > mx) mx = idx;
          }
        }
      }
      maxidx[static_cast<size_t>(tid)] = mx;
    };
    for (int t = 0; t < nt; ++t) threads.emplace_back(scan, t);
    for (auto& th : threads) th.join();
  }
  int64_t n_cols = 0;
  for (int64_t m : maxidx)
    if (m + 1 > n_cols) n_cols = m + 1;
  if (n_cols == 0) return 2;

  double* mat = static_cast<double*>(
      std::calloc(static_cast<size_t>(n_rows * n_cols), sizeof(double)));
  double* lab = static_cast<double*>(
      std::malloc(sizeof(double) * static_cast<size_t>(n_rows)));
  if (!mat || !lab) {
    std::free(mat);
    std::free(lab);
    return 3;
  }

  std::vector<std::thread> threads;
  auto work = [&](int tid) {
    int64_t lo = n_rows * tid / nt, hi = n_rows * (tid + 1) / nt;
    for (int64_t r = lo; r < hi; ++r) {
      size_t b = starts[static_cast<size_t>(r)];
      size_t e = line_end(fb.data, fb.size, b);
      size_t i = b;
      while (i < e && !std::isspace(static_cast<unsigned char>(fb.data[i])))
        ++i;
      lab[r] = parse_field(fb.data + b, fb.data + i);
      while (i < e) {
        while (i < e && std::isspace(static_cast<unsigned char>(fb.data[i])))
          ++i;
        size_t fs = i;
        while (i < e && fb.data[i] != ':' &&
               !std::isspace(static_cast<unsigned char>(fb.data[i])))
          ++i;
        if (i >= e || fb.data[i] != ':') continue;
        bool all_digits = i > fs;
        for (size_t k = fs; k < i && all_digits; ++k)
          if (!std::isdigit(static_cast<unsigned char>(fb.data[k])))
            all_digits = false;
        if (!all_digits) {
          // qid:/cost: style token — skip it (value included) entirely
          while (i < e && !std::isspace(static_cast<unsigned char>(fb.data[i])))
            ++i;
          continue;
        }
        int64_t idx = std::strtoll(
            std::string(fb.data + fs, fb.data + i).c_str(), nullptr, 10);
        ++i;
        size_t vs = i;
        while (i < e && !std::isspace(static_cast<unsigned char>(fb.data[i])))
          ++i;
        if (idx >= 0 && idx < n_cols)
          mat[r * n_cols + idx] = parse_field(fb.data + vs, fb.data + i);
      }
    }
  };
  for (int t = 0; t < nt; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();

  *out = mat;
  *out_label = lab;
  *out_rows = n_rows;
  *out_cols = n_cols;
  return 0;
}

// ---------------------------------------------------------------- binning
// GreedyFindBin (reference src/io/bin.cpp:80), bit-identical to the
// Python mirror in binning.py:46 — the Python greedy loop over a 200k
// distinct-value sample costs ~110 ms per call (~6 s of a 1M x 28
// Dataset construct); this is the same double arithmetic in C++.

static bool check_double_equal_ordered(double a, double b) {
  return b <= std::nextafter(a, std::numeric_limits<double>::infinity());
}

// out must hold max_bin + 2 doubles; returns the number of bounds.
int64_t fp_greedy_find_bin(const double* distinct, const int64_t* counts,
                           int64_t n, int64_t max_bin, int64_t total_cnt,
                           int64_t min_data_in_bin, double* out) {
  const double kInf = std::numeric_limits<double>::infinity();
  int64_t nb = 0;
  if (n == 0) {
    out[nb++] = kInf;
    return nb;
  }
  if (n <= max_bin) {
    int64_t cur = 0;
    for (int64_t i = 0; i + 1 < n; ++i) {
      cur += counts[i];
      if (cur >= min_data_in_bin) {
        double val = std::nextafter((distinct[i] + distinct[i + 1]) / 2.0,
                                    kInf);
        if (nb == 0 || !check_double_equal_ordered(out[nb - 1], val)) {
          out[nb++] = val;
          cur = 0;
        }
      }
    }
    out[nb++] = kInf;
    return nb;
  }

  if (min_data_in_bin > 0) {
    int64_t mb = total_cnt / min_data_in_bin;
    if (mb < max_bin) max_bin = mb;
    if (max_bin < 1) max_bin = 1;
  }
  double mean_bin_size = static_cast<double>(total_cnt) / max_bin;
  std::vector<char> is_big(n);
  int64_t big_cnt = 0, big_data = 0;
  for (int64_t i = 0; i < n; ++i) {
    is_big[i] = counts[i] >= mean_bin_size;
    if (is_big[i]) {
      ++big_cnt;
      big_data += counts[i];
    }
  }
  int64_t rest_bin_cnt = max_bin - big_cnt;
  int64_t rest_sample_cnt = total_cnt - big_data;
  mean_bin_size = rest_bin_cnt > 0
                      ? static_cast<double>(rest_sample_cnt) / rest_bin_cnt
                      : kInf;
  // max_bin + 1: the loop body writes lowers[bin_cnt] BEFORE the
  // bin_cnt >= max_bin - 1 break check runs, so with max_bin == 1 the
  // statement order would write lowers[1] one element past a
  // max_bin-sized buffer (found by manual bounds review of this file
  // while hunting a suite heap corruption; the count arithmetic makes
  // the max_bin==1 write unreachable today, but the ordering is a
  // heap-overflow trap for any future threshold tweak)
  std::vector<double> uppers(max_bin + 1, kInf), lowers(max_bin + 1, kInf);
  int64_t bin_cnt = 0;
  lowers[0] = distinct[0];
  int64_t cur = 0;
  for (int64_t i = 0; i + 1 < n; ++i) {
    if (!is_big[i]) rest_sample_cnt -= counts[i];
    cur += counts[i];
    if (is_big[i] || cur >= mean_bin_size ||
        (is_big[i + 1] &&
         cur >= std::max(1.0, mean_bin_size * 0.5))) {
      uppers[bin_cnt] = distinct[i];
      ++bin_cnt;
      lowers[bin_cnt] = distinct[i + 1];
      if (bin_cnt >= max_bin - 1) break;
      cur = 0;
      if (!is_big[i]) {
        --rest_bin_cnt;
        mean_bin_size = rest_bin_cnt > 0
                            ? static_cast<double>(rest_sample_cnt) /
                                  rest_bin_cnt
                            : kInf;
      }
    }
  }
  ++bin_cnt;
  for (int64_t i = 0; i + 1 < bin_cnt; ++i) {
    double val = std::nextafter((uppers[i] + lowers[i + 1]) / 2.0, kInf);
    if (nb == 0 || !check_double_equal_ordered(out[nb - 1], val)) {
      out[nb++] = val;
    }
  }
  out[nb++] = kInf;
  return nb;
}

// Vectorized numerical ValueToBin (reference bin.h:161; the Python
// np.searchsorted path is single-threaded): first index with
// bounds[i] >= v (lower_bound), NaN -> nan_target. Multithreaded.
void fp_values_to_bins(const double* values, int64_t n, const double* bounds,
                       int64_t nb, int32_t nan_target, int32_t* out) {
  int nt = static_cast<int>(std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (nt > 16) nt = 16;
  if (n < (1 << 16)) nt = 1;
  std::vector<std::thread> threads;
  auto work = [&](int t) {
    int64_t lo = n * t / nt, hi = n * (t + 1) / nt;
    for (int64_t i = lo; i < hi; ++i) {
      double v = values[i];
      if (std::isnan(v)) {
        out[i] = nan_target;
        continue;
      }
      int64_t b = std::lower_bound(bounds, bounds + nb, v) - bounds;
      if (b >= nb) b = nb - 1;
      out[i] = static_cast<int32_t>(b);
    }
  };
  for (int t = 0; t < nt; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
}

// ---------------------------------------------------------------- predict
// Batch prediction over packed tree arrays (the reference predicts in
// C++, src/io/tree.h Tree::Predict; the numpy level-vectorized walk in
// tree.py peaks ~1.4M row-trees/s — pointer-chasing threads reach tens
// of millions). Semantics mirror tree.py predict_leaf exactly:
// decision_type bit0 = categorical, bit1 = default_left, bits2-3 =
// missing type (0 none, 1 zero: NaN or |x|<=1e-35, 2 NaN); NaN with
// missing type != NaN is treated as 0.0; categorical NaN goes right.

int64_t fp_predict(const double* X, int64_t n_rows, int64_t n_cols,
                   const int32_t* tree_idx, int64_t n_trees,
                   const int64_t* node_off, const int32_t* feature,
                   const double* threshold, const int32_t* dtype,
                   const int32_t* left, const int32_t* right,
                   const int64_t* leaf_off, const double* leaf_value,
                   const uint32_t* catw, const int64_t* cat_lo,
                   const int64_t* cat_hi, double* out) {
  int nt = static_cast<int>(std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (nt > 16) nt = 16;
  if (n_rows < (1 << 12)) nt = 1;
  std::vector<std::thread> threads;
  auto work = [&](int t) {
    int64_t lo = n_rows * t / nt, hi = n_rows * (t + 1) / nt;
    for (int64_t r = lo; r < hi; ++r) {
      const double* row = X + r * n_cols;
      double acc = 0.0;
      for (int64_t ti = 0; ti < n_trees; ++ti) {
        int64_t tr = tree_idx[ti];
        int64_t base = node_off[tr];
        int64_t n_nodes = node_off[tr + 1] - base;
        if (n_nodes == 0) {
          acc += leaf_value[leaf_off[tr]];
          continue;
        }
        int32_t node = 0;
        while (node >= 0) {
          int64_t k = base + node;
          double v = row[feature[k]];
          int32_t dt = dtype[k];
          bool go_left;
          if (dt & 1) {  // categorical
            bool ok = !std::isnan(v);
            int64_t iv = ok ? static_cast<int64_t>(v) : -1;
            int64_t wlo = cat_lo[k], whi = cat_hi[k];
            int64_t nbits = (whi - wlo) * 32;
            go_left = ok && iv >= 0 && iv < nbits &&
                      ((catw[wlo + iv / 32] >> (iv % 32)) & 1u);
          } else {
            int32_t mt = (dt >> 2) & 3;
            bool dl = (dt & 2) != 0;
            bool isna = std::isnan(v);
            bool miss = mt == 2 ? isna
                        : mt == 1 ? (isna || std::fabs(v) <= 1e-35)
                                  : false;
            double xv = (isna && mt != 2) ? 0.0 : v;
            go_left = miss ? dl : (xv <= threshold[k]);
          }
          node = go_left ? left[k] : right[k];
        }
        acc += leaf_value[leaf_off[tr] + (~node)];
      }
      out[r] = acc;
    }
  };
  for (int t = 0; t < nt; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
  return 0;
}

void fp_free(double* p) { std::free(p); }

}  // extern "C"
