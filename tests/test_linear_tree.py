"""Linear trees (linear_tree): per-leaf ridge models on path features
(reference linear_tree_learner.cpp, tree.cpp is_linear blocks)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _linear_problem(n=3000, seed=11):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 4)
    # piecewise-linear target: trees split on X0, linear leaves capture
    # the in-segment slope of X1
    y = np.where(X[:, 0] > 0, 2.0 + 3.0 * X[:, 1], -1.0 - 2.0 * X[:, 1])
    y = y + 0.05 * rs.randn(n)
    return X, y


def test_linear_tree_beats_piecewise_constant():
    X, y = _linear_problem()
    params = dict(objective="regression", num_leaves=4, min_data_in_leaf=20,
                  learning_rate=0.5, verbosity=-1)
    mses = {}
    for lin in (False, True):
        ds = lgb.Dataset(X, label=y, params={"linear_tree": lin},
                         free_raw_data=False)
        bst = lgb.train({**params, "linear_tree": lin}, ds,
                        num_boost_round=10)
        mses[lin] = float(np.mean((bst.predict(X) - y) ** 2))
    # a handful of linear leaves capture the slopes that constant leaves
    # can only staircase-approximate
    assert mses[True] < 0.25 * mses[False], mses


def test_linear_tree_model_roundtrip(tmp_path):
    X, y = _linear_problem(seed=12)
    ds = lgb.Dataset(X, label=y, params={"linear_tree": True},
                     free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 5, "linear_tree": True,
         "min_data_in_leaf": 20, "verbosity": -1},
        ds, num_boost_round=4,
    )
    assert any(t.is_linear for t in bst._gbdt.models)
    p = str(tmp_path / "linear.txt")
    bst.save_model(p)
    assert "leaf_coeff=" in open(p).read()
    b2 = lgb.Booster(model_file=p)
    np.testing.assert_allclose(
        bst.predict(X), b2.predict(X), rtol=1e-6, atol=1e-8
    )


def test_linear_tree_nan_falls_back_to_leaf_value():
    X, y = _linear_problem(seed=13)
    ds = lgb.Dataset(X, label=y, params={"linear_tree": True},
                     free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 4, "linear_tree": True,
         "min_data_in_leaf": 20, "verbosity": -1},
        ds, num_boost_round=3,
    )
    Xq = X[:50].copy()
    Xq[:, 1] = np.nan  # leaf feature NaN -> plain leaf_value path
    pred = bst.predict(Xq)
    assert np.isfinite(pred).all()
    # must differ from the linear outputs on the clean rows
    assert not np.allclose(pred, bst.predict(X[:50]))


def test_linear_tree_shap_raises():
    X, y = _linear_problem(seed=14)
    ds = lgb.Dataset(X, label=y, params={"linear_tree": True},
                     free_raw_data=False)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 4, "linear_tree": True,
         "verbosity": -1}, ds, num_boost_round=2,
    )
    with pytest.raises(Exception):
        bst.predict(X[:10], pred_contrib=True)
