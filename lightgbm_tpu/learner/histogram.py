"""Feature-histogram construction as MXU matmuls.

The reference builds per-(leaf, feature) histograms of (sum_grad,
sum_hess, count) with sequential scatter loops on CPU
(src/io/dense_bin.hpp:99-174 ConstructHistogram) and shared-memory
atomics on CUDA (src/treelearner/cuda/cuda_histogram_constructor.cu).
Scatter-add is the wrong primitive for a TPU; instead each block of rows
is expanded to a one-hot {0,1} matrix over the bin axis and contracted
against the (grad, hess, count) channels — a batched matmul that tiles
onto the MXU. A `lax.scan` over row blocks bounds the one-hot
materialization to one block at a time.

Accumulation is float32 (`preferred_element_type`), matching the CUDA
backend's float histograms (gpu_hist_t) rather than the CPU's doubles.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _hist_scan(
    bins_fb: jax.Array,  # (nblocks, F, Bk) int — feature-major row blocks
    gh_b: jax.Array,  # (nblocks, Bk, 3) f32
    num_bins: int,
) -> jax.Array:
    """Shared one-hot-matmul accumulation body: (F, B, 3) f32."""
    nblocks, F, Bk = bins_fb.shape
    iota = jnp.arange(num_bins, dtype=bins_fb.dtype)

    def body(acc, xs):
        b, g = xs  # (F, Bk) int, (Bk, 3) f32
        onehot = (b[:, :, None] == iota).astype(jnp.float32)  # (F, Bk, B)
        acc = acc + jnp.einsum(
            "frb,rc->fbc", onehot, g, preferred_element_type=jnp.float32
        )
        return acc, None

    init = jnp.zeros((F, num_bins, 3), dtype=jnp.float32)
    hist, _ = lax.scan(body, init, (bins_fb, gh_b))
    return hist


def leaf_histogram(
    bins_blocked: jax.Array,  # (nblocks, F, Bk) int32 — feature-major row blocks
    gh: jax.Array,  # (N, 3) float32 — (grad, hess, count) already masked to the leaf
    num_bins: int,  # uniform bin-axis size B
) -> jax.Array:
    """Return (F, B, 3) histogram of the rows whose gh mask is nonzero."""
    nblocks, F, Bk = bins_blocked.shape
    return _hist_scan(bins_blocked, gh.reshape(nblocks, Bk, 3), num_bins)


def leaf_histogram_rows(
    bins_rows: jax.Array,  # (R, F) int32 — gathered rows, row-major
    gh_rows: jax.Array,  # (R, 3) f32
    num_bins: int,
    block: int = 512,
) -> jax.Array:
    """Histogram over a gathered row subset (row-major layout).

    Same one-hot-matmul formulation as `leaf_histogram`, but over a
    compacted buffer whose size is a power-of-two fraction of N — the
    TPU analog of the reference constructing histograms only over the
    leaf's index list (data_partition.hpp + dense_bin.hpp:99 loops over
    data_indices)."""
    R, F = bins_rows.shape
    if R % block != 0:
        # pad to a block multiple (zero gh -> no contribution); keeps the
        # scan tiled even for odd-sized fallback buffers
        pad = block - R % block
        bins_rows = jnp.pad(bins_rows, ((0, pad), (0, 0)))
        gh_rows = jnp.pad(gh_rows, ((0, pad), (0, 0)))
        R += pad
    nb = R // block
    bb = bins_rows.reshape(nb, block, F).transpose(0, 2, 1)  # (nb, F, block)
    gg = gh_rows.reshape(nb, block, 3)
    return _hist_scan(bb, gg, num_bins)


def gather_rows(bins_blocked: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather rows by flat index from the blocked (nblocks, F, Bk) layout
    -> (len(idx), F). Out-of-range idx (pad slots) clamp; callers zero
    their gh so clamped rows contribute nothing."""
    nb, F, Bk = bins_blocked.shape
    blk = jnp.clip(idx // Bk, 0, nb - 1)
    off = idx % Bk
    return bins_blocked[blk, :, off]


def hist_capacities(n_rows: int, min_cap: int = 1024) -> tuple:
    """Static ladder of gather-buffer sizes: N/2, N/4, ... >= min_cap.
    The smaller child always fits in N/2; deep (small) leaves use the
    small buffers so histogram cost tracks leaf size."""
    def _round(c: int) -> int:
        return ((c + 511) // 512) * 512

    caps = []
    c = n_rows // 2
    while c >= min_cap:
        caps.append(_round(c))
        c //= 2
    if not caps:
        caps.append(_round(max(n_rows // 2, 1)))
    return tuple(caps)


def masked_leaf_histogram(
    bins_blocked: jax.Array,
    gh_all: jax.Array,  # (N, 3) masked for validity/bagging but not leaf
    row_leaf: jax.Array,  # (N,) int32
    leaf: jax.Array,  # scalar int32
    num_bins: int,
) -> jax.Array:
    """Histogram of rows currently assigned to `leaf`."""
    mask = (row_leaf == leaf).astype(gh_all.dtype)
    return leaf_histogram(bins_blocked, gh_all * mask[:, None], num_bins)


def root_sums(gh: jax.Array, axis_name: Optional[str] = None) -> jax.Array:
    """(sum_grad, sum_hess, count) over all in-bag rows; float64-free but
    accumulated in f32 pairwise by jnp.sum. Globally reduced over the data
    mesh axis when present (reference data_parallel_tree_learner.cpp:169-221
    root allreduce)."""
    s = jnp.sum(gh, axis=0)
    if axis_name is not None:
        s = lax.psum(s, axis_name)
    return s
