"""Fault-tolerant training & serving (lightgbm_tpu/resilience,
docs/RESILIENCE.md).

The contract under test, end to end: a training run crashed at an
arbitrary round resumes via ``resume=auto`` and produces a model
string BIT-IDENTICAL to the uninterrupted run (stateless fold_in RNG +
crash-consistent checkpoints); serving degrades instead of dying —
deadline'd requests raise typed :class:`DeadlineExceeded`, an over-cap
burst fast-fails with :class:`QueueOverflow` (HTTP 503 + Retry-After)
without poisoning in-flight futures, and an injected device fault
falls back to host scoring with unchanged predictions. Faults are
planted deterministically by resilience/faultinject.py — the ``chaos``
marker ties these to tools/chaos.sh."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.metrics import default_registry
from lightgbm_tpu.resilience import checkpoint as ckpt
from lightgbm_tpu.resilience import faultinject
from lightgbm_tpu.resilience.backoff import backoff_delay, delays, retry_call
from lightgbm_tpu.resilience.errors import (
    CheckpointError,
    DeadlineExceeded,
    InjectedFault,
    QueueOverflow,
    ShutdownError,
)
from lightgbm_tpu.resilience.heartbeat import (
    HeartbeatWriter,
    health_report,
    heartbeat_path,
    read_heartbeats,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _disarm_fault_plan():
    """Chaos tests arm process-global fault plans; none may leak."""
    yield
    faultinject.disarm()


# ===================================================== checkpoint file
def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "run.ckpt")
    hist = [[("v", "l2", 0.5, False)], [("v", "l2", 0.4, False)]]
    ckpt.save_checkpoint(
        path, "tree\nv=4\n", engine_round=2, total_iters=7,
        eval_history=hist, record_offset=123, fingerprint="abcd",
    )
    state = ckpt.load_checkpoint(path)
    assert state["engine_round"] == 2
    assert state["total_iters"] == 7
    assert state["model"] == "tree\nv=4\n"
    assert state["record_offset"] == 123
    assert state["fingerprint"] == "abcd"
    # eval rows come back as tuples, positionally identical
    assert state["eval_history"] == [[("v", "l2", 0.5, False)],
                                     [("v", "l2", 0.4, False)]]
    # rolling: a later save atomically replaces, no tmp file left
    ckpt.save_checkpoint(path, "m2", engine_round=4, total_iters=9)
    assert ckpt.load_checkpoint(path)["engine_round"] == 4
    assert not os.path.exists(path + ".tmp")


def test_checkpoint_corrupt_and_missing(tmp_path):
    torn = tmp_path / "torn.ckpt"
    torn.write_text('{"schema": "lightgbm-tpu/checkpoint/v1", "eng')
    with pytest.raises(CheckpointError, match="corrupt"):
        ckpt.load_checkpoint(str(torn))
    alien = tmp_path / "alien.ckpt"
    alien.write_text(json.dumps({"schema": "something/else"}))
    with pytest.raises(CheckpointError, match="schema"):
        ckpt.load_checkpoint(str(alien))
    incomplete = tmp_path / "inc.ckpt"
    incomplete.write_text(json.dumps(
        {"schema": ckpt.SCHEMA, "engine_round": 1}
    ))
    with pytest.raises(CheckpointError, match="missing"):
        ckpt.load_checkpoint(str(incomplete))
    with pytest.raises(CheckpointError, match="cannot read"):
        ckpt.load_checkpoint(str(tmp_path / "absent.ckpt"))
    # resume=auto treats an ABSENT rolling checkpoint as a fresh start…
    assert ckpt.find_resume_checkpoint(
        "auto", "", str(tmp_path / "absent.ckpt")
    ) == (None, None)
    # …but a corrupt one is surfaced, never silently retrained over
    with pytest.raises(CheckpointError):
        ckpt.find_resume_checkpoint("auto", "", str(torn))
    # resume_from= names an explicit file: absent is an error
    with pytest.raises(CheckpointError):
        ckpt.find_resume_checkpoint("off", str(tmp_path / "no.ckpt"),
                                    str(torn))


def test_resume_from_missing_and_torn_tmp_ignored(monkeypatch, tmp_path):
    """Two recovery edges that must never be silent: ``resume_from=``
    naming an absent checkpoint errors LOUDLY instead of retraining
    from scratch over it, and a torn ``.ckpt.tmp`` left by a crash
    mid-write is invisible to ``resume=auto`` — the atomic tmp +
    ``os.replace`` protocol only ever publishes complete files."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv(faultinject.ENV_VAR, raising=False)
    X, y = _resume_data()
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    with pytest.raises(CheckpointError):
        lgb.train(dict(_RESUME_PARAMS, resume="off",
                       resume_from="absent.ckpt"), ds, num_boost_round=6)

    # crash residue beside the rolling checkpoint path: resume=auto
    # ignores the unpublished tmp and starts fresh
    (tmp_path / "model.txt.ckpt.tmp").write_text(
        '{"schema": "lightgbm-tpu/checkpoint/v1", "eng')
    assert ckpt.find_resume_checkpoint(
        "auto", "", "model.txt.ckpt") == (None, None)
    bst = lgb.train(dict(_RESUME_PARAMS), ds, num_boost_round=6)
    assert bst.num_trees() == 6  # trained fresh, tmp never loaded


def test_config_fingerprint_ignores_recovery_knobs():
    base = {"objective": "binary", "num_leaves": 31, "seed": 7}
    fp = ckpt.config_fingerprint(base)
    # rollback legitimately shrinks learning_rate and sets resume keys
    assert ckpt.config_fingerprint(
        dict(base, learning_rate=0.05, resume="auto",
             resume_from="x.ckpt", fault_plan="round:3:raise")
    ) == fp
    assert ckpt.config_fingerprint(dict(base, num_leaves=15)) != fp


def test_truncate_eval_history():
    hist = [[("v", "l2", float(i), False)] for i in range(5)]
    assert ckpt.truncate_eval_history(hist, 3) == hist[:3]
    assert ckpt.truncate_eval_history(hist, 0) == []
    assert ckpt.truncate_eval_history(hist, -2) == []
    assert ckpt.truncate_eval_history(hist, 99) == hist


# ============================================================ backoff
def test_backoff_schedule():
    assert backoff_delay(1, base_s=10, cap_s=120) == 10
    assert backoff_delay(2, base_s=10, cap_s=120) == 20
    assert backoff_delay(5, base_s=10, cap_s=120) == 120  # capped
    assert delays(3, base_s=10) == [10.0, 20.0, 40.0]
    with pytest.raises(ValueError):
        backoff_delay(0)


def test_retry_call_retries_then_succeeds():
    calls, slept, seen = [], [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = retry_call(
        flaky, retries=5, base_s=0.5, sleep=slept.append,
        on_retry=lambda a, d, e: seen.append((a, d, type(e).__name__)),
    )
    assert out == "ok" and len(calls) == 3
    assert slept == [0.5, 1.0]
    assert seen == [(1, 0.5, "OSError"), (2, 1.0, "OSError")]


def test_retry_call_gives_up_and_respects_predicate():
    calls = []

    def always():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, retries=2, base_s=0.1, sleep=lambda s: None)
    assert len(calls) == 3  # first attempt + 2 retries

    calls.clear()
    # the retriable predicate is how pull_snapshot refuses to retry an
    # HTTP error status: fail-fast on the first attempt
    with pytest.raises(OSError):
        retry_call(always, retries=5, retriable=lambda e: False,
                   sleep=lambda s: None)
    assert len(calls) == 1

    # a non-matching exception type propagates without any retry
    def typed():
        calls.append(1)
        raise ValueError("not retriable")

    calls.clear()
    with pytest.raises(ValueError):
        retry_call(typed, retries=5, sleep=lambda s: None)
    assert len(calls) == 1


# ======================================================== fault plans
def test_fault_plan_parsing():
    plan = faultinject.FaultPlan(
        "round:7:kill; device_put:1:raise, serve_request:2:delay:0.25"
    )
    assert [repr(c) for c in plan.clauses] == [
        "round:7:kill", "device_put:1:raise",
        "serve_request:2:delay:0.25",
    ]
    for bad in ("round:7", "nowhere:1:raise", "round:1:explode",
                "serve_request:1:delay"):
        with pytest.raises(ValueError):
            faultinject.FaultPlan(bad)


def test_fault_plan_one_shot_and_triggers():
    plan = faultinject.arm("round:5:raise")
    # index-triggered: only the exact round fires, and only once
    plan.visit("round", index=4)
    with pytest.raises(InjectedFault):
        plan.visit("round", index=5)
    plan.visit("round", index=5)  # clause already consumed

    plan = faultinject.arm("serve_request:2:raise")
    plan.visit("serve_request")  # 1st hit
    with pytest.raises(InjectedFault):
        plan.visit("serve_request")  # 2nd hit
    plan.visit("serve_request")

    t0 = time.monotonic()
    faultinject.arm("device_put:1:delay:0.05").visit("device_put")
    assert time.monotonic() - t0 >= 0.05


def test_fault_point_disarmed_and_env_configure(monkeypatch):
    faultinject.disarm()
    faultinject.fault_point("round", 3)  # no plan: pure no-op
    monkeypatch.setenv(faultinject.ENV_VAR, "round:1:raise")
    assert faultinject.configure("").spec == "round:1:raise"
    # explicit config param wins over the env var
    assert faultinject.configure("round:9:raise").spec == "round:9:raise"
    monkeypatch.delenv(faultinject.ENV_VAR)
    assert faultinject.configure("") is None
    assert faultinject.active() is None


# ========================================== crash/resume — bit match
_RESUME_PARAMS = {
    "objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
    "min_data_in_leaf": 5, "verbosity": -1, "seed": 7,
    "bagging_fraction": 0.7, "bagging_freq": 1, "feature_fraction": 0.8,
    "snapshot_freq": 5, "resume": "auto", "output_model": "model.txt",
}


def _resume_data():
    rs = np.random.RandomState(11)
    X = rs.randn(800, 6)
    y = ((X @ rs.randn(6) + 0.3 * rs.randn(800)) > 0).astype(float)
    return X, y


def _train_in(dirpath, monkeypatch, plan=None):
    """One train() run with per-run cwd: the model text embeds the
    EXPLICIT params verbatim, so crash and clean runs must share an
    identical params dict — relative output_model, fault plan via the
    env var (never a param)."""
    monkeypatch.chdir(dirpath)
    if plan:
        monkeypatch.setenv(faultinject.ENV_VAR, plan)
    else:
        monkeypatch.delenv(faultinject.ENV_VAR, raising=False)
    X, y = _resume_data()
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    return lgb.train(dict(_RESUME_PARAMS), ds, num_boost_round=10)


@pytest.mark.chaos
def test_crash_resume_bit_identical(monkeypatch, tmp_path):
    """Crash at round 7 (checkpoint at 5), resume=auto: the final
    model string is bit-identical to the uninterrupted run — the
    stateless fold_in sampling RNG plus the checkpoint's round index
    ARE the whole training state. Re-resuming a finished run is
    idempotent (0 remaining rounds, same bits)."""
    crashed = tmp_path / "crashed"
    clean = tmp_path / "clean"
    crashed.mkdir()
    clean.mkdir()

    with pytest.raises(InjectedFault):
        _train_in(crashed, monkeypatch, plan="round:7:raise")
    state = ckpt.load_checkpoint(str(crashed / "model.txt.ckpt"))
    assert state["engine_round"] == 5  # last snapshot_freq boundary

    resumed = _train_in(crashed, monkeypatch)
    uninterrupted = _train_in(clean, monkeypatch)
    assert resumed.num_trees() == uninterrupted.num_trees() == 10
    assert resumed.model_to_string() == uninterrupted.model_to_string()

    # idempotent: resuming a COMPLETE checkpoint trains 0 rounds
    again = _train_in(crashed, monkeypatch)
    assert again.model_to_string() == uninterrupted.model_to_string()


@pytest.mark.chaos
def test_resume_replays_eval_history(monkeypatch, tmp_path):
    """record_evaluation across a crash/resume sees the identical
    metric sequence the uninterrupted run saw (the checkpoint carries
    the eval history; resume replays it into stateful callbacks)."""
    def run(dirpath, plan=None):
        monkeypatch.chdir(dirpath)
        if plan:
            monkeypatch.setenv(faultinject.ENV_VAR, plan)
        else:
            monkeypatch.delenv(faultinject.ENV_VAR, raising=False)
        X, y = _resume_data()
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        rs = np.random.RandomState(12)
        Xv = rs.randn(200, 6)
        vs = lgb.Dataset(Xv, label=(Xv[:, 0] > 0).astype(float),
                         reference=ds, free_raw_data=False)
        hist = {}
        bst = lgb.train(
            dict(_RESUME_PARAMS, metric="binary_logloss"), ds,
            num_boost_round=10, valid_sets=[vs], valid_names=["v"],
            callbacks=[lgb.record_evaluation(hist)],
        )
        return bst, hist

    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir()
    b.mkdir()
    with pytest.raises(InjectedFault):
        run(a, plan="round:7:raise")
    bst_r, hist_r = run(a)
    bst_c, hist_c = run(b)
    assert bst_r.model_to_string() == bst_c.model_to_string()
    assert len(hist_r["v"]["binary_logloss"]) == 10
    assert hist_r == hist_c


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_cli_resume_bit_identical(tmp_path):
    """The real thing: a CLI training process SIGKILLed mid-boosting
    (fault plan ``round:7:kill`` — no cleanup, no flush) resumes via
    ``resume=auto`` and writes a model file byte-identical to an
    uninterrupted run's."""
    worker = str(REPO / "tests" / "_resilience_train_worker.py")
    conf = (
        "task = train\n"
        "data = train.tsv\n"
        "objective = binary\n"
        "num_leaves = 15\n"
        "num_trees = 8\n"
        "learning_rate = 0.1\n"
        "min_data_in_leaf = 5\n"
        "seed = 7\n"
        "bagging_fraction = 0.7\n"
        "bagging_freq = 1\n"
        "snapshot_freq = 3\n"
        "resume = auto\n"
        "output_model = model.txt\n"
        "verbosity = -1\n"
    )
    rs = np.random.RandomState(3)
    X = rs.randn(500, 5)
    y = ((X @ rs.randn(5)) > 0).astype(float)

    def setup(d):
        d.mkdir()
        np.savetxt(d / "train.tsv", np.column_stack([y, X]),
                   delimiter="\t", fmt="%.8g")
        (d / "train.conf").write_text(conf)

    def run(d, plan=None, expect_kill=False):
        env = dict(os.environ)
        env.pop(faultinject.ENV_VAR, None)
        if plan:
            env[faultinject.ENV_VAR] = plan
        p = subprocess.run(
            [sys.executable, worker, "config=train.conf"],
            cwd=str(d), env=env, timeout=600,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        if expect_kill:
            assert p.returncode == -9, p.stdout.decode()
        else:
            assert p.returncode == 0, p.stdout.decode()

    crashed, clean = tmp_path / "crashed", tmp_path / "clean"
    setup(crashed)
    setup(clean)
    run(crashed, plan="round:7:kill", expect_kill=True)
    assert not (crashed / "model.txt").exists()  # it really died
    state = ckpt.load_checkpoint(str(crashed / "model.txt.ckpt"))
    assert state["engine_round"] == 6  # last snapshot_freq=3 boundary
    run(crashed)
    run(clean)
    assert (crashed / "model.txt").read_bytes() == \
        (clean / "model.txt").read_bytes()


# ========================================= anomaly rollback recovery
def _diverging(rng, tmp_path, **over):
    X = rng.randn(400, 4)
    y = X[:, 0] + 0.1 * rng.randn(400)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    Xv = rng.randn(150, 4)
    vs = lgb.Dataset(Xv, label=Xv[:, 0], reference=ds,
                     free_raw_data=False)
    params = {
        "objective": "regression", "metric": "l2", "num_leaves": 7,
        "learning_rate": 5.0, "verbosity": -1,
        "anomaly_policy": "rollback", "snapshot_freq": 2,
        "anomaly_rollback_lr_decay": 0.02, "anomaly_rollback_max": 2,
        "record_file": str(tmp_path / "roll.jsonl"),
        "output_model": str(tmp_path / "roll_model.txt"),
    }
    params.update(over)
    return params, ds, vs


@pytest.mark.chaos
def test_anomaly_rollback_recovers(rng, tmp_path):
    """learning_rate=5.0 trips the loss_spike sentinel; under
    anomaly_policy=rollback the run restores the last checkpoint and
    retrains with a shrunken learning_rate instead of aborting."""
    from lightgbm_tpu.obs.anomaly import AnomalyAbort

    params, ds, vs = _diverging(rng, tmp_path)
    bst = lgb.train(params, ds, num_boost_round=14,
                    valid_sets=[vs], valid_names=["v"])
    assert bst.num_trees() == 14  # completed despite the divergence
    # the rollback really went through a checkpoint restore
    state = ckpt.load_checkpoint(str(tmp_path / "roll_model.txt.ckpt"))
    assert state["engine_round"] == 14

    # with the retry budget exhausted the policy degrades to abort
    params2, ds2, vs2 = _diverging(rng, tmp_path,
                                   anomaly_rollback_max=0)
    with pytest.raises(AnomalyAbort):
        lgb.train(params2, ds2, num_boost_round=14,
                  valid_sets=[vs2], valid_names=["v"])


# ======================================== serving: typed degradation
def _serving_model(rng):
    X = rng.randn(600, 5)
    y = X @ rng.randn(5) + 0.1 * rng.randn(600)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    ds, num_boost_round=8)
    return bst, X


def _rejections(entry, kind):
    return default_registry().counter(
        "lgbmtpu_serve_rejected_total", labels=("entry", "kind")
    ).value(entry=entry, kind=kind)


@pytest.mark.chaos
def test_deadline_exceeded_typed(rng):
    """A request whose deadline passes in the queue fails with
    DeadlineExceeded (a TimeoutError) before any device call is spent
    on it, and counts into the rejection metric."""
    from lightgbm_tpu.serving import MicroBatcher, ModelRegistry

    bst, X = _serving_model(rng)
    reg = ModelRegistry()
    reg.load("m", bst.model_to_string())
    mv = reg._entry("m")
    before = _rejections("serve:m", "deadline")
    mb = MicroBatcher(mv.dispatcher, max_delay_s=0.05)
    try:
        fut = mb.submit(X[:4], deadline_s=1e-7)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=10)
        assert isinstance(fut.exception(), TimeoutError)  # generic too
        assert _rejections("serve:m", "deadline") == before + 1
        # an undeadlined submit on the same batcher still scores fine
        out = mb.submit(X[:4]).result(timeout=30)
        np.testing.assert_allclose(
            out.ravel(), bst.predict(X[:4], raw_score=True),
            rtol=1e-5, atol=1e-6,
        )
    finally:
        mb.close()


@pytest.mark.chaos
def test_queue_overflow_fast_fail_no_poisoning(rng):
    """Admission control: while a backlog exists, a submit past the
    row cap raises QueueOverflow in the CALLER's thread; the in-flight
    and queued requests score normally (no poisoned futures)."""
    from lightgbm_tpu.serving import MicroBatcher, ModelRegistry

    bst, X = _serving_model(rng)
    reg = ModelRegistry()
    reg.load("m", bst.model_to_string())
    mv = reg._entry("m")
    before = _rejections("serve:m", "overloaded")
    # hold the worker inside the first device call for 0.5 s so a
    # backlog builds deterministically behind it
    faultinject.arm("device_put:1:delay:0.5")
    mb = MicroBatcher(mv.dispatcher, max_delay_s=0.001, queue_cap=8)
    try:
        fut_a = mb.submit(X[:4])
        time.sleep(0.15)  # worker is now sleeping inside score_raw
        fut_b = mb.submit(X[:6])  # empty queue: admitted
        with pytest.raises(QueueOverflow) as ei:
            mb.submit(X[:4])  # 6 + 4 > cap 8 while backlog exists
        assert ei.value.retry_after_s >= 1
        assert _rejections("serve:m", "overloaded") == before + 1
        for fut, rows in ((fut_a, X[:4]), (fut_b, X[:6])):
            np.testing.assert_allclose(
                fut.result(timeout=30).ravel(),
                bst.predict(rows, raw_score=True),
                rtol=1e-5, atol=1e-6,
            )
    finally:
        mb.close()


@pytest.mark.chaos
def test_device_fault_host_fallback_parity(rng):
    """An injected device-put fault degrades that chunk to the host
    tree-walker: predictions (and leaf indices) are unchanged, the
    degradation is warn-once and metric-counted."""
    from lightgbm_tpu.serving import ModelRegistry

    bst, X = _serving_model(rng)
    reg = ModelRegistry()
    reg.load("m", bst.model_to_string())
    c = default_registry().counter(
        "lgbmtpu_serve_host_fallback_total", labels=("entry",)
    )
    before = c.value(entry="serve:m")

    faultinject.arm("device_put:1:raise")
    pred = reg.predict("m", X[:32])
    np.testing.assert_allclose(pred, bst.predict(X[:32]),
                               rtol=1e-5, atol=1e-6)
    assert c.value(entry="serve:m") == before + 1
    assert reg._entry("m").dispatcher._fallback_warned

    faultinject.arm("device_put:1:raise")
    leaf = reg.predict("m", X[:32], pred_leaf=True)
    np.testing.assert_array_equal(leaf, bst.predict(X[:32],
                                                    pred_leaf=True))
    assert c.value(entry="serve:m") == before + 2

    # without a fallback installed the fault propagates typed
    reg2 = ModelRegistry(host_fallback=False)
    reg2.load("m", bst.model_to_string())
    faultinject.arm("device_put:1:raise")
    with pytest.raises(InjectedFault):
        reg2.predict("m", X[:32])


@pytest.mark.chaos
def test_serve_request_fault_site():
    """The serve_request seam maps an injected fault to a typed 500
    (error_kind=fault) and a delay clause stalls exactly one request."""
    from lightgbm_tpu.serving import ModelRegistry
    from lightgbm_tpu.serving.server import ERROR_STATUS, handle_request

    reg = ModelRegistry()
    faultinject.arm("serve_request:1:raise")
    resp = handle_request(reg, {"op": "ping"})
    assert not resp["ok"] and resp["error_kind"] == "fault"
    assert ERROR_STATUS[resp["error_kind"]] == 500
    assert handle_request(reg, {"op": "ping"})["ok"]  # one-shot

    faultinject.arm("serve_request:1:delay:0.05")
    t0 = time.monotonic()
    assert handle_request(reg, {"op": "ping"})["ok"]
    assert time.monotonic() - t0 >= 0.05


@pytest.mark.chaos
def test_http_degradation_statuses(rng):
    """Over HTTP: a deadline'd request answers 504, an over-cap burst
    answers 503 with a Retry-After header, in-flight requests still
    answer 200 with correct predictions."""
    from lightgbm_tpu.serving import ModelRegistry, serve_http

    bst, X = _serving_model(rng)
    reg = ModelRegistry(queue_cap=8)
    reg.load("default", bst.model_to_string())
    httpd = serve_http(reg, port=0, block=False)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def post(body, timeout=30):
        req = urllib.request.Request(
            base + "/v1/score", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    try:
        out = post({"rows": X[:5].tolist(), "queue": True})
        np.testing.assert_allclose(out["pred"], bst.predict(X[:5]),
                                   rtol=1e-5, atol=1e-6)

        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"rows": X[:5].tolist(), "queue": True,
                  "deadline_ms": 1e-4})
        assert ei.value.code == 504
        assert json.loads(ei.value.read())["error_kind"] == "deadline"

        # hold the device for 0.6 s; queue a second request behind it;
        # the third exceeds the row cap -> 503 + Retry-After
        faultinject.arm("device_put:1:delay:0.6")
        results = {}

        def bg(key, rows):
            results[key] = post({"rows": rows.tolist(), "queue": True})

        ta = threading.Thread(target=bg, args=("a", X[:4]))
        ta.start()
        time.sleep(0.2)
        tb = threading.Thread(target=bg, args=("b", X[:6]))
        tb.start()
        time.sleep(0.1)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"rows": X[:4].tolist(), "queue": True})
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert json.loads(ei.value.read())["error_kind"] == "overloaded"
        ta.join(timeout=30)
        tb.join(timeout=30)
        np.testing.assert_allclose(results["a"]["pred"],
                                   bst.predict(X[:4]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(results["b"]["pred"],
                                   bst.predict(X[:6]),
                                   rtol=1e-5, atol=1e-6)
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=5)


# =============================================== MicroBatcher close()
class _StubForest:
    @staticmethod
    def _check_width(X):
        return None


class _StubDispatcher:
    """Minimal dispatcher double: lets close() semantics be tested
    without device calls, including a worker wedged mid-score."""

    name = "stub"
    buckets = (8,)
    forest = _StubForest()

    def __init__(self, gate=None):
        self.gate = gate  # worker blocks here when set

    def score_raw(self, X):
        if self.gate is not None:
            self.gate.wait()
        return np.zeros((1, X.shape[0]), np.float32)


def test_microbatcher_close_drains_queue():
    """Regression: close() on a healthy batcher DRAINS the queue (the
    worker finishes pending work on the way out); submits after close
    fast-fail with ShutdownError instead of queueing forever."""
    from lightgbm_tpu.serving import MicroBatcher

    mb = MicroBatcher(_StubDispatcher(), max_delay_s=0.2)
    futs = [mb.submit(np.zeros((2, 3), np.float32)) for _ in range(3)]
    mb.close()
    for fut in futs:
        assert fut.result(timeout=5).shape == (2, 1)
    with pytest.raises(ShutdownError):
        mb.submit(np.zeros((1, 3), np.float32))


@pytest.mark.slow
def test_microbatcher_close_fails_pending_when_wedged():
    """close() with the worker wedged inside a device call: queued
    futures are failed with ShutdownError after the join timeout —
    a shutdown must never leave callers blocked on Future.result()."""
    from lightgbm_tpu.serving import MicroBatcher

    gate = threading.Event()
    mb = MicroBatcher(_StubDispatcher(gate=gate), max_delay_s=0.001)
    in_flight = mb.submit(np.zeros((2, 3), np.float32))
    time.sleep(0.2)  # worker is now blocked inside score_raw
    queued = mb.submit(np.zeros((2, 3), np.float32))
    try:
        mb.close()  # join times out (worker wedged), sweeps the queue
        with pytest.raises(ShutdownError):
            queued.result(timeout=1)
        assert not in_flight.done()  # coalesced work is never cancelled
    finally:
        gate.set()  # release the wedged worker thread


# ======================================================== heartbeats
def test_heartbeat_and_health_report(tmp_path):
    d = str(tmp_path)
    hb = HeartbeatWriter(d, rank=1, interval_s=60.0)
    hb.start()
    try:
        beats = read_heartbeats(d)
        assert beats[1]["rank"] == 1 and beats[1]["seq"] == 0
        rep = health_report(d, expected=3)
        assert rep["alive"] == [1]
        assert rep["missing"] == [0, 2]
        assert not rep["healthy"]
    finally:
        hb.stop()
    # the final beat marks a clean shutdown: alive even when old
    rep = health_report(d, expected=2, stale_after_s=0.0,
                        now=time.time() + 1000)
    assert 1 in rep["alive"]

    # a rank whose beats stopped mid-run classifies as stale
    with open(heartbeat_path(d, 0), "w") as f:
        json.dump({"rank": 0, "pid": 1, "seq": 4,
                   "t_unix": time.time() - 1000, "final": False}, f)
    rep = health_report(d, expected=2, stale_after_s=30.0)
    assert rep["stale"] == [0] and not rep["healthy"]

    # torn/alien heartbeat files are skipped, not fatal
    (tmp_path / "heartbeat_rank00002.json").write_text("{torn")
    assert 2 not in read_heartbeats(d)
