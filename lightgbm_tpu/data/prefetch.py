"""Double-buffered host->HBM chunk prefetch (docs/DATA_PLANE.md
"Prefetch contract").

While the device consumes chunk *k* (one dynamic_update_slice into the
resident bin matrix), a background reader thread prepares chunk *k+1*:
read from the spool, verify, convert to the device dtype, pad, and
start the host->device transfer. The thread hands device buffers
through a BOUNDED queue (maxsize = prefetch depth), so host memory is
capped at (depth + 1) chunks no matter how far the reader could run
ahead.

Thread discipline (pinned by analysis/concurrency_lint.py):

- the producer queue is constructed with an explicit maxsize
  (``unbounded-producer-queue``);
- the reader thread performs NO JAX work other than the
  ``jax.device_put`` transfer itself (``jax-in-reader-thread``) —
  tracing/compilation from a non-main thread races the main thread's
  trace state, and dispatching compiled computations from two threads
  serializes on the backend anyway.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional, Tuple

import numpy as np

DEFAULT_PREFETCH_DEPTH = 2

# sentinel chunk index for an exception crossing the thread boundary
_ERR = -1


def chunk_update_step(buf, chunk, lo):
    """Pure per-chunk device step of the streamed construct: write one
    (G, chunk_rows) bin block into the resident (G, Np) matrix at
    column offset ``lo``. Traced once per chunk width (constant body +
    tail), audited by analysis/jaxpr_audit.py entry
    ``streamed_construct`` (no host callbacks, no f64)."""
    import jax.lax as lax

    return lax.dynamic_update_slice(buf, chunk, (0, lo))


def read_rss_mb() -> float:
    """Current resident set size of this process in MB (Linux
    /proc/self/statm; 0.0 where unavailable)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os

        return pages * os.sysconf("SC_PAGE_SIZE") / (1 << 20)
    except (OSError, ValueError, IndexError):
        return 0.0


def prefetch_depth(chunk_bytes: int, budget_bytes: int) -> int:
    """Queue depth that keeps (depth + 1) in-flight chunks inside the
    RAM budget, clamped to [1, DEFAULT_PREFETCH_DEPTH * 2]."""
    if chunk_bytes <= 0:
        return DEFAULT_PREFETCH_DEPTH
    fit = budget_bytes // max(1, chunk_bytes) - 1
    return int(max(1, min(DEFAULT_PREFETCH_DEPTH * 2, fit,
                          DEFAULT_PREFETCH_DEPTH)))


class ChunkPrefetcher:
    """Background reader streaming device-resident chunks in order.

    ``load_fn(idx)`` runs ON THE READER THREAD and must be host-only:
    read + verify the chunk, bin/convert/pad it, and return
    (np_chunk, payload) where np_chunk is the ready-to-transfer array
    and payload is arbitrary host metadata forwarded to the consumer.
    The reader then issues the jax.device_put and enqueues; the
    consumer iterates committed device buffers in chunk order.
    """

    def __init__(self, load_fn: Callable[[int], Tuple[np.ndarray, Any]],
                 n_chunks: int, depth: int = DEFAULT_PREFETCH_DEPTH,
                 device_put: bool = True):
        self._load = load_fn
        self._n = int(n_chunks)
        self._device_put = device_put
        # bounded: the reader blocks once `depth` chunks are in flight
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._reader_loop, name="chunk-prefetch", daemon=True
        )
        self._thread.start()

    def _reader_loop(self) -> None:
        try:
            for idx in range(self._n):
                if self._stop.is_set():
                    return
                np_chunk, payload = self._load(idx)
                if self._device_put:
                    import jax

                    # the ONLY jax call permitted on this thread
                    buf = jax.device_put(np_chunk)
                else:
                    buf = np_chunk
                while not self._stop.is_set():
                    try:
                        self._q.put((idx, buf, payload), timeout=0.2)
                        break
                    except queue.Full:
                        continue
            self._q.put(None)
        except BaseException as e:  # noqa: BLE001 — crosses the thread boundary
            try:
                self._q.put((_ERR, None, e), timeout=5.0)
            except queue.Full:
                pass

    def __iter__(self) -> Iterator[Tuple[int, Any, Any]]:
        expect = 0
        while True:
            item = self._q.get()
            if item is None:
                if expect != self._n:
                    raise RuntimeError(
                        f"prefetcher ended after {expect} of {self._n} chunks"
                    )
                return
            idx, buf, payload = item
            if idx == _ERR:
                raise RuntimeError("chunk prefetch reader failed") from payload
            if idx != expect:
                raise RuntimeError(
                    f"prefetcher yielded chunk {idx}, expected {expect}"
                )
            expect += 1
            yield idx, buf, payload

    def close(self) -> None:
        """Stop the reader (idempotent; safe mid-iteration on error)."""
        self._stop.set()
        # drain so a blocked put() can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10.0)

    def __enter__(self) -> "ChunkPrefetcher":
        return self

    def __exit__(self, *exc: Any) -> Optional[bool]:
        self.close()
        return None
