"""Retrace guard: fail fast on unexpected jit recompiles + tracer leaks.

Every perf regression class this repo has hit so far — executable
bloat, fused-step cache staleness, per-iteration retraces from an
unhashable static or a drifting shape — shows up FIRST as an
unexpected jit cache miss. This module counts them:

- globally, through a `jax.monitoring` duration-event listener
  (`/jax/core/compile/jaxpr_trace_duration` fires once per trace,
  `backend_compile_duration` once per XLA compile);
- per entry point, through the `_cache_size()` of jitted callables.

`retrace_guard` is a context manager; `tests/conftest.py` wires it in
as the `retrace_guard` pytest fixture. `jax.checking_leaks` (tracer
leak detection) can be enabled on the same guard.

    with retrace_guard(entry_points=[grow_tree_rounds], max_retraces=1):
        train_two_iterations()   # second iteration must reuse the trace

The listener counts for the whole process lifetime once installed (an
int increment per trace/compile event — events fire per compilation,
not per dispatch, so the idle cost is nil): guards read deltas, and
`compile_counters()` exposes the running totals to the run manifest
(obs/manifest.py). Install happens on the first guard or explicitly
via `ensure_installed()` (cli.py does this when a manifest or profile
is requested, so the counts cover the run from the start).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence


class RetraceError(AssertionError):
    """An entry point retraced (or the process compiled) more than the
    guard allows."""


_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_counters: Dict[str, int] = {_TRACE_EVENT: 0, _COMPILE_EVENT: 0}


def _listener(event: str, duration: float, **kwargs: Any) -> None:
    if event in _counters:
        with _lock:
            _counters[event] += 1


def _install() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        import jax

        jax.monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def ensure_installed() -> None:
    """Start counting trace/compile events now (idempotent). Call early
    when compile counts should cover the whole run — the manifest's
    numbers only include events after installation."""
    _install()


def compile_counters() -> Dict[str, int]:
    """Process-lifetime (since install) jaxpr-trace and backend-compile
    event totals — the run manifest's compile section."""
    with _lock:
        return {
            "jaxpr_traces": _counters[_TRACE_EVENT],
            "backend_compiles": _counters[_COMPILE_EVENT],
            "listener_installed": int(_installed),
        }


def _cache_size(fn: Any) -> Optional[int]:
    """Trace-cache entry count of a jitted callable (None if the
    callable exposes no cache — plain functions pass through)."""
    size = getattr(fn, "_cache_size", None)
    if callable(size):
        try:
            return int(size())
        except Exception:  # noqa: BLE001 — cache introspection only
            return None
    return None


class GuardReport:
    """Mutable result the context manager fills at exit."""

    def __init__(self) -> None:
        self.traces = 0
        self.compiles = 0
        self.per_entry: Dict[str, int] = {}

    def __repr__(self) -> str:
        return (
            f"GuardReport(traces={self.traces}, compiles={self.compiles}, "
            f"per_entry={self.per_entry})"
        )


@contextlib.contextmanager
def retrace_guard(
    entry_points: Sequence[Any] = (),
    max_retraces: int = 0,
    check_leaks: bool = False,
    what: str = "guarded region",
) -> Iterator[GuardReport]:
    """Fail with RetraceError when jit caches miss more than allowed.

    entry_points: jitted callables — each one's `_cache_size()` may
        grow by at most `max_retraces` inside the guard. With no entry
        points, the GLOBAL trace count is bounded instead (any jit
        tracing anywhere counts, including first-call traces — use
        entry points after a warmup call for precise contracts).
    check_leaks: also run the body under `jax.checking_leaks()` so
        tracers escaping a trace raise immediately. The leak-check
        config is part of the jit cache key, so cached entry points
        RETRACE by design under it — raise max_retraces accordingly
        when combining it with entry_points.
    """
    import jax

    _install()
    report = GuardReport()
    names: List[str] = []
    before_entry: List[Optional[int]] = []
    for fn in entry_points:
        names.append(getattr(fn, "__name__", repr(fn)))
        before_entry.append(_cache_size(fn))
    with _lock:
        before = dict(_counters)
    try:
        ctx = jax.checking_leaks() if check_leaks else contextlib.nullcontext()
        with ctx:
            yield report
    finally:
        with _lock:
            report.traces = _counters[_TRACE_EVENT] - before[_TRACE_EVENT]
            report.compiles = (
                _counters[_COMPILE_EVENT] - before[_COMPILE_EVENT]
            )
    offenders: List[str] = []
    for fn, name, b in zip(entry_points, names, before_entry):
        after = _cache_size(fn)
        if b is None or after is None:
            continue
        grew = after - b
        report.per_entry[name] = grew
        if grew > max_retraces:
            offenders.append(
                f"{name}: {grew} new trace-cache entr"
                f"{'y' if grew == 1 else 'ies'} (allowed {max_retraces})"
            )
    # checking_leaks alters the trace-context cache key, forcing fresh
    # traces by design — the global bound only means something without it
    if not entry_points and not check_leaks \
            and report.traces > max_retraces:
        offenders.append(
            f"global: {report.traces} jaxpr traces "
            f"(allowed {max_retraces})"
        )
    if offenders:
        raise RetraceError(
            f"unexpected retrace in {what}: " + "; ".join(offenders)
            + " — a shape/dtype/static argument is drifting between "
            "calls, or a traced value is used as a cache key"
        )
